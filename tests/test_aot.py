"""AOT compile service (karpenter_tpu/aot): the bucket ladder, the
persistent executable cache (incl. every pathology: corruption,
truncation, version-mismatched keys, concurrent writers, read-only dirs —
all degrade to JIT, never crash), the warm-start walk (second boot against
a warm cache performs ZERO fresh ladder compiles), the dispatch-table
interception (decisions bit-identical, broken executables fall back), the
off-ladder warning path, the /debug/kernels?view=ladder view, and the
solverd-restart-midstream sim scenario."""

import os
import threading

import jax
import numpy as np
import pytest

from karpenter_tpu import aot
from karpenter_tpu.aot import cache as cachemod
from karpenter_tpu.aot import compiler as aotc
from karpenter_tpu.aot import ladder as lmod
from karpenter_tpu.aot import runtime as aotrt
from karpenter_tpu.aot.cache import ExecutableCache
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.cloudprovider.kwok.instance_types import (
    construct_instance_types,
)
from karpenter_tpu.metrics import global_registry
from karpenter_tpu.observability import kernels as kobs
from karpenter_tpu.ops import catalog as catmod
from karpenter_tpu.ops.catalog import CatalogEngine
from karpenter_tpu.scheduling.requirements import (
    Operator,
    Requirement,
    Requirements,
)

TINY_LADDER = lmod.make(
    {
        "feasibility.cube": [(1, 4), (4, 8)],
        "catalog.row_compat": [(32,)],
        "packer.solve_block": [(8,)],
    }
)


@pytest.fixture
def clean_aot():
    """Isolate AOT process-global state per spec."""
    reg = kobs.registry()
    reg.reset()
    aotrt.clear_executables()
    aotrt.reset_off_ladder()
    yield
    aotrt.configure(None, None)
    aotrt.clear_executables()
    aotrt.reset_off_ladder()
    reg.reset()


def small_engine() -> CatalogEngine:
    return CatalogEngine(construct_instance_types())


def probe_feasibility(engine):
    reqs = Requirements(Requirement(wk.LABEL_ARCH, Operator.IN, ["amd64"]))
    rows = engine.rows_for(reqs)
    return engine.feasibility(
        [rows], np.zeros((1, len(engine.resource_dims)))
    )


class TestLadder:
    def test_bucket_for_picks_smallest_fit(self):
        assert lmod.DEFAULT.bucket_for("feasibility.cube", (3, 5)) == (8, 16)
        assert lmod.DEFAULT.bucket_for("feasibility.cube", (1, 1)) == (1, 4)
        assert lmod.DEFAULT.bucket_for("catalog.row_compat", (40,)) == (64,)

    def test_off_ladder_is_none(self):
        assert lmod.DEFAULT.bucket_for("feasibility.cube", (4096, 4)) is None
        assert lmod.DEFAULT.bucket_for("unknown.kernel", (1,)) is None
        # arity mismatch can't select a bucket
        assert lmod.DEFAULT.bucket_for("feasibility.cube", (1,)) is None

    def test_serialization_round_trip(self, tmp_path):
        path = tmp_path / "ladder.json"
        path.write_text(TINY_LADDER.dumps())
        loaded = lmod.load(str(path))
        assert loaded == TINY_LADDER
        assert lmod.resolve(str(path)) == TINY_LADDER

    def test_resolve_specs(self):
        assert lmod.resolve("") is None
        assert lmod.resolve("off") is None
        assert lmod.resolve("default") is lmod.DEFAULT

    def test_from_observatory_rounds_up_device_buckets(self):
        counts = {
            "feasibility.cube": {
                "shapes": {
                    "3x5,5x144,...": {"warmup": 1, "steady": 4},
                    # host-twin buckets never shape the ladder
                    "9x9,...": {"host": 2},
                },
                "recompiles": 0,
            },
            "catalog.row_compat": {
                "shapes": {"40,40,40": {"steady": 1}},
                "recompiles": 0,
            },
        }
        ladder = lmod.from_observatory(counts, headroom=1)
        assert (4, 8) in ladder.buckets("feasibility.cube")
        assert (8, 16) in ladder.buckets("feasibility.cube")  # headroom
        assert (64,) in ladder.buckets("catalog.row_compat")
        assert not any(b[0] >= 16 and b != (8, 16)
                       for b in ladder.buckets("feasibility.cube"))

    def test_from_observatory_headroom_covers_every_axis(self):
        """Headroom doubles the per-axis maxima: growth along the R axis
        must stay on-ladder even when the lexicographically-largest bucket
        is wide-and-shallow."""
        counts = {
            "feasibility.cube": {
                "shapes": {
                    "512x4,4x144": {"steady": 1},
                    "64x64,64x144": {"steady": 1},
                },
                "recompiles": 0,
            },
        }
        ladder = lmod.from_observatory(counts, headroom=1)
        assert (1024, 128) in ladder.buckets("feasibility.cube")
        assert ladder.bucket_for("feasibility.cube", (128, 128)) == (1024, 128)


class TestExecutableCache:
    def test_round_trip(self, tmp_path):
        c = ExecutableCache(str(tmp_path))
        assert c.get("k" * 64) is None  # miss
        assert c.put("k" * 64, b"payload")
        assert c.get("k" * 64) == b"payload"
        # a hit is only counted once the caller confirms the payload loaded
        assert c.stats()["hits"] == 0
        c.count_hit()
        assert c.stats()["hits"] == 1
        assert c.stats()["misses"] == 1

    def test_valid_envelope_bad_payload_evicts_without_hit(self, tmp_path):
        """An entry whose checksum is clean but whose payload fails to load
        (toolchain drift inside a valid envelope): the caller evicts it —
        one eviction, zero hits, so cache metrics never claim a warm start
        that didn't happen."""
        c = ExecutableCache(str(tmp_path))
        c.put("p" * 64, b"not a pickled executable")
        body = c.get("p" * 64)
        assert body == b"not a pickled executable"  # envelope verifies
        c.evict("p" * 64, "deserialize: boom")  # what the compiler does
        assert c.stats()["hits"] == 0
        assert c.stats()["evictions"] == 1
        assert c.get("p" * 64) is None  # gone

    def test_corrupted_entry_evicts_and_degrades(self, tmp_path):
        c = ExecutableCache(str(tmp_path))
        c.put("a" * 64, b"good bytes")
        path = c._path("a" * 64)
        with open(path, "r+b") as f:
            f.seek(len(cachemod.MAGIC) + 70)
            f.write(b"XXXX")  # flip body bytes: checksum now fails
        assert c.get("a" * 64) is None
        assert not os.path.exists(path), "corrupt entry not evicted"
        assert c.stats()["evictions"] == 1

    def test_truncated_entry_evicts(self, tmp_path):
        c = ExecutableCache(str(tmp_path))
        c.put("b" * 64, b"a longer body that will be cut")
        path = c._path("b" * 64)
        raw = open(path, "rb").read()
        open(path, "wb").write(raw[: len(raw) // 2])
        assert c.get("b" * 64) is None
        assert not os.path.exists(path)

    def test_bad_magic_evicts(self, tmp_path):
        c = ExecutableCache(str(tmp_path))
        path = c._path("c" * 64)
        open(path, "wb").write(b"not an aot entry at all")
        assert c.get("c" * 64) is None
        assert not os.path.exists(path)

    def test_version_mismatched_key_is_a_miss(self, monkeypatch, tmp_path):
        """The jax/XLA version lives in the cache KEY: a version bump makes
        yesterday's entries unreachable misses, never wrong loads."""
        k_now = aotc.cache_key("cat", "feasibility.cube", "1x4", 1)
        monkeypatch.setattr(
            aotc, "_toolchain_fingerprint", lambda: "jax=9.9.9;backend=tpu"
        )
        k_other = aotc.cache_key("cat", "feasibility.cube", "1x4", 1)
        assert k_now != k_other
        c = ExecutableCache(str(tmp_path))
        c.put(k_other, b"old-version executable")
        assert c.get(k_now) is None  # miss, not corruption
        assert c.stats()["evictions"] == 0
        # ladder version + catalog content rotate the key the same way
        assert aotc.cache_key("cat", "feasibility.cube", "1x4", 2) != k_other
        assert aotc.cache_key("dog", "feasibility.cube", "1x4", 1) != k_other

    def test_concurrent_writers_share_a_dir(self, tmp_path):
        """Two daemons sharing one cache dir: interleaved writes to the
        same keys never produce a torn read or an exception."""
        c1 = ExecutableCache(str(tmp_path))
        c2 = ExecutableCache(str(tmp_path))
        body = b"x" * 4096
        errors = []

        def writer(c):
            try:
                for i in range(50):
                    c.put("e" * 64, body)
                    got = c.get("e" * 64)
                    assert got is None or got == body
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [
            threading.Thread(target=writer, args=(c,)) for c in (c1, c2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        assert c1.get("e" * 64) == body
        assert c1.stats()["evictions"] == 0

    def test_read_only_dir_degrades_to_jit(self, monkeypatch, tmp_path):
        """An unwritable cache dir (read-only volume) must not crash the
        boot: writes warn + count, reads keep working."""
        c = ExecutableCache(str(tmp_path))
        c.put("f" * 64, b"pre-existing")

        def deny(*args, **kwargs):
            raise PermissionError("read-only file system")

        monkeypatch.setattr(cachemod.os, "replace", deny)
        assert c.put("g" * 64, b"new entry") is False
        assert c.stats()["write_errors"] == 1
        assert c.get("f" * 64) == b"pre-existing"  # reads unaffected
        # no temp-file litter
        assert not [p for p in os.listdir(tmp_path) if ".tmp." in p]

    def test_uncreatable_root_is_an_empty_cache(self, tmp_path):
        target = tmp_path / "file"
        target.write_text("occupied")
        c = ExecutableCache(str(target / "sub"))  # parent is a file
        assert c.get("h" * 64) is None
        assert c.put("h" * 64, b"x") is False


class TestWarmStart:
    def test_cold_then_warm_boot_zero_fresh_compiles(self, clean_aot, tmp_path):
        """The acceptance contract: boot #1 compiles the ladder and fills
        the cache; boot #2 (fresh process stand-in: executables + jit
        caches dropped) loads every bucket from disk and compiles NOTHING,
        asserted via the observatory's aot-warm compile counters."""
        cache = ExecutableCache(str(tmp_path))
        aotrt.configure(TINY_LADDER, cache)
        reg = kobs.registry()

        s1 = aot.warm_start(small_engine())
        assert s1["buckets"] > 0
        assert s1["fresh_compiles"] == s1["buckets"]
        assert s1["cache_hits"] == 0 and s1["errors"] == 0

        # restart: drop every executable this process holds
        aotrt.clear_executables()
        jax.clear_caches()
        reg.reset()
        e2 = small_engine()
        s2 = aot.warm_start(e2)
        assert s2["fresh_compiles"] == 0, s2
        assert s2["cache_hits"] == s2["buckets"] == s1["buckets"]
        # observatory agrees: every aot-warm record was a warm load
        for row in reg.debug_snapshot()["kernels"]:
            assert row["compiles"] == 0, row
            assert row["phases"]["aot-warm"] > 0
        assert e2.aot_ladder is TINY_LADDER
        assert getattr(e2, "_aot_warmed") is True

    def test_warm_start_idempotent_per_engine(self, clean_aot, tmp_path):
        aotrt.configure(TINY_LADDER, ExecutableCache(str(tmp_path)))
        base = aotrt.stats()["warm_starts"]
        engine = small_engine()
        s1 = aot.warm_start(engine)
        s2 = aot.warm_start(engine)  # no second walk
        assert s2 is s1 or s2 == s1
        assert aotrt.stats()["warm_starts"] == base + 1

    def test_corrupt_cache_entry_falls_back_to_compile(self, clean_aot, tmp_path):
        cache = ExecutableCache(str(tmp_path))
        aotrt.configure(TINY_LADDER, cache)
        aot.warm_start(small_engine())
        # corrupt every entry, restart, warm again: evict + recompile, no crash
        for name in os.listdir(tmp_path):
            path = os.path.join(tmp_path, name)
            raw = open(path, "rb").read()
            open(path, "wb").write(raw[:-8] + b"CORRUPTX")
        aotrt.clear_executables()
        s2 = aot.warm_start(small_engine())
        assert s2["fresh_compiles"] == s2["buckets"]
        assert s2["cache_hits"] == 0
        assert cache.stats()["evictions"] >= s2["buckets"]

    def test_without_cache_dir_still_prepays_compiles(self, clean_aot):
        aotrt.configure(TINY_LADDER, None)
        s = aot.warm_start(small_engine())
        assert s["fresh_compiles"] == s["buckets"] > 0

    def test_disabled_runs_lazy_warmup(self, clean_aot):
        engine = small_engine()
        assert aot.warm_start(engine) is None
        assert engine.aot_ladder is None
        assert getattr(engine, "_warmed", False) is True

    def test_key_capacity_stabilized(self, clean_aot):
        """warm_start pre-interns the well-known label keys so the padded
        key axis at boot matches steady state — pod selectors (arch, zone,
        capacity-type...) must not grow K past the AOT'd shapes."""
        aotrt.configure(TINY_LADDER, None)
        engine = small_engine()
        aot.warm_start(engine)
        k_boot = engine._key_capacity
        for key in (wk.LABEL_ARCH, wk.LABEL_TOPOLOGY_ZONE,
                    wk.CAPACITY_TYPE_LABEL_KEY, wk.LABEL_HOSTNAME):
            engine.vocab.key_id(key)
        engine._maybe_reencode()
        assert engine._key_capacity == k_boot


class TestDispatchInterception:
    def test_feasibility_served_by_aot_executable(self, clean_aot, tmp_path):
        aotrt.configure(TINY_LADDER, ExecutableCache(str(tmp_path)))
        prev = catmod.FORCE_BACKEND
        catmod.FORCE_BACKEND = "device"
        try:
            engine = small_engine()
            aot.warm_start(engine)
            rows = engine.rows_for(
                Requirements(Requirement(wk.LABEL_ARCH, Operator.IN, ["amd64"]))
            )
            reg = kobs.registry()
            reg.seal()
            base = reg.steady_recompiles()
            engine.feasibility(
                [rows], np.zeros((1, len(engine.resource_dims)))
            )
            snap = reg.debug_snapshot("feasibility.cube")
            assert snap["aot_served"] >= 1, snap
            assert reg.steady_recompiles() == base
        finally:
            catmod.FORCE_BACKEND = prev

    def test_decisions_identical_with_and_without_aot(self, clean_aot, tmp_path):
        aotrt.configure(TINY_LADDER, ExecutableCache(str(tmp_path)))
        e_aot = small_engine()
        aot.warm_start(e_aot)
        fz_aot = probe_feasibility(e_aot)
        aotrt.configure(None, None)
        e_ref = small_engine()
        e_ref.warmup()
        fz_ref = probe_feasibility(e_ref)
        assert (fz_aot.feasible == fz_ref.feasible).all()

    def test_broken_executable_falls_back_and_discards(self, clean_aot):
        """An installed executable that raises at call time (backend drift)
        must degrade to the jit path and drop out of the table."""
        from karpenter_tpu.tracing import kernel as ktime

        calls = []

        def broken(*args):
            calls.append(1)
            raise TypeError("aval mismatch")

        f = jax.jit(lambda x: x * 2.0)
        import jax.numpy as jnp

        sig = kobs.shape_signature((jnp.ones((6,)),))
        aotrt.install("spec.broken", sig, broken)
        ctr = global_registry.get("karpenter_aot_executable_fallbacks_total")
        base = ctr.value({"kernel": "spec.broken"})
        out = ktime.dispatch(f, jnp.ones((6,)), kernel="spec.broken")
        assert float(np.asarray(out)[0]) == 2.0  # jit fallback answered
        assert calls == [1]
        assert aotrt.lookup("spec.broken", sig) is None  # discarded
        assert ctr.value({"kernel": "spec.broken"}) == base + 1
        # next dispatch goes straight to jit, no second failure
        ktime.dispatch(f, jnp.ones((6,)), kernel="spec.broken")
        assert calls == [1]

    def test_packer_pads_group_axis_to_bucket(self, clean_aot):
        from karpenter_tpu.ops.packer import (
            GroupSolver,
            encode_pods_for_packer,
        )
        from karpenter_tpu.utils.resources import parse_resource_list

        aotrt.configure(TINY_LADDER, None)
        engine = small_engine()
        aot.warm_start(engine)
        reqs = Requirements(Requirement(wk.LABEL_OS, Operator.IN, ["linux"]))
        dims = engine.resource_dims
        requests = np.zeros((3, len(dims)))
        cpu = parse_resource_list({"cpu": "1"})["cpu"]
        requests[:, dims[wk.RESOURCE_CPU]] = cpu
        grouped = encode_pods_for_packer(engine, [reqs] * 3, requests)
        solver = GroupSolver(engine)
        choice, feasible, nodes, unsched = solver.solve(grouped)
        # G groups in, G results out (padding sliced off) and all feasible
        G = grouped.membership.shape[0]
        assert len(choice) == len(nodes) == G
        assert feasible.all()
        shapes = kobs.registry().debug_snapshot("packer.solve_block")["shapes"]
        # the dispatched group axis is the ladder bucket (8), not G
        assert any(s["shape"].startswith("8x") for s in shapes), shapes


class TestOffLadder:
    def test_note_counts_warns_once_and_fires_callbacks(self, clean_aot):
        fired = []
        aotrt.on_off_ladder(lambda k, s: fired.append((k, s)), key="spec")
        ctr = global_registry.get("karpenter_aot_offladder_dispatches_total")
        base = ctr.value({"kernel": "spec.k", "mesh": ""})
        aotrt.note_off_ladder("spec.k", "1024x8")
        aotrt.note_off_ladder("spec.k", "1024x8")
        assert ctr.value({"kernel": "spec.k", "mesh": ""}) == base + 2
        assert fired == [("spec.k", "1024x8")] * 2
        assert aotrt.stats()["off_ladder_dispatches"] == 2

    def test_oversized_cube_flags_off_ladder(self, clean_aot):
        """A sweep past the largest bucket keeps the pow2 padding and is
        counted — it will jit-compile a shape the warm start never saw."""
        aotrt.configure(TINY_LADDER, None)
        prev = catmod.FORCE_BACKEND
        catmod.FORCE_BACKEND = "device"
        try:
            engine = small_engine()
            aot.warm_start(engine)
            # 5 rowsets > the tiny ladder's largest P bucket (4)
            many = [
                Requirements(
                    Requirement(wk.LABEL_TOPOLOGY_ZONE, Operator.IN,
                                [f"kwok-zone-{i % 4 + 1}"]),
                    Requirement(wk.LABEL_ARCH, Operator.IN,
                                ["amd64" if i % 2 else "arm64"]),
                )
                for i in range(5)
            ]
            row_sets = [engine.rows_for(r) for r in many]
            base = aotrt.stats()["off_ladder_dispatches"]
            engine.feasibility(
                row_sets, np.zeros((len(row_sets), len(engine.resource_dims)))
            )
            assert aotrt.stats()["off_ladder_dispatches"] > base
        finally:
            catmod.FORCE_BACKEND = prev


class TestLadderView:
    def test_debug_kernels_view_ladder(self, clean_aot, tmp_path):
        aotrt.configure(TINY_LADDER, ExecutableCache(str(tmp_path)))
        aot.warm_start(small_engine())
        aotrt.note_off_ladder("feasibility.cube", "2048x4")
        view = kobs.registry().debug_snapshot(view="ladder")
        assert view["enabled"] is True
        assert view["ladder_version"] == lmod.LADDER_VERSION
        assert [4, 8] in view["ladder"]["feasibility.cube"]
        assert view["executables"]
        assert view["off_ladder"]["count"] == 1
        assert view["off_ladder"]["events"] == [
            {"kernel": "feasibility.cube", "shape": "2048x4"}
        ]
        assert view["cache"]["misses"] > 0
        # observed buckets flag ladder membership for device dispatches
        cube_rows = view["observed"].get("feasibility.cube", [])
        assert any(r.get("on_ladder") for r in cube_rows), cube_rows

    def test_view_when_disabled(self, clean_aot):
        view = kobs.registry().debug_snapshot(view="ladder")
        assert view["enabled"] is False
        assert view["ladder"] == {}
        assert view["cache"] is None


class TestOptionsWiring:
    def test_cache_dir_implies_default_ladder(self, clean_aot, tmp_path):
        from karpenter_tpu.operator.options import Options

        aotrt.configure_from_options(
            Options(compile_cache_dir=str(tmp_path))
        )
        assert aotrt.enabled()
        assert aotrt.active_ladder() is lmod.DEFAULT
        assert aotrt.active_cache().root == str(tmp_path)

    def test_off_and_default_specs(self, clean_aot, tmp_path):
        from karpenter_tpu.operator.options import Options

        aotrt.configure_from_options(Options(aot_ladder="off"))
        assert not aotrt.enabled()
        aotrt.configure_from_options(Options(aot_ladder="default"))
        assert aotrt.enabled()
        assert aotrt.active_cache() is None  # ladder without persistence

    def test_options_parse_flags(self):
        from karpenter_tpu.operator.options import Options

        opts = Options.parse(
            ["--compile-cache-dir", "/var/cache/karpenter-aot",
             "--aot-ladder", "default"],
            env={},
        )
        assert opts.compile_cache_dir == "/var/cache/karpenter-aot"
        assert opts.aot_ladder == "default"


class TestProvisionerWiring:
    def test_prewarm_walks_ladder_and_registers_offladder_events(
        self, clean_aot, tmp_path
    ):
        """Operator boot with --compile-cache-dir: the first provisioning
        pass AOT-warm-starts the engine, and off-ladder dispatches publish
        AOTOffLadderDispatch warning events through the recorder."""
        from karpenter_tpu.cloudprovider.kwok.provider import KwokCloudProvider
        from karpenter_tpu.operator.operator import Operator
        from karpenter_tpu.operator.options import Options
        from karpenter_tpu.runtime.store import Store
        from karpenter_tpu.utils.clock import FakeClock
        from helpers import nodepool

        ladder_path = tmp_path / "ladder.json"
        ladder_path.write_text(TINY_LADDER.dumps())
        base = aotrt.stats()
        clock = FakeClock()
        store = Store(clock=clock)
        operator = Operator(
            store,
            KwokCloudProvider(store, clock),
            clock=clock,
            options=Options(
                compile_cache_dir=str(tmp_path / "cache"),
                aot_ladder=str(ladder_path),
            ),
        )
        assert aotrt.enabled()
        store.create(nodepool("workers"))
        operator.run_once()
        stats = aotrt.stats_delta(base)
        assert stats["warm_starts"] == 1
        assert stats["fresh_compiles"] > 0
        # the off-ladder warning event path is wired through the recorder
        aotrt.note_off_ladder("feasibility.cube", "4096x8")
        events = [
            e for e in operator.recorder.events
            if e.reason == "AOTOffLadderDispatch"
        ]
        assert events and "4096x8" in events[0].message
        # ladder view serves through the operator's debug surface
        view = operator.kernel_snapshot(view="ladder")
        assert view["enabled"] is True


class TestSolverdRestartScenario:
    """The restart-midstream acceptance: the scenario completes
    deterministically (digest equality across same-seed runs) with no SLO
    breach, the restart is in the record, and with a cache dir the second
    process's boots warm-start."""

    TRACE = {
        "version": 1,
        "name": "restart-mini",
        "duration": 120.0,
        "tick": 1.0,
        "nodepools": [{"name": "workers"}],
        "events": [
            {"at": 2.0, "kind": "submit", "group": "svc", "count": 4,
             "pod": {"cpu": "1", "memory": "1Gi"}, "replace": True},
            {"at": 60.0, "kind": "solverd-restart"},
            {"at": 70.0, "kind": "submit", "group": "post", "count": 3,
             "pod": {"cpu": "2"}, "replace": True},
        ],
    }

    def test_deterministic_and_no_slo_breach(self, clean_aot):
        from karpenter_tpu.sim.harness import run_scenario

        a = run_scenario(dict(self.TRACE), seed=11)
        b = run_scenario(dict(self.TRACE), seed=11)
        assert a.digest == b.digest
        assert a.report["kernels"]["digest"] == b.report["kernels"]["digest"]
        assert a.report["faults"]["solverd_restarts"] == 1
        assert a.report["slo"]["pods_never_bound"] == 0
        assert a.report["kernels"]["steady_recompiles"] == 0
        # post-restart demand was actually solved (the restart didn't
        # strand the operator on a dead solver client)
        assert a.report["slo"]["pods_bound"] == 7

    def test_fault_profile_survives_the_restart(self, clean_aot):
        """A trace combining a solver rejection storm with a mid-trace
        restart: the rebuilt client re-wraps with the SAME flaky profile
        (continuing the rng stream), so rejections keep landing after the
        restart and same-seed runs stay byte-identical."""
        from karpenter_tpu.sim.harness import run_scenario

        trace = dict(self.TRACE)
        trace["faults"] = {"solver_rejection_rate": 0.5}
        a = run_scenario(dict(trace), seed=11)
        b = run_scenario(dict(trace), seed=11)
        assert a.digest == b.digest
        assert a.report["faults"]["solver_rejections"] > 0
        # rejections recorded AFTER the restart prove the wrapper survived
        restart_t = next(
            e["t"] for e in a.log if e["ev"] == "solverd-restart"
        )
        post = [
            e for e in a.log
            if e["ev"] == "fault-solver-reject" and e["t"] > restart_t
        ]
        assert post, "no solver rejections after the restart — wrapper lost"
        assert a.report["slo"]["pods_never_bound"] == 0

    def test_registered_scenario_resolves(self):
        from karpenter_tpu.sim import scenarios

        trace = scenarios.resolve("solverd-restart", 7)
        kinds = [e["kind"] for e in trace["events"]]
        assert "solverd-restart" in kinds

    def test_restart_with_aot_cache_warm_starts(self, clean_aot, tmp_path):
        from karpenter_tpu.operator.options import Options
        from karpenter_tpu.sim.harness import run_scenario

        opts = Options(
            compile_cache_dir=str(tmp_path), aot_ladder="default"
        )
        a = run_scenario(dict(self.TRACE), seed=11, options=opts)
        aot_a = a.report["kernels"]["aot"]
        # boot + post-restart re-warm both walked the ladder
        assert aot_a["warm_starts"] == 2, aot_a
        assert aot_a["fresh_compiles"] > 0
        assert a.report["slo"]["pods_never_bound"] == 0
        # a second process (fresh executables + jit caches) boots warm
        aotrt.clear_executables()
        jax.clear_caches()
        b = run_scenario(dict(self.TRACE), seed=11, options=opts)
        aot_b = b.report["kernels"]["aot"]
        assert aot_b["fresh_compiles"] == 0, aot_b
        assert aot_b["cache_hits"] > 0
        assert a.digest == b.digest
        assert a.report["kernels"]["digest"] == b.report["kernels"]["digest"]
