"""Store-backed leader election (reference pkg/operator/operator.go:144-151):
two operator replicas sharing one store must not both provision; failover
happens when the incumbent's lease goes stale or is released."""

from karpenter_tpu.cloudprovider.kwok.provider import KwokCloudProvider
from karpenter_tpu.operator.leaderelection import (
    LEASE_DURATION,
    LEASE_NAME,
    LeaderElector,
)
from karpenter_tpu.operator.operator import Operator
from karpenter_tpu.operator.options import Options
from karpenter_tpu.runtime.store import Store
from karpenter_tpu.utils.clock import FakeClock

from helpers import nodepool, unschedulable_pod


def _env():
    clock = FakeClock()
    store = Store(clock=clock)
    return clock, store


class TestLeaderElector:
    def test_first_acquires_second_defers(self):
        clock, store = _env()
        a = LeaderElector(store, clock, identity="a")
        b = LeaderElector(store, clock, identity="b")
        assert a.try_acquire_or_renew() is True
        assert b.try_acquire_or_renew() is False
        assert a.is_leader() and not b.is_leader()
        lease = store.get("Lease", LEASE_NAME)
        assert lease.spec.holder_identity == "a"

    def test_renewal_keeps_leadership(self):
        clock, store = _env()
        a = LeaderElector(store, clock, identity="a")
        b = LeaderElector(store, clock, identity="b")
        a.try_acquire_or_renew()
        for _ in range(10):
            clock.step(LEASE_DURATION / 2)
            assert a.try_acquire_or_renew() is True
            assert b.try_acquire_or_renew() is False

    def test_stale_lease_taken_over(self):
        clock, store = _env()
        a = LeaderElector(store, clock, identity="a")
        b = LeaderElector(store, clock, identity="b")
        a.try_acquire_or_renew()
        clock.step(LEASE_DURATION + 0.1)  # a stops renewing
        assert b.try_acquire_or_renew() is True
        assert store.get("Lease", LEASE_NAME).spec.holder_identity == "b"
        # a comes back: it must observe it lost
        assert a.try_acquire_or_renew() is False
        assert not a.is_leader()

    def test_release_hands_over_immediately(self):
        clock, store = _env()
        a = LeaderElector(store, clock, identity="a")
        b = LeaderElector(store, clock, identity="b")
        a.try_acquire_or_renew()
        assert b.try_acquire_or_renew() is False
        a.release()
        # no lease-duration wait needed after a clean release
        assert b.try_acquire_or_renew() is True

    def test_disabled_always_leads_without_lease(self):
        clock, store = _env()
        a = LeaderElector(store, clock, identity="a", enabled=False)
        b = LeaderElector(store, clock, identity="b", enabled=False)
        assert a.try_acquire_or_renew() is True
        assert b.try_acquire_or_renew() is True
        assert store.try_get("Lease", LEASE_NAME) is None


class TestOperatorHA:
    def _two_operators(self, disable=False):
        clock = FakeClock()
        store = Store(clock=clock)
        provider = KwokCloudProvider(store, clock)
        opts = Options(disable_leader_election=disable)
        op1 = Operator(store, provider, clock=clock, options=opts)
        op2 = Operator(store, provider, clock=clock, options=opts)
        store.create(nodepool("workers"))
        store.create(unschedulable_pod(requests={"cpu": "1"}))
        return clock, store, op1, op2

    def test_exactly_one_replica_provisions(self):
        """Both replicas tick against one store; only the leader writes —
        the pod lands on exactly one claim instead of two."""
        clock, store, op1, op2 = self._two_operators()
        for _ in range(10):
            clock.step(2.0)
            op1.run_once()
            op2.run_once()
        assert op1.elector.is_leader() and not op2.elector.is_leader()
        claims = store.list("NodeClaim")
        assert len(claims) == 1
        pod = store.list("Pod")[0]
        assert pod.spec.node_name, "leader must finish the provisioning flow"

    def test_failover_after_lease_expiry(self):
        """The incumbent stops ticking; the standby takes over once the
        lease goes stale and finishes outstanding work."""
        clock, store, op1, op2 = self._two_operators()
        clock.step(2.0)
        op1.run_once()
        op2.run_once()
        assert op1.elector.is_leader() and not op2.elector.is_leader()
        # op1 crashes (stops renewing); op2 keeps ticking
        clock.step(LEASE_DURATION + 0.1)
        for _ in range(10):
            clock.step(2.0)
            op2.run_once()
        assert op2.elector.is_leader()
        claims = store.list("NodeClaim")
        assert len(claims) == 1
        assert claims[0].condition_is_true("Initialized")

    def test_failover_resyncs_dropped_events(self):
        """Watch events the standby drained-and-dropped must be re-derived
        on its first leader pass: a NodePool spec change made while standing
        by still gets its hash annotation updated after takeover."""
        from karpenter_tpu.apis import labels as wk

        clock, store, op1, op2 = self._two_operators()
        clock.step(2.0)
        op1.run_once()
        op2.run_once()
        pool = store.get("NodePool", "workers")
        old_hash = pool.metadata.annotations[wk.NODEPOOL_HASH_ANNOTATION_KEY]
        # spec change while op2 stands by: op2 drains+drops the event
        pool.spec.template.spec.expire_after = 12345.0
        store.update(pool)
        clock.step(2.0)
        op2.run_once()
        # op1 crashes; op2 takes over after lease expiry
        clock.step(LEASE_DURATION + 0.1)
        op2.run_once()
        assert op2.elector.is_leader()
        new_hash = store.get("NodePool", "workers").metadata.annotations[
            wk.NODEPOOL_HASH_ANNOTATION_KEY
        ]
        assert new_hash != old_hash, "resync must re-reconcile the NodePool"

    def test_clean_shutdown_fails_over_without_wait(self):
        clock, store, op1, op2 = self._two_operators()
        clock.step(2.0)
        op1.run_once()
        op2.run_once()
        op1.shutdown()
        clock.step(2.0)  # far less than LEASE_DURATION
        op2.run_once()
        assert op2.elector.is_leader()

    def test_disabled_both_run(self):
        """--disable-leader-election: both replicas run their loops (and
        demonstrably double-provision — the hazard the lease prevents)."""
        clock, store, op1, op2 = self._two_operators(disable=True)
        for _ in range(3):
            clock.step(2.0)
            op1.run_once()
            op2.run_once()
        assert op1.elector.is_leader() and op2.elector.is_leader()
        assert store.try_get("Lease", LEASE_NAME) is None
        assert len(store.list("NodeClaim")) >= 1

    def test_master_status_metric_exposed(self):
        clock, store, op1, op2 = self._two_operators()
        clock.step(2.0)
        op1.run_once()
        op2.run_once()
        text = op1.metrics_text()
        assert "leader_election_master_status" in text
        from karpenter_tpu.operator.leaderelection import _MASTER_STATUS

        assert _MASTER_STATUS.value({"name": op1.elector.identity}) == 1.0
        assert _MASTER_STATUS.value({"name": op2.elector.identity}) == 0.0
