"""Scheduling oracle: specs ported from the reference's scheduling suite
(pkg/controllers/provisioning/scheduling/suite_test.go — spec names kept,
reference line cited per test class), each run against BOTH solver paths:

- host:   the per-pod FFD loop (engine off)
- device: the batched fast path (engine on, DEVICE_MIN_PODS patched to 1)

Device runs assert DEVICE_SOLVES advanced; the ONE feature the device path
intentionally declines (BestEffort minValues relaxation) asserts the
fallback EXPLICITLY, so eligibility regressions can't hide. Hostname
selectors, reserved capacity in both offering modes, and strict minValues
all RUN on the device path since round 4.
Topology and preferred-affinity/relaxation specs run the topo-aware driver
(ops/ffd_topo.py) and must match host decisions exactly. Deleting-node rescheduling specs
(suite_test.go:3545-3699) live with the provisioner/e2e tests instead —
they exercise provisioner machinery, not Scheduler.solve.
"""

import pytest

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import (
    Affinity,
    Container,
    NodeAffinity,
    NodeSelectorTerm,
    PreferredSchedulingTerm,
    Taint,
    Toleration,
)
from karpenter_tpu.cloudprovider.kwok.instance_types import construct_instance_types
from karpenter_tpu.ops import ffd
from karpenter_tpu.ops.catalog import CatalogEngine
from karpenter_tpu.utils.resources import parse_resource_list

from helpers import (
    bind_pod,
    daemonset,
    daemonset_pod,
    node_claim_pair,
    nodepool,
    registered_node,
    unschedulable_pod,
)
from test_scheduler import Env

CATALOG = construct_instance_types()


@pytest.fixture(params=["host", "device"])
def path(request, monkeypatch):
    if request.param == "device":
        monkeypatch.setattr(ffd, "DEVICE_MIN_PODS", 1)
        monkeypatch.setattr(ffd, "STRICT", True)
    return request.param


def make_env(path, **kwargs):
    if path == "device":
        kwargs.setdefault("engine", CatalogEngine(CATALOG))
    return Env(**kwargs)


def schedule(path, pods, device_falls_back=False, env=None, **env_kwargs):
    """Solve and enforce the expected device-path behavior."""
    if env is None:
        env = make_env(path, **env_kwargs)
    s0, f0 = ffd.DEVICE_SOLVES, ffd.DEVICE_FALLBACKS
    results = env.schedule(pods)
    if path == "device":
        if device_falls_back:
            assert ffd.DEVICE_FALLBACKS > f0, "expected the device path to decline"
        else:
            assert ffd.DEVICE_SOLVES > s0, "expected the device path to run"
    return results


def scheduled(results):
    return [p for nc in results.new_node_claims for p in nc.pods] + [
        p for en in results.existing_nodes for p in en.pods
    ]


def node_affinity(*terms, preferred=()):
    return Affinity(
        node_affinity=NodeAffinity(
            required=[NodeSelectorTerm(match_expressions=list(t)) for t in terms],
            preferred=list(preferred),
        )
    )


def req(key, operator, *values):
    return {"key": key, "operator": operator, "values": list(values)}


class TestNodeSelectors:
    """suite_test.go:151-260 (custom labels) / :525-705 (well-known)."""

    def test_unconstrained_pods_schedule(self, path):
        results = schedule(path, [unschedulable_pod()])
        assert len(results.new_node_claims) == 1

    def test_matching_value_in_operator(self, path):
        pod = unschedulable_pod(node_selector={wk.LABEL_TOPOLOGY_ZONE: "kwok-zone-2"})
        results = schedule(path, [pod])
        [nc] = results.new_node_claims
        assert nc.requirements.get(wk.LABEL_TOPOLOGY_ZONE).has("kwok-zone-2")

    def test_matching_value_not_in_operator_fails(self, path):
        # nodepool pinned to zone-2; pod NotIn zone-2 → nothing left
        pools = [
            nodepool(
                "default",
                requirements=[req(wk.LABEL_TOPOLOGY_ZONE, "In", "kwok-zone-2")],
            )
        ]
        pod = unschedulable_pod(
            affinity=node_affinity(
                [req(wk.LABEL_TOPOLOGY_ZONE, "NotIn", "kwok-zone-2")]
            )
        )
        results = schedule(path, [pod], node_pools=pools)
        assert results.pod_errors

    def test_different_value_not_in_operator_schedules(self, path):
        pod = unschedulable_pod(
            affinity=node_affinity(
                [req(wk.LABEL_TOPOLOGY_ZONE, "NotIn", "kwok-zone-2")]
            )
        )
        results = schedule(path, [pod])
        [nc] = results.new_node_claims
        assert not nc.requirements.get(wk.LABEL_TOPOLOGY_ZONE).has("kwok-zone-2")

    def test_in_operator_undefined_key_fails(self, path):
        results = schedule(
            path, [unschedulable_pod(node_selector={"undefined-key": "value"})]
        )
        assert len(results.pod_errors) == 1

    def test_not_in_operator_undefined_key_schedules(self, path):
        pod = unschedulable_pod(
            affinity=node_affinity([req("undefined-key", "NotIn", "value")])
        )
        results = schedule(path, [pod])
        assert not results.pod_errors

    def test_exists_operator_undefined_key_fails(self, path):
        pod = unschedulable_pod(affinity=node_affinity([req("undefined-key", "Exists")]))
        results = schedule(path, [pod])
        assert len(results.pod_errors) == 1

    def test_does_not_exist_operator_undefined_key_schedules(self, path):
        pod = unschedulable_pod(
            affinity=node_affinity([req("undefined-key", "DoesNotExist")])
        )
        results = schedule(path, [pod])
        assert not results.pod_errors

    def test_exists_operator_defined_key_schedules(self, path):
        pools = [nodepool("default", labels={"team": "infra"})]
        pod = unschedulable_pod(affinity=node_affinity([req("team", "Exists")]))
        results = schedule(path, [pod], node_pools=pools)
        assert not results.pod_errors

    def test_does_not_exist_operator_defined_key_fails(self, path):
        pools = [nodepool("default", labels={"team": "infra"})]
        pod = unschedulable_pod(affinity=node_affinity([req("team", "DoesNotExist")]))
        results = schedule(path, [pod], node_pools=pools)
        assert len(results.pod_errors) == 1

    def test_hostname_selector_not_schedulable(self, path):
        # suite_test.go:221 — placeholder hostnames never match a selector
        pod = unschedulable_pod(node_selector={wk.LABEL_HOSTNAME: "some-node"})
        results = schedule(path, [pod])
        assert len(results.pod_errors) == 1
        [err] = results.pod_errors.values()
        assert "incompatible requirements" in str(err)
        assert wk.LABEL_HOSTNAME in str(err)

    def test_hostname_selector_matches_existing_node(self, path):
        """A hostname-pinned pod can only land on the named existing node."""
        node = registered_node(
            name="pinned-node", pool="default",
            capacity={"cpu": "16", "memory": "64Gi", "pods": "110"},
        )
        pod = unschedulable_pod(
            requests={"cpu": "1"},
            node_selector={wk.LABEL_HOSTNAME: "pinned-node"},
        )
        filler = [unschedulable_pod(requests={"cpu": "1"}) for _ in range(3)]
        results = schedule(path, [pod] + filler, state_nodes=[node])
        assert not results.pod_errors
        [en] = [e for e in results.existing_nodes if e.pods]
        # the host loop binds deepcopies — compare by name
        assert pod.metadata.name in {p.metadata.name for p in en.pods}

    def test_hostname_not_in_schedules_anywhere(self, path):
        """NotIn hostname rows are satisfied by any placeholder — the pod
        packs onto new claims normally (double-negative carve-out)."""
        pod = unschedulable_pod(
            requests={"cpu": "1"},
            affinity=node_affinity(
                [req(wk.LABEL_HOSTNAME, "NotIn", "forbidden-node")]
            ),
        )
        others = [unschedulable_pod(requests={"cpu": "1"}) for _ in range(3)]
        results = schedule(path, [pod] + others)
        assert not results.pod_errors
        assert len(results.new_node_claims) == 1

    def test_selector_outside_nodepool_constraints_fails(self, path):
        pools = [
            nodepool(
                "default",
                requirements=[req(wk.LABEL_TOPOLOGY_ZONE, "In", "kwok-zone-1")],
            )
        ]
        pod = unschedulable_pod(node_selector={wk.LABEL_TOPOLOGY_ZONE: "kwok-zone-2"})
        results = schedule(path, [pod], node_pools=pools)
        assert len(results.pod_errors) == 1

    def test_nodepool_constraints_narrow_claims(self, path):
        pools = [
            nodepool(
                "default",
                requirements=[req(wk.LABEL_TOPOLOGY_ZONE, "In", "kwok-zone-3")],
            )
        ]
        results = schedule(path, [unschedulable_pod()], node_pools=pools)
        [nc] = results.new_node_claims
        assert set(nc.requirements.get(wk.LABEL_TOPOLOGY_ZONE).values_list()) == {
            "kwok-zone-3"
        }

    def test_compatible_pods_share_node(self, path):
        # suite_test.go:604 — zone In [1,2] and zone In [2,3] intersect
        a = unschedulable_pod(
            affinity=node_affinity(
                [req(wk.LABEL_TOPOLOGY_ZONE, "In", "kwok-zone-1", "kwok-zone-2")]
            )
        )
        b = unschedulable_pod(
            affinity=node_affinity(
                [req(wk.LABEL_TOPOLOGY_ZONE, "In", "kwok-zone-2", "kwok-zone-3")]
            )
        )
        results = schedule(path, [a, b])
        assert len(results.new_node_claims) == 1
        [nc] = results.new_node_claims
        assert set(nc.requirements.get(wk.LABEL_TOPOLOGY_ZONE).values_list()) == {
            "kwok-zone-2"
        }

    def test_incompatible_pods_get_different_nodes(self, path):
        a = unschedulable_pod(node_selector={wk.LABEL_TOPOLOGY_ZONE: "kwok-zone-1"})
        b = unschedulable_pod(node_selector={wk.LABEL_TOPOLOGY_ZONE: "kwok-zone-2"})
        results = schedule(path, [a, b])
        assert len(results.new_node_claims) == 2

    def test_restricted_label_rejected(self, path):
        pod = unschedulable_pod(node_selector={"karpenter.sh/nodepool-hash": "x"})
        results = schedule(path, [pod])
        assert len(results.pod_errors) == 1

    def test_restricted_domain_rejected(self, path):
        pod = unschedulable_pod(node_selector={"kubernetes.io/custom": "x"})
        results = schedule(path, [pod])
        assert len(results.pod_errors) == 1

    def test_restricted_domain_exception_allowed(self, path):
        # subdomains of node-restriction.kubernetes.io are user-allowed
        pools = [
            nodepool(
                "default",
                labels={"node-restriction.kubernetes.io/team": "infra"},
            )
        ]
        pod = unschedulable_pod(
            node_selector={"node-restriction.kubernetes.io/team": "infra"}
        )
        results = schedule(path, [pod], node_pools=pools)
        assert not results.pod_errors

    def test_not_ready_nodepool_unused(self, path):
        # readiness filtering happens at nodepool listing (provisioner.go:220)
        from karpenter_tpu.utils import nodepool as nodepoolutil

        env = make_env(path)
        pool = env.node_pools[0]
        pool.set_condition("Ready", "False")
        env.store.apply(pool)
        assert nodepoolutil.list_managed(env.store, ready_only=True) == []


class TestRequirementOperators:
    """suite_test.go:249-309 — Gt/Lt and compatible/conflicting sets. The
    kwok catalog carries no integer-valued label, so these specs annotate
    each type with example.com/cpus (the reference uses a fake label too)."""

    CPU_LABEL = "example.com/cpus"

    @classmethod
    def _int_catalog(cls):
        from karpenter_tpu.cloudprovider.types import InstanceType
        from karpenter_tpu.scheduling.requirements import (
            Operator,
            Requirement,
            Requirements,
        )

        out = []
        for it in CATALOG[::4]:
            reqs = Requirements(*it.requirements.values())
            reqs.add(
                Requirement(
                    cls.CPU_LABEL, Operator.IN, [str(int(float(it.capacity["cpu"])))]
                )
            )
            out.append(
                InstanceType(
                    name=it.name,
                    requirements=reqs,
                    offerings=it.offerings,
                    capacity=it.capacity,
                    overhead=it.overhead,
                )
            )
        return out

    def _env(self, path):
        catalog = self._int_catalog()
        # custom labels become "known" through the nodepool (labels outside
        # the well-known set must be declared; requirements.go:170-191)
        pools = [nodepool("default", requirements=[req(self.CPU_LABEL, "Exists")])]
        kwargs = {"catalog": catalog, "node_pools": pools}
        if path == "device":
            kwargs["engine"] = CatalogEngine(catalog)
        return Env(**kwargs)

    def test_gt_operator(self, path):
        pod = unschedulable_pod(
            affinity=node_affinity([req(self.CPU_LABEL, "Gt", "8")])
        )
        results = schedule(path, [pod], env=self._env(path))
        assert not results.pod_errors
        [nc] = results.new_node_claims
        for it in nc.instance_type_options:
            assert int(it.requirements.get(self.CPU_LABEL).any()) > 8

    def test_lt_operator(self, path):
        pod = unschedulable_pod(
            affinity=node_affinity([req(self.CPU_LABEL, "Lt", "2")])
        )
        results = schedule(path, [pod], env=self._env(path))
        assert not results.pod_errors
        [nc] = results.new_node_claims
        for it in nc.instance_type_options:
            assert int(it.requirements.get(self.CPU_LABEL).any()) < 2

    def test_conflicting_requirements_fail(self, path):
        pod = unschedulable_pod(
            affinity=node_affinity(
                [
                    req(wk.LABEL_TOPOLOGY_ZONE, "In", "kwok-zone-1"),
                    req(wk.LABEL_TOPOLOGY_ZONE, "In", "kwok-zone-2"),
                ]
            )
        )
        results = schedule(path, [pod])
        assert len(results.pod_errors) == 1

    def test_conflicting_gt_lt_fail(self, path):
        pod = unschedulable_pod(
            affinity=node_affinity(
                [
                    req(self.CPU_LABEL, "Gt", "8"),
                    req(self.CPU_LABEL, "Lt", "4"),
                ]
            )
        )
        results = schedule(path, [pod], env=self._env(path))
        assert len(results.pod_errors) == 1


class TestPreferences:
    """suite_test.go:310-363, 1106-1225 — the relaxation ladder. Preferred
    terms make shapes ineligible for the device path by design."""

    def _preferred(self, weight, *exprs):
        return PreferredSchedulingTerm(
            weight=weight,
            preference=NodeSelectorTerm(match_expressions=list(exprs)),
        )

    def test_compatible_preference_honored(self, path):
        pod = unschedulable_pod(
            affinity=Affinity(
                node_affinity=NodeAffinity(
                    preferred=[
                        self._preferred(
                            1, req(wk.LABEL_TOPOLOGY_ZONE, "In", "kwok-zone-2")
                        )
                    ]
                )
            )
        )
        results = schedule(path, [pod])
        [nc] = results.new_node_claims
        assert set(nc.requirements.get(wk.LABEL_TOPOLOGY_ZONE).values_list()) == {
            "kwok-zone-2"
        }

    def test_incompatible_preference_relaxed_away(self, path):
        pools = [
            nodepool(
                "default",
                requirements=[req(wk.LABEL_TOPOLOGY_ZONE, "In", "kwok-zone-1")],
            )
        ]
        pod = unschedulable_pod(
            affinity=Affinity(
                node_affinity=NodeAffinity(
                    preferred=[
                        self._preferred(
                            1, req(wk.LABEL_TOPOLOGY_ZONE, "In", "kwok-zone-2")
                        )
                    ]
                )
            )
        )
        results = schedule(path, [pod], node_pools=pools)
        assert not results.pod_errors

    def test_relax_to_lighter_weights_first(self, path):
        # heavier preferred terms survive longer (preferences.go:60-77)
        pod = unschedulable_pod(
            affinity=Affinity(
                node_affinity=NodeAffinity(
                    preferred=[
                        self._preferred(
                            1, req(wk.LABEL_TOPOLOGY_ZONE, "In", "kwok-zone-2")
                        ),
                        self._preferred(
                            10, req(wk.LABEL_TOPOLOGY_ZONE, "In", "kwok-zone-3")
                        ),
                    ]
                )
            )
        )
        results = schedule(path, [pod])
        [nc] = results.new_node_claims
        assert set(nc.requirements.get(wk.LABEL_TOPOLOGY_ZONE).values_list()) == {
            "kwok-zone-3"
        }

    def test_required_terms_never_relaxed(self, path):
        pod = unschedulable_pod(
            affinity=node_affinity([req(wk.LABEL_TOPOLOGY_ZONE, "In", "no-such-zone")])
        )
        results = schedule(path, [pod])
        assert len(results.pod_errors) == 1

    def test_preference_conflicting_with_requirement_schedules(self, path):
        pod = unschedulable_pod(
            affinity=Affinity(
                node_affinity=NodeAffinity(
                    required=[
                        NodeSelectorTerm(
                            match_expressions=[
                                req(wk.LABEL_TOPOLOGY_ZONE, "In", "kwok-zone-1")
                            ]
                        )
                    ],
                    preferred=[
                        self._preferred(
                            1, req(wk.LABEL_TOPOLOGY_ZONE, "In", "kwok-zone-2")
                        )
                    ],
                )
            )
        )
        results = schedule(path, [pod])
        assert not results.pod_errors
        [nc] = results.new_node_claims
        assert set(nc.requirements.get(wk.LABEL_TOPOLOGY_ZONE).values_list()) == {
            "kwok-zone-1"
        }


class TestInstanceTypeSelection:
    """suite_test.go:1226-1457."""

    def test_more_resources_than_any_instance_type_fails(self, path):
        results = schedule(path, [unschedulable_pod(requests={"cpu": "512"})])
        assert len(results.pod_errors) == 1

    def test_different_archs_on_different_instances(self, path):
        a = unschedulable_pod(node_selector={wk.LABEL_ARCH: "amd64"})
        b = unschedulable_pod(node_selector={wk.LABEL_ARCH: "arm64"})
        results = schedule(path, [a, b])
        assert len(results.new_node_claims) == 2

    def test_different_operating_systems_on_different_instances(self, path):
        a = unschedulable_pod(node_selector={wk.LABEL_OS: "linux"})
        b = unschedulable_pod(node_selector={wk.LABEL_OS: "windows"})
        results = schedule(path, [a, b])
        assert len(results.new_node_claims) == 2

    def test_different_zone_selectors_on_different_instances(self, path):
        a = unschedulable_pod(node_selector={wk.LABEL_TOPOLOGY_ZONE: "kwok-zone-1"})
        b = unschedulable_pod(node_selector={wk.LABEL_TOPOLOGY_ZONE: "kwok-zone-4"})
        results = schedule(path, [a, b])
        assert len(results.new_node_claims) == 2

    def test_affinity_excludes_instance_types(self, path):
        pod = unschedulable_pod(
            affinity=node_affinity([req(wk.LABEL_INSTANCE_TYPE, "In", "c-4x-amd64-linux")])
        )
        results = schedule(path, [pod])
        [nc] = results.new_node_claims
        assert [it.name for it in nc.instance_type_options] == ["c-4x-amd64-linux"]

    def test_provider_arch_constraint(self, path):
        pools = [nodepool("default", requirements=[req(wk.LABEL_ARCH, "In", "arm64")])]
        results = schedule(path, [unschedulable_pod()], node_pools=pools)
        [nc] = results.new_node_claims
        for it in nc.instance_type_options:
            assert it.requirements.get(wk.LABEL_ARCH).has("arm64")


class TestBinpacking:
    """suite_test.go:1514-1754."""

    def test_small_pod_on_smallest_instance(self, path):
        results = schedule(path, [unschedulable_pod(requests={"cpu": "100m"})])
        [nc] = results.new_node_claims
        cpus = [float(it.capacity["cpu"]) for it in nc.instance_type_options]
        assert min(cpus) == 1.0  # smallest kwok size still offered

    def test_multiple_small_pods_pack_on_one_claim(self, path):
        pods = [unschedulable_pod(requests={"cpu": "10m"}) for _ in range(100)]
        results = schedule(path, pods)
        assert len(results.new_node_claims) == 1

    def test_new_node_when_at_capacity(self, path):
        # each pod takes >half the largest (256-cpu) kwok type
        pods = [unschedulable_pod(requests={"cpu": "150"}) for _ in range(4)]
        results = schedule(path, pods)
        assert len(results.new_node_claims) == 4

    def test_pack_small_and_large_pods_together(self, path):
        pods = (
            [unschedulable_pod(requests={"cpu": "4"}) for _ in range(4)]
            + [unschedulable_pod(requests={"cpu": "100m"}) for _ in range(8)]
        )
        results = schedule(path, pods)
        assert not results.pod_errors
        assert len(results.new_node_claims) <= 2

    def test_zero_quantity_requests(self, path):
        results = schedule(path, [unschedulable_pod(requests={"cpu": "0"})])
        assert not results.pod_errors

    def test_pods_per_node_limit_forces_new_node(self, path):
        # kwok types allocate pods=110; 111 tiny pods can't share one node
        pods = [unschedulable_pod(requests={"cpu": "1m"}) for _ in range(111)]
        results = schedule(path, pods)
        assert not results.pod_errors
        assert len(results.new_node_claims) >= 2

    def test_init_container_requests_counted(self, path):
        pod = unschedulable_pod(requests={"cpu": "1"})
        pod.spec.init_containers = [
            Container(requests=parse_resource_list({"cpu": "48"}))
        ]
        results = schedule(path, [pod])
        assert not results.pod_errors
        [nc] = results.new_node_claims
        for it in nc.instance_type_options:
            assert float(it.capacity["cpu"]) >= 48

    def test_oversized_init_container_fails(self, path):
        pod = unschedulable_pod(requests={"cpu": "1"})
        pod.spec.init_containers = [
            Container(requests=parse_resource_list({"cpu": "512"}))
        ]
        results = schedule(path, [pod])
        assert len(results.pod_errors) == 1


class TestInFlightNodes:
    """suite_test.go:1831-2204 — existing/in-flight capacity reuse."""

    def _env_with_node(self, path, **node_kwargs):
        node, claim = node_claim_pair("existing-1", **node_kwargs)
        return make_env(path, state_nodes=[node, claim])

    def test_no_second_node_if_existing_supports_pod(self, path):
        env = self._env_with_node(path)
        results = schedule(path, [unschedulable_pod(requests={"cpu": "1"})], env=env)
        assert not results.new_node_claims
        assert sum(len(en.pods) for en in results.existing_nodes) == 1

    def test_no_second_node_with_matching_selector(self, path):
        env = self._env_with_node(path, zone="kwok-zone-2")
        pod = unschedulable_pod(
            requests={"cpu": "1"},
            node_selector={wk.LABEL_TOPOLOGY_ZONE: "kwok-zone-2"},
        )
        results = schedule(path, [pod], env=env)
        assert not results.new_node_claims

    def test_second_node_if_pod_does_not_fit(self, path):
        env = self._env_with_node(path)  # 4-cpu node
        results = schedule(path, [unschedulable_pod(requests={"cpu": "16"})], env=env)
        assert len(results.new_node_claims) == 1

    def test_second_node_if_selector_incompatible(self, path):
        env = self._env_with_node(path, zone="kwok-zone-1")
        pod = unschedulable_pod(
            requests={"cpu": "1"},
            node_selector={wk.LABEL_TOPOLOGY_ZONE: "kwok-zone-2"},
        )
        results = schedule(path, [pod], env=env)
        assert len(results.new_node_claims) == 1

    def test_terminating_node_not_reused(self, path):
        # the provisioner hands the scheduler only active() nodes
        # (provisioner.go:294); a deleting node's capacity is gone
        from karpenter_tpu.state.statenode import active

        node, claim = node_claim_pair("terminating-1")
        claim.metadata.deletion_timestamp = 1.0
        env = make_env(path, state_nodes=[node, claim])
        assert active(env.cluster.state_nodes()) == []

    def test_pods_pack_existing_before_new(self, path):
        env = self._env_with_node(path)  # 4 cpu
        pods = [unschedulable_pod(requests={"cpu": "1"}) for _ in range(6)]
        results = schedule(path, pods, env=env)
        assert sum(len(en.pods) for en in results.existing_nodes) >= 3
        assert len(results.new_node_claims) == 1


class TestTaintAssumptions:
    """suite_test.go:2019-2175 — ephemeral/startup taints on in-flight
    nodes are invisible until initialization."""

    def _uninitialized(self, name, node_taints=(), startup_taints=()):
        node, claim = node_claim_pair(name)
        node.metadata.labels[wk.NODE_INITIALIZED_LABEL_KEY] = "false"
        node.spec.taints = list(node_taints)
        claim.set_condition("Initialized", "False")
        claim.spec.startup_taints = list(startup_taints)
        return node, claim

    def test_assume_ephemeral_not_ready_taint_uninitialized(self, path):
        node, claim = self._uninitialized(
            "nn-1",
            node_taints=[
                Taint(key=wk.TAINT_NODE_NOT_READY, value="", effect="NoExecute")
            ],
        )
        env = make_env(path, state_nodes=[node, claim])
        results = schedule(path, [unschedulable_pod(requests={"cpu": "1"})], env=env)
        assert not results.new_node_claims

    def test_not_assume_arbitrary_taint(self, path):
        node, claim = self._uninitialized(
            "nn-2",
            node_taints=[Taint(key="team", value="infra", effect="NoSchedule")],
        )
        env = make_env(path, state_nodes=[node, claim])
        results = schedule(path, [unschedulable_pod(requests={"cpu": "1"})], env=env)
        assert len(results.new_node_claims) == 1

    def test_assume_custom_startup_taint(self, path):
        startup = Taint(key="example.com/agent", value="", effect="NoSchedule")
        node, claim = self._uninitialized(
            "nn-3", node_taints=[startup], startup_taints=[startup]
        )
        env = make_env(path, state_nodes=[node, claim])
        results = schedule(path, [unschedulable_pod(requests={"cpu": "1"})], env=env)
        assert not results.new_node_claims

    def test_startup_taint_respected_after_initialization(self, path):
        startup = Taint(key="example.com/agent", value="", effect="NoSchedule")
        node, claim = node_claim_pair("nn-4")
        node.spec.taints = [startup]
        claim.spec.startup_taints = [startup]
        env = make_env(path, state_nodes=[node, claim])
        results = schedule(path, [unschedulable_pod(requests={"cpu": "1"})], env=env)
        assert len(results.new_node_claims) == 1


class TestDaemonSetOverhead:
    """suite_test.go:2204-2348."""

    def test_daemonset_overhead_reserved_per_claim(self, path):
        ds = daemonset(requests={"cpu": "1"})
        env = make_env(path, daemonset_pods=[daemonset_pod(ds)])
        pods = [unschedulable_pod(requests={"cpu": "3"})]
        results = schedule(path, pods, env=env)
        [nc] = results.new_node_claims
        assert nc.requests.get("cpu", 0) >= 4.0

    def test_incompatible_daemonset_not_counted(self, path):
        # overhead is computed per nodeclaim TEMPLATE: a daemonset whose
        # selector the nodepool can't satisfy adds nothing
        ds = daemonset(requests={"cpu": "1"})
        ds_pod = daemonset_pod(ds)
        ds_pod.spec.node_selector = {wk.LABEL_ARCH: "arm64"}
        pools = [nodepool("default", requirements=[req(wk.LABEL_ARCH, "In", "amd64")])]
        env = make_env(path, node_pools=pools, daemonset_pods=[ds_pod])
        pod = unschedulable_pod(requests={"cpu": "3"})
        results = schedule(path, [pod], env=env)
        [nc] = results.new_node_claims
        assert nc.requests.get("cpu", 0) == pytest.approx(3.0)


class TestErrorSurfacing:
    """suite_test.go:4460-4573 — pod errors carry filter diagnostics."""

    def test_error_when_no_instance_types(self, path):
        pool = nodepool(
            "default", requirements=[req(wk.LABEL_INSTANCE_TYPE, "In", "nope")]
        )
        results = schedule(path, [unschedulable_pod()], node_pools=[pool])
        [err] = list(results.pod_errors.values())
        assert "instance type" in str(err) or "requirements" in str(err)

    def test_multiple_pods_all_filtered(self, path):
        pool = nodepool(
            "default", requirements=[req(wk.LABEL_TOPOLOGY_ZONE, "In", "no-zone")]
        )
        pods = [unschedulable_pod() for _ in range(3)]
        results = schedule(path, pods, node_pools=[pool])
        assert len(results.pod_errors) == 3

    def test_zone_requirement_filters_all(self, path):
        pod = unschedulable_pod(node_selector={wk.LABEL_TOPOLOGY_ZONE: "mars"})
        results = schedule(path, [pod])
        assert len(results.pod_errors) == 1

    def test_resources_error_mentions_resources(self, path):
        results = schedule(path, [unschedulable_pod(requests={"cpu": "9999"})])
        [err] = list(results.pod_errors.values())
        assert "resources" in str(err)


class TestSchedulerMetrics:
    """suite_test.go:3839-3905 — host-path self-measurement."""

    def test_scheduling_duration_recorded(self):
        from karpenter_tpu.scheduler.scheduler import _DURATION_HIST

        before = _DURATION_HIST.count()
        schedule("host", [unschedulable_pod()])
        assert _DURATION_HIST.count() == before + 1

    def test_unschedulable_pods_count_surfaced(self):
        from karpenter_tpu.scheduler.scheduler import _UNSCHEDULABLE_GAUGE

        schedule("host", [unschedulable_pod(requests={"cpu": "9999"})])
        assert _UNSCHEDULABLE_GAUGE.value() == 1.0

    def test_queue_depth_surfaced_while_solving(self, monkeypatch):
        """suite_test.go 'should surface the queueDepth metric while
        executing the scheduling loop': the gauge carries the live queue
        size during the solve and its per-solve series is deleted after."""
        from karpenter_tpu.scheduler import scheduler as schedmod

        observed = []
        real_set = schedmod._QUEUE_DEPTH.set
        monkeypatch.setattr(
            schedmod._QUEUE_DEPTH, "set",
            lambda value, labels=None: (observed.append(value), real_set(value, labels)),
        )
        schedule("host", [unschedulable_pod() for _ in range(5)])
        assert observed and observed[0] == 5.0
        assert schedmod._QUEUE_DEPTH.series() == {}, "per-solve series must not leak"

    def test_unfinished_work_seconds_surfaced_and_cleared(self, monkeypatch):
        from karpenter_tpu.scheduler import scheduler as schedmod

        observed = []
        real_set = schedmod._UNFINISHED_WORK.set
        monkeypatch.setattr(
            schedmod._UNFINISHED_WORK, "set",
            lambda value, labels=None: (observed.append(value), real_set(value, labels)),
        )
        schedule("host", [unschedulable_pod()])
        assert observed == [0.0]
        assert schedmod._UNFINISHED_WORK.series() == {}

    def test_ignored_pods_count_surfaced(self):
        """provisioning suite 'invalid pvc' spec: pods failing validation
        count into karpenter_scheduler_ignored_pods_count
        (provisioner.go:177)."""
        from helpers import make_provisioner_harness
        from karpenter_tpu.apis.core import Volume
        from karpenter_tpu.controllers.provisioning.provisioner import _IGNORED_PODS

        clock, store, provider, cluster, informer, prov = make_provisioner_harness()
        store.create(nodepool("default"))
        pod = unschedulable_pod()
        pod.spec.volumes = [Volume(name="data", persistent_volume_claim="invalid")]
        store.create(pod)
        informer.flush()
        assert prov.get_pending_pods() == []
        assert _IGNORED_PODS.value() == 1.0


class TestHostPortsBothPaths:
    """Host-port conflict semantics on BOTH paths (hostportusage.go:35-120;
    ports shapes run the topo driver's volatile paths)."""

    def _port_pod(self, port=8080, ip="", protocol="TCP", **kwargs):
        from karpenter_tpu.apis.core import ContainerPort

        p = unschedulable_pod(requests={"cpu": "100m"}, **kwargs)
        p.spec.containers[0].ports = [
            ContainerPort(container_port=80, host_port=port, host_ip=ip, protocol=protocol)
        ]
        return p

    def test_same_host_port_forces_separate_claims(self, path):
        results = schedule(path, [self._port_pod() for _ in range(3)])
        assert not results.pod_errors
        assert len(results.new_node_claims) == 3

    def test_distinct_ips_share_a_claim(self, path):
        pods = [
            self._port_pod(ip="10.0.0.1"),
            self._port_pod(ip="10.0.0.2"),
        ]
        results = schedule(path, pods)
        assert not results.pod_errors
        assert len(results.new_node_claims) == 1

    def test_wildcard_conflicts_with_specific_ip(self, path):
        pods = [self._port_pod(ip=""), self._port_pod(ip="10.0.0.1")]
        results = schedule(path, pods)
        assert not results.pod_errors
        assert len(results.new_node_claims) == 2

    def test_different_protocols_share_a_claim(self, path):
        pods = [self._port_pod(protocol="TCP"), self._port_pod(protocol="UDP")]
        results = schedule(path, pods)
        assert not results.pod_errors
        assert len(results.new_node_claims) == 1

    def test_port_pod_avoids_conflicting_existing_node(self, path):
        # an existing node already running the port forces a new claim
        node = registered_node(name="port-node", pool="default")
        occupant = self._port_pod(name="occupant")
        bind_pod(occupant, node)
        env = make_env(path, state_nodes=[node], pods=[occupant])
        results = schedule(path, [self._port_pod(name="newcomer")], env=env)
        assert not results.pod_errors
        assert len(results.new_node_claims) == 1
        assert all(not en.pods for en in results.existing_nodes)

    def test_init_container_host_ports_conflict(self, path):
        # host ports on INIT containers must route to the topo driver too
        # (the eligibility gate covers both container lists)
        from karpenter_tpu.apis.core import Container, ContainerPort

        pods = []
        for i in range(3):
            p = unschedulable_pod(name=f"initport-{i}", requests={"cpu": "100m"})
            p.spec.init_containers = [
                Container(
                    requests={},
                    ports=[ContainerPort(container_port=80, host_port=8080)],
                )
            ]
            pods.append(p)
        results = schedule(path, pods)
        assert not results.pod_errors
        assert len(results.new_node_claims) == 3

    def test_abort_restores_existing_node_port_usage(self):
        # a mid-solve fallback must not leave phantom port entries on the
        # SHARED state-node usage the host fallback then reads
        from karpenter_tpu.ops import ffd_topo

        node = registered_node(name="pn1", pool="default")
        env = make_env("device", state_nodes=[node])
        pods = [self._port_pod(name=f"pp-{i}") for i in range(2)]
        for i, p in enumerate(pods):
            p.metadata.uid = f"pp-uid-{i}"
        state_nodes = env.cluster.state_nodes()
        from karpenter_tpu.scheduler.topology import Topology
        from karpenter_tpu.scheduler.scheduler import Scheduler

        topology = Topology(
            env.store, env.cluster, state_nodes, env.node_pools,
            env.instance_types, pods,
        )
        scheduler = Scheduler(
            env.store, env.node_pools, env.cluster, state_nodes, topology,
            env.instance_types, [], env.recorder, env.clock,
            engine=env.scheduler_kwargs["engine"],
        )
        sn = state_nodes[0]
        assert not sn.hostport_usage
        solve = ffd_topo._TopoSolve(scheduler, pods)
        solve.run(60.0)
        # copy-on-write: the join forks usage onto the ExistingNode; the
        # shared StateNode must stay pristine throughout
        en = scheduler.existing_nodes[0]
        assert en.hostport_usage, "expected a port join on the existing node"
        assert not sn.hostport_usage, "solve wrote through the StateNode"
        solve.abort()
        assert not en.hostport_usage, "abort left phantom port entries"
        assert not sn.hostport_usage

    def test_abort_restores_existing_node_volume_usage(self):
        # volume twin of the port rollback spec: a mid-solve fallback must
        # not leave phantom PVC attach counts on the shared state node
        from karpenter_tpu.apis.core import (
            CSINode,
            CSINodeDriver,
            ObjectMeta,
            PersistentVolumeClaim,
            StorageClass,
            Volume,
        )
        from karpenter_tpu.ops import ffd_topo
        from karpenter_tpu.scheduler.scheduler import Scheduler
        from karpenter_tpu.scheduler.topology import Topology

        driver = "ebs.csi.example.com"
        env = make_env("device")
        env.store.create(
            StorageClass(metadata=ObjectMeta(name="fast"), provisioner=driver)
        )
        env.store.create(
            CSINode(
                metadata=ObjectMeta(name="vn1"),
                drivers=[CSINodeDriver(name=driver, allocatable_count=4)],
            )
        )
        env.store.create(registered_node(name="vn1", pool="default"))
        env.informer.flush()
        pods = []
        for i in range(2):
            env.store.create(
                PersistentVolumeClaim(
                    metadata=ObjectMeta(name=f"rb-pvc-{i}"), storage_class_name="fast"
                )
            )
            p = unschedulable_pod(
                name=f"vp-{i}",
                requests={"cpu": "100m"},
                volumes=[Volume(name="data", persistent_volume_claim=f"rb-pvc-{i}")],
            )
            p.metadata.uid = f"vp-uid-{i}"
            pods.append(p)
        state_nodes = env.cluster.state_nodes()
        topology = Topology(
            env.store, env.cluster, state_nodes, env.node_pools,
            env.instance_types, pods,
        )
        scheduler = Scheduler(
            env.store, env.node_pools, env.cluster, state_nodes, topology,
            env.instance_types, [], env.recorder, env.clock,
            engine=env.scheduler_kwargs["engine"],
        )
        sn = state_nodes[0]
        solve = ffd_topo._TopoSolve(scheduler, pods)
        solve.run(60.0)
        # copy-on-write: the fork on the ExistingNode carries the joins,
        # the shared StateNode stays pristine
        en = scheduler.existing_nodes[0]
        assert en.volume_usage._volumes, "expected a volume join on the node"
        assert not sn.volume_usage._volumes, "solve wrote through the StateNode"
        solve.abort()
        assert not en.volume_usage._volumes, "abort left phantom volume entries"


class TestNodePoolSelection:
    """provisioning/suite_test.go:2521-2628 — which pool hosts a pod."""

    def test_schedules_to_explicitly_selected_nodepool(self, path):
        pools = [nodepool("target"), nodepool("other")]
        pod = unschedulable_pod(node_selector={wk.NODEPOOL_LABEL_KEY: "target"})
        results = schedule(path, [pod], node_pools=pools)
        [nc] = results.new_node_claims
        assert nc.nodepool_name == "target"

    def test_schedules_to_nodepool_by_template_labels(self, path):
        pools = [nodepool("labeled", labels={"foo": "bar"}), nodepool("plain")]
        pod = unschedulable_pod(node_selector={"foo": "bar"})
        results = schedule(path, [pod], node_pools=pools)
        [nc] = results.new_node_claims
        assert nc.nodepool_name == "labeled"

    def test_avoids_prefer_no_schedule_pool_when_another_matches(self, path):
        from karpenter_tpu.apis.core import Taint

        tainted = nodepool(
            "soft-tainted",
            taints=[Taint(key="foo", value="bar", effect="PreferNoSchedule")],
        )
        pools = [tainted, nodepool("clean")]
        results = schedule(path, [unschedulable_pod()], node_pools=pools)
        [nc] = results.new_node_claims
        assert nc.nodepool_name == "clean"

    def test_highest_weight_pool_always_wins(self, path):
        pools = [
            nodepool("w0"),
            nodepool("w20", weight=20),
            nodepool("w100", weight=100),
        ]
        pods = [unschedulable_pod() for _ in range(3)]
        results = schedule(path, pods, node_pools=pools)
        assert not results.pod_errors
        for nc in results.new_node_claims:
            assert nc.nodepool_name == "w100"

    def test_explicit_selection_beats_weight(self, path):
        pools = [nodepool("target"), nodepool("heavy", weight=100)]
        pod = unschedulable_pod(node_selector={wk.NODEPOOL_LABEL_KEY: "target"})
        results = schedule(path, [pod], node_pools=pools)
        [nc] = results.new_node_claims
        assert nc.nodepool_name == "target"


class TestCapacityShapes:
    """provisioning/suite_test.go:413-458 — accelerators and maxPods."""

    @staticmethod
    def _gpu_catalog():
        from karpenter_tpu.cloudprovider.types import (
            InstanceType,
            Offering,
            Offerings,
        )
        from karpenter_tpu.scheduling.requirements import (
            Operator,
            Requirement,
            Requirements,
        )

        def it(name, extra_resources, pods="110"):
            cap = parse_resource_list({"cpu": "8", "memory": "32Gi", "pods": pods})
            cap.update(parse_resource_list(extra_resources))
            return InstanceType(
                name=name,
                requirements=Requirements(
                    Requirement(wk.LABEL_INSTANCE_TYPE, Operator.IN, [name]),
                    Requirement(wk.LABEL_ARCH, Operator.IN, ["amd64"]),
                    Requirement(wk.LABEL_OS, Operator.IN, ["linux"]),
                    Requirement(
                        wk.LABEL_TOPOLOGY_ZONE, Operator.IN, ["kwok-zone-1"]
                    ),
                    Requirement(
                        wk.CAPACITY_TYPE_LABEL_KEY,
                        Operator.IN,
                        [wk.CAPACITY_TYPE_ON_DEMAND],
                    ),
                ),
                offerings=Offerings(
                    [
                        Offering(
                            requirements=Requirements(
                                Requirement(
                                    wk.CAPACITY_TYPE_LABEL_KEY,
                                    Operator.IN,
                                    [wk.CAPACITY_TYPE_ON_DEMAND],
                                ),
                                Requirement(
                                    wk.LABEL_TOPOLOGY_ZONE,
                                    Operator.IN,
                                    ["kwok-zone-1"],
                                ),
                            ),
                            price=1.0,
                            available=True,
                        )
                    ]
                ),
                capacity=cap,
            )

        return [
            it("gpu-vendor-a", {"vendor-a.example.com/gpu": "2"}),
            it("gpu-vendor-b", {"vendor-b.example.com/gpu": "2"}),
        ]

    def test_provisions_nodes_for_accelerators(self, path):
        """:413 — each pod lands on the type carrying its vendor's GPU."""
        catalog = self._gpu_catalog()
        kwargs = {"catalog": catalog}
        if path == "device":
            kwargs["engine"] = CatalogEngine(catalog)
        env = Env(**kwargs)
        pods = [
            unschedulable_pod(
                name="gpu-a", requests={"vendor-a.example.com/gpu": "1"}
            ),
            unschedulable_pod(
                name="gpu-b", requests={"vendor-b.example.com/gpu": "1"}
            ),
        ]
        results = schedule(path, pods, env=env)
        assert not results.pod_errors
        assert len(results.new_node_claims) == 2
        by_pod = {
            nc.pods[0].metadata.name: {it.name for it in nc.instance_type_options}
            for nc in results.new_node_claims
        }
        assert by_pod["gpu-a"] == {"gpu-vendor-a"}
        assert by_pod["gpu-b"] == {"gpu-vendor-b"}

    def test_provisions_multiple_nodes_when_max_pods_set(self, path):
        """:428 — a single-pod instance type forces one claim per pod."""
        from karpenter_tpu.cloudprovider.types import (
            InstanceType,
            Offering,
            Offerings,
        )
        from karpenter_tpu.scheduling.requirements import (
            Operator,
            Requirement,
            Requirements,
        )

        single = InstanceType(
            name="single-pod-instance-type",
            requirements=Requirements(
                Requirement(
                    wk.LABEL_INSTANCE_TYPE, Operator.IN, ["single-pod-instance-type"]
                ),
                Requirement(wk.LABEL_ARCH, Operator.IN, ["amd64"]),
                Requirement(wk.LABEL_OS, Operator.IN, ["linux"]),
                Requirement(wk.LABEL_TOPOLOGY_ZONE, Operator.IN, ["kwok-zone-1"]),
                Requirement(
                    wk.CAPACITY_TYPE_LABEL_KEY,
                    Operator.IN,
                    [wk.CAPACITY_TYPE_ON_DEMAND],
                ),
            ),
            offerings=Offerings(
                [
                    Offering(
                        requirements=Requirements(
                            Requirement(
                                wk.CAPACITY_TYPE_LABEL_KEY,
                                Operator.IN,
                                [wk.CAPACITY_TYPE_ON_DEMAND],
                            ),
                            Requirement(
                                wk.LABEL_TOPOLOGY_ZONE,
                                Operator.IN,
                                ["kwok-zone-1"],
                            ),
                        ),
                        price=0.5,
                        available=True,
                    )
                ]
            ),
            capacity=parse_resource_list(
                {"cpu": "16", "memory": "64Gi", "pods": "1"}
            ),
        )
        catalog = [single]
        kwargs = {"catalog": catalog}
        if path == "device":
            kwargs["engine"] = CatalogEngine(catalog)
        env = Env(**kwargs)
        pods = [unschedulable_pod(requests={"cpu": "100m"}) for _ in range(3)]
        results = schedule(path, pods, env=env)
        assert not results.pod_errors
        assert len(results.new_node_claims) == 3
        for nc in results.new_node_claims:
            assert len(nc.pods) == 1


class TestExplicitDeviceFallbacks:
    """The features the device path still declines must decline LOUDLY —
    these specs pin the eligibility gates (ffd.py eligible())."""

    def test_reserved_capacity_fallback_mode_runs_on_device(self, path):
        """Fallback-mode reserved capacity is device-supported since round 4:
        the claim reserves cr-1 and finalize pins it (nodeclaim.go:207-220)."""
        from karpenter_tpu.cloudprovider.types import RESERVATION_ID_LABEL

        from test_reserved_and_deleting import reserved_catalog

        catalog = reserved_catalog(reservation_capacity=1)
        kwargs = {"catalog": catalog}
        if path == "device":
            kwargs["engine"] = CatalogEngine(catalog)
        env = Env(**kwargs)
        results = schedule(
            path, [unschedulable_pod(requests={"cpu": "1"})], env=env,
        )
        assert not results.pod_errors
        [nc] = results.new_node_claims
        assert nc.requirements.get(wk.CAPACITY_TYPE_LABEL_KEY).has(
            wk.CAPACITY_TYPE_RESERVED
        )
        assert nc.requirements.get(RESERVATION_ID_LABEL).has("cr-1")

    def test_strict_reserved_runs_on_device(self, path):
        """Strict reserved mode runs on the all-volatile topo driver since
        round 4: successful solves reserve, exhaustion raises the host's
        scan-aborting ReservedOfferingError."""
        from karpenter_tpu.cloudprovider.types import RESERVATION_ID_LABEL
        from karpenter_tpu.scheduler.nodeclaim import RESERVED_OFFERING_MODE_STRICT

        from test_reserved_and_deleting import reserved_catalog

        catalog = reserved_catalog(reservation_capacity=2)
        kwargs = {
            "catalog": catalog,
            "reserved_offering_mode": RESERVED_OFFERING_MODE_STRICT,
        }
        if path == "device":
            kwargs["engine"] = CatalogEngine(catalog)
        env = Env(**kwargs)
        results = schedule(
            path, [unschedulable_pod(requests={"cpu": "1"})], env=env,
        )
        assert not results.pod_errors
        [nc] = results.new_node_claims
        assert nc.requirements.get(RESERVATION_ID_LABEL).has("cr-1")

    def test_strict_min_values_runs_on_device(self, path):
        """Strict-policy minValues is device-supported since round 4 (the
        diversity count only shrinks, so rejections stay monotone); only
        BestEffort relaxation declines (see test_minvalues_oracle)."""
        pools = [
            nodepool(
                "default",
                requirements=[
                    {
                        "key": wk.LABEL_INSTANCE_TYPE,
                        "operator": "Exists",
                        "minValues": 2,
                    }
                ],
            )
        ]
        results = schedule(
            path, [unschedulable_pod(requests={"cpu": "1"})], node_pools=pools,
        )
        assert not results.pod_errors
        [nc] = results.new_node_claims
        assert len(nc.instance_type_options) >= 2
