"""In-memory store: versioning, finalizers, watches; events; metrics."""

import pytest

from karpenter_tpu.apis.core import ObjectMeta, Pod
from karpenter_tpu.events.recorder import Event, Recorder
from karpenter_tpu.metrics.registry import Registry, Store as MetricStore
from karpenter_tpu.runtime.store import (
    ADDED,
    DELETED,
    MODIFIED,
    AlreadyExists,
    Conflict,
    NotFound,
    Store,
)
from karpenter_tpu.utils.clock import FakeClock


def pod(name="p"):
    return Pod(metadata=ObjectMeta(name=name))


class TestStore:
    def test_create_get_list(self):
        s = Store()
        s.create(pod("a"))
        s.create(pod("b"))
        assert s.get("Pod", "a").metadata.name == "a"
        assert len(s.list("Pod")) == 2
        with pytest.raises(AlreadyExists):
            s.create(pod("a"))

    def test_versions_bump(self):
        s = Store()
        p = s.create(pod())
        v1 = p.metadata.resource_version
        s.update(p)
        assert p.metadata.resource_version > v1

    def test_optimistic_conflict(self):
        s = Store()
        p = s.create(pod())
        stale = p.metadata.resource_version
        s.update(p)
        with pytest.raises(Conflict):
            s.update(p, expect_version=stale)

    def test_delete_without_finalizer_removes(self):
        s = Store()
        p = s.create(pod())
        s.delete(p)
        with pytest.raises(NotFound):
            s.get("Pod", "p")

    def test_delete_with_finalizer_sets_timestamp(self):
        s = Store(clock=FakeClock(5.0))
        p = pod()
        p.metadata.finalizers.append("karpenter.sh/termination")
        s.create(p)
        s.delete(p)
        assert s.get("Pod", "p").metadata.deletion_timestamp == 5.0
        # removing the finalizer completes deletion
        s.remove_finalizer(p, "karpenter.sh/termination")
        with pytest.raises(NotFound):
            s.get("Pod", "p")

    def test_watch_streams_events_in_order(self):
        s = Store()
        w = s.watch(["Pod"])
        p = s.create(pod())
        s.update(p)
        s.delete(p)
        events = w.drain()
        assert [e.type for e in events] == [ADDED, MODIFIED, DELETED]

    def test_watch_kind_filter(self):
        s = Store()
        w = s.watch(["Node"])
        s.create(pod())
        assert len(w.drain()) == 0


class TestApplySnapshot:
    def test_apply_suppresses_only_unchanged_since_last_write(self):
        """apply() compares against the object's latest WRITTEN state: an
        interleaved update() must not let a later apply() suppress a revert
        (the reference's DeepEqual guard compares the stored object)."""
        s = Store()
        w = s.watch(["Pod"])
        p = s.create(pod("a"))
        p.spec.node_name = "n1"
        s.apply(p)
        # same state re-applied: suppressed
        w.drain()
        s.apply(p)
        assert len(w.drain()) == 0
        # interleaved update() to a different state...
        p.spec.node_name = "n2"
        s.update(p)
        # ...then a revert back to the last-applied state MUST emit
        p.spec.node_name = "n1"
        w.drain()
        s.apply(p)
        assert [e.type for e in w.drain()] == [MODIFIED]
        assert s.get("Pod", "a").spec.node_name == "n1"


class TestRecorder:
    def test_dedupes_within_ttl(self):
        clock = FakeClock()
        r = Recorder(clock=clock)
        p = pod()
        for _ in range(5):
            r.publish(Event(p, "Normal", "Launched", "launched"))
        assert len(r.events) == 1
        clock.step(121.0)
        r.publish(Event(p, "Normal", "Launched", "launched"))
        assert len(r.events) == 2

    def test_rate_limiter(self):
        clock = FakeClock()
        r = Recorder(clock=clock)
        r.rate_limit("Nominate", rate=1.0, burst=2)
        for i in range(5):
            r.publish(Event(pod(f"p{i}"), "Normal", "Nominate", f"m{i}"))
        assert r.calls("Nominate") == 2


class TestMetrics:
    def test_counter_gauge_histogram(self):
        reg = Registry()
        c = reg.counter("pods_total", labels=["phase"])
        c.inc({"phase": "pending"})
        c.inc({"phase": "pending"})
        assert c.value({"phase": "pending"}) == 2
        g = reg.gauge("limit")
        g.set(5.0)
        assert g.value() == 5.0
        h = reg.histogram("latency")
        h.observe(0.2)
        assert h.count() == 1
        text = reg.expose()
        assert "pods_total" in text and "latency_count" in text

    def test_store_replaces_series(self):
        reg = Registry()
        g = reg.gauge("node_capacity", labels=["node", "resource"])
        ms = MetricStore()
        ms.update("node-1", [(g, {"node": "node-1", "resource": "cpu"}, 4.0)])
        assert g.value({"node": "node-1", "resource": "cpu"}) == 4.0
        ms.update("node-1", [(g, {"node": "node-1", "resource": "memory"}, 8.0)])
        assert g.value({"node": "node-1", "resource": "cpu"}) == 0.0
        ms.delete("node-1")
        assert g.value({"node": "node-1", "resource": "memory"}) == 0.0


class TestOptions:
    def test_defaults_env_flags(self):
        from karpenter_tpu.operator.options import Options

        opts = Options.parse([], env={})
        assert opts.batch_idle_duration == 1.0
        assert opts.feature_gates.reserved_capacity is True
        opts = Options.parse(
            ["--batch-idle-duration", "2.5", "--feature-gates", "SpotToSpotConsolidation=true"],
            env={"BATCH_MAX_DURATION": "20"},
        )
        assert opts.batch_idle_duration == 2.5
        assert opts.batch_max_duration == 20.0
        assert opts.feature_gates.spot_to_spot_consolidation is True

    def test_memory_limit_bounds_solver_caches(self):
        """--memory-limit is wired: it scales the solver's cache clear-at
        caps (the TPU-native analog of the reference feeding GOMEMLIMIT,
        operator.go:115-118)."""
        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
        from karpenter_tpu.operator.operator import Operator
        from karpenter_tpu.operator.options import Options
        from karpenter_tpu.ops import ffd
        from karpenter_tpu.utils.clock import FakeClock

        clock = FakeClock()
        try:
            Operator(
                Store(clock=clock), FakeCloudProvider(), clock=clock,
                options=Options.parse(["--memory-limit", "16"], env={}),
            )
            assert ffd._SIG_CAP == 20_000
            assert ffd._ENGINE_CACHE_CAP == 10_000
            # an operator with the UNSET default must not clobber the
            # configured budget (HA standbys, test fixtures)
            Operator(
                Store(clock=clock), FakeCloudProvider(), clock=clock,
                options=Options.parse([], env={}),
            )
            assert ffd._SIG_CAP == 20_000
            # an EXPLICIT --memory-limit 0 restores the unbounded defaults
            Operator(
                Store(clock=clock), FakeCloudProvider(), clock=clock,
                options=Options.parse(["--memory-limit", "0"], env={}),
            )
            assert ffd._SIG_CAP == 200_000
            assert ffd._ENGINE_CACHE_CAP == 100_000
        finally:
            ffd.set_memory_budget(-1)

    def test_every_option_field_has_a_reader(self):
        """No parity theater: each Options field must be consumed somewhere
        in the package (VERDICT r4 weak #2)."""
        import pathlib
        from dataclasses import fields

        import karpenter_tpu
        from karpenter_tpu.operator.options import Options

        pkg_root = pathlib.Path(karpenter_tpu.__file__).parent
        source = "".join(
            p.read_text()
            for p in pkg_root.rglob("*.py")
            if p.name != "options.py"
        )
        for f in fields(Options):
            assert f.name in source, f"Options.{f.name} has no reader"


class TestPodNodeIndex:
    """The pod-by-node field index (the reference's field-indexer analog,
    operator.go:235-278) must stay coherent across every write transition."""

    def _store(self):
        from karpenter_tpu.utils.clock import FakeClock

        return Store(clock=FakeClock())

    def _pod(self, name, node=""):
        from karpenter_tpu.apis.core import ObjectMeta, Pod, PodSpec

        return Pod(metadata=ObjectMeta(name=name), spec=PodSpec(node_name=node))

    def test_bound_pod_indexed_on_create(self):
        store = self._store()
        store.create(self._pod("a", node="n1"))
        assert [p.metadata.name for p in store.pods_on_node("n1")] == ["a"]
        assert store.pods_on_node("n2") == []

    def test_unbound_pod_not_indexed_until_bind(self):
        store = self._store()
        pod = store.create(self._pod("a"))
        assert store.pods_on_node("n1") == []
        pod.spec.node_name = "n1"
        store.update(pod)
        assert [p.metadata.name for p in store.pods_on_node("n1")] == ["a"]

    def test_rebind_moves_index_entry(self):
        store = self._store()
        pod = store.create(self._pod("a", node="n1"))
        pod.spec.node_name = "n2"
        store.update(pod)
        assert store.pods_on_node("n1") == []
        assert [p.metadata.name for p in store.pods_on_node("n2")] == ["a"]

    def test_delete_removes_entry(self):
        store = self._store()
        pod = store.create(self._pod("a", node="n1"))
        store.delete(pod)
        assert store.pods_on_node("n1") == []

    def test_finalizer_deferred_delete(self):
        store = self._store()
        pod = self._pod("a", node="n1")
        pod.metadata.finalizers = ["example.com/finalizer"]
        store.create(pod)
        store.delete(pod)  # only sets deletionTimestamp
        assert [p.metadata.name for p in store.pods_on_node("n1")] == ["a"]
        store.remove_finalizer(pod, "example.com/finalizer")  # object removed
        assert store.pods_on_node("n1") == []

    def test_stale_in_place_mutation_filtered(self):
        store = self._store()
        pod = store.create(self._pod("a", node="n1"))
        pod.spec.node_name = "n2"  # mutated WITHOUT a store write
        assert store.pods_on_node("n1") == []  # stale entry filtered
        store.update(pod)
        assert [p.metadata.name for p in store.pods_on_node("n2")] == ["a"]

    def test_deterministic_insertion_order(self):
        store = self._store()
        for name in ("c", "a", "b"):
            store.create(self._pod(name, node="n1"))
        assert [p.metadata.name for p in store.pods_on_node("n1")] == ["c", "a", "b"]
