"""Provisioner loop behaviors, mirroring the reference's provisioning
suite (provisioner.go specs)."""

import pytest

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import StorageClass, ObjectMeta
from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
from karpenter_tpu.controllers.provisioning import Provisioner
from karpenter_tpu.events.recorder import Recorder
from karpenter_tpu.operator.options import Options
from karpenter_tpu.runtime.store import Store
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.state.informer import StateInformer
from karpenter_tpu.utils.clock import FakeClock

from helpers import nodepool, registered_node, unschedulable_pod


@pytest.fixture
def env():
    from helpers import make_provisioner_harness

    return make_provisioner_harness()


def run_batch(clock, informer, prov, pods):
    for p in pods:
        prov.trigger(p.metadata.uid)
    informer.flush()
    clock.step(1.5)  # close the idle window
    return prov.reconcile()


class TestProvisioner:
    def test_pending_pod_creates_nodeclaim(self, env):
        clock, store, provider, cluster, informer, prov = env
        store.create(nodepool("default"))
        pod = store.create(unschedulable_pod(requests={"cpu": "1"}))
        results = run_batch(clock, informer, prov, [pod])
        assert results is not None
        claims = store.list("NodeClaim")
        assert len(claims) == 1
        claim = claims[0]
        assert claim.metadata.labels[wk.NODEPOOL_LABEL_KEY] == "default"
        assert claim.metadata.name.startswith("default-")
        # instance-type requirement truncated to <= 60
        it_req = next(
            r for r in claim.spec.requirements if r["key"] == wk.LABEL_INSTANCE_TYPE
        )
        assert 0 < len(it_req["values"]) <= 60

    def test_prewarm_builds_and_warms_engine_before_first_batch(self, env):
        """The operator loop calls prewarm() at idle: once nodepools exist,
        the engine for the current catalog is built and warmed so the first
        batch doesn't pay the encode/compile cold cost (VERDICT r4 #5)."""
        clock, store, provider, cluster, informer, prov = env
        if prov.engine_factory is None:
            pytest.skip("host-only solver configured")
        store.create(nodepool("default"))
        informer.flush()
        prov.prewarm()
        its = {
            "default": provider.get_instance_types(store.get("NodePool", "default"))
        }
        engine = prov.engine_factory(its)
        assert engine is not None and getattr(engine, "_warmed", False)
        # idempotent: second call is a flag check, same engine object
        prov.prewarm()
        assert prov.engine_factory(its) is engine

    def test_prewarm_without_nodepools_is_noop(self, env):
        clock, store, provider, cluster, informer, prov = env
        prov.prewarm()  # must not raise with an empty store

    def test_no_trigger_no_schedule(self, env):
        clock, store, provider, cluster, informer, prov = env
        store.create(nodepool("default"))
        store.create(unschedulable_pod())
        informer.flush()
        clock.step(5.0)
        assert prov.reconcile() is None  # batcher never triggered

    def test_batch_window_not_elapsed(self, env):
        clock, store, provider, cluster, informer, prov = env
        store.create(nodepool("default"))
        pod = store.create(unschedulable_pod())
        informer.flush()
        prov.trigger(pod.metadata.uid)
        assert prov.reconcile() is None  # idle window still open
        clock.step(1.5)
        assert prov.reconcile() is not None

    def test_max_window_closes_despite_triggers(self, env):
        clock, store, provider, cluster, informer, prov = env
        store.create(nodepool("default"))
        pod = store.create(unschedulable_pod())
        informer.flush()
        prov.trigger(pod.metadata.uid)
        for i in range(12):  # 10.8s total > 10s max
            clock.step(0.9)  # keep idle timer resetting
            prov.trigger(f"uid-{i}")
        assert prov.reconcile() is not None  # max 10s window closed

    def test_not_ready_nodepool_ignored(self, env):
        clock, store, provider, cluster, informer, prov = env
        np = nodepool("default")
        np.set_condition("Ready", "False")
        store.create(np)
        pod = store.create(unschedulable_pod())
        results = run_batch(clock, informer, prov, [pod])
        assert store.list("NodeClaim") == []

    def test_nodepool_limits_checked_at_create(self, env):
        clock, store, provider, cluster, informer, prov = env
        store.create(nodepool("default", limits={"cpu": "16"}))
        node = registered_node(pool="default", capacity={"cpu": "16", "memory": "64Gi", "pods": "110"})
        store.create(node)
        pod = store.create(unschedulable_pod(requests={"cpu": "1"}))
        results = run_batch(clock, informer, prov, [pod])
        # limits already consumed by the existing node -> no new claims
        assert store.list("NodeClaim") == []

    def test_unsynced_cluster_blocks(self, env):
        clock, store, provider, cluster, informer, prov = env
        store.create(nodepool("default"))
        pod = store.create(unschedulable_pod())
        prov.trigger(pod.metadata.uid)
        clock.step(1.5)
        # informer NOT flushed: cluster misses the store's nodeclaim-less pod
        # state is still consistent... force inconsistency with a claim:
        from karpenter_tpu.apis.nodeclaim import NodeClaim
        store.create(NodeClaim(metadata=ObjectMeta(name="ghost")))
        assert prov.reconcile() is None

    def test_do_not_disrupt_nodepool_requirement_rejected(self, env):
        clock, store, provider, cluster, informer, prov = env
        store.create(nodepool("default"))
        pod = unschedulable_pod()
        pod.spec.affinity = None
        pod.spec.node_selector = {}
        from karpenter_tpu.apis.core import Affinity, NodeAffinity, NodeSelectorTerm
        pod.spec.affinity = Affinity(node_affinity=NodeAffinity(required=[
            NodeSelectorTerm(match_expressions=[
                {"key": wk.NODEPOOL_LABEL_KEY, "operator": "DoesNotExist"}
            ])
        ]))
        store.create(pod)
        results = run_batch(clock, informer, prov, [pod])
        assert store.list("NodeClaim") == []

    def test_restricted_label_rejected(self, env):
        clock, store, provider, cluster, informer, prov = env
        store.create(nodepool("default"))
        pod = store.create(unschedulable_pod(node_selector={"karpenter.sh/custom": "x"}))
        run_batch(clock, informer, prov, [pod])
        assert store.list("NodeClaim") == []

    def test_unbound_pvc_without_storageclass_rejected(self, env):
        clock, store, provider, cluster, informer, prov = env
        from karpenter_tpu.apis.core import PersistentVolumeClaim, Volume
        store.create(nodepool("default"))
        store.create(PersistentVolumeClaim(metadata=ObjectMeta(name="pvc-x")))
        pod = unschedulable_pod()
        pod.spec.volumes = [Volume(name="data", persistent_volume_claim="pvc-x")]
        store.create(pod)
        run_batch(clock, informer, prov, [pod])
        assert store.list("NodeClaim") == []

    def test_storageclass_zone_injected(self, env):
        clock, store, provider, cluster, informer, prov = env
        from karpenter_tpu.apis.core import NodeSelectorTerm, PersistentVolumeClaim, Volume
        store.create(nodepool("default"))
        store.create(
            StorageClass(
                metadata=ObjectMeta(name="zonal"),
                provisioner="ebs.csi.aws.com",
                allowed_topologies=[
                    NodeSelectorTerm(match_expressions=[
                        {"key": wk.LABEL_TOPOLOGY_ZONE, "operator": "In",
                         "values": ["kwok-zone-3"]}
                    ])
                ],
            )
        )
        store.create(PersistentVolumeClaim(metadata=ObjectMeta(name="pvc-z"), storage_class_name="zonal"))
        pod = unschedulable_pod()
        pod.spec.volumes = [Volume(name="data", persistent_volume_claim="pvc-z")]
        store.create(pod)
        run_batch(clock, informer, prov, [pod])
        [claim] = store.list("NodeClaim")
        zone_req = next(
            r for r in claim.spec.requirements if r["key"] == wk.LABEL_TOPOLOGY_ZONE
        )
        assert zone_req["values"] == ["kwok-zone-3"]

    def test_multiple_pools_weight_order(self, env):
        clock, store, provider, cluster, informer, prov = env
        store.create(nodepool("light", weight=1))
        store.create(nodepool("heavy", weight=50))
        pod = store.create(unschedulable_pod())
        run_batch(clock, informer, prov, [pod])
        [claim] = store.list("NodeClaim")
        assert claim.metadata.labels[wk.NODEPOOL_LABEL_KEY] == "heavy"


class TestVolumeTopologyVariants:
    """provisioning/suite_test.go:1746-2101 — ephemeral volumes, bound PVs,
    and invalid-PVC isolation."""

    def test_ephemeral_volume_storageclass_zone_injected(self, env):
        """:1867 — a generic ephemeral volume resolves through its storage
        class; the zone constraint lands on the claim."""
        clock, store, provider, cluster, informer, prov = env
        from karpenter_tpu.apis.core import NodeSelectorTerm, StorageClass, Volume

        store.create(nodepool("default"))
        store.create(
            StorageClass(
                metadata=ObjectMeta(name="zonal-eph"),
                provisioner="ebs.csi.aws.com",
                allowed_topologies=[
                    NodeSelectorTerm(match_expressions=[
                        {"key": wk.LABEL_TOPOLOGY_ZONE, "operator": "In",
                         "values": ["kwok-zone-2"]}
                    ])
                ],
            )
        )
        pod = unschedulable_pod()
        pod.spec.volumes = [Volume(name="scratch", ephemeral_storage_class="zonal-eph")]
        store.create(pod)
        run_batch(clock, informer, prov, [pod])
        [claim] = store.list("NodeClaim")
        zone_req = next(
            r for r in claim.spec.requirements if r["key"] == wk.LABEL_TOPOLOGY_ZONE
        )
        assert zone_req["values"] == ["kwok-zone-2"]

    def test_ephemeral_volume_incompatible_zone_fails(self, env):
        """:1901 — storage-class zones outside the nodepool's reach leave
        the pod pending."""
        clock, store, provider, cluster, informer, prov = env
        from karpenter_tpu.apis.core import NodeSelectorTerm, StorageClass, Volume

        store.create(
            nodepool(
                "default",
                requirements=[
                    {"key": wk.LABEL_TOPOLOGY_ZONE, "operator": "In",
                     "values": ["kwok-zone-1"]}
                ],
            )
        )
        store.create(
            StorageClass(
                metadata=ObjectMeta(name="elsewhere"),
                provisioner="ebs.csi.aws.com",
                allowed_topologies=[
                    NodeSelectorTerm(match_expressions=[
                        {"key": wk.LABEL_TOPOLOGY_ZONE, "operator": "In",
                         "values": ["kwok-zone-4"]}
                    ])
                ],
            )
        )
        pod = unschedulable_pod()
        pod.spec.volumes = [Volume(name="scratch", ephemeral_storage_class="elsewhere")]
        store.create(pod)
        run_batch(clock, informer, prov, [pod])
        assert store.list("NodeClaim") == []

    def test_bound_pvc_schedules_to_volume_zone(self, env):
        """:1922 — a PVC bound to a real PV inherits the PV's node affinity."""
        clock, store, provider, cluster, informer, prov = env
        from karpenter_tpu.apis.core import (
            NodeSelectorTerm,
            PersistentVolume,
            PersistentVolumeClaim,
            Volume,
        )

        store.create(nodepool("default"))
        store.create(
            PersistentVolume(
                metadata=ObjectMeta(name="pv-1"),
                node_affinity_required=[
                    NodeSelectorTerm(match_expressions=[
                        {"key": wk.LABEL_TOPOLOGY_ZONE, "operator": "In",
                         "values": ["kwok-zone-3"]}
                    ])
                ],
            )
        )
        pvc = PersistentVolumeClaim(metadata=ObjectMeta(name="pvc-bound"))
        pvc.volume_name = "pv-1"
        store.create(pvc)
        pod = unschedulable_pod()
        pod.spec.volumes = [Volume(name="data", persistent_volume_claim="pvc-bound")]
        store.create(pod)
        run_batch(clock, informer, prov, [pod])
        [claim] = store.list("NodeClaim")
        zone_req = next(
            r for r in claim.spec.requirements if r["key"] == wk.LABEL_TOPOLOGY_ZONE
        )
        assert zone_req["values"] == ["kwok-zone-3"]

    def test_invalid_pvc_does_not_poison_valid_pods(self, env):
        """:1817 — a pod referencing a missing PVC stays pending; the rest
        of the batch provisions normally."""
        clock, store, provider, cluster, informer, prov = env
        from karpenter_tpu.apis.core import Volume

        store.create(nodepool("default"))
        bad = unschedulable_pod(name="bad-pvc-pod")
        bad.spec.volumes = [Volume(name="data", persistent_volume_claim="no-such-pvc")]
        good = unschedulable_pod(name="good-pod", requests={"cpu": "1"})
        store.create(bad)
        store.create(good)
        run_batch(clock, informer, prov, [bad, good])
        claims = store.list("NodeClaim")
        assert len(claims) == 1
        # only the valid pod is accounted on the claim
        assert claims[0].spec.resources.requests.get("cpu", 0) >= 1.0


class TestSchedulingConsistency:
    """provisioning/suite_test.go:459-530."""

    def test_nodepool_hash_stable_across_mid_scheduling_change(self, env):
        """:459 — the claim's nodepool-hash annotation reflects the pool AT
        scheduling time, even if the pool mutates before create."""
        clock, store, provider, cluster, informer, prov = env

        pool = nodepool("default")
        store.create(pool)
        hash_before = pool.static_hash()
        pod = unschedulable_pod(requests={"cpu": "1"})
        store.create(pod)
        informer.flush()
        prov.trigger(pod.metadata.uid)
        clock.step(1.5)
        # mutate the pool AFTER batching/scheduling begins
        results = prov.reconcile()
        assert results is not None
        pool.spec.template.labels["new-label"] = "new-value"
        store.update(pool)
        assert pool.static_hash() != hash_before
        [claim] = store.list("NodeClaim")
        assert (
            claim.metadata.annotations[wk.NODEPOOL_HASH_ANNOTATION_KEY]
            == hash_before
        )

    def test_pods_pack_onto_replacement_when_node_deleting(self, env):
        """:491 — pods from a deleting node batch together and land on ONE
        replacement claim."""
        from helpers import bind_pod, node_claim_pair

        clock, store, provider, cluster, informer, prov = env
        store.create(nodepool("default"))
        node, claim = node_claim_pair("leaving-1")
        node.metadata.deletion_timestamp = 5.0
        node.metadata.finalizers.append(wk.TERMINATION_FINALIZER)
        claim.metadata.deletion_timestamp = 5.0
        store.create(claim)
        store.create(node)
        pods = []
        for i in range(3):
            p = bind_pod(unschedulable_pod(requests={"cpu": "1"}), node)
            store.create(p)
            pods.append(p)
        informer.flush()
        for p in pods:
            prov.trigger(p.metadata.uid)
        clock.step(1.5)
        assert prov.reconcile() is not None
        replacements = [
            c for c in store.list("NodeClaim") if c.metadata.name != claim.metadata.name
        ]
        assert len(replacements) == 1
        # all three pods fit the single replacement's resource envelope
        assert replacements[0].spec.resources.requests.get("cpu", 0) >= 3.0

    def test_volume_zone_not_relaxed_away_with_multiple_terms(self, env):
        """:2101 — the volume-derived zone requirement is injected into ALL
        OR'd node-affinity terms, so relaxing the unsatisfiable first term
        away cannot lose it."""
        from karpenter_tpu.apis.core import (
            Affinity,
            NodeAffinity,
            NodeSelectorTerm,
            PersistentVolume,
            PersistentVolumeClaim,
            Volume,
        )

        clock, store, provider, cluster, informer, prov = env
        store.create(nodepool("default"))
        store.create(
            PersistentVolume(
                metadata=ObjectMeta(name="pv-z3"),
                node_affinity_required=[
                    NodeSelectorTerm(match_expressions=[
                        {"key": wk.LABEL_TOPOLOGY_ZONE, "operator": "In",
                         "values": ["kwok-zone-3"]}
                    ])
                ],
            )
        )
        pvc = PersistentVolumeClaim(metadata=ObjectMeta(name="pvc-z3"))
        pvc.volume_name = "pv-z3"
        store.create(pvc)
        pod = unschedulable_pod(requests={"cpu": "1"})
        pod.spec.volumes = [Volume(name="data", persistent_volume_claim="pvc-z3")]
        pod.spec.affinity = Affinity(
            node_affinity=NodeAffinity(
                required=[
                    NodeSelectorTerm(match_expressions=[
                        {"key": "example.com/label", "operator": "In",
                         "values": ["unsupported"]}
                    ]),
                    NodeSelectorTerm(match_expressions=[
                        {"key": wk.CAPACITY_TYPE_LABEL_KEY, "operator": "In",
                         "values": [wk.CAPACITY_TYPE_ON_DEMAND]}
                    ]),
                ]
            )
        )
        store.create(pod)
        run_batch(clock, informer, prov, [pod])
        [claim] = store.list("NodeClaim")
        zone_req = next(
            r for r in claim.spec.requirements if r["key"] == wk.LABEL_TOPOLOGY_ZONE
        )
        assert zone_req["values"] == ["kwok-zone-3"]
        ct_req = next(
            r for r in claim.spec.requirements
            if r["key"] == wk.CAPACITY_TYPE_LABEL_KEY
        )
        assert ct_req["values"] == [wk.CAPACITY_TYPE_ON_DEMAND]

    def test_restricted_domain_exception_selector_validates(self, env):
        """suite_test.go:431-457 — pod selectors under the exception domains
        (and their subdomains) pass the provisioner's restricted-label
        validation and schedule when the NodePool defines them."""
        clock, store, provider, cluster, informer, prov = env
        store.create(
            nodepool(
                "default",
                requirements=[
                    {"key": "kops.k8s.io/gpu", "operator": "In", "values": ["1"]},
                    {
                        "key": "sub.node-restriction.kubernetes.io/team",
                        "operator": "In",
                        "values": ["infra"],
                    },
                ],
            )
        )
        pod = store.create(
            unschedulable_pod(
                node_selector={
                    "kops.k8s.io/gpu": "1",
                    "sub.node-restriction.kubernetes.io/team": "infra",
                }
            )
        )
        run_batch(clock, informer, prov, [pod])
        [claim] = store.list("NodeClaim")
        gpu_req = next(
            r for r in claim.spec.requirements if r["key"] == "kops.k8s.io/gpu"
        )
        assert gpu_req["values"] == ["1"]
