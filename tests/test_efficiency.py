"""Efficiency observatory (observability/efficiency.py + the dispatch
split in tracing/kernel.py): HLO cost tables built once per executable,
sidecar persistence alongside the AOT cache, roofline utilization,
per-batch host-stall attribution (host twins NEVER count as device-busy
time), triggered device profiling, the breach→capture→flight-bundle
pipeline, and the graceful-degradation specs (no cost_analysis / no
jax.profiler / unwritable dirs — warn once, never affect boot or seal)."""

import json
import os
import time

import jax
import numpy as np
import pytest

from karpenter_tpu.observability import efficiency as eff
from karpenter_tpu.observability import kernels as kobs
from karpenter_tpu.tracing import kernel as ktime
from karpenter_tpu.utils.clock import FakeClock


@pytest.fixture
def clean_eff():
    """Isolate the efficiency observatory's process-global state."""
    reg = kobs.registry()
    reg.reset()
    eff.tables().reset()
    prof = eff.profiler()
    prof.configure(profile_dir="")
    prof.reset()
    yield
    # wait out any armed background capture before resetting (a non-daemon
    # worker from a spec must not leak a live trace into the next one)
    deadline = time.monotonic() + 10.0
    while prof.snapshot()["active"] and time.monotonic() < deadline:
        time.sleep(0.02)
    prof.configure(profile_dir="")
    prof.reset()
    eff.tables().reset()
    reg.reset()


def compiled_executable(n: int = 16):
    """A real compiled executable (cost_analysis works on CPU jaxlib)."""
    fn = jax.jit(lambda x: x @ x)
    return fn.lower(
        jax.ShapeDtypeStruct((n, n), np.float32)
    ).compile()


class _BrokenExe:
    def cost_analysis(self):
        raise RuntimeError("backend without cost models")


class _PartialExe:
    """cost_analysis yields bytes only, memory_analysis missing."""

    def cost_analysis(self):
        return [{"bytes accessed": 4096.0}]

    def memory_analysis(self):
        raise NotImplementedError


class TestCostTables:
    def test_note_executable_builds_entry(self, clean_eff):
        exe = compiled_executable()
        entry = eff.note_executable("spec.mm", "16x16", exe)
        assert entry is not None
        assert entry["flops"] > 0
        assert entry["bytes_accessed"] > 0
        assert entry["floor_s"] > 0
        stats = eff.tables().stats()
        assert stats == {"entries": 1, "analysis_calls": 1, "errors": 0}

    def test_idempotent_per_key(self, clean_eff):
        exe = compiled_executable()
        eff.note_executable("spec.mm", "16x16", exe)
        again = eff.note_executable("spec.mm", "16x16", exe)
        assert again is not None
        # the second note answered from the table: NO second analysis
        assert eff.tables().stats()["analysis_calls"] == 1

    def test_scope_blind_lookup(self, clean_eff):
        """The observatory's shape telemetry is scope-free by design, so
        utilization joins on (kernel, sig) regardless of the mesh scope
        the executable compiled under."""
        exe = compiled_executable()
        eff.note_executable("spec.mm", "16x16", exe, scope="mesh=8:pods")
        assert eff.tables().lookup("spec.mm", "16x16") is not None
        assert eff.tables().lookup("spec.mm", "32x32") is None

    def test_broken_backend_degrades_to_absent_entry(self, clean_eff):
        """Graceful-degradation spec: a backend whose executables raise
        from cost_analysis yields NO entry and NO exception — and warns
        once per boot, not once per bucket."""
        assert eff.note_executable("spec.a", "1", _BrokenExe()) is None
        assert eff.note_executable("spec.b", "2", _BrokenExe()) is None
        stats = eff.tables().stats()
        assert stats["entries"] == 0
        assert stats["errors"] == 2
        # re-noting a failed key never retries the analysis
        calls = stats["analysis_calls"]
        assert eff.note_executable("spec.a", "1", _BrokenExe()) is None
        assert eff.tables().stats()["analysis_calls"] == calls

    def test_partial_cost_dict_keeps_what_it_got(self, clean_eff):
        entry = eff.note_executable("spec.part", "4", _PartialExe())
        assert entry is not None
        assert "flops" not in entry
        assert entry["bytes_accessed"] == 4096.0
        # the roofline floor binds on the only term available
        assert entry["floor_s"] > 0

    def test_sidecar_rides_the_executable_cache(self, clean_eff, tmp_path):
        """Cost entries persist as sidecar JSON alongside the executable
        cache, keyed the same way: a second boot loads the sidecar and
        pays zero cost_analysis calls."""
        from karpenter_tpu.aot.cache import ExecutableCache

        cache = ExecutableCache(str(tmp_path))
        exe = compiled_executable()
        eff.note_executable("spec.mm", "16x16", exe, cache=cache, key="k" * 64)
        sidecar = tmp_path / ("k" * 64 + ".cost.json")
        assert sidecar.exists()
        fresh = eff.CostTables()
        entry = fresh.note_executable(
            "spec.mm", "16x16", _BrokenExe(), cache=cache, key="k" * 64
        )
        # the broken exe was never consulted: the sidecar answered
        assert entry is not None and entry["flops"] > 0
        assert fresh.stats()["analysis_calls"] == 0

    def test_sidecar_write_failure_degrades(self, clean_eff, tmp_path):
        """An unwritable cache dir loses the sidecar, not the entry."""
        from karpenter_tpu.aot.cache import ExecutableCache

        cache = ExecutableCache(str(tmp_path))
        os.chmod(tmp_path, 0o500)
        try:
            entry = eff.note_executable(
                "spec.mm", "16x16", compiled_executable(),
                cache=cache, key="r" * 64,
            )
            assert entry is not None
        finally:
            os.chmod(tmp_path, 0o700)

    def test_peak_env_overrides(self, clean_eff, monkeypatch):
        monkeypatch.setenv("KARPENTER_TPU_PEAK_FLOPS", "1e12")
        monkeypatch.setenv("KARPENTER_TPU_PEAK_BYTES", "1e11")
        assert eff._device_peaks() == (1e12, 1e11)
        floor = eff._floor_seconds({"flops": 1e12, "bytes_accessed": 1e10})
        assert floor == pytest.approx(1.0)  # compute-bound term wins

    def test_malformed_peak_env_never_crashes_a_boot(
        self, clean_eff, monkeypatch
    ):
        """Regression: a garbage/negative peak override falls back to the
        device defaults instead of raising out of the warm start."""
        monkeypatch.setenv("KARPENTER_TPU_PEAK_FLOPS", "400T")
        monkeypatch.setenv("KARPENTER_TPU_PEAK_BYTES", "-5")
        pf, pb = eff._device_peaks()
        assert pf > 0 and pb > 0
        entry = eff.note_executable(
            "spec.badenv", "16x16", compiled_executable()
        )
        assert entry is not None and entry["floor_s"] > 0


class TestDispatchSplit:
    def test_measure_carries_enqueue_and_block(self, clean_eff):
        f = jax.jit(lambda x: x + 1)
        x = np.ones((8,), np.float32)
        with ktime.measure() as m:
            ktime.dispatch(f, x, kernel="spec.split")
            ktime.dispatch(f, x, kernel="spec.split")
        assert m["dispatches"] == 2
        assert m["enqueue_s"] > 0
        assert m["block_s"] >= 0
        # the split re-attributes the same wall: it can never exceed the
        # compile+execute total
        assert m["enqueue_s"] + m["block_s"] <= (
            m["compile_s"] + m["execute_s"] + 1e-6
        )

    def test_host_twin_never_counts_device_busy(self, clean_eff):
        """THE regression contract: record_host (host twins, topo count
        resyncs) marks the batch but contributes neither dispatches nor
        device-busy wall — a host-paced batch reads as exactly 1.0."""
        reg = kobs.registry()
        with reg.batch_scope(label="host-twin") as acc:
            reg.record_host("spec.twin", "8x4")
            reg.record_host("spec.twin", "8x4")
        assert acc["dispatches"] == 0
        assert acc["fenced"] == 0
        assert acc["host_records"] == 2
        assert acc["device_busy_s"] == 0.0
        assert acc["host_stall_fraction"] == 1.0
        assert acc["timeline"] == []

    def test_unfenced_dispatch_counts_but_not_busy(self, clean_eff):
        """A named dispatch OUTSIDE a measurement context is unfenced: it
        counts as a device dispatch (the one-dispatch contract) but its
        device wall was never awaited, so it adds no busy time."""
        reg = kobs.registry()
        f = jax.jit(lambda x: x * 2)
        x = np.ones((4,), np.float32)
        ktime.dispatch(f, x, kernel="spec.unfenced")  # warm the jit cache
        with reg.batch_scope(label="unfenced") as acc:
            ktime.dispatch(f, x, kernel="spec.unfenced")
        assert acc["dispatches"] == 1
        assert acc["fenced"] == 0
        assert acc["device_busy_s"] == 0.0
        assert acc["host_stall_fraction"] == 1.0

    def test_nested_innermost_only_split_intact(self, clean_eff):
        """The nested-fence guard survives the split: a driver wrapping an
        inner dispatch attributes each second once — the measured totals
        never exceed the outer wall."""
        inner = jax.jit(lambda x: x @ x)
        x = np.ones((32, 32), np.float32)

        def driver(y):
            return ktime.dispatch(inner, y, kernel="spec.inner")

        t0 = time.perf_counter()
        with ktime.measure() as m:
            ktime.dispatch(driver, x, kernel="spec.outer")
        wall = time.perf_counter() - t0
        assert m["dispatches"] == 2
        assert m["compile_s"] + m["execute_s"] <= wall + 1e-6
        assert m["enqueue_s"] + m["block_s"] <= wall + 1e-6


class TestBatchTimeline:
    def test_device_batch_reconstruction(self, clean_eff):
        reg = kobs.registry()
        f = jax.jit(lambda x: x @ x)
        x = np.ones((16, 16), np.float32)
        ktime.dispatch(f, x, kernel="spec.tl")  # pay the compile outside
        with reg.batch_scope(label="timeline") as acc:
            with ktime.measure():
                ktime.dispatch(f, x, kernel="spec.tl")
        assert acc["dispatches"] == 1
        assert acc["fenced"] == 1
        assert acc["device_busy_s"] > 0
        assert acc["wall_s"] >= acc["device_busy_s"]
        assert 0.0 <= acc["host_stall_fraction"] <= 1.0
        (event,) = acc["timeline"]
        assert event["kernel"] == "spec.tl"
        assert event["fenced"] is True
        assert event["enqueue_s"] >= 0 and event["block_s"] >= 0

    def test_timeline_view_and_steady_counters(self, clean_eff):
        reg = kobs.registry()
        f = jax.jit(lambda x: x + 1)
        x = np.ones((8,), np.float32)
        ktime.dispatch(f, x, kernel="spec.view")
        reg.seal()
        with reg.batch_scope(label="steady-a"):
            with ktime.measure():
                ktime.dispatch(f, x, kernel="spec.view")
        with reg.batch_scope(label="steady-b"):
            pass  # host-only
        reg.unseal()
        view = reg.debug_snapshot(view="timeline")
        assert view["steady"]["steady_batches"] == 2
        assert view["steady"]["device_batches"] == 1
        assert view["steady"]["host_only_batches"] == 1
        assert 0.0 <= view["steady"]["host_stall_fraction"] <= 1.0
        labels = [b["label"] for b in view["batches"]]
        assert labels == ["steady-a", "steady-b"]
        assert all("timeline" in b for b in view["batches"])

    def test_warmup_batches_stay_out_of_steady_counters(self, clean_eff):
        reg = kobs.registry()
        with reg.batch_scope(label="warmup"):
            pass
        assert reg.efficiency_counters()["steady_batches"] == 0

    def test_report_section_delta_and_exact_one(self, clean_eff):
        reg = kobs.registry()
        base = eff.snapshot_base()
        reg.seal()
        with reg.batch_scope(label="host-only"):
            reg.record_host("spec.sect", "4")
        reg.unseal()
        section = eff.report_section(base)
        assert section["steady_batches"] == 1
        assert section["host_only_batches"] == 1
        assert section["device_batches"] == 0
        assert section["steady_device_dispatches"] == 0
        # fully host-paced: the fraction is EXACTLY 1.0 (deterministic —
        # no wall-clock division involved), which is what keeps same-seed
        # sim reports byte-equal on host-routed scenarios
        assert section["host_stall_fraction"] == 1.0

    def test_report_section_without_steady_batches(self, clean_eff):
        section = eff.report_section(eff.snapshot_base())
        assert section["steady_batches"] == 0
        assert section["host_stall_fraction"] is None


class TestUtilization:
    def test_ratio_joins_cost_and_measured(self, clean_eff):
        f = jax.jit(lambda x: x @ x)
        x = np.ones((16, 16), np.float32)
        exe = compiled_executable(16)
        ktime.dispatch(f, x, kernel="spec.util")  # compile
        with ktime.measure():
            ktime.dispatch(f, x, kernel="spec.util")  # fenced execute
        eff.note_executable("spec.util", "16x16", exe)
        view = eff.utilization_view()
        row = view["spec.util"]["16x16"]
        assert row["floor_s"] > 0
        assert row["mean_execute_s"] > 0
        # the view rounds the ratio to 6 decimals
        assert row["utilization"] == pytest.approx(
            row["floor_s"] / row["mean_execute_s"], abs=1e-5
        )

    def test_publish_sets_gauge(self, clean_eff):
        from karpenter_tpu.metrics import global_registry

        f = jax.jit(lambda x: x @ x)
        x = np.ones((16, 16), np.float32)
        ktime.dispatch(f, x, kernel="spec.pub")
        with ktime.measure():
            ktime.dispatch(f, x, kernel="spec.pub")
        eff.note_executable("spec.pub", "16x16", compiled_executable(16))
        view = eff.publish_utilization()
        gauge = global_registry.get("karpenter_kernel_utilization")
        assert gauge.value(
            {"kernel": "spec.pub", "bucket": "16x16"}
        ) == pytest.approx(view["spec.pub"]["16x16"]["utilization"])

    def test_absent_without_cost_tables(self, clean_eff):
        f = jax.jit(lambda x: x + 1)
        with ktime.measure():
            ktime.dispatch(f, np.ones((4,), np.float32), kernel="spec.none")
        assert eff.utilization_view() == {}


class TestCostView:
    def test_view_and_drilldown_and_404(self, clean_eff):
        eff.note_executable("spec.cv", "8x8", compiled_executable(8))
        view = eff.cost_view()
        assert view["cost_tables"]["entries"] == 1
        assert view["rows"][0]["kernel"] == "spec.cv"
        drill = eff.cost_view(kernel="spec.cv")
        assert len(drill["rows"]) == 1
        assert eff.cost_view(kernel="missing") is None
        # the registry's kernels count as known even without cost entries
        kobs.registry().record_host("spec.hostonly", "2")
        assert eff.cost_view(kernel="spec.hostonly") is not None

    def test_registry_view_routing(self, clean_eff):
        eff.note_executable("spec.route", "4x4", compiled_executable(4))
        snap = kobs.registry().debug_snapshot(view="cost")
        assert snap["rows"][0]["kernel"] == "spec.route"
        assert kobs.registry().debug_snapshot(
            kernel="missing", view="cost"
        ) is None


class TestDeviceProfiler:
    def test_disabled_returns_none(self, clean_eff):
        prof = eff.profiler()
        assert prof.capture(0.1) is None
        assert prof.arm("slo:x") is None
        assert prof.snapshot()["enabled"] is False

    def test_capture_writes_files_and_counts(self, clean_eff, tmp_path):
        from karpenter_tpu.metrics import global_registry

        prof = eff.profiler().configure(profile_dir=str(tmp_path))
        base = global_registry.get(
            "karpenter_profiler_captures_total"
        ).value({"trigger": "debug"})
        record = prof.capture(0.0, trigger="debug")
        assert record["name"] == "device-0001-debug"
        assert "error" not in record
        files = [
            os.path.join(r, fn)
            for r, _, fs in os.walk(record["path"])
            for fn in fs
        ]
        assert files, "capture produced no trace files"
        assert global_registry.get(
            "karpenter_profiler_captures_total"
        ).value({"trigger": "debug"}) == base + 1

    def test_arm_cooldown_and_busy_slot(self, clean_eff, tmp_path):
        clock = FakeClock()
        prof = eff.profiler().configure(
            clock=clock, profile_dir=str(tmp_path)
        )
        record = prof.arm("slo:obj", seconds=0.0)
        assert record is not None and record["name"].startswith("device-0001")
        # same trigger inside the cooldown window: no second capture
        clock.step(10.0)
        assert prof.arm("slo:obj", seconds=0.0) is None
        # past the cooldown (and once the worker released the slot): armed
        clock.step(eff.CAPTURE_COOLDOWN)
        deadline = time.monotonic() + 10.0
        while prof.snapshot()["active"] and time.monotonic() < deadline:
            time.sleep(0.02)
        second = prof.arm("slo:obj", seconds=0.0)
        assert second is not None and second["name"].startswith("device-0002")

    def test_unwritable_dir_degrades(self, clean_eff, tmp_path):
        blocker = tmp_path / "file"
        blocker.write_text("not a dir")
        prof = eff.profiler().configure(profile_dir=str(blocker / "nested"))
        assert prof.arm("slo:x") is None  # warned, never raised
        result = prof.capture(0.0)
        assert result == {
            "error": "capture already in progress or dir unwritable"
        }
        assert prof.snapshot()["active"] is False

    def test_unavailable_profiler_disables(self, clean_eff, tmp_path):
        prof = eff.profiler().configure(profile_dir=str(tmp_path))
        prof._available = False  # simulate a jaxlib without jax.profiler
        try:
            assert prof.enabled is False
            assert prof.capture(0.1) is None
            assert prof.arm("slo:x") is None
        finally:
            prof._available = None

    def test_reset_restarts_sequence_and_cooldowns(self, clean_eff, tmp_path):
        clock = FakeClock()
        prof = eff.profiler().configure(
            clock=clock, profile_dir=str(tmp_path)
        )
        assert prof.arm("slo:r", seconds=0.0)["name"] == "device-0001-slo-r"
        deadline = time.monotonic() + 10.0
        while prof.snapshot()["active"] and time.monotonic() < deadline:
            time.sleep(0.02)
        prof.reset()
        assert prof.arm("slo:r", seconds=0.0)["name"] == "device-0001-slo-r"


class TestBreachCapturePipeline:
    """Acceptance: an SLO-breach-triggered capture lands in the flight
    bundle — and absent --profile-dir, the breach path is untouched."""

    def _operator(self, tmp_path, profile: bool):
        from karpenter_tpu.cloudprovider.kwok.provider import KwokCloudProvider
        from karpenter_tpu.operator.operator import Operator
        from karpenter_tpu.operator.options import Options
        from karpenter_tpu.runtime.store import Store

        clock = FakeClock()
        store = Store(clock=clock)
        options = Options(
            flight_dir=str(tmp_path / "flight"),
            profile_dir=str(tmp_path / "profiles") if profile else "",
        )
        op = Operator(
            store, KwokCloudProvider(store, clock), clock=clock,
            options=options,
        )
        # the flight recorder and SLO engine are process-global: drop the
        # previous spec's bundles/series so each test reads its own breach
        from karpenter_tpu.observability import flight as flightmod
        from karpenter_tpu.observability import slo as slomod

        slomod.engine().reset()
        flightmod.recorder().reset()
        return clock, op

    def test_breach_bundle_records_capture(self, clean_eff, tmp_path):
        clock, op = self._operator(tmp_path, profile=True)
        try:
            op.run_once()
            op.slo.record("solverd-availability", bad=100)
            op.run_once()  # evaluates → breach → arm + dump
            snap = op.flight.snapshot()
            assert snap["bundles"], "breach dumped no bundle"
            bundle = snap["bundles"][0]
            assert bundle["trigger"] == "slo:solverd-availability"
            assert bundle["path"], "bundle not written to --flight-dir"
            header = json.loads(
                open(bundle["path"], encoding="utf-8").readline()
            )
            capture = header["context"]["device_profile"]
            assert capture["name"] == (
                "device-0001-slo-solverd-availability"
            )
            assert capture["path"].startswith(str(tmp_path / "profiles"))
            # the capture completes on its worker and leaves real files
            deadline = time.monotonic() + 15.0
            while (
                op.profiler.snapshot()["active"]
                and time.monotonic() < deadline
            ):
                time.sleep(0.02)
            files = [
                os.path.join(r, fn)
                for r, _, fs in os.walk(capture["path"])
                for fn in fs
            ]
            assert files, "armed capture produced no trace files"
        finally:
            op.shutdown()

    def test_breach_without_profile_dir_unchanged(self, clean_eff, tmp_path):
        clock, op = self._operator(tmp_path, profile=False)
        try:
            op.run_once()
            op.slo.record("solverd-availability", bad=100)
            op.run_once()
            snap = op.flight.snapshot()
            assert snap["bundles"]
            header = json.loads(
                open(snap["bundles"][0]["path"], encoding="utf-8").readline()
            )
            assert "device_profile" not in header["context"]
        finally:
            op.shutdown()


class TestGracefulWarmStart:
    """Graceful-degradation spec: a backend whose cost_analysis raises
    leaves warm start, the executable table, and the seal untouched —
    only the cost tables stay empty."""

    def test_warm_start_survives_cost_analysis_failure(
        self, clean_eff, monkeypatch, tmp_path
    ):
        from karpenter_tpu.aot import compiler as aotc
        from karpenter_tpu.aot import ladder as lmod
        from karpenter_tpu.aot import runtime as aotrt
        from karpenter_tpu.cloudprovider.kwok.instance_types import (
            construct_instance_types,
        )
        from karpenter_tpu.ops.catalog import CatalogEngine

        def boom(exe):
            raise RuntimeError("no cost models on this backend")

        monkeypatch.setattr(eff, "_extract_cost", boom)
        ladder = lmod.make(
            {
                "feasibility.cube": [(1, 4)],
                "catalog.row_compat": [(32,)],
                "packer.solve_block": [(8,)],
            }
        )
        aotrt.clear_executables()
        try:
            engine = CatalogEngine(construct_instance_types())
            summary = aotc.warm_start(engine, ladder=ladder)
            assert summary is not None
            # cost failures are NOT warm-start errors: the boot is clean
            assert summary["errors"] == 0
            assert summary["buckets"] > 0
            assert aotrt.executables(), "executables still installed"
            stats = eff.tables().stats()
            assert stats["entries"] == 0
            assert stats["errors"] >= 1
            assert kobs.registry().steady_recompiles() == 0
        finally:
            aotrt.clear_executables()
