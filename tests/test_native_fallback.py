"""Native-kernel fallback alerting (VERDICT Weak #6).

When ops/native.py fails to build the C steady-state kernel, the solver
silently served identical decisions from the ~100x slower pure-Python
loop — observable only as a counter. These specs poison the toolchain
(KARPENTER_TPU_CXX pointed at /bin/false, fresh source copy so the
hash-keyed .so cache cannot mask the failure) and assert the degradation
ALERTS: a warning log line from the native loader, and a Warning event
(NativeKernelUnavailable) from the Provisioner.
"""

import io
import pathlib

from karpenter_tpu.operator import logging as klog
from karpenter_tpu.ops import native


def _poison(monkeypatch, tmp_path):
    """Fresh source copy (cache-busting) + a compiler that always fails +
    pristine module state."""
    src = tmp_path / "ffd_kernel.cc"
    src.write_text(
        pathlib.Path(native._SRC).read_text() + "\n// poisoned-toolchain spec\n"
    )
    monkeypatch.setattr(native, "_SRC", str(src))
    monkeypatch.setattr(native, "_DIR", str(tmp_path))
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_tried", False)
    monkeypatch.setattr(native, "_build_error", None)
    monkeypatch.setenv("KARPENTER_TPU_CXX", "/bin/false")
    monkeypatch.delenv("KARPENTER_TPU_NATIVE", raising=False)


class TestNativeFallbackAlert:
    def test_poisoned_toolchain_fails_build_and_logs_warning(
        self, monkeypatch, tmp_path
    ):
        _poison(monkeypatch, tmp_path)
        stream = io.StringIO()
        klog.configure("info", stream=stream)
        try:
            assert native.get_lib() is None
            reason = native.build_failure()
            assert reason is not None and "/bin/false" in reason
            out = stream.getvalue()
            assert "native FFD kernel unavailable" in out
            assert "pure-Python steady-state loop" in out
            # verdict cached: repeat lookups don't re-run the compiler or
            # re-log
            stream.truncate(0)
            stream.seek(0)
            assert native.get_lib() is None
            assert stream.getvalue() == ""
        finally:
            import sys

            klog.configure("info", stream=sys.stderr)

    def test_deliberate_disable_does_not_alert(self, monkeypatch, tmp_path):
        _poison(monkeypatch, tmp_path)
        monkeypatch.setenv("KARPENTER_TPU_NATIVE", "0")
        assert native.get_lib() is None
        assert native.build_failure() is None  # opted out, not broken

    def test_provisioner_publishes_warning_event(self, monkeypatch, tmp_path):
        from helpers import make_provisioner_harness, nodepool, unschedulable_pod

        _poison(monkeypatch, tmp_path)
        assert native.get_lib() is None  # the first solve's build attempt
        clock, store, provider, cluster, informer, prov = (
            make_provisioner_harness()
        )
        store.create(nodepool("default"))
        pod = unschedulable_pod(requests={"cpu": "1"})
        store.create(pod)
        informer.flush()
        prov.trigger(pod.metadata.uid)
        clock.step(1.5)
        assert prov.reconcile() is not None
        events = [
            e
            for e in prov.recorder.events
            if e.reason == "NativeKernelUnavailable"
        ]
        assert len(events) == 1
        assert events[0].type == "Warning"
        assert "pure-Python steady-state loop" in events[0].message
        # once per process: a second batch does not duplicate the event
        pod2 = unschedulable_pod(name="p2", requests={"cpu": "1"})
        store.create(pod2)
        informer.flush()
        prov.trigger(pod2.metadata.uid)
        clock.step(1.5)
        prov.reconcile()
        assert (
            len(
                [
                    e
                    for e in prov.recorder.events
                    if e.reason == "NativeKernelUnavailable"
                ]
            )
            == 1
        )

    def test_healthy_toolchain_publishes_nothing(self):
        from helpers import make_provisioner_harness, nodepool, unschedulable_pod

        clock, store, provider, cluster, informer, prov = (
            make_provisioner_harness()
        )
        store.create(nodepool("default"))
        pod = unschedulable_pod(requests={"cpu": "1"})
        store.create(pod)
        informer.flush()
        prov.trigger(pod.metadata.uid)
        clock.step(1.5)
        assert prov.reconcile() is not None
        assert not [
            e
            for e in prov.recorder.events
            if e.reason == "NativeKernelUnavailable"
        ]
