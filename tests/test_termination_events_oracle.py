"""Drain-ordering/TGP termination specs (reference node/termination
suite_test.go + terminator.go:96-166) and events recorder specs
(pkg/events/recorder.go:30-117)."""

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import Taint, Toleration
from karpenter_tpu.controllers.node.termination import EvictionQueue, Terminator
from karpenter_tpu.events.recorder import DEDUPE_TTL, Event, Recorder
from karpenter_tpu.runtime.store import Store
from karpenter_tpu.utils.clock import FakeClock

from helpers import bind_pod, node_claim_pair, nodepool, unschedulable_pod


class Harness:
    def __init__(self):
        self.clock = FakeClock()
        self.store = Store(clock=self.clock)
        self.recorder = Recorder(clock=self.clock)
        self.queue = EvictionQueue(self.store, self.recorder, self.clock)
        self.terminator = Terminator(self.clock, self.store, self.queue, self.recorder)

    def node_with_pods(self, *pods, name="drain-1"):
        node, claim = node_claim_pair(name)
        self.store.create(claim)
        self.store.create(node)
        for p in pods:
            bind_pod(p, node)
            self.store.create(p)
        return node


class TestDrainOrdering:
    """terminator.go:96-138 — critical pods leave LAST."""

    def test_critical_pods_evicted_after_non_critical(self):
        h = Harness()
        app = unschedulable_pod(name="app-pod")
        critical = unschedulable_pod(name="critical-pod")
        critical.spec.priority_class_name = "system-cluster-critical"
        node = h.node_with_pods(app, critical)
        # first drain pass queues only the non-critical group
        msg = h.terminator.drain(node, None)
        assert msg is not None
        assert h.queue.has(app)
        assert not h.queue.has(critical)
        h.queue.reconcile()  # evicts the app pod
        assert h.store.try_get("Pod", "app-pod") is None
        # next pass reaches the critical group
        h.terminator.drain(node, None)
        assert h.queue.has(critical)

    def test_do_not_disrupt_pod_stalls_drain_without_eviction(self):
        # scheduling.go:50-85 — do-not-disrupt pods are never evicted but the
        # drain must still wait for them
        h = Harness()
        pod = unschedulable_pod(name="dnd-pod")
        pod.metadata.annotations[wk.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
        node = h.node_with_pods(pod)
        msg = h.terminator.drain(node, None)
        assert msg is not None and "terminate" in msg
        assert not h.queue.has(pod)

    def test_pods_tolerating_disrupted_taint_not_drained(self):
        h = Harness()
        pod = unschedulable_pod(
            name="tolerant-pod",
            tolerations=[Toleration(key=wk.DISRUPTED_TAINT_KEY, operator="Exists")],
        )
        node = h.node_with_pods(pod)
        assert h.terminator.drain(node, None) is None  # nothing to wait on

    def test_terminal_pods_do_not_block_drain(self):
        h = Harness()
        pod = unschedulable_pod(name="done-pod")
        pod.status.phase = "Succeeded"
        node = h.node_with_pods(pod)
        assert h.terminator.drain(node, None) is None


class TestTerminationGracePeriod:
    """terminator.go:140-166 — pods whose own grace period overruns the node
    deadline are force-deleted."""

    def test_overrunning_pod_force_deleted(self):
        h = Harness()
        slow = unschedulable_pod(name="slow-pod")
        slow.spec.termination_grace_period_seconds = 600
        fast = unschedulable_pod(name="fast-pod")
        fast.spec.termination_grace_period_seconds = 5
        node = h.node_with_pods(slow, fast)
        deadline = h.clock.now() + 60.0
        h.terminator.drain(node, deadline)
        assert h.store.try_get("Pod", "slow-pod") is None  # forced out
        assert h.store.try_get("Pod", "fast-pod") is not None
        assert any(e.reason == "ForcedEviction" for e in h.recorder.events)

    def test_no_deadline_no_forced_eviction(self):
        h = Harness()
        slow = unschedulable_pod(name="slow-pod-2")
        slow.spec.termination_grace_period_seconds = 600
        node = h.node_with_pods(slow)
        h.terminator.drain(node, None)
        assert h.store.try_get("Pod", "slow-pod-2") is not None


class TestEventsRecorder:
    """recorder.go:30-117."""

    def _event(self, message="m1", reason="TestReason"):
        pool = nodepool("events-pool")
        return Event(pool, "Normal", reason, message)

    def test_duplicates_deduped_within_ttl(self):
        recorder = Recorder(clock=FakeClock())
        recorder.publish(self._event())
        recorder.publish(self._event())
        assert len(recorder.events) == 1

    def test_republished_after_ttl(self):
        clock = FakeClock()
        recorder = Recorder(clock=clock)
        recorder.publish(self._event())
        clock.step(DEDUPE_TTL + 1.0)
        recorder.publish(self._event())
        assert len(recorder.events) == 2

    def test_different_messages_not_deduped(self):
        recorder = Recorder(clock=FakeClock())
        recorder.publish(self._event(message="m1"))
        recorder.publish(self._event(message="m2"))
        assert len(recorder.events) == 2

    def test_rate_limited_reason_capped_at_burst(self):
        recorder = Recorder(clock=FakeClock())
        recorder.rate_limit("Limited", rate=0.0, burst=3)
        for i in range(10):
            recorder.publish(self._event(message=f"m{i}", reason="Limited"))
        assert len(recorder.events) == 3

    def test_dedupe_values_override_key(self):
        recorder = Recorder(clock=FakeClock())
        a = self._event(message="m1")
        a.dedupe_values = ("group-a",)
        b = self._event(message="completely different")
        b.dedupe_values = ("group-a",)
        recorder.publish(a)
        recorder.publish(b)
        assert len(recorder.events) == 1
