"""Property tests: device feasibility kernel ≡ host requirements algebra.

The host `Requirements.intersects` is the semantic oracle (itself tested
against reference behaviors); the kernel must agree on randomized inputs
including complements, bounds, and exemption cases.
"""

import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from karpenter_tpu.ops import encoding as enc  # noqa: E402
from karpenter_tpu.ops import feasibility as feas  # noqa: E402
from karpenter_tpu.scheduling.requirements import (  # noqa: E402
    Operator,
    Requirement,
    Requirements,
)

KEYS = ["zone", "arch", "size", "team", "tier"]
VALUES = {
    "zone": ["z1", "z2", "z3", "z4"],
    "arch": ["amd64", "arm64"],
    "size": ["1", "2", "4", "8", "16"],
    "team": ["a", "b", "c"],
    "tier": ["0", "1", "x"],
}


def random_requirement(rng: random.Random, key: str) -> Requirement:
    op = rng.choice(
        [Operator.IN, Operator.NOT_IN, Operator.EXISTS, Operator.DOES_NOT_EXIST]
        + ([Operator.GT, Operator.LT] if key == "size" else [])
    )
    vals = VALUES[key]
    if op in (Operator.IN, Operator.NOT_IN):
        n = rng.randint(1, len(vals))
        return Requirement(key, op, rng.sample(vals, n))
    if op in (Operator.GT, Operator.LT):
        return Requirement(key, op, [str(rng.choice([0, 1, 2, 3, 5, 9, 20]))])
    return Requirement(key, op)


def random_req_set(rng: random.Random) -> Requirements:
    n = rng.randint(0, len(KEYS))
    keys = rng.sample(KEYS, n)
    return Requirements(*(random_requirement(rng, k) for k in keys))


def kernel_compat(rows, sets, vocab):
    """Run the device kernel for requirement rows vs sets."""
    er = enc.encode_requirement_rows(vocab, rows)
    es = enc.encode_requirement_sets(
        vocab, sets, key_capacity=vocab.key_capacity, word_capacity=vocab.word_capacity
    )
    # rows may have interned new slots after their encoding — re-encode to be safe
    er = enc.encode_requirement_rows(vocab, rows)
    tables = vocab.tables()
    out = feas.req_rows_vs_sets(
        jnp.asarray(er.key),
        jnp.asarray(er.complement),
        jnp.asarray(er.has_values),
        jnp.asarray(er.gt),
        jnp.asarray(er.lt),
        jnp.asarray(er.mask),
        jnp.asarray(es.present),
        jnp.asarray(es.complement),
        jnp.asarray(es.has_values),
        jnp.asarray(es.gt),
        jnp.asarray(es.lt),
        jnp.asarray(es.mask),
        jnp.asarray(tables.slot_key),
        jnp.asarray(tables.value_int),
    )
    return np.asarray(out)


class TestKernelMatchesHost:
    def test_randomized_equivalence(self):
        rng = random.Random(42)
        # pre-intern the full value space so capacities are stable
        vocab = enc.Vocab()
        for k, vs in VALUES.items():
            for v in vs:
                vocab.slot(k, v)

        rows = [random_requirement(rng, rng.choice(KEYS)) for _ in range(60)]
        sets = [random_req_set(rng) for _ in range(40)]
        got = kernel_compat(rows, sets, vocab)

        for i, row in enumerate(rows):
            for j, s in enumerate(sets):
                # oracle: existing set `s` vs incoming single-row requirements
                expected = s.intersects(Requirements(row)) is None
                assert got[i, j] == expected, (
                    f"row={row!r} set={s!r}: kernel={got[i, j]} host={expected}"
                )

    def test_unconstrained_key_is_compatible(self):
        vocab = enc.Vocab()
        rows = [Requirement("zone", Operator.IN, ["z9"])]
        sets = [Requirements(Requirement("arch", Operator.IN, ["amd64"]))]
        assert kernel_compat(rows, sets, vocab)[0, 0]

    def test_bounds_vs_concrete(self):
        vocab = enc.Vocab()
        rows = [Requirement("size", Operator.GT, ["4"])]
        sets = [
            Requirements(Requirement("size", Operator.IN, ["2", "4"])),
            Requirements(Requirement("size", Operator.IN, ["8"])),
        ]
        got = kernel_compat(rows, sets, vocab)
        assert not got[0, 0] and got[0, 1]

    def test_membership_all(self):
        membership = jnp.asarray(
            np.array([[1, 1, 0], [0, 0, 1], [0, 0, 0]], dtype=bool)
        )
        row_ok = jnp.asarray(
            np.array([[1, 0], [1, 1], [0, 1]], dtype=bool)
        )
        got = np.asarray(feas.membership_all(membership, row_ok))
        # pod0 needs rows {0,1}: target0 -> 1&1=yes, target1 -> 0&1=no
        # pod1 needs row {2}: target0 -> no, target1 -> yes
        # pod2 unconstrained: both yes
        expected = np.array([[True, False], [False, True], [True, True]])
        assert (got == expected).all()

    def test_fits_matrix(self):
        req = jnp.asarray(np.array([[1.0, 2.0, 0.0], [4.0, 0.0, 1.0]], np.float32))
        alloc = jnp.asarray(
            np.array([[2.0, 2.0, 0.0], [8.0, 8.0, 0.0]], np.float32)
        )
        got = np.asarray(feas.fits_matrix(req, alloc))
        expected = np.array([[True, True], [False, False]])  # gpu=1 never fits
        assert (got == expected).all()
