"""solverd: the batched solver service — coalescing, admission control,
transport parity (ISSUE 1 acceptance criteria)."""

import threading

import pytest

from karpenter_tpu.cloudprovider.kwok.instance_types import construct_instance_types
from karpenter_tpu.events.recorder import Recorder
from karpenter_tpu.ops import ffd
from karpenter_tpu.ops.catalog import CatalogEngine
from karpenter_tpu.runtime.store import Store
from karpenter_tpu.scheduler.scheduler import Scheduler
from karpenter_tpu.scheduler.topology import Topology
from karpenter_tpu.solverd import (
    KIND_SIMULATE,
    KIND_SOLVE,
    DeadlineExceededError,
    InProcessClient,
    QueueFullError,
    SocketClient,
    SolveRequest,
    SolverClosedError,
    SolverDaemon,
    SolverService,
    TransportError,
    build_solver,
)
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.state.informer import StateInformer
from karpenter_tpu.utils.clock import Clock, FakeClock

from helpers import nodepool, unschedulable_pod

CATALOG = construct_instance_types()


def build_scheduler(engine=None, n_pods=6, cpu="1"):
    """A minimal, fully deterministic solve scenario over the kwok catalog.
    Identical arguments build bit-identical scenarios (pinned uids and
    timestamps) so transport-parity tests can compare decisions exactly."""
    clock = FakeClock()
    store = Store(clock=clock)
    cluster = Cluster(clock, store, cloud_provider=None)
    informer = StateInformer(store, cluster)
    recorder = Recorder(clock=clock)
    pool = nodepool("default")
    store.create(pool)
    informer.flush()
    pods = []
    for i in range(n_pods):
        p = unschedulable_pod(name=f"pod-{i:03d}", requests={"cpu": cpu})
        p.metadata.uid = f"uid-{i:03d}"
        p.metadata.creation_timestamp = 1000.0 + i
        store.create(p)
        pods.append(p)
    instance_types = {"default": list(CATALOG)}
    topology = Topology(store, cluster, [], [pool], instance_types, pods)
    scheduler = Scheduler(
        store, [pool], cluster, [], topology, instance_types, [],
        recorder, clock, engine=engine,
    )
    return scheduler, pods


def decisions(results):
    """The transport-invariant shape of a solve: per-claim (nodepool, pods,
    instance-type options) plus failure names."""
    claims = sorted(
        (
            nc.nodepool_name,
            tuple(sorted(p.metadata.name for p in nc.pods)),
            tuple(sorted(it.name for it in nc.instance_type_options)),
        )
        for nc in results.new_node_claims
    )
    errors = sorted(p.metadata.name for p in results.pod_errors)
    return claims, errors


class TestAdmissionControl:
    def test_queue_full_rejects_not_blocks(self):
        svc = SolverService(clock=FakeClock(), max_queue_depth=2)
        reqs = [
            SolveRequest(KIND_SOLVE, *build_scheduler(n_pods=1), timeout=60.0)
            for _ in range(3)
        ]
        svc.submit(reqs[0])
        svc.submit(reqs[1])
        with pytest.raises(QueueFullError):
            svc.submit(reqs[2])
        # the shed request did not poison the queue: admitted work executes
        assert svc.run_pending() == 2
        assert svc.rejected == 1

    def test_deadline_rejected_at_offer(self):
        clock = FakeClock()
        svc = SolverService(clock=clock)
        scheduler, pods = build_scheduler(n_pods=1)
        with pytest.raises(DeadlineExceededError):
            svc.submit(
                SolveRequest(
                    KIND_SOLVE, scheduler, pods, deadline=clock.now() - 1.0
                )
            )

    def test_deadline_expires_in_queue(self):
        clock = FakeClock()
        svc = SolverService(clock=clock)
        scheduler, pods = build_scheduler(n_pods=1)
        entry = svc.submit(
            SolveRequest(KIND_SOLVE, scheduler, pods, deadline=clock.now() + 5.0)
        )
        clock.step(10.0)  # deadline passes while queued
        assert svc.run_pending() == 0  # expired work is NOT executed
        assert entry.done
        assert isinstance(entry.error, DeadlineExceededError)

    def test_closed_service_rejects(self):
        svc = SolverService(clock=FakeClock())
        svc.close()
        scheduler, pods = build_scheduler(n_pods=1)
        with pytest.raises(SolverClosedError):
            svc.submit(SolveRequest(KIND_SOLVE, scheduler, pods))


class TestInProcessTransport:
    def test_solve_matches_direct_scheduler_solve(self):
        direct_scheduler, direct_pods = build_scheduler()
        direct = direct_scheduler.solve(direct_pods, timeout=60.0)
        scheduler, pods = build_scheduler()
        client = InProcessClient(SolverService(clock=FakeClock()))
        via = client.solve(KIND_SOLVE, scheduler, pods, timeout=60.0)
        assert decisions(via) == decisions(direct)

    def test_solve_error_propagates(self):
        svc = SolverService(clock=FakeClock())

        class Boom:
            engine = None

            def solve(self, pods, timeout=None):
                raise RuntimeError("boom")

        with pytest.raises(RuntimeError, match="boom"):
            svc.solve(SolveRequest(KIND_SOLVE, Boom(), []))

    def test_provisioner_routes_through_solverd(self):
        from helpers import make_provisioner_harness

        clock, store, provider, cluster, informer, prov = (
            make_provisioner_harness()
        )
        assert isinstance(prov.solver, InProcessClient)
        store.create(nodepool("default"))
        pod = store.create(unschedulable_pod(requests={"cpu": "1"}))
        prov.trigger(pod.metadata.uid)
        informer.flush()
        clock.step(1.5)
        results = prov.reconcile()
        assert results is not None and results.new_node_claims
        stats = prov.solver.stats()
        assert stats["requests"] >= 1
        assert stats["batches"] >= 1

    def test_build_solver_from_options(self):
        from karpenter_tpu.operator.options import Options

        opts = Options(solverd_queue_depth=7, solverd_coalesce_window=0.25)
        client = build_solver(opts, FakeClock())
        assert isinstance(client, InProcessClient)
        assert client.service.queue.max_depth == 7
        assert client.service.coalesce_window == 0.25
        opts = Options(
            solver_transport="socket", solver_daemon_address="127.0.0.1:19999"
        )
        client = build_solver(opts, FakeClock())
        assert isinstance(client, SocketClient)
        # socket mode without an address must fail loudly, not silently
        # fall back to in-process (which would contend for the accelerator)
        with pytest.raises(ValueError, match="solver-daemon-address"):
            build_solver(Options(solver_transport="socket"), FakeClock())


class TestCoalescing:
    def test_two_requests_one_device_batch(self, monkeypatch):
        """Two concurrent solve/simulate requests sharing a catalog merge
        into a single coalesced batch that dispatches ONE joint-mask device
        sweep; both results match un-coalesced solves of the same
        scenarios."""
        monkeypatch.setattr(ffd, "DEVICE_MIN_PODS", 1)
        monkeypatch.setattr(ffd, "STRICT", True)
        # reference decisions, solo (one engine per scenario: no sharing)
        ref1 = build_scheduler(engine=CatalogEngine(CATALOG))
        ref2 = build_scheduler(engine=CatalogEngine(CATALOG), cpu="2")
        want1 = decisions(ref1[0].solve(ref1[1], timeout=60.0))
        want2 = decisions(ref2[0].solve(ref2[1], timeout=60.0))
        # coalesced: both requests share one engine
        engine = CatalogEngine(CATALOG)
        s1, p1 = build_scheduler(engine=engine)
        s2, p2 = build_scheduler(engine=engine, cpu="2")
        svc = SolverService(clock=FakeClock())
        e1 = svc.submit(SolveRequest(KIND_SOLVE, s1, p1, timeout=60.0))
        e2 = svc.submit(SolveRequest(KIND_SIMULATE, s2, p2, timeout=60.0))
        sweeps0 = ffd.JOINT_SWEEPS
        assert svc.run_pending() == 2
        assert ffd.JOINT_SWEEPS == sweeps0 + 1, (
            "coalesced batch must dispatch exactly one joint-mask sweep"
        )
        assert svc.max_batch_size == 2
        assert decisions(e1.result) == want1
        assert decisions(e2.result) == want2

    def test_concurrent_threads_share_one_batch(self, monkeypatch):
        """Threads racing into the service inside the coalescing window ride
        one batch — the daemon-mode concurrency story, minus the socket."""
        monkeypatch.setattr(ffd, "DEVICE_MIN_PODS", 1)
        engine = CatalogEngine(CATALOG)
        scenarios = [build_scheduler(engine=engine) for _ in range(2)]
        svc = SolverService(clock=Clock(), coalesce_window=0.4)
        client = InProcessClient(svc)
        results = [None, None]
        errors = []
        barrier = threading.Barrier(2)

        def run(i, scheduler, pods):
            try:
                barrier.wait(timeout=5)
                results[i] = client.solve(
                    KIND_SIMULATE, scheduler, pods, timeout=60.0
                )
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [
            threading.Thread(target=run, args=(i, s, p))
            for i, (s, p) in enumerate(scenarios)
        ]
        sweeps0 = ffd.JOINT_SWEEPS
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors
        assert all(r is not None for r in results)
        assert svc.max_batch_size >= 2, "window should have merged both"
        assert ffd.JOINT_SWEEPS <= sweeps0 + 1

    def test_singleton_batch_skips_priming(self, monkeypatch):
        """A lone request must not pay the collect/prime pass (bench p50
        guard): its only sweep is the solve's own."""
        monkeypatch.setattr(ffd, "DEVICE_MIN_PODS", 1)
        engine = CatalogEngine(CATALOG)
        s1, p1 = build_scheduler(engine=engine)
        svc = SolverService(clock=FakeClock())
        svc.submit(SolveRequest(KIND_SOLVE, s1, p1, timeout=60.0))
        sweeps0 = ffd.JOINT_SWEEPS
        assert svc.run_pending() == 1
        assert ffd.JOINT_SWEEPS == sweeps0 + 1  # the solve's own sweep only


class TestSocketTransport:
    def _daemon(self):
        svc = SolverService(clock=Clock())
        daemon = SolverDaemon(svc, address="127.0.0.1:0").start()
        return svc, daemon

    def test_identical_decisions_host_path(self):
        scheduler, pods = build_scheduler()
        want = decisions(scheduler.solve(pods, timeout=60.0))
        svc, daemon = self._daemon()
        client = SocketClient(daemon.address)
        try:
            s2, p2 = build_scheduler()
            got = client.solve(KIND_SOLVE, s2, p2, timeout=60.0)
        finally:
            client.close()
            daemon.stop()
            svc.close()
        assert decisions(got) == want

    def test_identical_decisions_device_path(self, monkeypatch):
        """The kwok-catalog parity check from the acceptance criteria: the
        daemon rebuilds its own engine from the shipped catalog, runs the
        device path, and lands on the same node decisions as in-process."""
        monkeypatch.setattr(ffd, "DEVICE_MIN_PODS", 1)
        s1, p1 = build_scheduler(engine=CatalogEngine(CATALOG), n_pods=12)
        inproc = InProcessClient(SolverService(clock=FakeClock()))
        want = decisions(inproc.solve(KIND_SOLVE, s1, p1, timeout=60.0))
        svc, daemon = self._daemon()
        client = SocketClient(daemon.address)
        try:
            s2, p2 = build_scheduler(engine=CatalogEngine(CATALOG), n_pods=12)
            got = client.solve(KIND_SOLVE, s2, p2, timeout=60.0)
        finally:
            client.close()
            daemon.stop()
            svc.close()
        assert decisions(got) == want

    def test_stats_rpc_surfaces_daemon_counters(self):
        svc, daemon = self._daemon()
        client = SocketClient(daemon.address)
        try:
            scheduler, pods = build_scheduler(n_pods=2)
            client.solve(KIND_SOLVE, scheduler, pods, timeout=60.0)
            stats = client.stats()
        finally:
            client.close()
            daemon.stop()
            svc.close()
        assert stats["transport"] == "socket"
        assert stats["address"] == daemon.address
        assert stats["requests"] >= 1 and stats["batches"] >= 1

    def test_typed_rejection_crosses_the_wire(self):
        svc = SolverService(clock=Clock(), max_queue_depth=0)
        daemon = SolverDaemon(svc, address="127.0.0.1:0").start()
        client = SocketClient(daemon.address)
        try:
            scheduler, pods = build_scheduler(n_pods=1)
            with pytest.raises(QueueFullError):
                client.solve(KIND_SOLVE, scheduler, pods, timeout=60.0)
        finally:
            client.close()
            daemon.stop()
            svc.close()


class TestSocketReconnect:
    """Satellite (ISSUE 2): the socket transport must survive a daemon
    restart mid-stream via reconnect-with-backoff, and in-flight requests
    against a dead daemon must surface a typed retryable error promptly
    instead of hanging."""

    def test_survives_daemon_restart_midstream(self, tmp_path):
        # unix socket: restart-on-same-address without TCP TIME_WAIT games
        address = str(tmp_path / "solverd.sock")
        svc1 = SolverService(clock=Clock())
        daemon1 = SolverDaemon(svc1, address=address).start()
        sleeps = []
        client = SocketClient(address, sleep=sleeps.append)
        scheduler, pods = build_scheduler(n_pods=2)
        want = decisions(client.solve(KIND_SOLVE, scheduler, pods, timeout=60.0))
        # restart the daemon on the SAME address: the client's persistent
        # connection is now a dead socket it must notice and re-dial
        daemon1.stop()
        svc1.close()
        svc2 = SolverService(clock=Clock())
        daemon2 = SolverDaemon(svc2, address=address).start()
        try:
            s2, p2 = build_scheduler(n_pods=2)
            got = decisions(client.solve(KIND_SOLVE, s2, p2, timeout=60.0))
        finally:
            client.close()
            daemon2.stop()
            svc2.close()
        assert got == want
        assert client.reconnects >= 1

    def test_dead_daemon_raises_typed_retryable_not_hang(self):
        svc = SolverService(clock=Clock())
        daemon = SolverDaemon(svc, address="127.0.0.1:0").start()
        daemon.stop()
        svc.close()
        sleeps = []
        client = SocketClient(
            daemon.address,
            connect_timeout=0.5,
            reconnect_attempts=3,
            backoff_base=0.05,
            backoff_max=1.0,
            sleep=sleeps.append,
        )
        scheduler, pods = build_scheduler(n_pods=1)
        done = threading.Event()
        caught = []

        def attempt():
            with pytest.raises(TransportError) as exc:
                client.solve(KIND_SOLVE, scheduler, pods, timeout=60.0)
            caught.append(exc.value)
            done.set()

        t = threading.Thread(target=attempt, daemon=True)
        t.start()
        # "promptly": bounded by attempts x connect_timeout, not a recv hang
        assert done.wait(timeout=10.0), "in-flight request hung on dead daemon"
        t.join()
        client.close()
        assert caught[0].retryable is True
        # exponential backoff between re-dials: base, then base*2
        assert sleeps == [pytest.approx(0.05), pytest.approx(0.1)]

    def test_backoff_capped_and_attempts_bounded(self):
        svc = SolverService(clock=Clock())
        daemon = SolverDaemon(svc, address="127.0.0.1:0").start()
        daemon.stop()
        svc.close()
        sleeps = []
        client = SocketClient(
            daemon.address,
            connect_timeout=0.2,
            reconnect_attempts=5,
            backoff_base=0.1,
            backoff_max=0.25,
            sleep=sleeps.append,
        )
        with pytest.raises(TransportError), client._lock:
            client._rpc({"v": 1, "op": "stats"})
        client.close()
        assert sleeps == [
            pytest.approx(0.1),
            pytest.approx(0.2),
            pytest.approx(0.25),
            pytest.approx(0.25),
        ]

    def test_stats_degrades_instead_of_raising(self):
        svc = SolverService(clock=Clock())
        daemon = SolverDaemon(svc, address="127.0.0.1:0").start()
        daemon.stop()
        svc.close()
        client = SocketClient(
            daemon.address, connect_timeout=0.2, sleep=lambda s: None
        )
        stats = client.stats()
        client.close()
        assert stats["transport"] == "socket"
        assert "error" in stats


class TestStatsConsistency:
    """/debug/solverd snapshots are taken under the service's stats lock:
    a concurrent reader must never observe counters torn mid-batch (e.g.
    `executed` ahead of `requests`, or `batches` ahead of `executed`)."""

    def test_concurrent_reads_see_consistent_counters(self):
        import threading

        svc = SolverService(clock=FakeClock())
        stop = threading.Event()
        violations = []

        def reader():
            while not stop.is_set():
                s = svc.stats()
                if not (
                    s["rejected"] + s["executed"] <= s["requests"]
                    and s["batches"] <= s["executed"] + 1
                ):
                    violations.append(s)

        threads = [threading.Thread(target=reader) for _ in range(3)]
        for t in threads:
            t.start()
        try:
            for _ in range(20):
                scheduler, pods = build_scheduler(n_pods=1)
                svc.submit(SolveRequest(KIND_SOLVE, scheduler, pods, timeout=60.0))
                svc.run_pending()
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10)
            svc.close()
        assert not violations, violations[:3]
        final = svc.stats()
        assert final["requests"] == final["executed"] == 20
        assert final["batches"] == 20

    def test_snapshot_invariants_after_rejections(self):
        svc = SolverService(clock=FakeClock(), max_queue_depth=1)
        s1, p1 = build_scheduler(n_pods=1)
        svc.submit(SolveRequest(KIND_SOLVE, s1, p1, timeout=60.0))
        s2, p2 = build_scheduler(n_pods=1)
        with pytest.raises(QueueFullError):
            svc.submit(SolveRequest(KIND_SOLVE, s2, p2, timeout=60.0))
        svc.run_pending()
        svc.close()
        stats = svc.stats()
        assert stats["requests"] == 1
        assert stats["executed"] == 1
        assert stats["rejected"] == 1
        assert stats["executed"] <= stats["requests"]


class _ExplodingScheduler:
    """Module-level (picklable) scheduler stub whose solve always raises —
    the per-item error-isolation case for solve_many on both transports."""

    engine = None

    def __init__(self):
        self.clock = FakeClock()

    def solve(self, pods, timeout=None):
        raise RuntimeError("boom")


class TestSolveMany:
    """Batched submission (the consolidation frontier's transport): one
    admission group, one coalesced batch, per-item verdicts."""

    def test_one_batch_and_ordered_results(self):
        direct = []
        batch = []
        for n in (2, 4, 3):
            s, p = build_scheduler(n_pods=n)
            direct.append(decisions(s.solve(p, timeout=60.0)))
            s2, p2 = build_scheduler(n_pods=n)
            batch.append((s2, p2))
        svc = SolverService(clock=FakeClock())
        client = InProcessClient(svc)
        try:
            out = client.solve_many(
                KIND_SIMULATE, batch, timeout=60.0, group="frontier-test"
            )
        finally:
            svc.close()
        assert [err for _, err in out] == [None, None, None]
        assert [decisions(res) for res, _ in out] == direct
        stats = svc.stats()
        assert stats["batches"] == 1, "a frontier group must run as ONE batch"
        assert stats["executed"] == 3

    def test_per_item_error_isolation(self):
        s1, p1 = build_scheduler(n_pods=2)
        svc = SolverService(clock=FakeClock())
        client = InProcessClient(svc)
        try:
            out = client.solve_many(
                KIND_SIMULATE,
                [(s1, p1), (_ExplodingScheduler(), [])],
                timeout=60.0,
            )
        finally:
            svc.close()
        (res, err), (res2, err2) = out
        assert err is None and res is not None
        assert res2 is None and isinstance(err2, RuntimeError)

    def test_rejection_cancels_the_whole_group(self):
        svc = SolverService(clock=FakeClock(), max_queue_depth=2)
        batch = []
        for _ in range(3):
            s, p = build_scheduler(n_pods=1)
            batch.append(
                SolveRequest(KIND_SIMULATE, s, list(p), timeout=60.0)
            )
        with pytest.raises(QueueFullError):
            svc.solve_many(batch)
        # the two admitted siblings were un-admitted: nothing left to run
        assert svc.queue.depth() == 0
        assert svc.run_pending() == 0
        assert svc.stats()["cancelled"] == 2
        svc.close()

    def test_remove_after_concurrent_drain_is_a_noop(self):
        """AdmissionQueue.remove vs a concurrent leader's drain: entries
        the leader already took are simply not found — remove() must not
        resurrect, double-complete, or corrupt the queue."""
        from karpenter_tpu.solverd import AdmissionQueue

        q = AdmissionQueue(FakeClock())
        entries = []
        for _ in range(3):
            s, p = build_scheduler(n_pods=1)

            class E:
                pass

            e = E()
            e.request = SolveRequest(KIND_SIMULATE, s, list(p))
            e.enqueued_at = 0.0
            entries.append(e)
            q.offer(e)
        ready, _ = q.drain()  # the concurrent leader won the race
        assert len(ready) == 3
        assert q.remove(entries) == []
        assert q.depth() == 0
        # partial race: one entry still queued, two already drained
        q.offer(entries[0])
        assert q.remove(entries) == [entries[0]]
        assert q.depth() == 0

    def test_midgroup_shed_unadmits_while_leader_executes(self):
        """A solve_many group shed mid-admission while a concurrent leader
        is EXECUTING an earlier batch: the group's admitted prefix must be
        un-admitted (the later drain runs none of it) and the in-flight
        batch must be untouched."""
        svc = SolverService(clock=FakeClock(), max_queue_depth=2)
        started, release = threading.Event(), threading.Event()
        orig = svc.coalescer.execute

        def gated(entries):
            started.set()
            assert release.wait(timeout=10)
            return orig(entries)

        svc.coalescer.execute = gated
        s0, p0 = build_scheduler(n_pods=1)
        leader_box = []
        leader = threading.Thread(
            target=lambda: leader_box.append(
                svc.solve(SolveRequest(KIND_SOLVE, s0, list(p0), timeout=60.0))
            )
        )
        leader.start()
        assert started.wait(timeout=10)  # leader drained its batch, executing
        batch = []
        for _ in range(3):
            s, p = build_scheduler(n_pods=1)
            batch.append(SolveRequest(KIND_SIMULATE, s, list(p), timeout=60.0))
        with pytest.raises(QueueFullError):
            svc.solve_many(batch)  # third offer tops the depth-2 queue
        assert svc.queue.depth() == 0  # admitted prefix un-admitted
        assert svc.stats()["cancelled"] == 2
        release.set()
        leader.join(timeout=10)
        assert leader_box and leader_box[0].new_node_claims is not None
        assert svc.run_pending() == 0  # nothing abandoned left to execute
        assert svc.stats()["executed"] == 1
        svc.close()

    def test_leader_loss_mid_round_fails_followers_not_hangs(self):
        """The batch leader dying mid-frontier-round (its coalescer pass
        raising) must complete every drained entry with a terminal error —
        followers waiting on the group observe failure, never a hang —
        and the service must stay serviceable afterwards."""
        svc = SolverService(clock=FakeClock())
        s1, p1 = build_scheduler(n_pods=1)
        s2, p2 = build_scheduler(n_pods=1)
        follower_entries = [
            svc.submit(SolveRequest(KIND_SIMULATE, s1, list(p1), timeout=60.0)),
            svc.submit(SolveRequest(KIND_SIMULATE, s2, list(p2), timeout=60.0)),
        ]

        def dying(entries):
            raise RuntimeError("leader lost mid-round")

        orig = svc.coalescer.execute
        svc.coalescer.execute = dying
        s0, p0 = build_scheduler(n_pods=1)
        with pytest.raises(RuntimeError, match="leader lost"):
            # this caller becomes the leader and drains ALL three entries
            svc.solve(SolveRequest(KIND_SOLVE, s0, list(p0), timeout=60.0))
        for entry in follower_entries:
            assert entry.done, "follower stranded by the dead leader"
            assert isinstance(entry.error, RuntimeError)
            assert "aborted" in str(entry.error)
        # the service recovered: the next group runs normally
        svc.coalescer.execute = orig
        s3, p3 = build_scheduler(n_pods=1)
        entries = svc.solve_many(
            [SolveRequest(KIND_SIMULATE, s3, list(p3), timeout=60.0)]
        )
        assert entries[0].error is None
        assert entries[0].result.new_node_claims is not None
        svc.close()

    def test_socket_solve_many_matches_inprocess(self):
        batch_sizes = (2, 3)
        inproc_svc = SolverService(clock=FakeClock())
        inproc = InProcessClient(inproc_svc)
        try:
            want = [
                decisions(res)
                for res, err in inproc.solve_many(
                    KIND_SIMULATE,
                    [build_scheduler(n_pods=n) for n in batch_sizes],
                    timeout=60.0,
                    group="g1",
                )
            ]
        finally:
            inproc_svc.close()
        svc = SolverService(clock=Clock())
        daemon = SolverDaemon(svc, address="127.0.0.1:0").start()
        client = SocketClient(daemon.address)
        try:
            out = client.solve_many(
                KIND_SIMULATE,
                [build_scheduler(n_pods=n) for n in batch_sizes],
                timeout=60.0,
                group="g1",
            )
            assert [err for _, err in out] == [None, None]
            assert [decisions(res) for res, _ in out] == want
            # the whole group rode ONE frame into ONE coalesced batch
            assert svc.stats()["batches"] == 1
        finally:
            client.close()
            daemon.stop()
            svc.close()

    def test_socket_solve_many_per_item_error(self):
        svc = SolverService(clock=Clock())
        daemon = SolverDaemon(svc, address="127.0.0.1:0").start()
        client = SocketClient(daemon.address)
        try:
            s1, p1 = build_scheduler(n_pods=2)
            out = client.solve_many(
                KIND_SIMULATE,
                [(s1, p1), (_ExplodingScheduler(), [])],
                timeout=60.0,
            )
        finally:
            client.close()
            daemon.stop()
            svc.close()
        (res, err), (res2, err2) = out
        assert err is None and res is not None
        assert res2 is None and isinstance(err2, TransportError)
        assert "boom" in str(err2)

    def test_base_class_fallback_is_sequential_solves(self):
        from karpenter_tpu.solverd.transport import SolverClient

        calls = []

        class Seq(SolverClient):
            def solve(self, kind, scheduler, pods, timeout=None, deadline=None,
                      request_id=None, tenant=None):
                calls.append(scheduler)
                if scheduler == "bad":
                    raise RuntimeError("nope")
                return f"ok-{scheduler}"

        out = Seq().solve_many("simulate", [("a", []), ("bad", []), ("c", [])])
        assert calls == ["a", "bad", "c"]
        assert out[0] == ("ok-a", None)
        assert out[1][0] is None and isinstance(out[1][1], RuntimeError)
        assert out[2] == ("ok-c", None)
