"""Instance-selection specs (instance_selection_test.go:87-431): the launch
picks one of the cheapest instances compatible with pod + nodepool
constraints — asserted end-to-end through the kwok provider, which owns
launch-time price ordering. Plus namespace-filtered affinity
(topology_test.go:2853-2930) and device-path timeout surfacing."""

import pytest

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import (
    Affinity,
    LabelSelector,
    ObjectMeta,
    PodAffinity,
    PodAffinityTerm,
)
from karpenter_tpu.cloudprovider.kwok.instance_types import construct_instance_types
from karpenter_tpu.cloudprovider.kwok.provider import KwokCloudProvider
from karpenter_tpu.operator.operator import Operator
from karpenter_tpu.runtime.store import Store
from karpenter_tpu.utils.clock import FakeClock

from helpers import bind_pod, nodepool, registered_node, unschedulable_pod
from test_scheduler import Env

CATALOG = construct_instance_types()


def cheapest_price(predicate, offering_predicate=lambda o: True):
    prices = [
        offering.price
        for it in CATALOG
        if predicate(it)
        for offering in it.offerings
        if offering.available and offering_predicate(offering)
    ]
    return min(prices)


def launch_and_get_node(pod=None, pool=None):
    clock = FakeClock()
    store = Store(clock=clock)
    op = Operator(store, KwokCloudProvider(store, clock), clock=clock)
    store.create(pool or nodepool("workers"))
    store.create(pod or unschedulable_pod(requests={"cpu": "100m"}))
    for _ in range(12):
        clock.step(2.0)
        op.run_once()
    [node] = store.list("Node")
    return node


def node_price(node):
    name = node.metadata.labels[wk.LABEL_INSTANCE_TYPE]
    it = next(i for i in CATALOG if i.name == name)
    zone = node.metadata.labels[wk.LABEL_TOPOLOGY_ZONE]
    ct = node.metadata.labels[wk.CAPACITY_TYPE_LABEL_KEY]
    return next(
        o.price for o in it.offerings if o.zone == zone and o.capacity_type == ct
    )


class TestCheapestInstanceSelection:
    """instance_selection_test.go:87-431 — launch lands on a cheapest
    compatible offering."""

    def test_unconstrained(self):
        node = launch_and_get_node()
        assert node_price(node) == cheapest_price(lambda it: True)

    @pytest.mark.parametrize("arch", ["amd64", "arm64"])
    def test_pod_arch(self, arch):
        node = launch_and_get_node(
            pod=unschedulable_pod(
                requests={"cpu": "100m"}, node_selector={wk.LABEL_ARCH: arch}
            )
        )
        assert node.metadata.labels[wk.LABEL_ARCH] == arch
        assert node_price(node) == cheapest_price(
            lambda it: it.requirements.get(wk.LABEL_ARCH).has(arch)
        )

    def test_pod_os_windows(self):
        node = launch_and_get_node(
            pod=unschedulable_pod(
                requests={"cpu": "100m"}, node_selector={wk.LABEL_OS: "windows"}
            )
        )
        assert node_price(node) == cheapest_price(
            lambda it: it.requirements.get(wk.LABEL_OS).has("windows")
        )

    def test_nodepool_capacity_type_on_demand(self):
        pool = nodepool(
            "workers",
            requirements=[
                {
                    "key": wk.CAPACITY_TYPE_LABEL_KEY,
                    "operator": "In",
                    "values": [wk.CAPACITY_TYPE_ON_DEMAND],
                }
            ],
        )
        node = launch_and_get_node(pool=pool)
        assert (
            node.metadata.labels[wk.CAPACITY_TYPE_LABEL_KEY]
            == wk.CAPACITY_TYPE_ON_DEMAND
        )
        # cheapest ON-DEMAND offering (spot is cheaper but filtered out)
        assert node_price(node) == cheapest_price(
            lambda it: True,
            lambda o: o.capacity_type == wk.CAPACITY_TYPE_ON_DEMAND,
        )

    def test_pod_zone_and_capacity_type(self):
        pod = unschedulable_pod(
            requests={"cpu": "100m"},
            node_selector={
                wk.LABEL_TOPOLOGY_ZONE: "kwok-zone-2",
                wk.CAPACITY_TYPE_LABEL_KEY: wk.CAPACITY_TYPE_SPOT,
            },
        )
        node = launch_and_get_node(pod=pod)
        assert node.metadata.labels[wk.LABEL_TOPOLOGY_ZONE] == "kwok-zone-2"
        assert node_price(node) == cheapest_price(
            lambda it: True,
            lambda o: o.capacity_type == wk.CAPACITY_TYPE_SPOT
            and o.zone == "kwok-zone-2",
        )


class TestNamespaceFilteredAffinity:
    """topology_test.go:2853-2930 — affinity terms only see pods in the
    term's namespaces (the pod's own namespace by default)."""

    def _target_on_node(self, namespace):
        node = registered_node(zone="kwok-zone-1", pool="default")
        target = unschedulable_pod(labels={"app": "web"})
        target.metadata.namespace = namespace
        bind_pod(target, node)
        return node, target

    def _affine_pod(self):
        return unschedulable_pod(
            labels={"app": "db"},
            affinity=Affinity(
                pod_affinity=PodAffinity(
                    required=[
                        PodAffinityTerm(
                            topology_key=wk.LABEL_TOPOLOGY_ZONE,
                            label_selector=LabelSelector(match_labels={"app": "web"}),
                        )
                    ]
                )
            ),
        )

    def test_no_matching_pods_in_namespace(self):
        # the target lives in another namespace: affinity finds nothing
        node, target = self._target_on_node("other-namespace")
        env = Env(state_nodes=[node], pods=[target])
        results = env.schedule([self._affine_pod()])
        assert len(results.pod_errors) == 1

    def test_matching_pods_via_namespace_list(self):
        node, target = self._target_on_node("other-namespace")
        env = Env(state_nodes=[node], pods=[target])
        pod = self._affine_pod()
        pod.spec.affinity.pod_affinity.required[0].namespaces = ["other-namespace"]
        results = env.schedule([pod])
        assert not results.pod_errors
        # the pod must land in the target's zone — on the existing zone-1
        # node or a new zone-1 claim
        placed_zones = set()
        for en in results.existing_nodes:
            if en.pods:
                placed_zones.add(en.labels().get(wk.LABEL_TOPOLOGY_ZONE))
        for nc in results.new_node_claims:
            placed_zones.update(
                nc.requirements.get(wk.LABEL_TOPOLOGY_ZONE).values_list()
            )
        assert placed_zones == {"kwok-zone-1"}


class TestDeviceTimeout:
    def test_device_path_surfaces_timeout(self):
        """A zero budget times the native solve out; unprocessed pods carry
        TimeoutError and the Results flag is set (scheduler.go ctx.Err)."""
        from karpenter_tpu.ops.catalog import CatalogEngine

        env = Env(engine=CatalogEngine(CATALOG))
        pods = [unschedulable_pod(requests={"cpu": "100m"}) for _ in range(2000)]
        results = env.schedule(pods, timeout=0.0)
        assert results.timed_out
        assert results.pod_errors
        assert any(
            isinstance(e, TimeoutError) for e in results.pod_errors.values()
        )
