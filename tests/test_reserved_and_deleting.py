"""Reserved-capacity scheduling (reference suite_test.go:3976-4455) and
deleting-node rescheduling (suite_test.go:3545-3699) specs."""

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import Condition
from karpenter_tpu.state.statenode import deleting
from karpenter_tpu.utils.pdb import Limits
from karpenter_tpu.cloudprovider.types import (
    InstanceType,
    Offering,
    Offerings,
    RESERVATION_ID_LABEL,
)
from karpenter_tpu.scheduler.nodeclaim import (
    RESERVED_OFFERING_MODE_STRICT,
    ReservedOfferingError,
)
from karpenter_tpu.scheduling.requirements import Operator, Requirement, Requirements
from karpenter_tpu.utils.resources import parse_resource_list

from helpers import (
    bind_pod,
    daemonset,
    daemonset_pod,
    node_claim_pair,
    nodepool,
    unschedulable_pod,
)
from device_path import both_paths_fixture
from test_scheduler import Env as HostEnv

Env = HostEnv
path = both_paths_fixture(globals())


def env_for(catalog, **kwargs):
    """Env over a custom catalog; the device leg gets an engine on it."""
    from karpenter_tpu.ops.catalog import CatalogEngine

    kwargs["catalog"] = catalog
    if Env is not HostEnv:
        kwargs["engine"] = CatalogEngine(catalog)
    return Env(**kwargs)


def reserved_catalog(reservation_capacity=2):
    """One 4-cpu instance type: on-demand at 1.0 plus a reserved offering
    (reservation cr-1) at a tenth of the price."""

    def offering(ct, price, rid=None, capacity=0):
        rows = [
            Requirement(wk.CAPACITY_TYPE_LABEL_KEY, Operator.IN, [ct]),
            Requirement(wk.LABEL_TOPOLOGY_ZONE, Operator.IN, ["kwok-zone-1"]),
        ]
        if rid is not None:
            rows.append(Requirement(RESERVATION_ID_LABEL, Operator.IN, [rid]))
        return Offering(
            requirements=Requirements(*rows),
            price=price,
            available=True,
            reservation_capacity=capacity,
        )

    return [
        InstanceType(
            name="r-4x",
            requirements=Requirements(
                Requirement(wk.LABEL_INSTANCE_TYPE, Operator.IN, ["r-4x"]),
                Requirement(wk.LABEL_ARCH, Operator.IN, ["amd64"]),
                Requirement(wk.LABEL_OS, Operator.IN, ["linux"]),
                Requirement(wk.LABEL_TOPOLOGY_ZONE, Operator.IN, ["kwok-zone-1"]),
                Requirement(
                    wk.CAPACITY_TYPE_LABEL_KEY,
                    Operator.IN,
                    [wk.CAPACITY_TYPE_ON_DEMAND, wk.CAPACITY_TYPE_RESERVED],
                ),
            ),
            offerings=Offerings(
                [
                    offering(wk.CAPACITY_TYPE_ON_DEMAND, 1.0),
                    offering(
                        wk.CAPACITY_TYPE_RESERVED,
                        0.1,
                        rid="cr-1",
                        capacity=reservation_capacity,
                    ),
                ]
            ),
            capacity=parse_resource_list(
                {"cpu": "4", "memory": "16Gi", "pods": "110"}
            ),
        )
    ]


class TestReservedCapacity:
    """scheduling/reservationmanager.go + nodeclaim.go reserved offerings."""

    def test_reserved_offering_preferred(self):
        env = env_for(reserved_catalog())
        results = env.schedule([unschedulable_pod(requests={"cpu": "1"})])
        [nc] = results.new_node_claims
        # the claim holds the reservation: capacity-type narrowed to reserved
        assert nc.reserved_offerings
        assert nc.reserved_offerings[0].reservation_id == "cr-1"

    def test_reservation_capacity_tracked_across_claims(self):
        # 2 reserved instances available; 3 claims' worth of pods → the third
        # claim falls back to on-demand (fallback mode default)
        env = env_for(reserved_catalog(reservation_capacity=2))
        pods = [unschedulable_pod(requests={"cpu": "3"}) for _ in range(3)]
        results = env.schedule(pods)
        assert len(results.new_node_claims) == 3
        reserved_claims = [
            nc for nc in results.new_node_claims if nc.reserved_offerings
        ]
        assert len(reserved_claims) == 2

    def test_exhausted_reservation_falls_back_to_on_demand(self):
        env = env_for(reserved_catalog(reservation_capacity=0))
        results = env.schedule([unschedulable_pod(requests={"cpu": "1"})])
        [nc] = results.new_node_claims
        assert not nc.reserved_offerings
        assert not results.pod_errors

    def test_reserved_disabled_by_feature_gate(self):
        env = env_for(reserved_catalog(), reserved_capacity_enabled=False)
        results = env.schedule([unschedulable_pod(requests={"cpu": "1"})])
        [nc] = results.new_node_claims
        assert not nc.reserved_offerings


class TestDeletingNodeRescheduling:
    """provisioner.go:294-311 — pods on deleting nodes re-enter the pending
    set so replacement capacity is provisioned before the drain completes."""

    def test_active_pods_rescheduled_through_provisioner(self):
        """The full path: a bound pod on a deleting node joins the batch and
        the provisioner computes replacement capacity for it."""
        from helpers import make_provisioner_harness

        clock, store, provider, cluster, informer, prov = make_provisioner_harness()
        store.create(nodepool("default"))
        node, claim = node_claim_pair("dying-1")
        node.metadata.deletion_timestamp = 1.0
        claim.metadata.deletion_timestamp = 1.0
        store.create(node)
        store.create(claim)
        pod = bind_pod(unschedulable_pod(requests={"cpu": "1"}), node)
        store.create(pod)
        informer.flush()
        prov.trigger(pod.metadata.uid)
        clock.step(1.5)
        results = prov.reconcile()
        assert results is not None
        # the bound pod was treated as pending: a replacement claim exists
        replacement = [
            c for c in store.list("NodeClaim") if c.metadata.name != claim.metadata.name
        ]
        assert len(replacement) == 1

    def test_inactive_pods_not_rescheduled(self):
        env = Env(state_nodes=[])
        node, claim = node_claim_pair("dying-2")
        node.metadata.deletion_timestamp = 1.0
        claim.metadata.deletion_timestamp = 1.0
        env.store.create(node)
        env.store.create(claim)
        pod = bind_pod(unschedulable_pod(requests={"cpu": "1"}), node)
        pod.status.phase = "Succeeded"
        env.store.create(pod)
        env.informer.flush()
        dying = deleting(env.cluster.state_nodes())
        resched = [
            p
            for n in dying
            for p in n.currently_reschedulable_pods(env.store, Limits.from_pdbs([]))
        ]
        assert resched == []

    def test_daemonset_pods_not_rescheduled(self):
        env = Env(state_nodes=[])
        node, claim = node_claim_pair("dying-3")
        node.metadata.deletion_timestamp = 1.0
        claim.metadata.deletion_timestamp = 1.0
        env.store.create(node)
        env.store.create(claim)
        ds = daemonset(requests={"cpu": "1"})
        ds_pod = daemonset_pod(ds, node_name=node.metadata.name)
        ds_pod.status.conditions.append(Condition(type="PodScheduled", status="True"))
        env.store.create(ds_pod)
        env.informer.flush()
        dying = deleting(env.cluster.state_nodes())
        resched = [
            p
            for n in dying
            for p in n.currently_reschedulable_pods(env.store, Limits.from_pdbs([]))
        ]
        assert resched == []


class TestStrictReservedMode:
    """Strict mode runs on the device path since round 4 (the all-volatile
    topo driver evaluates the reservation gate at the host's can_add
    position); both legs must agree, including the scan-aborting errors."""

    def _strict_env(self, capacity):
        return env_for(
            reserved_catalog(reservation_capacity=capacity),
            reserved_offering_mode=RESERVED_OFFERING_MODE_STRICT,
        )

    def test_strict_mode_errors_instead_of_falling_back(self):
        """suite_test.go:3976 — with compatible reserved offerings that can't
        be reserved, strict mode surfaces ReservedOfferingError instead of
        silently falling back to on-demand."""
        env = self._strict_env(0)
        results = env.schedule([unschedulable_pod(requests={"cpu": "1"})])
        assert not results.new_node_claims
        [err] = list(results.pod_errors.values())
        assert isinstance(err, ReservedOfferingError)

    def test_strict_mode_reserves_when_capacity_available(self):
        env = self._strict_env(1)
        results = env.schedule([unschedulable_pod(requests={"cpu": "1"})])
        assert not results.pod_errors
        [nc] = results.new_node_claims
        assert nc.reserved_offerings

    def test_strict_mode_capacity_exhausts_across_claims(self):
        """Two claims' worth of pods against one reserved slot: the first
        claim reserves, the second pod's scan aborts with the host's error."""
        env = self._strict_env(1)
        pods = [
            unschedulable_pod(name=f"p-{i}", requests={"cpu": "3"})
            for i in range(2)
        ]
        results = env.schedule(pods)
        assert len(results.new_node_claims) == 1
        [err] = list(results.pod_errors.values())
        assert isinstance(err, ReservedOfferingError)
        assert "could not be reserved" in str(err)
