"""Device-resident consolidation frontier search: parity fuzz against the
sequential binary-search oracle, the prefix reductions, solverd's batched
solve_many, and the frontier's telemetry/timeout/budget contracts.

The load-bearing invariant: the frontier search must select the SAME
command as the reference's sequential binary search on every input — it
evaluates the sequential search's own decision tree speculatively, so any
divergence is a bug, not a tolerance.
"""

from __future__ import annotations

import math
from random import Random

import pytest

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.nodepool import Budget
from karpenter_tpu.controllers.disruption import methods as dmethods
from karpenter_tpu.controllers.disruption.consolidation import (
    Consolidation,
    get_candidate_prices,
)
from karpenter_tpu.controllers.disruption.helpers import (
    FrontierSimulator,
    build_disruption_budget_mapping,
    get_candidates,
)
from karpenter_tpu.controllers.disruption.types import (
    Command,
    DECISION_NOOP,
)
from karpenter_tpu.operator.options import Options
from karpenter_tpu.ops import ffd
from karpenter_tpu.ops import frontier as ftr

from helpers import nodepool, unschedulable_pod
from test_disruption import Env

SIZES = ["s-4x-amd64-linux", "c-4x-amd64-linux", "m-4x-amd64-linux"]


@pytest.fixture(autouse=True)
def _device_path(monkeypatch):
    """Fuzz fixtures are far below DEVICE_MIN_PODS (64); pin it to 1 so
    every probe simulation actually exercises the device path (the
    device_path.py discipline), and STRICT so a silent host fallback
    raises instead of hiding — the '0 fallbacks' half of the acceptance
    criterion."""
    monkeypatch.setattr(ffd, "DEVICE_MIN_PODS", 1)
    monkeypatch.setattr(ffd, "STRICT", True)


def build_env(
    seed: int,
    n_nodes: int = 8,
    pools: tuple = ("default",),
    spot_frac: float = 0.0,
    spot_gate: bool = False,
    budgets: dict | None = None,
) -> Env:
    """A deterministic consolidation fixture: `seed` fully determines the
    cluster, so two builds of the same seed are byte-identical — the parity
    fuzz runs the sequential oracle and the frontier search on SEPARATE
    twin environments and compares their outputs, events included."""
    rng = Random(seed)
    options = Options()
    if spot_gate:
        options.feature_gates.spot_to_spot_consolidation = True
    env = Env(options=options)
    for pool in pools:
        np_ = nodepool(pool)
        np_.spec.disruption.budgets = [
            Budget(nodes=(budgets or {}).get(pool, "100%"))
        ]
        env.store.create(np_)
    for i in range(n_nodes):
        cpu = rng.choice([4, 4, 8])
        itype = rng.choice(SIZES).replace("4x", f"{cpu}x")
        pods = []
        for j in range(rng.randrange(0, 3)):
            pods.append(
                unschedulable_pod(
                    name=f"n{i}-p{j}",
                    requests={"cpu": f"{rng.choice([100, 200, 500])}m"},
                )
            )
        env.add_pair(
            f"node-{i:03d}",
            pods=pods,
            pool=rng.choice(pools),
            instance_type=itype,
            capacity={"cpu": str(cpu), "memory": f"{cpu * 4}Gi", "pods": "110"},
            capacity_type=(
                wk.CAPACITY_TYPE_SPOT
                if rng.random() < spot_frac
                else wk.CAPACITY_TYPE_ON_DEMAND
            ),
        )
    env.informer.flush()
    env.clock.step(120)
    return env


def multi_method(env) -> dmethods.MultiNodeConsolidation:
    c = Consolidation(
        env.clock, env.cluster, env.store, env.provisioner, env.provider,
        env.recorder, env.queue,
    )
    return dmethods.MultiNodeConsolidation(c)


def single_method(env) -> dmethods.SingleNodeConsolidation:
    c = Consolidation(
        env.clock, env.cluster, env.store, env.provisioner, env.provider,
        env.recorder, env.queue,
    )
    return dmethods.SingleNodeConsolidation(c)


def candidates_for(env, method):
    return get_candidates(
        env.store, env.cluster, env.recorder, env.clock, env.provider,
        method.should_disrupt, method.disruption_class(), env.queue,
    )


def budgets_for(env, method):
    return build_disruption_budget_mapping(
        env.store, env.cluster, env.clock, env.recorder, method.reason()
    )


def command_signature(cmd: Command) -> tuple:
    """Everything decision-relevant about a Command, comparably."""
    replacements = []
    for rep in cmd.replacements:
        nc = rep.node_claim
        ct = nc.requirements.get(wk.CAPACITY_TYPE_LABEL_KEY)
        replacements.append(
            (
                tuple(it.name for it in nc.instance_type_options),
                tuple(sorted(ct.values)) if ct.values is not None else None,
            )
        )
    return (
        cmd.decision(),
        tuple(sorted(c.name() for c in cmd.candidates)),
        tuple(replacements),
    )


def event_stream(env) -> list[tuple]:
    return [
        (
            e.type,
            e.reason,
            e.message,
            getattr(getattr(e.involved_object, "metadata", None), "name", ""),
        )
        for e in env.recorder.events
    ]


def sequential_single_oracle(method, budgets, candidates) -> Command:
    """The reference's singlenodeconsolidation.go walk, verbatim (pre-
    frontier): cheapest first, one simulation per candidate, first
    non-noop wins."""
    c = method.c
    budgets = dict(budgets)
    if c.is_consolidated():
        return Command()
    cands = method.sort_candidates(list(candidates))
    constrained = False
    unseen = {x.node_pool.metadata.name for x in cands}
    for cand in cands:
        unseen.discard(cand.node_pool.metadata.name)
        if budgets.get(cand.node_pool.metadata.name, 0) == 0:
            constrained = True
            continue
        if not cand.reschedulable_pods:
            continue
        cmd = c.compute_consolidation(cand)
        if cmd.decision() == DECISION_NOOP:
            continue
        return cmd
    if not constrained:
        c.mark_consolidated()
    method.previously_unseen_nodepools = unseen
    return Command()


def run_multi_pair(seed: int, depth: int = 2, **env_kw) -> None:
    """Twin environments, same seed: sequential oracle on one, frontier on
    the other. Commands AND event streams must match byte for byte."""
    env_a, env_b = build_env(seed, **env_kw), build_env(seed, **env_kw)
    fallbacks0 = ffd.DEVICE_FALLBACKS

    m_seq = multi_method(env_a)
    cands_a = candidates_for(env_a, m_seq)
    m_seq._first_n_consolidation_option = m_seq._first_n_sequential
    cmd_a = m_seq.compute_command(budgets_for(env_a, m_seq), *cands_a)

    m_frontier = multi_method(env_b)
    env_b.provisioner.options.consolidation_frontier_depth = depth
    cands_b = candidates_for(env_b, m_frontier)
    cmd_b = m_frontier.compute_command(budgets_for(env_b, m_frontier), *cands_b)

    assert command_signature(cmd_a) == command_signature(cmd_b), (
        f"seed {seed}: frontier diverged from the sequential oracle"
    )
    assert event_stream(env_a) == event_stream(env_b), (
        f"seed {seed}: event streams diverged"
    )
    assert ffd.DEVICE_FALLBACKS == fallbacks0, "a probe fell back to the host loop"


class TestSpeculativeProbes:
    def test_level_set_is_distinct_and_covers_binary_path(self):
        rng = Random(17)
        for _ in range(200):
            lo = rng.randrange(1, 60)
            hi = lo + rng.randrange(0, 60)
            depth = rng.randrange(1, 5)
            probes = ftr.speculative_probes(lo, hi, depth)
            assert len(probes) == len(set(probes))
            assert all(lo <= m <= hi for m in probes)
            # every (lo, hi) walk of `depth` verdicts only visits probed mids
            for verdicts in range(2 ** depth):
                l, h = lo, hi
                for bit in range(depth):
                    if l > h:
                        break
                    mid = (l + h) // 2
                    assert mid in probes, (lo, hi, depth, mid)
                    if (verdicts >> bit) & 1:
                        l = mid + 1
                    else:
                        h = mid - 1

    def test_depth_one_is_single_probe(self):
        assert ftr.speculative_probes(1, 99, 1) == [(1 + 99) // 2]

    def test_empty_interval(self):
        assert ftr.speculative_probes(5, 4, 3) == []


class TestPrefixReductions:
    def test_prefix_prices_match_oracle(self):
        env = build_env(21, n_nodes=10, spot_frac=0.4)
        method = multi_method(env)
        cands = method.c.sort_candidates(candidates_for(env, method))
        prices = ftr.PrefixPrices(cands)
        for m in range(1, len(cands) + 1):
            assert prices.for_prefix(m) == get_candidate_prices(cands[:m]), m

    def test_prefix_type_floors_match_filter_oracle(self):
        env = build_env(22, n_nodes=12)
        method = multi_method(env)
        cands = method.c.sort_candidates(candidates_for(env, method))
        floors = ftr.PrefixTypeFloors(cands)
        for m in range(1, len(cands) + 1):
            # oracle: _filter_out_same_type's own existing_types/price scan
            existing, by_type = set(), {}
            for c in cands[:m]:
                existing.add(c.instance_type.name)
                from karpenter_tpu.cloudprovider.types import Offerings
                from karpenter_tpu.scheduling.requirements import Requirements

                compatible = Offerings(c.instance_type.offerings).compatible(
                    Requirements.from_labels(c.state_node.labels())
                )
                if compatible:
                    p = compatible.cheapest().price
                    by_type[c.instance_type.name] = min(
                        p, by_type.get(c.instance_type.name, math.inf)
                    )
            names = sorted({c.instance_type.name for c in cands}) + ["absent"]
            expect = math.inf
            for name in names:
                if name in existing:
                    expect = min(expect, by_type.get(name, math.inf))
            assert floors.max_price(m, names) == expect, m


class TestFrontierParity:
    """The acceptance invariant: identical Commands, zero divergences."""

    @pytest.mark.parametrize("seed", range(12))
    def test_fuzz_basic(self, seed):
        run_multi_pair(seed, n_nodes=6 + seed % 5)

    @pytest.mark.parametrize("seed", range(12, 18))
    def test_fuzz_spot_mix(self, seed):
        run_multi_pair(seed, n_nodes=7, spot_frac=0.5, spot_gate=True)

    @pytest.mark.parametrize("seed", range(18, 24))
    def test_fuzz_multi_pool_constrained(self, seed):
        run_multi_pair(
            seed,
            n_nodes=9,
            pools=("default", "burst"),
            budgets={"burst": "0"},
        )

    @pytest.mark.parametrize("depth", [1, 2, 3, 4])
    def test_depth_invariance(self, depth):
        """Any speculation depth must pick the same command — the walk is
        the same decision tree regardless of how many levels batch."""
        run_multi_pair(31, n_nodes=9, depth=depth)

    def test_replace_case(self):
        """Many small half-full nodes fold into one bigger cheaper node —
        the REPLACE path through the price gate, both searches agreeing."""
        def build():
            env = Env()
            np_ = nodepool("default")
            np_.spec.disruption.budgets = [Budget(nodes="100%")]
            env.store.create(np_)
            for i in range(3):
                pods = [
                    unschedulable_pod(name=f"r{i}-p{j}", requests={"cpu": "1"})
                    for j in range(2)
                ]
                env.add_pair(
                    f"rep-{i}",
                    pods=pods,
                    instance_type="c-4x-amd64-linux",
                    capacity={"cpu": "4", "memory": "8Gi", "pods": "110"},
                )
            env.informer.flush()
            env.clock.step(120)
            return env

        env_a, env_b = build(), build()
        m_seq = multi_method(env_a)
        m_seq._first_n_consolidation_option = m_seq._first_n_sequential
        cmd_a = m_seq.compute_command(
            budgets_for(env_a, m_seq), *candidates_for(env_a, m_seq)
        )
        m_f = multi_method(env_b)
        cmd_b = m_f.compute_command(
            budgets_for(env_b, m_f), *candidates_for(env_b, m_f)
        )
        assert command_signature(cmd_a) == command_signature(cmd_b)
        assert cmd_a.decision() != DECISION_NOOP

    def test_single_candidate_edge(self):
        env = build_env(40, n_nodes=1)
        method = multi_method(env)
        cmd = method.compute_command(
            budgets_for(env, method), *candidates_for(env, method)
        )
        assert cmd.decision() == DECISION_NOOP  # needs >= 2 candidates

    def test_no_candidates_edge(self):
        env = build_env(41, n_nodes=0)
        method = multi_method(env)
        cmd = method.compute_command(budgets_for(env, method))
        assert cmd.decision() == DECISION_NOOP

    @pytest.mark.parametrize("seed", range(50, 58))
    def test_single_node_parity(self, seed):
        """The batched single-node walk vs the sequential reference loop:
        same command, same deferred-then-published event stream."""
        env_a, env_b = build_env(seed, n_nodes=7), build_env(seed, n_nodes=7)
        s_a = single_method(env_a)
        cmd_a = sequential_single_oracle(
            s_a, budgets_for(env_a, s_a), candidates_for(env_a, s_a)
        )
        s_b = single_method(env_b)
        cmd_b = s_b.compute_command(
            budgets_for(env_b, s_b), *candidates_for(env_b, s_b)
        )
        assert command_signature(cmd_a) == command_signature(cmd_b), seed
        assert event_stream(env_a) == event_stream(env_b), seed


class TestBudgetsDefensiveCopy:
    """A shed/timeout retry re-enters compute_command with the SAME budget
    mapping; the pass must not see pre-decremented budgets."""

    def test_multi_node_leaves_caller_budgets_untouched(self):
        env = build_env(60, n_nodes=5)
        method = multi_method(env)
        budgets = budgets_for(env, method)
        snapshot = dict(budgets)
        method.compute_command(budgets, *candidates_for(env, method))
        assert budgets == snapshot

    def test_emptiness_leaves_caller_budgets_untouched(self):
        env = Env()
        env.store.create(nodepool("default"))
        for i in range(3):
            env.add_pair(f"empty-{i}")
        env.informer.flush()
        env.clock.step(120)
        c = Consolidation(
            env.clock, env.cluster, env.store, env.provisioner, env.provider,
            env.recorder, env.queue,
        )
        method = dmethods.Emptiness(c)
        budgets = budgets_for(env, method)
        snapshot = dict(budgets)
        cmd = method.compute_command(budgets, *candidates_for(env, method))
        assert cmd.candidates, "expected empties to consolidate"
        assert budgets == snapshot

    def test_single_node_leaves_caller_budgets_untouched(self):
        env = build_env(61, n_nodes=4)
        method = single_method(env)
        budgets = budgets_for(env, method)
        snapshot = dict(budgets)
        method.compute_command(budgets, *candidates_for(env, method))
        assert budgets == snapshot


class TestFrontierTimeout:
    def test_mid_search_timeout_returns_last_saved(self, monkeypatch):
        """Satellite contract: the 60s deadline checked BETWEEN rounds — a
        mid-search timeout returns the best command validated so far and
        increments the timeout counter."""
        env = build_env(70, n_nodes=6)
        env.provisioner.options.consolidation_frontier_depth = 1
        method = multi_method(env)
        before = dmethods._CONSOLIDATION_TIMEOUTS.value(
            {"consolidation_type": "multi"}
        )
        orig = FrontierSimulator.solve_batch

        def slow_batch(sim, plans):
            env.clock.step(dmethods.MULTI_NODE_CONSOLIDATION_TIMEOUT + 1.0)
            return orig(sim, plans)

        monkeypatch.setattr(FrontierSimulator, "solve_batch", slow_batch)
        cands = candidates_for(env, method)
        cmd = method.compute_command(budgets_for(env, method), *cands)
        assert (
            dmethods._CONSOLIDATION_TIMEOUTS.value({"consolidation_type": "multi"})
            == before + 1
        )
        # depth 1, round 1 probed the sequential search's first mid and its
        # verdict was applied before the round-2 deadline check fired: the
        # returned command is that probe's (last validated), not a fresh
        # recompute — compare against the oracle's first probe
        twin = build_env(70, n_nodes=6)
        m2 = multi_method(twin)
        cands2 = m2.c.sort_candidates(candidates_for(twin, m2))
        disruptable = [c for c in cands2 if c.reschedulable_pods]
        lo, hi = 1, min(len(disruptable), dmethods.MAX_PARALLEL_CONSOLIDATION) - 1
        mid = (lo + hi) // 2
        first_probe = m2.c.compute_consolidation(*disruptable[: mid + 1])
        if first_probe.decision() != DECISION_NOOP:
            assert command_signature(cmd) == command_signature(first_probe)
        else:
            assert cmd.decision() == DECISION_NOOP


class TestFrontierTelemetry:
    def test_probe_and_round_metrics_and_span(self):
        from karpenter_tpu import tracing

        env = build_env(80, n_nodes=8)
        method = multi_method(env)
        labels = {"consolidation_type": "multi"}
        probes0 = dmethods._FRONTIER_PROBES.value(labels)
        rounds0 = dmethods._FRONTIER_ROUNDS.count(labels)
        cands = candidates_for(env, method)
        method.compute_command(budgets_for(env, method), *cands)
        assert dmethods._FRONTIER_PROBES.value(labels) > probes0
        assert dmethods._FRONTIER_ROUNDS.count(labels) == rounds0 + 1
        names = [s["name"] for s in tracing.tracer().ring.spans()]
        assert "consolidation.frontier" in names

    def test_coalescer_counts_frontier_groups(self):
        from karpenter_tpu.solverd import coalescer as dcoal

        env = build_env(81, n_nodes=8)
        method = multi_method(env)
        groups0 = dcoal._FRONTIER_GROUPS.value()
        method.compute_command(
            budgets_for(env, method), *candidates_for(env, method)
        )
        assert dcoal._FRONTIER_GROUPS.value() > groups0


class TestCollectPrefixRowsets:
    def test_collects_from_largest_member(self, monkeypatch):
        seen = []

        def fake_collect(scheduler, pods):
            seen.append((scheduler, len(pods)))
            return [("rows", "reqs")]

        monkeypatch.setattr(ffd, "collect_joint_rowsets", fake_collect)
        group = [("sched-a", [1]), ("sched-b", [1, 2, 3]), ("sched-c", [1, 2])]
        pairs = ffd.collect_prefix_rowsets(group)
        assert pairs == [("rows", "reqs")]
        assert seen == [("sched-b", 3)]

    def test_empty_group(self):
        assert ffd.collect_prefix_rowsets([]) == []


class TestCoalescerGroupPriming:
    """Nested groups prime from their largest member; disjoint groups
    (single-node rounds) must still collect EVERY member — the siblings'
    row-sets are not subsets of anyone's."""

    class _Entry:
        def __init__(self, request):
            self.request = request
            self.result = None
            self.error = None

    def _entries(self, engine, nested):
        from karpenter_tpu.solverd.api import SolveRequest

        out = []
        for i, pods in enumerate(([1], [1, 2], [1, 2, 3])):
            sched = type("S", (), {"engine": engine})()
            out.append(
                self._Entry(
                    SolveRequest(
                        kind="simulate", scheduler=sched, pods=pods,
                        group="g", group_nested=nested,
                    )
                )
            )
        return out

    def _prime_with(self, monkeypatch, nested):
        from karpenter_tpu.solverd.coalescer import Coalescer

        collected = []
        monkeypatch.setattr(
            ffd, "collect_joint_rowsets",
            lambda s, p: collected.append(("member", len(p))) or [],
        )
        monkeypatch.setattr(
            ffd, "collect_prefix_rowsets",
            lambda sp: collected.append(("largest", max(len(p) for _, p in sp))) or [],
        )
        engine = object()
        Coalescer()._prime(self._entries(engine, nested))
        return collected

    def test_nested_group_collects_largest_only(self, monkeypatch):
        assert self._prime_with(monkeypatch, nested=True) == [("largest", 3)]

    def test_disjoint_group_collects_every_member(self, monkeypatch):
        assert self._prime_with(monkeypatch, nested=False) == [
            ("member", 1), ("member", 2), ("member", 3),
        ]
