"""Node auto-repair suite (reference node/health/suite_test.go, 14 specs):
policy-matched unhealthy nodes force-delete their NodeClaims after the
toleration window, with a per-NodePool 20%-rounded-up circuit breaker,
forced (now-stamped) termination deadlines, and no regard for disruption
budgets or do-not-disrupt."""

import pytest

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import Condition, ObjectMeta
from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
from karpenter_tpu.cloudprovider.types import RepairPolicy
from karpenter_tpu.controllers.node.health import (
    _DISRUPTED_TOTAL,
    _REPAIRED_TOTAL,
    HealthController,
)
from karpenter_tpu.events.recorder import Recorder
from karpenter_tpu.runtime.store import Store
from karpenter_tpu.utils.clock import FakeClock

from helpers import node_claim_pair, nodepool

POLICY = RepairPolicy(
    condition_type="BadNode", condition_status="True", toleration_duration=600.0
)


@pytest.fixture()
def env():
    clock = FakeClock()
    store = Store(clock=clock)
    provider = FakeCloudProvider()
    provider._repair_policies = [POLICY]
    recorder = Recorder(clock=clock)
    ctrl = HealthController(store, provider, recorder, clock, enabled=True)
    store.create(nodepool("workers"))
    return clock, store, provider, recorder, ctrl


def add_node(store, clock, name, unhealthy=False, since=None, pool="workers",
             condition_type="BadNode", condition_status="True"):
    node, claim = node_claim_pair(name, pool=pool)
    if unhealthy:
        node.status.conditions.append(
            Condition(
                type=condition_type,
                status=condition_status,
                last_transition_time=clock.now() if since is None else since,
            )
        )
    store.create(claim)
    store.create(node)
    return node, claim


class TestNodeRepair:
    def test_deletes_unhealthy_node_claim(self, env):
        """'should delete nodes that are unhealthy by the cloud provider' —
        the CLAIM is deleted (its finalizer pipeline handles the node), the
        termination deadline is stamped to NOW, and both disruption
        counters fire."""
        clock, store, provider, recorder, ctrl = env
        node, claim = add_node(store, clock, "sick-1", unhealthy=True)
        labels = {"nodepool": "workers", "capacity_type": claim.metadata.labels.get(
            wk.CAPACITY_TYPE_LABEL_KEY, "")}
        repaired0 = _REPAIRED_TOTAL.value({"condition": "BadNode", **labels})
        disrupted0 = _DISRUPTED_TOTAL.value({"reason": "unhealthy", **labels})
        clock.step(601.0)
        ctrl.reconcile(node)
        live = store.try_get("NodeClaim", "sick-1-claim")
        assert live is None or live.metadata.deletion_timestamp is not None
        assert _REPAIRED_TOTAL.value({"condition": "BadNode", **labels}) == repaired0 + 1
        assert _DISRUPTED_TOTAL.value({"reason": "unhealthy", **labels}) == disrupted0 + 1
        assert recorder.calls("NodeUnhealthy") == 1

    def test_condition_type_mismatch_ignored(self, env):
        clock, store, provider, recorder, ctrl = env
        node, _ = add_node(
            store, clock, "odd-1", unhealthy=True, condition_type="OtherProblem"
        )
        clock.step(601.0)
        ctrl.reconcile(node)
        assert store.get("NodeClaim", "odd-1-claim").metadata.deletion_timestamp is None

    def test_condition_status_mismatch_ignored(self, env):
        clock, store, provider, recorder, ctrl = env
        node, _ = add_node(
            store, clock, "odd-2", unhealthy=True, condition_status="Unknown"
        )
        clock.step(601.0)
        ctrl.reconcile(node)
        assert store.get("NodeClaim", "odd-2-claim").metadata.deletion_timestamp is None

    def test_waits_out_toleration_duration(self, env):
        clock, store, provider, recorder, ctrl = env
        node, _ = add_node(store, clock, "sick-2", unhealthy=True)
        clock.step(599.0)
        ctrl.reconcile(node)
        assert store.get("NodeClaim", "sick-2-claim").metadata.deletion_timestamp is None
        clock.step(2.0)
        ctrl.reconcile(node)
        live = store.try_get("NodeClaim", "sick-2-claim")
        assert live is None or live.metadata.deletion_timestamp is not None

    def test_termination_deadline_stamped_to_now_ignoring_nodepool_tgp(self, env):
        """'should set annotation termination grace period when force
        termination is started' + 'should not respect TGP set on the
        nodepool' — repair is forced."""
        clock, store, provider, recorder, ctrl = env
        node, claim = add_node(store, clock, "sick-3", unhealthy=True)
        claim.spec.termination_grace_period = 86400.0  # repair must ignore it
        claim.metadata.finalizers.append(wk.TERMINATION_FINALIZER)
        store.apply(claim)
        clock.step(601.0)
        ctrl.reconcile(node)
        live = store.get("NodeClaim", "sick-3-claim")
        assert live.metadata.annotations[
            wk.NODECLAIM_TERMINATION_TIMESTAMP_ANNOTATION_KEY
        ] == str(clock.now())

    def test_earlier_termination_deadline_preserved(self, env):
        """'should not update termination grace period if set before the
        current time'."""
        clock, store, provider, recorder, ctrl = env
        node, claim = add_node(store, clock, "sick-4", unhealthy=True)
        claim.metadata.finalizers.append(wk.TERMINATION_FINALIZER)
        claim.metadata.annotations[
            wk.NODECLAIM_TERMINATION_TIMESTAMP_ANNOTATION_KEY
        ] = "5.0"
        store.apply(claim)
        clock.step(601.0)
        ctrl.reconcile(node)
        live = store.get("NodeClaim", "sick-4-claim")
        assert live.metadata.annotations[
            wk.NODECLAIM_TERMINATION_TIMESTAMP_ANNOTATION_KEY
        ] == "5.0"

    def test_circuit_breaker_per_nodepool(self, env):
        """'should ignore unhealthy nodes if more than 20% ... are
        unhealthy' — scoped to the node's own NodePool."""
        clock, store, provider, recorder, ctrl = env
        sick = []
        for i in range(5):
            n, _ = add_node(
                store, clock, f"cb-{i}", unhealthy=(i < 2)
            )
            if i < 2:
                sick.append(n)
        # 2 of 5 unhealthy > ceil(20% * 5) = 1 -> blocked
        clock.step(601.0)
        ctrl.reconcile(sick[0])
        assert store.get("NodeClaim", "cb-0-claim").metadata.deletion_timestamp is None
        assert recorder.calls("NodeRepairBlocked") == 1
        # a DIFFERENT healthy pool is not affected by workers' sickness
        store.create(nodepool("other"))
        other_sick, _ = add_node(
            store, clock, "ob-1", unhealthy=True, since=clock.now(), pool="other"
        )
        clock.step(601.0)
        ctrl.reconcile(other_sick)
        live = store.try_get("NodeClaim", "ob-1-claim")
        assert live is None or live.metadata.deletion_timestamp is not None

    def test_round_up_allows_one_unhealthy_in_small_pools(self, env):
        """'should consider round up when there is a low number of nodes' —
        4 nodes: threshold ceil(0.8) = 1, so ONE unhealthy node repairs."""
        clock, store, provider, recorder, ctrl = env
        sick_node = None
        for i in range(4):
            n, _ = add_node(store, clock, f"ru-{i}", unhealthy=(i == 0))
            if i == 0:
                sick_node = n
        clock.step(601.0)
        ctrl.reconcile(sick_node)
        live = store.try_get("NodeClaim", "ru-0-claim")
        assert live is None or live.metadata.deletion_timestamp is not None

    def test_ignores_budgets_and_do_not_disrupt(self, env):
        """'should ignore node disruption budgets' + 'should ignore
        do-not-disrupt on a node' — auto-repair is not voluntary
        disruption."""
        from karpenter_tpu.apis.nodepool import Budget

        clock, store, provider, recorder, ctrl = env
        pool = store.get("NodePool", "workers")
        pool.spec.disruption.budgets = [Budget(nodes="0")]
        store.apply(pool)
        node, _ = add_node(store, clock, "dnd-1", unhealthy=True)
        node.metadata.annotations[wk.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
        store.apply(node)
        clock.step(601.0)
        ctrl.reconcile(node)
        live = store.try_get("NodeClaim", "dnd-1-claim")
        assert live is None or live.metadata.deletion_timestamp is not None

    def test_disabled_without_feature_gate(self, env):
        clock, store, provider, recorder, ctrl = env
        ctrl.enabled = False
        node, _ = add_node(store, clock, "off-1", unhealthy=True)
        clock.step(601.0)
        ctrl.reconcile(node)
        assert store.get("NodeClaim", "off-1-claim").metadata.deletion_timestamp is None
