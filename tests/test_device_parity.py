"""Device-FFD decision-parity suite.

The device fast path (ops/ffd.py) must produce decisions IDENTICAL to the
host per-pod loop — claim count, per-claim pod sets, per-claim instance-type
option sets, per-claim requirements, existing-node assignments, and pod
errors (BASELINE.md decision-parity requirement; the semantics oracle is the
reference's scheduler.go:346-401 + nodeclaim.go:373-441).

Workloads are randomized but fully deterministic (seeded; pinned pod UIDs
and creation timestamps — the host queue tie-breaks on them, so identity
across runs requires identical metadata). Run the long fuzz directly:

    python tests/test_device_parity.py 1000
"""

import itertools
import os
import random
import sys

if __name__ == "__main__":  # direct fuzz runs (CI smoke job, soak scripts)
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import (
    Affinity,
    LabelSelector,
    NodeAffinity,
    NodeSelectorTerm,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    PreferredSchedulingTerm,
    Taint,
    Toleration,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
)
from karpenter_tpu.cloudprovider.kwok.instance_types import construct_instance_types
from karpenter_tpu.ops import ffd
from karpenter_tpu.ops.catalog import CatalogEngine
from karpenter_tpu.scheduler import nodeclaim as ncmod

from helpers import (
    bind_pod,
    daemonset,
    daemonset_pod,
    nodepool,
    registered_node,
    unschedulable_pod,
)
from test_scheduler import Env

CATALOG = construct_instance_types()
_CATALOG_RES = None


def reserved_catalog():
    """The kwok catalog with deterministic reserved offerings grafted onto
    every 9th type (two zones, ~quarter price, small per-reservation
    capacities) — exercises the fallback-mode reservation bookkeeping:
    capacity counting across claims, release on narrowing, finalize pinning."""
    global _CATALOG_RES
    if _CATALOG_RES is not None:
        return _CATALOG_RES
    from karpenter_tpu.cloudprovider.types import (
        RESERVATION_ID_LABEL,
        InstanceType,
        Offering,
        Offerings,
    )
    from karpenter_tpu.scheduling.requirements import (
        Operator,
        Requirement,
        Requirements,
    )

    out = []
    for i, it in enumerate(CATALOG):
        if i % 9 != 0:
            out.append(it)
            continue
        od = min(o.price for o in it.offerings)
        res_offs = [
            Offering(
                requirements=Requirements(
                    Requirement(
                        wk.CAPACITY_TYPE_LABEL_KEY,
                        Operator.IN,
                        [wk.CAPACITY_TYPE_RESERVED],
                    ),
                    Requirement(wk.LABEL_TOPOLOGY_ZONE, Operator.IN, [zone]),
                    Requirement(
                        RESERVATION_ID_LABEL, Operator.IN, [f"cr-{i}-{zone}"]
                    ),
                ),
                price=od * 0.25,
                available=True,
                reservation_capacity=1 + (i // 9) % 3,
            )
            for zone in ("kwok-zone-1", "kwok-zone-2")
        ]
        out.append(
            InstanceType(
                name=it.name,
                requirements=it.requirements,
                offerings=Offerings(list(it.offerings) + res_offs),
                capacity=it.capacity,
                overhead=it.overhead,
            )
        )
    _CATALOG_RES = out
    return out


ZONES = ["kwok-zone-1", "kwok-zone-2", "kwok-zone-3", "kwok-zone-4"]
ARCHS = ["amd64", "arm64"]
OSES = ["linux", "windows"]
CPUS = ["250m", "500m", "1", "2", "3", "4", "7", "16"]
MEMS = ["256Mi", "512Mi", "1Gi", "2Gi", "7Gi"]


APPS = ["app-0", "app-1", "app-2"]
TIERS = ["gold", "silver", "bronze"]


def _random_nodepools(
    rng: random.Random, topo: bool = False, best_effort: bool = False,
    fused: bool = False,
):
    pools = []
    for i in range(rng.randint(1, 3)):
        requirements = []
        if rng.random() < 0.4:
            requirements.append(
                {"key": wk.LABEL_ARCH, "operator": "In", "values": [rng.choice(ARCHS)]}
            )
        if topo and rng.random() < 0.3:
            # custom-key domain universe for "tier"-keyed spread
            # (topology.go buildDomainGroups from nodepool requirements)
            requirements.append(
                {
                    "key": "tier",
                    "operator": "In",
                    "values": rng.sample(TIERS, rng.randint(1, 3)),
                }
            )
        if rng.random() < 0.3:
            requirements.append(
                {
                    "key": wk.LABEL_TOPOLOGY_ZONE,
                    "operator": rng.choice(["In", "NotIn"]),
                    "values": rng.sample(ZONES, rng.randint(1, 2)),
                }
            )
        if rng.random() < (0.0 if fused else 0.85 if best_effort else 0.25):
            # strict-policy minValues (device-supported since round 4):
            # diversity gates reject joins as claims narrow. BestEffort mode
            # amps both frequency and magnitude so many opens actually
            # relax (counts above the catalog's diversity force write-downs)
            requirements.append(
                {
                    "key": rng.choice(
                        [wk.LABEL_INSTANCE_TYPE, "karpenter.kwok.sh/instance-family"]
                    ),
                    "operator": "Exists",
                    "minValues": rng.choice(
                        [2, 3, 5, 12, 20, 150, 500]
                        if best_effort
                        else [2, 3, 5, 12]
                    ),
                }
            )
        taints = []
        if rng.random() < 0.25:
            taints.append(Taint(key="team", value="infra", effect="NoSchedule"))
        if rng.random() < 0.12 and not fused:
            # engages the relax ladder's wildcard-toleration rung for the
            # whole solve (routes to the topo driver; the fused generator
            # skips it — the one-dispatch scan declines topo-routed solves)
            taints.append(Taint(key="soft", value="lane", effect="PreferNoSchedule"))
        limits = None
        if rng.random() < 0.3:
            limits = {"cpu": str(rng.choice([16, 64, 256]))}
        pools.append(
            nodepool(
                f"pool-{i}",
                requirements=requirements,
                taints=taints,
                limits=limits,
                weight=rng.randint(0, 10),
            )
        )
    return pools


def _random_selector(rng: random.Random):
    roll = rng.random()
    if roll < 0.15:
        return None  # nil selector: matches nothing, but lists every pod in
        # _count_domains (topology.go:466-471 TopologyListOptions mirror)
    if roll < 0.75:
        return LabelSelector(match_labels={"app": rng.choice(APPS)})
    return LabelSelector(
        match_expressions=[
            {
                "key": "app",
                "operator": "In",
                "values": rng.sample(APPS, rng.randint(1, 2)),
            }
        ]
    )


def _random_spread(rng: random.Random):
    roll = rng.random()
    if roll < 0.55:
        key = wk.LABEL_TOPOLOGY_ZONE
    elif roll < 0.7:
        key = wk.LABEL_HOSTNAME
    elif roll < 0.8:
        key = wk.CAPACITY_TYPE_LABEL_KEY
    elif roll < 0.9:
        key = wk.LABEL_ARCH
    else:
        key = "tier"
    tsc = TopologySpreadConstraint(
        max_skew=rng.choice([1, 1, 1, 2, 3]),
        topology_key=key,
        when_unsatisfiable=rng.choice(
            ["DoNotSchedule", "DoNotSchedule", "ScheduleAnyway"]
        ),
        label_selector=_random_selector(rng),
    )
    if rng.random() < 0.2:
        tsc.min_domains = rng.randint(1, 4)
    if rng.random() < 0.25:
        tsc.node_affinity_policy = rng.choice(["Honor", "Ignore"])
    if rng.random() < 0.2:
        tsc.node_taints_policy = rng.choice(["Honor", "Ignore"])
    if rng.random() < 0.15:
        tsc.match_label_keys = ["app"]
    return tsc


def _random_aff_term(rng: random.Random, own_app: str):
    key = rng.choice(
        [wk.LABEL_TOPOLOGY_ZONE, wk.LABEL_TOPOLOGY_ZONE, wk.LABEL_HOSTNAME]
    )
    # sometimes target the pod's own app (self-affinity / one-per-domain
    # anti-affinity), sometimes another app in the batch
    target = own_app if rng.random() < 0.6 else rng.choice(APPS)
    return PodAffinityTerm(
        topology_key=key,
        label_selector=LabelSelector(match_labels={"app": target}),
    )


def _random_pod_affinity(rng: random.Random, own_app: str) -> Affinity:
    aff = Affinity()
    roll = rng.random()
    if roll < 0.45:
        terms = [_random_aff_term(rng, own_app)]
        if rng.random() < 0.3:
            aff.pod_affinity = PodAffinity(preferred=[
                WeightedPodAffinityTerm(weight=rng.randint(1, 100), pod_affinity_term=t)
                for t in terms
            ])
        else:
            aff.pod_affinity = PodAffinity(required=terms)
    else:
        terms = [_random_aff_term(rng, own_app)]
        if rng.random() < 0.3:
            aff.pod_anti_affinity = PodAntiAffinity(preferred=[
                WeightedPodAffinityTerm(weight=rng.randint(1, 100), pod_affinity_term=t)
                for t in terms
            ])
        else:
            aff.pod_anti_affinity = PodAntiAffinity(required=terms)
    return aff


def _random_node_affinity(rng: random.Random) -> Affinity:
    """Preferred and/or multi-term required node affinity (relax-ladder
    coverage: preferences.go:70-83, 55-61)."""
    na = NodeAffinity()
    if rng.random() < 0.6:
        na.preferred = [
            PreferredSchedulingTerm(
                weight=rng.randint(1, 100),
                preference=NodeSelectorTerm(
                    match_expressions=[
                        {
                            "key": wk.LABEL_TOPOLOGY_ZONE,
                            "operator": "In",
                            "values": rng.sample(ZONES, rng.randint(1, 2)),
                        }
                    ]
                ),
            )
            for _ in range(rng.randint(1, 2))
        ]
    if rng.random() < 0.4 or not na.preferred:
        na.required = [
            NodeSelectorTerm(
                match_expressions=[
                    {
                        "key": wk.LABEL_TOPOLOGY_ZONE,
                        "operator": "In",
                        "values": rng.sample(ZONES, rng.randint(1, 3)),
                    }
                ]
            )
            for _ in range(rng.randint(1, 2))
        ]
    return Affinity(node_affinity=na)


def _random_shape(
    rng: random.Random, si: int, topo: bool = False, fused: bool = False
):
    kwargs = {"requests": {"cpu": rng.choice(CPUS), "memory": rng.choice(MEMS)}}
    if topo:
        own_app = rng.choice(APPS)
        if rng.random() < 0.8:
            kwargs["labels"] = {"app": own_app}
        n_tsc = rng.choice([0, 1, 1, 1, 2]) if rng.random() < 0.45 else 0
        if n_tsc:
            kwargs["topology_spread_constraints"] = [
                _random_spread(rng) for _ in range(n_tsc)
            ]
        aff_roll = rng.random()
        if aff_roll < 0.18:
            kwargs["affinity"] = _random_pod_affinity(rng, own_app)
        elif aff_roll < 0.3:
            kwargs["affinity"] = _random_node_affinity(rng)
        if rng.random() < 0.12:
            # host ports: same-port shapes conflict (wildcard IP), distinct
            # IPs coexist — claims accumulate usage on the topo driver
            from karpenter_tpu.apis.core import ContainerPort

            kwargs["host_port"] = ContainerPort(
                container_port=80,
                host_port=rng.choice([8080, 8080, 9090, 7070]),
                host_ip=rng.choice(["", "", "10.0.0.1"]),
                protocol=rng.choice(["TCP", "TCP", "UDP"]),
            )
        if rng.random() < 0.1:
            # PVC-backed volumes: per-pod or shared claims against CSI
            # attach limits on seeded existing nodes
            kwargs["volume"] = rng.choice(["own", "own", f"shared-{si}"])
    selector = {}
    roll = rng.random()
    if roll < 0.3:
        selector[wk.LABEL_ARCH] = rng.choice(ARCHS)
    if 0.2 < roll < 0.45:
        selector[wk.LABEL_TOPOLOGY_ZONE] = rng.choice(ZONES)
    if roll > 0.9:
        selector[wk.LABEL_OS] = rng.choice(OSES)
    if roll > 0.97 and not fused:
        # seeded nodes carry no capacity-type label: a ct-selecting group
        # would make the node requirement state narrowable, which the fused
        # scan's static node tables decline — keep the fused generator
        # inside the scan-shaped class so its fallback assert stays at zero
        selector[wk.CAPACITY_TYPE_LABEL_KEY] = rng.choice(
            [wk.CAPACITY_TYPE_SPOT, wk.CAPACITY_TYPE_ON_DEMAND]
        )
    hostname_pin = None
    if rng.random() < 0.06 and not fused:
        # hostname pins: an existing node's name (joins it if feasible), a
        # bogus name (per-template compat errors embedding the consumed
        # placeholder strings), or a NotIn row (satisfied by any placeholder)
        hn_roll = rng.random()
        if hn_roll < 0.45:
            selector[wk.LABEL_HOSTNAME] = f"existing-{rng.randint(0, 5)}"
        elif hn_roll < 0.8:
            selector[wk.LABEL_HOSTNAME] = "no-such-node"
        else:
            hostname_pin = f"existing-{rng.randint(0, 5)}"
    if selector:
        kwargs["node_selector"] = selector
    spec_kwargs = {}
    if hostname_pin is not None and "affinity" not in kwargs:
        spec_kwargs["affinity"] = Affinity(
            node_affinity=NodeAffinity(
                required=[
                    NodeSelectorTerm(
                        match_expressions=[
                            {
                                "key": wk.LABEL_HOSTNAME,
                                "operator": "NotIn",
                                "values": [hostname_pin],
                            }
                        ]
                    )
                ]
            )
        )
    if rng.random() < 0.25:
        spec_kwargs["tolerations"] = [
            Toleration(key="team", operator="Equal", value="infra", effect="NoSchedule")
        ]
    if rng.random() < 0.15 and "affinity" not in kwargs and "affinity" not in spec_kwargs:
        op = rng.choice(["In", "NotIn"])
        spec_kwargs["affinity"] = Affinity(
            node_affinity=NodeAffinity(
                required=[
                    NodeSelectorTerm(
                        match_expressions=[
                            {
                                "key": wk.LABEL_TOPOLOGY_ZONE,
                                "operator": op,
                                "values": rng.sample(ZONES, rng.randint(1, 3)),
                            }
                        ]
                    )
                ]
            )
        )
    if rng.random() < 0.04:
        kwargs["requests"] = {"cpu": "10000"}  # unschedulable: error-path parity
    return kwargs, spec_kwargs


def build_case(
    seed: int,
    topo: bool = False,
    reserved: bool = False,
    cluster: bool = False,
    best_effort: bool = False,
    fused: bool = False,
):
    """(node_pools, state_nodes, bound_pods, daemonset_pods, build_pods)."""
    rng = random.Random(
        seed + 1_000_000
        if topo and not best_effort
        else seed + 2_000_000
        if reserved
        else seed + 3_000_000
        if cluster and not fused
        else seed + 4_000_000
        if best_effort and not topo
        else seed + 5_000_000
        if best_effort
        else seed + 6_000_000
        if fused and not cluster
        else seed + 7_000_000
        if fused
        else seed
    )
    pools = _random_nodepools(rng, topo, best_effort, fused)
    nodes = []
    bound = []
    # cluster mode: a steady-state fleet — most pods join EXISTING nodes,
    # exercising the _try_nodes path, per-node usage tracking, and the
    # emptiest-first/in-order scan at production-like node counts
    n_existing = rng.randint(24, 64) if cluster else rng.randint(0, 6)
    for i in range(n_existing):
        pool = rng.choice(pools).metadata.name
        labels = {wk.LABEL_ARCH: "amd64", wk.LABEL_OS: "linux"}
        if topo and rng.random() < 0.3:
            labels["tier"] = rng.choice(TIERS)
        if cluster:
            size = rng.choice([("16", "64Gi"), ("16", "64Gi"), ("32", "128Gi"), ("8", "32Gi")])
        else:
            size = ("16", "64Gi")
        node = registered_node(
            name=f"existing-{i}",
            pool=pool,
            instance_type="s-4x-amd64-linux",
            zone=rng.choice(ZONES),
            capacity={"cpu": size[0], "memory": size[1], "pods": "110"},
            labels=labels,
        )
        nodes.append(node)
        if cluster and rng.random() < 0.7:
            # seed partial usage so nodes present varied headroom
            for j in range(rng.randint(1, 4)):
                bp = unschedulable_pod(
                    name=f"seed-{i}-{j}",
                    requests={"cpu": rng.choice(["500m", "1", "2"])},
                )
                bp.metadata.uid = f"seed-uid-{i}-{j}"
                bp.metadata.creation_timestamp = 0.0
                bound.append(bind_pod(bp, node))
        if topo:
            # live pods seed domain counts (topology.go countDomains); some
            # carry required anti-affinity, creating INVERSE topology groups
            # that constrain even plain batch pods (topology.go:55-58)
            for j in range(rng.randint(0, 2)):
                bp_kwargs = {}
                if rng.random() < 0.25:
                    bp_kwargs["affinity"] = Affinity(
                        pod_anti_affinity=PodAntiAffinity(
                            required=[
                                PodAffinityTerm(
                                    topology_key=rng.choice(
                                        [wk.LABEL_TOPOLOGY_ZONE, wk.LABEL_HOSTNAME]
                                    ),
                                    label_selector=LabelSelector(
                                        match_labels={"app": rng.choice(APPS)}
                                    ),
                                )
                            ]
                        )
                    )
                bp = unschedulable_pod(
                    name=f"bound-{i}-{j}",
                    requests={"cpu": "100m"},
                    labels={"app": rng.choice(APPS)} if rng.random() < 0.8 else {},
                    **bp_kwargs,
                )
                bp.metadata.uid = f"bound-uid-{i}-{j}"
                bp.metadata.creation_timestamp = 0.0
                bound.append(bind_pod(bp, node))
    ds_pods = []
    if rng.random() < 0.4:
        ds = daemonset(requests={"cpu": "100m", "memory": "64Mi"})
        ds_pods.append(daemonset_pod(ds))
    n_pods = rng.randint(ffd.DEVICE_MIN_PODS, 320)
    shapes = [
        _random_shape(rng, si, topo, fused)
        for si in range(rng.randint(3, 24))
    ]
    if topo and not any(s[0].get("topology_spread_constraints") for s in shapes):
        shapes[0][0]["topology_spread_constraints"] = [_random_spread(rng)]
    picks = [rng.randrange(len(shapes)) for _ in range(n_pods)]

    # storage objects for volume shapes: StorageClass + one PVC per
    # volume-bearing pod (or per shared group) + CSINode attach limits on
    # some existing nodes (created BEFORE the Node so ingestion sees them)
    storage: list = []
    if topo and any(s[0].get("volume") for s in shapes):
        from karpenter_tpu.apis.core import (
            CSINode,
            CSINodeDriver,
            ObjectMeta,
            PersistentVolumeClaim,
            StorageClass,
        )

        driver = "ebs.csi.example.com"
        storage.append(
            StorageClass(metadata=ObjectMeta(name="fast"), provisioner=driver)
        )
        pvc_names = set()
        for i, si in enumerate(picks):
            mode = shapes[si][0].get("volume")
            if mode == "own":
                pvc_names.add(f"pvc-p-{i:05d}")
            elif mode:
                pvc_names.add(f"pvc-{mode}")
        for name in sorted(pvc_names):
            storage.append(
                PersistentVolumeClaim(
                    metadata=ObjectMeta(name=name), storage_class_name="fast"
                )
            )
        limited = [
            CSINode(
                metadata=ObjectMeta(name=node.metadata.name),
                drivers=[
                    CSINodeDriver(name=driver, allocatable_count=rng.randint(1, 2))
                ],
            )
            for node in nodes
            if rng.random() < 0.5
        ]
        nodes = limited + nodes

    def build_pods():
        from karpenter_tpu.apis.core import Volume

        pods = []
        for i, si in enumerate(picks):
            kwargs, spec_kwargs = shapes[si]
            port = kwargs.get("host_port")
            volume = kwargs.get("volume")
            if port is not None or volume is not None:
                kwargs = {
                    k: v
                    for k, v in kwargs.items()
                    if k not in ("host_port", "volume")
                }
            p = unschedulable_pod(name=f"p-{i:05d}", **kwargs, **spec_kwargs)
            if port is not None:
                p.spec.containers[0].ports = [port]
            if volume is not None:
                pvc = f"pvc-p-{i:05d}" if volume == "own" else f"pvc-{volume}"
                p.spec.volumes = [Volume(name="data", persistent_volume_claim=pvc)]
            p.metadata.uid = f"uid-{i:05d}"
            p.metadata.creation_timestamp = float(i % 7)  # exercise uid ties
            pods.append(p)
        return pods

    return pools, storage + nodes, bound, ds_pods, build_pods


def decisions(results):
    claims = []
    for nc in results.new_node_claims:
        claims.append(
            (
                nc.nodepool_name,
                tuple(sorted(it.name for it in nc.instance_type_options)),
                tuple(sorted(p.metadata.name for p in nc.pods)),
                tuple(
                    sorted(
                        (
                            r.key, tuple(sorted(r.values)), r.complement,
                            r.greater_than, r.less_than, r.min_values,
                        )
                        for r in nc.requirements
                    )
                ),
                nc.annotations.get(wk.NODECLAIM_MIN_VALUES_RELAXED_ANNOTATION_KEY),
            )
        )
    claims.sort()
    existing = sorted(
        (en.name(), tuple(sorted(p.metadata.name for p in en.pods)))
        for en in results.existing_nodes
        if en.pods
    )
    errors = sorted(
        (p.metadata.name, type(e).__name__, str(e)) for p, e in results.pod_errors.items()
    )
    return claims, existing, errors


def run_case(
    seed: int,
    topo: bool = False,
    reserved: bool = False,
    cluster: bool = False,
    strict: bool = False,
    best_effort: bool = False,
    mesh_devices: int = 0,
    fused: bool = False,
):
    """Returns (host_decisions, device_decisions, device_ran). With
    `mesh_devices` >= 1 the device engine carries an N-device mesh, so the
    sweep runs through the `_sharded` kernels — the host oracle must still
    match bit-for-bit at every mesh size. With `fused` the device leg runs
    with the one-dispatch scan forced ON (ops/fused.py) — the sequential
    host walk stays the oracle."""
    reserved = reserved or strict
    pools, nodes, bound, ds_pods, build_pods = build_case(
        seed, topo, reserved, cluster, best_effort, fused
    )
    catalog = reserved_catalog() if reserved else CATALOG
    extra = {"reserved_offering_mode": "Strict"} if strict else {}
    if best_effort:
        extra["min_values_policy"] = "BestEffort"

    def env(engine):
        import copy

        return Env(
            node_pools=copy.deepcopy(pools),
            state_nodes=copy.deepcopy(nodes),
            pods=copy.deepcopy(bound),
            daemonset_pods=copy.deepcopy(ds_pods),
            catalog=catalog,
            engine=engine,
            **extra,
        )

    # hostname placeholder strings are decision-relevant under topology
    # (sorted-domain iteration) — both runs must draw the same sequence
    ncmod._hostname_counter = itertools.count(1)
    host = decisions(env(None).schedule(build_pods()))
    solves0 = ffd.DEVICE_SOLVES
    old_strict = ffd.STRICT
    ffd.STRICT = True
    from karpenter_tpu.ops import fused as fused_mod

    old_fused = fused_mod.FUSED_MODE
    if fused:
        fused_mod.FUSED_MODE = "on"
    ncmod._hostname_counter = itertools.count(1)
    mesh = None
    if mesh_devices:
        import jax
        import numpy as np
        from jax.sharding import Mesh

        mesh = Mesh(np.array(jax.devices()[:mesh_devices]), ("pods",))
    try:
        dev = decisions(
            env(CatalogEngine(catalog, mesh=mesh)).schedule(build_pods())
        )
    finally:
        ffd.STRICT = old_strict
        fused_mod.FUSED_MODE = old_fused
    return host, dev, ffd.DEVICE_SOLVES > solves0


class TestDeviceParity:
    @pytest.mark.parametrize("seed", range(40))
    def test_randomized_decision_parity(self, seed):
        host, dev, ran = run_case(seed)
        assert host == dev
        assert ran, "device path unexpectedly fell back to the host loop"

    @pytest.mark.parametrize("seed", [101, 147])
    def test_group_rep_immune_to_later_relax_mutation(self, seed):
        """Regression (soak seeds 101/147): a mid-relax pod mutates in place
        on later rungs (e.g. the wildcard PreferNoSchedule toleration); the
        driver's per-group representative must be a snapshot, or a
        mid-solve group refresh re-points earlier shape groups at the FUTURE
        shape's topology groups — whose fresh store-seeded counts admit
        over-skew joins the host rejects."""
        host, dev, ran = run_case(seed, topo=True)
        assert host == dev
        assert ran

    def test_relaxation_creates_topology_group_mid_solve(self):
        """Regression (soak seed 469): relaxing a multi-term node-affinity
        pod creates a NEW topology group mid-solve (its node-filter hash
        differs); the device must record subsequent placements into it, or
        final error messages embed stale domain counts."""
        host, dev, ran = run_case(469, topo=True)
        assert host == dev
        assert ran

    @pytest.mark.parametrize("seed", range(30))
    def test_topology_spread_decision_parity(self, seed):
        """Topology-engaged solves on the topo driver (ops/ffd_topo.py):
        spread over zone/hostname/capacity-type/arch/custom keys, mixed
        skews/policies/selectors, ScheduleAnyway relaxation, live-pod-seeded
        counts — decisions must match the host loop exactly."""
        host, dev, ran = run_case(seed, topo=True)
        assert host == dev
        assert ran, "topo device path unexpectedly fell back to the host loop"

    @pytest.mark.parametrize("seed", range(12))
    def test_python_loop_parity(self, seed, monkeypatch):
        """The pure-Python steady-state loop (fallback when the native kernel
        can't build) must make the same decisions as the native kernel."""
        from karpenter_tpu.ops import native

        monkeypatch.setattr(native, "_tried", True)
        monkeypatch.setattr(native, "_lib", None)
        host, dev, ran = run_case(seed)
        assert host == dev
        assert ran

    @pytest.mark.parametrize("seed", range(20))
    def test_reserved_capacity_decision_parity(self, seed):
        """Fallback-mode reserved capacity on the device path: per-join
        reservation bookkeeping (reserve/release/capacity counting) and
        finalize pinning must match the host loop exactly."""
        host, dev, ran = run_case(seed, reserved=True)
        assert host == dev
        assert ran, "reserved device path unexpectedly fell back to the host loop"

    @pytest.mark.parametrize("seed", range(12))
    def test_reserved_with_topology_decision_parity(self, seed):
        """Reserved bookkeeping on the TOPO driver: zone-narrowed volatile
        joins must hold/release exactly the offerings the host would."""
        host, dev, ran = run_case(seed, topo=True, reserved=True)
        assert host == dev
        assert ran, "reserved+topo device path unexpectedly fell back"

    @pytest.mark.parametrize("seed", range(15))
    def test_strict_reserved_decision_parity(self, seed):
        """Strict-mode reserved capacity on the all-volatile topo driver:
        pre-commit reservation gates, scan-aborting ReservedOfferingErrors,
        and capacity exhaustion across claims must match the host exactly
        (same workloads as the fallback-mode reserved seeds)."""
        host, dev, ran = run_case(seed, strict=True)
        assert host == dev
        assert ran, "strict-reserved device path unexpectedly fell back"

    @pytest.mark.parametrize("seed", range(20))
    def test_best_effort_minvalues_decision_parity(self, seed):
        """BestEffort minValues on the device path: open-time relaxation
        into per-claim specs (nodeclaim.go:425-436) — relaxed counts,
        annotations, and every decision must match the host exactly, with
        no fallback (the last metered decline, retired round 5)."""
        host, dev, ran = run_case(seed, best_effort=True)
        assert host == dev
        assert ran, "BestEffort device path unexpectedly fell back"

    @pytest.mark.parametrize("seed", range(12))
    def test_best_effort_with_topology_decision_parity(self, seed):
        """BestEffort relaxation on the TOPO driver: volatile joins must
        gate on the open-relaxed per-claim specs exactly like the host."""
        host, dev, ran = run_case(seed, topo=True, best_effort=True)
        assert host == dev
        assert ran, "BestEffort+topo device path unexpectedly fell back"

    @pytest.mark.parametrize("seed", range(15))
    def test_large_existing_cluster_parity(self, seed):
        """Steady-state fleet shape: 24-64 existing nodes with seeded usage;
        most pods join existing capacity (the _try_nodes scan) rather than
        opening claims — decisions must match the host exactly."""
        host, dev, ran = run_case(seed, cluster=True)
        assert host == dev
        assert ran, "cluster-mode device path unexpectedly fell back"

    def test_device_solves_counter_never_regresses_to_fallback(self):
        """The production-shaped workload (≥64 plain pods, kwok catalog) must
        take the device path — guards against silent eligibility regressions."""
        _, _, ran = run_case(12345)
        assert ran

    @pytest.mark.parametrize("seed", range(10))
    @pytest.mark.parametrize("mesh_devices", [1, 8])
    def test_mesh_sharded_decision_parity(self, seed, mesh_devices):
        """The sweep shard_mapped over a device mesh (pod axis sharded,
        catalog replicated) must match the host oracle at EVERY mesh size —
        a 1-device mesh included (bit-identity with the unsharded path)."""
        host, dev, ran = run_case(seed, mesh_devices=mesh_devices)
        assert host == dev
        assert ran, "mesh device path unexpectedly fell back"

    @pytest.mark.parametrize("seed", range(6))
    def test_mesh_with_topology_decision_parity(self, seed):
        """Topology-engaged solves (count-tensor gates) with the cube
        sharded over the full 8-device mesh."""
        host, dev, ran = run_case(seed, topo=True, mesh_devices=8)
        assert host == dev
        assert ran, "mesh+topo device path unexpectedly fell back"


class TestFusedParity:
    """One-dispatch solve (ops/fused.py + packer._solve_scan): sequential
    host oracle vs the device-resident scan on twin seeded envs. The fused
    generator keeps cases inside the scan-shaped class (no minValues, no
    PreferNoSchedule, no hostname pins, no capacity-type selectors against
    label-less nodes), so the fallback assert is exact: every seed must
    execute as a fused dispatch — 0 divergences, 0 unexpected fallbacks."""

    def _run(self, seed, **kw):
        from karpenter_tpu.ops import fused as fused_mod

        f0 = fused_mod.FUSED_SOLVES
        d0 = dict(fused_mod.FUSED_DECLINES)
        host, dev, ran = run_case(seed, fused=True, **kw)
        delta = {
            k: v - d0.get(k, 0)
            for k, v in fused_mod.FUSED_DECLINES.items()
            if v != d0.get(k, 0)
        }
        return host, dev, ran, fused_mod.FUSED_SOLVES - f0, delta

    @pytest.mark.parametrize("seed", range(15))
    def test_fused_decision_parity(self, seed):
        host, dev, ran, fused_n, declines = self._run(seed)
        assert host == dev
        assert ran, "device path fell back to the host loop"
        assert fused_n == 1, f"fused scan unexpectedly fell back: {declines}"

    @pytest.mark.parametrize("seed", range(10))
    def test_fused_cluster_decision_parity(self, seed):
        """Steady-state fleet shape: existing nodes with seeded usage —
        the scan's node pointer phase — still ONE dispatch per batch."""
        host, dev, ran, fused_n, declines = self._run(seed, cluster=True)
        assert host == dev
        assert ran, "device path fell back to the host loop"
        assert fused_n == 1, f"fused scan unexpectedly fell back: {declines}"

    @pytest.mark.parametrize("seed", range(6))
    def test_fusedtopo_declines_with_parity(self, seed):
        """Topology-engaged solves with the fused path ON: the scan must
        decline (metered `topo`, never a crash or a wrong answer) and the
        topo driver must still match the host exactly."""
        host, dev, ran, fused_n, declines = self._run(seed, topo=True)
        assert host == dev
        assert ran
        assert fused_n == 0
        assert set(declines) <= {"topo", "min"}, declines

    @pytest.mark.parametrize("seed", range(4))
    def test_fusedmesh_decision_parity(self, seed):
        """The fused scan's mesh twin (replicated shard_map) at mesh size
        8: one dispatch, decisions bit-identical to the host oracle."""
        host, dev, ran, fused_n, declines = self._run(seed, mesh_devices=8)
        assert host == dev
        assert ran
        assert fused_n == 1, f"fused mesh scan fell back: {declines}"


def run_explain_case(seed: int, fused: bool = False, cluster: bool = False):
    """run_case's twin-leg pattern with the explain recorder ON for both
    legs. Two ride-along pods that cannot schedule anywhere guarantee
    ledger rows; after each leg the staged funnels commit through the same
    barrier the solverd coalescer uses. Returns (host_decisions,
    device_decisions, host_ledger, device_ledger, device_ran) where a
    ledger is the sorted per-failed-pod view of (name, error, stages,
    per-nodepool funnel) — the /debug/explain payload must not depend on
    which solve path ran."""
    import copy

    from karpenter_tpu.observability import explain as explmod
    from karpenter_tpu.ops import fused as fused_mod

    pools, nodes, bound, ds_pods, build_pods = build_case(
        seed, False, False, cluster, False, fused
    )

    def env(engine):
        return Env(
            node_pools=copy.deepcopy(pools),
            state_nodes=copy.deepcopy(nodes),
            pods=copy.deepcopy(bound),
            daemonset_pods=copy.deepcopy(ds_pods),
            catalog=CATALOG,
            engine=engine,
        )

    def unsat_pods():
        giant = unschedulable_pod(name="xx-giant", requests={"cpu": "9999"})
        giant.metadata.uid = "uid-xx-giant"
        lost = unschedulable_pod(
            name="xx-lost-zone",
            requests={"cpu": "1"},
            node_selector={"topology.kubernetes.io/zone": "zone-nowhere"},
        )
        lost.metadata.uid = "uid-xx-lost"
        return [giant, lost]

    rec = explmod.recorder()
    old_mode = rec.mode or "off"

    def leg(engine):
        rec.reset()
        ncmod._hostname_counter = itertools.count(1)
        pods = build_pods() + unsat_pods()
        results = env(engine).schedule(pods)
        rec.commit_solve(pods, results.pod_errors, kind="solve")
        ledger = []
        for p in sorted(results.pod_errors, key=lambda p: p.metadata.name):
            e = rec.entry(p.metadata.uid)
            assert e is not None, f"no ledger entry for failed pod {p.metadata.name}"
            ledger.append(
                (
                    e["pod"],
                    e["error"],
                    tuple(e["stages"]),
                    tuple(
                        (f["nodepool"], tuple(f["stages"]), f["error"])
                        for f in e["funnel"]
                    ),
                )
            )
        return decisions(results), ledger

    solves0 = ffd.DEVICE_SOLVES
    old_strict = ffd.STRICT
    old_fused = fused_mod.FUSED_MODE
    try:
        explmod.configure(mode="on")
        host, host_ledger = leg(None)
        ffd.STRICT = True
        if fused:
            fused_mod.FUSED_MODE = "on"
        dev, dev_ledger = leg(CatalogEngine(CATALOG))
    finally:
        ffd.STRICT = old_strict
        fused_mod.FUSED_MODE = old_fused
        explmod.configure(mode=old_mode)
        rec.reset()
    return host, dev, host_ledger, dev_ledger, ffd.DEVICE_SOLVES > solves0


class TestExplainParity:
    """Decision provenance rides decision parity: the device leg and the
    one-dispatch fused leg must NARRATE eliminations identically to the
    host oracle — same per-pod funnel (nodepool walk order, stages, error
    text) and same classified final stages — or /debug/explain's answer
    would depend on which solve path happened to run."""

    @pytest.mark.parametrize("seed", range(6))
    def test_device_explanation_parity(self, seed):
        host, dev, host_ledger, dev_ledger, ran = run_explain_case(seed)
        assert host == dev
        assert ran, "device path fell back to the host loop"
        assert host_ledger == dev_ledger
        names = {row[0] for row in host_ledger}
        assert {"xx-giant", "xx-lost-zone"} <= names
        stages = {s for row in host_ledger for s in row[2]}
        assert stages <= set(explain_stage_vocab()), stages

    @pytest.mark.parametrize("seed", range(4))
    def test_fused_explanation_parity(self, seed):
        """The fused scan either solves the batch in one dispatch or
        declines to the device loop — in BOTH cases the ledger must match
        the host story exactly."""
        host, dev, host_ledger, dev_ledger, ran = run_explain_case(
            seed, fused=True
        )
        assert host == dev
        assert ran
        assert host_ledger == dev_ledger

    @pytest.mark.parametrize("seed", range(3))
    def test_cluster_explanation_parity(self, seed):
        """Existing-node assignments engaged: failed pods still narrate
        identically across legs."""
        host, dev, host_ledger, dev_ledger, ran = run_explain_case(
            seed, cluster=True
        )
        assert host == dev
        assert ran
        assert host_ledger == dev_ledger


def explain_stage_vocab():
    from karpenter_tpu.observability import explain as explmod

    return explmod.STAGES


def main(
    n_cases: int,
    topo: bool = False,
    reserved: bool = False,
    cluster: bool = False,
    strict: bool = False,
    best_effort: bool = False,
    mesh_devices: int = 0,
    fused: bool = False,
) -> int:
    failures = 0
    fallbacks = 0
    label = (
        "strict-reserved"
        if strict
        else "reserved+topo"
        if topo and reserved
        else "besteffort+topo"
        if topo and best_effort
        else "besteffort"
        if best_effort
        else "fusedtopo" if fused and topo
        else "topo" if topo else "reserved" if reserved else
        "fusedcluster" if fused and cluster else
        "fused" if fused else
        "cluster" if cluster else "plain"
    )
    if mesh_devices:
        label = f"{label}@mesh{mesh_devices}"
    for seed in range(n_cases):
        host, dev, ran = run_case(
            seed, topo, reserved, cluster, strict, best_effort,
            mesh_devices=mesh_devices, fused=fused,
        )
        if host != dev:
            failures += 1
            print(f"{label} seed {seed}: DIVERGED")
        if not ran:
            fallbacks += 1
            print(f"{label} seed {seed}: fell back to host loop")
        if seed % 100 == 99:
            print(
                f"{label} {seed + 1}/{n_cases} cases, {failures} divergences, "
                f"{fallbacks} fallbacks"
            )
    print(f"DONE {label}: {n_cases} cases, {failures} divergences, {fallbacks} fallbacks")
    return 1 if (failures or fallbacks) else 0


if __name__ == "__main__":
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1000
    mode = sys.argv[2] if len(sys.argv) > 2 else "both"
    rc = 0
    if mode in ("plain", "both", "all"):
        rc |= main(n)
    if mode in ("topo", "both", "all"):
        rc |= main(n, topo=True)
    if mode in ("reserved", "all"):
        rc |= main(n, reserved=True)
    if mode in ("restopo", "all"):
        rc |= main(n, topo=True, reserved=True)
    if mode in ("cluster", "all"):
        rc |= main(n, cluster=True)
    if mode in ("strictres", "all"):
        rc |= main(n, strict=True)
    if mode in ("besteffort", "all"):
        rc |= main(n, best_effort=True)
    if mode in ("mesh", "all"):
        # host-oracle identity at every mesh size, padding edges included
        for devices in (1, 2, 3, 8):
            rc |= main(n, mesh_devices=devices)
    if mode in ("meshtopo", "all"):
        rc |= main(n, topo=True, mesh_devices=8)
    if mode in ("betopo", "all"):
        rc |= main(n, topo=True, best_effort=True)
    if mode in ("fused", "all"):
        rc |= main(n, fused=True)
    if mode in ("fusedcluster", "all"):
        rc |= main(n, cluster=True, fused=True)
    if mode in ("fusedtopo", "all"):
        rc |= main(n, topo=True, fused=True)
    if mode in ("fusedmesh", "all"):
        rc |= main(n, fused=True, mesh_devices=8)
    sys.exit(rc)
