"""NodePool runtime-validation specs ported from the reference's CEL rules
(nodepool_validation_cel_test.go; the CRD enforces these via kubebuilder
markers — here the ValidationController is the runtime twin, surfacing
failures as the ValidationSucceeded condition)."""

import pytest

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import Taint
from karpenter_tpu.apis.nodepool import Budget
from karpenter_tpu.controllers.nodepool_controllers import ValidationController
from karpenter_tpu.runtime.store import Store
from karpenter_tpu.utils.clock import FakeClock

from helpers import nodepool

CONDITION = "ValidationSucceeded"


def validate(pool):
    clock = FakeClock()
    store = Store(clock=clock)
    store.create(pool)
    ValidationController(store, clock).reconcile(pool)
    cond = pool.get_condition(CONDITION)
    return cond.status == "True", cond.message


def expect_valid(pool):
    ok, msg = validate(pool)
    assert ok, msg


def expect_invalid(pool, needle=""):
    ok, msg = validate(pool)
    assert not ok
    if needle:
        assert needle in msg, msg


class TestBudgetValidation:
    """nodepool_validation_cel_test.go — Budgets context."""

    def _pool(self, *budgets):
        np = nodepool("default")
        np.spec.disruption.budgets = list(budgets)
        return np

    def test_invalid_cron_fails(self):
        expect_invalid(
            self._pool(Budget(nodes="10", schedule="*", duration=3600.0)),
            "schedule",
        )

    def test_schedule_with_fewer_than_5_fields_fails(self):
        expect_invalid(
            self._pool(Budget(nodes="10", schedule="* * * *", duration=3600.0)),
            "schedule",
        )

    def test_negative_duration_fails(self):
        expect_invalid(
            self._pool(Budget(nodes="10", schedule="* * * * *", duration=-60.0)),
            "duration",
        )

    def test_seconds_precision_duration_fails(self):
        expect_invalid(
            self._pool(Budget(nodes="10", schedule="* * * * *", duration=90.0)),
            "seconds",
        )

    def test_negative_nodes_int_fails(self):
        expect_invalid(self._pool(Budget(nodes="-10")), "nodes")

    def test_negative_nodes_percent_fails(self):
        expect_invalid(self._pool(Budget(nodes="-10%")), "nodes")

    def test_percent_with_more_than_3_digits_fails(self):
        expect_invalid(self._pool(Budget(nodes="1000%")), "nodes")

    def test_cron_without_duration_fails(self):
        expect_invalid(
            self._pool(Budget(nodes="10", schedule="* * * * *")), "together"
        )

    def test_duration_without_cron_fails(self):
        expect_invalid(self._pool(Budget(nodes="10", duration=3600.0)), "together")

    def test_both_duration_and_cron_succeeds(self):
        expect_valid(
            self._pool(Budget(nodes="10", schedule="* * * * *", duration=3600.0))
        )

    def test_neither_duration_nor_cron_succeeds(self):
        expect_valid(self._pool(Budget(nodes="10")))

    def test_special_cased_crons_succeed(self):
        expect_valid(
            self._pool(Budget(nodes="10", schedule="@daily", duration=3600.0))
        )

    def test_one_invalid_budget_of_many_fails(self):
        expect_invalid(
            self._pool(
                Budget(nodes="10"),
                Budget(nodes="10", schedule="@foo", duration=3600.0),
            )
        )

    def test_multiple_reasons_allowed(self):
        expect_valid(
            self._pool(Budget(nodes="10", reasons=["Drifted", "Underutilized", "Empty"]))
        )


class TestTaintValidation:
    def _pool(self, *taints):
        return nodepool("default", taints=list(taints))

    def test_valid_taints_succeed(self):
        expect_valid(
            self._pool(
                Taint(key="team", value="infra", effect="NoSchedule"),
                Taint(key="example.com/lane", value="slow", effect="PreferNoSchedule"),
                Taint(key="a.b/c", effect="NoExecute"),
            )
        )

    def test_invalid_taint_key_fails(self):
        expect_invalid(self._pool(Taint(key="-bad-", effect="NoSchedule")), "key")

    def test_missing_taint_key_fails(self):
        expect_invalid(self._pool(Taint(key="", effect="NoSchedule")), "key")

    def test_overlong_taint_key_fails(self):
        expect_invalid(
            self._pool(Taint(key="k" * 400, effect="NoSchedule")), "key"
        )

    def test_invalid_taint_value_fails(self):
        expect_invalid(
            self._pool(Taint(key="team", value="bad value!", effect="NoSchedule")),
            "value",
        )

    def test_invalid_taint_effect_fails(self):
        expect_invalid(
            self._pool(Taint(key="team", effect="EvictEverything")), "effect"
        )

    def test_same_key_different_effects_succeeds(self):
        expect_valid(
            self._pool(
                Taint(key="team", value="infra", effect="NoSchedule"),
                Taint(key="team", value="infra", effect="NoExecute"),
            )
        )


class TestRequirementValidation:
    def _pool(self, *reqs):
        return nodepool("default", requirements=list(reqs))

    def test_valid_requirement_keys_succeed(self):
        expect_valid(
            self._pool(
                {"key": "example.com/tier", "operator": "In", "values": ["gold"]},
                {"key": wk.LABEL_TOPOLOGY_ZONE, "operator": "Exists"},
            )
        )

    def test_invalid_requirement_key_fails(self):
        expect_invalid(
            self._pool({"key": "bad key!", "operator": "Exists"}), "key"
        )

    def test_overlong_requirement_key_fails(self):
        expect_invalid(
            self._pool({"key": "d" * 317, "operator": "Exists"}), "key"
        )

    def test_nodepool_label_key_rejected(self):
        expect_invalid(
            self._pool(
                {"key": wk.NODEPOOL_LABEL_KEY, "operator": "In", "values": ["x"]}
            ),
            "reserved",
        )

    def test_supported_operators_allowed(self):
        for op in ("In", "NotIn", "Exists", "DoesNotExist", "Gt", "Lt"):
            values = ["1"] if op in ("In", "NotIn", "Gt", "Lt") else []
            expect_valid(
                self._pool({"key": "example.com/k", "operator": op, "values": values})
            )

    def test_unsupported_operator_fails(self):
        expect_invalid(
            self._pool({"key": "example.com/k", "operator": "Near", "values": []}),
            "operator",
        )

    def test_restricted_domain_fails(self):
        expect_invalid(
            self._pool({"key": "kubernetes.io/custom", "operator": "Exists"}),
            "restricted",
        )

    def test_restricted_domain_exceptions_allowed(self):
        expect_valid(
            self._pool(
                {"key": "node.kubernetes.io/instance-type", "operator": "Exists"},
                {"key": "subdomain.kops.k8s.io/gpu", "operator": "Exists"},
            )
        )
