"""Provisioning-suite oracle specs (reference
pkg/controllers/provisioning/suite_test.go — names kept, lines cited)."""

import pytest

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import (
    Affinity,
    Container,
    NodeAffinity,
    NodeSelectorTerm,
    ObjectMeta,
    Pod,
    PodSpec,
    PreferredSchedulingTerm,
    Taint,
    Toleration,
    pod_resource_requests,
)
from karpenter_tpu.operator.options import Options
from karpenter_tpu.utils.resources import parse_resource_list

from helpers import (
    daemonset,
    daemonset_pod,
    make_provisioner_harness,
    nodepool,
    unschedulable_pod,
)
from test_scheduler import Env


def run_batch(harness, pods):
    clock, store, provider, cluster, informer, prov = harness
    for p in pods:
        prov.trigger(p.metadata.uid)
    informer.flush()
    clock.step(1.5)
    return prov.reconcile()


class TestNodeClaimCreation:
    def test_nodepool_termination_grace_period_propagates(self):
        # suite_test.go:267 — nodepool TGP lands on created claims
        harness = make_provisioner_harness()
        clock, store, provider, cluster, informer, prov = harness
        pool = nodepool("default")
        pool.spec.template.spec.termination_grace_period = 123.0
        store.create(pool)
        pod = store.create(unschedulable_pod(requests={"cpu": "1"}))
        run_batch(harness, [pod])
        [claim] = store.list("NodeClaim")
        assert claim.spec.termination_grace_period == 123.0

    def test_no_termination_grace_period_by_default(self):
        # suite_test.go:256
        harness = make_provisioner_harness()
        clock, store, provider, cluster, informer, prov = harness
        store.create(nodepool("default"))
        pod = store.create(unschedulable_pod(requests={"cpu": "1"}))
        run_batch(harness, [pod])
        [claim] = store.list("NodeClaim")
        assert claim.spec.termination_grace_period is None

    def test_global_termination_grace_period_default(self, monkeypatch):
        # suite_test.go:244 — the process-level default applies when the
        # nodepool doesn't set one...
        from karpenter_tpu.scheduler import nodeclaimtemplate as ncltmpl

        monkeypatch.setattr(
            ncltmpl, "DEFAULT_TERMINATION_GRACE_PERIOD", 98 * 3600.0
        )
        harness = make_provisioner_harness()
        clock, store, provider, cluster, informer, prov = harness
        store.create(nodepool("default"))
        pod = store.create(unschedulable_pod(requests={"cpu": "1"}))
        run_batch(harness, [pod])
        [claim] = store.list("NodeClaim")
        assert claim.spec.termination_grace_period == 98 * 3600.0

    def test_nodepool_termination_grace_period_beats_global(self, monkeypatch):
        # suite_test.go:232 — ...and the nodepool's own value wins over it
        from karpenter_tpu.scheduler import nodeclaimtemplate as ncltmpl

        monkeypatch.setattr(
            ncltmpl, "DEFAULT_TERMINATION_GRACE_PERIOD", 98 * 3600.0
        )
        harness = make_provisioner_harness()
        clock, store, provider, cluster, informer, prov = harness
        pool = nodepool("default")
        pool.spec.template.spec.termination_grace_period = 123.0
        store.create(pool)
        pod = store.create(unschedulable_pod(requests={"cpu": "1"}))
        run_batch(harness, [pod])
        [claim] = store.list("NodeClaim")
        assert claim.spec.termination_grace_period == 123.0

    def test_deleting_nodepools_ignored(self):
        # suite_test.go:280
        harness = make_provisioner_harness()
        clock, store, provider, cluster, informer, prov = harness
        pool = nodepool("default")
        pool.metadata.finalizers.append("karpenter.sh/test")
        store.create(pool)
        store.delete(pool)  # finalizer present: deletion_timestamp set
        pod = store.create(unschedulable_pod(requests={"cpu": "1"}))
        run_batch(harness, [pod])
        assert store.list("NodeClaim") == []

    def test_unschedulable_without_valid_nodepools(self):
        # suite_test.go:291
        harness = make_provisioner_harness()
        clock, store, provider, cluster, informer, prov = harness
        pod = store.create(unschedulable_pod(requests={"cpu": "1"}))
        results = run_batch(harness, [pod])
        assert results is None or not store.list("NodeClaim")


class TestLimits:
    def test_partial_scheduling_when_limits_would_be_exceeded(self):
        # suite_test.go:726 — capacity up to the limit provisions; the rest
        # of the demand stays pending
        harness = make_provisioner_harness()
        clock, store, provider, cluster, informer, prov = harness
        store.create(nodepool("default", limits={"cpu": "20"}))
        pods = [
            store.create(unschedulable_pod(requests={"cpu": "10"})) for _ in range(5)
        ]
        run_batch(harness, pods)
        claims = store.list("NodeClaim")
        assert claims, "some capacity should provision"
        # pessimistic tracking keeps launched capacity bounded near the
        # limit; demand for all 5 pods (50 cpu) must NOT be fully provisioned
        assert len(claims) < 5


class TestSidecarResourceAccounting:
    """suite_test.go:531-685 — max(containers+sidecars, init ceiling)."""

    def _pod(self, containers, init_containers):
        pod = Pod(
            metadata=ObjectMeta(name="sc-pod"),
            spec=PodSpec(
                containers=[
                    Container(requests=parse_resource_list(c)) for c in containers
                ],
                init_containers=[
                    Container(
                        requests=parse_resource_list(c),
                        restart_policy=policy,
                    )
                    for c, policy in init_containers
                ],
            ),
        )
        return pod

    def test_init_before_sidecar(self):
        # init (3 cpu) runs before the sidecar exists: ceiling is
        # max(init, app+sidecar) = max(3, 1+2) = 3
        pod = self._pod(
            containers=[{"cpu": "1"}],
            init_containers=[({"cpu": "3"}, None), ({"cpu": "2"}, "Always")],
        )
        assert pod_resource_requests(pod)["cpu"] == pytest.approx(3.0)

    def test_sidecar_before_small_init(self):
        # sidecar (2) starts first; later init (1) runs alongside it:
        # max(2+1 init phase, 1+2 app phase) = 3
        pod = self._pod(
            containers=[{"cpu": "1"}],
            init_containers=[({"cpu": "2"}, "Always"), ({"cpu": "1"}, None)],
        )
        assert pod_resource_requests(pod)["cpu"] == pytest.approx(3.0)

    def test_sidecar_before_large_init(self):
        # later init (4) + running sidecar (2) dominates the app phase (1+2)
        pod = self._pod(
            containers=[{"cpu": "1"}],
            init_containers=[({"cpu": "2"}, "Always"), ({"cpu": "4"}, None)],
        )
        assert pod_resource_requests(pod)["cpu"] == pytest.approx(6.0)


class TestDaemonSetAccounting:
    def test_daemonset_overhead_too_large_blocks(self):
        # suite_test.go:906
        ds = daemonset(requests={"cpu": "10000"})
        env = Env(daemonset_pods=[daemonset_pod(ds)])
        results = env.schedule([unschedulable_pod(requests={"cpu": "1"})])
        assert len(results.pod_errors) == 1

    def test_pods_without_requests_schedule(self):
        # suite_test.go:1037
        env = Env()
        pod = unschedulable_pod()
        pod.spec.containers[0].requests = {}
        results = env.schedule([pod])
        assert not results.pod_errors


class TestDaemonSetEligibility:
    """suite_test.go:1045-1320 — which daemonsets count toward claim
    overhead. Asserted via the scheduler-sim claim's accumulated requests
    (the created-claim stamping itself is covered by the hostname-affinity
    and request-carrying specs below)."""

    def _overhead_env(self, ds_pod, **pool_kwargs):
        return Env(node_pools=[nodepool("default", **pool_kwargs)], daemonset_pods=[ds_pod])

    def _claim_cpu(self, env, pod_kwargs=None):
        results = env.schedule(
            [unschedulable_pod(**(pod_kwargs or {"requests": {"cpu": "1"}}))]
        )
        assert not results.pod_errors
        [nc] = results.new_node_claims
        return nc.requests["cpu"]

    def test_intolerable_daemonset_ignored(self):
        # suite_test.go:1045 — pool tainted; the daemon lacks a toleration
        dp = daemonset_pod(daemonset(requests={"cpu": "2"}))
        env = self._overhead_env(
            dp, taints=[Taint(key="foo", value="bar", effect="NoSchedule")]
        )
        cpu = self._claim_cpu(
            env,
            {
                "requests": {"cpu": "1"},
                "tolerations": [Toleration(operator="Exists")],
            },
        )
        assert cpu == pytest.approx(1.0)

    def test_invalid_selector_daemonset_ignored(self):
        # suite_test.go:1077
        dp = daemonset_pod(daemonset(requests={"cpu": "2"}))
        dp.spec.node_selector = {"node": "invalid"}
        env = self._overhead_env(dp)
        assert self._claim_cpu(env) == pytest.approx(1.0)

    def test_not_in_unspecified_key_daemonset_counted(self):
        # suite_test.go:1099 — NotIn over an undefined key matches
        dp = daemonset_pod(daemonset(requests={"cpu": "2"}))
        dp.spec.affinity = Affinity(
            node_affinity=NodeAffinity(
                required=[
                    NodeSelectorTerm(
                        match_expressions=[
                            {"key": "foo", "operator": "NotIn", "values": ["bar"]}
                        ]
                    )
                ]
            )
        )
        env = self._overhead_env(dp)
        assert self._claim_cpu(env) == pytest.approx(3.0)

    def test_hostname_affinity_daemonset_replaced_by_template(self):
        # suite_test.go:1122 — the daemonset controller stamps per-node name
        # affinity on live pods; the provisioner replaces it with the
        # TEMPLATE's required affinity while keeping the live pod's requests
        # (which a LimitRange may have overridden)
        harness = make_provisioner_harness()
        clock, store, provider, cluster, informer, prov = harness
        store.create(nodepool("default", labels={"foo": "bar"}))
        ds = daemonset(requests={"cpu": "4"})
        ds.spec.template_spec.affinity = Affinity(
            node_affinity=NodeAffinity(
                required=[
                    NodeSelectorTerm(
                        match_expressions=[
                            {"key": "foo", "operator": "In", "values": ["bar"]}
                        ]
                    )
                ]
            )
        )
        store.create(ds)
        live = daemonset_pod(ds, node_name="node-name")
        live.spec.affinity = Affinity(
            node_affinity=NodeAffinity(
                required=[
                    NodeSelectorTerm(
                        match_expressions=[
                            {
                                "key": "metadata.name",
                                "operator": "In",
                                "values": ["node-name"],
                            }
                        ]
                    )
                ]
            )
        )
        live.spec.containers[0].requests = parse_resource_list({"cpu": "2"})
        store.create(live)
        informer.flush()
        pod = store.create(
            unschedulable_pod(
                requests={"cpu": "1"}, node_selector={"foo": "bar"}
            )
        )
        run_batch(harness, [pod])
        [claim] = store.list("NodeClaim")
        # live requests (2) respected, hostname pin replaced: daemon counted
        assert claim.spec.resources.requests["cpu"] == pytest.approx(3.0)

    def test_multi_term_affinity_daemonset_counted(self):
        # suite_test.go:1194 — one incompatible OR term doesn't disqualify
        dp = daemonset_pod(daemonset(requests={"cpu": "2"}))
        dp.spec.affinity = Affinity(
            node_affinity=NodeAffinity(
                required=[
                    NodeSelectorTerm(
                        match_expressions=[
                            {"key": "undefined-custom", "operator": "In", "values": ["x"]}
                        ]
                    ),
                    NodeSelectorTerm(
                        match_expressions=[
                            {
                                "key": wk.LABEL_TOPOLOGY_ZONE,
                                "operator": "In",
                                "values": ["kwok-zone-1"],
                            }
                        ]
                    ),
                ]
            )
        )
        env = self._overhead_env(dp)
        assert self._claim_cpu(env) == pytest.approx(3.0)

    def test_incompatible_preference_daemonset_counted(self):
        # suite_test.go:1254 — preferences are ignored for daemon compat
        dp = daemonset_pod(daemonset(requests={"cpu": "2"}))
        dp.spec.affinity = Affinity(
            node_affinity=NodeAffinity(
                preferred=[
                    PreferredSchedulingTerm(
                        weight=1,
                        preference=NodeSelectorTerm(
                            match_expressions=[
                                {"key": "undefined-custom", "operator": "In", "values": ["x"]}
                            ]
                        ),
                    )
                ]
            )
        )
        env = self._overhead_env(dp)
        assert self._claim_cpu(env) == pytest.approx(3.0)

    def test_prefer_no_schedule_taint_daemonset_counted(self):
        # suite_test.go:1282 — daemons auto-tolerate PreferNoSchedule
        dp = daemonset_pod(daemonset(requests={"cpu": "2"}))
        env = self._overhead_env(
            dp, taints=[Taint(key="soft", value="true", effect="PreferNoSchedule")]
        )
        cpu = self._claim_cpu(
            env,
            {
                "requests": {"cpu": "1"},
                "tolerations": [Toleration(operator="Exists")],
            },
        )
        assert cpu == pytest.approx(3.0)


class TestNodeClaimRequestContents:
    """suite_test.go:1468-1745 — what the created NodeClaim carries."""

    def _provision_one(self, pool, pod=None):
        harness = make_provisioner_harness()
        clock, store, provider, cluster, informer, prov = harness
        store.create(pool)
        p = store.create(pod or unschedulable_pod(requests={"cpu": "1"}))
        run_batch(harness, [p])
        [claim] = store.list("NodeClaim")
        return claim

    def test_request_has_expected_requirements(self):
        # suite_test.go:1468 — instance-type and nodepool requirements
        pool = nodepool("default")
        claim = self._provision_one(pool)
        by_key = {r["key"]: r for r in claim.spec.requirements}
        assert by_key[wk.NODEPOOL_LABEL_KEY]["values"] == ["default"]
        assert wk.LABEL_INSTANCE_TYPE in by_key
        assert len(by_key[wk.LABEL_INSTANCE_TYPE]["values"]) >= 1

    def test_request_has_additional_requirements(self):
        # suite_test.go:1489 — custom template requirements propagate
        pool = nodepool(
            "default",
            requirements=[
                {"key": "custom-requirement-key", "operator": "In", "values": ["value"]},
                {"key": "custom-requirement-key2", "operator": "In", "values": ["value"]},
            ],
        )
        claim = self._provision_one(pool)
        by_key = {r["key"]: r for r in claim.spec.requirements}
        assert by_key["custom-requirement-key"]["values"] == ["value"]
        assert by_key["custom-requirement-key2"]["values"] == ["value"]

    def test_request_restricts_instance_types_on_architecture(self):
        # suite_test.go:1543
        pool = nodepool(
            "default",
            requirements=[{"key": wk.LABEL_ARCH, "operator": "In", "values": ["arm64"]}],
        )
        claim = self._provision_one(pool)
        by_key = {r["key"]: r for r in claim.spec.requirements}
        assert by_key[wk.LABEL_ARCH]["values"] == ["arm64"]
        assert all("arm64" in name for name in by_key[wk.LABEL_INSTANCE_TYPE]["values"])

    def test_request_has_owner_reference(self):
        # suite_test.go:1648
        pool = nodepool("default")
        claim = self._provision_one(pool)
        [ref] = [o for o in claim.metadata.owner_references if o.kind == "NodePool"]
        assert ref.name == "default"
        assert ref.uid == pool.metadata.uid

    def test_request_propagates_node_class_ref(self):
        # suite_test.go:1666
        pool = nodepool("default")
        pool.spec.template.spec.node_class_ref.group = "karpenter.test.sh"
        pool.spec.template.spec.node_class_ref.kind = "TestNodeClass"
        pool.spec.template.spec.node_class_ref.name = "test"
        claim = self._provision_one(pool)
        ref = claim.spec.node_class_ref
        assert (ref.group, ref.kind, ref.name) == (
            "karpenter.test.sh",
            "TestNodeClass",
            "test",
        )

    def test_request_carries_resource_requests_with_daemon_overhead(self):
        # suite_test.go:1694/1720
        harness = make_provisioner_harness()
        clock, store, provider, cluster, informer, prov = harness
        store.create(nodepool("default"))
        ds = daemonset(requests={"cpu": "1"})
        store.create(ds)
        p = store.create(unschedulable_pod(requests={"cpu": "1", "memory": "1Mi"}))
        run_batch(harness, [p])
        [claim] = store.list("NodeClaim")
        assert claim.spec.resources.requests["cpu"] == pytest.approx(2.0)


class TestClaimMetadataStamping:
    """suite_test.go:1321-1394 — template annotations/labels and
    requirement-derived labels land on created claims."""

    def _claim_for(self, pool):
        harness = make_provisioner_harness()
        clock, store, provider, cluster, informer, prov = harness
        store.create(pool)
        pod = store.create(unschedulable_pod(requests={"cpu": "1"}))
        run_batch(harness, [pod])
        [claim] = store.list("NodeClaim")
        return claim

    def test_annotations_propagate(self):
        # suite_test.go:1321
        pool = nodepool("default")
        pool.spec.template.annotations[wk.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
        claim = self._claim_for(pool)
        assert claim.metadata.annotations[wk.DO_NOT_DISRUPT_ANNOTATION_KEY] == "true"

    def test_labels_propagate(self):
        # suite_test.go:1339 — template labels + single-valued In
        # requirements become labels; other operators don't
        pool = nodepool(
            "default",
            labels={"test-key-1": "test-value-1"},
            requirements=[
                {"key": "test-key-2", "operator": "In", "values": ["test-value-2"]},
                {"key": "test-key-3", "operator": "NotIn", "values": ["test-value-3"]},
            ],
        )
        claim = self._claim_for(pool)
        assert claim.metadata.labels[wk.NODEPOOL_LABEL_KEY] == "default"
        assert claim.metadata.labels["test-key-1"] == "test-value-1"
        by_key = {r["key"]: r for r in claim.spec.requirements}
        assert by_key["test-key-2"]["values"] == ["test-value-2"]
        assert by_key["test-key-3"]["operator"] == "NotIn"


class TestHealthyNodePoolScheduledTime:
    """suite_test.go:305-332 — the healthy-pool scheduled timestamp drives
    the pod-provisioning-latency SLO metric."""

    def _run(self, healthy):
        harness = make_provisioner_harness()
        clock, store, provider, cluster, informer, prov = harness
        pool = nodepool("default")
        pool.set_condition("NodeRegistrationHealthy", "True" if healthy else "False")
        store.create(pool)
        pod = store.create(unschedulable_pod(requests={"cpu": "1"}))
        run_batch(harness, [pod])
        key = (pod.metadata.namespace, pod.metadata.name)
        return key in cluster.pod_healthy_nodepool_scheduled_time

    def test_marked_when_nodepool_registration_healthy(self):
        # suite_test.go:305
        assert self._run(healthy=True) is True

    def test_not_marked_when_nodepool_registration_unhealthy(self):
        # suite_test.go:319
        assert self._run(healthy=False) is False
