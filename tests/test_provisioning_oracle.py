"""Provisioning-suite oracle specs (reference
pkg/controllers/provisioning/suite_test.go — names kept, lines cited)."""

import pytest

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import Container, ObjectMeta, Pod, PodSpec, pod_resource_requests
from karpenter_tpu.operator.options import Options
from karpenter_tpu.utils.resources import parse_resource_list

from helpers import (
    daemonset,
    daemonset_pod,
    make_provisioner_harness,
    nodepool,
    unschedulable_pod,
)
from test_scheduler import Env


def run_batch(harness, pods):
    clock, store, provider, cluster, informer, prov = harness
    for p in pods:
        prov.trigger(p.metadata.uid)
    informer.flush()
    clock.step(1.5)
    return prov.reconcile()


class TestNodeClaimCreation:
    def test_nodepool_termination_grace_period_propagates(self):
        # suite_test.go:267 — nodepool TGP lands on created claims
        harness = make_provisioner_harness()
        clock, store, provider, cluster, informer, prov = harness
        pool = nodepool("default")
        pool.spec.template.spec.termination_grace_period = 123.0
        store.create(pool)
        pod = store.create(unschedulable_pod(requests={"cpu": "1"}))
        run_batch(harness, [pod])
        [claim] = store.list("NodeClaim")
        assert claim.spec.termination_grace_period == 123.0

    def test_no_termination_grace_period_by_default(self):
        # suite_test.go:256
        harness = make_provisioner_harness()
        clock, store, provider, cluster, informer, prov = harness
        store.create(nodepool("default"))
        pod = store.create(unschedulable_pod(requests={"cpu": "1"}))
        run_batch(harness, [pod])
        [claim] = store.list("NodeClaim")
        assert claim.spec.termination_grace_period is None

    def test_deleting_nodepools_ignored(self):
        # suite_test.go:280
        harness = make_provisioner_harness()
        clock, store, provider, cluster, informer, prov = harness
        pool = nodepool("default")
        pool.metadata.finalizers.append("karpenter.sh/test")
        store.create(pool)
        store.delete(pool)  # finalizer present: deletion_timestamp set
        pod = store.create(unschedulable_pod(requests={"cpu": "1"}))
        run_batch(harness, [pod])
        assert store.list("NodeClaim") == []

    def test_unschedulable_without_valid_nodepools(self):
        # suite_test.go:291
        harness = make_provisioner_harness()
        clock, store, provider, cluster, informer, prov = harness
        pod = store.create(unschedulable_pod(requests={"cpu": "1"}))
        results = run_batch(harness, [pod])
        assert results is None or not store.list("NodeClaim")


class TestLimits:
    def test_partial_scheduling_when_limits_would_be_exceeded(self):
        # suite_test.go:726 — capacity up to the limit provisions; the rest
        # of the demand stays pending
        harness = make_provisioner_harness()
        clock, store, provider, cluster, informer, prov = harness
        store.create(nodepool("default", limits={"cpu": "20"}))
        pods = [
            store.create(unschedulable_pod(requests={"cpu": "10"})) for _ in range(5)
        ]
        run_batch(harness, pods)
        claims = store.list("NodeClaim")
        assert claims, "some capacity should provision"
        # pessimistic tracking keeps launched capacity bounded near the
        # limit; demand for all 5 pods (50 cpu) must NOT be fully provisioned
        assert len(claims) < 5


class TestSidecarResourceAccounting:
    """suite_test.go:531-685 — max(containers+sidecars, init ceiling)."""

    def _pod(self, containers, init_containers):
        pod = Pod(
            metadata=ObjectMeta(name="sc-pod"),
            spec=PodSpec(
                containers=[
                    Container(requests=parse_resource_list(c)) for c in containers
                ],
                init_containers=[
                    Container(
                        requests=parse_resource_list(c),
                        restart_policy=policy,
                    )
                    for c, policy in init_containers
                ],
            ),
        )
        return pod

    def test_init_before_sidecar(self):
        # init (3 cpu) runs before the sidecar exists: ceiling is
        # max(init, app+sidecar) = max(3, 1+2) = 3
        pod = self._pod(
            containers=[{"cpu": "1"}],
            init_containers=[({"cpu": "3"}, None), ({"cpu": "2"}, "Always")],
        )
        assert pod_resource_requests(pod)["cpu"] == pytest.approx(3.0)

    def test_sidecar_before_small_init(self):
        # sidecar (2) starts first; later init (1) runs alongside it:
        # max(2+1 init phase, 1+2 app phase) = 3
        pod = self._pod(
            containers=[{"cpu": "1"}],
            init_containers=[({"cpu": "2"}, "Always"), ({"cpu": "1"}, None)],
        )
        assert pod_resource_requests(pod)["cpu"] == pytest.approx(3.0)

    def test_sidecar_before_large_init(self):
        # later init (4) + running sidecar (2) dominates the app phase (1+2)
        pod = self._pod(
            containers=[{"cpu": "1"}],
            init_containers=[({"cpu": "2"}, "Always"), ({"cpu": "4"}, None)],
        )
        assert pod_resource_requests(pod)["cpu"] == pytest.approx(6.0)


class TestDaemonSetAccounting:
    def test_daemonset_overhead_too_large_blocks(self):
        # suite_test.go:906
        ds = daemonset(requests={"cpu": "10000"})
        env = Env(daemonset_pods=[daemonset_pod(ds)])
        results = env.schedule([unschedulable_pod(requests={"cpu": "1"})])
        assert len(results.pod_errors) == 1

    def test_pods_without_requests_schedule(self):
        # suite_test.go:1037
        env = Env()
        pod = unschedulable_pod()
        pod.spec.containers[0].requests = {}
        results = env.schedule([pod])
        assert not results.pod_errors
