"""Drift-detection oracle: specs ported from the reference's drift suite
(pkg/controllers/nodeclaim/disruption/drift_test.go:85-199 — names kept)."""

import pytest

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import Taint
from karpenter_tpu.apis.nodeclaim import CONDITION_DRIFTED, CONDITION_LAUNCHED
from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
from karpenter_tpu.controllers.nodeclaim.disruption import DisruptionController
from karpenter_tpu.controllers.nodepool_controllers import HashController
from karpenter_tpu.events.recorder import Recorder
from karpenter_tpu.runtime.store import Store
from karpenter_tpu.utils.clock import FakeClock

from helpers import node_claim_pair, nodepool


@pytest.fixture
def env():
    import copy

    clock = FakeClock()
    store = Store(clock=clock)
    provider = FakeCloudProvider()
    # the kwok catalog is memoized process-wide; these specs MUTATE instance
    # types (clearing offerings, flipping availability), so they get copies
    provider.instance_types = copy.deepcopy(provider.instance_types)
    return clock, store, provider, Recorder(clock=clock)


def launched_claim(store, pool, name="dc-1", instance_type="s-4x-amd64-linux"):
    node, claim = node_claim_pair(name, instance_type=instance_type)
    claim.set_condition(CONDITION_LAUNCHED, "True")
    claim.metadata.annotations.update(pool.metadata.annotations)
    return store.create(claim)


class TestStaleInstanceTypeDrift:
    """drift_test.go:85-131."""

    def test_drift_if_instance_type_label_missing(self, env):
        clock, store, provider, recorder = env
        pool = store.create(nodepool("default"))
        claim = launched_claim(store, pool)
        del claim.metadata.labels[wk.LABEL_INSTANCE_TYPE]
        DisruptionController(store, provider, clock).reconcile(claim)
        assert claim.get_condition(CONDITION_DRIFTED).reason == "InstanceTypeNotFound"

    def test_drift_if_instance_type_gone(self, env):
        clock, store, provider, recorder = env
        pool = store.create(nodepool("default"))
        claim = launched_claim(store, pool)
        provider.instance_types = [
            it for it in provider.instance_types if it.name != "s-4x-amd64-linux"
        ]
        DisruptionController(store, provider, clock).reconcile(claim)
        assert claim.get_condition(CONDITION_DRIFTED).reason == "InstanceTypeNotFound"

    def test_drift_if_offerings_gone(self, env):
        clock, store, provider, recorder = env
        pool = store.create(nodepool("default"))
        claim = launched_claim(store, pool)
        it = next(i for i in provider.instance_types if i.name == "s-4x-amd64-linux")
        it.offerings.clear()
        DisruptionController(store, provider, clock).reconcile(claim)
        assert claim.get_condition(CONDITION_DRIFTED).reason == "InstanceTypeNotFound"

    def test_unavailable_offerings_are_not_drift(self, env):
        # drift.go:112-114 — temporary unavailability must NOT drift
        clock, store, provider, recorder = env
        pool = store.create(nodepool("default"))
        claim = launched_claim(store, pool)
        it = next(i for i in provider.instance_types if i.name == "s-4x-amd64-linux")
        for offering in it.offerings:
            offering.available = False
        DisruptionController(store, provider, clock).reconcile(claim)
        assert not claim.condition_is_true(CONDITION_DRIFTED)

    def test_reserved_claim_demoted_to_on_demand_not_drifted(self, env):
        # drift.go:131-139 — a reserved claim whose label hasn't been updated
        # after demotion matches on-demand offerings too
        clock, store, provider, recorder = env
        pool = store.create(nodepool("default"))
        claim = launched_claim(store, pool)
        claim.metadata.labels[wk.CAPACITY_TYPE_LABEL_KEY] = wk.CAPACITY_TYPE_RESERVED
        DisruptionController(store, provider, clock).reconcile(claim)
        assert not claim.condition_is_true(CONDITION_DRIFTED)

    def test_drift_if_offerings_incompatible(self, env):
        clock, store, provider, recorder = env
        pool = store.create(nodepool("default"))
        # claim launched in a zone its type no longer offers
        claim = launched_claim(store, pool)
        claim.metadata.labels[wk.LABEL_TOPOLOGY_ZONE] = "kwok-zone-9"
        DisruptionController(store, provider, clock).reconcile(claim)
        assert claim.get_condition(CONDITION_DRIFTED).reason == "InstanceTypeNotFound"


class TestDriftPrecedence:
    """drift_test.go:133-166 — static and requirement drift outrank the
    cloud provider's own drift verdict."""

    def test_static_drift_before_cloud_provider_drift(self, env):
        clock, store, provider, recorder = env
        pool = store.create(nodepool("default"))
        HashController(store).reconcile(pool)
        claim = launched_claim(store, pool)
        provider.drifted = "CloudDriftReason"
        pool.spec.template.spec.taints = [Taint(key="new", value="x")]
        HashController(store).reconcile(pool)
        DisruptionController(store, provider, clock).reconcile(claim)
        assert claim.get_condition(CONDITION_DRIFTED).reason == "NodePoolDrifted"

    def test_requirement_drift_before_cloud_provider_drift(self, env):
        clock, store, provider, recorder = env
        pool = store.create(
            nodepool(
                "default",
                requirements=[
                    {"key": wk.LABEL_ARCH, "operator": "In", "values": ["arm64"]}
                ],
            )
        )
        claim = launched_claim(store, pool)  # labels arch=amd64
        provider.drifted = "CloudDriftReason"
        DisruptionController(store, provider, clock).reconcile(claim)
        assert claim.get_condition(CONDITION_DRIFTED).reason == "RequirementsDrifted"


class TestDriftConditionLifecycle:
    """drift_test.go:167-199."""

    def test_condition_removed_when_not_launched(self, env):
        clock, store, provider, recorder = env
        pool = store.create(nodepool("default"))
        claim = launched_claim(store, pool)
        provider.drifted = "CloudDriftReason"
        ctrl = DisruptionController(store, provider, clock)
        ctrl.reconcile(claim)
        assert claim.condition_is_true(CONDITION_DRIFTED)
        claim.set_condition(CONDITION_LAUNCHED, "False")
        ctrl.reconcile(claim)
        assert not claim.condition_is_true(CONDITION_DRIFTED)

    def test_no_drift_if_nodepool_missing(self, env):
        clock, store, provider, recorder = env
        pool = nodepool("default")  # never stored
        _, claim = node_claim_pair("dc-9")
        claim.set_condition(CONDITION_LAUNCHED, "True")
        store.create(claim)
        provider.drifted = "CloudDriftReason"
        DisruptionController(store, provider, clock).reconcile(claim)
        assert not claim.condition_is_true(CONDITION_DRIFTED)

    def test_condition_removed_when_no_longer_drifted(self, env):
        clock, store, provider, recorder = env
        pool = store.create(nodepool("default"))
        claim = launched_claim(store, pool)
        provider.drifted = "CloudDriftReason"
        ctrl = DisruptionController(store, provider, clock)
        ctrl.reconcile(claim)
        assert claim.condition_is_true(CONDITION_DRIFTED)
        provider.drifted = ""
        ctrl.reconcile(claim)
        assert not claim.condition_is_true(CONDITION_DRIFTED)
