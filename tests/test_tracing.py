"""End-to-end scheduling traces (karpenter_tpu/tracing): span core +
sampling, ring-buffer eviction order, journey assembly, trace-context
propagation across the socket transport (daemon-side spans re-join the
caller's trace), same-seed sim span-digest equality, and the
/debug/traces serving surface (200 / 404 / drill-down / slowest view)."""

import json
import urllib.error
import urllib.request

import pytest

from karpenter_tpu import tracing
from karpenter_tpu.apis import core as apicore
from karpenter_tpu.cloudprovider.kwok.provider import KwokCloudProvider
from karpenter_tpu.operator.operator import Operator
from karpenter_tpu.operator.options import Options
from karpenter_tpu.operator.serving import Server, ServingConfig
from karpenter_tpu.runtime.store import Store
from karpenter_tpu.sim.harness import run_scenario
from karpenter_tpu.solverd.api import KIND_SOLVE
from karpenter_tpu.solverd.service import SolverService
from karpenter_tpu.solverd.transport import SocketClient, SolverDaemon
from karpenter_tpu.tracing.core import Tracer
from karpenter_tpu.tracing.export import RingBufferExporter, canonical
from karpenter_tpu.tracing.journey import JourneyRecorder
from karpenter_tpu.utils.clock import Clock, FakeClock
from random import Random

from helpers import nodepool, unschedulable_pod
from test_solverd import build_scheduler


@pytest.fixture(autouse=True)
def _fresh_tracer():
    """Each test gets a clean process-global tracer (and leaves one)."""
    tracing.configure()
    yield
    tracing.configure()


class TestSpanCore:
    def test_nesting_links_parent_child(self):
        tr = Tracer(clock=FakeClock())
        with tr.span("outer") as outer:
            with tr.span("inner") as inner:
                assert inner.context.trace_id == outer.context.trace_id
                assert inner.parent_id == outer.context.span_id
        spans = tr.ring.spans()
        assert [s["name"] for s in spans] == ["inner", "outer"]
        assert spans[1]["parent"] is None

    def test_explicit_root_breaks_ambient_chain(self):
        tr = Tracer(clock=FakeClock())
        with tr.span("outer") as outer:
            with tr.span("fresh", parent=None) as fresh:
                assert fresh.context.trace_id != outer.context.trace_id
                assert fresh.parent_id is None

    def test_exception_marks_span_failed_and_reraises(self):
        tr = Tracer(clock=FakeClock())
        with pytest.raises(ValueError):
            with tr.span("boom"):
                raise ValueError("nope")
        (span,) = tr.ring.spans()
        assert span["status"] == "error"
        assert "ValueError" in span["attrs"]["error"]

    def test_timestamps_come_from_injected_clock(self):
        clock = FakeClock()
        tr = Tracer(clock=clock)
        with tr.span("timed"):
            clock.step(5.0)
        (span,) = tr.ring.spans()
        assert span["end"] - span["start"] == 5.0

    def test_sample_rate_zero_exports_nothing(self):
        tr = Tracer(clock=FakeClock(), sample_rate=0.0)
        with tr.span("dropped") as sp:
            # children of an unsampled span are unsampled too, for free
            with tr.span("child") as child:
                assert not child.sampled
            assert not sp.sampled
        assert len(tr.ring) == 0

    def test_volatile_attrs_dropped_only_in_deterministic_mode(self):
        live = Tracer(clock=FakeClock())
        with live.span("s") as sp:
            sp.set_attr(pods=3)
            sp.set_volatile(wall_s=0.123)
        assert live.ring.spans()[0]["attrs"] == {"pods": 3, "wall_s": 0.123}

        det = Tracer(clock=FakeClock(), deterministic=True)
        with det.span("s") as sp:
            sp.set_attr(pods=3)
            sp.set_volatile(wall_s=0.123)
        assert det.ring.spans()[0]["attrs"] == {"pods": 3}

    def test_seeded_uid_source_yields_identical_ids(self):
        def run():
            apicore.set_uid_source(Random("tracing-test"))
            try:
                tr = Tracer(clock=FakeClock())
                with tr.span("a"):
                    with tr.span("b"):
                        pass
                return [canonical(s) for s in tr.ring.spans()]
            finally:
                apicore.set_uid_source(None)

        assert run() == run()


class TestRingBuffer:
    def test_eviction_is_strictly_oldest_first(self):
        ring = RingBufferExporter(capacity=3)
        for i in range(5):
            ring.export({"trace": "t", "name": f"s{i}", "start": float(i)})
        assert [s["name"] for s in ring.spans()] == ["s2", "s3", "s4"]

    def test_take_trace_removes_exactly_that_trace(self):
        ring = RingBufferExporter(capacity=10)
        for i in range(4):
            ring.export(
                {"trace": "a" if i % 2 else "b", "name": f"s{i}", "start": float(i)}
            )
        taken = ring.take_trace("a")
        assert [s["name"] for s in taken] == ["s1", "s3"]
        assert [s["trace"] for s in ring.spans()] == ["b", "b"]
        assert ring.take_trace("a") == []


class TestJourneyAssembly:
    def test_stages_assemble_from_spans(self):
        rec = JourneyRecorder()
        t = "trace-1"
        rec.export({"trace": t, "name": "pod.pending", "start": 0.0, "end": 2.0,
                    "status": "ok", "attrs": {"pod": "p1"}})
        rec.export({"trace": t, "name": "solverd.queue", "start": 2.0, "end": 2.5,
                    "status": "ok", "attrs": {}})
        rec.export({"trace": t, "name": "solverd.solve", "start": 2.5, "end": 3.0,
                    "status": "ok", "attrs": {}})
        rec.export({"trace": t, "name": "nodeclaim.create", "start": 3.0,
                    "end": 3.0, "status": "ok", "attrs": {"nodeclaim": "nc1"}})
        rec.export({"trace": t, "name": "pod.schedule", "start": 3.0, "end": 3.0,
                    "status": "ok", "attrs": {"pod": "p1", "nodeclaim": "nc1"}})
        rec.export({"trace": t, "name": "nodeclaim.launch", "start": 3.0,
                    "end": 4.0, "status": "ok", "attrs": {"nodeclaim": "nc1"}})
        rec.export({"trace": t, "name": "nodeclaim.registration", "start": 4.0,
                    "end": 6.0, "status": "ok", "attrs": {"nodeclaim": "nc1"}})
        rec.export({"trace": t, "name": "pod.bind", "start": 7.0, "end": 7.0,
                    "status": "ok", "attrs": {"pod": "p1", "node": "n1"}})
        (journey,) = rec.completed()
        assert journey["pod"] == "p1"
        assert journey["nodeclaim"] == "nc1"
        assert journey["total"] == 7.0
        got = list(journey["stages"])
        assert got == ["pending", "admit", "solve", "create", "launch",
                       "registration", "bind"]
        stats = rec.stats()
        assert stats["completed"] == 1
        assert stats["stages"]["registration"]["p50"] == 2.0


    def test_same_name_different_uids_stay_separate(self):
        """Names collide across namespaces and pod lifetimes; uids never
        do — two in-flight pods named 'web-0' must not merge journeys."""
        rec = JourneyRecorder()
        for i, (trace, uid) in enumerate((("t-a", "uid-a"), ("t-b", "uid-b"))):
            rec.export({"trace": trace, "name": "pod.pending",
                        "start": float(i), "end": float(i) + 1.0,
                        "status": "ok",
                        "attrs": {"pod": "web-0", "pod_uid": uid}})
        for i, (trace, uid) in enumerate((("t-a", "uid-a"), ("t-b", "uid-b"))):
            rec.export({"trace": trace, "name": "pod.bind",
                        "start": float(i) + 2.0, "end": float(i) + 2.0,
                        "status": "ok",
                        "attrs": {"pod": "web-0", "pod_uid": uid,
                                  "node": f"n{i}"}})
        journeys = rec.completed()
        assert len(journeys) == 2
        assert {j["trace"] for j in journeys} == {"t-a", "t-b"}
        # each journey kept ITS OWN pending window
        assert journeys[0]["stages"]["pending"]["start"] == 0.0
        assert journeys[1]["stages"]["pending"]["start"] == 1.0


class _ExplodingScheduler:
    """Picklable scheduler whose solve always raises (daemon error path)."""

    engine = None

    def solve(self, pods, timeout=None):
        raise RuntimeError("boom")


class TestSocketPropagation:
    def test_daemon_spans_rejoin_callers_trace(self):
        """The acceptance-criteria linkage: a solve over the socket
        transport produces daemon-side solverd spans whose trace id is the
        CALLER's trace and whose parent is the caller's active span — the
        carrier rides the JSON frame out, the spans ride the reply home."""
        svc = SolverService(clock=Clock())
        daemon = SolverDaemon(svc, address="127.0.0.1:0").start()
        client = SocketClient(daemon.address)
        tr = tracing.tracer()
        try:
            scheduler, pods = build_scheduler(n_pods=2)
            with tr.span("provisioner.batch", parent=None) as batch:
                client.solve(KIND_SOLVE, scheduler, pods, timeout=60.0)
                trace_id = batch.context.trace_id
                caller_span = batch.context.span_id
        finally:
            client.close()
            daemon.stop()
            svc.close()
        spans = tr.ring.trace(trace_id)
        by_name = {s["name"]: s for s in spans}
        assert "solverd.solve" in by_name, [s["name"] for s in spans]
        assert "solverd.queue" in by_name
        for name in ("solverd.solve", "solverd.queue"):
            assert by_name[name]["trace"] == trace_id
            assert by_name[name]["parent"] == caller_span

    def test_failed_solve_spans_still_ship_home(self):
        """A solve that FAILS daemon-side is exactly the one a user debugs:
        the error reply must carry the daemon spans back too."""
        svc = SolverService(clock=Clock())
        daemon = SolverDaemon(svc, address="127.0.0.1:0").start()
        client = SocketClient(daemon.address)
        tr = tracing.tracer()
        try:
            with tr.span("provisioner.batch", parent=None) as batch:
                with pytest.raises(Exception):
                    client.solve(
                        KIND_SOLVE, _ExplodingScheduler(), [], timeout=60.0
                    )
                trace_id = batch.context.trace_id
        finally:
            client.close()
            daemon.stop()
            svc.close()
        solves = [
            s for s in tr.ring.trace(trace_id) if s["name"] == "solverd.solve"
        ]
        assert solves, "daemon-side solve span did not ship home on error"
        assert solves[0]["status"] == "error"

    def test_in_process_transport_propagates_context(self):
        svc = SolverService(clock=FakeClock())
        from karpenter_tpu.solverd.transport import InProcessClient

        client = InProcessClient(svc)
        tr = tracing.tracer()
        scheduler, pods = build_scheduler(n_pods=2)
        with tr.span("provisioner.batch", parent=None) as batch:
            client.solve(KIND_SOLVE, scheduler, pods, timeout=60.0)
            trace_id = batch.context.trace_id
        svc.close()
        names = {s["name"] for s in tr.ring.trace(trace_id)}
        assert {"solverd.queue", "solverd.solve"} <= names


class TestKernelTiming:
    def test_dispatch_classifies_compile_vs_execute(self):
        import jax
        import jax.numpy as jnp

        from karpenter_tpu.tracing import kernel as ktime

        @jax.jit
        def f(x):
            return x * 2.0

        with ktime.measure() as acc:
            f_x = ktime.dispatch(f, jnp.ones((4,)))  # cold: compiles
            ktime.dispatch(f, jnp.ones((4,)))  # warm: executes
        assert f_x is not None
        assert acc["dispatches"] == 2
        assert acc["compiles"] == 1
        assert acc["compile_s"] > 0.0
        assert acc["execute_s"] > 0.0

    def test_dispatch_is_transparent_outside_measure(self):
        from karpenter_tpu.tracing import kernel as ktime

        assert ktime.dispatch(lambda x: x + 1, 41) == 42

    def test_solve_span_carries_kernel_and_cache_attrs(self):
        """The LIVE (non-deterministic) tracer keeps the volatile solve
        attribution: wall compile/execute split + cache-hit deltas."""
        svc = SolverService(clock=FakeClock())
        from karpenter_tpu.solverd.transport import InProcessClient

        client = InProcessClient(svc)
        tr = tracing.tracer()
        scheduler, pods = build_scheduler(n_pods=2)
        with tr.span("provisioner.batch", parent=None) as batch:
            client.solve(KIND_SOLVE, scheduler, pods, timeout=60.0)
            trace_id = batch.context.trace_id
        svc.close()
        (solve,) = [
            s for s in tr.ring.trace(trace_id) if s["name"] == "solverd.solve"
        ]
        attrs = solve["attrs"]
        for key in ("wall_compile_s", "wall_execute_s", "kernel_dispatches",
                    "joint_cache_hits", "pack_cache_hits"):
            assert key in attrs, (key, attrs)


class TestSimDeterminism:
    TRACE = {
        "version": 1,
        "name": "tracing-mini",
        "duration": 60.0,
        "tick": 1.0,
        "nodepools": [{"name": "workers"}],
        "events": [
            {"at": 2.0, "kind": "submit", "group": "job", "count": 3,
             "pod": {"cpu": "1"}},
        ],
    }

    def test_same_seed_runs_emit_identical_span_digests(self, tmp_path):
        out1, out2 = tmp_path / "s1.jsonl", tmp_path / "s2.jsonl"
        r1 = run_scenario(dict(self.TRACE), seed=11, trace_export=str(out1))
        r2 = run_scenario(dict(self.TRACE), seed=11, trace_export=str(out2))
        t1, t2 = r1.report["tracing"], r2.report["tracing"]
        assert t1["spans"] > 0
        assert t1["span_digest"] == t2["span_digest"]
        assert out1.read_bytes() == out2.read_bytes()  # byte-identical JSONL

    def test_report_carries_per_stage_percentiles(self):
        result = run_scenario(dict(self.TRACE), seed=11)
        journeys = result.report["tracing"]["journeys"]
        assert journeys["completed"] == 3
        for stage in ("pending", "create", "launch", "registration", "bind"):
            assert journeys["stages"][stage]["p50"] is not None, stage
            assert journeys["stages"][stage]["p99"] is not None, stage

    def test_every_bound_pod_has_a_complete_journey(self, tmp_path):
        out = tmp_path / "spans.jsonl"
        result = run_scenario(dict(self.TRACE), seed=11, trace_export=str(out))
        spans = [json.loads(line) for line in out.read_text().splitlines()]
        binds = [s for s in spans if s["name"] == "pod.bind"]
        bound = {e["pod"] for e in result.log.entries("pod-bound")}
        assert {s["attrs"]["pod"] for s in binds} == bound
        for s in binds:  # no orphan spans: every bind joined a trace
            assert s["parent"] is not None, s


class TestDebugTraces:
    def _operator_with_traffic(self):
        clock = FakeClock()
        store = Store(clock=clock)
        op = Operator(
            store, KwokCloudProvider(store, clock), clock=clock,
            options=Options(),
        )
        store.create(nodepool("workers"))
        store.create(unschedulable_pod(requests={"cpu": "1"}))
        for _ in range(8):
            clock.step(2.0)
            op.run_once()
        return op

    @pytest.fixture
    def traced_server(self):
        op = self._operator_with_traffic()
        cfg = ServingConfig(
            metrics_text=lambda: "",
            healthy=lambda: True,
            ready=lambda: True,
            trace_snapshot=op.trace_snapshot,
        )
        server = Server(0, cfg, host="127.0.0.1").start()
        yield op, server
        server.stop()
        op.shutdown()

    def _get(self, server, path):
        url = f"http://127.0.0.1:{server.port}{path}"
        try:
            with urllib.request.urlopen(url, timeout=10) as resp:
                return resp.status, resp.read().decode()
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode()

    def test_index_lists_recent_traces_and_journey_stats(self, traced_server):
        op, server = traced_server
        code, body = self._get(server, "/debug/traces")
        assert code == 200
        doc = json.loads(body)
        assert doc["traces"], "expected at least one recent trace"
        assert doc["journeys"]["completed"] >= 1
        entry = doc["traces"][0]
        assert {"trace_id", "root", "spans", "errors", "duration"} <= set(entry)

    def test_trace_id_drilldown_returns_full_journey(self, traced_server):
        op, server = traced_server
        # find the batch trace that scheduled the pod
        journey = op.tracer.journeys.completed()[0]
        code, body = self._get(
            server, f"/debug/traces?trace_id={journey['trace']}"
        )
        assert code == 200
        doc = json.loads(body)
        names = {s["name"] for s in doc["spans"]}
        assert {"provisioner.batch", "pod.schedule", "nodeclaim.create",
                "pod.bind"} <= names
        assert doc["journeys"][0]["pod"] == journey["pod"]

    def test_unknown_trace_id_is_404(self, traced_server):
        _, server = traced_server
        code, body = self._get(server, "/debug/traces?trace_id=deadbeef")
        assert code == 404
        assert "unknown trace_id" in body

    def test_slowest_view(self, traced_server):
        _, server = traced_server
        code, body = self._get(server, "/debug/traces?view=slowest&limit=5")
        assert code == 200
        doc = json.loads(body)
        assert len(doc["slowest_journeys"]) >= 1
        totals = [j["total"] for j in doc["slowest_journeys"]]
        assert totals == sorted(totals, reverse=True)

    def test_without_snapshot_fn_is_404(self):
        cfg = ServingConfig(
            metrics_text=lambda: "", healthy=lambda: True, ready=lambda: True
        )
        server = Server(0, cfg, host="127.0.0.1").start()
        try:
            code, _ = self._get(server, "/debug/traces")
            assert code == 404
        finally:
            server.stop()


class TestLogCorrelation:
    def test_log_lines_inside_span_carry_trace_ids(self):
        import io
        import sys

        from karpenter_tpu.operator import logging as klog

        buf = io.StringIO()
        klog.configure("info", stream=buf)
        try:
            log = klog.logger("tracing-test")
            tr = tracing.tracer()
            with tr.span("corr") as sp:
                log.info("inside")
                trace_id, span_id = sp.context.trace_id, sp.context.span_id
            log.info("outside")
        finally:
            klog.configure("error", stream=sys.stderr)
        entries = [json.loads(line) for line in buf.getvalue().splitlines()]
        inside = next(e for e in entries if e["message"] == "inside")
        outside = next(e for e in entries if e["message"] == "outside")
        assert inside["trace_id"] == trace_id
        assert inside["span_id"] == span_id
        assert "trace_id" not in outside
