"""solverd wire-framing faults (solverd/transport.py): corrupt, torn, and
oversized frames surface as typed retryable TransportError — never a raw
JSONDecodeError — the daemon survives a poisoned connection, and the
client's reconnect-with-backoff replays through a corrupt reply."""

import json
import socket
import struct
import threading

import pytest

from karpenter_tpu.solverd import SocketClient, SolverDaemon, SolverService, TransportError
from karpenter_tpu.solverd.transport import recv_frame, send_frame
from karpenter_tpu.utils.clock import Clock


class ScriptedSocket:
    """A byte-level fault-injection 'socket': recv() drains a scripted
    buffer, then reports EOF — exactly what a peer that wrote those bytes
    and closed looks like."""

    def __init__(self, data: bytes, chunk: int = 0):
        self._buf = data
        self._chunk = chunk  # 0 = serve whatever was asked

    def recv(self, n: int) -> bytes:
        if self._chunk:
            n = min(n, self._chunk)
        out, self._buf = self._buf[:n], self._buf[n:]
        return out


def frame(payload: bytes) -> bytes:
    return struct.pack(">I", len(payload)) + payload


class TestRecvFrame:
    def test_valid_frame_roundtrip(self):
        msg = {"op": "stats", "v": 1}
        sock = ScriptedSocket(frame(json.dumps(msg).encode()))
        assert recv_frame(sock) == msg

    def test_dribbling_peer_reassembled(self):
        msg = {"op": "solve", "payload": "x" * 300}
        sock = ScriptedSocket(frame(json.dumps(msg).encode()), chunk=7)
        assert recv_frame(sock) == msg

    def test_corrupt_payload_is_typed_not_jsondecodeerror(self):
        # a bit-flipped frame: valid length prefix, garbage payload — the
        # caller's retry loops catch (OSError, TransportError), so a raw
        # JSONDecodeError would escape them and kill the operator pass
        sock = ScriptedSocket(frame(b"\xff\xfe{not json at all"))
        with pytest.raises(TransportError, match="malformed frame payload"):
            recv_frame(sock)
        try:
            recv_frame(ScriptedSocket(frame(b"{truncated")))
        except TransportError as e:
            assert not isinstance(e, json.JSONDecodeError)

    def test_clean_eof_between_frames_is_none(self):
        assert recv_frame(ScriptedSocket(b"")) is None

    def test_torn_header_mid_frame(self):
        with pytest.raises(TransportError, match="closed mid-frame"):
            recv_frame(ScriptedSocket(b"\x00\x00"))

    def test_torn_payload_mid_frame(self):
        blob = frame(b'{"op": "stats"}')[:-5]
        with pytest.raises(TransportError, match="closed mid-frame"):
            recv_frame(ScriptedSocket(blob))

    def test_oversized_length_capped(self):
        # desynced framing often reads garbage as a huge length; the cap
        # turns that into an immediate typed error instead of an OOM recv
        sock = ScriptedSocket(struct.pack(">I", (1 << 31) - 1) + b"x" * 64)
        with pytest.raises(TransportError, match="exceeds cap"):
            recv_frame(sock)


class TestDaemonSurvivesCorruptFrames:
    def _connect(self, daemon):
        host, _, port = daemon.address.rpartition(":")
        return socket.create_connection((host, int(port)), timeout=5.0)

    def test_poisoned_connection_dropped_daemon_lives(self):
        svc = SolverService(clock=Clock())
        daemon = SolverDaemon(svc, address="127.0.0.1:0").start()
        try:
            poison = self._connect(daemon)
            poison.sendall(frame(b"\x00garbage that is not json"))
            # the daemon drops the poisoned connection (EOF to us)...
            assert poison.recv(4096) == b""
            poison.close()
            # ...and keeps serving fresh connections
            client = SocketClient(daemon.address)
            try:
                assert client.stats()["transport"] == "socket"
            finally:
                client.close()
        finally:
            daemon.stop()
            svc.close()

    def test_torn_frame_then_disconnect_daemon_lives(self):
        svc = SolverService(clock=Clock())
        daemon = SolverDaemon(svc, address="127.0.0.1:0").start()
        try:
            torn = self._connect(daemon)
            torn.sendall(struct.pack(">I", 4096) + b"only-part-of-it")
            torn.close()  # mid-frame hangup
            client = SocketClient(daemon.address)
            try:
                stats = client.stats()
                assert stats.get("requests", 0) >= 0
            finally:
                client.close()
        finally:
            daemon.stop()
            svc.close()


class TestClientReplayThroughCorruptReply:
    def _evil_then_honest_server(self, replies_ok: dict):
        """One listener, two scripted connections: the first answers with a
        corrupt frame and hangs up; the second answers honestly."""
        srv = socket.create_server(("127.0.0.1", 0))
        address = f"127.0.0.1:{srv.getsockname()[1]}"

        def run():
            conn, _ = srv.accept()
            with conn:
                recv_frame(conn)
                conn.sendall(frame(b"\xde\xad corrupt reply"))
            conn2, _ = srv.accept()
            with conn2:
                recv_frame(conn2)
                send_frame(conn2, replies_ok)

        thread = threading.Thread(target=run, daemon=True)
        thread.start()
        return srv, address, thread

    def test_rpc_redials_and_replays(self):
        reply = {"ok": True, "stats": {"requests": 7}}
        srv, address, thread = self._evil_then_honest_server(reply)
        client = SocketClient(address, sleep=lambda s: None)
        try:
            got = client._rpc({"v": 1, "op": "stats"})
            assert got == reply
            assert client.reconnects == 1
        finally:
            client.close()
            srv.close()
            thread.join(timeout=5.0)

    def test_exhausted_attempts_raise_typed_error(self):
        srv = socket.create_server(("127.0.0.1", 0))
        address = f"127.0.0.1:{srv.getsockname()[1]}"

        def run():
            for _ in range(3):
                try:
                    conn, _ = srv.accept()
                except OSError:
                    return
                with conn:
                    recv_frame(conn)
                    conn.sendall(frame(b"\xff never json"))

        thread = threading.Thread(target=run, daemon=True)
        thread.start()
        client = SocketClient(address, reconnect_attempts=3, sleep=lambda s: None)
        try:
            with pytest.raises(TransportError, match="malformed frame payload"):
                client._rpc({"v": 1, "op": "stats"})
            assert client.reconnects == 2
        finally:
            client.close()
            srv.close()
            thread.join(timeout=5.0)
