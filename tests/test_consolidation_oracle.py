"""Consolidation specs ported from the reference's consolidation_test.go
(delete/replace gates, scheduling-interaction blocks, reserved offerings,
lifetime-weighted candidate order). Complements test_disruption.py's
emptiness/budget/spot-to-spot coverage."""

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import (
    Affinity,
    LabelSelector,
    PodAffinityTerm,
    PodAntiAffinity,
    TopologySpreadConstraint,
)
from karpenter_tpu.apis.nodepool import Budget
from karpenter_tpu.cloudprovider.types import (
    RESERVATION_ID_LABEL,
    InstanceType,
    Offering,
    Offerings,
)
from karpenter_tpu.scheduling.requirements import Operator, Requirement, Requirements
from karpenter_tpu.utils.resources import parse_resource_list

from helpers import nodepool, registered_node, unschedulable_pod
from test_disruption import Env


def owned_pod(requests=None, **kw):
    """A ReplicaSet-owned pod (the reference binds RS pods so they're
    reschedulable; ours are reschedulable regardless — kept for fidelity)."""
    return unschedulable_pod(requests=requests or {"cpu": "1"}, **kw)


class TestConsolidationDelete:
    """consolidation_test.go:2309-3104 — the Delete context."""

    def test_can_delete_nodes(self):
        """:2309 — two underfilled nodes merge; candidates deleted."""
        env = Env()
        np = nodepool("default")
        np.spec.disruption.budgets = [Budget(nodes="100%")]
        env.store.create(np)
        for i in range(2):
            env.add_pair(
                f"del-{i}", pods=[owned_pod()],
                instance_type="s-16x-amd64-linux",
                capacity={"cpu": "16", "memory": "64Gi", "pods": "110"},
            )
        assert env.reconcile() is True
        [cmd] = env.queue.get_commands()
        assert len(cmd.candidates) == 2

    def test_evicts_pods_without_owner_ref(self):
        """:2709 — ownerRef-less pods don't block consolidation; they are
        rescheduled like any active pod."""
        env = Env()
        np = nodepool("default")
        np.spec.disruption.budgets = [Budget(nodes="100%")]
        env.store.create(np)
        bare = unschedulable_pod(requests={"cpu": "1"})
        assert not bare.metadata.owner_references
        env.add_pair(
            "bare-0", pods=[bare],
            instance_type="s-16x-amd64-linux",
            capacity={"cpu": "16", "memory": "64Gi", "pods": "110"},
        )
        env.add_pair(
            "bare-1", pods=[owned_pod()],
            instance_type="s-16x-amd64-linux",
            capacity={"cpu": "16", "memory": "64Gi", "pods": "110"},
        )
        assert env.reconcile() is True
        [cmd] = env.queue.get_commands()
        assert len(cmd.candidates) == 2

    def test_delete_when_non_karpenter_capacity_fits(self):
        """:2424 — an unmanaged node with room counts as a rescheduling
        target, so the managed candidate can be deleted outright."""
        env = Env()
        env.store.create(nodepool("default"))
        unmanaged = registered_node(
            name="byo-node",
            capacity={"cpu": "64", "memory": "256Gi", "pods": "110"},
        )
        del unmanaged.metadata.labels[wk.NODEPOOL_LABEL_KEY]
        env.store.create(unmanaged)
        env.add_pair(
            "managed-0", pods=[owned_pod()],
            instance_type="s-16x-amd64-linux",
            capacity={"cpu": "16", "memory": "64Gi", "pods": "110"},
        )
        assert env.reconcile() is True
        [cmd] = env.queue.get_commands()
        assert cmd.decision() == "delete"
        assert [c.state_node.name() for c in cmd.candidates] == ["managed-0"]

    def test_delete_while_invalid_nodepool_exists(self):
        """:3041 — a nodepool whose requirements admit no instance type
        doesn't poison consolidation for healthy pools."""
        env = Env()
        np = nodepool("default")
        np.spec.disruption.budgets = [Budget(nodes="100%")]
        env.store.create(np)
        bad = nodepool(
            "invalid",
            requirements=[
                {"key": wk.LABEL_ARCH, "operator": "In", "values": ["s390x"]}
            ],
        )
        env.store.create(bad)
        for i in range(2):
            env.add_pair(
                f"ok-{i}", pods=[owned_pod()],
                instance_type="s-16x-amd64-linux",
                capacity={"cpu": "16", "memory": "64Gi", "pods": "110"},
            )
        assert env.reconcile() is True
        [cmd] = env.queue.get_commands()
        assert len(cmd.candidates) == 2

    def test_delete_with_permanently_pending_pod(self):
        """:2949 — a pod that can never schedule anywhere doesn't block
        consolidating unrelated nodes."""
        env = Env()
        np = nodepool("default")
        np.spec.disruption.budgets = [Budget(nodes="100%")]
        env.store.create(np)
        giant = unschedulable_pod(name="stuck", requests={"cpu": "10000"})
        env.store.create(giant)
        for i in range(2):
            env.add_pair(
                f"ok-{i}", pods=[owned_pod()],
                instance_type="s-16x-amd64-linux",
                capacity={"cpu": "16", "memory": "64Gi", "pods": "110"},
            )
        env.informer.flush()
        assert env.reconcile() is True
        [cmd] = env.queue.get_commands()
        assert len(cmd.candidates) == 2

    def test_wont_make_non_pending_pod_go_pending(self):
        """:3001 — no consolidation when the candidates' pods have nowhere
        cheaper to go (deleting would leave them pending)."""
        env = Env()
        env.store.create(nodepool("default"))
        # each node is fully used by its pod (cpu AND memory): the cheaper
        # low-memory c-family can't fit 14Gi, larger shapes cost more, and
        # the nodes are already on the cheapest capacity type (spot)
        for i in range(2):
            env.add_pair(
                f"full-{i}",
                pods=[owned_pod(requests={"cpu": "3.5", "memory": "14Gi"})],
                instance_type="s-4x-amd64-linux",
                capacity_type=wk.CAPACITY_TYPE_SPOT,
                capacity={"cpu": "4", "memory": "16Gi", "pods": "110"},
            )
        assert env.reconcile() is False
        assert env.queue.get_commands() == []

    def test_wont_delete_if_pods_land_on_uninitialized_node(self):
        """:2757 — rescheduling targets must be initialized; a command whose
        simulation uses an uninitialized node is discarded."""
        env = Env()
        env.store.create(nodepool("default"))
        node, claim = env.add_pair(
            "young-0",
            instance_type="s-32x-amd64-linux",
            capacity={"cpu": "32", "memory": "128Gi", "pods": "110"},
        )
        # strip initialization: lifecycle hasn't finished this node yet
        claim.set_condition("Initialized", "False")
        del node.metadata.labels[wk.NODE_INITIALIZED_LABEL_KEY]
        env.store.update(node)
        env.store.update(claim)
        env.add_pair(
            "old-0", pods=[owned_pod()],
            instance_type="s-16x-amd64-linux",
            capacity={"cpu": "16", "memory": "64Gi", "pods": "110"},
        )
        env.informer.flush()
        env.reconcile()
        for cmd in env.queue.get_commands():
            assert "old-0" not in [c.state_node.name() for c in cmd.candidates]

    def test_considers_initialized_nodes_before_uninitialized(self):
        """:2803 — with an initialized node offering the same room, the
        candidate IS deletable (pods target the initialized node)."""
        env = Env()
        env.store.create(nodepool("default"))
        env.add_pair(
            "ready-0",
            instance_type="s-32x-amd64-linux",
            capacity={"cpu": "32", "memory": "128Gi", "pods": "110"},
        )
        env.add_pair(
            "old-0", pods=[owned_pod()],
            instance_type="s-16x-amd64-linux",
            capacity={"cpu": "16", "memory": "64Gi", "pods": "110"},
        )
        assert env.reconcile() is True
        cmds = env.queue.get_commands()
        assert any(
            "old-0" in [c.state_node.name() for c in cmd.candidates]
            or "ready-0" in [c.state_node.name() for c in cmd.candidates]
            for cmd in cmds
        )


class TestConsolidationScheduling:
    """consolidation_test.go:4099-4233 — topology interplay."""

    def test_replace_maintains_zonal_topology_spread(self):
        """:4099 — the replacement for a spread-constrained pod is pinned to
        the candidate's zone so the spread stays satisfied."""
        env = Env()
        env.store.create(nodepool("default"))
        spread = TopologySpreadConstraint(
            topology_key=wk.LABEL_TOPOLOGY_ZONE,
            max_skew=1,
            when_unsatisfiable="DoNotSchedule",
            label_selector=LabelSelector(match_labels={"app": "spread"}),
        )
        for i, zone in enumerate(["kwok-zone-1", "kwok-zone-2", "kwok-zone-3"]):
            pod = owned_pod(
                labels={"app": "spread"}, topology_spread_constraints=[spread]
            )
            env.add_pair(
                f"zonal-{i}", pods=[pod], zone=zone,
                instance_type="s-32x-amd64-linux",
                capacity={"cpu": "32", "memory": "128Gi", "pods": "110"},
            )
        assert env.reconcile() is True
        [cmd] = env.queue.get_commands()
        assert cmd.decision() == "replace"
        [candidate] = cmd.candidates
        cand_zone = candidate.state_node.labels()[wk.LABEL_TOPOLOGY_ZONE]
        [rep] = cmd.replacements
        zone_row = rep.node_claim.requirements.get(wk.LABEL_TOPOLOGY_ZONE)
        assert set(zone_row.values_list()) == {cand_zone}

    def test_wont_delete_if_it_violates_pod_anti_affinity(self):
        """:4173 — pods with required hostname anti-affinity can't co-locate,
        so the would-be delete is rejected."""
        env = Env()
        env.store.create(nodepool("default"))
        anti = Affinity(
            pod_anti_affinity=PodAntiAffinity(
                required=[
                    PodAffinityTerm(
                        topology_key=wk.LABEL_HOSTNAME,
                        label_selector=LabelSelector(match_labels={"app": "anti"}),
                    )
                ]
            )
        )
        for i in range(2):
            pod = owned_pod(labels={"app": "anti"}, affinity=anti)
            env.add_pair(
                f"anti-{i}", pods=[pod],
                instance_type="s-16x-amd64-linux",
                capacity={"cpu": "16", "memory": "64Gi", "pods": "110"},
            )
        env.reconcile()
        # neither a delete nor a merge may co-locate the two pods: any
        # command must keep them on separate hosts (1 candidate + replacement)
        for cmd in env.queue.get_commands():
            assert len(cmd.candidates) == 1


class TestReservedConsolidation:
    """consolidation_test.go:4389 — reserved→reserved moves."""

    @staticmethod
    def reserved_types():
        def it(name, cpu, od_price, rid, res_price):
            rows = Requirements(
                Requirement(wk.LABEL_INSTANCE_TYPE, Operator.IN, [name]),
                Requirement(wk.LABEL_ARCH, Operator.IN, ["amd64"]),
                Requirement(wk.LABEL_OS, Operator.IN, ["linux"]),
                Requirement(wk.LABEL_TOPOLOGY_ZONE, Operator.IN, ["kwok-zone-1"]),
                Requirement(
                    wk.CAPACITY_TYPE_LABEL_KEY,
                    Operator.IN,
                    [wk.CAPACITY_TYPE_ON_DEMAND, wk.CAPACITY_TYPE_RESERVED],
                ),
            )

            def off(ct, price, rid=None, cap=0):
                r = [
                    Requirement(wk.CAPACITY_TYPE_LABEL_KEY, Operator.IN, [ct]),
                    Requirement(
                        wk.LABEL_TOPOLOGY_ZONE, Operator.IN, ["kwok-zone-1"]
                    ),
                ]
                if rid:
                    r.append(Requirement(RESERVATION_ID_LABEL, Operator.IN, [rid]))
                return Offering(
                    requirements=Requirements(*r), price=price, available=True,
                    reservation_capacity=cap,
                )

            return InstanceType(
                name=name,
                requirements=rows,
                offerings=Offerings(
                    [
                        off(wk.CAPACITY_TYPE_ON_DEMAND, od_price),
                        off(wk.CAPACITY_TYPE_RESERVED, res_price, rid, cap=4),
                    ]
                ),
                capacity=parse_resource_list(
                    {"cpu": str(cpu), "memory": f"{cpu * 4}Gi", "pods": "110"}
                ),
            )

        return [
            it("big-reserved", 16, 2.0, "cr-big", 1.0),
            it("small-reserved", 4, 0.6, "cr-small", 0.2),
        ]

    def test_consolidates_reserved_to_reserved(self):
        env = Env(instance_types=self.reserved_types())
        env.store.create(nodepool("default"))
        node, claim = env.add_pair(
            "res-0", pods=[owned_pod()],
            instance_type="big-reserved",
            capacity_type=wk.CAPACITY_TYPE_RESERVED,
            capacity={"cpu": "16", "memory": "64Gi", "pods": "110"},
        )
        node.metadata.labels[RESERVATION_ID_LABEL] = "cr-big"
        claim.metadata.labels[RESERVATION_ID_LABEL] = "cr-big"
        env.store.update(node)
        env.store.update(claim)
        env.informer.flush()
        assert env.reconcile() is True
        [cmd] = env.queue.get_commands()
        assert cmd.decision() == "replace"
        [rep] = cmd.replacements
        names = {it.name for it in rep.node_claim.instance_type_options}
        assert names == {"small-reserved"}
        # the replacement holds the cheaper reservation
        assert rep.node_claim.requirements.get(RESERVATION_ID_LABEL).has(
            "cr-small"
        )


class TestMinValuesConsolidation:
    """consolidation_test.go:4680 — consolidation never relaxes minValues."""

    @staticmethod
    def minvalues_types():
        def it(name, cpu, price):
            return InstanceType(
                name=name,
                requirements=Requirements(
                    Requirement(wk.LABEL_INSTANCE_TYPE, Operator.IN, [name]),
                    Requirement(wk.LABEL_ARCH, Operator.IN, ["amd64"]),
                    Requirement(wk.LABEL_OS, Operator.IN, ["linux"]),
                    Requirement(
                        wk.LABEL_TOPOLOGY_ZONE, Operator.IN, ["kwok-zone-1"]
                    ),
                    Requirement(
                        wk.CAPACITY_TYPE_LABEL_KEY,
                        Operator.IN,
                        [wk.CAPACITY_TYPE_ON_DEMAND],
                    ),
                ),
                offerings=Offerings(
                    [
                        Offering(
                            requirements=Requirements(
                                Requirement(
                                    wk.CAPACITY_TYPE_LABEL_KEY,
                                    Operator.IN,
                                    [wk.CAPACITY_TYPE_ON_DEMAND],
                                ),
                                Requirement(
                                    wk.LABEL_TOPOLOGY_ZONE,
                                    Operator.IN,
                                    ["kwok-zone-1"],
                                ),
                            ),
                            price=price,
                            available=True,
                        )
                    ]
                ),
                capacity=parse_resource_list(
                    {"cpu": str(cpu), "memory": f"{cpu * 4}Gi", "pods": "110"}
                ),
            )

        # candidate shape + exactly TWO cheaper types
        return [it("huge", 32, 4.0), it("mid", 4, 0.5), it("small", 2, 0.3)]

    def test_does_not_relax_min_values_when_best_effort(self):
        from karpenter_tpu.operator.options import Options

        opts = Options(min_values_policy="BestEffort")
        env = Env(options=opts, instance_types=self.minvalues_types())
        # minValues 3: provisioning (BestEffort) may relax, but consolidation
        # replacements must NOT — the cheaper set has only 2 distinct types
        env.store.create(
            nodepool(
                "default",
                requirements=[
                    {
                        "key": wk.LABEL_INSTANCE_TYPE,
                        "operator": "Exists",
                        "minValues": 3,
                    }
                ],
            )
        )
        env.add_pair(
            "huge-0", pods=[owned_pod()],
            instance_type="huge",
            capacity={"cpu": "32", "memory": "128Gi", "pods": "110"},
        )
        env.reconcile()
        for cmd in env.queue.get_commands():
            assert cmd.decision() != "replace"


class TestLifetimeWeightedOrder:
    """consolidation_test.go:4003 — candidates closer to expiry disrupt
    first (disruption cost scales by lifetime remaining)."""

    def test_expiring_candidate_preferred(self):
        env = Env()
        env.store.create(nodepool("default"))
        _, young = env.add_pair(
            "young-1", pods=[owned_pod()],
            instance_type="s-32x-amd64-linux",
            capacity={"cpu": "32", "memory": "128Gi", "pods": "110"},
        )
        _, dying = env.add_pair(
            "dying-1", pods=[owned_pod()],
            instance_type="s-32x-amd64-linux",
            capacity={"cpu": "32", "memory": "128Gi", "pods": "110"},
        )
        env.clock.step(90.0)
        young.spec.expire_after = 10_000.0
        dying.spec.expire_after = 100.0  # ~10% lifetime left
        env.store.update(young)
        env.store.update(dying)
        env.informer.flush()
        assert env.reconcile() is True
        [cmd] = env.queue.get_commands()
        assert "dying-1" in [c.state_node.name() for c in cmd.candidates]


class TestEmptinessEligibility:
    """emptiness_test.go — which pods keep a node non-empty."""

    def _empty_command(self, env):
        assert env.reconcile() is True
        [cmd] = env.queue.get_commands()
        return cmd

    def test_daemonset_pods_do_not_block_emptiness(self):
        """emptiness_test.go — a node with only a DaemonSet pod is empty."""
        from karpenter_tpu.apis.core import OwnerReference

        env = Env()
        env.store.create(nodepool("default"))
        ds_pod = unschedulable_pod(requests={"cpu": "100m"})
        ds_pod.metadata.owner_references.append(
            OwnerReference(kind="DaemonSet", name="ds", uid="ds-uid")
        )
        env.add_pair("empty-ds", pods=[ds_pod])
        cmd = self._empty_command(env)
        assert [c.state_node.name() for c in cmd.candidates] == ["empty-ds"]
        assert cmd.decision() == "delete"

    def test_terminating_deployment_pods_do_not_block(self):
        """A terminating (deletion-timestamped) ReplicaSet pod counts as
        gone — the node is empty."""
        env = Env()
        env.store.create(nodepool("default"))
        dying = unschedulable_pod(requests={"cpu": "1"})
        dying.metadata.deletion_timestamp = 1.0
        dying.metadata.finalizers.append("keep")
        env.add_pair("empty-term", pods=[dying])
        cmd = self._empty_command(env)
        assert [c.state_node.name() for c in cmd.candidates] == ["empty-term"]

    def test_terminating_statefulset_pod_blocks(self):
        """A terminating StatefulSet pod still needs its slot (the
        replacement can't start until it fully exits) — not empty."""
        from karpenter_tpu.apis.core import OwnerReference

        env = Env()
        env.store.create(nodepool("default"))
        sts_pod = unschedulable_pod(requests={"cpu": "1"})
        sts_pod.metadata.owner_references.append(
            OwnerReference(kind="StatefulSet", name="db", uid="sts-uid")
        )
        sts_pod.metadata.deletion_timestamp = 1.0
        sts_pod.metadata.finalizers.append("keep")
        env.add_pair("sts-node", pods=[sts_pod])
        env.reconcile()
        for cmd in env.queue.get_commands():
            # the node may consolidate via other methods but never as EMPTY
            assert cmd.decision() != "delete" or cmd.candidates[0].reschedulable_pods

    def test_do_not_disrupt_false_annotation_allows_emptiness(self):
        env = Env()
        env.store.create(nodepool("default"))
        node, claim = env.add_pair("empty-false")
        node.metadata.annotations[wk.DO_NOT_DISRUPT_ANNOTATION_KEY] = "false"
        env.store.update(node)
        env.informer.flush()
        cmd = self._empty_command(env)
        assert [c.state_node.name() for c in cmd.candidates] == ["empty-false"]


class TestDriftOrdering:
    """drift_test.go — replacement flow and candidate order."""

    def _drifted_pair(self, env, name, at, pods=()):
        from karpenter_tpu.apis.nodeclaim import CONDITION_DRIFTED

        node, claim = env.add_pair(
            name, pods=pods,
            instance_type="s-16x-amd64-linux",
            capacity={"cpu": "16", "memory": "64Gi", "pods": "110"},
        )
        claim.set_condition(CONDITION_DRIFTED, "True", now=at)
        env.store.update(claim)
        return node, claim

    def test_earliest_drift_goes_first(self):
        env = Env()
        env.store.create(nodepool("default"))
        env.clock.step(100.0)
        self._drifted_pair(env, "late-drift", at=90.0, pods=[owned_pod()])
        self._drifted_pair(env, "early-drift", at=10.0, pods=[owned_pod()])
        assert env.reconcile() is True
        [cmd] = env.queue.get_commands()
        assert [c.state_node.name() for c in cmd.candidates] == ["early-drift"]

    def test_empty_drifted_node_not_counted_against_drift_budget(self):
        """Empty drifted nodes take the emptiness path; the drift budget is
        spent on non-empty ones only."""
        from karpenter_tpu.apis.nodepool import Budget

        env = Env()
        np = nodepool("default")
        np.spec.disruption.budgets = [
            Budget(nodes="1", reasons=["Drifted"]),
            Budget(nodes="100%"),
        ]
        env.store.create(np)
        self._drifted_pair(env, "drift-empty", at=5.0)  # no pods -> emptiness
        self._drifted_pair(env, "drift-busy", at=6.0, pods=[owned_pod()])
        assert env.reconcile() is True
        # first pass wins with emptiness (method order); the empty node's
        # command must not consume the Drifted budget
        [cmd] = env.queue.get_commands()
        assert [c.state_node.name() for c in cmd.candidates] == ["drift-empty"]

    def test_drift_replacement_failure_untaints(self):
        """drift_test.go — when the replacement dies (lifecycle gave up on
        the launch), the command rolls back: candidates untainted, the
        Disrupted condition cleared, the original claim kept."""
        from karpenter_tpu.apis.nodeclaim import CONDITION_DISRUPTION_REASON

        env = Env()
        env.store.create(nodepool("default"))
        node, claim = self._drifted_pair(env, "drift-fail", at=5.0, pods=[owned_pod()])
        assert env.reconcile() is True
        [cmd] = env.queue.get_commands()
        [rep] = cmd.replacements
        # the launch failed terminally: lifecycle deleted the replacement
        env.store.delete("NodeClaim", rep.name)
        env.informer.flush()
        env.queue.reconcile()
        env.informer.flush()
        node = env.store.get("Node", "drift-fail")
        assert not any(t.key == wk.DISRUPTED_TAINT_KEY for t in node.spec.taints)
        claim = env.store.get("NodeClaim", "drift-fail-claim")
        assert not claim.condition_is_true(CONDITION_DISRUPTION_REASON)
