"""Device group solver: host-oracle parity + sharded-vs-single parity."""

import numpy as np
import pytest

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.cloudprovider.kwok.instance_types import construct_instance_types
from karpenter_tpu.ops.catalog import CatalogEngine
from karpenter_tpu.ops.packer import GroupSolver, encode_pods_for_packer
from karpenter_tpu.scheduling.requirements import Operator, Requirement, Requirements


@pytest.fixture(scope="module")
def setup():
    catalog = construct_instance_types()
    engine = CatalogEngine(catalog)
    rng = np.random.RandomState(3)
    shapes = []
    zones = ["kwok-zone-1", "kwok-zone-2", "kwok-zone-3", "kwok-zone-4"]
    for i in range(20):
        reqs = Requirements(Requirement(wk.LABEL_OS, Operator.IN, ["linux"]))
        if i % 2:
            reqs.add(Requirement(wk.LABEL_ARCH, Operator.IN, ["amd64"]))
        if i % 3 == 0:
            reqs.add(Requirement(wk.LABEL_TOPOLOGY_ZONE, Operator.IN, [zones[i % 4]]))
        shapes.append(reqs)
    picks = rng.randint(len(shapes), size=500)
    reqs_list = [shapes[i] for i in picks]
    requests = np.zeros((500, len(engine.resource_dims)), dtype=np.float64)
    cpu_d = engine.resource_dims[wk.RESOURCE_CPU]
    mem_d = engine.resource_dims[wk.RESOURCE_MEMORY]
    pods_d = engine.resource_dims[wk.RESOURCE_PODS]
    requests[:, cpu_d] = rng.choice([0.1, 0.5, 1.0, 2.0], size=500)
    requests[:, mem_d] = rng.choice([128, 512, 1024], size=500) * 2**20
    requests[:, pods_d] = 1.0
    return catalog, engine, reqs_list, requests


class TestGroupSolver:
    def test_choice_matches_host_oracle(self, setup):
        catalog, engine, reqs_list, requests = setup
        grouped = encode_pods_for_packer(engine, reqs_list, requests)
        solver = GroupSolver(engine)
        choice, feasible, nodes, unsched = solver.solve(grouped)
        assert feasible.all() and unsched.sum() == 0
        # verify each group's chosen type against the host algebra: it must
        # be feasible and cheapest among feasible
        from karpenter_tpu.scheduler.nodeclaim import _triples_host

        for g in range(min(10, grouped.membership.shape[0])):
            pod_idx = int(np.where(grouped.group_of_pod == g)[0][0])
            reqs = reqs_list[pod_idx]
            rl = {
                name: requests[pod_idx][d]
                for name, d in engine.resource_dims.items()
                if requests[pod_idx][d] > 0
            }
            triples = _triples_host(catalog, reqs, rl)
            feasible_idx = [i for i, t in enumerate(triples) if all(t)]
            assert int(choice[g]) in feasible_idx
            best_price = min(solver.price[i] for i in feasible_idx)
            assert solver.price[int(choice[g])] == pytest.approx(best_price)

    def test_sharded_matches_single_device(self, setup):
        import jax
        from jax.sharding import Mesh

        catalog, engine, reqs_list, requests = setup
        grouped = encode_pods_for_packer(engine, reqs_list, requests)
        solver = GroupSolver(engine)
        single = solver.solve(grouped)
        mesh = Mesh(np.array(jax.devices("cpu")[:8]), ("pods",))
        sharded = solver.solve_sharded(grouped, mesh)
        for a, b in zip(single, sharded):
            np.testing.assert_array_equal(a, b)

    def test_node_count_packing_math(self, setup):
        catalog, engine, reqs_list, requests = setup
        # one group: 10 pods of 2 cpu onto nodes; cheapest feasible type is
        # 1-cpu-smallest that fits 2 cpu => type cpu>=2; pods-per-node math
        reqs = Requirements(Requirement(wk.LABEL_OS, Operator.IN, ["linux"]))
        req_vec = np.zeros((10, len(engine.resource_dims)))
        req_vec[:, engine.resource_dims[wk.RESOURCE_CPU]] = 2.0
        req_vec[:, engine.resource_dims[wk.RESOURCE_PODS]] = 1.0
        grouped = encode_pods_for_packer(engine, [reqs] * 10, req_vec)
        solver = GroupSolver(engine)
        choice, feasible, nodes, unsched = solver.solve(grouped)
        assert grouped.membership.shape[0] == 1
        it = engine.instance_types[int(choice[0])]
        cpu = it.allocatable()[wk.RESOURCE_CPU]
        pods_per_node = int(cpu // 2.0)
        assert int(nodes[0]) == -(-10 // pods_per_node)

    def test_infeasible_group_reports_unschedulable(self, setup):
        catalog, engine, reqs_list, requests = setup
        reqs = Requirements(
            Requirement(wk.LABEL_TOPOLOGY_ZONE, Operator.IN, ["nonexistent-zone"])
        )
        req_vec = np.zeros((3, len(engine.resource_dims)))
        req_vec[:, engine.resource_dims[wk.RESOURCE_CPU]] = 1.0
        grouped = encode_pods_for_packer(engine, [reqs] * 3, req_vec)
        solver = GroupSolver(engine)
        choice, feasible, nodes, unsched = solver.solve(grouped)
        assert not feasible.any()
        assert unsched.sum() == 3
