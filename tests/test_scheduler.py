"""Scheduler.Solve behaviors, mirroring the reference's provisioning/
scheduling suite (scheduler.go / topology.go / nodeclaim.go specs)."""

import pytest

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import (
    Affinity,
    Container,
    ContainerPort,
    LabelSelector,
    NodeAffinity,
    NodeSelectorTerm,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    PodSpec,
    PreferredSchedulingTerm,
    Taint,
    Toleration,
    TopologySpreadConstraint,
)
from karpenter_tpu.cloudprovider.kwok.instance_types import construct_instance_types
from karpenter_tpu.events.recorder import Recorder
from karpenter_tpu.runtime.store import Store
from karpenter_tpu.scheduler.scheduler import Scheduler
from karpenter_tpu.scheduler.topology import Topology
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.state.informer import StateInformer
from karpenter_tpu.utils.clock import FakeClock

from helpers import (
    bind_pod,
    daemonset,
    daemonset_pod,
    nodepool,
    registered_node,
    unschedulable_pod,
)

CATALOG = construct_instance_types()


class Env:
    def __init__(self, node_pools=None, state_nodes=(), daemonset_pods=(), pods=(),
                 catalog=None, **scheduler_kwargs):
        self.clock = FakeClock()
        self.store = Store(clock=self.clock)
        self.cluster = Cluster(self.clock, self.store, cloud_provider=None)
        self.informer = StateInformer(self.store, self.cluster)
        self.recorder = Recorder(clock=self.clock)
        # weight order, as the provisioner delivers pools to the scheduler
        # (nodepoolutil.order_by_weight; stable for the default weight 0)
        self.node_pools = sorted(
            node_pools if node_pools is not None else [nodepool("default")],
            key=lambda np: -(np.spec.weight or 0),
        )
        for np in self.node_pools:
            self.store.create(np)
        for obj in state_nodes:
            self.store.create(obj)
        for p in pods:
            self.store.create(p)
        self.informer.flush()
        self.instance_types = {
            np.metadata.name: list(catalog or CATALOG) for np in self.node_pools
        }
        self.daemonset_pods = list(daemonset_pods)
        self.scheduler_kwargs = scheduler_kwargs

    def schedule(self, pods, timeout=60.0):
        state_nodes = self.cluster.state_nodes()
        topology = Topology(
            self.store, self.cluster, state_nodes, self.node_pools,
            self.instance_types, pods,
            preference_policy=self.scheduler_kwargs.get("preference_policy", "Respect"),
        )
        scheduler = Scheduler(
            self.store, self.node_pools, self.cluster, state_nodes, topology,
            self.instance_types, self.daemonset_pods, self.recorder, self.clock,
            **self.scheduler_kwargs,
        )
        return scheduler.solve(pods, timeout=timeout)


class TestBasicScheduling:
    def test_single_pod_new_nodeclaim(self):
        env = Env()
        results = env.schedule([unschedulable_pod(requests={"cpu": "1"})])
        assert len(results.new_node_claims) == 1
        assert not results.pod_errors
        nc = results.new_node_claims[0]
        assert len(nc.pods) == 1
        assert nc.instance_type_options

    def test_pods_pack_onto_one_claim(self):
        env = Env()
        pods = [unschedulable_pod(requests={"cpu": "1"}) for _ in range(4)]
        results = env.schedule(pods)
        assert len(results.new_node_claims) == 1
        assert len(results.new_node_claims[0].pods) == 4

    def test_huge_pod_fails(self):
        env = Env()
        results = env.schedule([unschedulable_pod(requests={"cpu": "10000"})])
        assert len(results.pod_errors) == 1
        assert "enough resources" in str(list(results.pod_errors.values())[0])

    def test_node_selector_filters_instance_types(self):
        env = Env()
        pod = unschedulable_pod(node_selector={wk.LABEL_ARCH: "arm64"})
        results = env.schedule([pod])
        [nc] = results.new_node_claims
        for it in nc.instance_type_options:
            assert it.requirements.get(wk.LABEL_ARCH).has("arm64")

    def test_incompatible_node_selector_fails(self):
        env = Env(node_pools=[nodepool("default", requirements=[
            {"key": wk.LABEL_ARCH, "operator": "In", "values": ["amd64"]}
        ])])
        pod = unschedulable_pod(node_selector={wk.LABEL_ARCH: "arm64"})
        results = env.schedule([pod])
        assert len(results.pod_errors) == 1

    def test_unknown_nodeselector_label_fails(self):
        env = Env()
        pod = unschedulable_pod(node_selector={"custom-label": "value"})
        results = env.schedule([pod])
        assert len(results.pod_errors) == 1

    def test_nodepool_custom_label_allows(self):
        env = Env(node_pools=[nodepool("default", labels={"custom-label": "value"})])
        pod = unschedulable_pod(node_selector={"custom-label": "value"})
        results = env.schedule([pod])
        assert not results.pod_errors

    def test_ffd_order_large_pods_first(self):
        env = Env()
        small = [unschedulable_pod(requests={"cpu": "100m"}) for _ in range(3)]
        large = unschedulable_pod(requests={"cpu": "200"})
        results = env.schedule(small + [large])
        # the big pod forces a large instance type; smalls ride along
        assert not results.pod_errors


class TestExistingNodes:
    def test_pod_lands_on_existing_node(self):
        node = registered_node(pool="default")
        env = Env(state_nodes=[node])
        results = env.schedule([unschedulable_pod(requests={"cpu": "1"})])
        assert len(results.new_node_claims) == 0
        [en] = [e for e in results.existing_nodes if e.pods]
        assert en.name() == node.metadata.name

    def test_full_existing_node_overflows_to_new_claim(self):
        node = registered_node(pool="default", capacity={"cpu": "2", "memory": "8Gi", "pods": "110"})
        env = Env(state_nodes=[node])
        pods = [unschedulable_pod(requests={"cpu": "1500m"}) for _ in range(2)]
        results = env.schedule(pods)
        assert len(results.new_node_claims) == 1
        assert sum(len(e.pods) for e in results.existing_nodes) == 1

    def test_existing_node_usage_respected(self):
        node = registered_node(pool="default", capacity={"cpu": "4", "memory": "16Gi", "pods": "110"})
        running = bind_pod(unschedulable_pod(requests={"cpu": "3"}), node)
        env = Env(state_nodes=[node], pods=[running])
        results = env.schedule([unschedulable_pod(requests={"cpu": "2"})])
        assert len(results.new_node_claims) == 1  # only 1 cpu left on node

    def test_tainted_node_needs_toleration(self):
        node = registered_node(pool="default", taints=[Taint(key="team", value="a")])
        env = Env(state_nodes=[node])
        results = env.schedule([unschedulable_pod()])
        assert len(results.new_node_claims) == 1  # can't use the node
        tolerant = unschedulable_pod()
        tolerant.spec.tolerations = [Toleration(key="team", value="a")]
        env2 = Env(state_nodes=[registered_node(pool="default", taints=[Taint(key="team", value="a")])])
        results2 = env2.schedule([tolerant])
        assert len(results2.new_node_claims) == 0


class TestTaints:
    def test_nodepool_taint_requires_toleration(self):
        env = Env(node_pools=[nodepool("default", taints=[Taint(key="dedicated", value="gpu")])])
        results = env.schedule([unschedulable_pod()])
        assert len(results.pod_errors) == 1
        pod = unschedulable_pod()
        pod.spec.tolerations = [Toleration(key="dedicated", operator="Exists")]
        env2 = Env(node_pools=[nodepool("default", taints=[Taint(key="dedicated", value="gpu")])])
        results2 = env2.schedule([pod])
        assert not results2.pod_errors

    def test_prefer_no_schedule_taint_relaxes(self):
        env = Env(node_pools=[nodepool("default", taints=[
            Taint(key="soft", value="x", effect="PreferNoSchedule")
        ])])
        results = env.schedule([unschedulable_pod()])
        assert not results.pod_errors


class TestNodePoolSelection:
    def test_weight_order_wins(self):
        heavy = nodepool("heavy", weight=100, labels={"pool": "heavy"})
        light = nodepool("light", weight=1, labels={"pool": "light"})
        # light listed FIRST: the scheduler must sort by weight itself
        env = Env(node_pools=[light, heavy])
        results = env.schedule([unschedulable_pod()])
        [nc] = results.new_node_claims
        assert nc.nodepool_name == "heavy"

    def test_fallback_to_compatible_pool(self):
        amd = nodepool("amd", weight=100, requirements=[
            {"key": wk.LABEL_ARCH, "operator": "In", "values": ["amd64"]}
        ])
        arm = nodepool("arm", weight=1, requirements=[
            {"key": wk.LABEL_ARCH, "operator": "In", "values": ["arm64"]}
        ])
        env = Env(node_pools=[amd, arm])
        pod = unschedulable_pod(node_selector={wk.LABEL_ARCH: "arm64"})
        results = env.schedule([pod])
        [nc] = results.new_node_claims
        assert nc.nodepool_name == "arm"

    def test_limits_exclude_pool(self):
        limited = nodepool("limited", weight=100, limits={"cpu": "1"})
        open_pool = nodepool("open", weight=1)
        env = Env(node_pools=[limited, open_pool])
        results = env.schedule([unschedulable_pod(requests={"cpu": "2"})])
        [nc] = results.new_node_claims
        assert nc.nodepool_name == "open"

    def test_limits_tracked_pessimistically_across_claims(self):
        limited = nodepool("limited", limits={"cpu": "4"})
        env = Env(node_pools=[limited])
        # Each pod needs its own node (hostports conflict)
        pods = []
        for _ in range(3):
            p = unschedulable_pod(requests={"cpu": "1"})
            p.spec.containers[0].ports = [ContainerPort(container_port=80, host_port=8080)]
            pods.append(p)
        results = env.schedule(pods)
        # 4-cpu budget and the smallest viable type is 1cpu, but subtractMax
        # subtracts the LARGEST compatible capacity -> only some pods fit
        assert len(results.pod_errors) >= 1


class TestHostPortsAndDaemons:
    def test_hostport_conflict_forces_two_nodes(self):
        env = Env()
        pods = []
        for _ in range(2):
            p = unschedulable_pod(requests={"cpu": "100m"})
            p.spec.containers[0].ports = [ContainerPort(container_port=80, host_port=8080)]
            pods.append(p)
        results = env.schedule(pods)
        assert len(results.new_node_claims) == 2

    def test_daemon_overhead_added(self):
        ds = daemonset(requests={"cpu": "1"})
        ds_pod = daemonset_pod(ds)
        env = Env(daemonset_pods=[ds_pod])
        results = env.schedule([unschedulable_pod(requests={"cpu": "1"})])
        [nc] = results.new_node_claims
        # requests include daemon overhead: 1 (daemon) + 1 (pod) + pods
        assert nc.requests["cpu"] == pytest.approx(2.0)

    def test_incompatible_daemon_not_counted(self):
        ds = daemonset(requests={"cpu": "1"})
        ds_pod = daemonset_pod(ds)
        # contradicts the nodepool's explicit arch requirement -> not counted
        ds_pod.spec.node_selector = {wk.LABEL_ARCH: "arm64"}
        env = Env(
            node_pools=[nodepool("default", requirements=[
                {"key": wk.LABEL_ARCH, "operator": "In", "values": ["amd64"]}
            ])],
            daemonset_pods=[ds_pod],
        )
        results = env.schedule([unschedulable_pod(requests={"cpu": "1"})])
        [nc] = results.new_node_claims
        assert nc.requests["cpu"] == pytest.approx(1.0)

    def test_daemon_single_required_term_not_relaxed_away(self):
        # A daemon whose ONLY required node-affinity term contradicts the
        # pool must NOT be counted (its last term is not removable,
        # reference preferences.go:70-83)
        from karpenter_tpu.apis.core import Affinity, NodeAffinity, NodeSelectorTerm
        ds = daemonset(requests={"cpu": "1"})
        ds_pod = daemonset_pod(ds)
        ds_pod.spec.affinity = Affinity(node_affinity=NodeAffinity(required=[
            NodeSelectorTerm(match_expressions=[
                {"key": wk.LABEL_ARCH, "operator": "In", "values": ["arm64"]}])]))
        env = Env(
            node_pools=[nodepool("default", requirements=[
                {"key": wk.LABEL_ARCH, "operator": "In", "values": ["amd64"]}
            ])],
            daemonset_pods=[ds_pod],
        )
        results = env.schedule([unschedulable_pod(requests={"cpu": "1"})])
        [nc] = results.new_node_claims
        assert nc.requests["cpu"] == pytest.approx(1.0)

    def test_spread_without_selector_is_inert(self):
        # nil selector matches nothing (labels.Nothing()): other pods are not
        # counted and the constraint never forces a spread
        node = registered_node(pool="default", zone="kwok-zone-1")
        existing = bind_pod(unschedulable_pod(labels={"app": "other"}, requests={"cpu": "100m"}), node)
        env = Env(state_nodes=[node], pods=[existing])
        pod = unschedulable_pod(
            requests={"cpu": "1"},
            topology_spread_constraints=[
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=wk.LABEL_TOPOLOGY_ZONE,
                    when_unsatisfiable="DoNotSchedule",
                    label_selector=None,
                )
            ],
        )
        results = env.schedule([pod])
        assert not results.pod_errors


class TestTopologySpread:
    def zone_spread_pod(self, labels=None, max_skew=1):
        return unschedulable_pod(
            labels=labels or {"app": "web"},
            topology_spread_constraints=[
                TopologySpreadConstraint(
                    max_skew=max_skew,
                    topology_key=wk.LABEL_TOPOLOGY_ZONE,
                    when_unsatisfiable="DoNotSchedule",
                    label_selector=LabelSelector(match_labels={"app": "web"}),
                )
            ],
        )

    def test_zone_spread_across_claims(self):
        env = Env()
        pods = [self.zone_spread_pod() for _ in range(4)]
        # force separate nodes via hostports
        for p in pods:
            p.spec.containers[0].ports = [ContainerPort(container_port=80, host_port=8080)]
        results = env.schedule(pods)
        assert not results.pod_errors
        zones = []
        for nc in results.new_node_claims:
            zone_req = nc.requirements.get(wk.LABEL_TOPOLOGY_ZONE)
            zones.append(tuple(zone_req.values_list()))
        # 4 kwok zones, 4 pods with maxSkew 1 -> all distinct zones
        assert len(set(zones)) == 4

    def test_hostname_spread_forces_new_nodes(self):
        env = Env()
        pods = [
            unschedulable_pod(
                labels={"app": "web"},
                topology_spread_constraints=[
                    TopologySpreadConstraint(
                        max_skew=1,
                        topology_key=wk.LABEL_HOSTNAME,
                        when_unsatisfiable="DoNotSchedule",
                        label_selector=LabelSelector(match_labels={"app": "web"}),
                    )
                ],
            )
            for _ in range(3)
        ]
        results = env.schedule(pods)
        assert not results.pod_errors
        # maxSkew 1 on hostname: pods spread 2/1 at most -> >= 2 claims
        assert len(results.new_node_claims) >= 2

    def test_existing_pods_counted_in_spread(self):
        node = registered_node(pool="default", zone="kwok-zone-1")
        existing = bind_pod(unschedulable_pod(labels={"app": "web"}, requests={"cpu": "100m"}), node)
        env = Env(state_nodes=[node], pods=[existing])
        pod = self.zone_spread_pod()
        results = env.schedule([pod])
        assert not results.pod_errors
        # zone-1 already has 1 pod; new pod must go to another zone
        if results.new_node_claims:
            zone_req = results.new_node_claims[0].requirements.get(wk.LABEL_TOPOLOGY_ZONE)
            assert "kwok-zone-1" not in zone_req.values_list()

    def test_schedule_anyway_relaxed(self):
        env = Env(node_pools=[nodepool("default", requirements=[
            {"key": wk.LABEL_TOPOLOGY_ZONE, "operator": "In", "values": ["kwok-zone-1"]}
        ])])
        pods = [
            unschedulable_pod(
                labels={"app": "web"},
                topology_spread_constraints=[
                    TopologySpreadConstraint(
                        max_skew=1,
                        topology_key=wk.LABEL_TOPOLOGY_ZONE,
                        when_unsatisfiable="ScheduleAnyway",
                        label_selector=LabelSelector(match_labels={"app": "web"}),
                    )
                ],
            )
            for _ in range(3)
        ]
        for p in pods:
            p.spec.containers[0].ports = [ContainerPort(container_port=80, host_port=8080)]
        results = env.schedule(pods)
        # only one zone available; DoNotSchedule would fail, ScheduleAnyway relaxes
        assert not results.pod_errors


class TestPodAffinity:
    def affinity_pod(self, labels=None, key=wk.LABEL_TOPOLOGY_ZONE, anti=False):
        term = PodAffinityTerm(
            topology_key=key,
            label_selector=LabelSelector(match_labels={"app": "web"}),
        )
        affinity = (
            Affinity(pod_anti_affinity=PodAntiAffinity(required=[term]))
            if anti
            else Affinity(pod_affinity=PodAffinity(required=[term]))
        )
        return unschedulable_pod(labels=labels or {"app": "web"}, affinity=affinity)

    def test_affinity_colocates(self):
        env = Env()
        pods = [self.affinity_pod() for _ in range(3)]
        results = env.schedule(pods)
        assert not results.pod_errors
        zones = set()
        for nc in results.new_node_claims:
            zones.update(nc.requirements.get(wk.LABEL_TOPOLOGY_ZONE).values_list())
        for en in results.existing_nodes:
            if en.pods:
                zones.update(en.requirements.get(wk.LABEL_TOPOLOGY_ZONE).values_list())
        assert len(zones) == 1

    def test_anti_affinity_separates_hostname(self):
        env = Env()
        pods = [self.affinity_pod(key=wk.LABEL_HOSTNAME, anti=True) for _ in range(3)]
        results = env.schedule(pods)
        assert not results.pod_errors
        assert len(results.new_node_claims) == 3

    def test_anti_affinity_zone_late_committal(self):
        env = Env()
        # Late committal (reference topology_test.go:2696-2700): the first
        # anti-affine pod's claim could collapse to ANY zone, so within one
        # batch only one zonal anti-affine pod schedules.
        pods = [self.affinity_pod(anti=True) for _ in range(5)]
        results = env.schedule(pods)
        assert len(results.pod_errors) == 4
        assert len(results.new_node_claims) == 1

    def test_inverse_anti_affinity_blocks_new_pods(self):
        # an existing pod with anti-affinity to app=web on the node's zone
        node = registered_node(pool="default", zone="kwok-zone-1")
        repeller = unschedulable_pod(
            labels={"app": "repeller"},
            affinity=Affinity(
                pod_anti_affinity=PodAntiAffinity(
                    required=[
                        PodAffinityTerm(
                            topology_key=wk.LABEL_TOPOLOGY_ZONE,
                            label_selector=LabelSelector(match_labels={"app": "web"}),
                        )
                    ]
                )
            ),
        )
        bind_pod(repeller, node)
        env = Env(state_nodes=[node], pods=[repeller])
        pod = unschedulable_pod(labels={"app": "web"})
        results = env.schedule([pod])
        assert not results.pod_errors
        # new pod must avoid kwok-zone-1
        for nc in results.new_node_claims:
            assert "kwok-zone-1" not in nc.requirements.get(wk.LABEL_TOPOLOGY_ZONE).values_list()


class TestPreferences:
    def test_preferred_node_affinity_respected_then_relaxed(self):
        env = Env()
        pod = unschedulable_pod(
            affinity=Affinity(
                node_affinity=NodeAffinity(
                    preferred=[
                        PreferredSchedulingTerm(
                            weight=1,
                            preference=NodeSelectorTerm(
                                match_expressions=[
                                    {"key": wk.LABEL_TOPOLOGY_ZONE, "operator": "In",
                                     "values": ["nonexistent-zone"]}
                                ]
                            ),
                        )
                    ]
                )
            )
        )
        results = env.schedule([pod])
        assert not results.pod_errors  # preference relaxed away

    def test_ignore_preference_policy(self):
        env = Env(preference_policy="Ignore")
        pod = unschedulable_pod(
            affinity=Affinity(
                node_affinity=NodeAffinity(
                    preferred=[
                        PreferredSchedulingTerm(
                            weight=1,
                            preference=NodeSelectorTerm(
                                match_expressions=[
                                    {"key": wk.LABEL_TOPOLOGY_ZONE, "operator": "In",
                                     "values": ["nonexistent-zone"]}
                                ]
                            ),
                        )
                    ]
                )
            )
        )
        results = env.schedule([pod])
        assert not results.pod_errors
        # with Ignore, preference was never applied, so no relaxation needed
        [nc] = results.new_node_claims
        assert "nonexistent-zone" not in nc.requirements.get(
            wk.LABEL_TOPOLOGY_ZONE
        ).values_list()

    def test_required_affinity_multiple_or_terms(self):
        env = Env()
        pod = unschedulable_pod(
            affinity=Affinity(
                node_affinity=NodeAffinity(
                    required=[
                        NodeSelectorTerm(match_expressions=[
                            {"key": wk.LABEL_TOPOLOGY_ZONE, "operator": "In",
                             "values": ["nonexistent"]}
                        ]),
                        NodeSelectorTerm(match_expressions=[
                            {"key": wk.LABEL_TOPOLOGY_ZONE, "operator": "In",
                             "values": ["kwok-zone-2"]}
                        ]),
                    ]
                )
            )
        )
        results = env.schedule([pod])
        assert not results.pod_errors
        [nc] = results.new_node_claims
        assert nc.requirements.get(wk.LABEL_TOPOLOGY_ZONE).values_list() == ["kwok-zone-2"]


class TestResults:
    def test_truncate_instance_types(self):
        env = Env()
        results = env.schedule([unschedulable_pod()])
        [nc] = results.new_node_claims
        assert len(nc.instance_type_options) > 60
        results.truncate_instance_types(60)
        assert len(results.new_node_claims[0].instance_type_options) == 60
        # cheapest kept
        prices = [
            min(o.price for o in it.offerings if o.available)
            for it in results.new_node_claims[0].instance_type_options
        ]
        assert prices == sorted(prices)

    def test_nodepool_to_pod_mapping(self):
        env = Env()
        pods = [unschedulable_pod() for _ in range(2)]
        results = env.schedule(pods)
        mapping = results.nodepool_to_pod_mapping()
        assert sum(len(v) for v in mapping.values()) == 2


class TestEngineParity:
    """The batched device path must produce byte-identical decisions to the
    host oracle (BASELINE.json decision-parity requirement)."""

    def _decisions(self, results):
        out = []
        for nc in sorted(results.new_node_claims, key=lambda n: n.hostname):
            out.append((
                nc.nodepool_name,
                sorted(it.name for it in nc.instance_type_options),
                sorted(p.metadata.name for p in nc.pods),
            ))
        errors = sorted(p.metadata.name for p in results.pod_errors)
        return out, errors

    def test_identical_decisions_with_engine(self):
        from karpenter_tpu.ops.catalog import CatalogEngine
        import karpenter_tpu.scheduler.nodeclaim as snc

        pods_spec = []
        for i in range(12):
            kwargs = {"requests": {"cpu": f"{(i % 4) + 1}"}}
            if i % 3 == 0:
                kwargs["node_selector"] = {wk.LABEL_ARCH: "arm64"}
            if i % 5 == 0:
                kwargs["node_selector"] = {wk.LABEL_OS: "linux",
                                           wk.LABEL_TOPOLOGY_ZONE: "kwok-zone-2"}
            pods_spec.append(kwargs)

        def build_pods():
            return [unschedulable_pod(name=f"p-{i}", **kw) for i, kw in enumerate(pods_spec)]

        host_results = Env().schedule(build_pods())
        engine = CatalogEngine(CATALOG)
        old_min = snc.ENGINE_MIN_CATALOG
        snc.ENGINE_MIN_CATALOG = 1  # force engine path
        try:
            engine_results = Env(engine=engine).schedule(build_pods())
        finally:
            snc.ENGINE_MIN_CATALOG = old_min
        assert self._decisions(host_results) == self._decisions(engine_results)
