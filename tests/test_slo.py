"""SLO burn-rate engine (observability/slo.py): window math, multi-window
edge-triggered breaches (fast trips before slow), per-tenant attribution,
the forced-breach spec via synthetic latency injection, /healthz hard-breach
degrade-and-recover, spec loading, and Prometheus exposition round-trips
for the karpenter_slo_* families."""

import json

import pytest

from karpenter_tpu.observability import slo
from karpenter_tpu.observability.slo import (
    BURN_CAP,
    SLOEngine,
    SLOSpec,
    Window,
    _budget_remaining,
    _burn_rate,
    default_specs,
    load_specs,
    spec_to_dict,
)
from karpenter_tpu.utils.clock import FakeClock

from test_metrics_exposition import parse_exposition


def make_engine(*specs, clock=None):
    return SLOEngine(clock=clock or FakeClock(), specs=list(specs))


FAST = Window("fast", 60.0, 14.4)
SLOW = Window("slow", 300.0, 6.0)


def ratio_spec(name="avail", objective=0.99, availability=False):
    return SLOSpec(
        name, "test objective", objective=objective,
        windows=(FAST, SLOW), availability=availability,
    )


class TestBurnMath:
    def test_burn_rate_is_error_rate_over_budget(self):
        # 5% errors against a 1% budget burns 5x
        assert _burn_rate(95, 5, 0.99) == pytest.approx(5.0)
        assert _burn_rate(100, 0, 0.99) == 0.0
        assert _burn_rate(0, 0, 0.99) == 0.0

    def test_zero_tolerance_burn_is_capped_infinite(self):
        assert _burn_rate(1000, 1, 1.0) == BURN_CAP
        assert _burn_rate(0, 0, 1.0) == 0.0

    def test_budget_remaining(self):
        # 100 events, 1% budget => 1 allowed bad; none spent => 1.0
        assert _budget_remaining(100, 0, 0.99) == pytest.approx(1.0)
        # exactly the allowance spent => 0.0
        assert _budget_remaining(99, 1, 0.99) == pytest.approx(0.0)
        # overspent goes negative
        assert _budget_remaining(90, 10, 0.99) < 0.0
        # zero tolerance: binary
        assert _budget_remaining(10, 0, 1.0) == 1.0
        assert _budget_remaining(10, 1, 1.0) == 0.0
        assert _budget_remaining(0, 0, 0.99) == 1.0


class TestEngineCore:
    def test_observe_classifies_by_threshold(self):
        clock = FakeClock()
        spec = SLOSpec("lat", "", 0.99, windows=(FAST,), threshold_s=10.0)
        eng = make_engine(spec, clock=clock)
        eng.observe("lat", 5.0)
        eng.observe("lat", 10.0)  # inclusive: at threshold is good
        eng.observe("lat", 10.1)
        series = eng._series[("lat", "")]
        assert (series.cum_good, series.cum_bad) == (2, 1)

    def test_unknown_objective_is_ignored(self):
        eng = make_engine(ratio_spec())
        eng.record("nope", good=1)
        eng.observe("nope", 1.0)
        assert ("nope", "") not in eng._series

    def test_per_tenant_attribution_feeds_aggregate_too(self):
        eng = make_engine(ratio_spec())
        eng.record("avail", good=3, tenant="gold")
        eng.record("avail", bad=1, tenant="free")
        agg = eng._series[("avail", "")]
        assert (agg.cum_good, agg.cum_bad) == (3, 1)
        assert eng._series[("avail", "gold")].cum_good == 3
        assert eng._series[("avail", "free")].cum_bad == 1
        section = eng.tenant_section("gold")
        assert section["avail"]["events"] == {"good": 3, "bad": 0}
        assert eng.tenant_section("nobody") == {}

    def test_series_prunes_to_longest_window(self):
        clock = FakeClock()
        eng = make_engine(ratio_spec(), clock=clock)
        eng.record("avail", good=1)
        clock.step(400.0)  # past the 300s slow window
        eng.record("avail", good=1)
        eng.evaluate()
        series = eng._series[("avail", "")]
        assert len(series.events) == 1  # old record pruned
        assert series.cum_good == 2  # cumulative totals survive pruning


class TestBreachEdgeTrigger:
    def test_fast_window_trips_before_slow(self):
        """The forced-breach spec: good traffic fills both windows, then a
        synthetic latency injection turns everything bad — the fast window
        saturates while the slow window is still diluted by history."""
        clock = FakeClock()
        spec = SLOSpec("lat", "", 0.99, windows=(FAST, SLOW), threshold_s=1.0)
        eng = make_engine(spec, clock=clock)
        breaches = []
        eng.subscribe(breaches.append, key="t")
        # 240s of healthy traffic at 1 observation/s
        for _ in range(240):
            eng.observe("lat", 0.1)
            eng.evaluate()
            clock.step(1.0)
        assert breaches == []
        # inject latency: every observation now blows the threshold
        fast_tripped_at = slow_tripped_at = None
        for i in range(120):
            eng.observe("lat", 30.0)
            for b in eng.evaluate():
                if b.window == "fast" and fast_tripped_at is None:
                    fast_tripped_at = i
                if b.window == "slow" and slow_tripped_at is None:
                    slow_tripped_at = i
            clock.step(1.0)
        assert fast_tripped_at is not None and slow_tripped_at is not None
        assert fast_tripped_at < slow_tripped_at, (
            "the fast-burn window must trip before the slow one"
        )

    def test_breach_fires_once_per_edge_and_again_after_recovery(self):
        clock = FakeClock()
        eng = make_engine(ratio_spec(), clock=clock)
        breaches = []
        eng.subscribe(breaches.append, key="t")
        eng.record("avail", bad=10)
        eng.evaluate()
        eng.evaluate()  # still burning: no second breach
        fast = [b for b in breaches if b.window == "fast"]
        assert len(fast) == 1
        # recovery: the bad burst ages out of the fast window
        clock.step(120.0)
        eng.record("avail", good=100)
        eng.evaluate()
        assert ("avail", "", "fast") not in eng._burning
        # a fresh burst is a fresh edge
        eng.record("avail", bad=50)
        eng.evaluate()
        fast = [b for b in breaches if b.window == "fast"]
        assert len(fast) == 2

    def test_breach_carries_burn_and_budget(self):
        eng = make_engine(ratio_spec())
        breaches = []
        eng.subscribe(breaches.append, key="t")
        eng.record("avail", good=50, bad=50)
        eng.evaluate()
        b = breaches[0]
        assert b.objective == "avail"
        assert b.burn_rate == pytest.approx(50.0)
        assert b.budget_remaining < 0.0
        d = b.to_dict()
        assert set(d) == {
            "objective", "tenant", "window", "burn_rate",
            "budget_remaining", "t",
        }

    def test_subscriber_exceptions_are_isolated(self):
        eng = make_engine(ratio_spec())
        seen = []
        eng.subscribe(lambda b: 1 / 0, key="a")
        eng.subscribe(seen.append, key="b")
        eng.record("avail", bad=5)
        eng.evaluate()  # must not raise
        assert len(seen) >= 1

    def test_subscribe_is_keyed_replace(self):
        eng = make_engine(ratio_spec())
        first, second = [], []
        eng.subscribe(first.append, key="sim")
        eng.subscribe(second.append, key="sim")
        eng.record("avail", bad=5)
        eng.evaluate()
        # both windows breach (all-bad series); only the live key sees them
        assert first == [] and len(second) == 2

    def test_zero_tolerance_objective_breaches_on_one_bad(self):
        spec = SLOSpec(
            "recompiles", "", 1.0, windows=(Window("steady", 300.0, 1.0),)
        )
        eng = make_engine(spec)
        breaches = []
        eng.subscribe(breaches.append, key="t")
        eng.record("recompiles", bad=1)
        eng.evaluate()
        assert len(breaches) == 1
        assert breaches[0].burn_rate == BURN_CAP
        assert breaches[0].budget_remaining == 0.0


class TestHardBreach:
    def test_availability_objective_burning_all_windows(self):
        clock = FakeClock()
        eng = make_engine(ratio_spec(availability=True), clock=clock)
        assert eng.hard_breached() == []
        # saturate both windows at once
        eng.record("avail", bad=100)
        eng.evaluate()
        assert eng.hard_breached() == ["avail"]
        worst = eng.worst_burning()
        assert worst["objective"] == "avail"
        assert worst["burn_rate"] == pytest.approx(100.0)  # all-bad / 1% budget
        # recover the fast window: good traffic dilutes it while the slow
        # window (longer memory) keeps burning — no longer a HARD breach
        clock.step(90.0)
        eng.record("avail", good=300)
        eng.evaluate()
        # fast window sees only the goods; slow still holds the bad burst
        # (100 bad / 400 total = 25x burn >= 6) — burning, but not hard
        assert ("avail", "", "fast") not in eng._burning
        assert ("avail", "", "slow") in eng._burning
        assert eng.hard_breached() == []

    def test_non_availability_objectives_never_hard_breach(self):
        eng = make_engine(ratio_spec(availability=False))
        eng.record("avail", bad=100)
        eng.evaluate()
        assert eng.hard_breached() == []


class TestSnapshotAndReport:
    def test_snapshot_table_and_drilldown(self):
        eng = make_engine(ratio_spec())
        eng.record("avail", good=9, bad=1, tenant="gold")
        eng.evaluate()
        snap = eng.snapshot()
        assert "avail" in snap["objectives"]
        entry = snap["objectives"]["avail"]
        assert entry["events"] == {"good": 9, "bad": 1}
        assert "fast" in entry["windows"] and "slow" in entry["windows"]
        drill = eng.snapshot(objective="avail")
        assert drill["spec"]["name"] == "avail"
        assert "gold" in drill["tenants"]
        assert eng.snapshot(objective="nope") is None

    def test_snapshot_covers_specs_with_no_events(self):
        snap = make_engine(ratio_spec()).snapshot()
        entry = snap["objectives"]["avail"]
        assert entry["compliance"] == 1.0
        assert entry["error_budget_remaining"] == 1.0

    def test_report_digest_is_replay_stable(self):
        def replay():
            clock = FakeClock()
            eng = make_engine(ratio_spec(), clock=clock)
            for _ in range(10):
                eng.record("avail", good=3, bad=1, tenant="gold")
                eng.evaluate()
                clock.step(5.0)
            return eng.report()

        a, b = replay(), replay()
        assert a == b
        assert a["digest"] == b["digest"]
        assert a["objectives"]["avail"]["tenants"]["gold"]["events"] == {
            "good": 30, "bad": 10,
        }

    def test_reset_keeps_specs_and_subscribers(self):
        eng = make_engine(ratio_spec())
        seen = []
        eng.subscribe(seen.append, key="t")
        eng.record("avail", bad=5)
        eng.evaluate()
        eng.reset()
        assert eng._series == {} and eng._burning == {}
        assert [s.name for s in eng.specs()] == ["avail"]
        eng.record("avail", bad=5)
        eng.evaluate()
        assert len(seen) >= 2  # the subscriber survived the reset


class TestSpecLoading:
    def test_default_and_off(self):
        assert load_specs("") == default_specs()
        assert load_specs("default") == default_specs()
        assert load_specs("off") == []
        names = {s.name for s in default_specs()}
        assert {"pod-bind-latency", "solverd-availability",
                "steady-recompiles"} <= names
        # exactly one availability objective in the default set
        assert sum(s.availability for s in default_specs()) == 1

    def test_json_file_round_trip(self, tmp_path):
        specs = [ratio_spec("a", availability=True),
                 SLOSpec("b", "zero", 1.0, windows=(Window("w", 10.0, 1.0),),
                         threshold_s=2.0)]
        path = tmp_path / "specs.json"
        path.write_text(json.dumps([spec_to_dict(s) for s in specs]))
        loaded = load_specs(str(path))
        assert loaded == specs


class TestExposition:
    def test_slo_families_round_trip(self):
        """karpenter_slo_* on the REAL global registry: gauges per
        objective×tenant(×window), the events/breach counters, and the
        breach-duration histogram's _bucket/+Inf/_sum/_count."""
        from karpenter_tpu.metrics import global_registry

        clock = FakeClock()
        eng = slo.engine().configure(clock=clock, specs=[ratio_spec("expo-obj")])
        try:
            eng.record("expo-obj", good=19, bad=1, tenant='ten"ant\\x')
            eng.evaluate()
            # drive a recovery so the breach-duration histogram observes
            eng.record("expo-obj", bad=100)
            eng.evaluate()
            clock.step(120.0)
            eng.record("expo-obj", good=100000)
            eng.evaluate()
            fam = parse_exposition(global_registry.expose())

            comp = fam["karpenter_slo_compliance_ratio"]
            assert comp["type"] == "gauge"
            agg = comp["samples"][
                ("karpenter_slo_compliance_ratio",
                 tuple(sorted((("objective", "expo-obj"), ("tenant", "")))))
            ]
            assert 0.0 <= agg <= 1.0
            # the escaped tenant label round-trips intact
            nasty = tuple(sorted(
                (("objective", "expo-obj"), ("tenant", 'ten"ant\\x'))
            ))
            assert ("karpenter_slo_compliance_ratio", nasty) in comp["samples"]

            burn = fam["karpenter_slo_burn_rate"]
            key = tuple(sorted(
                (("objective", "expo-obj"), ("tenant", ""), ("window", "fast"))
            ))
            assert ("karpenter_slo_burn_rate", key) in burn["samples"]

            events = fam["karpenter_slo_events_total"]
            assert events["type"] == "counter"
            good_key = tuple(sorted(
                (("objective", "expo-obj"), ("outcome", "good"))
            ))
            assert events["samples"][
                ("karpenter_slo_events_total", good_key)
            ] >= 19.0

            breaches = fam["karpenter_slo_breaches_total"]
            bkey = tuple(sorted((("objective", "expo-obj"), ("window", "fast"))))
            assert breaches["samples"][
                ("karpenter_slo_breaches_total", bkey)
            ] >= 1.0

            hist = fam["karpenter_slo_breach_duration_seconds"]
            assert hist["type"] == "histogram"
            hkey = tuple(sorted((("objective", "expo-obj"), ("window", "fast"))))
            inf = hist["samples"][
                ("karpenter_slo_breach_duration_seconds_bucket",
                 tuple(sorted(hkey + (("le", "+Inf"),))))
            ]
            count = hist["samples"][
                ("karpenter_slo_breach_duration_seconds_count", hkey)
            ]
            total = hist["samples"][
                ("karpenter_slo_breach_duration_seconds_sum", hkey)
            ]
            assert inf == count >= 1.0
            assert total > 0.0
        finally:
            slo.engine().configure(specs=default_specs())


class TestOperatorHealthzFold:
    """Satellite: /healthz folds SLO state and 503s on a hard breach of a
    configured availability objective — and recovers."""

    def _operator(self):
        from karpenter_tpu.cloudprovider.kwok.provider import KwokCloudProvider
        from karpenter_tpu.operator.operator import Operator
        from karpenter_tpu.operator.options import Options
        from karpenter_tpu.runtime.store import Store

        clock = FakeClock()
        store = Store(clock=clock)
        provider = KwokCloudProvider(store, clock)
        op = Operator(store, provider, clock=clock, options=Options())
        return clock, op

    def test_healthz_degrades_on_hard_breach_and_recovers(self):
        clock, op = self._operator()
        try:
            op.run_once()
            snap = op.health_snapshot()
            assert snap["healthy"] is True
            assert snap["slo"] == {"worst_burning": None, "hard_breached": []}
            # drive the configured availability objective into hard breach
            op.slo.record("solverd-availability", bad=100)
            op.run_once()  # the pass evaluates the engine
            snap = op.health_snapshot()
            assert snap["healthy"] is False
            assert snap["slo"]["hard_breached"] == ["solverd-availability"]
            assert snap["slo"]["worst_burning"]["objective"] == (
                "solverd-availability"
            )
            assert any("hard breach" in r for r in snap["degraded_reasons"])
            assert op.healthy() is False
            # an SLOBreach warning event was published
            assert op.recorder.calls("SLOBreach") >= 1
            # recover: good traffic ages the burst out of the fast window
            clock.step(90.0)
            op.slo.record("solverd-availability", good=100000)
            op.run_once()
            snap = op.health_snapshot()
            assert snap["slo"]["hard_breached"] == []
            assert snap["healthy"] is True
        finally:
            op.shutdown()

    def test_healthz_http_503_and_recovery(self):
        import urllib.error
        import urllib.request

        from karpenter_tpu.operator.serving import Server, ServingConfig

        clock, op = self._operator()
        server = Server(
            0,
            ServingConfig(
                metrics_text=op.metrics_text,
                healthy=op.healthy,
                ready=op.ready,
                health_snapshot=op.health_snapshot,
                slo_snapshot=op.slo_snapshot,
            ),
            host="127.0.0.1",
        ).start()

        def get(path):
            url = f"http://127.0.0.1:{server.port}{path}"
            try:
                with urllib.request.urlopen(url, timeout=10) as resp:
                    return resp.status, resp.read().decode()
            except urllib.error.HTTPError as e:
                return e.code, e.read().decode()

        try:
            op.run_once()
            assert get("/healthz")[0] == 200
            op.slo.record("solverd-availability", bad=100)
            op.run_once()
            code, body = get("/healthz")
            assert code == 503
            payload = json.loads(body)
            assert payload["slo"]["hard_breached"] == ["solverd-availability"]
            clock.step(90.0)
            op.slo.record("solverd-availability", good=100000)
            op.run_once()
            assert get("/healthz")[0] == 200
        finally:
            server.stop()
            op.shutdown()
