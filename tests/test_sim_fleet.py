"""Fleet replica-kill simulation: 3 tenant clusters on a 2-replica solverd
pool, one replica SIGKILLed mid-run — deterministic recovery, zero
double-executed solves, no SLO breach (ISSUE 10 acceptance criteria)."""

import pytest

from karpenter_tpu.sim import scenarios
from karpenter_tpu.sim import trace as tracemod
from karpenter_tpu.sim.fleet import FleetSimulation, run_fleet_scenario

SEED = 11


@pytest.fixture(scope="module")
def result():
    return run_fleet_scenario(scenarios.resolve("fleet-replica-kill", SEED), SEED)


class TestTraceSchema:
    def test_generator_is_seed_deterministic(self):
        a = scenarios.resolve("fleet-replica-kill", 3)
        b = scenarios.resolve("fleet-replica-kill", 3)
        assert a == b
        assert a["fleet"]["replicas"] == 2
        assert len(a["tenants"]) == 3

    def test_validate_rejects_bad_fleet_traces(self):
        base = scenarios.resolve("fleet-replica-kill", 1)
        bad = dict(base, fleet=dict(base["fleet"], replicas=0))
        with pytest.raises(ValueError, match="replicas"):
            tracemod.validate(bad)
        bad = dict(base, tenants=[])
        with pytest.raises(ValueError, match="tenants"):
            tracemod.validate(bad)
        bad = dict(
            base,
            fleet=dict(base["fleet"], kills=[{"at": 1.0, "replica": 7}]),
        )
        with pytest.raises(ValueError, match="unknown replica"):
            tracemod.validate(bad)
        dupe = dict(base, tenants=[base["tenants"][0], base["tenants"][0]])
        with pytest.raises(ValueError, match="duplicate"):
            tracemod.validate(dupe)

    def test_fleet_simulation_requires_fleet_section(self):
        plain = scenarios.resolve("steady-state", 1)
        with pytest.raises(ValueError, match="fleet"):
            FleetSimulation(plain, 1)


class TestReplicaKillScenario:
    def test_replica_killed_and_recovered(self, result):
        fleet = result.report["fleet"]
        assert fleet["replica_kills"] == ["replica-0"]
        replicas = {r["id"]: r for r in fleet["replicas"]}
        assert replicas["replica-0"]["killed"] is True
        assert replicas["replica-1"]["killed"] is False
        # the survivor served real post-kill traffic
        assert replicas["replica-1"]["executed"] > 0
        # at least one tenant actually rode the failover path
        assert sum(c["failovers"] for c in fleet["clients"].values()) > 0
        assert sum(c["replays"] for c in fleet["clients"].values()) > 0
        # ... and its client-side breaker took the dead replica out
        assert any(
            c["breakers"]["replica-0"] == "open"
            for c in fleet["clients"].values()
        )

    def test_zero_double_executed_solves(self, result):
        audit = result.report["fleet"]["double_executed"]
        assert audit == {
            "same_replica": 0,
            "cross_replica": 0,
            "total": 0,
            "audit_overflow": False,
        }

    def test_no_slo_breach_for_any_tenant(self, result):
        for name, report in result.report["tenants"].items():
            slo = report["slo"]
            assert slo["pods_submitted"] > 0, name
            assert slo["pods_never_bound"] == 0, (
                f"tenant {name} stranded {slo['pods_never_bound']} pods "
                f"after the replica kill"
            )

    def test_surviving_replica_zero_steady_recompiles(self, result):
        assert result.report["kernels"]["steady_recompiles"] == 0

    def test_per_tenant_slo_sections_present(self, result):
        """Every tenant's report carries its SLO-engine section (the shape
        the ~100-cell macrobench scales to): burn-rate windows, budget
        remaining, and per-objective events attributed by tenant tag."""
        for name, report in result.report["tenants"].items():
            objectives = report["slo"]["objectives"]
            assert "solverd-failover" in objectives, name
            assert "pod-bind-latency" in objectives, name
            for entry in objectives.values():
                assert {"events", "compliance", "error_budget_remaining",
                        "windows"} <= set(entry)
        # the pool-level section carries the same tenants
        pool = result.report["slo"]["objectives"]
        assert set(pool["solverd-failover"]["tenants"]) == set(
            result.report["tenants"]
        )
        assert result.report["slo"]["digest"]

    def test_failovers_recorded_per_tenant(self, result):
        """The kill forces failovers: at least one tenant's failover
        objective saw bad events, and the aggregate series folds them."""
        agg = result.report["slo"]["objectives"]["solverd-failover"]
        assert agg["events"]["bad"] >= 1
        by_tenant = sum(
            entry["events"]["bad"]
            for entry in agg["tenants"].values()
        )
        assert by_tenant == agg["events"]["bad"]

    def test_flight_section_digest_stable(self, result):
        flight = result.report["flight"]
        assert flight["frames_recorded"] > 0
        assert flight["ring_digest"].startswith("sha256:")

    def test_kill_event_in_merged_log(self, result):
        kills = result.log.entries("replica-kill")
        assert len(kills) == 1
        assert kills[0]["replica"] == "replica-0"
        # tenant streams are tagged in the merged log
        tenants = {
            e.get("tenant")
            for e in result.log.entries("pod-submitted")
        }
        assert tenants == {"tenant-web", "tenant-batch", "tenant-ml"}

    def test_deterministic_report_and_digest(self, result):
        again = run_fleet_scenario(
            scenarios.resolve("fleet-replica-kill", SEED), SEED
        )
        assert again.digest == result.digest
        assert again.report == result.report

    def test_different_seed_different_digest(self, result):
        other = run_fleet_scenario(
            scenarios.resolve("fleet-replica-kill", SEED + 1), SEED + 1
        )
        assert other.digest != result.digest
