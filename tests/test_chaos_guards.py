"""Chaos/regression guards (reference test/suites/regression/chaos_test.go
runaway-launch detection and scheduling_benchmark_test.go:58 MinPodsPerSec).
"""

import time

from karpenter_tpu.cloudprovider.kwok.provider import KwokCloudProvider
from karpenter_tpu.operator.operator import Operator
from karpenter_tpu.runtime.store import Store
from karpenter_tpu.utils.clock import FakeClock

from helpers import nodepool, unschedulable_pod
from test_scheduler import Env


class TestRunawayLaunchGuard:
    """chaos_test.go: a pod that can never schedule must not cause the
    operator to launch nodes without bound across reconcile passes."""

    def test_unsatisfiable_pod_launches_nothing(self):
        clock = FakeClock()
        store = Store(clock=clock)
        op = Operator(store, KwokCloudProvider(store, clock), clock=clock)
        store.create(nodepool("workers"))
        store.create(unschedulable_pod(requests={"cpu": "99999"}))
        for _ in range(30):
            clock.step(2.0)
            op.run_once()
        assert store.list("NodeClaim") == []
        assert store.list("Node") == []

    def test_satisfied_demand_stops_launching(self):
        """Once pods bind, further passes must not keep creating claims."""
        clock = FakeClock()
        store = Store(clock=clock)
        op = Operator(store, KwokCloudProvider(store, clock), clock=clock)
        store.create(nodepool("workers"))
        for _ in range(5):
            store.create(unschedulable_pod(requests={"cpu": "1"}))
        for _ in range(12):
            clock.step(2.0)
            op.run_once()
        settled = len(store.list("NodeClaim"))
        assert settled >= 1
        for _ in range(20):
            clock.step(2.0)
            op.run_once()
        assert len(store.list("NodeClaim")) == settled


class TestThroughputFloor:
    """The reference CI asserts a 100 pods/sec scheduler floor
    (scheduling_benchmark_test.go:58). The device fast path runs orders of
    magnitude above it; this guard is deliberately lenient (10x the
    reference floor at a fraction of bench scale) so it only trips on
    catastrophic regressions, never on machine noise."""

    def test_device_path_beats_reference_floor(self):
        from karpenter_tpu.cloudprovider.kwok.instance_types import (
            construct_instance_types,
        )
        from karpenter_tpu.ops.catalog import CatalogEngine

        catalog = construct_instance_types()
        env = Env(catalog=catalog, engine=CatalogEngine(catalog))
        pods = [unschedulable_pod(requests={"cpu": "500m"}) for _ in range(2000)]
        env.schedule(pods)  # warm: compile + caches
        start = time.perf_counter()
        results = env.schedule(pods)
        elapsed = time.perf_counter() - start
        assert not results.pod_errors
        pods_per_sec = len(pods) / elapsed
        assert pods_per_sec > 1000, f"scheduler throughput {pods_per_sec:.0f} pods/s"
