"""Unit specs for the device-resident topology count tensors
(ops/topo_counts.py): vocabulary interning, scatter-add updates, the
generation sync contract with the host TopologyGroup oracle, rollback
freshness, and gate-vs-oracle agreement on randomized count states."""

import random

import numpy as np
import pytest

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import LabelSelector, ObjectMeta, Pod, PodSpec
from karpenter_tpu.ops.encoding import DomainVocab
from karpenter_tpu.ops.packer import scatter_add_counts
from karpenter_tpu.ops.topo_counts import (
    AntiGate,
    GroupCounts,
    HostAffinityGate,
    SpreadGate,
    build_gate,
)
from karpenter_tpu.scheduler.topology import (
    MAX_SKEW_UNBOUNDED,
    TYPE_AFFINITY,
    TYPE_ANTI_AFFINITY,
    TYPE_SPREAD,
    TopologyDomainGroup,
    TopologyGroup,
)
from karpenter_tpu.scheduling.requirements import Operator, Requirement

ZONES = ["z1", "z2", "z3", "z4"]


def make_pod(labels=None):
    return Pod(
        metadata=ObjectMeta(name="p", uid="uid-p", labels=labels or {"app": "a"}),
        spec=PodSpec(),
    )


def make_group(type_=TYPE_SPREAD, key=wk.LABEL_TOPOLOGY_ZONE, max_skew=1,
               min_domains=None, domains=ZONES):
    dg = TopologyDomainGroup()
    for d in domains:
        dg.insert(d, [])
    tg = TopologyGroup(
        type_,
        key,
        make_pod(),
        {"default"},
        LabelSelector(match_labels={"app": "a"}),
        max_skew if type_ == TYPE_SPREAD else MAX_SKEW_UNBOUNDED,
        min_domains,
        None,
        None,
        dg,
    )
    return tg


class TestScatterAdd:
    def test_accumulates_duplicates(self):
        counts = np.zeros(4, dtype=np.int64)
        counts = scatter_add_counts(counts, [1, 1, 3])
        assert counts.tolist() == [0, 2, 0, 1]

    def test_grows_past_capacity(self):
        counts = np.zeros(2, dtype=np.int64)
        counts = scatter_add_counts(counts, [5])
        assert len(counts) >= 6 and counts[5] == 1

    def test_empty_batch_is_noop(self):
        counts = np.ones(2, dtype=np.int64)
        assert scatter_add_counts(counts, []) is counts


class TestDomainVocab:
    def test_ids_are_stable_and_append_only(self):
        v = DomainVocab()
        a = v.id("z1")
        b = v.id("z2")
        assert (a, b) == (0, 1)
        assert v.id("z1") == a  # re-intern keeps the slot
        assert v.lookup("z3") is None
        assert len(v) == 2


class TestGroupCounts:
    def test_mirrors_host_counts(self):
        tg = make_group()
        tg.record("z1", "z1", "z2")
        gc = GroupCounts(tg)
        assert gc.count("z1") == 2
        assert gc.count("z2") == 1
        assert gc.count("z3") == 0  # seeded empty domain
        assert gc.count("nope") == -1

    def test_record_keeps_generations_aligned(self):
        tg = make_group()
        gc = GroupCounts(tg)
        gc.record("z1")
        gc.record("z1", "z2")
        assert gc.synced_gen == tg._gen
        assert gc.count("z1") == tg.domains["z1"] == 2
        assert "z1" not in tg.empty_domains

    def test_out_of_band_mutation_resyncs(self):
        tg = make_group()
        gc = GroupCounts(tg)
        tg.record("z4")  # host oracle path, tensor not told
        assert gc.synced_gen != tg._gen
        gc.fresh()
        assert gc.count("z4") == 1
        assert gc.synced_gen == tg._gen

    def test_tensor_export(self):
        tg = make_group()
        tg.record("z2")
        gc = GroupCounts(tg)
        t = gc.tensor()
        assert t.dtype == np.int64
        assert t[gc.vocab.lookup("z2")] == 1
        assert t.min() >= 0  # absent domains export as 0, not -1

    def test_restore_counts_freshens_generations(self):
        from karpenter_tpu.runtime.store import Store
        from karpenter_tpu.state.cluster import Cluster
        from karpenter_tpu.utils.clock import FakeClock
        from karpenter_tpu.scheduler.topology import Topology

        clock = FakeClock()
        store = Store(clock=clock)
        cluster = Cluster(clock, store, cloud_provider=None)
        topo = Topology(store, cluster, [], [], {}, [])
        tg = make_group()
        topo.topology_groups[("k",)] = tg
        snap = topo.snapshot_counts()
        gc = GroupCounts(tg)
        gc.record("z1")
        gen_before = tg._gen
        topo.restore_counts(snap)
        assert tg.domains["z1"] == 0  # rolled back
        assert tg._gen != gen_before  # fresh stamp: tensors cannot alias
        assert gc.synced_gen != tg._gen
        gc.fresh()
        assert gc.count("z1") == 0


def _exists():
    return Requirement("x", Operator.EXISTS)


class TestGatesMatchOracle:
    """The gates must answer exactly what `tg.get(pod, pod_dom, In[z]).has(z)`
    answers, across randomized count states (the whole-solve guarantee is
    the parity fuzz; this pins the per-gate contract)."""

    @pytest.mark.parametrize("seed", range(20))
    def test_spread_gate(self, seed):
        rng = random.Random(seed)
        tg = make_group(max_skew=rng.choice([1, 2, 3]),
                        min_domains=rng.choice([None, 2, 5]))
        pod = make_pod()
        pod_dom = (
            _exists()
            if rng.random() < 0.5
            else Requirement(tg.key, Operator.IN, rng.sample(ZONES, rng.randint(1, 4)))
        )
        gate = SpreadGate(GroupCounts(tg), pod_dom, tg.selects(pod))
        for _ in range(30):
            gate.gc.record(rng.choice(ZONES))
            z = rng.choice(ZONES + ["unknown"])
            node_row = Requirement(tg.key, Operator.IN, [z])
            want = tg.get(pod, pod_dom, node_row).has(z)
            assert gate.ok(gate.intern(z)) == want, (z, tg.domains)

    @pytest.mark.parametrize("seed", range(10))
    def test_anti_gate(self, seed):
        rng = random.Random(seed)
        tg = make_group(type_=TYPE_ANTI_AFFINITY)
        pod = make_pod()
        pod_dom = (
            _exists()
            if rng.random() < 0.5
            else Requirement(tg.key, Operator.IN, rng.sample(ZONES, rng.randint(1, 4)))
        )
        gate = AntiGate(GroupCounts(tg), pod_dom, tg.selects(pod))
        for _ in range(20):
            if rng.random() < 0.5:
                gate.gc.record(rng.choice(ZONES))
            z = rng.choice(ZONES)
            node_row = Requirement(tg.key, Operator.IN, [z])
            want = tg.get(pod, pod_dom, node_row).has(z)
            assert gate.ok(gate.intern(z)) == want

    @pytest.mark.parametrize("seed", range(10))
    def test_affinity_gate(self, seed):
        rng = random.Random(seed)
        tg = make_group(type_=TYPE_AFFINITY)
        pod = make_pod()
        pod_dom = (
            _exists()
            if rng.random() < 0.5
            else Requirement(tg.key, Operator.IN, rng.sample(ZONES, rng.randint(1, 4)))
        )
        gate = build_gate(GroupCounts(tg), pod_dom, tg.selects(pod), pod)
        for _ in range(20):
            if rng.random() < 0.6:
                gate.gc.record(rng.choice(ZONES))
            z = rng.choice(ZONES)
            node_row = Requirement(tg.key, Operator.IN, [z])
            want = tg.get(pod, pod_dom, node_row).has(z)
            assert gate.ok_with_row(gate.intern(z), z, node_row) == want

    @pytest.mark.parametrize("seed", range(10))
    def test_hostname_affinity_gate(self, seed):
        rng = random.Random(seed)
        hosts = [f"h{i}" for i in range(4)]
        tg = make_group(type_=TYPE_AFFINITY, key=wk.LABEL_HOSTNAME, domains=hosts)
        pod = make_pod()
        pod_dom = (
            _exists()
            if rng.random() < 0.5
            else Requirement(tg.key, Operator.IN, rng.sample(hosts, rng.randint(1, 4)))
        )
        gate = HostAffinityGate(tg, pod_dom, tg.selects(pod))
        for _ in range(20):
            if rng.random() < 0.5:
                tg.record(rng.choice(hosts))
            h = rng.choice(hosts + ["h-new"])
            node_row = Requirement(tg.key, Operator.IN, [h])
            want = tg.get(pod, pod_dom, node_row).has(h)
            assert gate.ok(h) == want
