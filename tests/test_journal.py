"""Write-ahead intent journal (runtime/journal.py): frame format, torn-tail
and mid-file corruption discipline, in-memory degrade, compaction via
tmp+os.replace, replay determinism, crash barriers, and the Prometheus
round-trip for the karpenter_journal_* counters."""

import glob
import hashlib
import os
import struct

import pytest

from karpenter_tpu.metrics.registry import global_registry
from karpenter_tpu.runtime import journal as journal_mod
from karpenter_tpu.runtime.journal import (
    BARRIER_POST_EFFECT,
    BARRIER_POST_INTENT,
    BARRIER_PRE_INTENT,
    JOURNAL_FILE,
    MAGIC,
    Journal,
    OperatorCrash,
    _encode,
)
from karpenter_tpu.utils.clock import FakeClock

from test_metrics_exposition import parse_exposition


def journal_at(tmp_path):
    return Journal(str(tmp_path), clock=FakeClock())


class TestFrameFormat:
    def test_roundtrip_and_pending(self, tmp_path):
        j = journal_at(tmp_path)
        s1 = j.intent("nodeclaim.launch", uid="u1", key="k1", nodeclaim="c1")
        s2 = j.intent("nodeclaim.delete", uid="u2", provider_id="kwok://n2")
        j.done(s1, provider_id="kwok://n1")
        j.close()
        reloaded = journal_at(tmp_path)
        pending = reloaded.pending()
        assert [r["seq"] for r in pending] == [s2]
        assert pending[0]["action"] == "nodeclaim.delete"
        assert pending[0]["provider_id"] == "kwok://n2"
        # sequence numbers continue past everything already on disk
        assert reloaded.intent("pod.bind", uid="u3") == s2 + 1

    def test_frame_layout_is_length_digest_payload(self, tmp_path):
        j = journal_at(tmp_path)
        j.intent("nodeclaim.launch", uid="u1")
        j.close()
        blob = (tmp_path / JOURNAL_FILE).read_bytes()
        assert blob.startswith(MAGIC)
        (length,) = struct.unpack_from(">I", blob, len(MAGIC))
        digest = blob[len(MAGIC) + 4 : len(MAGIC) + 36]
        payload = blob[len(MAGIC) + 36 : len(MAGIC) + 36 + length]
        assert hashlib.sha256(payload).digest() == digest
        assert len(blob) == len(MAGIC) + 36 + length

    def test_fresh_boot_is_not_recovering(self, tmp_path):
        j = journal_at(tmp_path)
        assert not j.recovering()
        # pending intents written by THIS incarnation don't flip it either
        j.intent("nodeclaim.launch", uid="u1")
        assert not j.recovering()

    def test_reboot_with_pending_is_recovering(self, tmp_path):
        j = journal_at(tmp_path)
        j.intent("nodeclaim.launch", uid="u1")
        j.close()
        reloaded = journal_at(tmp_path)
        assert reloaded.recovering()
        reloaded.mark_recovered()
        assert not reloaded.recovering()


class TestCorruption:
    def test_torn_tail_truncated_on_open(self, tmp_path):
        j = journal_at(tmp_path)
        s1 = j.intent("nodeclaim.launch", uid="u1")
        j.intent("nodeclaim.launch", uid="u2")
        j.done(s1)
        j.close()
        path = tmp_path / JOURNAL_FILE
        good = path.read_bytes()
        # a crash mid-append: half a frame lands
        torn = _encode({"type": "intent", "seq": 99, "action": "x"})[: 17]
        path.write_bytes(good + torn)
        reloaded = journal_at(tmp_path)
        assert reloaded.frame()["truncated_frames"] == 1
        assert [r["uid"] for r in reloaded.pending()] == ["u2"]
        # the truncation is durable: the file shrank back to the good bytes
        assert path.read_bytes() == good
        reloaded.close()
        again = journal_at(tmp_path)
        assert again.frame()["truncated_frames"] == 0

    def test_checksum_mismatch_stops_replay_at_last_good_frame(self, tmp_path):
        frames = [
            _encode({"type": "intent", "seq": n, "action": "nodeclaim.launch",
                     "uid": f"u{n}", "key": "", "pass": 1, "ts": 0.0})
            for n in (1, 2, 3)
        ]
        corrupt = bytearray(frames[1])
        corrupt[40] ^= 0xFF  # flip a payload byte; the sha256 no longer matches
        path = tmp_path / JOURNAL_FILE
        path.write_bytes(MAGIC + frames[0] + bytes(corrupt) + frames[2])
        j = journal_at(tmp_path)
        # replay stops at the last provably-good frame: u1 survives, u2 is
        # the corrupt frame, u3 (good bytes AFTER the corruption) must NOT
        # be trusted — the log is only valid up to the first bad frame
        assert [r["uid"] for r in j.pending()] == ["u1"]
        assert j.frame()["truncated_frames"] == 1
        assert path.read_bytes() == MAGIC + frames[0]

    def test_bad_magic_starts_fresh(self, tmp_path):
        path = tmp_path / JOURNAL_FILE
        path.write_bytes(b"not a journal at all")
        j = journal_at(tmp_path)
        assert j.pending() == []
        assert j.frame()["truncated_frames"] == 1
        j.intent("nodeclaim.launch", uid="u1")
        j.close()
        assert [r["uid"] for r in journal_at(tmp_path).pending()] == ["u1"]

    def test_oversized_length_treated_as_corrupt(self, tmp_path):
        path = tmp_path / JOURNAL_FILE
        path.write_bytes(MAGIC + struct.pack(">I", 1 << 30) + b"\x00" * 40)
        j = journal_at(tmp_path)
        assert j.pending() == []
        assert j.frame()["truncated_frames"] == 1


class TestDegrade:
    def test_unwritable_dir_degrades_to_memory(self, tmp_path):
        blocker = tmp_path / "not-a-dir"
        blocker.write_text("a file where the journal dir should be")
        j = Journal(str(blocker / "sub"), clock=FakeClock())
        assert j.frame()["mode"] == "memory"
        # the journal still works, it just lost crash durability
        seq = j.intent("nodeclaim.launch", uid="u1")
        assert [r["seq"] for r in j.pending()] == [seq]
        j.done(seq)
        assert j.pending() == []

    def test_append_failure_mid_flight_degrades(self, tmp_path):
        j = journal_at(tmp_path)
        j.intent("nodeclaim.launch", uid="u1")
        assert j.frame()["mode"] == "file"

        class BrokenFh:
            def write(self, data):
                raise OSError("disk full")

            def close(self):
                pass

        j._fh.close()
        j._fh = BrokenFh()
        seq = j.intent("nodeclaim.launch", uid="u2")
        frame = j.frame()
        assert frame["mode"] == "memory"
        assert frame["write_errors"] == 1
        # the in-memory record is intact even though the disk write failed
        assert seq in [r["seq"] for r in j.pending()]
        # further appends don't raise and don't re-count
        j.done(seq)
        assert j.frame()["write_errors"] == 1

    def test_memory_journal_without_dir(self):
        j = Journal("", clock=FakeClock())
        assert j.frame()["mode"] == "memory"
        assert j.snapshot()["path"] is None
        seq = j.intent("pod.bind", uid="u1")
        j.failed(seq, error="x")
        assert j.pending() == []


class TestCompaction:
    def test_compact_keeps_only_pending(self, tmp_path):
        j = journal_at(tmp_path)
        keep = j.intent("nodeclaim.launch", uid="keep")
        for i in range(20):
            j.done(j.intent("nodeclaim.launch", uid=f"drop-{i}"))
        j.compact()
        assert j.frame()["compactions"] == 1
        assert not glob.glob(str(tmp_path / "*.tmp.*"))
        j.close()
        reloaded = journal_at(tmp_path)
        assert [r["seq"] for r in reloaded.pending()] == [keep]
        assert reloaded.snapshot()["records"] == 1
        # appends after a compaction land in the rewritten file
        reloaded.intent("nodeclaim.launch", uid="after")
        reloaded.close()
        assert [r["uid"] for r in journal_at(tmp_path).pending()] == ["keep", "after"]

    def test_resolved_threshold_triggers_compaction(self, tmp_path, monkeypatch):
        monkeypatch.setattr(journal_mod, "COMPACT_THRESHOLD", 4)
        j = journal_at(tmp_path)
        for i in range(4):
            j.done(j.intent("nodeclaim.launch", uid=f"u{i}"))
        assert j.frame()["compactions"] >= 1

    def test_concurrent_writer_tmp_is_per_writer(self, tmp_path):
        # two journals over the same dir (a crashed incarnation's handle
        # still open while the successor compacts): os.replace keeps the
        # log whole and neither writer's tmp file survives
        a = journal_at(tmp_path)
        b = Journal(str(tmp_path), clock=FakeClock())
        a.intent("nodeclaim.launch", uid="a1")
        a.compact()
        b.compact()
        assert not glob.glob(str(tmp_path / "*.tmp.*"))
        blob = (tmp_path / JOURNAL_FILE).read_bytes()
        assert blob.startswith(MAGIC)
        a.close()
        b.close()
        journal_at(tmp_path)  # loads without truncation warnings
        assert journal_at(tmp_path).frame()["truncated_frames"] == 0


class TestReplayDeterminism:
    def test_same_bytes_same_pending_list(self, tmp_path):
        j = journal_at(tmp_path)
        j.intent("nodeclaim.launch", uid="u1", key="k1")
        s2 = j.intent("disruption.command", candidates=["c1", "c2"])
        j.intent("pod.bind", uid="u3")
        j.failed(s2, error="rolled back")
        j.close()
        blob = (tmp_path / JOURNAL_FILE).read_bytes()
        replicas = []
        for sub in ("a", "b"):
            d = tmp_path / sub
            d.mkdir()
            (d / JOURNAL_FILE).write_bytes(blob)
            replicas.append(Journal(str(d), clock=FakeClock()).pending())
        assert replicas[0] == replicas[1]
        assert [r["action"] for r in replicas[0]] == [
            "nodeclaim.launch", "pod.bind",
        ]


class TestCrashBarriers:
    def test_post_intent_crash_is_one_shot_and_durable(self, tmp_path):
        j = journal_at(tmp_path)
        j.arm_crash(BARRIER_POST_INTENT)
        with pytest.raises(OperatorCrash) as exc:
            j.intent("nodeclaim.launch", uid="u1")
        assert exc.value.barrier == BARRIER_POST_INTENT
        # the intent hit the disk BEFORE the crash: a restart replays it
        j.close()
        assert [r["uid"] for r in journal_at(tmp_path).pending()] == ["u1"]
        # one-shot: the next intent sails through
        j2 = journal_at(tmp_path)
        j2.intent("nodeclaim.launch", uid="u2")

    def test_pre_intent_crash_leaves_no_record(self, tmp_path):
        j = journal_at(tmp_path)
        j.arm_crash(BARRIER_PRE_INTENT)
        with pytest.raises(OperatorCrash):
            j.intent("nodeclaim.launch", uid="u1")
        assert j.pending() == []
        assert j.frame()["appends"] == 0
        j.close()
        assert journal_at(tmp_path).pending() == []

    def test_post_effect_crash_loses_the_done_record(self, tmp_path):
        j = journal_at(tmp_path)
        seq = j.intent("nodeclaim.launch", uid="u1", key="k1")
        j.arm_crash(BARRIER_POST_EFFECT)
        with pytest.raises(OperatorCrash):
            j.done(seq, provider_id="kwok://n1")
        j.close()
        # the effect happened but its completion never landed: this is
        # exactly the adoption work-list recovery must resolve by key
        assert [r["key"] for r in journal_at(tmp_path).pending()] == ["k1"]

    def test_recovery_resolutions_skip_the_barrier(self, tmp_path):
        j = journal_at(tmp_path)
        seq = j.intent("nodeclaim.launch", uid="u1")
        j.arm_crash(BARRIER_POST_EFFECT)
        j.done(seq, barrier=False, recovered=True)  # must NOT crash
        assert j.pending() == []
        # the armed crash is still pending for the next real mutation
        s2 = j.intent("nodeclaim.launch", uid="u2")
        with pytest.raises(OperatorCrash):
            j.done(s2)

    def test_action_filter(self, tmp_path):
        j = journal_at(tmp_path)
        j.arm_crash(BARRIER_POST_INTENT, action="nodeclaim.delete")
        j.intent("nodeclaim.launch", uid="u1")  # different action: no crash
        with pytest.raises(OperatorCrash) as exc:
            j.intent("nodeclaim.delete", uid="u2")
        assert exc.value.action == "nodeclaim.delete"

    def test_failed_never_fires_a_barrier(self, tmp_path):
        j = journal_at(tmp_path)
        seq = j.intent("nodeclaim.launch", uid="u1")
        j.arm_crash(BARRIER_POST_EFFECT)
        j.failed(seq, error="create raised")  # the effect never happened
        assert j.pending() == []

    def test_unknown_barrier_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="unknown journal barrier"):
            journal_at(tmp_path).arm_crash("post-mortem")

    def test_crash_is_not_an_exception(self):
        # the reconciler harness isolates failures with `except Exception`;
        # a simulated SIGKILL must never be absorbed by it
        assert not issubclass(OperatorCrash, Exception)
        assert issubclass(OperatorCrash, BaseException)


class TestMetrics:
    def test_journal_counters_round_trip_exposition(self, tmp_path):
        appends = global_registry.get("karpenter_journal_appends_total")
        truncations = global_registry.get("karpenter_journal_truncations_total")
        before_intent = appends.value({"type": "intent"})
        before_done = appends.value({"type": "done"})
        before_trunc = truncations.value()
        j = journal_at(tmp_path)
        s1 = j.intent("nodeclaim.launch", uid="u1")
        j.intent("nodeclaim.launch", uid="u2")
        j.done(s1)
        j.note_replay()
        j.note_adoption()
        j.note_orphan()
        j.note_rollback()
        j.close()
        (tmp_path / JOURNAL_FILE).write_bytes(b"garbage")
        journal_at(tmp_path)  # bad magic => one truncation
        families = parse_exposition(global_registry.expose())
        for name in (
            "karpenter_journal_appends_total",
            "karpenter_journal_replays_total",
            "karpenter_journal_adoptions_total",
            "karpenter_journal_orphans_total",
            "karpenter_journal_rollbacks_total",
            "karpenter_journal_truncations_total",
        ):
            assert families[name]["type"] == "counter", name
        samples = families["karpenter_journal_appends_total"]["samples"]
        assert samples[
            ("karpenter_journal_appends_total", (("type", "intent"),))
        ] == before_intent + 2
        assert samples[
            ("karpenter_journal_appends_total", (("type", "done"),))
        ] == before_done + 1
        assert families["karpenter_journal_truncations_total"]["samples"][
            ("karpenter_journal_truncations_total", ())
        ] == before_trunc + 1


class TestSnapshot:
    def test_snapshot_shape(self, tmp_path):
        clock = FakeClock()
        j = Journal(str(tmp_path), clock=clock)
        j.set_pass(7)
        j.intent("nodeclaim.launch", uid="u1", key="k1", nodeclaim="c1")
        snap = j.snapshot()
        assert snap["path"] == os.path.join(str(tmp_path), JOURNAL_FILE)
        assert snap["depth"] == 1
        [pending] = snap["pending"]
        assert pending == {
            "seq": 1, "action": "nodeclaim.launch", "uid": "u1",
            "key": "k1", "pass": 7, "ts": round(clock.now(), 6),
        }
