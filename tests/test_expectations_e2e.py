"""Expectation-library-driven e2e flows (the reference suites' idiom:
ExpectApplied → drive → ExpectScheduled/ExpectProvisioned;
pkg/test/expectations/expectations.go)."""

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.cloudprovider.kwok.provider import KwokCloudProvider
from karpenter_tpu.operator.operator import Operator
from karpenter_tpu.runtime.store import Store
from karpenter_tpu.utils.clock import FakeClock

from expectations import (
    expect_applied,
    expect_node_labels,
    expect_condition,
    expect_initialized,
    expect_node_claims,
    expect_not_scheduled,
    expect_provisioned,
    expect_scheduled,
)
from helpers import nodepool, unschedulable_pod


def make_operator():
    clock = FakeClock()
    store = Store(clock=clock)
    op = Operator(store, KwokCloudProvider(store, clock), clock=clock)
    return clock, store, op


class TestExpectationFlows:
    def test_provisioned_pods_land_on_nodes(self):
        clock, store, op = make_operator()
        expect_applied(store, nodepool("workers"))
        pods = [unschedulable_pod(requests={"cpu": "1"}) for _ in range(3)]
        expect_applied(store, *pods)
        nodes = expect_provisioned(clock, op, *pods)
        assert len({n.metadata.name for n in nodes}) >= 1
        for claim in expect_node_claims(store):
            expect_initialized(store, claim)
            expect_condition(claim, "Launched")

    def test_unsatisfiable_pod_stays_pending(self):
        clock, store, op = make_operator()
        expect_applied(store, nodepool("workers"))
        good = expect_applied(store, unschedulable_pod(requests={"cpu": "1"}))
        bad = expect_applied(store, unschedulable_pod(requests={"cpu": "9999"}))
        expect_provisioned(clock, op, good)
        expect_not_scheduled(store, bad)

    def test_selector_respected_end_to_end(self):
        clock, store, op = make_operator()
        expect_applied(store, nodepool("workers"))
        pod = expect_applied(
            store,
            unschedulable_pod(
                requests={"cpu": "1"}, node_selector={wk.LABEL_ARCH: "arm64"}
            ),
        )
        expect_provisioned(clock, op, pod)
        node = expect_scheduled(store, pod)
        expect_node_labels(node, {wk.LABEL_ARCH: "arm64"})
