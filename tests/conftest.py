"""Test bootstrap: force an 8-device virtual CPU mesh so all sharding code
paths (shard_map/pjit over the pod axis) are exercised without TPU hardware.
Must run before jax is used anywhere; the axon sitecustomize may have
force-registered a TPU backend via jax.config.update, so we override the
config (not just the env) too."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
