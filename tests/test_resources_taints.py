from karpenter_tpu.apis.core import (
    Container,
    Pod,
    PodSpec,
    Taint,
    Toleration,
)
from karpenter_tpu.apis.core import pod_resource_requests
from karpenter_tpu.scheduling.taints import Taints
from karpenter_tpu.utils import resources as r


class TestQuantity:
    def test_parse(self):
        assert r.parse_quantity("100m") == 0.1
        assert r.parse_quantity("1") == 1
        assert r.parse_quantity("1Gi") == 2**30
        assert r.parse_quantity("1500Mi") == 1500 * 2**20
        assert r.parse_quantity("2k") == 2000
        assert r.parse_quantity(2.5) == 2.5

    def test_arithmetic(self):
        a = {"cpu": 1.0, "memory": 100.0}
        b = {"cpu": 0.5, "gpu": 1.0}
        assert r.merge(a, b) == {"cpu": 1.5, "memory": 100.0, "gpu": 1.0}
        # subtract keeps LHS keys only (reference resources.Subtract)
        assert r.subtract(a, b) == {"cpu": 0.5, "memory": 100.0}
        assert r.subtract_into(a, b) == {"cpu": 0.5, "memory": 100.0, "gpu": -1.0}

    def test_fits(self):
        assert r.fits({"cpu": 1.0}, {"cpu": 1.0, "memory": 5.0})
        assert not r.fits({"cpu": 2.0}, {"cpu": 1.0})
        # extended resource missing from the node => does not fit
        assert not r.fits({"gpu": 1.0}, {"cpu": 10.0})


class TestPodRequests:
    def test_max_of_init_and_main(self):
        pod = Pod(
            spec=PodSpec(
                containers=[
                    Container(requests={"cpu": 1.0}),
                    Container(requests={"cpu": 0.5, "memory": 64.0}),
                ],
                init_containers=[Container(requests={"cpu": 2.0})],
            )
        )
        got = pod_resource_requests(pod)
        assert got["cpu"] == 2.0  # init container dominates
        assert got["memory"] == 64.0
        assert got["pods"] == 1.0

    def test_sidecar_counts_as_main(self):
        pod = Pod(
            spec=PodSpec(
                containers=[Container(requests={"cpu": 1.0})],
                init_containers=[
                    Container(requests={"cpu": 1.0}, restart_policy="Always")
                ],
            )
        )
        assert pod_resource_requests(pod)["cpu"] == 2.0


class TestTaints:
    def test_tolerates(self):
        taints = Taints([Taint(key="dedicated", value="gpu", effect="NoSchedule")])
        pod = Pod(spec=PodSpec())
        assert taints.tolerates_pod(pod) is not None

        pod.spec.tolerations = [Toleration(key="dedicated", operator="Exists")]
        assert taints.tolerates_pod(pod) is None

        pod.spec.tolerations = [
            Toleration(key="dedicated", operator="Equal", value="cpu")
        ]
        assert taints.tolerates_pod(pod) is not None

        pod.spec.tolerations = [
            Toleration(key="dedicated", operator="Equal", value="gpu")
        ]
        assert taints.tolerates_pod(pod) is None

    def test_empty_key_exists_tolerates_all(self):
        taints = Taints([Taint(key="a", effect="NoSchedule"), Taint(key="b", effect="NoExecute")])
        pod = Pod(spec=PodSpec(tolerations=[Toleration(operator="Exists")]))
        assert taints.tolerates_pod(pod) is None

    def test_effect_scoping(self):
        taints = Taints([Taint(key="a", effect="NoExecute")])
        pod = Pod(
            spec=PodSpec(
                tolerations=[Toleration(key="a", operator="Exists", effect="NoSchedule")]
            )
        )
        assert taints.tolerates_pod(pod) is not None

    def test_merge(self):
        a = Taints([Taint(key="x", effect="NoSchedule", value="1")])
        merged = a.merge([Taint(key="x", effect="NoSchedule", value="2"), Taint(key="y")])
        assert len(merged) == 2
        assert merged[0].value == "1"


class TestPodRequestsEdgeCases:
    def test_sidecar_counts_into_init_ceiling(self):
        # sidecar (cpu=1) runs alongside later init (cpu=2): ceiling = 3
        pod = Pod(
            spec=PodSpec(
                containers=[Container(requests={"cpu": 0.5})],
                init_containers=[
                    Container(requests={"cpu": 1.0}, restart_policy="Always"),
                    Container(requests={"cpu": 2.0}),
                ],
            )
        )
        assert pod_resource_requests(pod)["cpu"] == 3.0

    def test_limits_default_requests(self):
        pod = Pod(spec=PodSpec(containers=[Container(limits={"cpu": 2.0})]))
        assert pod_resource_requests(pod)["cpu"] == 2.0

    def test_explicit_request_wins_over_limit(self):
        pod = Pod(
            spec=PodSpec(containers=[Container(requests={"cpu": 1.0}, limits={"cpu": 4.0})])
        )
        assert pod_resource_requests(pod)["cpu"] == 1.0


class TestTolerationOperators:
    def test_unknown_operator_never_tolerates(self):
        t = Toleration(key="a", operator="exists")  # typo'd operator
        assert not t.tolerates(Taint(key="a", effect="NoSchedule"))

    def test_exists_with_value_never_tolerates(self):
        t = Toleration(key="a", operator="Exists", value="x")
        assert not t.tolerates(Taint(key="a", effect="NoSchedule"))

    def test_empty_operator_is_equal(self):
        t = Toleration(key="a", operator="", value="v")
        assert t.tolerates(Taint(key="a", value="v", effect="NoSchedule"))
        assert not t.tolerates(Taint(key="a", value="w", effect="NoSchedule"))
