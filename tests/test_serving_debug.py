"""Serving debug surface: /debug/stacks, /debug/profile, /debug/solverd,
the 404 path, and profiling-disabled behavior (operator/serving.py)."""

import json
import urllib.error
import urllib.request

import pytest

from karpenter_tpu.operator.serving import Server, ServingConfig


def make_server(
    enable_profiling=False, solverd_stats=None, heap_stats=None,
    kernel_snapshot=None, device_profile=None, explain_snapshot=None,
):
    cfg = ServingConfig(
        metrics_text=lambda: "karpenter_test_metric 1\n",
        healthy=lambda: True,
        ready=lambda: True,
        enable_profiling=enable_profiling,
        solverd_stats=solverd_stats,
        heap_stats=heap_stats,
        kernel_snapshot=kernel_snapshot,
        device_profile=device_profile,
        explain_snapshot=explain_snapshot,
    )
    return Server(0, cfg, host="127.0.0.1").start()


def get(server, path):
    url = f"http://127.0.0.1:{server.port}{path}"
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


@pytest.fixture
def profiling_server():
    server = make_server(enable_profiling=True)
    yield server
    server.stop()


@pytest.fixture
def plain_server():
    server = make_server(enable_profiling=False)
    yield server
    server.stop()


class TestDebugEndpoints:
    def test_stacks_lists_threads(self, profiling_server):
        code, body = get(profiling_server, "/debug/stacks")
        assert code == 200
        assert "--- thread" in body
        # the serving thread itself must appear in the dump
        assert "serve_forever" in body or "karpenter" in body

    def test_profile_samples(self, profiling_server):
        code, body = get(profiling_server, "/debug/profile?seconds=0.1")
        assert code == 200
        assert "samples over" in body
        assert "hottest frames" in body

    def test_profile_default_seconds(self, profiling_server):
        code, body = get(profiling_server, "/debug/profile")
        assert code == 200
        assert "samples over 1.0s" in body

    def test_profile_bad_seconds_is_500_not_crash(self, profiling_server):
        code, body = get(profiling_server, "/debug/profile?seconds=nope")
        assert code == 500
        assert "error" in body
        # the server survives the handler failure
        code, _ = get(profiling_server, "/healthz")
        assert code == 200

    def test_unknown_path_404(self, profiling_server):
        code, body = get(profiling_server, "/debug/nonsense")
        assert code == 404
        assert "not found" in body

    def test_profiling_disabled_hides_debug(self, plain_server):
        for path in ("/debug/stacks", "/debug/profile?seconds=0.1", "/debug/heap"):
            code, body = get(plain_server, path)
            assert code == 404, f"{path} must 404 when profiling is off"
            assert "not found" in body

    def test_profiling_disabled_keeps_core_surface(self, plain_server):
        assert get(plain_server, "/metrics")[0] == 200
        assert get(plain_server, "/healthz")[0] == 200
        assert get(plain_server, "/readyz")[0] == 200


class TestHeapEndpoint:
    def test_heap_arms_then_reports_allocations(self):
        """First hit arms tracemalloc (no overhead until someone looks);
        the second reports allocation sites and traced totals."""
        import tracemalloc

        server = make_server(
            enable_profiling=True,
            heap_stats=lambda: {"ffd_shape_sigs": 7, "engine_joint_mask_cache": 3},
        )
        try:
            code, body = get(server, "/debug/heap")
            assert code == 200
            first = json.loads(body)
            assert first["tracing"] is True
            # interning-cache sizes surface on every response
            assert first["interning_caches"]["ffd_shape_sigs"] == 7
            if first["armed_now"]:
                assert "re-query" in first["note"]
            list(range(50_000))  # some allocations to record
            code, body = get(server, "/debug/heap?top=5")
            assert code == 200
            second = json.loads(body)
            assert second["armed_now"] is False
            assert second["traced_current_bytes"] >= 0
            assert len(second["top_allocations"]) <= 5
            for site in second["top_allocations"]:
                assert ":" in site["site"] and site["size_bytes"] >= 0
            assert second["interning_caches"]["engine_joint_mask_cache"] == 3
            # ?stop=1 disarms: the final snapshot comes back and the
            # tracing overhead ends with the investigation
            code, body = get(server, "/debug/heap?stop=1")
            assert code == 200
            final = json.loads(body)
            assert final["stopped_now"] is True
            assert final["tracing"] is False
            assert "top_allocations" in final
            assert not tracemalloc.is_tracing()
        finally:
            server.stop()
            if tracemalloc.is_tracing():
                tracemalloc.stop()

    def test_heap_without_stats_callable(self):
        import tracemalloc

        server = make_server(enable_profiling=True)
        try:
            code, body = get(server, "/debug/heap")
            assert code == 200
            assert "interning_caches" not in json.loads(body)
            get(server, "/debug/heap?stop=1")
            assert not tracemalloc.is_tracing()
        finally:
            server.stop()
            if tracemalloc.is_tracing():
                tracemalloc.stop()

    def test_operator_heap_stats_shape(self):
        """The operator's collector names every interning cache the memory
        budget governs (ffd.set_memory_budget)."""
        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
        from karpenter_tpu.operator.operator import Operator
        from karpenter_tpu.runtime.store import Store
        from karpenter_tpu.utils.clock import FakeClock

        clock = FakeClock()
        store = Store(clock=clock)
        op = Operator(store, FakeCloudProvider(), clock=clock)
        stats = op.heap_stats()
        for key in (
            "ffd_shape_sigs",
            "ffd_topo_shape_sigs",
            "topology_domain_groups_memo",
            "engine_content_cache",
            "engine_joint_mask_cache",
            "engine_fam_transition_cache",
        ):
            assert isinstance(stats[key], int)


class TestKernelsEndpoint:
    """/debug/kernels: the kernel observatory table, ?kernel= drill-down,
    404 for unknown kernels, and the unwired (profiling-off style) 404."""

    def _registry_snapshot(self):
        from karpenter_tpu.observability import kernels as kobs

        reg = kobs.registry()
        reg.reset()
        reg.record_host("spec.kernel", "8x4")
        return reg, reg.debug_snapshot

    def test_table_and_drilldown(self):
        reg, snapshot = self._registry_snapshot()
        server = make_server(kernel_snapshot=snapshot)
        try:
            code, body = get(server, "/debug/kernels")
            assert code == 200
            table = json.loads(body)
            assert table["sealed"] is False
            assert any(
                row["kernel"] == "spec.kernel" for row in table["kernels"]
            )
            code, body = get(server, "/debug/kernels?kernel=spec.kernel")
            assert code == 200
            drill = json.loads(body)
            assert drill["kernel"] == "spec.kernel"
            assert drill["shapes"][0]["shape"] == "8x4"
        finally:
            server.stop()
            reg.reset()

    def test_unknown_kernel_404(self):
        reg, snapshot = self._registry_snapshot()
        server = make_server(kernel_snapshot=snapshot)
        try:
            code, body = get(server, "/debug/kernels?kernel=missing")
            assert code == 404
            assert "unknown kernel" in body
        finally:
            server.stop()
            reg.reset()

    def test_unwired_404(self, plain_server):
        code, body = get(plain_server, "/debug/kernels")
        assert code == 404
        assert "not found" in body

    def test_from_operator(self):
        """End-to-end: the operator's kernel_snapshot callable serves the
        real registry through the endpoint."""
        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
        from karpenter_tpu.observability import kernels as kobs
        from karpenter_tpu.operator.operator import Operator
        from karpenter_tpu.runtime.store import Store
        from karpenter_tpu.utils.clock import FakeClock

        kobs.registry().record_host("spec.operator", "2x2")
        clock = FakeClock()
        operator = Operator(Store(clock=clock), FakeCloudProvider(), clock=clock)
        server = make_server(kernel_snapshot=operator.kernel_snapshot)
        try:
            code, body = get(server, "/debug/kernels")
            assert code == 200
            snap = json.loads(body)
            assert {"sealed", "phase", "steady_recompiles", "kernels"} <= set(snap)
            assert any(
                row["kernel"] == "spec.operator" for row in snap["kernels"]
            )
        finally:
            server.stop()


class TestKernelsEfficiencyViews:
    """/debug/kernels?view=cost and ?view=timeline (ISSUE 15): 200s, the
    cost drill-down, 404 on unknown kernels, and unwired→404."""

    def _wired(self):
        import jax
        import numpy as np

        from karpenter_tpu.observability import efficiency as eff
        from karpenter_tpu.observability import kernels as kobs
        from karpenter_tpu.tracing import kernel as ktime

        reg = kobs.registry()
        reg.reset()
        eff.tables().reset()
        f = jax.jit(lambda x: x @ x)
        x = np.ones((8, 8), np.float32)
        ktime.dispatch(f, x, kernel="spec.eff")
        with reg.batch_scope(label="spec-batch"):
            with ktime.measure():
                ktime.dispatch(f, x, kernel="spec.eff")
        eff.note_executable(
            "spec.eff", "8x8",
            f.lower(jax.ShapeDtypeStruct((8, 8), np.float32)).compile(),
        )
        return reg, eff, reg.debug_snapshot

    def _teardown(self, reg, eff):
        reg.reset()
        eff.tables().reset()

    def test_cost_view_and_drilldown(self):
        reg, eff, snapshot = self._wired()
        server = make_server(kernel_snapshot=snapshot)
        try:
            code, body = get(server, "/debug/kernels?view=cost")
            assert code == 200
            view = json.loads(body)
            assert view["cost_tables"]["entries"] == 1
            row = view["rows"][0]
            assert row["kernel"] == "spec.eff" and row["bucket"] == "8x8"
            assert row["floor_s"] > 0
            assert row["utilization"] > 0  # joined with the measured wall
            code, body = get(
                server, "/debug/kernels?view=cost&kernel=spec.eff"
            )
            assert code == 200
            assert len(json.loads(body)["rows"]) == 1
        finally:
            server.stop()
            self._teardown(reg, eff)

    def test_cost_view_unknown_kernel_404(self):
        reg, eff, snapshot = self._wired()
        server = make_server(kernel_snapshot=snapshot)
        try:
            code, body = get(
                server, "/debug/kernels?view=cost&kernel=missing"
            )
            assert code == 404
            assert "unknown kernel" in body
        finally:
            server.stop()
            self._teardown(reg, eff)

    def test_timeline_view(self):
        reg, eff, snapshot = self._wired()
        server = make_server(kernel_snapshot=snapshot)
        try:
            code, body = get(server, "/debug/kernels?view=timeline")
            assert code == 200
            view = json.loads(body)
            assert "steady" in view
            (batch,) = view["batches"]
            assert batch["label"] == "spec-batch"
            assert batch["dispatches"] == 1
            assert 0.0 <= batch["host_stall_fraction"] <= 1.0
            assert batch["timeline"][0]["kernel"] == "spec.eff"
        finally:
            server.stop()
            self._teardown(reg, eff)

    def test_views_unwired_404(self, plain_server):
        for view in ("cost", "timeline"):
            code, _ = get(plain_server, f"/debug/kernels?view={view}")
            assert code == 404


class TestDeviceProfileEndpoint:
    """/debug/profile/device: 200 with a capture record, 404 when device
    profiling is off (callable answers None), 400 on bad seconds, and
    unwired→404."""

    def test_capture_served(self):
        calls = []

        def fake(seconds):
            calls.append(seconds)
            return {"name": "device-0001-debug", "seconds": seconds}

        server = make_server(device_profile=fake)
        try:
            code, body = get(server, "/debug/profile/device?seconds=0.5")
            assert code == 200
            snap = json.loads(body)
            assert snap["name"] == "device-0001-debug"
            assert calls == [0.5]
        finally:
            server.stop()

    def test_profiling_off_404(self):
        server = make_server(device_profile=lambda seconds: None)
        try:
            code, body = get(server, "/debug/profile/device")
            assert code == 404
            assert "disabled" in body
        finally:
            server.stop()

    def test_bad_seconds_400(self):
        server = make_server(
            device_profile=lambda seconds: {"name": "never"}
        )
        try:
            for q in ("seconds=nope", "seconds=-1", "seconds=31"):
                code, body = get(server, f"/debug/profile/device?{q}")
                assert code == 400, q
                assert "seconds" in body
        finally:
            server.stop()

    def test_unwired_404(self, plain_server):
        code, body = get(plain_server, "/debug/profile/device")
        assert code == 404
        assert "not found" in body

    def test_from_operator_real_capture(self, tmp_path):
        """End-to-end over real HTTP: the operator's callable runs a real
        jax.profiler capture into --profile-dir."""
        import os

        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
        from karpenter_tpu.observability import efficiency as eff
        from karpenter_tpu.operator.operator import Operator
        from karpenter_tpu.operator.options import Options
        from karpenter_tpu.runtime.store import Store
        from karpenter_tpu.utils.clock import FakeClock

        clock = FakeClock()
        operator = Operator(
            Store(clock=clock), FakeCloudProvider(), clock=clock,
            options=Options(profile_dir=str(tmp_path)),
        )
        eff.profiler().reset()
        server = make_server(device_profile=operator.device_profile_snapshot)
        try:
            code, body = get(server, "/debug/profile/device?seconds=0")
            assert code == 200
            record = json.loads(body)
            assert record["path"].startswith(str(tmp_path))
            files = [
                os.path.join(r, fn)
                for r, _, fs in os.walk(record["path"])
                for fn in fs
            ]
            assert files, "no trace files written"
        finally:
            server.stop()
            eff.profiler().configure(profile_dir="")
            eff.profiler().reset()


class TestExplainEndpoint:
    """/debug/explain: the triage table, ?pod= drill-down, the what-if
    validation (400), disabled/unknown (404), and unwired (404)."""

    def _snapshot(self, pod=None, what_if=None):
        if pod is None:
            return {"mode": "on", "ring_depth": 1, "pods": [{"pod": "web-0"}]}
        if pod != "web-0":
            return None
        out = {"pod": "web-0", "stages": ["resources"], "funnel": []}
        if what_if:
            out["what_if"] = {"drop": what_if.split(":", 1)[1], "schedulable": True}
        return out

    def test_triage_and_drilldown(self):
        server = make_server(explain_snapshot=self._snapshot)
        try:
            code, body = get(server, "/debug/explain")
            assert code == 200
            table = json.loads(body)
            assert table["mode"] == "on" and table["pods"][0]["pod"] == "web-0"
            code, body = get(server, "/debug/explain?pod=web-0")
            assert code == 200
            assert json.loads(body)["stages"] == ["resources"]
        finally:
            server.stop()

    def test_what_if_served(self):
        server = make_server(explain_snapshot=self._snapshot)
        try:
            code, body = get(
                server, "/debug/explain?pod=web-0&what_if=drop:kubernetes.io/arch"
            )
            assert code == 200
            probe = json.loads(body)["what_if"]
            assert probe["drop"] == "kubernetes.io/arch"
            assert probe["schedulable"] is True
        finally:
            server.stop()

    def test_malformed_what_if_400(self):
        server = make_server(explain_snapshot=self._snapshot)
        try:
            for q in (
                "what_if=drop:zone",  # no pod
                "pod=web-0&what_if=add:zone",  # not drop:
                "pod=web-0&what_if=drop:",  # empty key
            ):
                code, body = get(server, f"/debug/explain?{q}")
                assert code == 400, q
                assert "what_if" in body
        finally:
            server.stop()

    def test_unknown_pod_404(self):
        server = make_server(explain_snapshot=self._snapshot)
        try:
            code, body = get(server, "/debug/explain?pod=missing")
            assert code == 404
            assert "unknown pod" in body
        finally:
            server.stop()

    def test_disabled_ledger_404(self):
        server = make_server(explain_snapshot=lambda pod=None, what_if=None: None)
        try:
            code, body = get(server, "/debug/explain")
            assert code == 404
            assert "disabled" in body
        finally:
            server.stop()

    def test_unwired_404(self, plain_server):
        code, body = get(plain_server, "/debug/explain")
        assert code == 404
        assert "not found" in body

    def test_from_operator(self):
        """End-to-end over real HTTP: the operator's explain_snapshot
        callable serves the live ledger (404 while disabled, the triage
        table once a capture is configured and committed)."""
        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
        from karpenter_tpu.observability import explain as explmod
        from karpenter_tpu.operator.operator import Operator
        from karpenter_tpu.operator.options import Options
        from karpenter_tpu.runtime.store import Store
        from karpenter_tpu.utils.clock import FakeClock

        clock = FakeClock()
        operator = Operator(
            Store(clock=clock), FakeCloudProvider(), clock=clock,
            options=Options(explain="on"),
        )
        server = make_server(explain_snapshot=operator.explain_snapshot)
        try:
            code, body = get(server, "/debug/explain")
            assert code == 200
            snap = json.loads(body)
            assert snap["mode"] == "on" and snap["ring_depth"] == 0
            code, _ = get(server, "/debug/explain?pod=never-committed")
            assert code == 404
        finally:
            server.stop()
            explmod.configure(mode="off")
            explmod.recorder().reset()


class TestSolverdEndpoint:
    def test_solverd_stats_served(self):
        server = make_server(
            solverd_stats=lambda: {"transport": "inprocess", "queue_depth": 0}
        )
        try:
            code, body = get(server, "/debug/solverd")
            assert code == 200
            stats = json.loads(body)
            assert stats["transport"] == "inprocess"
            assert stats["queue_depth"] == 0
        finally:
            server.stop()

    def test_solverd_unwired_404(self, plain_server):
        code, _ = get(plain_server, "/debug/solverd")
        assert code == 404

    def test_solverd_from_operator(self):
        """End-to-end: the operator's solver_stats callable serves real
        service counters through the debug endpoint."""
        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
        from karpenter_tpu.operator.operator import Operator
        from karpenter_tpu.runtime.store import Store
        from karpenter_tpu.utils.clock import FakeClock

        clock = FakeClock()
        store = Store(clock=clock)
        operator = Operator(store, FakeCloudProvider(), clock=clock)
        server = make_server(solverd_stats=operator.solver_stats)
        try:
            code, body = get(server, "/debug/solverd")
            assert code == 200
            stats = json.loads(body)
            assert stats["transport"] == "inprocess"
            assert {"queue_depth", "batches", "requests"} <= set(stats)
        finally:
            server.stop()
