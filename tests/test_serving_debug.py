"""Serving debug surface: /debug/stacks, /debug/profile, /debug/solverd,
the 404 path, and profiling-disabled behavior (operator/serving.py)."""

import json
import urllib.error
import urllib.request

import pytest

from karpenter_tpu.operator.serving import Server, ServingConfig


def make_server(enable_profiling=False, solverd_stats=None):
    cfg = ServingConfig(
        metrics_text=lambda: "karpenter_test_metric 1\n",
        healthy=lambda: True,
        ready=lambda: True,
        enable_profiling=enable_profiling,
        solverd_stats=solverd_stats,
    )
    return Server(0, cfg, host="127.0.0.1").start()


def get(server, path):
    url = f"http://127.0.0.1:{server.port}{path}"
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


@pytest.fixture
def profiling_server():
    server = make_server(enable_profiling=True)
    yield server
    server.stop()


@pytest.fixture
def plain_server():
    server = make_server(enable_profiling=False)
    yield server
    server.stop()


class TestDebugEndpoints:
    def test_stacks_lists_threads(self, profiling_server):
        code, body = get(profiling_server, "/debug/stacks")
        assert code == 200
        assert "--- thread" in body
        # the serving thread itself must appear in the dump
        assert "serve_forever" in body or "karpenter" in body

    def test_profile_samples(self, profiling_server):
        code, body = get(profiling_server, "/debug/profile?seconds=0.1")
        assert code == 200
        assert "samples over" in body
        assert "hottest frames" in body

    def test_profile_default_seconds(self, profiling_server):
        code, body = get(profiling_server, "/debug/profile")
        assert code == 200
        assert "samples over 1.0s" in body

    def test_profile_bad_seconds_is_500_not_crash(self, profiling_server):
        code, body = get(profiling_server, "/debug/profile?seconds=nope")
        assert code == 500
        assert "error" in body
        # the server survives the handler failure
        code, _ = get(profiling_server, "/healthz")
        assert code == 200

    def test_unknown_path_404(self, profiling_server):
        code, body = get(profiling_server, "/debug/nonsense")
        assert code == 404
        assert "not found" in body

    def test_profiling_disabled_hides_debug(self, plain_server):
        for path in ("/debug/stacks", "/debug/profile?seconds=0.1"):
            code, body = get(plain_server, path)
            assert code == 404, f"{path} must 404 when profiling is off"
            assert "not found" in body

    def test_profiling_disabled_keeps_core_surface(self, plain_server):
        assert get(plain_server, "/metrics")[0] == 200
        assert get(plain_server, "/healthz")[0] == 200
        assert get(plain_server, "/readyz")[0] == 200


class TestSolverdEndpoint:
    def test_solverd_stats_served(self):
        server = make_server(
            solverd_stats=lambda: {"transport": "inprocess", "queue_depth": 0}
        )
        try:
            code, body = get(server, "/debug/solverd")
            assert code == 200
            stats = json.loads(body)
            assert stats["transport"] == "inprocess"
            assert stats["queue_depth"] == 0
        finally:
            server.stop()

    def test_solverd_unwired_404(self, plain_server):
        code, _ = get(plain_server, "/debug/solverd")
        assert code == 404

    def test_solverd_from_operator(self):
        """End-to-end: the operator's solver_stats callable serves real
        service counters through the debug endpoint."""
        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
        from karpenter_tpu.operator.operator import Operator
        from karpenter_tpu.runtime.store import Store
        from karpenter_tpu.utils.clock import FakeClock

        clock = FakeClock()
        store = Store(clock=clock)
        operator = Operator(store, FakeCloudProvider(), clock=clock)
        server = make_server(solverd_stats=operator.solver_stats)
        try:
            code, body = get(server, "/debug/solverd")
            assert code == 200
            stats = json.loads(body)
            assert stats["transport"] == "inprocess"
            assert {"queue_depth", "batches", "requests"} <= set(stats)
        finally:
            server.stop()
