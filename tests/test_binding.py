"""BindingController: the fake kube-scheduler closing the e2e loop.

The reference gets binding from the real kube-scheduler in its kwok E2E
environment; these tests pin the stand-in's predicates (taints, labels,
resources, host ports, volume limits, anti-affinity) and its change-detection
short-circuit."""

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import (
    Affinity,
    LabelSelector,
    PodAffinityTerm,
    PodAntiAffinity,
    Taint,
)
from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
from karpenter_tpu.controllers.binding import BindingController
from karpenter_tpu.events.recorder import Recorder
from karpenter_tpu.runtime.store import Store
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.state.informer import StateInformer
from karpenter_tpu.utils.clock import FakeClock

from helpers import node_claim_pair, unschedulable_pod


def make_binder():
    clock = FakeClock()
    store = Store(clock=clock)
    cluster = Cluster(clock, store, FakeCloudProvider())
    informer = StateInformer(store, cluster)
    binder = BindingController(store, cluster, clock, Recorder(clock=clock))
    return clock, store, cluster, informer, binder


def add_node(store, informer, name="n1", **kwargs):
    node, claim = node_claim_pair(name, **kwargs)
    store.create(claim)
    store.create(node)
    informer.flush()
    return node, claim


class TestBinding:
    def test_binds_fitting_pod(self):
        clock, store, cluster, informer, binder = make_binder()
        add_node(store, informer)
        pod = store.create(unschedulable_pod(requests={"cpu": "1"}))
        informer.flush()
        assert binder.reconcile() == 1
        pod = store.get("Pod", pod.metadata.name)
        assert pod.spec.node_name == "n1"
        assert not any(
            c.type == "PodScheduled" and c.status == "False"
            for c in pod.status.conditions
        )

    def test_marks_unplaceable_pod_unschedulable(self):
        clock, store, cluster, informer, binder = make_binder()
        pod = store.create(unschedulable_pod(requests={"cpu": "100"}))
        pod.status.conditions = []  # fresh pod, never seen by a scheduler
        informer.flush()
        binder.reconcile()
        pod = store.get("Pod", pod.metadata.name)
        assert any(
            c.type == "PodScheduled" and c.reason == "Unschedulable"
            for c in pod.status.conditions
        )

    def test_respects_taints(self):
        clock, store, cluster, informer, binder = make_binder()
        node, claim = node_claim_pair("n1")
        node.spec.taints = [Taint(key="dedicated", value="gpu", effect="NoSchedule")]
        store.create(claim)
        store.create(node)
        informer.flush()
        store.create(unschedulable_pod(requests={"cpu": "1"}))
        informer.flush()
        assert binder.reconcile() == 0

    def test_respects_node_selector(self):
        clock, store, cluster, informer, binder = make_binder()
        add_node(store, informer, zone="kwok-zone-1")
        store.create(
            unschedulable_pod(
                requests={"cpu": "1"},
                node_selector={wk.LABEL_TOPOLOGY_ZONE: "kwok-zone-2"},
            )
        )
        informer.flush()
        assert binder.reconcile() == 0

    def test_respects_resources_across_binds(self):
        clock, store, cluster, informer, binder = make_binder()
        add_node(store, informer, capacity={"cpu": "3", "memory": "16Gi", "pods": "110"})
        for _ in range(3):
            store.create(unschedulable_pod(requests={"cpu": "2"}))
        informer.flush()
        # only one 2-cpu pod fits on a 3-cpu node; the sweep must account for
        # its own earlier binds within the same pass
        assert binder.reconcile() == 1

    def test_required_anti_affinity_blocks_second_pod(self):
        clock, store, cluster, informer, binder = make_binder()
        add_node(store, informer)
        term = PodAffinityTerm(
            topology_key=wk.LABEL_HOSTNAME,
            label_selector=LabelSelector(match_labels={"app": "db"}),
        )
        for _ in range(2):
            pod = unschedulable_pod(requests={"cpu": "1"}, labels={"app": "db"})
            pod.spec.affinity = Affinity(
                pod_anti_affinity=PodAntiAffinity(required=[term])
            )
            store.create(pod)
        informer.flush()
        assert binder.reconcile() == 1

    def test_inverse_anti_affinity_blocks_candidate(self):
        clock, store, cluster, informer, binder = make_binder()
        node, _ = add_node(store, informer)
        # a placed pod with anti-affinity against app=web
        placed = unschedulable_pod(requests={"cpu": "1"}, labels={"app": "db"})
        placed.spec.affinity = Affinity(
            pod_anti_affinity=PodAntiAffinity(
                required=[
                    PodAffinityTerm(
                        topology_key=wk.LABEL_HOSTNAME,
                        label_selector=LabelSelector(match_labels={"app": "web"}),
                    )
                ]
            )
        )
        placed.spec.node_name = node.metadata.name
        store.create(placed)
        informer.flush()
        candidate = store.create(
            unschedulable_pod(requests={"cpu": "1"}, labels={"app": "web"})
        )
        informer.flush()
        assert binder.reconcile() == 0
        assert store.get("Pod", candidate.metadata.name).spec.node_name == ""

    def test_terminal_pods_do_not_repel(self):
        """kube-scheduler ignores Succeeded/Failed pods for inter-pod
        anti-affinity; the per-sweep index must filter them."""
        clock, store, cluster, informer, binder = make_binder()
        node, _ = add_node(store, informer)
        placed = unschedulable_pod(requests={"cpu": "1"}, labels={"app": "db"})
        placed.spec.affinity = Affinity(
            pod_anti_affinity=PodAntiAffinity(
                required=[
                    PodAffinityTerm(
                        topology_key=wk.LABEL_HOSTNAME,
                        label_selector=LabelSelector(match_labels={"app": "web"}),
                    )
                ]
            )
        )
        placed.spec.node_name = node.metadata.name
        placed.status.phase = "Succeeded"
        store.create(placed)
        candidate = store.create(
            unschedulable_pod(requests={"cpu": "1"}, labels={"app": "web"})
        )
        informer.flush()
        assert binder.reconcile() == 1
        assert store.get("Pod", candidate.metadata.name).spec.node_name == "n1"

    def test_skips_sweep_when_store_unchanged(self):
        clock, store, cluster, informer, binder = make_binder()
        add_node(store, informer)
        store.create(unschedulable_pod(requests={"cpu": "100"}))  # can't fit
        informer.flush()
        binder.reconcile()
        v = store.resource_version
        assert binder.reconcile() == 0
        assert store.resource_version == v

    def test_prefers_nominated_claim_node(self):
        clock, store, cluster, informer, binder = make_binder()
        add_node(store, informer, "n1")
        add_node(store, informer, "n2")
        pod = store.create(unschedulable_pod(requests={"cpu": "1"}))
        informer.flush()
        key = (pod.metadata.namespace, pod.metadata.name)
        cluster.pod_to_node_claim[key] = "n2-claim"
        binder.reconcile()
        assert store.get("Pod", pod.metadata.name).spec.node_name == "n2"

    def test_prefer_no_schedule_taint_does_not_block_binding(self):
        """kube-scheduler hard-blocks only on NoSchedule/NoExecute;
        PreferNoSchedule is a scoring preference — a pod without any
        toleration still binds (regression: soft-only pools deadlocked the
        e2e loop because the simulation scheduled but the binder refused)."""
        from karpenter_tpu.apis.core import Taint

        clock, store, cluster, informer, binder = make_binder()
        node, claim = node_claim_pair("soft-n1")
        node.spec.taints = [Taint(key="lane", value="slow", effect="PreferNoSchedule")]
        store.create(claim)
        store.create(node)
        pod = store.create(unschedulable_pod(requests={"cpu": "1"}))
        informer.flush()
        assert binder.reconcile() == 1
        assert store.get("Pod", pod.metadata.name).spec.node_name == "soft-n1"

    def test_no_schedule_taint_still_blocks_binding(self):
        from karpenter_tpu.apis.core import Taint

        clock, store, cluster, informer, binder = make_binder()
        node, claim = node_claim_pair("hard-n1")
        node.spec.taints = [Taint(key="team", value="infra", effect="NoSchedule")]
        store.create(claim)
        store.create(node)
        pod = store.create(unschedulable_pod(requests={"cpu": "1"}))
        informer.flush()
        binder.reconcile()
        assert store.get("Pod", pod.metadata.name).spec.node_name == ""
