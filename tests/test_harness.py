"""Reconciler harness (ISSUE 3): per-item backoff growth/jitter/cap/reset
under FakeClock, circuit-breaker open/half-open/close transitions, chaos
isolation (one controller raising every pass must not stop the others),
and the real health surface (/healthz JSON, /debug/health)."""

import json
import urllib.error
import urllib.request

import pytest

from karpenter_tpu.cloudprovider.breaker import BreakerCloudProvider
from karpenter_tpu.cloudprovider.kwok.provider import KwokCloudProvider
from karpenter_tpu.cloudprovider.types import (
    CircuitBreakerOpenError,
    InsufficientCapacityError,
    NodeClaimNotFoundError,
    is_retryable_error,
)
from karpenter_tpu.metrics import global_registry
from karpenter_tpu.operator.harness import (
    BackoffRateLimiter,
    CircuitBreaker,
    RECONCILE_ERRORS,
    ReconcilerHarness,
    Result,
)
from karpenter_tpu.operator.operator import Operator
from karpenter_tpu.operator.options import Options
from karpenter_tpu.runtime.store import Store
from karpenter_tpu.utils.clock import FakeClock

from helpers import nodepool, unschedulable_pod


class TestBackoffRateLimiter:
    def _limiter(self, **kw):
        clock = FakeClock()
        return clock, BackoffRateLimiter(clock, **kw)

    def test_growth_curve_and_jitter_bounds(self):
        """delay(n) in [min(cap, base*factor^(n-1)), min(cap, that*(1+jitter))]."""
        _, limiter = self._limiter(base=1.0, cap=300.0, factor=2.0, jitter=0.5)
        for n in range(1, 8):
            raw = 1.0 * 2.0 ** (n - 1)
            delay = limiter.failure("item")
            assert raw <= delay <= raw * 1.5, (n, delay)

    def test_cap_is_a_hard_ceiling(self):
        _, limiter = self._limiter(base=1.0, cap=10.0, jitter=0.5)
        for _ in range(12):
            delay = limiter.failure("item")
            assert delay <= 10.0

    def test_reset_on_success(self):
        clock, limiter = self._limiter(base=1.0, cap=100.0, jitter=0.0)
        for _ in range(5):
            limiter.failure("item")
        assert limiter.retries("item") == 5
        limiter.success("item")
        assert limiter.retries("item") == 0
        assert limiter.allowed("item")
        # the growth curve restarts from the base
        assert limiter.failure("item") == pytest.approx(1.0)

    def test_allowed_tracks_virtual_time(self):
        clock, limiter = self._limiter(base=4.0, jitter=0.0)
        assert limiter.allowed("item")  # never-failed items are always due
        delay = limiter.failure("item")
        assert not limiter.allowed("item")
        clock.step(delay + 0.001)
        assert limiter.allowed("item")

    def test_items_are_independent(self):
        _, limiter = self._limiter(jitter=0.0)
        limiter.failure("a")
        assert not limiter.allowed("a")
        assert limiter.allowed("b")

    def test_deterministic_given_same_failure_sequence(self):
        _, l1 = self._limiter()
        _, l2 = self._limiter()
        assert [l1.failure("x") for _ in range(6)] == [
            l2.failure("x") for _ in range(6)
        ]


class TestCircuitBreaker:
    def _breaker(self, threshold=3, cooldown=30.0):
        clock = FakeClock()
        return clock, CircuitBreaker(clock, threshold=threshold, cooldown=cooldown)

    def test_opens_after_threshold_consecutive_failures(self):
        _, cb = self._breaker(threshold=3)
        for _ in range(2):
            cb.record_failure()
        assert cb.state == CircuitBreaker.CLOSED
        cb.record_failure()
        assert cb.state == CircuitBreaker.OPEN
        assert not cb.allow()

    def test_success_resets_the_streak(self):
        _, cb = self._breaker(threshold=3)
        cb.record_failure()
        cb.record_failure()
        cb.record_success()
        cb.record_failure()
        cb.record_failure()
        assert cb.state == CircuitBreaker.CLOSED

    def test_half_open_probe_after_cooldown_then_close(self):
        clock, cb = self._breaker(threshold=1, cooldown=30.0)
        cb.record_failure()
        assert cb.state == CircuitBreaker.OPEN
        clock.step(29.0)
        assert not cb.allow()
        clock.step(1.0)
        assert cb.allow()  # the single probe
        assert cb.state == CircuitBreaker.HALF_OPEN
        assert not cb.allow()  # no second call while the probe is out
        cb.record_success()
        assert cb.state == CircuitBreaker.CLOSED
        assert cb.consecutive_failures == 0

    def test_half_open_probe_failure_reopens(self):
        clock, cb = self._breaker(threshold=1, cooldown=30.0)
        cb.record_failure()
        clock.step(30.0)
        assert cb.allow()
        cb.record_failure()
        assert cb.state == CircuitBreaker.OPEN
        # the cooldown restarts from the re-open
        clock.step(29.0)
        assert not cb.allow()
        clock.step(1.0)
        assert cb.allow()

    def test_disabled_breaker_never_opens(self):
        _, cb = self._breaker(threshold=0)
        for _ in range(50):
            cb.record_failure()
            assert cb.allow()
        assert cb.state == CircuitBreaker.CLOSED

    def test_transitions_are_observable(self):
        clock, cb = self._breaker(threshold=1, cooldown=10.0)
        seen = []
        cb.subscribe(lambda old, new: seen.append((old, new)))
        cb.record_failure()
        clock.step(10.0)
        cb.allow()
        cb.record_success()
        assert seen == [
            ("closed", "open"),
            ("open", "half-open"),
            ("half-open", "closed"),
        ]

    def test_snapshot_shape(self):
        clock, cb = self._breaker(threshold=2, cooldown=15.0)
        cb.record_failure()
        cb.record_failure()
        snap = cb.snapshot()
        assert snap["state"] == "open"
        assert snap["consecutive_failures"] == 2
        assert snap["opened_at"] == clock.now()
        assert 0 < snap["retry_after_seconds"] <= 15.0


class TestHarnessIsolation:
    def _harness(self):
        clock = FakeClock()
        return clock, ReconcilerHarness(clock, base_delay=1.0, max_delay=60.0)

    def test_exception_is_swallowed_counted_and_backed_off(self):
        clock, harness = self._harness()
        calls = []

        def boom():
            calls.append(clock.now())
            raise RuntimeError("kaput")

        rec = harness.register("boom", boom)
        errors0 = RECONCILE_ERRORS.value({"controller": "boom"})
        assert rec() is None  # raised, swallowed
        assert RECONCILE_ERRORS.value({"controller": "boom"}) == errors0 + 1
        assert rec() is None  # backed off: NOT called again
        assert len(calls) == 1
        clock.step(2.0)  # past base*1.5 worst-case jitter
        rec()
        assert len(calls) == 2

    def test_per_item_backoff_does_not_block_other_items(self):
        clock, harness = self._harness()

        def only_a_fails(obj):
            if obj == "a":
                raise RuntimeError("a is broken")
            return obj

        rec = harness.register("picky", only_a_fails)
        assert rec("a", item="a") is None
        assert rec("b", item="b") == "b"  # a's backoff is not b's problem

    def test_result_requeue_after_defers_without_failure(self):
        clock, harness = self._harness()
        calls = []

        def periodic():
            calls.append(clock.now())
            return Result(requeue_after=10.0)

        rec = harness.register("periodic", periodic)
        rec()
        rec()  # deferred — not due yet
        assert len(calls) == 1
        clock.step(10.0)
        rec()
        assert len(calls) == 2
        assert harness._consecutive.get("periodic", 0) == 0

    def test_degraded_controllers_require_consecutive_failures(self):
        clock, harness = self._harness()
        flaky = {"fail": True}

        def sometimes():
            if flaky["fail"]:
                raise RuntimeError("nope")

        rec = harness.register("sometimes", sometimes)
        for _ in range(2):
            rec()
            clock.step(120.0)
        assert harness.degraded_controllers() == []
        rec()
        clock.step(120.0)
        assert harness.degraded_controllers() == ["sometimes"]
        flaky["fail"] = False
        rec()
        assert harness.degraded_controllers() == []


def make_operator(options=None):
    clock = FakeClock()
    store = Store(clock=clock)
    provider = KwokCloudProvider(store, clock)
    op = Operator(store, provider, clock=clock, options=options or Options())
    return clock, store, op


def settle(clock, op, passes=12, step=2.0):
    for _ in range(passes):
        clock.step(step)
        op.run_once()


class TestChaosIsolation:
    """ISSUE 3 acceptance: a controller stubbed to raise on every reconcile
    must not stop run_once, other controllers' writes still land, the error
    metric increments, and healthy() flips to degraded."""

    def test_failing_controller_does_not_take_down_the_pass(self):
        clock, store, op = make_operator()
        raises = {"n": 0}

        def boom(*args, **kwargs):
            raises["n"] += 1
            raise RuntimeError("injected chaos")

        # consistency runs in the per-claim dispatch/resync path, between
        # hydration and the nodepool controllers — a worst-case blast radius
        op.r_consistency.fn = boom
        errors0 = RECONCILE_ERRORS.value({"controller": "nodeclaim.consistency"})
        store.create(nodepool("workers"))
        for _ in range(3):
            store.create(unschedulable_pod(requests={"cpu": "1"}))
        settle(clock, op)
        # the chaos controller really ran and raised...
        assert raises["n"] >= 1
        assert (
            RECONCILE_ERRORS.value({"controller": "nodeclaim.consistency"})
            > errors0
        )
        # ...and everything else still made progress: pods became nodes
        assert len(store.list("Node")) >= 1
        for claim in store.list("NodeClaim"):
            assert claim.condition_is_true("Launched")
            assert claim.condition_is_true("Registered")
        assert all(p.spec.node_name for p in store.list("Pod"))

    def test_healthy_flips_to_degraded_and_recovers(self):
        clock, store, op = make_operator()
        assert op.healthy() is True

        real_fn = op.r_disruption.fn

        def boom(*args, **kwargs):
            raise RuntimeError("injected chaos")

        # disruption is a singleton that runs every pass
        op.r_disruption.fn = boom
        store.create(nodepool("workers"))
        settle(clock, op, passes=8, step=70.0)  # outlive every backoff
        snap = op.health_snapshot()
        assert op.healthy() is False
        assert snap["status"] == "degraded"
        assert any("disruption" in r for r in snap["degraded_reasons"])
        assert snap["controllers"]["disruption"]["consecutive_failures"] >= 3
        assert "injected chaos" in snap["controllers"]["disruption"]["last_error"]
        # fix the controller: one clean reconcile restores health
        op.r_disruption.fn = real_fn
        settle(clock, op, passes=4, step=70.0)
        assert op.healthy() is True

    def test_wedged_before_first_pass_goes_stale(self):
        """An operator that never completes even its FIRST pass (hung
        resync, deadlocked controller) must degrade after the grace
        window, not report healthy forever."""
        clock, store, op = make_operator()
        assert op.healthy() is True  # inside the startup grace window
        clock.step(61.0)  # STALE_PASS_AFTER with no pass ever landing
        assert op.healthy() is False
        assert any(
            "pass" in r for r in op.health_snapshot()["degraded_reasons"]
        )
        op.run_once()  # the loop comes alive: healthy again
        assert op.healthy() is True

    def test_snapshot_reports_pass_liveness_and_solverd(self):
        clock, store, op = make_operator()
        snap = op.health_snapshot()
        assert snap["passes"] == 0
        assert snap["last_successful_pass"] is None
        assert op.ready() is False
        op.run_once()
        snap = op.health_snapshot()
        assert snap["passes"] == 1
        assert snap["seconds_since_last_pass"] == 0.0
        assert snap["solverd"]["reachable"] is True
        assert snap["cloud_provider_breaker"]["state"] == "closed"
        assert op.ready() is True


class _AngryProvider(KwokCloudProvider):
    """create/delete fail like a dead cloud API until switched off."""

    def __init__(self, store, clock):
        super().__init__(store, clock)
        self.broken = True
        self.create_attempts = 0

    def create(self, node_claim):
        self.create_attempts += 1
        if self.broken:
            raise RuntimeError("cloud API down")
        return super().create(node_claim)

    def delete(self, node_claim):
        if self.broken:
            raise RuntimeError("cloud API down")
        return super().delete(node_claim)


class TestCloudProviderBreaker:
    def test_opens_fast_fails_and_recovers(self):
        clock = FakeClock()
        store = Store(clock=clock)
        provider = BreakerCloudProvider(
            _AngryProvider(store, clock), clock, threshold=3, cooldown=30.0
        )
        claim_store = Store(clock=clock)  # claims only, keep kwok happy
        from test_sim_faults import make_claim

        claim = make_claim(claim_store)
        for _ in range(3):
            with pytest.raises(RuntimeError):
                provider.create(claim)
        # open: fast-fail with the typed error, inner never called
        attempts = provider._inner.create_attempts
        with pytest.raises(CircuitBreakerOpenError) as exc:
            provider.create(claim)
        assert provider._inner.create_attempts == attempts
        assert exc.value.retry_after > 0
        assert exc.value.condition_reason == "CloudProviderCircuitOpen"
        # delete shares the breaker
        with pytest.raises(CircuitBreakerOpenError):
            provider.delete(claim)
        # recovery: cooldown elapses, the cloud is back, probe closes it
        provider._inner.broken = False
        clock.step(30.0)
        created = provider.create(claim)
        assert created.status.provider_id
        assert provider.breaker.state == "closed"

    def test_domain_errors_break_the_streak(self):
        """A typed not-found from delete is the cloud ANSWERING — it must
        reset the consecutive-failure streak instead of extending it."""
        clock = FakeClock()
        store = Store(clock=clock)

        class _NotFoundProvider(_AngryProvider):
            def delete(self, node_claim):
                raise NodeClaimNotFoundError("gone")

        provider = BreakerCloudProvider(
            _NotFoundProvider(store, clock), clock, threshold=2
        )
        provider.breaker.consecutive_failures = 1
        with pytest.raises(NodeClaimNotFoundError):
            provider.delete(None)
        assert provider.breaker.consecutive_failures == 0
        assert provider.breaker.state == "closed"

    def test_retryable_classification(self):
        assert is_retryable_error(RuntimeError("boom"))
        assert not is_retryable_error(NodeClaimNotFoundError())
        assert not is_retryable_error(InsufficientCapacityError())
        assert not is_retryable_error(CircuitBreakerOpenError("open"))

    def test_breaker_state_metric_tracks_transitions(self):
        clock = FakeClock()
        store = Store(clock=clock)
        provider = BreakerCloudProvider(
            _AngryProvider(store, clock), clock, threshold=1, cooldown=5.0
        )
        gauge = global_registry.get(
            "karpenter_cloudprovider_circuit_breaker_state"
        )
        labels = {"provider": "kwok"}
        assert gauge.value(labels) == 0.0
        with pytest.raises(RuntimeError):
            provider.create(type("C", (), {"metadata": None})())
        assert gauge.value(labels) == 2.0
        provider._inner.broken = False
        clock.step(5.0)
        from test_sim_faults import make_claim

        provider.create(make_claim(Store(clock=clock)))
        assert gauge.value(labels) == 0.0


class TestHealthServing:
    """/healthz serves the structured snapshot (503 when degraded) and
    /debug/health always returns the full document."""

    def _get(self, port, path):
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=5
            ) as resp:
                return resp.status, resp.read().decode()
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode()

    def test_healthz_json_and_debug_health(self):
        from karpenter_tpu.operator.serving import Server, ServingConfig

        state = {"healthy": True}

        def snapshot():
            return {
                "healthy": state["healthy"],
                "status": "ok" if state["healthy"] else "degraded",
                "degraded_reasons": [] if state["healthy"] else ["boom"],
            }

        config = ServingConfig(
            metrics_text=lambda: "",
            healthy=lambda: state["healthy"],
            ready=lambda: True,
            health_snapshot=snapshot,
        )
        server = Server(0, config).start()
        try:
            code, body = self._get(server.port, "/healthz")
            assert code == 200
            assert json.loads(body)["status"] == "ok"
            state["healthy"] = False
            code, body = self._get(server.port, "/healthz")
            assert code == 503
            assert json.loads(body)["degraded_reasons"] == ["boom"]
            # the debug surface always answers 200 with the full document
            code, body = self._get(server.port, "/debug/health")
            assert code == 200
            assert json.loads(body)["status"] == "degraded"
        finally:
            server.stop()

    def test_operator_end_to_end_snapshot_over_http(self):
        from karpenter_tpu.operator.serving import Server, ServingConfig

        clock, store, op = make_operator()
        store.create(nodepool("workers"))
        op.run_once()
        config = ServingConfig(
            metrics_text=op.metrics_text,
            healthy=op.healthy,
            ready=op.ready,
            health_snapshot=op.health_snapshot,
        )
        server = Server(0, config).start()
        try:
            code, body = self._get(server.port, "/healthz")
            assert code == 200
            snap = json.loads(body)
            assert snap["healthy"] is True
            assert snap["cloud_provider_breaker"]["state"] == "closed"
            assert "nodeclaim.lifecycle" in snap["controllers"]
        finally:
            server.stop()
