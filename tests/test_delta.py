"""Incremental delta solves (ops/delta.py): delta-vs-full identity + the
invalidation pathologies.

The contract under test is the ISSUE's acceptance line: with residency on,
every pass's decisions, error strings, and counters are bit-identical to a
from-scratch full solve — the delta path may be slower than designed,
never wrong. Coverage: the content-fingerprinted encode cache (bytes
re-encoded scale with churn, not cluster), seeded churn fuzz at the
GroupSolver level, the warm scan-resume path end to end through the
scheduler, the self-check cadence with an injected divergence (typed
event + fallback + residency drop), and every invalidation rule
(generation stamp, capacity overflow, engine rebuild, service close,
invalidate_all)."""

import numpy as np
import pytest

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.cloudprovider.kwok.instance_types import construct_instance_types
from karpenter_tpu.ops import delta, fused
from karpenter_tpu.ops.catalog import CatalogEngine
from karpenter_tpu.ops.packer import GroupSolver, encode_pods_for_packer
from karpenter_tpu.scheduling.requirements import Operator, Requirement, Requirements

from helpers import nodepool
from test_fused import plain_pods
from test_scheduler import Env

CATALOG = construct_instance_types()
ZONES = ["kwok-zone-1", "kwok-zone-2", "kwok-zone-3", "kwok-zone-4"]


@pytest.fixture
def delta_on():
    old_mode, old_every = delta.DELTA_MODE, delta.RESOLVE_FULL_EVERY
    delta.configure(mode="on", resolve_full_every=4)
    delta.invalidate_all("test-setup")
    yield
    delta.configure(mode=old_mode, resolve_full_every=old_every)
    delta.invalidate_all("test-teardown")


@pytest.fixture
def fused_on():
    old = fused.FUSED_MODE
    fused.FUSED_MODE = "on"
    yield
    fused.FUSED_MODE = old


def build_shapes(n: int = 10):
    """Value-stable requirement shapes, FRESH objects every call — the
    watch-churn pattern the content fingerprint exists for."""
    shapes = []
    for i in range(n):
        reqs = Requirements(Requirement(wk.LABEL_OS, Operator.IN, ["linux"]))
        if i % 2:
            reqs.add(Requirement(wk.LABEL_ARCH, Operator.IN, ["amd64"]))
        if i % 3 == 0:
            reqs.add(
                Requirement(wk.LABEL_TOPOLOGY_ZONE, Operator.IN, [ZONES[i % 4]])
            )
        shapes.append(reqs)
    return shapes


def churn_batch(engine, rng, shapes, pods: int):
    picks = rng.randint(len(shapes), size=pods)
    reqs_list = [shapes[i] for i in picks]
    requests = np.zeros((pods, len(engine.resource_dims)), dtype=np.float64)
    requests[:, engine.resource_dims[wk.RESOURCE_CPU]] = rng.choice(
        [0.1, 0.5, 1.0, 2.0], size=pods
    )
    requests[:, engine.resource_dims[wk.RESOURCE_MEMORY]] = (
        rng.choice([128, 512, 1024], size=pods) * 2**20
    )
    requests[:, engine.resource_dims[wk.RESOURCE_PODS]] = 1.0
    return reqs_list, requests


class TestEncodeCache:
    def test_content_fingerprint_reuses_rebuilt_shapes(self, delta_on):
        """Pass 2 rebuilds every Requirements object (same values) — all
        shapes must content-hit with ZERO bytes re-encoded."""
        engine = CatalogEngine(CATALOG)
        rng = np.random.RandomState(11)
        shapes1 = build_shapes()
        reqs1, requests = churn_batch(engine, rng, shapes1, 200)
        cold = None
        # cold reference from a delta-off encode of the same batch
        old = delta.DELTA_MODE
        delta.configure(mode="off")
        try:
            cold = encode_pods_for_packer(engine, reqs1, requests)
        finally:
            delta.configure(mode=old)
        g1 = encode_pods_for_packer(engine, reqs1, requests)
        cache = delta.encode_cache(engine)
        assert cache.last_pass_misses > 0 and cache.last_pass_bytes > 0
        shapes2 = build_shapes()
        assert all(a is not b for a, b in zip(shapes1, shapes2))
        # same picks, fresh objects: rebuild the list against shapes2
        id_of = {id(s): i for i, s in enumerate(shapes1)}
        reqs2 = [shapes2[id_of[id(r)]] for r in reqs1]
        g2 = encode_pods_for_packer(engine, reqs2, requests)
        assert cache.last_pass_misses == 0
        assert cache.last_pass_bytes == 0
        assert cache.last_pass_hits > 0
        for name in (
            "membership", "requests_q", "key_present", "counts", "group_of_pod"
        ):
            np.testing.assert_array_equal(getattr(cold, name), getattr(g1, name))
            np.testing.assert_array_equal(getattr(cold, name), getattr(g2, name))

    def test_bytes_scale_with_churn_not_cluster(self, delta_on):
        """Doubling the POD count re-encodes nothing new; adding one new
        SHAPE re-encodes exactly that shape's rows."""
        engine = CatalogEngine(CATALOG)
        rng = np.random.RandomState(12)
        shapes = build_shapes()
        reqs, requests = churn_batch(engine, rng, shapes, 100)
        encode_pods_for_packer(engine, reqs, requests)
        cache = delta.encode_cache(engine)
        # cluster doubles, zero new shapes -> zero bytes
        reqs2, requests2 = churn_batch(engine, rng, shapes, 200)
        encode_pods_for_packer(engine, reqs2, requests2)
        assert cache.last_pass_bytes == 0
        # one genuinely new shape -> small, nonzero
        novel = Requirements(
            Requirement(wk.CAPACITY_TYPE_LABEL_KEY, Operator.IN, ["spot"])
        )
        reqs3 = list(reqs2) + [novel]
        requests3 = np.vstack([requests2, requests2[-1:]])
        encode_pods_for_packer(engine, reqs3, requests3)
        assert cache.last_pass_misses == 1
        assert 0 < cache.last_pass_bytes < 10_000

    def test_capacity_overflow_resets_and_meters(self, delta_on, monkeypatch):
        monkeypatch.setattr(delta.EncodeCache, "MAX_SHAPES", 4)
        engine = CatalogEngine(CATALOG)
        cache = delta.encode_cache(engine)
        c0 = delta.delta_counters().get("delta_invalidations", 0)
        cache.begin_pass()
        for i in range(8):
            reqs = Requirements(
                Requirement(wk.LABEL_TOPOLOGY_ZONE, Operator.IN, [f"z-{i}"])
            )
            cache.lookup(engine, reqs, engine.num_rows)
        cache.end_pass()
        assert len(cache._by_content) <= 4
        assert delta.delta_counters()["delta_invalidations"] > c0


class TestGroupDeltaFuzz:
    def test_churn_stream_bit_identical_to_full(self, delta_on):
        """Seeded churn stream: every pass's delta result equals a
        from-scratch _solve_full on the same grouped batch, bit for bit."""
        engine = CatalogEngine(CATALOG)
        solver = GroupSolver(engine)
        rng = np.random.RandomState(21)
        res = delta.group_residency(solver)
        warm_seen = False
        for p in range(7):
            # churn: rebuild value-identical shapes each pass, vary batch
            shapes = build_shapes(8 + (p % 3))
            reqs, requests = churn_batch(engine, rng, shapes, 60 + 20 * p)
            grouped = encode_pods_for_packer(engine, reqs, requests)
            got = solver.solve(grouped)
            full = solver._solve_full(grouped)
            for a, b in zip(got, full):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            warm_seen = warm_seen or res.last_mode == "warm"
        assert warm_seen, "churn stream never reached a warm group pass"
        assert res.warm_passes > 0

    def test_count_only_churn_solves_zero_groups(self, delta_on):
        """Group COUNT changes (pods joining an existing shape — the
        dominant churn) must touch no resident slot."""
        engine = CatalogEngine(CATALOG)
        solver = GroupSolver(engine)
        rng = np.random.RandomState(22)
        shapes = build_shapes()
        reqs, requests = churn_batch(engine, rng, shapes, 120)
        grouped = encode_pods_for_packer(engine, reqs, requests)
        solver.solve(grouped)
        c0 = dict(delta.delta_counters())
        # identical shapes/requests, doubled counts
        grouped2 = encode_pods_for_packer(
            engine, reqs + reqs, np.vstack([requests, requests])
        )
        got = solver.solve(grouped2)
        full = solver._solve_full(grouped2)
        for a, b in zip(got, full):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        c1 = delta.delta_counters()
        assert c1["delta_groups_solved"] == c0.get("delta_groups_solved", 0)
        assert c1["delta_groups_reused"] > c0.get("delta_groups_reused", 0)

    def test_generation_bump_invalidates(self, delta_on):
        engine = CatalogEngine(CATALOG)
        solver = GroupSolver(engine)
        rng = np.random.RandomState(23)
        shapes = build_shapes()
        reqs, requests = churn_batch(engine, rng, shapes, 80)
        solver.solve(encode_pods_for_packer(engine, reqs, requests))
        res = delta.group_residency(solver)
        assert res.core is not None
        gen0 = res.gen
        # intern a NEW requirement row: the row generation stamp moves
        novel = Requirements(
            Requirement("example.com/delta-novel-row", Operator.EXISTS)
        )
        engine.rows_for(novel)
        engine._ensure_rows()
        c0 = delta.delta_counters().get("delta_invalidations", 0)
        got = solver.solve(encode_pods_for_packer(engine, reqs, requests))
        full = solver._solve_full(encode_pods_for_packer(engine, reqs, requests))
        for a, b in zip(got, full):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert res.gen != gen0
        assert delta.delta_counters()["delta_invalidations"] > c0

    def test_slot_capacity_overflow_resets(self, delta_on, monkeypatch):
        monkeypatch.setattr(delta, "MAX_GROUP_SLOTS", 4)
        engine = CatalogEngine(CATALOG)
        solver = GroupSolver(engine)
        rng = np.random.RandomState(24)
        shapes = build_shapes()
        reqs, requests = churn_batch(engine, rng, shapes, 120)
        grouped = encode_pods_for_packer(engine, reqs, requests)
        got = solver.solve(grouped)
        full = solver._solve_full(grouped)
        for a, b in zip(got, full):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_injected_divergence_fires_event_and_falls_back(self, delta_on):
        """Corrupt the resident core matrix, force the self-check every
        warm pass: the check must catch it, fire the divergence callback,
        drop the residency, and return the FULL result."""
        delta.configure(resolve_full_every=1)
        engine = CatalogEngine(CATALOG)
        solver = GroupSolver(engine)
        rng = np.random.RandomState(25)
        shapes = build_shapes()
        reqs, requests = churn_batch(engine, rng, shapes, 100)
        grouped = encode_pods_for_packer(engine, reqs, requests)
        solver.solve(grouped)
        res = delta.group_residency(solver)
        assert res.core is not None
        import jax.numpy as jnp

        # flip every resident choice to an absurd value
        res.core = res.core.at[:, 0].set(jnp.int32(7))
        fired = []
        delta.on_divergence(lambda k, d: fired.append((k, d)), key="test")
        try:
            got = solver.solve(grouped)
        finally:
            delta.on_divergence(lambda k, d: None, key="test")
        full = solver._solve_full(grouped)
        for a, b in zip(got, full):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert fired and fired[0][0] == "packer.solve_block"
        assert res.core is None  # residency dropped
        assert delta.delta_counters()["delta_selfchecks_divergent"] >= 1


class TestScanResidency:
    def test_repeat_solve_warm_resumes_bit_identical(self, delta_on, fused_on):
        """The coalescer shape: the same batch re-solved back to back warm-
        resumes (empty suffix) with identical claims and zero errors."""
        engine = CatalogEngine(CATALOG)
        env = Env(node_pools=[nodepool("default")], engine=engine)

        def canon(results):
            return sorted(
                (
                    sorted(p.metadata.name for p in nc.pods),
                    sorted(
                        it.name for it in nc.instance_type_options
                    ),
                )
                for nc in results.new_node_claims
            )

        r1 = env.schedule(plain_pods(96, cpus=("1",)))
        assert not r1.pod_errors
        res = delta.scan_residency(engine)
        assert res.state is not None and res.extendable
        c0 = dict(delta.delta_counters())
        r2 = env.schedule(plain_pods(96, cpus=("1",)))
        assert not r2.pod_errors
        assert canon(r1) == canon(r2)
        c1 = delta.delta_counters()
        assert c1["delta_scan_warm"] > c0.get("delta_scan_warm", 0)
        assert res.last_outcome == "warm"

    def test_suffix_arrivals_extend_warm(self, delta_on, fused_on):
        """Uniform-shape arrivals extend the previous stream as an exact
        suffix — the shape-stable churn the warm path is built for."""
        engine = CatalogEngine(CATALOG)
        env = Env(node_pools=[nodepool("default")], engine=engine)
        env.schedule(plain_pods(96, cpus=("1",)))
        c0 = dict(delta.delta_counters())
        r = env.schedule(plain_pods(128, cpus=("1",)))
        assert not r.pod_errors
        c1 = delta.delta_counters()
        assert c1["delta_scan_warm"] > c0.get("delta_scan_warm", 0)

    def test_mixed_size_arrival_misses_prefix_but_stays_correct(
        self, delta_on, fused_on
    ):
        """A LARGER new pod sorts to the front of the FFD stream — the
        prefix contract breaks, the pass must go cold, and the decisions
        must still match a delta-off solve."""
        engine = CatalogEngine(CATALOG)
        env = Env(node_pools=[nodepool("default")], engine=engine)
        env.schedule(plain_pods(96, cpus=("1",)))
        res = delta.scan_residency(engine)
        assert res.state is not None
        pods2 = plain_pods(97, cpus=("4",))
        r_delta = env.schedule(pods2)
        assert res.last_outcome in ("prefix", "operands", "rung")
        old = delta.DELTA_MODE
        delta.configure(mode="off")
        try:
            r_off = env.schedule(plain_pods(97, cpus=("4",)))
        finally:
            delta.configure(mode=old)

        def canon(results):
            return sorted(
                (
                    sorted(p.metadata.name for p in nc.pods),
                    sorted(it.name for it in nc.instance_type_options),
                )
                for nc in results.new_node_claims
            )

        assert canon(r_delta) == canon(r_off)
        assert {k.metadata.name: str(v) for k, v in r_delta.pod_errors.items()} == {
            k.metadata.name: str(v) for k, v in r_off.pod_errors.items()
        }

    def test_scan_selfcheck_divergence_drops_residency(self, delta_on, fused_on):
        """Corrupt the resident scan state; the every-pass self-check must
        fire the divergence event, fall back to the cold result, and drop
        the residency."""
        delta.configure(resolve_full_every=1)
        engine = CatalogEngine(CATALOG)
        env = Env(node_pools=[nodepool("default")], engine=engine)
        r1 = env.schedule(plain_pods(96, cpus=("1",)))
        res = delta.scan_residency(engine)
        assert res.state is not None
        import jax.numpy as jnp

        # corrupt pod_node (state component 10, a _SCAN_OUT_IDX output)
        state = list(res.state)
        state[10] = jnp.asarray(np.asarray(state[10]) + 7)
        res.state = tuple(state)
        fired = []
        delta.on_divergence(lambda k, d: fired.append((k, d)), key="test")
        try:
            r2 = env.schedule(plain_pods(96, cpus=("1",)))
        finally:
            delta.on_divergence(lambda k, d: None, key="test")
        assert not r2.pod_errors

        def canon(results):
            return sorted(
                (
                    sorted(p.metadata.name for p in nc.pods),
                    sorted(it.name for it in nc.instance_type_options),
                )
                for nc in results.new_node_claims
            )

        assert canon(r1) == canon(r2)
        assert fired and fired[0][0] == "packer.solve_scan"
        assert delta.delta_counters()["delta_selfchecks_divergent"] >= 1

    def test_small_batches_route_to_device_when_forced(self, delta_on, fused_on):
        """Satellite fix: below DEVICE_MIN_PODS, a forced fused+delta
        operator still takes the device path (no host resync) — and the
        decisions match the host walk."""
        from karpenter_tpu.ops import ffd

        engine = CatalogEngine(CATALOG)
        env = Env(node_pools=[nodepool("default")], engine=engine)
        d0 = ffd.DEVICE_SOLVES
        r = env.schedule(plain_pods(8, cpus=("1",)))
        assert not r.pod_errors
        assert ffd.DEVICE_SOLVES > d0


class TestInvalidationPathologies:
    def _seed_residencies(self):
        engine = CatalogEngine(CATALOG)
        solver = GroupSolver(engine)
        rng = np.random.RandomState(31)
        shapes = build_shapes()
        reqs, requests = churn_batch(engine, rng, shapes, 80)
        solver.solve(encode_pods_for_packer(engine, reqs, requests))
        sres = delta.scan_residency(engine)
        sres.state = (np.zeros(4, np.int32),)  # fake resident scan state
        return engine, solver

    def test_invalidate_all_drops_everything(self, delta_on):
        engine, solver = self._seed_residencies()
        cache = delta.encode_cache(engine)
        assert cache.stats()["shapes_cached"] > 0
        delta.invalidate_all("test-pathology")
        assert delta.group_residency(solver).core is None
        assert delta.scan_residency(engine).state is None
        assert cache.stats()["shapes_cached"] == 0

    def test_solverd_engine_rebuild_invalidates(self, delta_on):
        """A catalog change rebuilds the daemon engine — residencies
        stamped against the old engine must drop."""
        from karpenter_tpu.solverd.transport import _default_engine_factory

        engine, solver = self._seed_residencies()
        factory = _default_engine_factory()
        factory(list(CATALOG))  # cache miss -> rebuild -> invalidate_all
        assert delta.group_residency(solver).core is None
        assert delta.scan_residency(engine).state is None

    def test_service_close_invalidates(self, delta_on):
        from karpenter_tpu.solverd.service import SolverService

        engine, solver = self._seed_residencies()
        svc = SolverService()
        assert "delta" in svc.stats()
        svc.close()
        assert delta.group_residency(solver).core is None
        assert delta.scan_residency(engine).state is None

    def test_rollback_restore_invalidates(self, delta_on):
        """Topology.restore_counts — the device-fallback abort rollback —
        must drop residencies seeded by the aborted solve."""
        engine, solver = self._seed_residencies()
        env = Env(node_pools=[nodepool("default")], engine=engine)
        from karpenter_tpu.scheduler.topology import Topology

        topo = Topology(
            env.store, env.cluster, env.cluster.state_nodes(), env.node_pools,
            env.instance_types, [],
        )
        snap = topo.snapshot_counts()
        topo.restore_counts(snap)
        assert delta.group_residency(solver).core is None
        assert delta.scan_residency(engine).state is None

    def test_debug_view_surfaces_residencies(self, delta_on):
        from karpenter_tpu.observability import kernels as kobs

        engine, solver = self._seed_residencies()  # hold refs: the registry
        # is weakref-swept, so dropping them would empty the view
        view = kobs.registry().debug_snapshot(view="delta")
        assert view["enabled"] is True
        assert view["resolve_full_every"] == 4
        assert "delta_passes_cold" in view["counters"]
        assert view["group_residencies"], "seeded residency missing from view"
        assert view["resident_bytes"] > 0

    def test_ffd_counters_carry_delta_series(self, delta_on):
        from karpenter_tpu.ops import ffd

        snap = ffd.solver_cache_counters()
        assert "delta_passes_warm" in snap
        assert "delta_bytes_reencoded" in snap


class TestLadderFromObservatory:
    def test_scan_signature_roundtrip(self):
        """A real observed solve_scan signature parses back into the exact
        7-axis bucket that produced it."""
        from karpenter_tpu.aot import ladder

        sig = (
            "512,256,64x4,64x4,36x4,1x4,1x64,1x64,1x64,1x64x36,64x64,64x64,"
            "1x64x36,1,1,1x1,1x1,64x144,1x1,1x1x1,36x144,1,1x1,1,1x1,1x1,1"
        )
        dims = ladder._scan_signature_dims(sig)
        assert dims is not None
        P, G, C, N, F, T, L = dims
        assert (P, G, C) == (512, 64, 256)
        assert N == 0 and L == 0  # 1x1 dummies -> absent axes
        assert T == 1 and F == 64

    def test_from_observatory_buckets_scan(self):
        from karpenter_tpu.aot import ladder

        sig = (
            "512,256,64x4,64x4,36x4,1x4,1x64,1x64,1x64,1x64x36,64x64,64x64,"
            "1x64x36,1,1,1x1,1x1,64x144,1x1,1x1x1,36x144,1,1x1,1,1x1,1x1,1"
        )
        counts = {
            "packer.solve_scan": {"shapes": {sig: {"steady": 5}}},
        }
        lad = ladder.from_observatory(counts, headroom=1)
        buckets = lad.buckets("packer.solve_scan")
        assert (512, 64, 256, 0, 64, 1, 0) in buckets
