"""Topology-spread oracle: specs ported from the reference's topology suite
(pkg/controllers/provisioning/scheduling/topology_test.go — names kept,
source lines cited). Every spec runs on BOTH solver paths: the host per-pod
loop and the topo-aware device driver (ops/ffd_topo.py), which must make
identical decisions — device runs assert DEVICE_SOLVES advanced on every
solve, so an eligibility regression (silent fallback) fails loudly."""

import copy as _copy

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import (
    Affinity,
    LabelSelector,
    NodeAffinity,
    NodeSelectorTerm,
    PreferredSchedulingTerm,
    TopologySpreadConstraint,
)
from karpenter_tpu.utils import pod as podutil

from device_path import both_paths_fixture
from helpers import bind_pod, nodepool, registered_node, unschedulable_pod
from test_scheduler import Env as HostEnv

Env = HostEnv
path = both_paths_fixture(globals())

APP = {"app": "web"}


_APP_SELECTOR = object()  # sentinel: default to the app label selector


def spread(
    key=wk.LABEL_TOPOLOGY_ZONE,
    max_skew=1,
    when="DoNotSchedule",
    selector=_APP_SELECTOR,
    **kwargs,
):
    return TopologySpreadConstraint(
        max_skew=max_skew,
        topology_key=key,
        when_unsatisfiable=when,
        label_selector=LabelSelector(match_labels=dict(APP))
        if selector is _APP_SELECTOR
        else selector,
        **kwargs,
    )


def web_pod(constraints, requests=None, labels=None):
    return unschedulable_pod(
        requests=requests or {"cpu": "100m"},
        labels=dict(labels if labels is not None else APP),
        topology_spread_constraints=list(constraints),
    )


def zone_counts(results):
    """pods per zone across new claims; spread must have narrowed every
    claim to exactly one zone."""
    counts: dict[tuple, int] = {}
    for nc in results.new_node_claims:
        zones = tuple(sorted(nc.requirements.get(wk.LABEL_TOPOLOGY_ZONE).values_list()))
        assert len(zones) == 1, f"claim not narrowed to one zone: {zones}"
        counts[zones] = counts.get(zones, 0) + len(nc.pods)
    return counts


def skew_multiset(results, key=wk.LABEL_TOPOLOGY_ZONE):
    counts: dict[str, int] = {}
    for nc in results.new_node_claims:
        values = nc.requirements.get(key).values_list()
        assert len(values) == 1, f"claim not narrowed to one {key}: {values}"
        counts[values[0]] = counts.get(values[0], 0) + len(nc.pods)
    for en in results.existing_nodes:
        value = en.labels().get(key)
        counts[value] = counts.get(value, 0) + len(en.pods)
    return sorted(counts.values())


class TestZonalSpread:
    def test_ignore_unknown_topology_keys(self):
        # topology_test.go:60 — the constrained pod fails, the plain one lands
        env = Env()
        constrained = web_pod([spread(key="unknown")])
        plain = unschedulable_pod()
        results = env.schedule([constrained, plain])
        assert constrained in results.pod_errors
        assert plain not in results.pod_errors

    def test_balance_pods_across_zones_match_labels(self):
        # topology_test.go:95
        env = Env()
        results = env.schedule([web_pod([spread()]) for _ in range(9)])
        assert not results.pod_errors
        assert skew_multiset(results) == [2, 2, 2, 3]

    def test_balance_pods_across_zones_match_expressions(self):
        # topology_test.go:108
        selector = LabelSelector(
            match_expressions=[{"key": "app", "operator": "In", "values": ["web"]}]
        )
        env = Env()
        results = env.schedule(
            [web_pod([spread(selector=selector)]) for _ in range(9)]
        )
        assert not results.pod_errors
        assert skew_multiset(results) == [2, 2, 2, 3]

    def test_respect_nodepool_zonal_constraints(self):
        # topology_test.go:129 — domains limited to the pool's zones
        pools = [
            nodepool(
                "default",
                requirements=[
                    {
                        "key": wk.LABEL_TOPOLOGY_ZONE,
                        "operator": "In",
                        "values": ["kwok-zone-1", "kwok-zone-2"],
                    }
                ],
            )
        ]
        env = Env(node_pools=pools)
        results = env.schedule([web_pod([spread()]) for _ in range(6)])
        assert not results.pod_errors
        counts = zone_counts(results)
        assert all(z in (("kwok-zone-1",), ("kwok-zone-2",)) for z in counts)
        assert sorted(counts.values()) == [3, 3]

    def test_existing_pods_seed_domain_counts(self):
        # topology_test.go:219 — a running matching pod weights its zone
        node = registered_node(zone="kwok-zone-1", pool="default")
        existing = bind_pod(
            unschedulable_pod(requests={"cpu": "100m"}, labels=dict(APP)), node
        )
        env = Env(state_nodes=[node], pods=[existing])
        results = env.schedule([web_pod([spread()]) for _ in range(3)])
        assert not results.pod_errors
        # zone-1 already has 1: the three new pods take the other zones
        assert all(
            ("kwok-zone-1",) != z for z in zone_counts(results)
        )

    def test_non_minimum_domain_if_all_available(self):
        # topology_test.go:253 — maxSkew 5 against two seeded domains: the
        # pinned pool takes 6 pods in zone-3, the rest fail
        seeds = []
        state = []
        # seed nodes sized so they can't take another 1.1-cpu pod (the
        # reference uses rr=1.1 for the same reason)
        for i, zone in enumerate(("kwok-zone-1", "kwok-zone-2")):
            node = registered_node(
                name=f"seed-{i}", zone=zone, pool="default",
                capacity={"cpu": "1.5", "memory": "16Gi", "pods": "110"},
            )
            seeds.append(
                bind_pod(
                    unschedulable_pod(requests={"cpu": "1.1"}, labels=dict(APP)),
                    node,
                )
            )
            state.append(node)
        pools = [
            nodepool(
                "default",
                requirements=[
                    {
                        "key": wk.LABEL_TOPOLOGY_ZONE,
                        "operator": "In",
                        "values": ["kwok-zone-3"],
                    }
                ],
            )
        ]
        env = Env(node_pools=pools, state_nodes=state, pods=seeds)
        results = env.schedule(
            [web_pod([spread(max_skew=5)], requests={"cpu": "1.1"}) for _ in range(10)]
        )
        # zone-3 can reach min(1,1)+5 = 6; four pods cannot schedule
        # (reference asserts skew (1, 1, 6))
        assert len(results.pod_errors) == 4
        assert zone_counts(results) == {("kwok-zone-3",): 6}

    def test_min_domains_limits_scheduling_when_unsatisfiable(self):
        # topology_test.go:469 — minDomains above what the pool can offer
        pools = [
            nodepool(
                "default",
                requirements=[
                    {
                        "key": wk.LABEL_TOPOLOGY_ZONE,
                        "operator": "In",
                        "values": ["kwok-zone-1", "kwok-zone-2"],
                    }
                ],
            )
        ]
        env = Env(node_pools=pools)
        results = env.schedule([web_pod([spread(min_domains=3)]) for _ in range(3)])
        # unsatisfied minDomains pins the global min to 0, so each zone takes
        # maxSkew pods and the third pod fails (reference asserts skew (1,1))
        assert len(results.pod_errors) == 1
        assert skew_multiset(results) == [1, 1]

    def test_min_domains_satisfied_allows_scheduling(self):
        # topology_test.go:489
        env = Env()
        results = env.schedule([web_pod([spread(min_domains=4)]) for _ in range(4)])
        assert not results.pod_errors

    def test_match_all_pods_when_no_selector(self):
        # topology_test.go:432 — a NIL selector counts nothing, so the
        # constraint never binds and every pod schedules
        env = Env()
        results = env.schedule(
            [web_pod([spread(selector=None)]) for _ in range(4)]
        )
        assert not results.pod_errors


class TestScheduleAnyway:
    def test_schedule_anyway_violates_skew(self):
        # topology_test.go:703 analog — ScheduleAnyway pods relax the spread
        # once nothing else fits (nodepool pinned to one zone)
        pools = [
            nodepool(
                "default",
                requirements=[
                    {
                        "key": wk.LABEL_TOPOLOGY_ZONE,
                        "operator": "In",
                        "values": ["kwok-zone-1"],
                    }
                ],
            )
        ]
        env = Env(node_pools=pools)
        results = env.schedule(
            [web_pod([spread(when="ScheduleAnyway")]) for _ in range(5)]
        )
        assert not results.pod_errors
        assert zone_counts(results) == {("kwok-zone-1",): 5}


class TestCapacityTypeAndHostname:
    def test_balance_pods_across_capacity_types(self):
        # topology_test.go:640
        env = Env()
        results = env.schedule(
            [web_pod([spread(key=wk.CAPACITY_TYPE_LABEL_KEY)]) for _ in range(4)]
        )
        assert not results.pod_errors
        assert skew_multiset(results, key=wk.CAPACITY_TYPE_LABEL_KEY) == [2, 2]

    def test_respect_nodepool_capacity_type_constraints(self):
        # topology_test.go:653 — single capacity type: all pods land there
        pools = [
            nodepool(
                "default",
                requirements=[
                    {
                        "key": wk.CAPACITY_TYPE_LABEL_KEY,
                        "operator": "In",
                        "values": [wk.CAPACITY_TYPE_SPOT],
                    }
                ],
            )
        ]
        env = Env(node_pools=pools)
        results = env.schedule(
            [web_pod([spread(key=wk.CAPACITY_TYPE_LABEL_KEY)]) for _ in range(4)]
        )
        assert not results.pod_errors
        assert skew_multiset(results, key=wk.CAPACITY_TYPE_LABEL_KEY) == [4]

    def test_spread_respecting_hostname_and_zone(self):
        # topology_test.go:928 — both constraints hold simultaneously
        env = Env()
        results = env.schedule(
            [
                web_pod(
                    [spread(), spread(key=wk.LABEL_HOSTNAME, max_skew=1)],
                )
                for _ in range(4)
            ]
        )
        assert not results.pod_errors
        # hostname skew 1 forces one pod per claim; zones all distinct
        assert all(len(nc.pods) == 1 for nc in results.new_node_claims)
        assert skew_multiset(results) == [1, 1, 1, 1]


class TestMatchLabelKeys:
    def test_match_label_keys_scope_spread_per_value(self):
        # topology_test.go:1136 — pods spread independently per value of the
        # keyed label (two "revisions" of 4 pods each; each revision spreads
        # across all four zones on its own)
        env = Env()
        pods = []
        for revision in ("a", "b"):
            for _ in range(4):
                pods.append(
                    web_pod(
                        [spread(match_label_keys=["rev"])],
                        labels={**APP, "rev": revision},
                    )
                )
        results = env.schedule(pods)
        assert not results.pod_errors
        # each revision spreads independently: its 4 pods land one per zone
        for revision in ("a", "b"):
            rev_zones = []
            for nc in results.new_node_claims:
                zones = nc.requirements.get(wk.LABEL_TOPOLOGY_ZONE).values_list()
                assert len(zones) == 1
                rev_zones.extend(
                    zones[0]
                    for p in nc.pods
                    if p.metadata.labels.get("rev") == revision
                )
            assert sorted(rev_zones) == sorted(
                ["kwok-zone-1", "kwok-zone-2", "kwok-zone-3", "kwok-zone-4"]
            )

    def test_unknown_match_label_keys_ignored(self):
        # topology_test.go:1165
        env = Env()
        results = env.schedule(
            [web_pod([spread(match_label_keys=["not-a-label"])]) for _ in range(4)]
        )
        assert not results.pod_errors


class TestInterdependentSelectors:
    def test_interdependent_selectors(self):
        # topology_test.go:444 — pods whose spread selector matches a label
        # that only OTHER pods in the batch carry still schedule
        env = Env()
        pods = [
            unschedulable_pod(
                requests={"cpu": "100m"},
                labels={"group": "a" if i % 2 else "b"},
                topology_spread_constraints=[
                    TopologySpreadConstraint(
                        max_skew=1,
                        topology_key=wk.LABEL_TOPOLOGY_ZONE,
                        when_unsatisfiable="DoNotSchedule",
                        label_selector=LabelSelector(
                            match_labels={"group": "b" if i % 2 else "a"}
                        ),
                    )
                ],
            )
            for i in range(6)
        ]
        results = env.schedule(pods)
        assert not results.pod_errors


# ---------------------------------------------------------------------------
# Multi-pass harness: ExpectProvisioned / ExpectApplied / ExpectSkew analogs
# (test/expectations/expectations.go:617-642; kwok node fabrication)
# ---------------------------------------------------------------------------

def materialize(env, results, prefix):
    """ExpectProvisioned analog: fabricate a registered Node per new claim
    the way the kwok provider does at launch — cheapest compatible instance
    type, then cheapest available compatible offering; single-valued claim/
    type/offering requirements stamped as labels (provider.py:69-120) — but
    sized exactly to the claim's accumulated requests (the reference's
    rr-sized fake nodes are full once their pods land), and bind the claim's
    pods in the store so later passes count them as live cluster pods."""
    from karpenter_tpu.apis import labels as _wk

    for i, nc in enumerate(results.new_node_claims):
        it = min(nc.instance_type_options, key=lambda t: min(o.price for o in t.offerings))
        offering = next(
            o
            for o in sorted(it.offerings, key=lambda o: o.price)
            if o.available
            and nc.requirements.is_compatible(
                o.requirements, allow_undefined=_wk.WELL_KNOWN_LABELS
            )
        )
        labels = {}
        for source in (nc.requirements, it.requirements, offering.requirements):
            for r in source:
                if not r.complement and len(r.values) == 1 and r.key != wk.LABEL_HOSTNAME:
                    labels[r.key] = next(iter(r.values))
        labels[wk.LABEL_TOPOLOGY_ZONE] = offering.zone
        labels[wk.CAPACITY_TYPE_LABEL_KEY] = offering.capacity_type
        node = registered_node(
            name=f"{prefix}-{i}",
            pool=nc.nodepool_name,
            instance_type=it.name,
            zone=offering.zone,
            labels=labels,
        )
        cap = dict(nc.requests)
        cap.setdefault("pods", float(len(nc.pods)))
        node.status.capacity = cap
        node.status.allocatable = dict(cap)
        env.store.create(node)
        for p in nc.pods:
            bound = _copy.deepcopy(p)
            bind_pod(bound, node)
            env.store.create(bound)
    for en in results.existing_nodes:
        node = env.store.try_get("Node", en.name())
        for p in en.pods:
            bound = _copy.deepcopy(p)
            bind_pod(bound, node)
            env.store.create(bound)
    env.informer.flush()


def reapply(env, np):
    """ExpectApplied analog for a mutated NodePool: bump its version so the
    memoized domain-group scan (topology.py build_domain_groups) re-runs."""
    env.store.update(np)


def store_skew(env, key=wk.LABEL_TOPOLOGY_ZONE, match=None, namespace="default"):
    """ExpectSkew analog over the store (expectations.go:617-642): selector-
    matched pods in the namespace (TopologyListOptions is namespace-scoped),
    non-ignored, counted by their node's topology label (node NAME for
    hostname)."""
    match = APP if match is None else match
    counts: dict[str, int] = {}
    for p in env.store.list("Pod", namespace=namespace):
        if any(p.metadata.labels.get(k) != v for k, v in match.items()):
            continue
        if not podutil.is_scheduled(p) or podutil.is_terminal(p) or podutil.is_terminating(p):
            continue
        node = env.store.try_get("Node", p.spec.node_name)
        if node is None:
            continue
        if key == wk.LABEL_HOSTNAME:
            counts[node.metadata.name] = counts.get(node.metadata.name, 0) + 1
        else:
            domain = node.metadata.labels.get(key)
            if domain is not None:
                counts[domain] = counts.get(domain, 0) + 1
    return sorted(counts.values())


def zone_req(*zones):
    return {
        "key": wk.LABEL_TOPOLOGY_ZONE,
        "operator": "In",
        "values": list(zones),
    }


class TestNodePoolZonalSubsets:
    def test_subset_with_requirements(self):
        # topology_test.go:144
        env = Env(node_pools=[nodepool("default", requirements=[zone_req("kwok-zone-1", "kwok-zone-2")])])
        results = env.schedule([web_pod([spread()]) for _ in range(4)])
        assert not results.pod_errors
        assert skew_multiset(results) == [2, 2]
        assert all(
            set(z) <= {"kwok-zone-1", "kwok-zone-2"} for z in zone_counts(results)
        )

    def test_subset_with_labels(self):
        # topology_test.go:160 — a template zone LABEL narrows the universe
        # to that single zone
        env = Env(node_pools=[nodepool("default", labels={wk.LABEL_TOPOLOGY_ZONE: "kwok-zone-1"})])
        results = env.schedule([web_pod([spread()]) for _ in range(4)])
        assert not results.pod_errors
        assert skew_multiset(results) == [4]

    def test_subset_with_requirements_and_labels(self):
        # topology_test.go:175
        env = Env(
            node_pools=[
                nodepool(
                    "default",
                    requirements=[zone_req("kwok-zone-1", "kwok-zone-2")],
                    labels={wk.LABEL_TOPOLOGY_ZONE: "kwok-zone-1"},
                )
            ]
        )
        results = env.schedule([web_pod([spread()]) for _ in range(4)])
        assert not results.pod_errors
        assert skew_multiset(results) == [4]

    def test_subset_with_labels_across_nodepools(self):
        # topology_test.go:191 — two pools each pinned by label; the universe
        # is the union of the pinned zones
        env = Env(
            node_pools=[
                nodepool(
                    "default",
                    requirements=[zone_req("kwok-zone-1", "kwok-zone-2")],
                    labels={wk.LABEL_TOPOLOGY_ZONE: "kwok-zone-1"},
                ),
                nodepool("pool-b", labels={wk.LABEL_TOPOLOGY_ZONE: "kwok-zone-2"}),
            ]
        )
        results = env.schedule([web_pod([spread()]) for _ in range(4)])
        assert not results.pod_errors
        assert skew_multiset(results) == [2, 2]


class TestMultiPassSkew:
    def test_zonal_constraints_existing_pod(self):
        # topology_test.go:219 — an existing out-of-universe pod holds the
        # min count; the narrowed pool takes maxSkew above it per zone
        np = nodepool("default")
        env = Env(node_pools=[np])
        p0 = unschedulable_pod(
            requests={"cpu": "1.1"},
            labels=dict(APP),
            node_selector={wk.LABEL_TOPOLOGY_ZONE: "kwok-zone-3"},
        )
        first = env.schedule([p0])
        assert not first.pod_errors
        materialize(env, first, "pass1")
        np.spec.template.spec.requirements = [zone_req("kwok-zone-1", "kwok-zone-2")]
        reapply(env, np)
        second = env.schedule(
            [web_pod([spread()], requests={"cpu": "1.1"}) for _ in range(6)]
        )
        assert len(second.pod_errors) == 2
        materialize(env, second, "pass2")
        assert store_skew(env) == [1, 2, 2]

    def test_only_schedule_to_minimum_domains_if_violating_skew(self):
        # topology_test.go:295 — deleting pods creates skew; new pods recover
        # it by landing in the min-count domains only (3 zones as upstream)
        np = nodepool("default", requirements=[zone_req("kwok-zone-1", "kwok-zone-2", "kwok-zone-3")])
        env = Env(node_pools=[np])
        first = env.schedule(
            [web_pod([spread()], requests={"cpu": "1.1"}) for _ in range(9)]
        )
        assert not first.pod_errors
        assert skew_multiset(first) == [3, 3, 3]
        materialize(env, first, "pass1")
        for p in env.store.list("Pod"):
            node = env.store.try_get("Node", p.spec.node_name)
            if node and node.metadata.labels.get(wk.LABEL_TOPOLOGY_ZONE) != "kwok-zone-1":
                env.store.delete("Pod", p.metadata.name, p.metadata.namespace)
        env.informer.flush()
        assert store_skew(env) == [3]
        second = env.schedule(
            [web_pod([spread()], requests={"cpu": "1.1"}) for _ in range(3)]
        )
        assert not second.pod_errors
        materialize(env, second, "pass2")
        assert store_skew(env) == [1, 2, 3]

    def test_do_not_schedule_respects_prior_pass_counts(self):
        # topology_test.go:334 — a pod forced into zone-1, then the pool
        # narrowed to zones 2/3: two per zone, the rest unschedulable
        np = nodepool("default", requirements=[zone_req("kwok-zone-1")])
        env = Env(node_pools=[np])
        first = env.schedule([web_pod([spread()], requests={"cpu": "1.1"})])
        assert not first.pod_errors
        materialize(env, first, "pass1")
        np.spec.template.spec.requirements = [zone_req("kwok-zone-2", "kwok-zone-3")]
        reapply(env, np)
        second = env.schedule(
            [web_pod([spread()], requests={"cpu": "1.1"}) for _ in range(10)]
        )
        assert len(second.pod_errors) == 6
        materialize(env, second, "pass2")
        assert store_skew(env) == [1, 2, 2]

    def test_do_not_schedule_discovers_domains_from_unconstrained_pod(self):
        # topology_test.go:367 — the first pod carries NO constraint; its
        # zone still seeds the skew count for later constrained pods
        np = nodepool("default", requirements=[zone_req("kwok-zone-1")])
        env = Env(node_pools=[np])
        first = env.schedule(
            [unschedulable_pod(requests={"cpu": "1.1"}, labels=dict(APP))]
        )
        assert not first.pod_errors
        materialize(env, first, "pass1")
        np.spec.template.spec.requirements = [zone_req("kwok-zone-2", "kwok-zone-3")]
        reapply(env, np)
        second = env.schedule(
            [web_pod([spread()], requests={"cpu": "1.1"}) for _ in range(10)]
        )
        assert len(second.pod_errors) == 6
        materialize(env, second, "pass2")
        assert store_skew(env) == [1, 2, 2]

    def test_capacity_type_do_not_schedule_multi_pass(self):
        # topology_test.go:668 — spot pod first, then on-demand-only pool:
        # on-demand takes min+skew = 2, the rest fail
        np = nodepool(
            "default",
            requirements=[
                {
                    "key": wk.CAPACITY_TYPE_LABEL_KEY,
                    "operator": "In",
                    "values": [wk.CAPACITY_TYPE_SPOT],
                }
            ],
        )
        env = Env(node_pools=[np])
        ct_spread = spread(key=wk.CAPACITY_TYPE_LABEL_KEY)
        first = env.schedule([web_pod([ct_spread], requests={"cpu": "1.1"})])
        assert not first.pod_errors
        materialize(env, first, "pass1")
        np.spec.template.spec.requirements = [
            {
                "key": wk.CAPACITY_TYPE_LABEL_KEY,
                "operator": "In",
                "values": [wk.CAPACITY_TYPE_ON_DEMAND],
            }
        ]
        reapply(env, np)
        second = env.schedule(
            [
                web_pod([spread(key=wk.CAPACITY_TYPE_LABEL_KEY)], requests={"cpu": "1.1"})
                for _ in range(5)
            ]
        )
        assert len(second.pod_errors) == 3
        materialize(env, second, "pass2")
        assert store_skew(env, key=wk.CAPACITY_TYPE_LABEL_KEY) == [1, 2]


class TestTopologyCountingFilters:
    def test_only_counts_running_scheduled_matching_pods(self):
        # topology_test.go:399 — pending, terminal, terminating, unlabeled,
        # wrong-namespace, and domainless-node pods are all ignored
        np = nodepool("default", requirements=[zone_req("kwok-zone-1", "kwok-zone-2", "kwok-zone-3")])
        n1 = registered_node(name="n1", zone="kwok-zone-1")
        n2 = registered_node(name="n2", zone="kwok-zone-2")
        n3 = registered_node(name="n3", zone="kwok-zone-1")
        del n3.metadata.labels[wk.LABEL_TOPOLOGY_ZONE]  # missing domain
        seeds = []

        def seed(name, labels=None, node=None, phase=None, deleting=False, namespace="default"):
            p = unschedulable_pod(name=name, requests={"cpu": "10m"}, labels=labels or {})
            p.metadata.namespace = namespace
            if node is not None:
                bind_pod(p, node)
            if phase:
                p.status.phase = phase
            if deleting:
                p.metadata.deletion_timestamp = 10.0
            seeds.append(p)

        seed("ignored-unlabeled", labels={}, node=n1)
        seed("ignored-pending", labels=dict(APP))  # not bound
        seed("ignored-no-domain", labels=dict(APP), node=n3)
        seed("ignored-wrong-ns", labels=dict(APP), node=n1, namespace="other")
        seed("ignored-terminating", labels=dict(APP), node=n1, deleting=True)
        seed("ignored-failed", labels=dict(APP), node=n1, phase="Failed")
        seed("ignored-succeeded", labels=dict(APP), node=n1, phase="Succeeded")
        seed("counted-1", labels=dict(APP), node=n1)
        seed("counted-2", labels=dict(APP), node=n1)
        seed("counted-3", labels=dict(APP), node=n2)
        env = Env(node_pools=[np], state_nodes=[n1, n2, n3], pods=seeds)
        results = env.schedule([web_pod([spread()]) for _ in range(2)])
        assert not results.pod_errors
        materialize(env, results, "pass1")
        assert store_skew(env) == [1, 2, 2]


class TestMinDomainsExpanded:
    def test_min_domains_greater_than_minimum(self):
        # topology_test.go:509 — minDomains=2 over 3 zones, 11 pods
        env = Env(
            node_pools=[
                nodepool("default", requirements=[zone_req("kwok-zone-1", "kwok-zone-2", "kwok-zone-3")])
            ]
        )
        results = env.schedule(
            [web_pod([spread(min_domains=2)]) for _ in range(11)]
        )
        assert not results.pod_errors
        assert skew_multiset(results) == [3, 4, 4]


class TestHostnameBalancing:
    def test_balance_pods_across_nodes(self):
        # topology_test.go:532
        env = Env()
        results = env.schedule(
            [web_pod([spread(key=wk.LABEL_HOSTNAME)]) for _ in range(4)]
        )
        assert not results.pod_errors
        assert sorted(len(nc.pods) for nc in results.new_node_claims) == [1, 1, 1, 1]

    def test_balance_same_hostname_up_to_max_skew(self):
        # topology_test.go:545 — maxSkew 4 lets all four share one node
        env = Env()
        results = env.schedule(
            [web_pod([spread(key=wk.LABEL_HOSTNAME, max_skew=4)]) for _ in range(4)]
        )
        assert not results.pod_errors
        assert sorted(len(nc.pods) for nc in results.new_node_claims) == [4]

    def test_balance_multiple_deployments_hostname(self):
        # topology_test.go:558 (issue #1425) — two deployments spread over
        # hostname land on the minimum two nodes
        env = Env()
        pods = []
        for app in ("app1", "app2"):
            for _ in range(2):
                pods.append(
                    web_pod(
                        [spread(key=wk.LABEL_HOSTNAME, selector=LabelSelector(match_labels={"app": app}))],
                        labels={"app": app},
                    )
                )
        results = env.schedule(pods)
        assert not results.pod_errors
        assert len(results.new_node_claims) == 2

    def test_balance_multiple_deployments_hostname_varying_arch(self):
        # topology_test.go:594 — same, but arch split forces four nodes
        env = Env()
        pods = []
        for app, arch in (("app1", "amd64"), ("app2", "arm64")):
            for _ in range(2):
                pods.append(
                    unschedulable_pod(
                        requests={"cpu": "100m"},
                        labels={"app": app},
                        node_selector={wk.LABEL_ARCH: arch},
                        topology_spread_constraints=[
                            spread(
                                key=wk.LABEL_HOSTNAME,
                                selector=LabelSelector(match_labels={"app": app}),
                            )
                        ],
                    )
                )
        results = env.schedule(pods)
        assert not results.pod_errors
        assert len(results.new_node_claims) == 4


def store_max_skew(env, key=wk.LABEL_TOPOLOGY_ZONE, match=None):
    """ExpectMaxSkew analog (suite_test.go:4603-4640): max-min over counted
    domains."""
    counts = store_skew(env, key=key, match=match)
    return (counts[-1] - counts[0]) if counts else 0


class TestNodeInclusionPolicies:
    """topology_test.go:1193-1674 — NodeTaintsPolicy / NodeAffinityPolicy
    control which EXISTING nodes seed the domain universe."""

    def _tainted_node(self, name, label_value, extra_labels=None):
        from karpenter_tpu.apis.core import Taint

        return registered_node(
            name=name,
            capacity={"cpu": "100m", "memory": "1Gi", "pods": "110"},
            labels={"fake-label": label_value, **(extra_labels or {})},
            taints=[Taint(key="taintname", value="taintvalue", effect="NoSchedule")],
        )

    def test_node_taints_policy_ignore(self):
        # topology_test.go:1193 — tainted nodes still seed domains; only the
        # pool's own domain is schedulable so a single pod lands
        np = nodepool("default", labels={"fake-label": "baz"})
        nodes = [self._tainted_node("tn1", "foo"), self._tainted_node("tn2", "bar")]
        env = Env(node_pools=[np], state_nodes=nodes)
        results = env.schedule(
            [
                web_pod(
                    [spread(key="fake-label", node_taints_policy="Ignore")],
                    requests={"cpu": "1"},
                )
                for _ in range(5)
            ]
        )
        assert len(results.pod_errors) == 4
        materialize(env, results, "p1")
        assert store_skew(env, key="fake-label") == [1]

    def test_node_taints_policy_honor(self):
        # topology_test.go:1264 — intolerable tainted nodes are excluded
        # from domain discovery; the single remaining domain takes all pods
        np = nodepool("default", labels={"fake-label": "baz"})
        nodes = [self._tainted_node("tn1", "foo"), self._tainted_node("tn2", "bar")]
        env = Env(node_pools=[np], state_nodes=nodes)
        results = env.schedule(
            [
                web_pod(
                    [spread(key="fake-label", node_taints_policy="Honor")],
                    requests={"cpu": "1"},
                )
                for _ in range(5)
            ]
        )
        assert not results.pod_errors
        materialize(env, results, "p1")
        assert store_skew(env, key="fake-label") == [5]

    def _affinity_node(self, name, label_value):
        return registered_node(
            name=name,
            capacity={"cpu": "100m", "memory": "1Gi", "pods": "110"},
            labels={"fake-label": label_value, "selector": "mismatch"},
        )

    def test_node_affinity_policy_ignore(self):
        # topology_test.go:1542 — nodes the pod's selector can't reach still
        # seed domains, so skew blocks all but one pod
        np = nodepool("default", labels={"fake-label": "baz", "selector": "value"})
        nodes = [self._affinity_node("an1", "foo"), self._affinity_node("an2", "bar")]
        env = Env(node_pools=[np], state_nodes=nodes)
        results = env.schedule(
            [
                unschedulable_pod(
                    requests={"cpu": "1"},
                    labels=dict(APP),
                    node_selector={"selector": "value"},
                    topology_spread_constraints=[
                        spread(key="fake-label", node_affinity_policy="Ignore")
                    ],
                )
                for _ in range(5)
            ]
        )
        assert len(results.pod_errors) == 4
        materialize(env, results, "p1")
        assert store_skew(env, key="fake-label") == [1]

    def test_node_affinity_policy_honor(self):
        # topology_test.go:1609 — default Honor: unreachable nodes don't
        # seed domains; all pods land in the single reachable domain
        np = nodepool("default", labels={"fake-label": "baz", "selector": "value"})
        nodes = [self._affinity_node("an1", "foo"), self._affinity_node("an2", "bar")]
        env = Env(node_pools=[np], state_nodes=nodes)
        results = env.schedule(
            [
                unschedulable_pod(
                    requests={"cpu": "1"},
                    labels=dict(APP),
                    node_selector={"selector": "value"},
                    topology_spread_constraints=[
                        spread(key="fake-label", node_affinity_policy="Honor")
                    ],
                )
                for _ in range(5)
            ]
        )
        assert not results.pod_errors
        materialize(env, results, "p1")
        assert store_skew(env, key="fake-label") == [5]


class TestSpreadOptionLimiting:
    """topology_test.go:1753-1937 — node selectors/affinity narrow each
    pod's own domain choices without removing discovered domains."""

    def test_limit_spread_by_node_selector(self):
        # topology_test.go:1753 — zone pinned per pod: each pod's only valid
        # domain is its own zone, so both batches pack freely
        env = Env()
        pods = [
            web_pod([spread()], labels=dict(APP))
            for _ in range(5)
        ]
        for p in pods:
            p.spec.node_selector = {wk.LABEL_TOPOLOGY_ZONE: "kwok-zone-1"}
        pods2 = [
            web_pod([spread()], labels=dict(APP))
            for _ in range(10)
        ]
        for p in pods2:
            p.spec.node_selector = {wk.LABEL_TOPOLOGY_ZONE: "kwok-zone-2"}
        results = env.schedule(pods + pods2)
        assert not results.pod_errors
        assert skew_multiset(results) == [5, 10]

    def test_limit_spread_by_node_requirements(self):
        # topology_test.go:1779 — both zones allowed per pod: spread evenly
        env = Env()
        pods = []
        for _ in range(10):
            p = web_pod([spread()])
            p.spec.affinity = _zone_affinity("kwok-zone-1", "kwok-zone-2")
            pods.append(p)
        results = env.schedule(pods)
        assert not results.pod_errors
        assert skew_multiset(results) == [5, 5]

    def test_limit_spread_by_required_node_affinity_multi_pass(self):
        # topology_test.go:1801 — a later pod allowed into an empty zone
        # lands there even though it exceeds the old max, improving skew
        np = nodepool(
            "default",
            requirements=[zone_req("kwok-zone-1", "kwok-zone-2", "kwok-zone-3")],
        )
        env = Env(node_pools=[np])
        pods = []
        for _ in range(6):
            p = web_pod([spread()])
            p.spec.affinity = _zone_affinity("kwok-zone-1", "kwok-zone-2")
            pods.append(p)
        first = env.schedule(pods)
        assert not first.pod_errors
        materialize(env, first, "p1")
        assert store_skew(env) == [3, 3]
        p = web_pod([spread()])
        p.spec.affinity = _zone_affinity("kwok-zone-2", "kwok-zone-3")
        second = env.schedule([p])
        assert not second.pod_errors
        materialize(env, second, "p2")
        assert store_skew(env) == [1, 3, 3]
        third = env.schedule([web_pod([spread()]) for _ in range(5)])
        assert not third.pod_errors
        materialize(env, third, "p3")
        assert store_skew(env) == [4, 4, 4]

    def test_preferred_node_affinity_does_not_limit_spread(self):
        # topology_test.go:1845 — preference relaxes away; spread balances
        # over the full universe (pool pinned to 3 zones as upstream)
        np = nodepool(
            "default",
            requirements=[zone_req("kwok-zone-1", "kwok-zone-2", "kwok-zone-3")],
        )
        env = Env(node_pools=[np])
        pods = []
        for _ in range(6):
            p = web_pod([spread()])
            p.spec.affinity = Affinity(
                node_affinity=NodeAffinity(
                    preferred=[
                        PreferredSchedulingTerm(
                            weight=1,
                            preference=NodeSelectorTerm(
                                match_expressions=[
                                    zone_req("kwok-zone-1", "kwok-zone-2")
                                ]
                            ),
                        )
                    ]
                )
            )
            pods.append(p)
        results = env.schedule(pods)
        assert not results.pod_errors
        assert skew_multiset(results) == [2, 2, 2]

    def test_limit_spread_by_capacity_type_selector_schedule_anyway(self):
        # topology_test.go:1870
        env = Env()
        pods = []
        for ct, n in ((wk.CAPACITY_TYPE_SPOT, 5), (wk.CAPACITY_TYPE_ON_DEMAND, 5)):
            for _ in range(n):
                p = web_pod([spread(key=wk.CAPACITY_TYPE_LABEL_KEY, when="ScheduleAnyway")])
                p.spec.node_selector = {wk.CAPACITY_TYPE_LABEL_KEY: ct}
                pods.append(p)
        results = env.schedule(pods)
        assert not results.pod_errors
        assert skew_multiset(results, key=wk.CAPACITY_TYPE_LABEL_KEY) == [5, 5]

    def test_limit_spread_by_capacity_type_affinity_multi_pass(self):
        # topology_test.go:1894 — spot-only first, then opening to both
        # capacity types lets the empty one catch up
        env = Env()
        pods = []
        for _ in range(3):
            p = web_pod([spread(key=wk.CAPACITY_TYPE_LABEL_KEY)])
            p.spec.node_selector = {wk.CAPACITY_TYPE_LABEL_KEY: wk.CAPACITY_TYPE_SPOT}
            pods.append(p)
        first = env.schedule(pods)
        assert not first.pod_errors
        materialize(env, first, "p1")
        assert store_skew(env, key=wk.CAPACITY_TYPE_LABEL_KEY) == [3]
        p = web_pod([spread(key=wk.CAPACITY_TYPE_LABEL_KEY)])
        p.spec.affinity = Affinity(
            node_affinity=NodeAffinity(
                required=[
                    NodeSelectorTerm(
                        match_expressions=[
                            {
                                "key": wk.CAPACITY_TYPE_LABEL_KEY,
                                "operator": "In",
                                "values": [wk.CAPACITY_TYPE_ON_DEMAND, wk.CAPACITY_TYPE_SPOT],
                            }
                        ]
                    )
                ]
            )
        )
        second = env.schedule([p])
        assert not second.pod_errors
        materialize(env, second, "p2")
        assert store_skew(env, key=wk.CAPACITY_TYPE_LABEL_KEY) == [1, 3]
        third = env.schedule(
            [web_pod([spread(key=wk.CAPACITY_TYPE_LABEL_KEY)]) for _ in range(5)]
        )
        assert not third.pod_errors
        materialize(env, third, "p3")
        assert store_skew(env, key=wk.CAPACITY_TYPE_LABEL_KEY) == [4, 5]


def _zone_affinity(*zones):
    return Affinity(
        node_affinity=NodeAffinity(
            required=[NodeSelectorTerm(match_expressions=[zone_req(*zones)])]
        )
    )



class TestCombinedConstraints:
    def test_zone_spread_with_hostname_schedule_anyway_and_disabled_pool(self):
        # topology_test.go:1044 — a zero-limit pool disables its zone; the
        # hostname ScheduleAnyway spread puts one pod per node
        np_a = nodepool(
            "default", requirements=[zone_req("kwok-zone-1", "kwok-zone-2")]
        )
        np_b = nodepool(
            "pool-b", requirements=[zone_req("kwok-zone-3")], limits={"cpu": "0"}
        )
        env = Env(node_pools=[np_a, np_b])
        results = env.schedule(
            [
                web_pod(
                    [spread(), spread(key=wk.LABEL_HOSTNAME, when="ScheduleAnyway")]
                )
                for _ in range(10)
            ]
        )
        materialize(env, results, "p1")
        assert store_skew(env) == [1, 1]
        assert store_skew(env, key=wk.LABEL_HOSTNAME) == [1, 1]

    def test_capacity_type_and_hostname_spread_multi_pass(self):
        # topology_test.go:1087 — ct maxSkew 1 + hostname maxSkew 3 held
        # simultaneously across four passes
        env = Env()

        def batch(n):
            return [
                web_pod(
                    [
                        spread(key=wk.CAPACITY_TYPE_LABEL_KEY),
                        spread(key=wk.LABEL_HOSTNAME, max_skew=3),
                    ]
                )
                for _ in range(n)
            ]

        expected = [(2, [1, 1]), (3, [2, 3]), (5, [5, 5]), (11, [10, 11])]
        for i, (n, ct_skew) in enumerate(expected):
            results = env.schedule(batch(n))
            assert not results.pod_errors
            materialize(env, results, f"p{i}")
            assert store_skew(env, key=wk.CAPACITY_TYPE_LABEL_KEY) == ct_skew
            assert store_max_skew(env, key=wk.LABEL_HOSTNAME) <= 3

    def test_all_three_constraints_held_simultaneously(self):
        # topology_test.go:1715 — ct skew<=1, zone skew<=2, hostname skew<=3
        # maintained over growing batches
        env = Env()
        for i in range(1, 11):
            results = env.schedule(
                [
                    web_pod(
                        [
                            spread(key=wk.CAPACITY_TYPE_LABEL_KEY),
                            spread(max_skew=2),
                            spread(key=wk.LABEL_HOSTNAME, max_skew=3),
                        ]
                    )
                    for _ in range(i)
                ]
            )
            assert not results.pod_errors, (i, results.pod_errors)
            materialize(env, results, f"p{i}")
            assert store_max_skew(env, key=wk.CAPACITY_TYPE_LABEL_KEY) <= 1
            assert store_max_skew(env) <= 2
            assert store_max_skew(env, key=wk.LABEL_HOSTNAME) <= 3
