"""Topology-spread oracle: specs ported from the reference's topology suite
(pkg/controllers/provisioning/scheduling/topology_test.go — names kept,
source lines cited). Every spec runs on BOTH solver paths: the host per-pod
loop and the topo-aware device driver (ops/ffd_topo.py), which must make
identical decisions — device runs assert DEVICE_SOLVES advanced on every
solve, so an eligibility regression (silent fallback) fails loudly."""

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import LabelSelector, TopologySpreadConstraint

from device_path import both_paths_fixture
from helpers import bind_pod, nodepool, registered_node, unschedulable_pod
from test_scheduler import Env as HostEnv

Env = HostEnv
path = both_paths_fixture(globals())

APP = {"app": "web"}


_APP_SELECTOR = object()  # sentinel: default to the app label selector


def spread(
    key=wk.LABEL_TOPOLOGY_ZONE,
    max_skew=1,
    when="DoNotSchedule",
    selector=_APP_SELECTOR,
    **kwargs,
):
    return TopologySpreadConstraint(
        max_skew=max_skew,
        topology_key=key,
        when_unsatisfiable=when,
        label_selector=LabelSelector(match_labels=dict(APP))
        if selector is _APP_SELECTOR
        else selector,
        **kwargs,
    )


def web_pod(constraints, requests=None, labels=None):
    return unschedulable_pod(
        requests=requests or {"cpu": "100m"},
        labels=dict(labels if labels is not None else APP),
        topology_spread_constraints=list(constraints),
    )


def zone_counts(results):
    """pods per zone across new claims; spread must have narrowed every
    claim to exactly one zone."""
    counts: dict[tuple, int] = {}
    for nc in results.new_node_claims:
        zones = tuple(sorted(nc.requirements.get(wk.LABEL_TOPOLOGY_ZONE).values_list()))
        assert len(zones) == 1, f"claim not narrowed to one zone: {zones}"
        counts[zones] = counts.get(zones, 0) + len(nc.pods)
    return counts


def skew_multiset(results, key=wk.LABEL_TOPOLOGY_ZONE):
    counts: dict[str, int] = {}
    for nc in results.new_node_claims:
        values = nc.requirements.get(key).values_list()
        assert len(values) == 1, f"claim not narrowed to one {key}: {values}"
        counts[values[0]] = counts.get(values[0], 0) + len(nc.pods)
    for en in results.existing_nodes:
        value = en.labels().get(key)
        counts[value] = counts.get(value, 0) + len(en.pods)
    return sorted(counts.values())


class TestZonalSpread:
    def test_ignore_unknown_topology_keys(self):
        # topology_test.go:60 — the constrained pod fails, the plain one lands
        env = Env()
        constrained = web_pod([spread(key="unknown")])
        plain = unschedulable_pod()
        results = env.schedule([constrained, plain])
        assert constrained in results.pod_errors
        assert plain not in results.pod_errors

    def test_balance_pods_across_zones_match_labels(self):
        # topology_test.go:95
        env = Env()
        results = env.schedule([web_pod([spread()]) for _ in range(9)])
        assert not results.pod_errors
        assert skew_multiset(results) == [2, 2, 2, 3]

    def test_balance_pods_across_zones_match_expressions(self):
        # topology_test.go:108
        selector = LabelSelector(
            match_expressions=[{"key": "app", "operator": "In", "values": ["web"]}]
        )
        env = Env()
        results = env.schedule(
            [web_pod([spread(selector=selector)]) for _ in range(9)]
        )
        assert not results.pod_errors
        assert skew_multiset(results) == [2, 2, 2, 3]

    def test_respect_nodepool_zonal_constraints(self):
        # topology_test.go:129 — domains limited to the pool's zones
        pools = [
            nodepool(
                "default",
                requirements=[
                    {
                        "key": wk.LABEL_TOPOLOGY_ZONE,
                        "operator": "In",
                        "values": ["kwok-zone-1", "kwok-zone-2"],
                    }
                ],
            )
        ]
        env = Env(node_pools=pools)
        results = env.schedule([web_pod([spread()]) for _ in range(6)])
        assert not results.pod_errors
        counts = zone_counts(results)
        assert all(z in (("kwok-zone-1",), ("kwok-zone-2",)) for z in counts)
        assert sorted(counts.values()) == [3, 3]

    def test_existing_pods_seed_domain_counts(self):
        # topology_test.go:219 — a running matching pod weights its zone
        node = registered_node(zone="kwok-zone-1", pool="default")
        existing = bind_pod(
            unschedulable_pod(requests={"cpu": "100m"}, labels=dict(APP)), node
        )
        env = Env(state_nodes=[node], pods=[existing])
        results = env.schedule([web_pod([spread()]) for _ in range(3)])
        assert not results.pod_errors
        # zone-1 already has 1: the three new pods take the other zones
        assert all(
            ("kwok-zone-1",) != z for z in zone_counts(results)
        )

    def test_non_minimum_domain_if_all_available(self):
        # topology_test.go:253 — maxSkew 5 against two seeded domains: the
        # pinned pool takes 6 pods in zone-3, the rest fail
        seeds = []
        state = []
        # seed nodes sized so they can't take another 1.1-cpu pod (the
        # reference uses rr=1.1 for the same reason)
        for i, zone in enumerate(("kwok-zone-1", "kwok-zone-2")):
            node = registered_node(
                name=f"seed-{i}", zone=zone, pool="default",
                capacity={"cpu": "1.5", "memory": "16Gi", "pods": "110"},
            )
            seeds.append(
                bind_pod(
                    unschedulable_pod(requests={"cpu": "1.1"}, labels=dict(APP)),
                    node,
                )
            )
            state.append(node)
        pools = [
            nodepool(
                "default",
                requirements=[
                    {
                        "key": wk.LABEL_TOPOLOGY_ZONE,
                        "operator": "In",
                        "values": ["kwok-zone-3"],
                    }
                ],
            )
        ]
        env = Env(node_pools=pools, state_nodes=state, pods=seeds)
        results = env.schedule(
            [web_pod([spread(max_skew=5)], requests={"cpu": "1.1"}) for _ in range(10)]
        )
        # zone-3 can reach min(1,1)+5 = 6; four pods cannot schedule
        # (reference asserts skew (1, 1, 6))
        assert len(results.pod_errors) == 4
        assert zone_counts(results) == {("kwok-zone-3",): 6}

    def test_min_domains_limits_scheduling_when_unsatisfiable(self):
        # topology_test.go:469 — minDomains above what the pool can offer
        pools = [
            nodepool(
                "default",
                requirements=[
                    {
                        "key": wk.LABEL_TOPOLOGY_ZONE,
                        "operator": "In",
                        "values": ["kwok-zone-1", "kwok-zone-2"],
                    }
                ],
            )
        ]
        env = Env(node_pools=pools)
        results = env.schedule([web_pod([spread(min_domains=3)]) for _ in range(3)])
        # unsatisfied minDomains pins the global min to 0, so each zone takes
        # maxSkew pods and the third pod fails (reference asserts skew (1,1))
        assert len(results.pod_errors) == 1
        assert skew_multiset(results) == [1, 1]

    def test_min_domains_satisfied_allows_scheduling(self):
        # topology_test.go:489
        env = Env()
        results = env.schedule([web_pod([spread(min_domains=4)]) for _ in range(4)])
        assert not results.pod_errors

    def test_match_all_pods_when_no_selector(self):
        # topology_test.go:432 — a NIL selector counts nothing, so the
        # constraint never binds and every pod schedules
        env = Env()
        results = env.schedule(
            [web_pod([spread(selector=None)]) for _ in range(4)]
        )
        assert not results.pod_errors


class TestScheduleAnyway:
    def test_schedule_anyway_violates_skew(self):
        # topology_test.go:703 analog — ScheduleAnyway pods relax the spread
        # once nothing else fits (nodepool pinned to one zone)
        pools = [
            nodepool(
                "default",
                requirements=[
                    {
                        "key": wk.LABEL_TOPOLOGY_ZONE,
                        "operator": "In",
                        "values": ["kwok-zone-1"],
                    }
                ],
            )
        ]
        env = Env(node_pools=pools)
        results = env.schedule(
            [web_pod([spread(when="ScheduleAnyway")]) for _ in range(5)]
        )
        assert not results.pod_errors
        assert zone_counts(results) == {("kwok-zone-1",): 5}


class TestCapacityTypeAndHostname:
    def test_balance_pods_across_capacity_types(self):
        # topology_test.go:640
        env = Env()
        results = env.schedule(
            [web_pod([spread(key=wk.CAPACITY_TYPE_LABEL_KEY)]) for _ in range(4)]
        )
        assert not results.pod_errors
        assert skew_multiset(results, key=wk.CAPACITY_TYPE_LABEL_KEY) == [2, 2]

    def test_respect_nodepool_capacity_type_constraints(self):
        # topology_test.go:653 — single capacity type: all pods land there
        pools = [
            nodepool(
                "default",
                requirements=[
                    {
                        "key": wk.CAPACITY_TYPE_LABEL_KEY,
                        "operator": "In",
                        "values": [wk.CAPACITY_TYPE_SPOT],
                    }
                ],
            )
        ]
        env = Env(node_pools=pools)
        results = env.schedule(
            [web_pod([spread(key=wk.CAPACITY_TYPE_LABEL_KEY)]) for _ in range(4)]
        )
        assert not results.pod_errors
        assert skew_multiset(results, key=wk.CAPACITY_TYPE_LABEL_KEY) == [4]

    def test_spread_respecting_hostname_and_zone(self):
        # topology_test.go:928 — both constraints hold simultaneously
        env = Env()
        results = env.schedule(
            [
                web_pod(
                    [spread(), spread(key=wk.LABEL_HOSTNAME, max_skew=1)],
                )
                for _ in range(4)
            ]
        )
        assert not results.pod_errors
        # hostname skew 1 forces one pod per claim; zones all distinct
        assert all(len(nc.pods) == 1 for nc in results.new_node_claims)
        assert skew_multiset(results) == [1, 1, 1, 1]


class TestMatchLabelKeys:
    def test_match_label_keys_scope_spread_per_value(self):
        # topology_test.go:1136 — pods spread independently per value of the
        # keyed label (two "revisions" of 4 pods each; each revision spreads
        # across all four zones on its own)
        env = Env()
        pods = []
        for revision in ("a", "b"):
            for _ in range(4):
                pods.append(
                    web_pod(
                        [spread(match_label_keys=["rev"])],
                        labels={**APP, "rev": revision},
                    )
                )
        results = env.schedule(pods)
        assert not results.pod_errors
        # each revision spreads independently: its 4 pods land one per zone
        for revision in ("a", "b"):
            rev_zones = []
            for nc in results.new_node_claims:
                zones = nc.requirements.get(wk.LABEL_TOPOLOGY_ZONE).values_list()
                assert len(zones) == 1
                rev_zones.extend(
                    zones[0]
                    for p in nc.pods
                    if p.metadata.labels.get("rev") == revision
                )
            assert sorted(rev_zones) == sorted(
                ["kwok-zone-1", "kwok-zone-2", "kwok-zone-3", "kwok-zone-4"]
            )

    def test_unknown_match_label_keys_ignored(self):
        # topology_test.go:1165
        env = Env()
        results = env.schedule(
            [web_pod([spread(match_label_keys=["not-a-label"])]) for _ in range(4)]
        )
        assert not results.pod_errors


class TestInterdependentSelectors:
    def test_interdependent_selectors(self):
        # topology_test.go:444 — pods whose spread selector matches a label
        # that only OTHER pods in the batch carry still schedule
        env = Env()
        pods = [
            unschedulable_pod(
                requests={"cpu": "100m"},
                labels={"group": "a" if i % 2 else "b"},
                topology_spread_constraints=[
                    TopologySpreadConstraint(
                        max_skew=1,
                        topology_key=wk.LABEL_TOPOLOGY_ZONE,
                        when_unsatisfiable="DoNotSchedule",
                        label_selector=LabelSelector(
                            match_labels={"group": "b" if i % 2 else "a"}
                        ),
                    )
                ],
            )
            for i in range(6)
        ]
        results = env.schedule(pods)
        assert not results.pod_errors
