"""Disruption candidacy and disruption-cost oracle: specs ported from the
reference's disruption suite (pkg/controllers/disruption/suite_test.go:845-
1647 — names kept)."""

import pytest

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import (
    LabelSelector,
    ObjectMeta,
    OwnerReference,
    PodDisruptionBudget,
    PodDisruptionBudgetSpec,
    PodDisruptionBudgetStatus,
)
from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
from karpenter_tpu.controllers.disruption.types import (
    EVENTUAL_DISRUPTION_CLASS,
    GRACEFUL_DISRUPTION_CLASS,
    eviction_cost,
    new_candidate,
)
from karpenter_tpu.events.recorder import Recorder
from karpenter_tpu.runtime.store import Store
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.state.informer import StateInformer
from karpenter_tpu.utils.clock import FakeClock
from karpenter_tpu.utils.pdb import Limits

from helpers import bind_pod, node_claim_pair, nodepool, unschedulable_pod


class Harness:
    def __init__(self):
        self.clock = FakeClock()
        self.store = Store(clock=self.clock)
        self.provider = FakeCloudProvider()
        self.cluster = Cluster(self.clock, self.store, self.provider)
        self.informer = StateInformer(self.store, self.cluster)
        self.recorder = Recorder(clock=self.clock)
        self.pool = self.store.create(nodepool("default"))

    def add_node(self, name="cand-1", pods=(), tgp=None, **kwargs):
        node, claim = node_claim_pair(name, **kwargs)
        if tgp is not None:
            claim.spec.termination_grace_period = tgp
        self.store.create(claim)
        self.store.create(node)
        for p in pods:
            bind_pod(p, node)
            self.store.create(p)
        self.informer.flush()
        return next(
            n for n in self.cluster.state_nodes() if n.name() == name
        )

    def candidate(self, state_node, disruption_class=GRACEFUL_DISRUPTION_CLASS):
        its = {it.name: it for it in self.provider.get_instance_types(self.pool)}
        return new_candidate(
            self.store,
            self.recorder,
            self.clock,
            state_node,
            Limits.from_pdbs(self.store.list("PodDisruptionBudget")),
            {"default": self.pool},
            {"default": its},
            None,
            disruption_class,
        )


def dnd_pod(**kwargs):
    pod = unschedulable_pod(**kwargs)
    pod.metadata.annotations[wk.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
    return pod


class TestDisruptionCost:
    """suite_test.go:845-916."""

    def test_standard_cost_for_plain_pod(self):
        assert eviction_cost(unschedulable_pod()) == pytest.approx(1.0)

    def test_higher_cost_for_positive_deletion_cost(self):
        pod = unschedulable_pod()
        pod.metadata.annotations["controller.kubernetes.io/pod-deletion-cost"] = "100"
        assert eviction_cost(pod) > eviction_cost(unschedulable_pod())

    def test_lower_cost_for_negative_deletion_cost(self):
        pod = unschedulable_pod()
        pod.metadata.annotations["controller.kubernetes.io/pod-deletion-cost"] = "-100"
        assert eviction_cost(pod) < eviction_cost(unschedulable_pod())

    def test_monotone_in_deletion_cost(self):
        costs = []
        for value in ("-100", "0", "100"):
            pod = unschedulable_pod()
            pod.metadata.annotations["controller.kubernetes.io/pod-deletion-cost"] = value
            costs.append(eviction_cost(pod))
        assert costs == sorted(costs)

    def test_priority_raises_cost(self):
        high = unschedulable_pod()
        high.spec.priority = 100_000
        low = unschedulable_pod()
        low.spec.priority = -100_000
        assert eviction_cost(high) > eviction_cost(unschedulable_pod())
        assert eviction_cost(low) < eviction_cost(unschedulable_pod())


class TestCandidateFiltering:
    """suite_test.go:917-1647."""

    def test_do_not_disrupt_pod_blocks_graceful(self):
        h = Harness()
        sn = h.add_node(pods=[dnd_pod()])
        with pytest.raises(Exception, match="do-not-disrupt"):
            h.candidate(sn)

    def test_do_not_disrupt_with_tgp_allows_eventual(self):
        # suite_test.go:1022 — a terminationGracePeriod permits EVENTUAL
        # disruption (drift/expiration) despite blocking pods
        h = Harness()
        sn = h.add_node(pods=[dnd_pod()], tgp=300.0)
        candidate = h.candidate(sn, EVENTUAL_DISRUPTION_CLASS)
        assert candidate is not None

    def test_do_not_disrupt_with_tgp_still_blocks_graceful(self):
        # suite_test.go:1083
        h = Harness()
        sn = h.add_node(pods=[dnd_pod()], tgp=300.0)
        with pytest.raises(Exception, match="do-not-disrupt"):
            h.candidate(sn, GRACEFUL_DISRUPTION_CLASS)

    def test_pdb_blocked_pod_blocks_graceful(self):
        h = Harness()
        pod = unschedulable_pod(labels={"app": "guarded"})
        h.store.create(
            PodDisruptionBudget(
                metadata=ObjectMeta(name="pdb-1"),
                spec=PodDisruptionBudgetSpec(
                    selector=LabelSelector(match_labels={"app": "guarded"})
                ),
                status=PodDisruptionBudgetStatus(disruptions_allowed=0),
            )
        )
        sn = h.add_node(pods=[pod])
        with pytest.raises(Exception, match="pdb"):
            h.candidate(sn)

    def test_pdb_blocked_with_tgp_allows_eventual(self):
        # suite_test.go:1051
        h = Harness()
        pod = unschedulable_pod(labels={"app": "guarded"})
        h.store.create(
            PodDisruptionBudget(
                metadata=ObjectMeta(name="pdb-1"),
                spec=PodDisruptionBudgetSpec(
                    selector=LabelSelector(match_labels={"app": "guarded"})
                ),
                status=PodDisruptionBudgetStatus(disruptions_allowed=0),
            )
        )
        sn = h.add_node(pods=[pod], tgp=300.0)
        assert h.candidate(sn, EVENTUAL_DISRUPTION_CLASS) is not None

    def test_do_not_disrupt_terminal_pods_ignored(self):
        # suite_test.go:1241 — Succeeded/Failed pods can't block
        h = Harness()
        pod = dnd_pod()
        pod.status.phase = "Succeeded"
        sn = h.add_node(pods=[pod])
        assert h.candidate(sn) is not None

    def test_do_not_disrupt_on_node_blocks(self):
        # suite_test.go:1279 — the annotation on the NODE blocks entirely
        h = Harness()
        sn = h.add_node()
        sn.node.metadata.annotations[wk.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
        with pytest.raises(Exception, match="do-not-disrupt|blocked"):
            h.candidate(sn)

    def test_daemonset_do_not_disrupt_blocks(self):
        # suite_test.go:983 — daemonset-owned do-not-disrupt pods also block
        h = Harness()
        pod = dnd_pod()
        pod.metadata.owner_references = [
            OwnerReference(kind="DaemonSet", name="ds", uid="u1")
        ]
        sn = h.add_node(pods=[pod])
        with pytest.raises(Exception, match="do-not-disrupt"):
            h.candidate(sn)

    def test_node_only_representation_not_candidate(self):
        # suite_test.go:1628 — no NodeClaim: unmanaged, not disruptable
        h = Harness()
        from helpers import registered_node

        h.store.create(registered_node(name="bare-node"))
        h.informer.flush()
        sn = next(n for n in h.cluster.state_nodes() if n.name() == "bare-node")
        with pytest.raises(Exception):
            h.candidate(sn)


class TestCandidacyGates:
    """suite_test.go:1647-1869 — the remaining candidacy exclusions."""

    def test_nodeclaim_only_representation_not_candidate(self):
        # suite_test.go:1647 — a claim whose Node never appeared
        h = Harness()
        _, claim = node_claim_pair("claimonly")
        h.store.create(claim)
        h.informer.flush()
        sn = next(n for n in h.cluster.state_nodes() if n.node is None)
        with pytest.raises(ValueError):
            h.candidate(sn)

    def test_nominated_not_candidate(self):
        # suite_test.go:1666 — a recently nominated node is protected
        h = Harness()
        sn = h.add_node("nom-1")
        h.cluster.nominate_node_for_pod(sn.provider_id())
        sn = next(n for n in h.cluster.state_nodes() if n.name() == "nom-1")
        with pytest.raises(ValueError):
            h.candidate(sn)

    def test_deleting_not_candidate(self):
        # suite_test.go:1687
        h = Harness()
        sn = h.add_node("del-1")
        claim = h.store.get("NodeClaim", "del-1-claim")
        claim.metadata.finalizers.append("karpenter.sh/test-finalizer")
        h.store.update(claim)
        h.store.delete(claim)
        h.informer.flush()
        sn = next(n for n in h.cluster.state_nodes() if n.name() == "del-1")
        with pytest.raises(ValueError):
            h.candidate(sn)

    def test_marked_for_deletion_not_candidate(self):
        # suite_test.go:1709
        h = Harness()
        sn = h.add_node("marked-1")
        h.cluster.mark_for_deletion(sn.provider_id())
        sn = next(n for n in h.cluster.state_nodes() if n.name() == "marked-1")
        with pytest.raises(ValueError):
            h.candidate(sn)

    def test_uninitialized_not_candidate(self):
        # suite_test.go:1730
        h = Harness()
        sn = h.add_node("uninit-1")
        node = h.store.get("Node", "uninit-1")
        node.metadata.labels[wk.NODE_INITIALIZED_LABEL_KEY] = "false"
        h.store.update(node)
        h.informer.flush()
        sn = next(n for n in h.cluster.state_nodes() if n.name() == "uninit-1")
        with pytest.raises(ValueError):
            h.candidate(sn)

    def test_no_nodepool_label_not_candidate(self):
        # suite_test.go:1750
        h = Harness()
        sn = h.add_node("nolabel-1")
        node = h.store.get("Node", "nolabel-1")
        del node.metadata.labels[wk.NODEPOOL_LABEL_KEY]
        h.store.update(node)
        claim = h.store.get("NodeClaim", "nolabel-1-claim")
        del claim.metadata.labels[wk.NODEPOOL_LABEL_KEY]
        h.store.update(claim)
        h.informer.flush()
        sn = next(n for n in h.cluster.state_nodes() if n.name() == "nolabel-1")
        with pytest.raises(ValueError):
            h.candidate(sn)

    def test_nonexistent_nodepool_not_candidate(self):
        # suite_test.go:1769
        h = Harness()
        sn = h.add_node("ghostpool-1", pool="ghost")
        with pytest.raises(ValueError, match="not found"):
            h.candidate(sn)

    def test_missing_capacity_type_label_still_candidate(self):
        # suite_test.go:1794
        h = Harness()
        sn = h.add_node("noct-1")
        node = h.store.get("Node", "noct-1")
        node.metadata.labels.pop(wk.CAPACITY_TYPE_LABEL_KEY, None)
        h.store.update(node)
        h.informer.flush()
        sn = next(n for n in h.cluster.state_nodes() if n.name() == "noct-1")
        assert h.candidate(sn) is not None

    def test_missing_zone_label_still_candidate(self):
        # suite_test.go:1811
        h = Harness()
        sn = h.add_node("nozone-1")
        node = h.store.get("Node", "nozone-1")
        node.metadata.labels.pop(wk.LABEL_TOPOLOGY_ZONE, None)
        h.store.update(node)
        h.informer.flush()
        sn = next(n for n in h.cluster.state_nodes() if n.name() == "nozone-1")
        assert h.candidate(sn) is not None

    def test_missing_instance_type_label_still_candidate(self):
        # suite_test.go:1828
        h = Harness()
        sn = h.add_node("noit-1")
        node = h.store.get("Node", "noit-1")
        node.metadata.labels.pop(wk.LABEL_INSTANCE_TYPE, None)
        h.store.update(node)
        h.informer.flush()
        sn = next(n for n in h.cluster.state_nodes() if n.name() == "noit-1")
        cand = h.candidate(sn)
        assert cand is not None and cand.instance_type is None

    def test_unresolvable_instance_type_still_candidate(self):
        # suite_test.go:1845 — an instance type absent from the provider
        h = Harness()
        sn = h.add_node("weirdit-1", instance_type="retired-type")
        cand = h.candidate(sn)
        assert cand is not None and cand.instance_type is None

    def test_in_queue_not_candidate(self):
        # suite_test.go:1866 — actively processed candidates are excluded
        h = Harness()
        sn = h.add_node("queued-1")

        class FakeQueue:
            def has_any(self, *pids):
                return True

        its = {it.name: it for it in h.provider.get_instance_types(h.pool)}
        with pytest.raises(ValueError, match="already being disrupted"):
            new_candidate(
                h.store, h.recorder, h.clock, sn,
                Limits.from_pdbs([]), {"default": h.pool}, {"default": its},
                FakeQueue(), GRACEFUL_DISRUPTION_CLASS,
            )


class TestMirrorAndMultiPDB:
    """suite_test.go — mirror (static) pods and stacked PDBs."""

    def test_do_not_disrupt_mirror_pods_block(self):
        """suite_test.go — a do-not-disrupt MIRROR pod blocks candidacy just
        like any other do-not-disrupt pod (the annotation is an explicit
        operator signal regardless of evictability)."""
        h = Harness()
        mirror = unschedulable_pod(requests={"cpu": "1"})
        mirror.metadata.annotations[wk.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
        mirror.metadata.owner_references.append(
            OwnerReference(kind="Node", name="cand-1", uid="node-uid")
        )
        sn = h.add_node(pods=[mirror])
        with pytest.raises(Exception, match="do-not-disrupt"):
            h.candidate(sn)

    def test_fully_blocking_pdb_on_mirror_pod_does_not_block(self):
        h = Harness()
        mirror = unschedulable_pod(requests={"cpu": "1"}, labels={"app": "static"})
        mirror.metadata.owner_references.append(
            OwnerReference(kind="Node", name="cand-1", uid="node-uid")
        )
        h.store.create(
            PodDisruptionBudget(
                metadata=ObjectMeta(name="pdb-static"),
                spec=PodDisruptionBudgetSpec(
                    selector=LabelSelector(match_labels={"app": "static"})
                ),
                status=PodDisruptionBudgetStatus(disruptions_allowed=0),
            )
        )
        sn = h.add_node(pods=[mirror])
        assert h.candidate(sn) is not None

    def test_multiple_pdbs_on_same_pod_blocks(self):
        """A pod matched by MORE than one PDB can never be evicted via the
        Eviction API — the node is not a candidate (graceful)."""
        h = Harness()
        pod = unschedulable_pod(requests={"cpu": "1"}, labels={"app": "web"})
        for i in range(2):
            h.store.create(
                PodDisruptionBudget(
                    metadata=ObjectMeta(name=f"pdb-{i}"),
                    spec=PodDisruptionBudgetSpec(
                        selector=LabelSelector(match_labels={"app": "web"})
                    ),
                    status=PodDisruptionBudgetStatus(disruptions_allowed=10),
                )
            )
        sn = h.add_node(pods=[pod])
        with pytest.raises(Exception, match="pdb"):
            h.candidate(sn)
