"""Disruption candidacy and disruption-cost oracle: specs ported from the
reference's disruption suite (pkg/controllers/disruption/suite_test.go:845-
1647 — names kept)."""

import pytest

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import (
    LabelSelector,
    ObjectMeta,
    OwnerReference,
    PodDisruptionBudget,
    PodDisruptionBudgetSpec,
    PodDisruptionBudgetStatus,
)
from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
from karpenter_tpu.controllers.disruption.types import (
    EVENTUAL_DISRUPTION_CLASS,
    GRACEFUL_DISRUPTION_CLASS,
    eviction_cost,
    new_candidate,
)
from karpenter_tpu.events.recorder import Recorder
from karpenter_tpu.runtime.store import Store
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.state.informer import StateInformer
from karpenter_tpu.utils.clock import FakeClock
from karpenter_tpu.utils.pdb import Limits

from helpers import bind_pod, node_claim_pair, nodepool, unschedulable_pod


class Harness:
    def __init__(self):
        self.clock = FakeClock()
        self.store = Store(clock=self.clock)
        self.provider = FakeCloudProvider()
        self.cluster = Cluster(self.clock, self.store, self.provider)
        self.informer = StateInformer(self.store, self.cluster)
        self.recorder = Recorder(clock=self.clock)
        self.pool = self.store.create(nodepool("default"))

    def add_node(self, name="cand-1", pods=(), tgp=None, **kwargs):
        node, claim = node_claim_pair(name, **kwargs)
        if tgp is not None:
            claim.spec.termination_grace_period = tgp
        self.store.create(claim)
        self.store.create(node)
        for p in pods:
            bind_pod(p, node)
            self.store.create(p)
        self.informer.flush()
        return next(
            n for n in self.cluster.state_nodes() if n.name() == name
        )

    def candidate(self, state_node, disruption_class=GRACEFUL_DISRUPTION_CLASS):
        its = {it.name: it for it in self.provider.get_instance_types(self.pool)}
        return new_candidate(
            self.store,
            self.recorder,
            self.clock,
            state_node,
            Limits.from_pdbs(self.store.list("PodDisruptionBudget")),
            {"default": self.pool},
            {"default": its},
            None,
            disruption_class,
        )


def dnd_pod(**kwargs):
    pod = unschedulable_pod(**kwargs)
    pod.metadata.annotations[wk.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
    return pod


class TestDisruptionCost:
    """suite_test.go:845-916."""

    def test_standard_cost_for_plain_pod(self):
        assert eviction_cost(unschedulable_pod()) == pytest.approx(1.0)

    def test_higher_cost_for_positive_deletion_cost(self):
        pod = unschedulable_pod()
        pod.metadata.annotations["controller.kubernetes.io/pod-deletion-cost"] = "100"
        assert eviction_cost(pod) > eviction_cost(unschedulable_pod())

    def test_lower_cost_for_negative_deletion_cost(self):
        pod = unschedulable_pod()
        pod.metadata.annotations["controller.kubernetes.io/pod-deletion-cost"] = "-100"
        assert eviction_cost(pod) < eviction_cost(unschedulable_pod())

    def test_monotone_in_deletion_cost(self):
        costs = []
        for value in ("-100", "0", "100"):
            pod = unschedulable_pod()
            pod.metadata.annotations["controller.kubernetes.io/pod-deletion-cost"] = value
            costs.append(eviction_cost(pod))
        assert costs == sorted(costs)

    def test_priority_raises_cost(self):
        high = unschedulable_pod()
        high.spec.priority = 100_000
        low = unschedulable_pod()
        low.spec.priority = -100_000
        assert eviction_cost(high) > eviction_cost(unschedulable_pod())
        assert eviction_cost(low) < eviction_cost(unschedulable_pod())


class TestCandidateFiltering:
    """suite_test.go:917-1647."""

    def test_do_not_disrupt_pod_blocks_graceful(self):
        h = Harness()
        sn = h.add_node(pods=[dnd_pod()])
        with pytest.raises(Exception, match="do-not-disrupt"):
            h.candidate(sn)

    def test_do_not_disrupt_with_tgp_allows_eventual(self):
        # suite_test.go:1022 — a terminationGracePeriod permits EVENTUAL
        # disruption (drift/expiration) despite blocking pods
        h = Harness()
        sn = h.add_node(pods=[dnd_pod()], tgp=300.0)
        candidate = h.candidate(sn, EVENTUAL_DISRUPTION_CLASS)
        assert candidate is not None

    def test_do_not_disrupt_with_tgp_still_blocks_graceful(self):
        # suite_test.go:1083
        h = Harness()
        sn = h.add_node(pods=[dnd_pod()], tgp=300.0)
        with pytest.raises(Exception, match="do-not-disrupt"):
            h.candidate(sn, GRACEFUL_DISRUPTION_CLASS)

    def test_pdb_blocked_pod_blocks_graceful(self):
        h = Harness()
        pod = unschedulable_pod(labels={"app": "guarded"})
        h.store.create(
            PodDisruptionBudget(
                metadata=ObjectMeta(name="pdb-1"),
                spec=PodDisruptionBudgetSpec(
                    selector=LabelSelector(match_labels={"app": "guarded"})
                ),
                status=PodDisruptionBudgetStatus(disruptions_allowed=0),
            )
        )
        sn = h.add_node(pods=[pod])
        with pytest.raises(Exception, match="pdb"):
            h.candidate(sn)

    def test_pdb_blocked_with_tgp_allows_eventual(self):
        # suite_test.go:1051
        h = Harness()
        pod = unschedulable_pod(labels={"app": "guarded"})
        h.store.create(
            PodDisruptionBudget(
                metadata=ObjectMeta(name="pdb-1"),
                spec=PodDisruptionBudgetSpec(
                    selector=LabelSelector(match_labels={"app": "guarded"})
                ),
                status=PodDisruptionBudgetStatus(disruptions_allowed=0),
            )
        )
        sn = h.add_node(pods=[pod], tgp=300.0)
        assert h.candidate(sn, EVENTUAL_DISRUPTION_CLASS) is not None

    def test_do_not_disrupt_terminal_pods_ignored(self):
        # suite_test.go:1241 — Succeeded/Failed pods can't block
        h = Harness()
        pod = dnd_pod()
        pod.status.phase = "Succeeded"
        sn = h.add_node(pods=[pod])
        assert h.candidate(sn) is not None

    def test_do_not_disrupt_on_node_blocks(self):
        # suite_test.go:1279 — the annotation on the NODE blocks entirely
        h = Harness()
        sn = h.add_node()
        sn.node.metadata.annotations[wk.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
        with pytest.raises(Exception, match="do-not-disrupt|blocked"):
            h.candidate(sn)

    def test_daemonset_do_not_disrupt_blocks(self):
        # suite_test.go:983 — daemonset-owned do-not-disrupt pods also block
        h = Harness()
        pod = dnd_pod()
        pod.metadata.owner_references = [
            OwnerReference(kind="DaemonSet", name="ds", uid="u1")
        ]
        sn = h.add_node(pods=[pod])
        with pytest.raises(Exception, match="do-not-disrupt"):
            h.candidate(sn)

    def test_node_only_representation_not_candidate(self):
        # suite_test.go:1628 — no NodeClaim: unmanaged, not disruptable
        h = Harness()
        from helpers import registered_node

        h.store.create(registered_node(name="bare-node"))
        h.informer.flush()
        sn = next(n for n in h.cluster.state_nodes() if n.name() == "bare-node")
        with pytest.raises(Exception):
            h.candidate(sn)
