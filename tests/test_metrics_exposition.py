"""Prometheus text exposition conformance (metrics/registry.py): histogram
_bucket/_sum/_count with the mandatory +Inf bucket, cumulative bucket
counts, and label-value escaping — verified by a round-trip parse of the
exposed text back into families."""

import math

from karpenter_tpu.metrics.registry import Registry


def parse_exposition(text: str) -> dict:
    """A strict little parser for the Prometheus text format: returns
    {family: {"type": ..., "help": ..., "samples": {(name, labels): value}}}
    where labels is a sorted tuple of (k, v) with escapes DECODED."""
    families: dict = {}
    current = None
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_text = rest.partition(" ")
            current = families.setdefault(
                name, {"type": None, "help": None, "samples": {}}
            )
            current["help"] = (
                help_text.replace("\\n", "\n").replace("\\\\", "\\")
            )
        elif line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            families.setdefault(
                name, {"type": None, "help": None, "samples": {}}
            )["type"] = kind
        else:
            name, labels, value = _parse_sample(line)
            family = name
            for suffix in ("_bucket", "_sum", "_count"):
                if name.endswith(suffix) and name.removesuffix(suffix) in families:
                    family = name.removesuffix(suffix)
            families[family]["samples"][(name, labels)] = value
    return families


def _parse_sample(line: str):
    if "{" in line:
        name, _, rest = line.partition("{")
        labelblob, _, valuepart = rest.rpartition("} ")
        labels = []
        i = 0
        while i < len(labelblob):
            eq = labelblob.index("=", i)
            key = labelblob[i:eq]
            assert labelblob[eq + 1] == '"'
            j = eq + 2
            out = []
            while labelblob[j] != '"':
                if labelblob[j] == "\\":
                    esc = labelblob[j + 1]
                    out.append({"n": "\n", '"': '"', "\\": "\\"}[esc])
                    j += 2
                else:
                    out.append(labelblob[j])
                    j += 1
            labels.append((key, "".join(out)))
            i = j + 1
            if i < len(labelblob) and labelblob[i] == ",":
                i += 1
        return name, tuple(sorted(labels)), float(valuepart)
    name, _, value = line.partition(" ")
    return name, (), float(value)


class TestRoundTrip:
    def test_counter_and_gauge_round_trip(self):
        reg = Registry()
        c = reg.counter("karpenter_pods_total", "pods seen", labels=["phase"])
        c.inc({"phase": "pending"})
        c.inc({"phase": "pending"})
        c.inc({"phase": "bound"}, value=3.0)
        g = reg.gauge("karpenter_limit", "the limit")
        g.set(5.5)
        fam = parse_exposition(reg.expose())
        assert fam["karpenter_pods_total"]["type"] == "counter"
        samples = fam["karpenter_pods_total"]["samples"]
        assert samples[("karpenter_pods_total", (("phase", "pending"),))] == 2.0
        assert samples[("karpenter_pods_total", (("phase", "bound"),))] == 3.0
        assert fam["karpenter_limit"]["samples"][("karpenter_limit", ())] == 5.5

    def test_histogram_emits_buckets_inf_sum_count(self):
        reg = Registry()
        h = reg.histogram(
            "karpenter_latency_seconds", "latency", labels=["stage"],
            buckets=(0.1, 1.0, 10.0),
        )
        for v in (0.05, 0.5, 5.0, 50.0):
            h.observe(v, {"stage": "solve"})
        fam = parse_exposition(reg.expose())
        assert fam["karpenter_latency_seconds"]["type"] == "histogram"
        samples = fam["karpenter_latency_seconds"]["samples"]

        def bucket(le):
            return samples[
                ("karpenter_latency_seconds_bucket",
                 tuple(sorted((("stage", "solve"), ("le", le)))))
            ]

        # cumulative, monotone nondecreasing, +Inf == count
        assert bucket("0.1") == 1.0
        assert bucket("1") == 2.0
        assert bucket("10") == 3.0
        assert bucket("+Inf") == 4.0
        count = samples[
            ("karpenter_latency_seconds_count", (("stage", "solve"),))
        ]
        total = samples[("karpenter_latency_seconds_sum", (("stage", "solve"),))]
        assert count == 4.0
        assert math.isclose(total, 55.55)

    def test_label_value_escaping_round_trips(self):
        reg = Registry()
        c = reg.counter("karpenter_weird_total", "weird", labels=["item"])
        nasty = 'line1\nline2 "quoted" back\\slash'
        c.inc({"item": nasty})
        text = reg.expose()
        # the raw text must not contain a bare newline inside a sample line
        sample_lines = [l for l in text.splitlines() if l.startswith("karpenter_weird")]
        assert len(sample_lines) == 1
        assert '\\n' in sample_lines[0] and '\\"' in sample_lines[0]
        fam = parse_exposition(text)
        samples = fam["karpenter_weird_total"]["samples"]
        assert samples[("karpenter_weird_total", (("item", nasty),))] == 1.0

    def test_help_escaping(self):
        reg = Registry()
        reg.counter("karpenter_x_total", "first line\nsecond \\ line")
        fam = parse_exposition(reg.expose())
        assert fam["karpenter_x_total"]["help"] == "first line\nsecond \\ line"

    def test_every_emitted_line_is_parseable(self):
        """Feed the REAL global registry (whatever tests before us
        registered) through the parser: conformance must hold for the
        production metric set, not just synthetic examples."""
        from karpenter_tpu.metrics import global_registry

        global_registry.histogram(
            "karpenter_exposition_selftest_seconds", "selftest"
        ).observe(0.2)
        fam = parse_exposition(global_registry.expose())
        h = fam["karpenter_exposition_selftest_seconds"]
        assert h["type"] == "histogram"
        inf = h["samples"][
            ("karpenter_exposition_selftest_seconds_bucket", (("le", "+Inf"),))
        ]
        count = h["samples"][
            ("karpenter_exposition_selftest_seconds_count", ())
        ]
        assert inf == count >= 1.0
