"""Prometheus text exposition conformance (metrics/registry.py): histogram
_bucket/_sum/_count with the mandatory +Inf bucket, cumulative bucket
counts, and label-value escaping — verified by a round-trip parse of the
exposed text back into families."""

import math

from karpenter_tpu.metrics.registry import Registry


def parse_exposition(text: str) -> dict:
    """A strict little parser for the Prometheus text format: returns
    {family: {"type": ..., "help": ..., "samples": {(name, labels): value}}}
    where labels is a sorted tuple of (k, v) with escapes DECODED."""
    families: dict = {}
    current = None
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_text = rest.partition(" ")
            current = families.setdefault(
                name, {"type": None, "help": None, "samples": {}}
            )
            current["help"] = (
                help_text.replace("\\n", "\n").replace("\\\\", "\\")
            )
        elif line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            families.setdefault(
                name, {"type": None, "help": None, "samples": {}}
            )["type"] = kind
        else:
            name, labels, value = _parse_sample(line)
            family = name
            for suffix in ("_bucket", "_sum", "_count"):
                if name.endswith(suffix) and name.removesuffix(suffix) in families:
                    family = name.removesuffix(suffix)
            families[family]["samples"][(name, labels)] = value
    return families


def _parse_sample(line: str):
    if "{" in line:
        name, _, rest = line.partition("{")
        labelblob, _, valuepart = rest.rpartition("} ")
        labels = []
        i = 0
        while i < len(labelblob):
            eq = labelblob.index("=", i)
            key = labelblob[i:eq]
            assert labelblob[eq + 1] == '"'
            j = eq + 2
            out = []
            while labelblob[j] != '"':
                if labelblob[j] == "\\":
                    esc = labelblob[j + 1]
                    out.append({"n": "\n", '"': '"', "\\": "\\"}[esc])
                    j += 2
                else:
                    out.append(labelblob[j])
                    j += 1
            labels.append((key, "".join(out)))
            i = j + 1
            if i < len(labelblob) and labelblob[i] == ",":
                i += 1
        return name, tuple(sorted(labels)), float(valuepart)
    name, _, value = line.partition(" ")
    return name, (), float(value)


class TestRoundTrip:
    def test_counter_and_gauge_round_trip(self):
        reg = Registry()
        c = reg.counter("karpenter_pods_total", "pods seen", labels=["phase"])
        c.inc({"phase": "pending"})
        c.inc({"phase": "pending"})
        c.inc({"phase": "bound"}, value=3.0)
        g = reg.gauge("karpenter_limit", "the limit")
        g.set(5.5)
        fam = parse_exposition(reg.expose())
        assert fam["karpenter_pods_total"]["type"] == "counter"
        samples = fam["karpenter_pods_total"]["samples"]
        assert samples[("karpenter_pods_total", (("phase", "pending"),))] == 2.0
        assert samples[("karpenter_pods_total", (("phase", "bound"),))] == 3.0
        assert fam["karpenter_limit"]["samples"][("karpenter_limit", ())] == 5.5

    def test_histogram_emits_buckets_inf_sum_count(self):
        reg = Registry()
        h = reg.histogram(
            "karpenter_latency_seconds", "latency", labels=["stage"],
            buckets=(0.1, 1.0, 10.0),
        )
        for v in (0.05, 0.5, 5.0, 50.0):
            h.observe(v, {"stage": "solve"})
        fam = parse_exposition(reg.expose())
        assert fam["karpenter_latency_seconds"]["type"] == "histogram"
        samples = fam["karpenter_latency_seconds"]["samples"]

        def bucket(le):
            return samples[
                ("karpenter_latency_seconds_bucket",
                 tuple(sorted((("stage", "solve"), ("le", le)))))
            ]

        # cumulative, monotone nondecreasing, +Inf == count
        assert bucket("0.1") == 1.0
        assert bucket("1") == 2.0
        assert bucket("10") == 3.0
        assert bucket("+Inf") == 4.0
        count = samples[
            ("karpenter_latency_seconds_count", (("stage", "solve"),))
        ]
        total = samples[("karpenter_latency_seconds_sum", (("stage", "solve"),))]
        assert count == 4.0
        assert math.isclose(total, 55.55)

    def test_label_value_escaping_round_trips(self):
        reg = Registry()
        c = reg.counter("karpenter_weird_total", "weird", labels=["item"])
        nasty = 'line1\nline2 "quoted" back\\slash'
        c.inc({"item": nasty})
        text = reg.expose()
        # the raw text must not contain a bare newline inside a sample line
        sample_lines = [l for l in text.splitlines() if l.startswith("karpenter_weird")]
        assert len(sample_lines) == 1
        assert '\\n' in sample_lines[0] and '\\"' in sample_lines[0]
        fam = parse_exposition(text)
        samples = fam["karpenter_weird_total"]["samples"]
        assert samples[("karpenter_weird_total", (("item", nasty),))] == 1.0

    def test_help_escaping(self):
        reg = Registry()
        reg.counter("karpenter_x_total", "first line\nsecond \\ line")
        fam = parse_exposition(reg.expose())
        assert fam["karpenter_x_total"]["help"] == "first line\nsecond \\ line"

    def test_kernel_observatory_families_round_trip(self):
        """The kernel observatory's counters/gauges/histograms on the REAL
        global registry: dispatch/compile counters labelled by kernel+phase,
        the per-shape-bucket execute histogram (bucket label values carry
        commas and x's — they must survive the quote/escape round trip,
        including an escape-worthy synthetic bucket), the recompile counter,
        and the device-memory gauges."""
        import jax
        import jax.numpy as jnp

        from karpenter_tpu.metrics import global_registry
        from karpenter_tpu.observability import kernels as kobs
        from karpenter_tpu.tracing import kernel as ktime

        reg = kobs.registry()
        reg.reset()
        try:

            @jax.jit
            def f(x):
                return x * 2.0

            with ktime.measure():  # fenced → execute histogram observes
                ktime.dispatch(f, jnp.ones((4, 2)), kernel="expo.k")
                ktime.dispatch(f, jnp.ones((4, 2)), kernel="expo.k")
            reg.seal()
            with ktime.measure():
                ktime.dispatch(f, jnp.ones((5, 2)), kernel="expo.k")  # recompile
            kobs.sample_device_memory()
            # a pathological bucket value exercises label escaping on the
            # same family production shapes flow through
            global_registry.get("karpenter_kernel_execute_seconds").observe(
                0.001, {"kernel": "expo.k", "bucket": 'odd"\\bucket'}
            )
            fam = parse_exposition(global_registry.expose())

            disp = fam["karpenter_kernel_dispatches_total"]
            assert disp["type"] == "counter"
            key = tuple(sorted((("kernel", "expo.k"), ("phase", "warmup"))))
            assert disp["samples"][
                ("karpenter_kernel_dispatches_total", key)
            ] == 2.0
            steady = tuple(sorted((("kernel", "expo.k"), ("phase", "steady"))))
            assert disp["samples"][
                ("karpenter_kernel_dispatches_total", steady)
            ] == 1.0

            rec = fam["karpenter_kernel_recompiles_total"]
            assert rec["samples"][
                ("karpenter_kernel_recompiles_total", (("kernel", "expo.k"),))
            ] == 1.0

            execute = fam["karpenter_kernel_execute_seconds"]
            assert execute["type"] == "histogram"
            shape_key = tuple(
                sorted((("bucket", "4x2"), ("kernel", "expo.k"), ("le", "+Inf")))
            )
            inf = execute["samples"][
                ("karpenter_kernel_execute_seconds_bucket", shape_key)
            ]
            count = execute["samples"][
                ("karpenter_kernel_execute_seconds_count",
                 tuple(sorted((("bucket", "4x2"), ("kernel", "expo.k")))))
            ]
            assert inf == count >= 1.0  # at least the warm dispatch
            # the escaped synthetic bucket value round-trips intact
            nasty = tuple(
                sorted(
                    (("bucket", 'odd"\\bucket'), ("kernel", "expo.k"))
                )
            )
            assert execute["samples"][
                ("karpenter_kernel_execute_seconds_count", nasty)
            ] == 1.0

            gauge = fam["karpenter_device_live_array_bytes"]
            assert gauge["type"] == "gauge"
            assert gauge["samples"][
                ("karpenter_device_live_array_bytes", ())
            ] >= 0.0
        finally:
            reg.reset()

    def test_efficiency_families_round_trip(self):
        """ISSUE 15 conformance: karpenter_kernel_utilization (gauge with
        kernel+bucket labels — bucket values carry x's/commas),
        karpenter_profiler_captures_total (counter by trigger, trigger
        values carry colons), and the karpenter_kernel_host_stall_fraction
        histogram all survive the exposition round trip."""
        from karpenter_tpu.metrics import global_registry

        global_registry.get("karpenter_kernel_utilization").set(
            0.42, {"kernel": "expo.util", "bucket": "128x64,64"}
        )
        global_registry.get("karpenter_profiler_captures_total").inc(
            {"trigger": "slo:solve-latency"}
        )
        global_registry.get("karpenter_kernel_host_stall_fraction").observe(
            0.97
        )
        fam = parse_exposition(global_registry.expose())

        util = fam["karpenter_kernel_utilization"]
        assert util["type"] == "gauge"
        key = tuple(
            sorted((("kernel", "expo.util"), ("bucket", "128x64,64")))
        )
        assert util["samples"][("karpenter_kernel_utilization", key)] == 0.42

        caps = fam["karpenter_profiler_captures_total"]
        assert caps["type"] == "counter"
        assert caps["samples"][
            (
                "karpenter_profiler_captures_total",
                (("trigger", "slo:solve-latency"),),
            )
        ] >= 1.0

        stall = fam["karpenter_kernel_host_stall_fraction"]
        assert stall["type"] == "histogram"
        inf = stall["samples"][
            ("karpenter_kernel_host_stall_fraction_bucket", (("le", "+Inf"),))
        ]
        count = stall["samples"][
            ("karpenter_kernel_host_stall_fraction_count", ())
        ]
        assert inf == count >= 1.0
        # 0.97 lands in the 0.99 bucket but not 0.9
        in_99 = stall["samples"][
            ("karpenter_kernel_host_stall_fraction_bucket", (("le", "0.99"),))
        ]
        in_90 = stall["samples"][
            ("karpenter_kernel_host_stall_fraction_bucket", (("le", "0.9"),))
        ]
        assert in_99 - in_90 >= 1.0

    def test_explain_families_round_trip(self):
        """Provenance-ledger conformance (ISSUE 19): the karpenter_explain_*
        families on the REAL global registry — the per-stage elimination
        counter (including dynamic fused:<reason> stage values, whose
        colons must survive the quote round trip), the commit counter by
        mode, the ring-depth gauge, the probe-outcome counter, and the
        funnel-stage histogram's _bucket/+Inf/_sum/_count."""
        from karpenter_tpu.metrics import global_registry
        from karpenter_tpu.observability import explain as explmod

        rec = explmod.recorder()
        prior_mode = rec.mode
        rec.configure(mode="on")
        try:
            # the registry is process-global and other suites feed these
            # families too — every assertion below is a delta or floor,
            # never an absence, so ordering can't break it
            def sample(key, labels):
                fam0 = parse_exposition(global_registry.expose())
                family = fam0.get("karpenter_explain_eliminations_total")
                if family is None:
                    return 0.0
                return family["samples"].get((key, labels), 0.0)

            resources0 = sample(
                "karpenter_explain_eliminations_total",
                (("stage", "resources"),),
            )
            rec.note_plane_counts({"requirements": 3, "resources": 0})
            rec.note_fused_decline("reserved-offerings")
            rec.note_probe("schedulable")
            pod_uid = "expo-explain-uid"

            class _Meta:
                name = "expo-pod"
                namespace = "default"
                uid = pod_uid

            class _Pod:
                metadata = _Meta()

            pod = _Pod()
            rec.note_funnel(
                pod_uid,
                [{"nodepool": "workers", "stages": ["limits"], "error": "e"}],
            )
            rec.commit_solve([pod], {pod: ValueError("exceed limits for nodepool")})
            fam = parse_exposition(global_registry.expose())

            elims = fam["karpenter_explain_eliminations_total"]
            assert elims["type"] == "counter"
            assert elims["samples"][
                (
                    "karpenter_explain_eliminations_total",
                    (("stage", "requirements"),),
                )
            ] >= 3.0
            # a zero-count stage never increments its sample
            assert (
                elims["samples"].get(
                    (
                        "karpenter_explain_eliminations_total",
                        (("stage", "resources"),),
                    ),
                    0.0,
                )
                == resources0
            )
            # the dynamic fused stage (colon in the label value) round-trips
            assert elims["samples"][
                (
                    "karpenter_explain_eliminations_total",
                    (("stage", "fused:reserved-offerings"),),
                )
            ] >= 1.0

            commits = fam["karpenter_explain_pods_total"]
            assert commits["type"] == "counter"
            assert commits["samples"][
                ("karpenter_explain_pods_total", (("mode", "on"),))
            ] >= 1.0

            depth = fam["karpenter_explain_ring_depth"]
            assert depth["type"] == "gauge"
            assert depth["samples"][
                ("karpenter_explain_ring_depth", ())
            ] >= 1.0

            probes = fam["karpenter_explain_probes_total"]
            assert probes["samples"][
                ("karpenter_explain_probes_total", (("outcome", "schedulable"),))
            ] >= 1.0

            funnel = fam["karpenter_explain_funnel_stages"]
            assert funnel["type"] == "histogram"
            inf = funnel["samples"][
                ("karpenter_explain_funnel_stages_bucket", (("le", "+Inf"),))
            ]
            count = funnel["samples"][
                ("karpenter_explain_funnel_stages_count", ())
            ]
            assert inf == count >= 1.0
            # the single-stage commit lands in the le=1 bucket
            assert funnel["samples"][
                ("karpenter_explain_funnel_stages_bucket", (("le", "1"),))
            ] >= 1.0
            assert funnel["samples"][
                ("karpenter_explain_funnel_stages_sum", ())
            ] >= 1.0
        finally:
            rec.configure(mode=prior_mode or "off")
            rec.reset()

    def test_every_emitted_line_is_parseable(self):
        """Feed the REAL global registry (whatever tests before us
        registered) through the parser: conformance must hold for the
        production metric set, not just synthetic examples."""
        from karpenter_tpu.metrics import global_registry

        global_registry.histogram(
            "karpenter_exposition_selftest_seconds", "selftest"
        ).observe(0.2)
        fam = parse_exposition(global_registry.expose())
        h = fam["karpenter_exposition_selftest_seconds"]
        assert h["type"] == "histogram"
        inf = h["samples"][
            ("karpenter_exposition_selftest_seconds_bucket", (("le", "+Inf"),))
        ]
        count = h["samples"][
            ("karpenter_exposition_selftest_seconds_count", ())
        ]
        assert inf == count >= 1.0
