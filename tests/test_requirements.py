"""Requirements algebra semantics, mirroring the reference's
pkg/scheduling/suite_test.go behaviors."""

import pytest

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import (
    Affinity,
    NodeAffinity,
    NodeSelectorTerm,
    Pod,
    PodSpec,
    PreferredSchedulingTerm,
)
from karpenter_tpu.scheduling.requirements import (
    Operator,
    Requirement,
    Requirements,
    pod_requirements,
    requirements_from_dicts,
    strict_pod_requirements,
)


def r_in(key, *values):
    return Requirement(key, Operator.IN, values)


def r_notin(key, *values):
    return Requirement(key, Operator.NOT_IN, values)


class TestRequirement:
    def test_in_has(self):
        r = r_in("key", "a", "b")
        assert r.has("a") and r.has("b") and not r.has("c")

    def test_notin_has(self):
        r = r_notin("key", "a")
        assert not r.has("a") and r.has("b") and r.has("zzz")

    def test_exists(self):
        r = Requirement("key", Operator.EXISTS)
        assert r.has("anything")
        assert r.operator == Operator.EXISTS

    def test_does_not_exist(self):
        r = Requirement("key", Operator.DOES_NOT_EXIST)
        assert not r.has("anything")
        assert len(r) == 0

    def test_gt_lt(self):
        gt = Requirement("key", Operator.GT, ["5"])
        lt = Requirement("key", Operator.LT, ["10"])
        assert gt.has("6") and not gt.has("5") and not gt.has("abc")
        assert lt.has("9") and not lt.has("10")
        both = gt.intersection(lt)
        assert both.has("7") and not both.has("4") and not both.has("11")

    def test_gt_lt_empty(self):
        gt = Requirement("key", Operator.GT, ["10"])
        lt = Requirement("key", Operator.LT, ["5"])
        assert gt.intersection(lt).operator == Operator.DOES_NOT_EXIST
        assert not gt.has_intersection(lt)

    def test_intersection_in_in(self):
        got = r_in("k", "a", "b").intersection(r_in("k", "b", "c"))
        assert got.values == {"b"} and not got.complement

    def test_intersection_in_notin(self):
        got = r_in("k", "a", "b").intersection(r_notin("k", "b"))
        assert got.values == {"a"} and not got.complement

    def test_intersection_notin_notin(self):
        got = r_notin("k", "a").intersection(r_notin("k", "b"))
        assert got.complement and got.values == {"a", "b"}
        assert got.has("c") and not got.has("a")

    def test_has_intersection_matches_intersection(self):
        cases = [
            r_in("k", "a", "b"),
            r_in("k", "c"),
            r_notin("k", "a"),
            r_notin("k", "c", "d"),
            Requirement("k", Operator.EXISTS),
            Requirement("k", Operator.DOES_NOT_EXIST),
            Requirement("k", Operator.GT, ["3"]),
            Requirement("k", Operator.LT, ["7"]),
            r_in("k", "5", "9"),
        ]
        for a in cases:
            for b in cases:
                fast = a.has_intersection(b)
                slow = len(a.intersection(b)) != 0
                assert fast == slow, f"{a!r} vs {b!r}: fast={fast} slow={slow}"

    def test_normalized_keys(self):
        r = Requirement("beta.kubernetes.io/arch", Operator.IN, ["amd64"])
        assert r.key == wk.LABEL_ARCH

    def test_min_values_propagates(self):
        a = Requirement("k", Operator.IN, ["a", "b"], min_values=2)
        b = r_in("k", "a", "b", "c")
        assert a.intersection(b).min_values == 2
        assert b.intersection(a).min_values == 2


class TestRequirements:
    def test_add_intersects(self):
        reqs = Requirements(r_in("k", "a", "b"))
        reqs.add(r_in("k", "b", "c"))
        assert reqs.get("k").values == {"b"}

    def test_get_missing_is_exists(self):
        reqs = Requirements()
        assert reqs.get("zone").operator == Operator.EXISTS

    def test_compatible_well_known_undefined_allowed(self):
        node = Requirements(r_in(wk.LABEL_OS, "linux"))
        pod = Requirements(r_in(wk.LABEL_TOPOLOGY_ZONE, "zone-1"))
        # undefined custom label denied
        assert node.compatible(pod) is not None
        # well-known undefined allowed
        assert node.compatible(pod, allow_undefined=wk.WELL_KNOWN_LABELS) is None

    def test_compatible_custom_label_defined(self):
        node = Requirements(r_in("team", "a"))
        assert node.compatible(Requirements(r_in("team", "a"))) is None
        assert node.compatible(Requirements(r_in("team", "b"))) is not None

    def test_compatible_notin_undefined_ok(self):
        node = Requirements()
        assert node.compatible(Requirements(r_notin("team", "b"))) is None
        assert (
            node.compatible(Requirements(Requirement("team", Operator.DOES_NOT_EXIST)))
            is None
        )

    def test_intersects_double_complement_exemption(self):
        # NotIn vs DoesNotExist on the same key does not error even though
        # set-intersection may be empty (requirements.go:253-259)
        a = Requirements(Requirement("k", Operator.DOES_NOT_EXIST))
        b = Requirements(r_notin("k", "v"))
        assert a.intersects(b) is None

    def test_intersects_error(self):
        a = Requirements(r_in("k", "a"))
        b = Requirements(r_in("k", "b"))
        assert a.intersects(b) is not None

    def test_labels_skips_restricted(self):
        reqs = Requirements(
            r_in(wk.LABEL_HOSTNAME, "h1"),
            r_in("team", "a"),
            r_in(wk.LABEL_TOPOLOGY_ZONE, "z1"),  # well-known => restricted node label
        )
        labels = reqs.labels()
        assert labels == {"team": "a"}

    def test_from_dicts_roundtrip(self):
        raw = [
            {"key": "a", "operator": "In", "values": ["1", "2"]},
            {"key": "b", "operator": "Exists"},
            {"key": "c", "operator": "Gt", "values": ["4"], "minValues": None},
        ]
        reqs = requirements_from_dicts(raw)
        assert reqs.get("a").values == {"1", "2"}
        assert reqs.get("b").operator == Operator.EXISTS
        assert reqs.get("c").has("5") and not reqs.get("c").has("4")


class TestPodRequirements:
    def make_pod(self):
        return Pod(
            spec=PodSpec(
                node_selector={"team": "a"},
                affinity=Affinity(
                    node_affinity=NodeAffinity(
                        required=[
                            NodeSelectorTerm(
                                match_expressions=[
                                    {
                                        "key": wk.LABEL_TOPOLOGY_ZONE,
                                        "operator": "In",
                                        "values": ["z1", "z2"],
                                    }
                                ]
                            ),
                            NodeSelectorTerm(
                                match_expressions=[
                                    {
                                        "key": wk.LABEL_TOPOLOGY_ZONE,
                                        "operator": "In",
                                        "values": ["z3"],
                                    }
                                ]
                            ),
                        ],
                        preferred=[
                            PreferredSchedulingTerm(
                                weight=1,
                                preference=NodeSelectorTerm(
                                    match_expressions=[
                                        {
                                            "key": "light",
                                            "operator": "In",
                                            "values": ["x"],
                                        }
                                    ]
                                ),
                            ),
                            PreferredSchedulingTerm(
                                weight=10,
                                preference=NodeSelectorTerm(
                                    match_expressions=[
                                        {
                                            "key": "heavy",
                                            "operator": "In",
                                            "values": ["y"],
                                        }
                                    ]
                                ),
                            ),
                        ],
                    )
                ),
            )
        )

    def test_node_selector_and_first_term(self):
        reqs = pod_requirements(self.make_pod())
        assert reqs.get("team").values == {"a"}
        # only first required OR term
        assert reqs.get(wk.LABEL_TOPOLOGY_ZONE).values == {"z1", "z2"}
        # heaviest preference included
        assert reqs.get("heavy").values == {"y"}
        assert not reqs.has("light")

    def test_strict_excludes_preferences(self):
        reqs = strict_pod_requirements(self.make_pod())
        assert not reqs.has("heavy")
        assert reqs.get(wk.LABEL_TOPOLOGY_ZONE).values == {"z1", "z2"}
