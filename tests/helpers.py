"""Object builders for tests, mirroring the reference's pkg/test builders
(test.Pod, test.NodePool, test.UnschedulablePod...)."""

from __future__ import annotations

from typing import Optional, Sequence

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import (
    Condition,
    Container,
    DaemonSet,
    LabelSelector,
    Node,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    OwnerReference,
    Pod,
    PodSpec,
)
from karpenter_tpu.apis.nodeclaim import NodeClaim
from karpenter_tpu.apis.nodepool import NodePool
from karpenter_tpu.scheduling.requirements import Operator
from karpenter_tpu.utils.resources import parse_resource_list

_counter = [0]


def _name(prefix: str) -> str:
    _counter[0] += 1
    return f"{prefix}-{_counter[0]}"


def unschedulable_pod(
    name: Optional[str] = None,
    requests: Optional[dict] = None,
    labels: Optional[dict] = None,
    node_selector: Optional[dict] = None,
    **spec_kwargs,
) -> Pod:
    pod = Pod(
        metadata=ObjectMeta(name=name or _name("pod"), labels=labels or {}),
        spec=PodSpec(
            node_selector=node_selector or {},
            containers=[Container(requests=parse_resource_list(requests or {"cpu": "100m"}))],
            **spec_kwargs,
        ),
    )
    pod.status.conditions.append(
        Condition(type="PodScheduled", status="False", reason="Unschedulable")
    )
    return pod


def nodepool(
    name: Optional[str] = None,
    requirements: Sequence[dict] = (),
    labels: Optional[dict] = None,
    taints: Sequence = (),
    limits: Optional[dict] = None,
    weight: int = 0,
) -> NodePool:
    np = NodePool(metadata=ObjectMeta(name=name or _name("nodepool")))
    np.spec.template.spec.requirements = list(requirements)
    np.spec.template.labels = dict(labels or {})
    np.spec.template.spec.taints = list(taints)
    np.spec.weight = weight
    if limits:
        np.spec.limits = parse_resource_list(limits)
    np.set_condition("Ready", "True")
    return np


def daemonset(name: Optional[str] = None, requests: Optional[dict] = None) -> DaemonSet:
    ds = DaemonSet(metadata=ObjectMeta(name=name or _name("daemonset")))
    ds.spec.template_spec.containers = [
        Container(requests=parse_resource_list(requests or {"cpu": "100m"}))
    ]
    return ds


def daemonset_pod(ds: DaemonSet, node_name: str = "") -> Pod:
    pod = Pod(
        metadata=ObjectMeta(
            name=_name(f"{ds.metadata.name}-pod"),
            namespace=ds.metadata.namespace,
            owner_references=[
                OwnerReference(kind="DaemonSet", name=ds.metadata.name, uid=ds.metadata.uid)
            ],
        ),
        spec=PodSpec(
            node_name=node_name,
            containers=[Container(requests=dict(c.requests)) for c in ds.spec.template_spec.containers],
        ),
    )
    return pod


def registered_node(
    name: Optional[str] = None,
    pool: str = "default",
    instance_type: str = "t-4-16",
    zone: str = "kwok-zone-1",
    capacity: Optional[dict] = None,
    allocatable: Optional[dict] = None,
    labels: Optional[dict] = None,
    taints: Sequence = (),
) -> Node:
    name = name or _name("node")
    node_labels = {
        wk.NODEPOOL_LABEL_KEY: pool,
        wk.LABEL_INSTANCE_TYPE: instance_type,
        wk.LABEL_TOPOLOGY_ZONE: zone,
        wk.NODE_REGISTERED_LABEL_KEY: "true",
        wk.NODE_INITIALIZED_LABEL_KEY: "true",
        wk.LABEL_HOSTNAME: name,
    }
    node_labels.update(labels or {})
    cap = parse_resource_list(capacity or {"cpu": "4", "memory": "16Gi", "pods": "110"})
    return Node(
        metadata=ObjectMeta(name=name, labels=node_labels),
        spec=NodeSpec(provider_id=f"kwok://{name}", taints=list(taints)),
        status=NodeStatus(
            capacity=cap,
            allocatable=parse_resource_list(allocatable) if allocatable else dict(cap),
        ),
    )


def bind_pod(pod: Pod, node: Node) -> Pod:
    pod.spec.node_name = node.metadata.name
    pod.status.conditions = [
        c for c in pod.status.conditions if c.type != "PodScheduled"
    ]
    pod.status.conditions.append(Condition(type="PodScheduled", status="True"))
    return pod


def node_claim_pair(
    name: str,
    pool: str = "default",
    instance_type: str = "s-4x-amd64-linux",
    zone: str = "kwok-zone-1",
    capacity_type: str = wk.CAPACITY_TYPE_ON_DEMAND,
    capacity: Optional[dict] = None,
    consolidatable: bool = True,
):
    """A registered+initialized Node and its NodeClaim, as the lifecycle
    controllers would leave them."""
    cap = parse_resource_list(capacity or {"cpu": "4", "memory": "16Gi", "pods": "110"})
    labels = {
        wk.NODEPOOL_LABEL_KEY: pool,
        wk.LABEL_INSTANCE_TYPE: instance_type,
        wk.LABEL_TOPOLOGY_ZONE: zone,
        wk.CAPACITY_TYPE_LABEL_KEY: capacity_type,
        wk.LABEL_OS: "linux",
        wk.LABEL_ARCH: "amd64",
        wk.NODE_REGISTERED_LABEL_KEY: "true",
        wk.NODE_INITIALIZED_LABEL_KEY: "true",
        wk.LABEL_HOSTNAME: name,
    }
    node = Node(
        metadata=ObjectMeta(name=name, labels=dict(labels)),
        spec=NodeSpec(provider_id=f"kwok://{name}"),
        status=NodeStatus(capacity=dict(cap), allocatable=dict(cap)),
    )
    node.status.conditions.append(Condition(type="Ready", status="True"))
    claim = NodeClaim(
        metadata=ObjectMeta(
            name=f"{name}-claim",
            labels={k: v for k, v in labels.items()
                    if k not in (wk.NODE_REGISTERED_LABEL_KEY, wk.NODE_INITIALIZED_LABEL_KEY,
                                 wk.LABEL_HOSTNAME)},
        )
    )
    claim.status.provider_id = f"kwok://{name}"
    claim.status.node_name = name
    claim.status.capacity = dict(cap)
    claim.status.allocatable = dict(cap)
    claim.set_condition("Launched", "True")
    claim.set_condition("Registered", "True")
    claim.set_condition("Initialized", "True")
    if consolidatable:
        claim.set_condition("Consolidatable", "True")
    return node, claim


def make_provisioner_harness(options=None, instance_types=None):
    """Store + cluster + informer + Provisioner wiring shared by the
    provisioner-level suites (one copy; keep constructor churn here)."""
    from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
    from karpenter_tpu.controllers.provisioning.provisioner import Provisioner
    from karpenter_tpu.events.recorder import Recorder
    from karpenter_tpu.operator.options import Options as _Options
    from karpenter_tpu.runtime.store import Store
    from karpenter_tpu.state.cluster import Cluster
    from karpenter_tpu.state.informer import StateInformer
    from karpenter_tpu.utils.clock import FakeClock

    clock = FakeClock()
    store = Store(clock=clock)
    provider = FakeCloudProvider(instance_types)
    cluster = Cluster(clock, store, provider)
    informer = StateInformer(store, cluster)
    recorder = Recorder(clock=clock)
    prov = Provisioner(
        store, provider, cluster, recorder, clock, options or _Options()
    )
    return clock, store, provider, cluster, informer, prov
