"""Simulator harness: seed determinism, report shape, trace format, the
virtual-time event loop, and the CLI (ISSUE 2 acceptance criteria)."""

import json
import subprocess
import sys
import threading

import pytest

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.sim import scenarios
from karpenter_tpu.sim import trace as tracemod
from karpenter_tpu.sim.events import EventLog
from karpenter_tpu.sim.harness import build_pod, run_scenario
from karpenter_tpu.utils.clock import FakeClock


class TestEventLog:
    def test_digest_covers_every_entry(self):
        a, b = EventLog(), EventLog()
        a.append(1.0, "node-added", node="n1")
        b.append(1.0, "node-added", node="n1")
        assert a.digest() == b.digest()
        b.append(2.0, "node-deleted", node="n1")
        assert a.digest() != b.digest()

    def test_canonical_jsonl_roundtrip(self):
        log = EventLog()
        log.append(0.5, "pod-bound", pod="p", node="n")
        [line] = log.to_jsonl().splitlines()
        assert json.loads(line) == {"t": 0.5, "ev": "pod-bound", "pod": "p", "node": "n"}


class TestTraceFormat:
    def test_generators_are_seed_deterministic(self):
        for name in scenarios.names():
            assert scenarios.resolve(name, 5) == scenarios.resolve(name, 5)

    def test_version_gate(self):
        with pytest.raises(ValueError, match="version"):
            tracemod.validate({"version": 99, "name": "x", "duration": 1, "events": []})

    def test_events_must_be_sorted(self):
        trace = scenarios.resolve("steady-state", 1)
        trace["events"] = list(reversed(trace["events"]))
        with pytest.raises(ValueError, match="sorted"):
            tracemod.validate(trace)

    def test_dumps_loads_roundtrip(self):
        trace = scenarios.resolve("spot-interruption", 3)
        assert tracemod.loads(tracemod.dumps(trace)) == trace

    def test_unknown_scenario(self):
        with pytest.raises(KeyError, match="unknown scenario"):
            scenarios.resolve("nope", 0)


class TestDeterminism:
    def test_same_seed_identical_digest_and_log(self):
        a = run_scenario(scenarios.resolve("steady-state", 7), 7)
        b = run_scenario(scenarios.resolve("steady-state", 7), 7)
        assert a.digest == b.digest
        assert a.log.to_jsonl() == b.log.to_jsonl()
        assert a.report["event_log_digest"] == a.digest
        # the WHOLE report reproduces, including solver stats — process-global
        # counters must not leak between sims in one process
        assert a.report == b.report

    def test_different_seed_different_digest(self):
        a = run_scenario(scenarios.resolve("steady-state", 7), 7)
        b = run_scenario(scenarios.resolve("steady-state", 8), 8)
        assert a.digest != b.digest


class TestReport:
    @pytest.fixture(scope="class")
    def result(self):
        return run_scenario(scenarios.resolve("steady-state", 7), 7)

    def test_cost_fields(self, result):
        cost = result.report["cost"]
        assert cost["total_usd"] > 0
        assert cost["node_hours"] > 0
        assert cost["by_capacity_type"]
        # one node for ~236 virtual seconds: node-hours bounded by duration
        assert cost["node_hours"] <= result.report["virtual_duration_s"] / 3600.0 * (
            result.report["churn"]["max_concurrent_nodes"]
        )

    def test_slo_fields(self, result):
        slo = result.report["slo"]
        assert slo["pods_submitted"] > 0
        assert slo["pods_bound"] == slo["pods_submitted"]
        assert slo["pods_never_bound"] == 0
        tts = slo["time_to_schedule_s"]
        for p in ("p50", "p90", "p99", "max"):
            assert tts[p] is not None and tts[p] > 0
        assert tts["p50"] <= tts["p99"] <= tts["max"]

    def test_churn_fields(self, result):
        churn = result.report["churn"]
        assert churn["nodes_created"] >= 1
        assert churn["nodeclaims_created"] >= 1
        assert churn["max_concurrent_nodes"] >= 1

    def test_steady_state_injects_no_faults(self, result):
        assert all(v == 0 for v in result.report["faults"].values())

    def test_efficiency_section(self, result):
        """ISSUE 15 acceptance surface: every steady solve batch reports
        into report["kernels"]["efficiency"] — host_stall_fraction in
        [0, 1], batch counts consistent — and the section rides OUTSIDE
        the kernels digest (cost models are machine facts). On this
        host-routed scenario the fraction is EXACTLY 1.0: no device
        dispatch was awaited, a deterministic fact."""
        kernels = result.report["kernels"]
        eff = kernels["efficiency"]
        assert eff["steady_batches"] > 0
        assert (
            eff["device_batches"] + eff["host_only_batches"]
            == eff["steady_batches"]
        )
        assert 0.0 <= eff["host_stall_fraction"] <= 1.0
        assert eff["host_stall_fraction"] == 1.0  # fully host-paced
        assert eff["profiler_captures_armed"] == 0
        # outside the digest: the digest reproduces with the section
        # stripped, exactly like the aot section
        import hashlib as _hashlib
        import json as _json

        deterministic = {
            "kernels": kernels["kernels"],
            "steady_recompiles": kernels["steady_recompiles"],
        }
        assert kernels["digest"] == _hashlib.sha256(
            _json.dumps(deterministic, sort_keys=True).encode()
        ).hexdigest()

    def test_lifecycle_events_in_order(self, result):
        """claim first, node after registration delay, binds after that."""
        evs = [e["ev"] for e in result.log]
        assert evs.index("nodeclaim-added") < evs.index("node-added")
        first_bind = next(e for e in result.log if e["ev"] == "pod-bound")
        first_node = next(e for e in result.log if e["ev"] == "node-added")
        assert first_bind["t"] >= first_node["t"]


class TestBuildPod:
    def test_capacity_pin_and_group_label(self):
        pod = build_pod("p-0", "g", {"cpu": "2", "capacity_type": "spot"})
        assert pod.spec.node_selector[wk.CAPACITY_TYPE_LABEL_KEY] == "spot"
        assert pod.metadata.labels["sim.kwok.sh/group"] == "g"
        from karpenter_tpu.utils import pod as podutil

        assert podutil.is_provisionable(pod)

    def test_zone_spread(self):
        pod = build_pod("p-0", "g", {"spread": "zone"})
        [tsc] = pod.spec.topology_spread_constraints
        assert tsc.topology_key == wk.LABEL_TOPOLOGY_ZONE
        assert tsc.when_unsatisfiable == "DoNotSchedule"
        assert tsc.label_selector.match_labels == {"sim.kwok.sh/group": "g"}


class TestCli:
    def test_report_and_events_files(self, tmp_path):
        report = tmp_path / "report.json"
        events = tmp_path / "events.jsonl"
        from karpenter_tpu.sim.__main__ import main

        rc = main(
            [
                "--scenario", "steady-state", "--seed", "7",
                "--report", str(report), "--events", str(events),
            ]
        )
        assert rc == 0
        data = json.loads(report.read_text())
        assert data["scenario"] == "steady-state"
        assert data["event_log_digest"].startswith("sha256:")
        lines = [json.loads(l) for l in events.read_text().splitlines()]
        assert len(lines) == data["events"]

    def test_list(self, capsys):
        from karpenter_tpu.sim.__main__ import main

        assert main(["--list"]) == 0
        out = capsys.readouterr().out
        for name in scenarios.names():
            assert name in out

    def test_trace_file_input(self, tmp_path):
        trace_path = tmp_path / "t.json"
        trace_path.write_text(tracemod.dumps(scenarios.resolve("steady-state", 1)))
        from karpenter_tpu.sim.__main__ import main

        report = tmp_path / "r.json"
        assert main(["--trace", str(trace_path), "--seed", "1",
                     "--report", str(report)]) == 0
        assert json.loads(report.read_text())["events"] > 0


class TestFakeClockWaiters:
    """Satellite: registered-waiter wakeups on the shared FakeClock."""

    def test_default_sleep_still_steps(self):
        clock = FakeClock()
        t0 = clock.now()
        clock.sleep(5.0)
        assert clock.now() == t0 + 5.0

    def test_driver_sleep_steps_in_blocking_mode(self):
        clock = FakeClock()
        clock.enable_blocking_sleep()
        t0 = clock.now()
        clock.sleep(3.0)  # driver can never deadlock on itself
        assert clock.now() == t0 + 3.0

    def test_worker_sleep_blocks_until_time_passes(self):
        clock = FakeClock()
        clock.enable_blocking_sleep()
        woke = threading.Event()

        def worker():
            clock.sleep(10.0)
            woke.set()

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        # the worker must park, not step time itself
        for _ in range(100):
            if clock.waiter_count() == 1:
                break
            threading.Event().wait(0.01)
        assert clock.waiter_count() == 1
        assert clock.next_wakeup() == clock.now() + 10.0
        assert not woke.is_set()
        clock.step(5.0)
        assert not woke.wait(0.05)
        clock.step(5.0)
        assert woke.wait(2.0)
        t.join(2.0)
        assert clock.waiter_count() == 0

    def test_disable_releases_blocked_workers(self):
        clock = FakeClock()
        clock.enable_blocking_sleep()
        woke = threading.Event()

        def worker():
            clock.sleep(100.0)
            woke.set()

        threading.Thread(target=worker, daemon=True).start()
        for _ in range(100):
            if clock.waiter_count() == 1:
                break
            threading.Event().wait(0.01)
        clock.disable_blocking_sleep()
        assert woke.wait(2.0)
