"""End-to-end: pending pods become kwok nodes through the full operator
loop — the minimum end-to-end slice of SURVEY.md §7 step 4 — plus drift
replacement and consolidation e2e (kwok as the correctness harness)."""

import pytest

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.cloudprovider.kwok.provider import KwokCloudProvider
from karpenter_tpu.operator.operator import Operator
from karpenter_tpu.operator.options import Options
from karpenter_tpu.runtime.store import Store
from karpenter_tpu.utils.clock import FakeClock

from helpers import nodepool, unschedulable_pod


def make_operator(options=None):
    clock = FakeClock()
    store = Store(clock=clock)
    provider = KwokCloudProvider(store, clock)
    op = Operator(store, provider, clock=clock, options=options or Options())
    return clock, store, provider, op


def settle(clock, op, passes=12, step=2.0):
    for _ in range(passes):
        clock.step(step)
        op.run_once()


class TestEndToEnd:
    def test_pending_pods_become_kwok_nodes(self):
        clock, store, provider, op = make_operator()
        store.create(nodepool("workers"))
        pods = [store.create(unschedulable_pod(requests={"cpu": "1"})) for _ in range(5)]
        settle(clock, op)
        nodes = store.list("Node")
        assert len(nodes) >= 1
        claims = store.list("NodeClaim")
        assert claims
        for claim in claims:
            assert claim.condition_is_true("Launched")
            assert claim.condition_is_true("Registered")
            assert claim.condition_is_true("Initialized")
        for node in nodes:
            assert node.metadata.labels[wk.NODE_REGISTERED_LABEL_KEY] == "true"
            assert node.metadata.labels[wk.NODE_INITIALIZED_LABEL_KEY] == "true"
            assert not any(
                t.key == wk.UNREGISTERED_TAINT_KEY for t in node.spec.taints
            )

    def test_anti_affinity_schrodinger_across_batches(self):
        """topology_test.go:2512 'should not violate pod anti-affinity on
        zone (Schrödinger)': a pod whose anti-affinity zone is undetermined
        blocks its target in the SAME batch (it could land in any zone);
        once node creation commits the zone, a later batch schedules the
        target into a different zone."""
        from karpenter_tpu.apis.core import (
            Affinity,
            LabelSelector,
            PodAffinityTerm,
            PodAntiAffinity,
        )

        clock, store, provider, op = make_operator()
        store.create(nodepool("workers"))
        anti = Affinity(
            pod_anti_affinity=PodAntiAffinity(
                required=[
                    PodAffinityTerm(
                        topology_key=wk.LABEL_TOPOLOGY_ZONE,
                        label_selector=LabelSelector(
                            match_labels={"security": "s2"}
                        ),
                    )
                ]
            )
        )
        zone_anywhere = store.create(
            unschedulable_pod(
                name="zone-anywhere", requests={"cpu": "2"}, affinity=anti
            )
        )
        target = store.create(
            unschedulable_pod(name="target", labels={"security": "s2"})
        )
        # batch 1: the anti pod opens a claim; the target CANNOT share the
        # batch — the anti pod's zone is still undetermined
        for _ in range(2):  # trigger pass + batch-window close
            clock.step(2.0)
            op.run_once()
        assert store.list("NodeClaim"), "anti pod should open a claim"
        assert store.get("Pod", "target").spec.node_name == ""
        # nodes register, the zone commits, later batches admit the target
        settle(clock, op)
        bound_anti = store.get("Pod", "zone-anywhere")
        bound_target = store.get("Pod", "target")
        assert bound_anti.spec.node_name and bound_target.spec.node_name
        zone_of = {
            n.metadata.name: n.metadata.labels[wk.LABEL_TOPOLOGY_ZONE]
            for n in store.list("Node")
        }
        assert (
            zone_of[bound_anti.spec.node_name]
            != zone_of[bound_target.spec.node_name]
        )

    def test_node_selector_end_to_end(self):
        clock, store, provider, op = make_operator()
        store.create(nodepool("workers"))
        store.create(
            unschedulable_pod(
                requests={"cpu": "1"},
                node_selector={wk.LABEL_ARCH: "arm64", wk.LABEL_TOPOLOGY_ZONE: "kwok-zone-2"},
            )
        )
        settle(clock, op)
        [node] = store.list("Node")
        assert node.metadata.labels[wk.LABEL_ARCH] == "arm64"
        assert node.metadata.labels[wk.LABEL_TOPOLOGY_ZONE] == "kwok-zone-2"

    def test_no_nodepool_no_nodes(self):
        clock, store, provider, op = make_operator()
        store.create(unschedulable_pod())
        settle(clock, op)
        assert store.list("Node") == []

    def test_drift_replaces_node_end_to_end(self):
        clock, store, provider, op = make_operator()
        pool = store.create(nodepool("workers"))
        pod = store.create(unschedulable_pod(requests={"cpu": "1"}))
        settle(clock, op)
        [old_node] = store.list("Node")
        # bind the pod (kwok has no scheduler; bind manually like kube-scheduler)
        pod = store.get("Pod", pod.metadata.name)
        pod.spec.node_name = old_node.metadata.name
        pod.status.conditions = []
        store.update(pod)
        settle(clock, op, passes=2)
        # mutate a static field -> hash drift
        pool = store.get("NodePool", "workers")
        from karpenter_tpu.apis.core import Taint
        pool.spec.template.spec.startup_taints = [Taint(key="fresh", value="x")]
        store.update(pool)
        settle(clock, op, passes=30, step=4.0)
        # old claim replaced: a new claim exists and the old one is gone
        claims = store.list("NodeClaim")
        assert claims, "drift produced no claims"
        assert all(
            not c.condition_is_true("Drifted") or c.metadata.deletion_timestamp
            for c in claims
        ) or len(store.list("Node")) >= 1

    def test_empty_node_consolidated_end_to_end(self):
        clock, store, provider, op = make_operator()
        pool = nodepool("workers")
        pool.spec.disruption.consolidate_after = 10.0
        store.create(pool)
        pod = store.create(unschedulable_pod(requests={"cpu": "1"}))
        settle(clock, op)
        assert store.list("Node")
        # pod disappears; node sits empty past consolidateAfter
        store.delete(store.get("Pod", pod.metadata.name))
        settle(clock, op, passes=40, step=5.0)
        assert store.list("Node") == []
        assert store.list("NodeClaim") == []

    def test_metrics_exposed(self):
        clock, store, provider, op = make_operator()
        store.create(nodepool("workers"))
        store.create(unschedulable_pod())
        settle(clock, op)
        text = op.metrics_text()
        assert "karpenter_nodeclaims_created_total" in text
        assert "karpenter_cluster_state_node_count" in text
