"""Kernel observatory (observability/kernels.py + tracing/kernel.py): the
instrumented-dispatch choke point, shape-bucket accounting, the sealed
zero-recompile steady-state contract (with a forced-recompile spec proving
the guard trips), nested-fence attribution, device-memory sampling, the
/metrics mirror of the solver cache counters, the solverd.prewarm span,
and report["kernels"] determinism."""

import time

import jax
import jax.numpy as jnp
import pytest

from karpenter_tpu.metrics import global_registry
from karpenter_tpu.observability import kernels as kobs
from karpenter_tpu.tracing import kernel as ktime


@pytest.fixture
def registry():
    """The process-global registry, reset before and unsealed after so a
    seal from one spec never reclassifies another spec's dispatches."""
    reg = kobs.registry()
    reg.reset()
    yield reg
    reg.reset()


class TestRegistryAccounting:
    def test_dispatch_records_shapes_phases_and_cache_hits(self, registry):
        @jax.jit
        def f(x):
            return x * 2.0

        ktime.dispatch(f, jnp.ones((4,)), kernel="spec.k")  # cold: compiles
        ktime.dispatch(f, jnp.ones((4,)), kernel="spec.k")  # warm: cache hit
        snap = registry.debug_snapshot("spec.k")
        assert snap["dispatches"] == 2
        assert snap["compiles"] == 1
        assert snap["cache_hits"] == 1
        assert snap["recompiles"] == 0
        assert snap["phases"] == {"warmup": 2, "steady": 0, "aot-warm": 0}
        (shape,) = snap["shapes"]
        assert shape["shape"] == "4"
        assert shape["dispatches"] == 2

    def test_record_host_counts_host_twins(self, registry):
        registry.record_host("spec.twin", "8x8")
        registry.record_host("spec.twin", "8x8")
        snap = registry.debug_snapshot("spec.twin")
        assert snap["host_dispatches"] == 2
        assert snap["dispatches"] == 0
        assert snap["shapes"][0]["phases"]["host"] == 2

    def test_shape_signature_covers_array_args_only(self):
        sig = kobs.shape_signature(
            (jnp.ones((4, 2)), "static", 7, jnp.ones((3,)))
        )
        assert sig == "4x2,3"
        assert kobs.shape_signature(()) == "scalar"

    def test_debug_snapshot_unknown_kernel_is_none(self, registry):
        assert registry.debug_snapshot("nope") is None

    def test_full_snapshot_table_and_phase(self, registry):
        registry.record_host("spec.a", "1")
        snap = registry.debug_snapshot()
        assert snap["sealed"] is False
        assert snap["phase"] == "warmup"
        assert any(row["kernel"] == "spec.a" for row in snap["kernels"])


class TestSealContract:
    """The zero-recompile steady-state contract: compiles after seal() are
    recompiles — counter + callback + event list. The forced-recompile spec
    proves the guard actually trips."""

    def test_warm_steady_dispatches_do_not_trip(self, registry):
        @jax.jit
        def f(x):
            return x + 1.0

        ktime.dispatch(f, jnp.ones((16,)), kernel="spec.seal")  # warmup compile
        registry.seal()
        assert registry.phase == "steady"
        for _ in range(3):
            ktime.dispatch(f, jnp.ones((16,)), kernel="spec.seal")
        assert registry.steady_recompiles() == 0
        snap = registry.debug_snapshot("spec.seal")
        assert snap["phases"] == {"warmup": 1, "steady": 3, "aot-warm": 0}

    def test_forced_recompile_trips_guard(self, registry):
        @jax.jit
        def f(x):
            return x + 1.0

        ktime.dispatch(f, jnp.ones((16,)), kernel="spec.trip")
        registry.seal()
        fired = []
        registry.on_recompile(lambda k, s: fired.append((k, s)), key="spec")
        ctr = global_registry.get("karpenter_kernel_recompiles_total")
        base = ctr.value({"kernel": "spec.trip"})
        # a shape the executable cache has never seen — this IS a recompile
        ktime.dispatch(f, jnp.ones((17,)), kernel="spec.trip")
        assert registry.steady_recompiles() == 1
        assert fired == [("spec.trip", "17")]
        assert ctr.value({"kernel": "spec.trip"}) == base + 1
        snap = registry.debug_snapshot()
        assert {"kernel": "spec.trip", "shape": "17"} in snap["recompile_events"]

    def test_callback_replacement_by_key(self, registry):
        a, b = [], []
        registry.on_recompile(lambda k, s: a.append(k), key="slot")
        registry.on_recompile(lambda k, s: b.append(k), key="slot")
        registry.seal()

        @jax.jit
        def f(x):
            return x - 1.0

        ktime.dispatch(f, jnp.ones((19,)), kernel="spec.slot")
        assert a == [] and b == ["spec.slot"]


class TestSteadyStateSolveFloor:
    """Perf-floor-style guard: a REAL engine's steady-state feasibility
    sweeps must not recompile — a recompiling sweep pays hundreds of ms
    per solve, the regression class ROADMAP item 2 exists to kill."""

    def test_repeat_solves_zero_recompiles(self, registry):
        from karpenter_tpu.cloudprovider.kwok.instance_types import (
            construct_instance_types,
        )
        from karpenter_tpu.ops import catalog as catmod
        from karpenter_tpu.ops.catalog import CatalogEngine
        from karpenter_tpu.scheduling.requirements import (
            Operator,
            Requirement,
            Requirements,
        )
        from karpenter_tpu.apis import labels as wk
        import numpy as np

        engine = CatalogEngine(construct_instance_types())
        prev = catmod.FORCE_BACKEND
        catmod.FORCE_BACKEND = "device"
        try:
            engine.warmup()
            reqs = Requirements(
                Requirement(wk.LABEL_ARCH, Operator.IN, ["amd64"])
            )
            rows = engine.rows_for(reqs)
            req_vec = np.zeros((1, len(engine.resource_dims)))
            engine.feasibility([rows], req_vec)  # residual warmup compile
            registry.seal()
            base = registry.steady_recompiles()
            for _ in range(3):
                engine.feasibility([rows], req_vec)
            assert registry.steady_recompiles() == base, (
                "steady-state feasibility sweep recompiled: "
                f"{registry.debug_snapshot()['recompile_events']}"
            )
        finally:
            catmod.FORCE_BACKEND = prev


class TestNestedFenceGuard:
    """A fenced dispatch whose callable itself dispatches must attribute
    wall time to the INNERMOST dispatch only (satellite: no double-counted
    execute wall)."""

    def test_outer_subtracts_inner_elapsed(self):
        def inner():
            time.sleep(0.05)
            return 1

        def outer():
            ktime.dispatch(inner, kernel="spec.inner")
            time.sleep(0.02)
            return 2

        reg = kobs.registry()
        reg.reset()
        try:
            with ktime.measure() as acc:
                ktime.dispatch(outer, kernel="spec.outer")
            # both dispatches count, but the 0.05s of inner work is
            # attributed ONCE: total execute ~0.07s, not ~0.12s
            assert acc["dispatches"] == 2
            assert 0.06 < acc["execute_s"] < 0.11, acc
            outer_snap = reg.debug_snapshot("spec.outer")
            inner_snap = reg.debug_snapshot("spec.inner")
            assert 0.04 < inner_snap["execute_wall_s"] < 0.09
            # outer's self time excludes the inner dispatch entirely
            assert outer_snap["execute_wall_s"] < 0.05
        finally:
            reg.reset()

    def test_unnested_accounting_unchanged(self):
        @jax.jit
        def f(x):
            return x * 3.0

        with ktime.measure() as acc:
            ktime.dispatch(f, jnp.ones((4,)))
            ktime.dispatch(f, jnp.ones((4,)))
        assert acc["dispatches"] == 2
        assert acc["compiles"] in (0, 1)  # cold only on the first-ever run


class TestDeviceMemory:
    def test_sample_reports_live_bytes_and_sets_gauge(self):
        keep = jnp.ones((256,), jnp.float32)  # noqa: F841 — held live
        sample = kobs.sample_device_memory()
        assert sample["live_array_bytes"] >= 256 * 4
        assert sample["live_arrays"] >= 1
        gauge = global_registry.get("karpenter_device_live_array_bytes")
        assert gauge.value() == float(sample["live_array_bytes"])
        # the registry caches the last sample for /debug/kernels
        assert kobs.registry().debug_snapshot()["device_memory"] == sample


class TestCacheCounterMirror:
    def test_publish_increments_metrics_by_delta(self):
        from karpenter_tpu.ops import ffd

        ffd.publish_cache_counters()  # flush any prior drift
        ctr = global_registry.get("karpenter_solver_cache_events_total")
        base = ctr.value({"event": "topo_oracle_calls"})
        from karpenter_tpu.ops import topo_counts

        topo_counts.ORACLE_CALLS += 5
        snap = ffd.publish_cache_counters()
        assert snap["topo_oracle_calls"] == topo_counts.ORACLE_CALLS
        assert ctr.value({"event": "topo_oracle_calls"}) == base + 5
        # idempotent: republish without new events adds nothing
        ffd.publish_cache_counters()
        assert ctr.value({"event": "topo_oracle_calls"}) == base + 5

    def test_solverd_batch_publishes_counters(self):
        """run_pending is the choke point: after a batch, the mirrored
        counters are on /metrics without any scrape-time work."""
        from karpenter_tpu.ops import topo_counts
        from karpenter_tpu.solverd.api import SolveRequest
        from karpenter_tpu.solverd.service import SolverService
        from karpenter_tpu.utils.clock import FakeClock

        class _Sched:
            engine = None

            def solve(self, pods, timeout=None):
                topo_counts.ORACLE_CALLS += 1
                return "ok"

        svc = SolverService(clock=FakeClock())
        ctr = global_registry.get("karpenter_solver_cache_events_total")
        base = ctr.value({"event": "topo_oracle_calls"})
        svc.submit(SolveRequest(kind="solve", scheduler=_Sched(), pods=[]))
        svc.run_pending()
        assert ctr.value({"event": "topo_oracle_calls"}) == base + 1
        svc.close()


class TestPrewarmSpan:
    def test_first_provision_pass_emits_prewarm_span(self):
        """solverd's engine prewarm used to run outside any span — its
        compiles were invisible in /debug/traces. The first provisioning
        pass now wraps it in a solverd.prewarm root span carrying the
        kernel compile/execute split as volatile attrs."""
        from karpenter_tpu import tracing
        from karpenter_tpu.cloudprovider.kwok.provider import KwokCloudProvider
        from karpenter_tpu.operator.operator import Operator
        from karpenter_tpu.runtime.store import Store
        from karpenter_tpu.utils.clock import FakeClock
        from helpers import nodepool

        clock = FakeClock()
        store = Store(clock=clock)
        operator = Operator(store, KwokCloudProvider(store, clock), clock=clock)
        store.create(nodepool("workers"))
        operator.run_once()

        def prewarm_spans():
            ring = tracing.tracer().ring
            return [
                s
                for summary in ring.summaries(500)
                for s in ring.trace(summary["trace_id"])
                if s["name"] == "solverd.prewarm"
            ]

        spans = prewarm_spans()
        assert spans, "no solverd.prewarm span after the first pass"
        # the live tracer keeps the volatile kernel split on the span
        assert "kernel_compiles" in spans[0]["attrs"]
        # a second pass must NOT re-emit it (prewarm is idempotent once warm)
        operator.run_once()
        assert len(prewarm_spans()) == len(spans)


class TestSimReportDeterminism:
    """Acceptance: report["kernels"] is byte-deterministic across same-seed
    runs and steady-state recompile count is zero."""

    TRACE = {
        "version": 1,
        "name": "kernels-mini",
        "duration": 80.0,
        "tick": 1.0,
        "nodepools": [{"name": "workers"}],
        "events": [
            {"at": 2.0, "kind": "submit", "group": "job", "count": 4,
             "pod": {"cpu": "1"}},
            {"at": 30.0, "kind": "submit", "group": "late", "count": 3,
             "pod": {"cpu": "2", "memory": "2Gi"}},
        ],
    }

    def test_same_seed_identical_kernel_reports(self):
        from karpenter_tpu.sim.harness import run_scenario

        a = run_scenario(dict(self.TRACE), seed=13)
        b = run_scenario(dict(self.TRACE), seed=13)
        ka, kb = a.report["kernels"], b.report["kernels"]
        assert ka == kb
        assert ka["digest"] == kb["digest"]
        assert ka["kernels"], "no kernel activity recorded by the sim"

    def test_zero_steady_recompiles(self):
        from karpenter_tpu.sim.harness import run_scenario

        result = run_scenario(dict(self.TRACE), seed=13)
        assert result.report["kernels"]["steady_recompiles"] == 0
