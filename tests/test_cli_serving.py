"""CLI entry point, HTTP serving, and the logging subsystem
(reference kwok/main.go:28-47, operator.go:169-208, logging/logging.go)."""

import io
import json
import subprocess
import sys
import urllib.request

import pytest

from karpenter_tpu.operator import logging as klog
from karpenter_tpu.operator.serving import Server, ServingConfig

from helpers import nodepool, unschedulable_pod


class TestCLI:
    def test_help(self):
        out = subprocess.run(
            [sys.executable, "-m", "karpenter_tpu", "--help"],
            capture_output=True,
            text=True,
            timeout=120,
            cwd="/root/repo",
        )
        assert out.returncode == 0
        assert "--feature-gates" in out.stdout
        assert "--solver-backend" in out.stdout

    def test_main_runs_passes_and_logs(self):
        from karpenter_tpu.__main__ import main

        stream = io.StringIO()
        klog.configure("info", stream=stream)
        rc = main(
            argv=["--metrics-port", "0", "--health-probe-port", "0"],
            max_passes=2,
            pass_interval=0.0,
        )
        assert rc == 0
        lines = [json.loads(line) for line in stream.getvalue().splitlines()]
        assert any(e["message"] == "starting operator" for e in lines)
        assert any(e["message"] == "operator stopped" for e in lines)

    def test_unknown_flag_fails(self):
        out = subprocess.run(
            [sys.executable, "-m", "karpenter_tpu", "--definitely-not-a-flag"],
            capture_output=True,
            text=True,
            timeout=120,
            cwd="/root/repo",
        )
        assert out.returncode != 0


class TestServing:
    def _get(self, port, path):
        with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=5) as r:
            return r.status, r.read().decode()

    def test_metrics_health_ready(self):
        config = ServingConfig(
            metrics_text=lambda: "# HELP test_metric\ntest_metric 1\n",
            healthy=lambda: True,
            ready=lambda: True,
        )
        server = Server(0, config, host="127.0.0.1").start()
        try:
            status, body = self._get(server.port, "/metrics")
            assert status == 200 and "test_metric 1" in body
            status, body = self._get(server.port, "/healthz")
            assert status == 200 and body == "ok"
            status, body = self._get(server.port, "/readyz")
            assert status == 200
            with pytest.raises(urllib.error.HTTPError) as err:
                self._get(server.port, "/nope")
            assert err.value.code == 404
        finally:
            server.stop()

    def test_unhealthy_returns_500(self):
        config = ServingConfig(
            metrics_text=lambda: "", healthy=lambda: False, ready=lambda: False
        )
        server = Server(0, config, host="127.0.0.1").start()
        try:
            with pytest.raises(urllib.error.HTTPError) as err:
                self._get(server.port, "/healthz")
            assert err.value.code == 500
        finally:
            server.stop()

    def test_profiling_gated(self):
        config = ServingConfig(
            metrics_text=lambda: "", healthy=lambda: True, ready=lambda: True,
            enable_profiling=False,
        )
        server = Server(0, config, host="127.0.0.1").start()
        try:
            with pytest.raises(urllib.error.HTTPError) as err:
                self._get(server.port, "/debug/stacks")
            assert err.value.code == 404
        finally:
            server.stop()
        config.enable_profiling = True
        server = Server(0, config, host="127.0.0.1").start()
        try:
            status, body = self._get(server.port, "/debug/stacks")
            assert status == 200 and "thread" in body
        finally:
            server.stop()

    def test_profile_endpoint(self):
        """/debug/profile samples all threads and returns pprof-style text
        (reference operator.go:169-185). Regression: serving.py once shipped
        an undefined-name crash here because nothing drove the endpoint."""
        import threading
        import time

        config = ServingConfig(
            metrics_text=lambda: "", healthy=lambda: True, ready=lambda: True,
            enable_profiling=True,
        )
        server = Server(0, config, host="127.0.0.1").start()
        stop = threading.Event()

        def busy():
            while not stop.is_set():
                sum(i * i for i in range(1000))
                time.sleep(0.001)

        worker = threading.Thread(target=busy, name="busy-worker", daemon=True)
        worker.start()
        try:
            status, body = self._get(server.port, "/debug/profile?seconds=0.2")
            assert status == 200
            assert "samples over" in body
            assert "hottest frames" in body and "hottest stacks" in body
            # the sampler saw actual frames from other threads
            assert ".py:" in body
        finally:
            stop.set()
            server.stop()

    def test_profile_endpoint_gated(self):
        config = ServingConfig(
            metrics_text=lambda: "", healthy=lambda: True, ready=lambda: True,
            enable_profiling=False,
        )
        server = Server(0, config, host="127.0.0.1").start()
        try:
            with pytest.raises(urllib.error.HTTPError) as err:
                self._get(server.port, "/debug/profile?seconds=0.1")
            assert err.value.code == 404
        finally:
            server.stop()

    def test_operator_metrics_served_end_to_end(self):
        """The operator's registry rides the wire: counters from a real
        reconcile loop appear in /metrics."""
        from karpenter_tpu.cloudprovider.kwok.provider import KwokCloudProvider
        from karpenter_tpu.operator.operator import Operator
        from karpenter_tpu.runtime.store import Store
        from karpenter_tpu.utils.clock import FakeClock

        clock = FakeClock()
        store = Store(clock=clock)
        op = Operator(store, KwokCloudProvider(store, clock), clock=clock)
        store.create(nodepool("workers"))
        store.create(unschedulable_pod(requests={"cpu": "1"}))
        for _ in range(8):
            clock.step(2.0)
            op.run_once()
        config = ServingConfig(
            metrics_text=op.metrics_text, healthy=op.healthy, ready=op.healthy
        )
        server = Server(0, config, host="127.0.0.1").start()
        try:
            status, body = self._get(server.port, "/metrics")
            assert status == 200
            assert "karpenter_nodeclaims_created_total" in body
            # device fast-path observability rides the same registry
            # (ops/ffd.py counters; VERDICT r2 weak #5)
            assert "karpenter_scheduler_device" in body
            assert "karpenter_cloudprovider_duration_seconds" in body
        finally:
            server.stop()


class TestLogging:
    def test_json_structure_and_levels(self):
        stream = io.StringIO()
        klog.configure("info", stream=stream)
        log = klog.logger("test")
        log.debug("hidden")
        log.info("visible", pods=3)
        entries = [json.loads(line) for line in stream.getvalue().splitlines()]
        assert len(entries) == 1
        assert entries[0]["message"] == "visible"
        assert entries[0]["pods"] == 3
        assert entries[0]["logger"] == "karpenter.test"
        assert entries[0]["level"] == "info"

    def test_nop_silences(self):
        stream = io.StringIO()
        klog.configure("info", stream=stream)
        log = klog.logger("test")
        with klog.nop():
            log.info("silenced")
        log.info("audible")
        entries = [json.loads(line) for line in stream.getvalue().splitlines()]
        assert [e["message"] for e in entries] == ["audible"]

    def test_simulations_are_silent_e2e(self):
        """simulate_scheduling must not emit logs even though the same
        scheduler path logs during real provisioning."""
        from karpenter_tpu.cloudprovider.kwok.provider import KwokCloudProvider
        from karpenter_tpu.operator.operator import Operator
        from karpenter_tpu.runtime.store import Store
        from karpenter_tpu.utils.clock import FakeClock

        clock = FakeClock()
        store = Store(clock=clock)
        op = Operator(store, KwokCloudProvider(store, clock), clock=clock)
        store.create(nodepool("workers"))
        store.create(unschedulable_pod(requests={"cpu": "1"}))
        stream = io.StringIO()
        klog.configure("info", stream=stream)
        for _ in range(10):
            clock.step(2.0)
            op.run_once()
        provisioning_logs = [
            json.loads(line)
            for line in stream.getvalue().splitlines()
            if json.loads(line)["logger"] == "karpenter.provisioner"
        ]
        assert provisioning_logs, "real provisioning should log"
        # a simulation over the same stack emits nothing
        stream.truncate(0)
        stream.seek(0)
        from karpenter_tpu.controllers.disruption.helpers import simulate_scheduling

        simulate_scheduling(store, op.cluster, op.provisioner)
        assert stream.getvalue() == ""
