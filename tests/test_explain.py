"""Decision provenance observatory (observability/explain.py): stage
classification, funnel staging + the solve-completion commit barrier, ring
bounds under churn, sampled-mode determinism, the report digest, event
enrichment, what-if requirement dropping, and the operator's
/debug/explain snapshot + counterfactual probe end to end."""

import pytest

from karpenter_tpu.apis.core import Condition, Container, ObjectMeta, Pod, PodSpec
from karpenter_tpu.observability import explain as explmod
from karpenter_tpu.utils.clock import FakeClock

from helpers import nodepool, unschedulable_pod


@pytest.fixture(autouse=True)
def clean_ledger():
    """The recorder is process-global: every test starts disabled and
    empty, and leaves it that way."""
    rec = explmod.recorder()
    rec.configure(mode="off", capacity=256)
    rec.reset()
    yield rec
    rec.configure(mode="off", capacity=256)
    rec.reset()


def make_pod(name: str, uid: str) -> Pod:
    pod = Pod(
        metadata=ObjectMeta(name=name, uid=uid),
        spec=PodSpec(containers=[Container()]),
    )
    pod.status.conditions.append(
        Condition(type="PodScheduled", status="False", reason="Unschedulable")
    )
    return pod


class TestClassify:
    def test_typed_filter_error_individual_flags(self):
        from karpenter_tpu.scheduler.nodeclaim import InstanceTypeFilterError

        err = InstanceTypeFilterError(
            requirements_met=True, fits=False, has_offering=True
        )
        assert explmod.classify(err) == ("resources",)
        err = InstanceTypeFilterError(
            requirements_met=False, fits=True, has_offering=False
        )
        assert explmod.classify(err) == ("requirements", "offerings")

    def test_typed_filter_error_pairwise_blames_third(self):
        from karpenter_tpu.scheduler.nodeclaim import InstanceTypeFilterError

        base = dict(requirements_met=True, fits=True, has_offering=True)
        assert explmod.classify(
            InstanceTypeFilterError(**base, requirements_and_fits=True)
        ) == ("offerings",)
        assert explmod.classify(
            InstanceTypeFilterError(**base, fits_and_offering=True)
        ) == ("requirements",)
        assert explmod.classify(
            InstanceTypeFilterError(**base, requirements_and_offering=True)
        ) == ("resources",)

    def test_min_values_wins(self):
        from karpenter_tpu.scheduler.nodeclaim import InstanceTypeFilterError

        err = InstanceTypeFilterError(
            fits=False, min_values_incompatible="minValues requirement ..."
        )
        assert explmod.classify(err) == ("min-values",)

    def test_timeout(self):
        assert explmod.classify(TimeoutError("solve timed out")) == ("timeout",)

    def test_message_rules(self):
        cases = {
            "did not tolerate node taint gpu=true:NoSchedule": "taints",
            "incompatible requirements, key foo": "requirements",
            "all available instance types exceed limits for nodepool 'x'": "limits",
            "checking host port usage conflict on 8080": "host-ports",
            "would violate topology spread constraint": "topology",
            "no nodepools found": "no-nodepools",
        }
        for message, stage in cases.items():
            assert explmod.classify(ValueError(message)) == (stage,), message

    def test_aggregated_message_classifies_per_part(self):
        message = (
            "incompatible requirements, key a; "
            "all available instance types exceed limits for nodepool 'b'"
        )
        assert explmod.classify_message(message) == ("requirements", "limits")

    def test_unknown_falls_through(self):
        assert explmod.classify(ValueError("some novel failure")) == ("unknown",)

    def test_every_stage_is_interned(self):
        for stage in explmod.STAGES:
            assert explmod._stage_order(stage) < len(explmod.STAGES)


class TestLedger:
    def test_disabled_hooks_are_noops(self, clean_ledger):
        rec = clean_ledger
        pod = make_pod("p", "u1")
        rec.note_funnel("u1", [{"nodepool": "n", "stages": ["limits"], "error": "e"}])
        rec.commit_solve([pod], {pod: ValueError("x")})
        # nothing captured (the disabled->404 gate lives in the operator)
        snap = rec.snapshot()
        assert snap["mode"] == "off" and snap["ring_depth"] == 0
        assert rec.snapshot(pod="u1") is None
        assert rec.counters()["explain_committed"] == 0

    def test_commit_only_on_solve_kind(self, clean_ledger):
        rec = clean_ledger
        rec.configure(mode="on")
        pod = make_pod("p", "u1")
        rec.note_funnel("u1", [{"nodepool": "n", "stages": ["limits"], "error": "e"}])
        rec.commit_solve([pod], {pod: ValueError("x")}, kind="simulate")
        assert rec.snapshot()["ring_depth"] == 0
        assert rec.counters()["explain_staged"] == 0  # staging cleared
        rec.note_funnel("u1", [{"nodepool": "n", "stages": ["limits"], "error": "e"}])
        rec.commit_solve([pod], {pod: ValueError("x")}, kind="solve")
        assert rec.snapshot()["ring_depth"] == 1

    def test_scheduled_pod_drops_staging_without_entry(self, clean_ledger):
        rec = clean_ledger
        rec.configure(mode="on")
        pod = make_pod("p", "u1")
        rec.note_funnel("u1", [{"nodepool": "n", "stages": ["limits"], "error": "e"}])
        rec.commit_solve([pod], {})  # the pod placed
        assert rec.snapshot()["ring_depth"] == 0
        assert rec.counters()["explain_staged"] == 0

    def test_ring_eviction_and_recency_refresh(self, clean_ledger):
        rec = clean_ledger
        rec.configure(mode="on", capacity=2)
        pods = {u: make_pod(f"p-{u}", u) for u in ("a", "b", "c")}
        for u in ("a", "b"):
            rec.commit_solve([pods[u]], {pods[u]: ValueError("x")})
        # re-failing 'a' refreshes its recency: 'b' is now the oldest
        rec.commit_solve([pods["a"]], {pods["a"]: ValueError("x")})
        rec.commit_solve([pods["c"]], {pods["c"]: ValueError("x")})
        snap = rec.snapshot()
        assert snap["ring_depth"] == 2 and snap["evicted"] == 1
        held = {row["uid"] for row in snap["pods"]}
        assert held == {"a", "c"}
        assert rec.entry("a")["solves"] == 2
        assert rec.snapshot(pod="b") is None  # evicted -> 404

    def test_staging_bounded_under_churn(self, clean_ledger):
        rec = clean_ledger
        rec.configure(mode="on", capacity=4)
        for i in range(200):
            rec.note_funnel(
                f"uid-{i}", [{"nodepool": "n", "stages": ["limits"], "error": "e"}]
            )
        assert rec.counters()["explain_staged"] <= 4 * rec.capacity

    def test_sampled_mode_is_deterministic(self, clean_ledger):
        rec = clean_ledger
        rec.configure(mode="sampled")
        uids = [f"uid-{i}" for i in range(400)]
        picked = {u for u in uids if rec.want(u)}
        assert picked == {u for u in uids if rec.want(u)}  # pure function
        # ~25% draw: wide tolerance, zero flake (the set is fixed)
        assert 40 < len(picked) < 180
        other = explmod.ExplainRecorder()
        other.configure(mode="sampled")
        assert picked == {u for u in uids if other.want(u)}

    def test_entry_lookup_by_name_and_namespaced_name(self, clean_ledger):
        rec = clean_ledger
        rec.configure(mode="on")
        pod = make_pod("web-0", "u9")
        rec.commit_solve([pod], {pod: ValueError("x")})
        assert rec.entry("u9")["pod"] == "web-0"
        assert rec.entry("web-0")["uid"] == "u9"
        assert rec.entry("default/web-0")["uid"] == "u9"
        assert rec.entry("missing") is None

    def test_top_reasons_funnel_ordered(self, clean_ledger):
        rec = clean_ledger
        rec.configure(mode="on")
        pod = make_pod("p", "u1")
        rec.note_funnel(
            "u1",
            [
                {"nodepool": "gpu", "stages": ["taints"], "error": "e1"},
                {"nodepool": "workers", "stages": ["limits"], "error": "e2"},
            ],
        )
        rec.commit_solve([pod], {pod: ValueError("did not tolerate taint")})
        assert rec.top_reasons("u1", k=3) == ["taints(gpu)", "limits(workers)"]
        assert rec.top_reasons("u1", k=1) == ["taints(gpu)"]
        assert rec.top_reasons("nope") == []

    def test_report_digest_reproduces(self, clean_ledger):
        def build():
            rec = explmod.ExplainRecorder(clock=FakeClock())
            rec.configure(mode="on")
            for u in ("a", "b"):
                pod = make_pod(f"p-{u}", u)
                rec.note_funnel(
                    u, [{"nodepool": "n", "stages": ["limits"], "error": "e"}]
                )
                rec.commit_solve([pod], {pod: ValueError("exceed limits for nodepool 'n'")})
            return rec.report()

        one, two = build(), build()
        assert one["digest"].startswith("sha256:")
        assert one == two
        assert one["stage_totals"] == {"limits": 2}

    def test_reset_keeps_mode_and_capacity(self, clean_ledger):
        rec = clean_ledger
        rec.configure(mode="sampled", capacity=7)
        pod = make_pod("p", "u-keep")
        rec.configure(mode="on")
        rec.commit_solve([pod], {pod: ValueError("x")})
        rec.configure(mode="sampled")
        rec.reset()
        assert rec.mode == "sampled" and rec.capacity == 7
        assert rec.report()["ring_depth"] == 0

    def test_fused_declines_fold_in(self, clean_ledger):
        rec = clean_ledger
        rec.configure(mode="on")
        rec.note_fused_decline("topology")
        rec.note_fused_decline("topology")
        rec.note_fused_decline("reserved-offerings")
        snap = rec.snapshot()
        assert snap["fused_declines"] == {"reserved-offerings": 1, "topology": 2}
        assert rec.report()["fused_declines"]["topology"] == 2


class TestDropRequirement:
    def test_drops_node_selector_key(self):
        pod = unschedulable_pod(node_selector={"topology.kubernetes.io/zone": "z9"})
        assert explmod.drop_requirement(pod, "topology.kubernetes.io/zone")
        assert pod.spec.node_selector == {}

    def test_drops_topology_spread_on_key(self):
        from karpenter_tpu.apis.core import LabelSelector, TopologySpreadConstraint

        pod = unschedulable_pod()
        pod.spec.topology_spread_constraints = [
            TopologySpreadConstraint(
                max_skew=1,
                topology_key="topology.kubernetes.io/zone",
                when_unsatisfiable="DoNotSchedule",
                label_selector=LabelSelector(match_labels={"a": "b"}),
            )
        ]
        assert explmod.drop_requirement(pod, "topology.kubernetes.io/zone")
        assert pod.spec.topology_spread_constraints == []

    def test_no_op_returns_false(self):
        pod = unschedulable_pod(node_selector={"kubernetes.io/arch": "arm64"})
        assert not explmod.drop_requirement(pod, "some.other/key")
        assert pod.spec.node_selector == {"kubernetes.io/arch": "arm64"}


class TestEventEnrichment:
    """Satellite 1: unschedulable-pod Warning events embed the top
    eliminating reasons when (and only when) the ledger is capturing."""

    def _record(self, rec_events):
        from karpenter_tpu.scheduler.scheduler import Results

        pod = make_pod("pending-0", "u-ev")
        results = Results(
            new_node_claims=[],
            existing_nodes=[],
            pod_errors={pod: ValueError("exceed limits for nodepool 'workers'")},
        )
        results.record(rec_events, cluster=None)
        return pod

    class _Sink:
        def __init__(self):
            self.events = []

        def publish(self, *events):
            self.events.extend(events)

    def test_default_event_stream_is_unchanged(self, clean_ledger):
        sink = self._Sink()
        self._record(sink)
        (event,) = sink.events
        assert event.reason == "FailedScheduling"
        assert "top eliminations" not in event.message

    def test_enabled_ledger_enriches_with_top_reasons(self, clean_ledger):
        rec = clean_ledger
        rec.configure(mode="on")
        pod = make_pod("pending-0", "u-ev")
        rec.note_funnel(
            "u-ev",
            [{"nodepool": "workers", "stages": ["limits"], "error": "e"}],
        )
        rec.commit_solve(
            [pod], {pod: ValueError("exceed limits for nodepool 'workers'")}
        )
        sink = self._Sink()
        self._record(sink)
        (event,) = sink.events
        assert "top eliminations: limits(workers)" in event.message

    def test_enabled_but_unseen_pod_stays_plain(self, clean_ledger):
        clean_ledger.configure(mode="on")
        sink = self._Sink()
        self._record(sink)  # nothing committed for this uid
        (event,) = sink.events
        assert "top eliminations" not in event.message


class TestCoalescerBarrier:
    """The solve-completion barrier lives in the solverd coalescer: commits
    on provisioning solves, staging-only on simulations, and — satellite 6 —
    explain-off adds zero work to the sampled solve span."""

    class _Results:
        def __init__(self, pod_errors):
            self.pod_errors = pod_errors

    class _Scheduler:
        def __init__(self, results):
            self._results = results

        def solve(self, pods, timeout=None):
            return self._results

    class _Request:
        def __init__(self, scheduler, pods, kind="solve"):
            self.scheduler = scheduler
            self.pods = pods
            self.kind = kind
            self.timeout = 1.0
            self.trace_context = None

    class _Entry:
        def __init__(self, request):
            self.request = request
            self.result = None
            self.error = None

    def _execute(self, kind, fail):
        from karpenter_tpu.solverd.coalescer import Coalescer

        pod = make_pod("p", f"u-{kind}-{fail}")
        errors = {pod: ValueError("no instance type has enough resources")} if fail else {}
        entry = self._Entry(
            self._Request(self._Scheduler(self._Results(errors)), [pod], kind=kind)
        )
        Coalescer().execute([entry])
        assert entry.error is None
        return pod

    def test_solve_kind_commits_failed_pods(self, clean_ledger):
        clean_ledger.configure(mode="on")
        pod = self._execute("solve", fail=True)
        entry = clean_ledger.entry(pod.metadata.uid)
        assert entry["stages"] == ["resources"]

    def test_simulate_kind_never_commits(self, clean_ledger):
        clean_ledger.configure(mode="on")
        self._execute("simulate", fail=True)
        assert clean_ledger.snapshot()["ring_depth"] == 0

    def test_explain_off_skips_span_metering(self, clean_ledger, monkeypatch):
        calls = []
        orig = explmod.ExplainRecorder.counters

        def counting(self):
            calls.append(1)
            return orig(self)

        monkeypatch.setattr(explmod.ExplainRecorder, "counters", counting)
        from karpenter_tpu import tracing

        tracing.configure(sample_rate=1.0)
        try:
            self._execute("solve", fail=False)
            assert not calls, "explain off must not meter the solve span"
            clean_ledger.configure(mode="on")
            self._execute("solve", fail=False)
            assert calls, "explain on must meter the sampled solve span"
        finally:
            tracing.configure(sample_rate=1.0)


class TestOperatorExplain:
    """/debug/explain end to end through a real Operator: triage, ?pod=
    drill-down naming the exact eliminating stage, and the what-if probe
    re-solving a relaxed copy through the solverd coalescer."""

    def _operator(self):
        from karpenter_tpu.cloudprovider.kwok.provider import KwokCloudProvider
        from karpenter_tpu.operator.operator import Operator
        from karpenter_tpu.operator.options import Options
        from karpenter_tpu.runtime.store import Store

        clock = FakeClock()
        store = Store(clock=clock)
        op = Operator(
            store,
            KwokCloudProvider(store, clock),
            clock=clock,
            options=Options(explain="on"),
        )
        return clock, store, op

    def test_snapshot_names_exact_stage_and_probe_flips_it(self, clean_ledger):
        clock, store, op = self._operator()
        store.create(nodepool("workers"))
        # deliberately unsatisfiable: no kwok offering serves this zone
        store.create(
            unschedulable_pod(
                name="lost-zone",
                node_selector={"topology.kubernetes.io/zone": "kwok-zone-9"},
            )
        )
        for _ in range(3):
            clock.step(2.0)
            op.run_once()
        snap = op.explain_snapshot()
        assert snap["mode"] == "on" and snap["ring_depth"] >= 1
        drill = op.explain_snapshot(pod="lost-zone")
        assert drill["pod"] == "lost-zone"
        assert drill["stages"], "the eliminating stage must be named"
        assert set(drill["stages"]) <= {"requirements", "offerings"}
        assert drill["funnel"] and drill["funnel"][0]["nodepool"] == "workers"
        # the counterfactual: dropping the zone pin makes it schedulable
        probed = op.explain_snapshot(
            pod="lost-zone", what_if="drop:topology.kubernetes.io/zone"
        )
        assert probed["what_if"]["drop"] == "topology.kubernetes.io/zone"
        assert probed["what_if"]["schedulable"] is True
        assert probed["what_if"]["placement"]
        # probing never committed a simulate entry for the relaxed twin
        assert op.explain_snapshot()["ring_depth"] == snap["ring_depth"]

    def test_probe_on_irrelevant_key_is_a_no_op_answer(self, clean_ledger):
        clock, store, op = self._operator()
        store.create(nodepool("workers"))
        store.create(
            unschedulable_pod(
                name="lost-zone",
                node_selector={"topology.kubernetes.io/zone": "kwok-zone-9"},
            )
        )
        for _ in range(3):
            clock.step(2.0)
            op.run_once()
        probed = op.explain_snapshot(pod="lost-zone", what_if="drop:not.a/key")
        assert "no requirement" in probed["what_if"]["error"]

    def test_unknown_pod_404s(self, clean_ledger):
        clock, store, op = self._operator()
        assert op.explain_snapshot(pod="never-seen") is None

    def test_disabled_ledger_404s(self, clean_ledger):
        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
        from karpenter_tpu.operator.operator import Operator
        from karpenter_tpu.runtime.store import Store

        clock = FakeClock()
        op = Operator(Store(clock=clock), FakeCloudProvider(), clock=clock)
        assert op.explain_snapshot() is None
