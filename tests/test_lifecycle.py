"""NodeClaim lifecycle, termination, drift detection, GC, nodepool
controllers. Mirrors the reference's per-controller suites."""

import pytest

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import (
    Condition,
    Node,
    ObjectMeta,
    OwnerReference,
    Taint,
    VolumeAttachment,
)
from karpenter_tpu.apis.nodeclaim import (
    CONDITION_CONSOLIDATABLE,
    CONDITION_DRAINED,
    CONDITION_DRIFTED,
    CONDITION_INITIALIZED,
    CONDITION_LAUNCHED,
    CONDITION_REGISTERED,
    NodeClaim,
)
from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
from karpenter_tpu.cloudprovider.types import InsufficientCapacityError
from karpenter_tpu.controllers.node.termination import (
    EvictionQueue,
    TerminationController,
    Terminator,
)
from karpenter_tpu.controllers.nodeclaim.disruption import DisruptionController
from karpenter_tpu.controllers.nodeclaim.gc import (
    ConsistencyController,
    ExpirationController,
    GarbageCollectionController,
)
from karpenter_tpu.controllers.nodeclaim.lifecycle import (
    LAUNCH_TTL,
    REGISTRATION_TTL,
    LifecycleController,
)
from karpenter_tpu.controllers.nodepool_controllers import (
    CounterController,
    HashController,
    ReadinessController,
    ValidationController,
)
from karpenter_tpu.events.recorder import Recorder
from karpenter_tpu.runtime.store import Store
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.state.informer import StateInformer
from karpenter_tpu.utils.clock import FakeClock

from helpers import bind_pod, node_claim_pair, nodepool, unschedulable_pod


@pytest.fixture
def env():
    clock = FakeClock()
    store = Store(clock=clock)
    provider = FakeCloudProvider()
    recorder = Recorder(clock=clock)
    return clock, store, provider, recorder


def make_claim(store, pool="default"):
    claim = NodeClaim(
        metadata=ObjectMeta(
            name="claim-1",
            labels={wk.NODEPOOL_LABEL_KEY: pool},
        )
    )
    claim.spec.requirements = [
        {"key": wk.LABEL_OS, "operator": "In", "values": ["linux"]},
        {"key": wk.LABEL_ARCH, "operator": "In", "values": ["amd64"]},
    ]
    return store.create(claim)


def fabricate_node(store, claim, ready=True):
    """What the kwok controller would do after launch."""
    node = Node(
        metadata=ObjectMeta(
            name=f"node-for-{claim.metadata.name}",
            labels={wk.NODEPOOL_LABEL_KEY: claim.metadata.labels[wk.NODEPOOL_LABEL_KEY]},
        )
    )
    node.spec.provider_id = claim.status.provider_id
    node.spec.taints = [
        Taint(key=wk.UNREGISTERED_TAINT_KEY, effect="NoExecute")
    ]
    node.status.capacity = dict(claim.status.capacity)
    node.status.allocatable = dict(claim.status.allocatable)
    node.status.conditions.append(
        Condition(type="Ready", status="True" if ready else "False")
    )
    return store.create(node)


class TestLifecycle:
    def test_launch_sets_condition_and_provider_id(self, env):
        clock, store, provider, recorder = env
        ctrl = LifecycleController(store, provider, recorder, clock)
        claim = make_claim(store)
        ctrl.reconcile(claim)
        assert claim.condition_is_true(CONDITION_LAUNCHED)
        assert claim.status.provider_id.startswith("fake://")
        assert claim.metadata.labels[wk.LABEL_INSTANCE_TYPE]
        assert wk.TERMINATION_FINALIZER in claim.metadata.finalizers

    def test_insufficient_capacity_deletes_claim(self, env):
        clock, store, provider, recorder = env
        provider.next_create_err = InsufficientCapacityError("no capacity")
        ctrl = LifecycleController(store, provider, recorder, clock)
        claim = make_claim(store)
        ctrl.reconcile(claim)
        assert store.try_get("NodeClaim", "claim-1") is None

    def test_registration_syncs_node(self, env):
        clock, store, provider, recorder = env
        ctrl = LifecycleController(store, provider, recorder, clock)
        claim = make_claim(store)
        claim.spec.taints = [Taint(key="team", value="a")]
        ctrl.reconcile(claim)
        assert not claim.condition_is_true(CONDITION_REGISTERED)
        node = fabricate_node(store, claim)
        ctrl.reconcile(claim)
        assert claim.condition_is_true(CONDITION_REGISTERED)
        node = store.get("Node", node.metadata.name)
        assert node.metadata.labels[wk.NODE_REGISTERED_LABEL_KEY] == "true"
        assert any(t.key == "team" for t in node.spec.taints)
        assert not any(t.key == wk.UNREGISTERED_TAINT_KEY for t in node.spec.taints)
        assert wk.TERMINATION_FINALIZER in node.metadata.finalizers

    def test_initialization_waits_for_ready_and_taints(self, env):
        clock, store, provider, recorder = env
        ctrl = LifecycleController(store, provider, recorder, clock)
        claim = make_claim(store)
        claim.spec.startup_taints = [Taint(key="startup", value="x")]
        ctrl.reconcile(claim)
        node = fabricate_node(store, claim, ready=False)
        ctrl.reconcile(claim)
        assert not claim.condition_is_true(CONDITION_INITIALIZED)
        node = store.get("Node", node.metadata.name)
        node.status.conditions = [Condition(type="Ready", status="True")]
        store.update(node)
        ctrl.reconcile(claim)
        # startup taint (synced by registration) still present
        assert not claim.condition_is_true(CONDITION_INITIALIZED)
        node = store.get("Node", node.metadata.name)
        node.spec.taints = [t for t in node.spec.taints if t.key != "startup"]
        store.update(node)
        ctrl.reconcile(claim)
        assert claim.condition_is_true(CONDITION_INITIALIZED)
        node = store.get("Node", node.metadata.name)
        assert node.metadata.labels[wk.NODE_INITIALIZED_LABEL_KEY] == "true"

    def test_liveness_kills_unregistered_claim(self, env):
        clock, store, provider, recorder = env
        ctrl = LifecycleController(store, provider, recorder, clock)
        pool = store.create(nodepool("default"))
        claim = make_claim(store)
        claim.metadata.creation_timestamp = clock.now()
        ctrl.reconcile(claim)  # launched, no node appears
        clock.step(REGISTRATION_TTL + 1)
        ctrl.reconcile(claim)
        assert store.try_get("NodeClaim", "claim-1") is None
        pool = store.get("NodePool", "default")
        cond = pool.get_condition("NodeRegistrationHealthy")
        assert cond is not None and cond.status == "False"

    def test_finalize_deletes_node_then_instance(self, env):
        clock, store, provider, recorder = env
        ctrl = LifecycleController(store, provider, recorder, clock)
        claim = make_claim(store)
        ctrl.reconcile(claim)
        node = fabricate_node(store, claim)
        ctrl.reconcile(claim)
        # node has no finalizer-blocking pipeline in this test: strip it
        node = store.get("Node", node.metadata.name)
        node.metadata.finalizers = []
        store.update(node)
        store.delete(claim)
        ctrl.reconcile(store.get("NodeClaim", "claim-1"))
        # node deleted and instance delete issued in the same pass
        assert store.try_get("Node", node.metadata.name) is None
        assert provider.delete_calls
        # instance now gone -> NotFound -> finalizer removed
        ctrl.reconcile(store.get("NodeClaim", "claim-1"))
        assert store.try_get("NodeClaim", "claim-1") is None


class TestTermination:
    def build(self, env):
        clock, store, provider, recorder = env
        queue = EvictionQueue(store, recorder, clock)
        terminator = Terminator(clock, store, queue, recorder)
        ctrl = TerminationController(store, provider, terminator, recorder, clock)
        return queue, terminator, ctrl

    def test_drain_then_terminate(self, env):
        clock, store, provider, recorder = env
        queue, terminator, ctrl = self.build(env)
        node, claim = node_claim_pair("term-1")
        store.create(claim)
        node.metadata.finalizers.append(wk.TERMINATION_FINALIZER)
        store.create(node)
        provider.created[claim.status.provider_id] = claim
        pod = bind_pod(unschedulable_pod(), node)
        store.create(pod)
        store.delete(node)
        node = store.get("Node", "term-1")
        ctrl.reconcile(node)
        # draining: pod queued for eviction, taint applied
        assert any(t.key == wk.DISRUPTED_TAINT_KEY for t in node.spec.taints)
        claim = store.get("NodeClaim", "term-1-claim")
        cond = claim.get_condition(CONDITION_DRAINED)
        assert cond is not None and cond.status == "False"
        queue.reconcile()
        assert store.try_get("Pod", pod.metadata.name) is None
        ctrl.reconcile(store.get("Node", "term-1"))
        claim = store.get("NodeClaim", "term-1-claim")
        assert claim.condition_is_true(CONDITION_DRAINED)
        # instance deleted; node finalizer removed after NotFound
        assert provider.delete_calls
        ctrl.reconcile(store.get("Node", "term-1"))
        assert store.try_get("Node", "term-1") is None

    def test_volume_attachments_block(self, env):
        clock, store, provider, recorder = env
        queue, terminator, ctrl = self.build(env)
        node, claim = node_claim_pair("term-2")
        store.create(claim)
        node.metadata.finalizers.append(wk.TERMINATION_FINALIZER)
        store.create(node)
        provider.created[claim.status.provider_id] = claim
        store.create(
            VolumeAttachment(
                metadata=ObjectMeta(name="va-1"), node_name="term-2", pv_name="pv-1"
            )
        )
        # a nameless-PV attachment is NOT waited on (the reference rejects
        # nil PersistentVolumeName, controller.go:335-338)
        store.create(
            VolumeAttachment(metadata=ObjectMeta(name="va-inline"), node_name="term-2")
        )
        store.delete(node)
        ctrl.reconcile(store.get("Node", "term-2"))
        assert store.try_get("Node", "term-2") is not None  # blocked by va-1
        store.delete(store.get("VolumeAttachment", "va-1"))
        ctrl.reconcile(store.get("Node", "term-2"))
        ctrl.reconcile(store.get("Node", "term-2"))
        assert store.try_get("Node", "term-2") is None

    def test_volume_attachments_of_undrainable_pods_do_not_block(self, env):
        """termination suite 'should only wait for volume attachments
        associated with drainable pods': a volume used only by an
        undrainable pod (here: node-owned/static) will never detach —
        waiting on it would deadlock the finalizer."""
        from karpenter_tpu.apis.core import (
            OwnerReference,
            PersistentVolumeClaim,
            Volume,
        )

        clock, store, provider, recorder = env
        queue, terminator, ctrl = self.build(env)
        node, claim = node_claim_pair("term-3")
        store.create(claim)
        node.metadata.finalizers.append(wk.TERMINATION_FINALIZER)
        store.create(node)
        provider.created[claim.status.provider_id] = claim
        store.create(
            PersistentVolumeClaim(
                metadata=ObjectMeta(name="static-pvc"), volume_name="pv-static"
            )
        )
        static_pod = bind_pod(unschedulable_pod(name="static-1"), node)
        static_pod.metadata.owner_references = [
            OwnerReference(kind="Node", name="term-3", uid="u1", controller=True)
        ]
        static_pod.spec.volumes = [
            Volume(name="data", persistent_volume_claim="static-pvc")
        ]
        store.create(static_pod)
        store.create(
            VolumeAttachment(
                metadata=ObjectMeta(name="va-static"),
                node_name="term-3",
                pv_name="pv-static",
            )
        )
        store.delete(node)
        for _ in range(3):
            live = store.try_get("Node", "term-3")
            if live is None:
                break
            ctrl.reconcile(live)
        assert store.try_get("Node", "term-3") is None, (
            "static pod's attachment must not block termination"
        )

    def test_terminating_node_excluded_from_load_balancers(self, env):
        """termination suite:197 — the exclude-from-external-load-balancers
        label is applied with the disruption taint, BEFORE draining, so
        connections drain ahead of instance termination."""
        clock, store, provider, recorder = env
        queue, terminator, ctrl = self.build(env)
        node, claim = node_claim_pair("term-lb")
        store.create(claim)
        node.metadata.finalizers.append(wk.TERMINATION_FINALIZER)
        store.create(node)
        provider.created[claim.status.provider_id] = claim
        # a blocking pod keeps the node alive long enough to observe labels
        blocked = bind_pod(unschedulable_pod(name="lb-pod"), node)
        store.create(blocked)
        from karpenter_tpu.apis.core import (
            LabelSelector,
            PodDisruptionBudget,
            PodDisruptionBudgetSpec,
        )

        store.create(
            PodDisruptionBudget(
                metadata=ObjectMeta(name="block-all"),
                spec=PodDisruptionBudgetSpec(
                    selector=LabelSelector(match_labels={}), max_unavailable=0
                ),
            )
        )
        store.delete(node)
        ctrl.reconcile(store.get("Node", "term-lb"))
        live = store.get("Node", "term-lb")
        assert (
            live.metadata.labels[
                "node.kubernetes.io/exclude-from-external-load-balancers"
            ]
            == "karpenter"
        )

    def test_drained_total_and_lifetime_metrics(self, env):
        """termination suite metric specs: drained counter increments once
        per node (condition-transition guarded), and node lifetime lands in
        the histogram at finalize."""
        from karpenter_tpu.controllers.node.termination import (
            _NODE_LIFETIME,
            _NODES_DRAINED,
        )

        clock, store, provider, recorder = env
        queue, terminator, ctrl = self.build(env)
        node, claim = node_claim_pair("term-m")
        node.metadata.creation_timestamp = clock.now()
        store.create(claim)
        node.metadata.finalizers.append(wk.TERMINATION_FINALIZER)
        store.create(node)
        provider.created[claim.status.provider_id] = claim
        clock.step(500.0)  # the node lives a while
        pool_labels = {"nodepool": node.metadata.labels.get(wk.NODEPOOL_LABEL_KEY, "")}
        drained0 = _NODES_DRAINED.value(pool_labels)
        life0 = _NODE_LIFETIME.count(pool_labels)
        store.delete(node)
        for _ in range(4):
            live = store.try_get("Node", "term-m")
            if live is None:
                break
            ctrl.reconcile(live)
        assert store.try_get("Node", "term-m") is None
        assert _NODES_DRAINED.value(pool_labels) == drained0 + 1
        assert _NODE_LIFETIME.count(pool_labels) == life0 + 1
        assert _NODE_LIFETIME.sum(pool_labels) >= 500.0

    def test_deletes_node_without_nodeclaim(self, env):
        """termination suite:123 — node-only termination (no paired claim)
        walks the same finalizer pipeline."""
        clock, store, provider, recorder = env
        queue, terminator, ctrl = self.build(env)
        node, _ = node_claim_pair("solo-1")
        node.metadata.finalizers.append(wk.TERMINATION_FINALIZER)
        store.create(node)
        store.delete(node)
        ctrl.reconcile(store.get("Node", "solo-1"))
        assert store.try_get("Node", "solo-1") is None

    def test_instance_gone_skips_drain_when_not_ready(self, env):
        """termination suite:593 — a NotReady node whose cloud instance has
        vanished is deleted immediately, undrained (kubelet can't run pods)."""
        clock, store, provider, recorder = env
        queue, terminator, ctrl = self.build(env)
        node, claim = node_claim_pair("gone-1")
        store.create(claim)
        node.metadata.finalizers.append(wk.TERMINATION_FINALIZER)
        node.status.conditions = [Condition(type="Ready", status="False")]
        store.create(node)
        pod = bind_pod(unschedulable_pod(), node)
        store.create(pod)
        # provider.created intentionally empty: the instance is gone
        store.delete(node)
        ctrl.reconcile(store.get("Node", "gone-1"))
        assert store.try_get("Node", "gone-1") is None
        # the pod was never evicted — no graceful drain happened
        assert store.try_get("Pod", pod.metadata.name) is not None

    def test_instance_gone_still_drains_when_ready(self, env):
        """termination suite:626 — a READY node drains normally even if the
        provider says the instance is gone (the kubelet is demonstrably up)."""
        clock, store, provider, recorder = env
        queue, terminator, ctrl = self.build(env)
        node, claim = node_claim_pair("ready-1")
        store.create(claim)
        node.metadata.finalizers.append(wk.TERMINATION_FINALIZER)
        store.create(node)
        pod = bind_pod(unschedulable_pod(), node)
        store.create(pod)
        store.delete(node)
        ctrl.reconcile(store.get("Node", "ready-1"))
        assert store.try_get("Node", "ready-1") is not None  # drain pending
        assert queue.has(pod)

    def test_disrupted_taint_tolerating_pods_not_evicted(self, env):
        """termination suite:220,250 — pods tolerating the disruption taint
        (Equal or Exists) ride the node down without eviction, and don't
        block its deletion."""
        from karpenter_tpu.apis.core import Toleration

        clock, store, provider, recorder = env
        queue, terminator, ctrl = self.build(env)
        node, claim = node_claim_pair("tol-1")
        store.create(claim)
        node.metadata.finalizers.append(wk.TERMINATION_FINALIZER)
        store.create(node)
        provider.created[claim.status.provider_id] = claim
        equal = bind_pod(
            unschedulable_pod(
                name="tol-equal",
                tolerations=[
                    Toleration(
                        key=wk.DISRUPTED_TAINT_KEY,
                        operator="Equal",
                        value="",
                        effect="NoSchedule",
                    )
                ],
            ),
            node,
        )
        exists = bind_pod(
            unschedulable_pod(
                name="tol-exists",
                tolerations=[
                    Toleration(key=wk.DISRUPTED_TAINT_KEY, operator="Exists")
                ],
            ),
            node,
        )
        store.create(equal)
        store.create(exists)
        store.delete(node)
        ctrl.reconcile(store.get("Node", "tol-1"))
        assert not queue.has(equal) and not queue.has(exists)
        claim = store.get("NodeClaim", "tol-1-claim")
        assert claim.condition_is_true(CONDITION_DRAINED)

    def test_static_pods_not_evicted(self, env):
        """termination suite:509 — node-owned (static) pods are never posted
        to the eviction API and don't block the drain."""
        clock, store, provider, recorder = env
        queue, terminator, ctrl = self.build(env)
        node, claim = node_claim_pair("static-1")
        store.create(claim)
        node.metadata.finalizers.append(wk.TERMINATION_FINALIZER)
        store.create(node)
        provider.created[claim.status.provider_id] = claim
        static = bind_pod(unschedulable_pod(name="static-pod"), node)
        static.metadata.owner_references.append(
            OwnerReference(kind="Node", name="static-1", uid="node-uid")
        )
        store.create(static)
        store.delete(node)
        ctrl.reconcile(store.get("Node", "static-1"))
        assert not queue.has(static)
        claim = store.get("NodeClaim", "static-1-claim")
        assert claim.condition_is_true(CONDITION_DRAINED)

    def test_ownerless_pods_evicted(self, env):
        """termination suite:309 — pods without an ownerRef still drain."""
        clock, store, provider, recorder = env
        queue, terminator, ctrl = self.build(env)
        node, claim = node_claim_pair("bare-1")
        store.create(claim)
        node.metadata.finalizers.append(wk.TERMINATION_FINALIZER)
        store.create(node)
        provider.created[claim.status.provider_id] = claim
        bare = bind_pod(unschedulable_pod(name="bare-pod"), node)
        assert not bare.metadata.owner_references
        store.create(bare)
        store.delete(node)
        ctrl.reconcile(store.get("Node", "bare-1"))
        assert queue.has(bare)
        queue.reconcile()
        assert store.try_get("Pod", "bare-pod") is None
        ctrl.reconcile(store.get("Node", "bare-1"))
        claim = store.get("NodeClaim", "bare-1-claim")
        assert claim.condition_is_true(CONDITION_DRAINED)

    def test_pdb_blocks_eviction(self, env):
        clock, store, provider, recorder = env
        from karpenter_tpu.apis.core import (
            LabelSelector,
            PodDisruptionBudget,
            PodDisruptionBudgetSpec,
            PodDisruptionBudgetStatus,
        )
        queue, terminator, ctrl = self.build(env)
        node, claim = node_claim_pair("term-3")
        store.create(claim)
        store.create(node)
        pod = bind_pod(unschedulable_pod(labels={"app": "db"}), node)
        store.create(pod)
        store.create(
            PodDisruptionBudget(
                metadata=ObjectMeta(name="pdb"),
                spec=PodDisruptionBudgetSpec(
                    selector=LabelSelector(match_labels={"app": "db"})
                ),
                status=PodDisruptionBudgetStatus(disruptions_allowed=0),
            )
        )
        queue.add(pod)
        queue.reconcile()
        assert store.try_get("Pod", pod.metadata.name) is not None  # blocked


class TestDriftDetection:
    def test_nodepool_hash_drift(self, env):
        clock, store, provider, recorder = env
        pool = store.create(nodepool("default"))
        HashController(store).reconcile(pool)
        node, claim = node_claim_pair("d-1")
        claim.set_condition(CONDITION_LAUNCHED, "True")
        claim.metadata.annotations.update(pool.metadata.annotations)
        store.create(claim)
        ctrl = DisruptionController(store, provider, clock)
        ctrl.reconcile(claim)
        assert not claim.condition_is_true(CONDITION_DRIFTED)
        # change a static field -> hash changes -> drifted
        pool.spec.template.spec.taints = [Taint(key="new", value="x")]
        HashController(store).reconcile(pool)
        ctrl.reconcile(claim)
        assert claim.condition_is_true(CONDITION_DRIFTED)
        assert claim.get_condition(CONDITION_DRIFTED).reason == "NodePoolDrifted"

    def test_requirements_drift(self, env):
        clock, store, provider, recorder = env
        pool = store.create(
            nodepool("default", requirements=[
                {"key": wk.LABEL_ARCH, "operator": "In", "values": ["arm64"]}
            ])
        )
        node, claim = node_claim_pair("d-2")  # labels arch=amd64
        claim.set_condition(CONDITION_LAUNCHED, "True")
        store.create(claim)
        ctrl = DisruptionController(store, provider, clock)
        ctrl.reconcile(claim)
        assert claim.get_condition(CONDITION_DRIFTED).reason == "RequirementsDrifted"

    def test_provider_drift(self, env):
        clock, store, provider, recorder = env
        store.create(nodepool("default"))
        node, claim = node_claim_pair("d-3")
        claim.set_condition(CONDITION_LAUNCHED, "True")
        store.create(claim)
        provider.drifted = "CloudDriftReason"
        ctrl = DisruptionController(store, provider, clock)
        ctrl.reconcile(claim)
        assert claim.get_condition(CONDITION_DRIFTED).reason == "CloudDriftReason"

    def test_consolidatable_after_quiet_period(self, env):
        clock, store, provider, recorder = env
        pool = nodepool("default")
        pool.spec.disruption.consolidate_after = 30.0
        store.create(pool)
        node, claim = node_claim_pair("d-4", consolidatable=False)
        claim.get_condition(CONDITION_INITIALIZED).last_transition_time = clock.now()
        store.create(claim)
        ctrl = DisruptionController(store, provider, clock)
        ctrl.reconcile(claim)
        assert not claim.condition_is_true(CONDITION_CONSOLIDATABLE)
        clock.step(31.0)
        ctrl.reconcile(claim)
        assert claim.condition_is_true(CONDITION_CONSOLIDATABLE)
        # new pod event resets the window
        claim.status.last_pod_event_time = clock.now()
        ctrl.reconcile(claim)
        assert not claim.condition_is_true(CONDITION_CONSOLIDATABLE)


class TestGCAndExpiration:
    def test_expiration(self, env):
        clock, store, provider, recorder = env
        node, claim = node_claim_pair("x-1")
        claim.spec.expire_after = 100.0
        claim.metadata.creation_timestamp = clock.now()
        store.create(claim)
        ctrl = ExpirationController(store, clock, recorder)
        ctrl.reconcile(claim)
        assert store.try_get("NodeClaim", "x-1-claim") is not None
        clock.step(101.0)
        ctrl.reconcile(claim)
        assert store.try_get("NodeClaim", "x-1-claim") is None

    def test_expiration_metric_and_no_double_expire(self, env):
        """expiration suite — the disrupted counter fires with
        reason=expired, and an already-deleting claim is not expired again."""
        from karpenter_tpu.controllers.nodeclaim.gc import _EXPIRED_TOTAL

        clock, store, provider, recorder = env
        node, claim = node_claim_pair("exp-m")
        claim.spec.expire_after = 100.0
        claim.metadata.creation_timestamp = clock.now()
        claim.metadata.finalizers.append(wk.TERMINATION_FINALIZER)
        store.create(claim)
        ctrl = ExpirationController(store, clock, recorder)
        labels = {
            "reason": "expired",
            "nodepool": claim.metadata.labels.get(wk.NODEPOOL_LABEL_KEY, ""),
            "capacity_type": claim.metadata.labels.get(wk.CAPACITY_TYPE_LABEL_KEY, ""),
        }
        before = _EXPIRED_TOTAL.value(labels)
        clock.step(101.0)
        ctrl.reconcile(claim)
        assert _EXPIRED_TOTAL.value(labels) == before + 1
        # the claim is Terminating (finalizer); a second pass must not
        # expire it again ('shouldn't expire the same NodeClaim multiple
        # times')
        live = store.get("NodeClaim", "exp-m-claim")
        assert live.metadata.deletion_timestamp is not None
        ctrl.reconcile(live)
        assert _EXPIRED_TOTAL.value(labels) == before + 1

    def test_expiration_disabled_when_unset(self, env):
        clock, store, provider, recorder = env
        node, claim = node_claim_pair("exp-off")
        claim.spec.expire_after = None
        claim.metadata.creation_timestamp = clock.now()
        store.create(claim)
        ctrl = ExpirationController(store, clock, recorder)
        clock.step(1e9)
        ctrl.reconcile(claim)
        assert store.try_get("NodeClaim", "exp-off-claim") is not None


class TestPodEvents:
    """podevents suite — lastPodEvent stamping with the 10s dedupe window
    (podevents/controller.go:54-120)."""

    def _env(self, env):
        from karpenter_tpu.controllers.nodeclaim.gc import PodEventsController

        clock, store, provider, recorder = env
        return clock, store, PodEventsController(store, clock)

    def _pair(self, store, clock, name="pe-1"):
        node, claim = node_claim_pair(name)
        store.create(claim)
        store.create(node)
        pod = bind_pod(unschedulable_pod(name=f"{name}-pod"), node)
        store.create(pod)
        return node, claim, pod

    def test_sets_last_pod_event(self, env):
        clock, store, ctrl = self._env(env)
        node, claim, pod = self._pair(store, clock)
        ctrl.on_pod_event(pod)
        assert store.get("NodeClaim", "pe-1-claim").status.last_pod_event_time == clock.now()

    def test_node_missing_is_noop(self, env):
        clock, store, ctrl = self._env(env)
        node, claim, pod = self._pair(store, clock)
        pod.spec.node_name = "no-such-node"
        ctrl.on_pod_event(pod)  # must not raise
        assert store.get("NodeClaim", "pe-1-claim").status.last_pod_event_time == 0.0

    def test_claim_missing_is_noop(self, env):
        clock, store, ctrl = self._env(env)
        node, claim, pod = self._pair(store, clock)
        claim.metadata.finalizers = []
        store.apply(claim)
        store.delete(claim)
        ctrl.on_pod_event(pod)  # must not raise

    def test_dedupes_within_window_then_updates(self, env):
        from karpenter_tpu.controllers.nodeclaim.gc import POD_EVENT_DEDUPE

        clock, store, ctrl = self._env(env)
        node, claim, pod = self._pair(store, clock)
        ctrl.on_pod_event(pod)
        first = store.get("NodeClaim", "pe-1-claim").status.last_pod_event_time
        clock.step(POD_EVENT_DEDUPE / 2)
        ctrl.on_pod_event(pod)
        assert store.get("NodeClaim", "pe-1-claim").status.last_pod_event_time == first
        clock.step(POD_EVENT_DEDUPE)
        ctrl.on_pod_event(pod)
        assert (
            store.get("NodeClaim", "pe-1-claim").status.last_pod_event_time
            == clock.now()
        )


class TestGCContinued:
    def test_gc_orphaned_instance(self, env):
        clock, store, provider, recorder = env
        orphan = NodeClaim(metadata=ObjectMeta(name="orphan"))
        orphan.status.provider_id = "fake://orphan-1"
        provider.created["fake://orphan-1"] = orphan
        ctrl = GarbageCollectionController(store, provider, clock)
        clock.step(121.0)
        ctrl.reconcile()
        assert provider.created == {}

    def test_gc_delete_failure_is_visible(self, env):
        """A provider delete failure on an orphan must log, count, and
        emit a Warning event — never a silent pass (the orphan is real
        cost leaking until the 2m requeue retries it)."""
        from karpenter_tpu.controllers.nodeclaim.gc import _GC_DELETE_ERRORS

        clock, store, provider, recorder = env
        orphan = NodeClaim(metadata=ObjectMeta(name="orphan"))
        orphan.status.provider_id = "fake://orphan-err"
        provider.created["fake://orphan-err"] = orphan
        provider.next_delete_err = RuntimeError("api throttled")
        ctrl = GarbageCollectionController(store, provider, clock, recorder=recorder)
        before = _GC_DELETE_ERRORS.value()
        clock.step(121.0)
        ctrl.reconcile()
        assert provider.created, "failed delete leaves the orphan for retry"
        assert _GC_DELETE_ERRORS.value() == before + 1
        assert recorder.calls("FailedGarbageCollection") == 1
        # next GC period retries and succeeds
        clock.step(121.0)
        ctrl.reconcile()
        assert provider.created == {}

    def test_gc_already_gone_is_not_an_error(self, env):
        """NodeClaimNotFoundError from delete means the instance vanished
        between list() and delete() — success, not cost leakage."""
        from karpenter_tpu.cloudprovider.types import NodeClaimNotFoundError
        from karpenter_tpu.controllers.nodeclaim.gc import _GC_DELETE_ERRORS

        clock, store, provider, recorder = env
        orphan = NodeClaim(metadata=ObjectMeta(name="orphan"))
        orphan.status.provider_id = "fake://orphan-gone"
        provider.created["fake://orphan-gone"] = orphan
        provider.next_delete_err = NodeClaimNotFoundError("already gone")
        ctrl = GarbageCollectionController(store, provider, clock, recorder=recorder)
        before = _GC_DELETE_ERRORS.value()
        clock.step(121.0)
        ctrl.reconcile()
        assert _GC_DELETE_ERRORS.value() == before
        assert recorder.calls("FailedGarbageCollection") == 0

    def test_gc_claim_without_instance(self, env):
        clock, store, provider, recorder = env
        node, claim = node_claim_pair("gone-1")
        store.create(claim)
        ctrl = GarbageCollectionController(store, provider, clock)
        clock.step(121.0)
        ctrl.reconcile()
        assert store.try_get("NodeClaim", "gone-1-claim") is None


class TestNodePoolControllers:
    def test_hash_and_readiness_and_validation(self, env):
        clock, store, provider, recorder = env
        pool = NodePoolFactory = nodepool("p-1")
        pool.status.conditions = []
        store.create(pool)
        HashController(store).reconcile(pool)
        assert wk.NODEPOOL_HASH_ANNOTATION_KEY in pool.metadata.annotations
        ValidationController(store, clock).reconcile(pool)
        ReadinessController(store, clock).reconcile(pool)
        assert pool.condition_is_true("Ready")

    def test_counter_tracks_node_lifecycle(self, env):
        """counter suite — the nodepool resource counter rises as nodes
        join, falls when one is deleted, and zeroes out when all are gone."""
        clock, store, provider, recorder = env
        cluster = Cluster(clock, store, provider)
        informer = StateInformer(store, cluster)
        pool = store.create(nodepool("cnt-1"))
        ctrl = CounterController(store, cluster)
        ctrl.reconcile(pool)
        assert pool.status.node_count == 0
        assert pool.status.resources.get("cpu", 0.0) == 0.0
        pairs = []
        for i in range(2):
            node, claim = node_claim_pair(f"cnt-{i}", pool="cnt-1")
            store.create(claim)
            store.create(node)
            pairs.append((node, claim))
        informer.flush()
        ctrl.reconcile(pool)
        assert pool.status.node_count == 2
        cpu_two = pool.status.resources["cpu"]
        assert cpu_two > 0.0
        # delete one pair
        node, claim = pairs[0]
        for obj in (claim, node):
            obj.metadata.finalizers = []
            store.apply(obj)
            store.delete(obj)
        informer.flush()
        ctrl.reconcile(pool)
        assert pool.status.node_count == 1
        assert pool.status.resources["cpu"] == cpu_two / 2
        node, claim = pairs[1]
        for obj in (claim, node):
            obj.metadata.finalizers = []
            store.apply(obj)
            store.delete(obj)
        informer.flush()
        ctrl.reconcile(pool)
        assert pool.status.node_count == 0
        assert pool.status.resources.get("cpu", 0.0) == 0.0

    def test_hash_static_vs_behavior_fields(self, env):
        """hash suite — static template fields change the hash; behavior
        fields (disruption settings, limits, weight) must not."""
        clock, store, provider, recorder = env
        pool = nodepool("h-1")
        store.create(pool)
        ctrl = HashController(store)
        ctrl.reconcile(pool)
        h0 = pool.metadata.annotations[wk.NODEPOOL_HASH_ANNOTATION_KEY]
        pool.spec.disruption.consolidate_after = 300.0
        pool.spec.weight = 50
        ctrl.reconcile(pool)
        assert pool.metadata.annotations[wk.NODEPOOL_HASH_ANNOTATION_KEY] == h0
        pool.spec.template.labels["team"] = "infra"
        ctrl.reconcile(pool)
        assert pool.metadata.annotations[wk.NODEPOOL_HASH_ANNOTATION_KEY] != h0

    def test_hash_version_migration_backfills_claims(self, env):
        """hash suite — a hash-version bump restamps the pool and backfills
        undrifted claims (so the algorithm change doesn't spuriously drift
        them), while an already-Drifted claim keeps its old hash."""
        from karpenter_tpu.apis.nodepool import NODEPOOL_HASH_VERSION

        clock, store, provider, recorder = env
        pool = nodepool("h-2")
        store.create(pool)
        ctrl = HashController(store)
        ctrl.reconcile(pool)
        # simulate objects stamped by an OLDER karpenter version
        pool.metadata.annotations[wk.NODEPOOL_HASH_VERSION_ANNOTATION_KEY] = "v0"
        pool.metadata.annotations[wk.NODEPOOL_HASH_ANNOTATION_KEY] = "old-algo-hash"
        _, fresh = node_claim_pair("h2-fresh", pool="h-2")
        fresh.metadata.annotations[wk.NODEPOOL_HASH_ANNOTATION_KEY] = "old-algo-hash"
        fresh.metadata.annotations[wk.NODEPOOL_HASH_VERSION_ANNOTATION_KEY] = "v0"
        store.create(fresh)
        _, drifted = node_claim_pair("h2-drifted", pool="h-2")
        drifted.metadata.annotations[wk.NODEPOOL_HASH_ANNOTATION_KEY] = "old-algo-hash"
        drifted.metadata.annotations[wk.NODEPOOL_HASH_VERSION_ANNOTATION_KEY] = "v0"
        drifted.set_condition("Drifted", "True", now=clock.now())
        store.create(drifted)
        ctrl.reconcile(pool)
        current = pool.static_hash()
        assert pool.metadata.annotations[wk.NODEPOOL_HASH_ANNOTATION_KEY] == current
        assert (
            pool.metadata.annotations[wk.NODEPOOL_HASH_VERSION_ANNOTATION_KEY]
            == NODEPOOL_HASH_VERSION
        )
        fresh = store.get("NodeClaim", "h2-fresh-claim")
        assert fresh.metadata.annotations[wk.NODEPOOL_HASH_ANNOTATION_KEY] == current
        assert (
            fresh.metadata.annotations[wk.NODEPOOL_HASH_VERSION_ANNOTATION_KEY]
            == NODEPOOL_HASH_VERSION
        )
        drifted = store.get("NodeClaim", "h2-drifted-claim")
        assert (
            drifted.metadata.annotations[wk.NODEPOOL_HASH_ANNOTATION_KEY]
            == "old-algo-hash"
        )
        assert (
            drifted.metadata.annotations[wk.NODEPOOL_HASH_VERSION_ANNOTATION_KEY]
            == NODEPOOL_HASH_VERSION
        )

    def test_validation_rejects_bad_budget(self, env):
        clock, store, provider, recorder = env
        from karpenter_tpu.apis.nodepool import Budget
        pool = nodepool("p-2")
        pool.status.conditions = []
        pool.spec.disruption.budgets = [Budget(nodes="5", schedule="0 0 * * *")]
        store.create(pool)
        ValidationController(store, clock).reconcile(pool)
        ReadinessController(store, clock).reconcile(pool)
        assert not pool.condition_is_true("Ready")

    def test_counter_aggregates(self, env):
        clock, store, provider, recorder = env
        cluster = Cluster(clock, store, provider)
        informer = StateInformer(store, cluster)
        pool = store.create(nodepool("p-3"))
        node, claim = node_claim_pair("c-1", pool="p-3")
        store.create(claim)
        store.create(node)
        informer.flush()
        CounterController(store, cluster).reconcile(pool)
        assert pool.status.node_count == 1
        assert pool.status.resources["cpu"] == 4.0


class TestConsistency:
    """NodeShape + taint invariants (consistency/controller.go:66-161,
    nodeshape.go:35-59)."""

    def _pair(self, store, clock, cpu_found="4", cpu_expected=4.0):
        from karpenter_tpu.apis.core import Node, NodeSpec, NodeStatus
        from karpenter_tpu.utils.resources import parse_resource_list

        claim = NodeClaim(metadata=ObjectMeta(name="claim-c1"))
        claim.status.provider_id = "fake://c1"
        claim.spec.resources.requests = {"cpu": 1.0, "memory": 1.0}
        claim.status.capacity = {"cpu": cpu_expected, "memory": float(2**30)}
        claim.status.allocatable = dict(claim.status.capacity)
        for cond in ("Launched", "Registered", "Initialized"):
            claim.set_condition(cond, "True")
        store.create(claim)
        node = Node(
            metadata=ObjectMeta(name="node-c1"),
            spec=NodeSpec(provider_id="fake://c1"),
            status=NodeStatus(
                capacity=parse_resource_list({"cpu": cpu_found, "memory": "1Gi"}),
                allocatable=parse_resource_list({"cpu": cpu_found, "memory": "1Gi"}),
            ),
        )
        store.create(node)
        return claim, node

    def test_consistent_pair_passes(self, env):
        clock, store, provider, recorder = env
        claim, _ = self._pair(store, clock)
        ConsistencyController(store, recorder, clock).reconcile(claim)
        cond = claim.get_condition("ConsistentStateFound")
        assert cond is not None and cond.status == "True"

    def test_undersized_node_flagged(self, env):
        clock, store, provider, recorder = env
        # node carries 2 cpu where the claim promised 4 → 50% < 90%
        claim, _ = self._pair(store, clock, cpu_found="2", cpu_expected=4.0)
        ConsistencyController(store, recorder, clock).reconcile(claim)
        cond = claim.get_condition("ConsistentStateFound")
        assert cond is not None and cond.status == "False"
        assert "% of expected" in cond.message

    def test_missing_required_taint_flagged(self, env):
        from karpenter_tpu.apis.core import Taint

        clock, store, provider, recorder = env
        claim, node = self._pair(store, clock)
        claim.spec.taints = [Taint(key="team", value="infra", effect="NoSchedule")]
        store.update(claim)
        ConsistencyController(store, recorder, clock).reconcile(claim)
        cond = claim.get_condition("ConsistentStateFound")
        assert cond is not None and cond.status == "False"
        assert "taint" in cond.message


class TestLiveness:
    """liveness_test.go — timeouts run from condition transitions."""

    def _controller(self, env):
        clock, store, provider, recorder = env
        return LifecycleController(store, provider, recorder, clock)

    def test_unlaunched_claim_deleted_after_launch_timeout(self, env):
        clock, store, provider, recorder = env
        store.create(nodepool("default"))
        claim = make_claim(store)
        claim.set_condition(CONDITION_LAUNCHED, "Unknown", now=clock.now())
        ctrl = self._controller(env)
        clock.step(299.0)
        ctrl._liveness(claim)
        assert store.try_get("NodeClaim", claim.metadata.name) is not None
        clock.step(2.0)
        ctrl._liveness(claim)
        assert store.try_get("NodeClaim", claim.metadata.name) is None

    def test_launch_timeout_runs_from_condition_transition(self, env):
        # liveness_test.go: "should use the status condition transition time
        # for launch timeout, not the creation timestamp" — a launch
        # reconcile that first runs late gets the full window from there
        clock, store, provider, recorder = env
        store.create(nodepool("default"))
        claim = make_claim(store)
        ctrl = self._controller(env)
        clock.step(200.0)  # the first (failing) launch attempt happens late
        claim.set_condition(CONDITION_LAUNCHED, "Unknown", now=clock.now())
        clock.step(200.0)  # 400s since creation, 200s since transition
        ctrl._liveness(claim)
        assert store.try_get("NodeClaim", claim.metadata.name) is not None
        clock.step(150.0)  # 350s since transition
        ctrl._liveness(claim)
        assert store.try_get("NodeClaim", claim.metadata.name) is None

    def test_repeated_failures_do_not_extend_the_window(self, env):
        # Unknown -> Unknown re-writes keep the original transition time
        clock, store, provider, recorder = env
        store.create(nodepool("default"))
        claim = make_claim(store)
        claim.set_condition(CONDITION_LAUNCHED, "Unknown", now=clock.now())
        ctrl = self._controller(env)
        clock.step(250.0)
        claim.set_condition(
            CONDITION_LAUNCHED, "Unknown", reason="LaunchFailed", now=clock.now()
        )
        clock.step(100.0)  # 350s since the FIRST transition
        ctrl._liveness(claim)
        assert store.try_get("NodeClaim", claim.metadata.name) is None

    def test_registered_claim_never_deleted(self, env):
        # liveness_test.go: "shouldn't delete the nodeClaim when the node has
        # registered past the registration timeout"
        clock, store, provider, recorder = env
        store.create(nodepool("default"))
        claim = make_claim(store)
        claim.set_condition(CONDITION_LAUNCHED, "True", now=clock.now())
        claim.set_condition(CONDITION_REGISTERED, "True", now=clock.now())
        ctrl = self._controller(env)
        clock.step(10_000.0)
        ctrl._liveness(claim)
        assert store.try_get("NodeClaim", claim.metadata.name) is not None

    def test_registration_timeout_marks_pool_unhealthy(self, env):
        clock, store, provider, recorder = env
        pool = store.create(nodepool("default"))
        claim = make_claim(store)
        claim.set_condition(CONDITION_LAUNCHED, "True", now=clock.now())
        claim.set_condition(CONDITION_REGISTERED, "Unknown", now=clock.now())
        ctrl = self._controller(env)
        clock.step(901.0)
        ctrl._liveness(claim)
        assert store.try_get("NodeClaim", claim.metadata.name) is None
        pool = store.get("NodePool", "default")
        cond = pool.get_condition("NodeRegistrationHealthy")
        assert cond is not None and cond.status == "False"
