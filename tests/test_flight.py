"""Flight recorder (observability/flight.py): ring capture/eviction, source
isolation, volatile scrubbing, bundle dump format + digest, cooldown,
/debug/slo + /debug/flight serving, flaky-cloud ×2 byte-identical breach
bundles, the karpenter_flight_* exposition round-trip, and the
device-memory gauge reset on engine rebuild (satellite fix)."""

import hashlib
import json
import os

import pytest

from karpenter_tpu.observability import flight
from karpenter_tpu.observability.flight import (
    FlightRecorder,
    VOLATILE_KEYS,
    canonical,
    scrub,
)
from karpenter_tpu.utils.clock import FakeClock

from test_metrics_exposition import parse_exposition


def make_recorder(**kw):
    kw.setdefault("clock", FakeClock())
    return FlightRecorder(**kw)


class TestScrub:
    def test_volatile_keys_dropped_recursively(self):
        frame = {
            "ok": 1,
            "last_batch_seconds": 0.5,
            "nested": {"compile_wall_s": 2.0, "keep": [{"device_memory": 1}]},
            "list": [{"joint_sweeps": 3, "x": "y"}],
        }
        assert scrub(frame) == {
            "ok": 1,
            "nested": {"keep": [{}]},
            "list": [{"x": "y"}],
        }

    def test_wall_clock_families_are_covered(self):
        assert {"last_batch_seconds", "compile_wall_s", "execute_wall_s",
                "device_memory", "live_array_bytes"} <= VOLATILE_KEYS


class TestRecorderCore:
    def test_record_snapshots_all_sources(self):
        rec = make_recorder()
        rec.register_source("a", lambda: {"n": 1})
        rec.register_source("b", lambda: {"m": 2})
        frame = rec.record("pass")
        assert frame["seq"] == 1
        assert frame["sources"] == {"a": {"n": 1}, "b": {"m": 2}}

    def test_ring_is_bounded_oldest_first(self):
        rec = make_recorder(capacity=3)
        rec.register_source("s", lambda: {})
        for _ in range(5):
            rec.record("pass")
        snap = rec.snapshot()
        assert snap["ring_depth"] == 3
        assert snap["frames_recorded"] == 5
        seqs = [f["seq"] for f in rec._ring]
        assert seqs == [3, 4, 5]

    def test_source_error_is_recorded_not_raised(self):
        rec = make_recorder()
        rec.register_source("bad", lambda: 1 / 0)
        rec.register_source("good", lambda: {"ok": True})
        frame = rec.record("pass")
        assert frame["sources"]["good"] == {"ok": True}
        assert "ZeroDivisionError" in frame["sources"]["bad"]["error"]

    def test_register_source_is_keyed_replace(self):
        rec = make_recorder()
        rec.register_source("s", lambda: {"v": 1})
        rec.register_source("s", lambda: {"v": 2})
        assert rec.record("pass")["sources"] == {"s": {"v": 2}}

    def test_reset_keeps_sources_and_config(self):
        rec = make_recorder(capacity=7, flight_dir="/tmp/nope")
        rec.register_source("s", lambda: {})
        rec.record("pass")
        rec.dump("x", cooldown=0.0)
        rec.reset()
        snap = rec.snapshot()
        assert snap["ring_depth"] == 0 and snap["frames_recorded"] == 0
        assert snap["bundles"] == []
        assert snap["capacity"] == 7
        assert snap["sources"] == ["s"]


class TestDump:
    def test_bundle_file_format_and_digest(self, tmp_path):
        clock = FakeClock()
        rec = make_recorder(clock=clock, flight_dir=str(tmp_path))
        rec.register_source("s", lambda: {"v": 1, "last_batch_seconds": 9.9})
        rec.record("pass")
        clock.step(1.0)
        rec.record("pass")
        bundle = rec.dump("slo:avail")
        assert bundle["name"] == "flight-0001-slo-avail"
        path = bundle["path"]
        assert os.path.exists(path)
        lines = open(path).read().splitlines()
        header = json.loads(lines[0])
        assert header["bundle"] == bundle["name"]
        assert header["frames"] == 2
        # digest in the header matches a recompute over the frame lines
        h = hashlib.sha256()
        for line in lines[1:]:
            h.update(line.encode())
            h.update(b"\n")
        assert header["sha256"] == "sha256:" + h.hexdigest()
        # volatile keys were scrubbed from the written frames
        for line in lines[1:]:
            assert "last_batch_seconds" not in line
            assert json.loads(line)["sources"]["s"] == {"v": 1}

    def test_cooldown_dedupes_per_trigger(self):
        clock = FakeClock()
        rec = make_recorder(clock=clock)
        rec.register_source("s", lambda: {})
        rec.record("pass")
        assert rec.dump("slo:x", cooldown=60.0) is not None
        assert rec.dump("slo:x", cooldown=60.0) is None  # inside the window
        assert rec.dump("slo:y", cooldown=60.0) is not None  # distinct trigger
        clock.step(61.0)
        assert rec.dump("slo:x", cooldown=60.0) is not None

    def test_unwritable_dir_keeps_in_memory_bundle(self, tmp_path):
        blocked = tmp_path / "blocked"
        blocked.mkdir()
        blocked.chmod(0o500)
        rec = make_recorder(flight_dir=str(blocked / "sub"))
        rec.register_source("s", lambda: {})
        rec.record("pass")
        try:
            bundle = rec.dump("crash")
        finally:
            blocked.chmod(0o700)
        if os.geteuid() == 0:
            pytest.skip("running as root: directory modes are advisory")
        assert bundle is not None
        assert bundle["path"] is None and "write_error" in bundle
        assert rec.snapshot(bundle=bundle["name"]) is not None

    def test_snapshot_listing_and_drilldown(self):
        rec = make_recorder()
        rec.register_source("s", lambda: {"v": 7})
        rec.record("pass")
        bundle = rec.dump("sigquit", cooldown=0.0)
        snap = rec.snapshot()
        assert snap["bundles"][0]["name"] == bundle["name"]
        assert "_frames" not in json.dumps(snap)
        drill = rec.snapshot(bundle=bundle["name"])
        assert drill["frame_records"][0]["sources"]["s"] == {"v": 7}
        assert rec.snapshot(bundle="flight-9999-nope") is None

    def test_dump_lock_timeout_bails_instead_of_deadlocking(self):
        """The SIGQUIT path: signal handlers run on the main thread, which
        may be suspended INSIDE record() holding the recorder lock — a
        blocking dump would deadlock the operator. With lock_timeout the
        dump gives up and returns None."""
        import threading

        rec = make_recorder()
        rec.register_source("s", lambda: {})
        rec.record("pass")
        held = threading.Event()
        release = threading.Event()

        def holder():
            with rec._lock:
                held.set()
                release.wait(timeout=10)

        t = threading.Thread(target=holder, daemon=True)
        t.start()
        held.wait(timeout=10)
        try:
            assert rec.dump("sigquit", lock_timeout=0.05) is None
        finally:
            release.set()
            t.join(timeout=10)
        # lock free again: the bounded dump succeeds
        assert rec.dump("sigquit", cooldown=0.0, lock_timeout=0.05) is not None

    def test_operator_shutdown_releases_global_slots(self):
        """A retired operator must not keep snapshotting into frames (or
        receiving breaches) after shutdown — keyed replace only covers a
        successor with the SAME cluster name."""
        from karpenter_tpu.cloudprovider.kwok.provider import KwokCloudProvider
        from karpenter_tpu.observability import slo
        from karpenter_tpu.operator.operator import Operator
        from karpenter_tpu.operator.options import Options
        from karpenter_tpu.runtime.store import Store

        clock = FakeClock()
        store = Store(clock=clock)
        op = Operator(
            store, KwokCloudProvider(store, clock), clock=clock,
            options=Options(cluster_name="retired-cell"),
        )
        assert "cell:retired-cell" in flight.recorder().snapshot()["sources"]
        assert "operator:retired-cell" in slo.engine()._subscribers
        op.shutdown()
        assert "cell:retired-cell" not in flight.recorder().snapshot()["sources"]
        assert "operator:retired-cell" not in slo.engine()._subscribers
        # the operator-independent process-level sources stay registered
        assert {"kernels", "spans"} <= set(flight.recorder().snapshot()["sources"])

    def test_report_is_deterministic_and_path_free(self, tmp_path):
        def replay(d):
            clock = FakeClock()
            rec = make_recorder(clock=clock, flight_dir=d)
            rec.register_source("s", lambda: {"v": 1})
            for _ in range(3):
                rec.record("pass")
                clock.step(1.0)
            rec.dump("slo:x")
            return rec.report()

        a = replay(str(tmp_path / "a"))
        b = replay(str(tmp_path / "b"))  # different dirs, identical report
        assert a == b
        assert a["ring_digest"].startswith("sha256:")
        assert a["bundles"][0]["sha256"].startswith("sha256:")
        assert "path" not in a["bundles"][0]


class TestServingEndpoints:
    """/debug/slo and /debug/flight: 200 with drill-down, 404 on unknown
    ids, 404 when unwired (the acceptance-criteria serving surface)."""

    def _server(self, slo_snapshot=None, flight_snapshot=None):
        from karpenter_tpu.operator.serving import Server, ServingConfig

        cfg = ServingConfig(
            metrics_text=lambda: "x 1\n",
            healthy=lambda: True,
            ready=lambda: True,
            slo_snapshot=slo_snapshot,
            flight_snapshot=flight_snapshot,
        )
        return Server(0, cfg, host="127.0.0.1").start()

    @staticmethod
    def _get(server, path):
        import urllib.error
        import urllib.request

        url = f"http://127.0.0.1:{server.port}{path}"
        try:
            with urllib.request.urlopen(url, timeout=10) as resp:
                return resp.status, resp.read().decode()
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode()

    def test_slo_endpoint_table_drilldown_and_404(self):
        from karpenter_tpu.observability.slo import SLOEngine, SLOSpec, Window

        eng = SLOEngine(
            clock=FakeClock(),
            specs=[SLOSpec("obj", "", 0.99, windows=(Window("w", 60, 2.0),))],
        )
        eng.record("obj", good=5, bad=5, tenant="gold")
        eng.evaluate()
        server = self._server(slo_snapshot=eng.snapshot)
        try:
            code, body = self._get(server, "/debug/slo")
            assert code == 200
            table = json.loads(body)
            assert table["objectives"]["obj"]["events"] == {"good": 5, "bad": 5}
            assert table["burning"]
            code, body = self._get(server, "/debug/slo?objective=obj")
            assert code == 200
            assert "gold" in json.loads(body)["tenants"]
            code, body = self._get(server, "/debug/slo?objective=missing")
            assert code == 404
            assert "unknown objective" in body
        finally:
            server.stop()

    def test_flight_endpoint_listing_drilldown_and_404(self):
        rec = make_recorder()
        rec.register_source("s", lambda: {"v": 1})
        rec.record("pass")
        bundle = rec.dump("slo:obj", cooldown=0.0)
        server = self._server(flight_snapshot=rec.snapshot)
        try:
            code, body = self._get(server, "/debug/flight")
            assert code == 200
            listing = json.loads(body)
            assert listing["ring_depth"] == 1
            assert listing["bundles"][0]["name"] == bundle["name"]
            code, body = self._get(
                server, f"/debug/flight?bundle={bundle['name']}"
            )
            assert code == 200
            assert json.loads(body)["frame_records"]
            code, body = self._get(server, "/debug/flight?bundle=nope")
            assert code == 404
            assert "unknown bundle" in body
        finally:
            server.stop()

    def test_unwired_endpoints_404(self):
        server = self._server()
        try:
            assert self._get(server, "/debug/slo")[0] == 404
            assert self._get(server, "/debug/flight")[0] == 404
        finally:
            server.stop()


class TestFlightExposition:
    def test_flight_families_round_trip(self):
        from karpenter_tpu.metrics import global_registry

        rec = make_recorder()
        rec.register_source("s", lambda: {})
        rec.record("expo-pass")
        rec.dump("expo-trigger", cooldown=0.0)
        fam = parse_exposition(global_registry.expose())
        frames = fam["karpenter_flight_frames_total"]
        assert frames["type"] == "counter"
        assert frames["samples"][
            ("karpenter_flight_frames_total", (("trigger", "expo-pass"),))
        ] >= 1.0
        dumps = fam["karpenter_flight_dumps_total"]
        assert dumps["samples"][
            ("karpenter_flight_dumps_total", (("trigger", "expo-trigger"),))
        ] >= 1.0
        assert fam["karpenter_flight_ring_depth"]["type"] == "gauge"
        hist = fam["karpenter_flight_bundle_bytes"]
        assert hist["type"] == "histogram"
        inf = hist["samples"][
            ("karpenter_flight_bundle_bytes_bucket", (("le", "+Inf"),))
        ]
        count = hist["samples"][("karpenter_flight_bundle_bytes_count", ())]
        total = hist["samples"][("karpenter_flight_bundle_bytes_sum", ())]
        assert inf == count >= 1.0
        assert total > 0.0


class TestFlakyCloudDeterminism:
    """The acceptance criterion: a same-seed flaky-cloud run breaches a
    configured objective, emits SLOBreach, and dumps a flight bundle whose
    sha256 is byte-identical across two runs."""

    @pytest.fixture(scope="class")
    def two_runs(self, tmp_path_factory):
        from karpenter_tpu.operator.options import Options
        from karpenter_tpu.sim import scenarios
        from karpenter_tpu.sim.harness import run_scenario

        results = []
        for i in range(2):
            d = str(tmp_path_factory.mktemp(f"flight{i}"))
            result = run_scenario(
                scenarios.resolve("flaky-cloud", 3), 3,
                options=Options(flight_dir=d),
            )
            results.append((result, d))
        return results

    def test_breach_and_bundle(self, two_runs):
        (a, _), _ = two_runs
        assert a.report["slo"]["breaches_total"] > 0
        assert a.report["slo"]["breaches"][0]["objective"]
        # the event log carries the breach stream
        assert a.log.entries("slo-breach")
        # a bundle was dumped for the breaching objective
        bundles = a.report["flight"]["bundles"]
        assert bundles and bundles[0]["trigger"].startswith("slo:")

    def test_reports_and_digests_identical(self, two_runs):
        (a, _), (b, _) = two_runs
        assert a.digest == b.digest
        assert a.report == b.report
        assert a.report["slo"]["digest"] == b.report["slo"]["digest"]
        assert (
            a.report["flight"]["ring_digest"]
            == b.report["flight"]["ring_digest"]
        )

    def test_bundle_files_byte_identical(self, two_runs):
        (_, da), (_, db) = two_runs
        names_a = sorted(os.listdir(da))
        names_b = sorted(os.listdir(db))
        assert names_a == names_b and names_a
        for name in names_a:
            with open(os.path.join(da, name), "rb") as f:
                bytes_a = f.read()
            with open(os.path.join(db, name), "rb") as f:
                bytes_b = f.read()
            assert bytes_a == bytes_b, f"bundle {name} differs between runs"


class TestDeviceMemoryReset:
    """Satellite fix: per-device memory gauges cleared on engine rebuild
    instead of serving stale values from an evicted engine."""

    def test_reset_device_memory_clears_family(self):
        from karpenter_tpu.metrics import global_registry
        from karpenter_tpu.observability import kernels as kobs

        gauge = global_registry.get("karpenter_device_memory_bytes")
        gauge.set(123.0, {"device": "STALE:0", "stat": "bytes_in_use"})
        live = global_registry.get("karpenter_device_live_array_bytes")
        live.set(999.0)
        kobs.registry()._last_memory = {"stale": True}
        kobs.reset_device_memory()
        assert gauge.series() == {}
        assert live.value() == 0.0
        assert kobs.registry()._last_memory is None

    def test_daemon_engine_rebuild_clears_stale_series(self):
        """The PR 6 regression: a rebuilt daemon engine must not leave the
        previous engine's per-device series standing."""
        from karpenter_tpu.cloudprovider.kwok.instance_types import (
            construct_instance_types,
        )
        from karpenter_tpu.metrics import global_registry
        from karpenter_tpu.solverd.transport import _default_engine_factory

        gauge = global_registry.get("karpenter_device_memory_bytes")
        gauge.set(777.0, {"device": "EVICTED:0", "stat": "bytes_in_use"})
        factory = _default_engine_factory()
        catalog = construct_instance_types()[:4]
        engine = factory(catalog)
        assert engine is not None
        stale = {
            k: v for k, v in gauge.series().items()
            if ("device", "EVICTED:0") in k
        }
        assert stale == {}, "stale per-device series survived the rebuild"
        # the cached engine path must NOT clear fresh samples
        gauge.set(42.0, {"device": "FRESH:0", "stat": "bytes_in_use"})
        factory(catalog)  # cache hit
        assert gauge.value({"device": "FRESH:0", "stat": "bytes_in_use"}) == 42.0

    def test_provisioner_engine_rebuild_clears_stale_series(self):
        from karpenter_tpu.cloudprovider.kwok.instance_types import (
            construct_instance_types,
        )
        from karpenter_tpu.controllers.provisioning.provisioner import (
            _ENGINE_CONTENT_CACHE,
            default_engine_factory,
        )
        from karpenter_tpu.metrics import global_registry

        gauge = global_registry.get("karpenter_device_memory_bytes")
        gauge.set(555.0, {"device": "EVICTED:1", "stat": "bytes_in_use"})
        _ENGINE_CONTENT_CACHE.clear()
        factory = default_engine_factory()
        engine = factory({"pool": construct_instance_types()[:4]})
        assert engine is not None
        assert gauge.value(
            {"device": "EVICTED:1", "stat": "bytes_in_use"}
        ) == 0.0
