"""Pod affinity / anti-affinity oracle: specs ported from the reference's
topology suite (topology_test.go:1939-2930 — names kept, lines cited).
Every spec runs on BOTH solver paths: the host per-pod loop and the
topo-aware device driver (ops/ffd_topo.py), which must make identical
decisions — device runs assert DEVICE_SOLVES advanced on every solve."""

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import (
    Affinity,
    LabelSelector,
    NodeAffinity,
    NodeSelectorTerm,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    WeightedPodAffinityTerm,
)

from device_path import both_paths_fixture
from helpers import bind_pod, nodepool, registered_node, unschedulable_pod
from test_scheduler import Env as HostEnv

Env = HostEnv
path = both_paths_fixture(globals())

WEB = {"app": "web"}
DB = {"app": "db"}


def term(key=wk.LABEL_TOPOLOGY_ZONE, match=None):
    return PodAffinityTerm(
        topology_key=key,
        label_selector=LabelSelector(match_labels=dict(WEB if match is None else match)),
    )


def pod_with(labels=None, affinity=None, anti=None, preferred_anti=None,
             preferred=None, requests=None, **kwargs):
    aff = None
    if affinity or anti or preferred or preferred_anti:
        aff = Affinity(
            pod_affinity=PodAffinity(
                required=list(affinity or ()),
                preferred=list(preferred or ()),
            )
            if (affinity or preferred)
            else None,
            pod_anti_affinity=PodAntiAffinity(
                required=list(anti or ()),
                preferred=list(preferred_anti or ()),
            )
            if (anti or preferred_anti)
            else None,
        )
    return unschedulable_pod(
        labels=dict(WEB if labels is None else labels),
        affinity=aff,
        requests=requests or {"cpu": "100m"},
        **kwargs,
    )


def claim_zones(results):
    zones = set()
    for nc in results.new_node_claims:
        zones.update(nc.requirements.get(wk.LABEL_TOPOLOGY_ZONE).values_list())
    return zones


class TestPodAffinity:
    def test_empty_pod_affinity_and_anti_affinity(self):
        # topology_test.go:1939
        env = Env()
        pod = pod_with(labels={})
        pod.spec.affinity = Affinity(
            pod_affinity=PodAffinity(), pod_anti_affinity=PodAntiAffinity()
        )
        results = env.schedule([pod])
        assert not results.pod_errors

    def test_respect_pod_affinity_hostname(self):
        # topology_test.go:1949 — affine pods share one hostname
        env = Env()
        pods = [pod_with(affinity=[term(key=wk.LABEL_HOSTNAME)]) for _ in range(4)]
        results = env.schedule(pods)
        assert not results.pod_errors
        assert len(results.new_node_claims) == 1

    def test_self_affinity_zone(self):
        # topology_test.go:2136 — all pods land in one zone
        env = Env()
        results = env.schedule([pod_with(affinity=[term()]) for _ in range(6)])
        assert not results.pod_errors
        assert len(claim_zones(results)) == 1

    def test_self_affinity_zone_with_constraint(self):
        # topology_test.go:2160 — every pod provides its own zonal affinity
        # AND a zone-3 limit: one node in zone-3
        env = Env()
        pods = [
            pod_with(
                affinity=[term()],
                node_selector={wk.LABEL_TOPOLOGY_ZONE: "kwok-zone-3"},
            )
            for _ in range(3)
        ]
        results = env.schedule(pods)
        assert not results.pod_errors
        assert len(results.new_node_claims) == 1
        assert claim_zones(results) == {"kwok-zone-3"}

    def test_affinity_to_nonexistent_pod_fails(self):
        # topology_test.go:2723 — nothing to be affine to
        env = Env()
        results = env.schedule([pod_with(labels=DB, affinity=[term(match=WEB)])])
        assert len(results.pod_errors) == 1

    def test_affinity_with_zone_topology_unconstrained_target(self):
        # topology_test.go:2740 — the target's zone is undetermined within
        # the batch, so the affine pods CANNOT schedule this round; only the
        # target lands (they follow once it's bound, next round)
        env = Env()
        target = pod_with(labels=WEB)
        followers = [pod_with(labels=DB, affinity=[term(match=WEB)]) for _ in range(3)]
        results = env.schedule([target] + followers)
        assert set(results.pod_errors) == set(followers)
        assert sum(len(nc.pods) for nc in results.new_node_claims) == 1

    def test_affinity_with_zone_topology_constrained_target(self):
        # topology_test.go:2773
        env = Env()
        target = pod_with(
            labels=WEB, node_selector={wk.LABEL_TOPOLOGY_ZONE: "kwok-zone-2"}
        )
        followers = [pod_with(labels=DB, affinity=[term(match=WEB)]) for _ in range(3)]
        results = env.schedule([target] + followers)
        assert not results.pod_errors
        assert claim_zones(results) == {"kwok-zone-2"}

    def test_multiple_dependent_affinities(self):
        # topology_test.go:2802 — db -> web -> cache -> ui hostname chain
        # converges regardless of processing order (the solver requeues)
        env = Env()
        chain = [
            pod_with(labels={"app": "a"}),
            pod_with(
                labels={"app": "b"},
                affinity=[term(key=wk.LABEL_HOSTNAME, match={"app": "a"})],
            ),
            pod_with(
                labels={"app": "c"},
                affinity=[term(key=wk.LABEL_HOSTNAME, match={"app": "b"})],
            ),
            pod_with(
                labels={"app": "d"},
                affinity=[term(key=wk.LABEL_HOSTNAME, match={"app": "c"})],
            ),
        ]
        results = env.schedule(chain)
        assert not results.pod_errors

    def test_unsatisfiable_dependencies_fail(self):
        # topology_test.go:2837 — mutually exclusive zones break the chain
        env = Env()
        a = pod_with(
            labels={"app": "a"},
            node_selector={wk.LABEL_TOPOLOGY_ZONE: "kwok-zone-1"},
        )
        b = pod_with(
            labels={"app": "b"},
            affinity=[term(match={"app": "a"})],
            node_selector={wk.LABEL_TOPOLOGY_ZONE: "kwok-zone-2"},
        )
        results = env.schedule([a, b])
        assert len(results.pod_errors) == 1

    def test_allow_violation_of_preferred_pod_affinity(self):
        # topology_test.go:2244 — preference to a pod that doesn't exist
        env = Env()
        preferred = WeightedPodAffinityTerm(
            weight=50, pod_affinity_term=term(match={"app": "ghost"})
        )
        results = env.schedule([pod_with(preferred=[preferred])])
        assert not results.pod_errors


class TestPodAntiAffinity:
    def test_separate_nodes_simple_anti_affinity_hostname(self):
        # topology_test.go:2310
        env = Env()
        pods = [pod_with(anti=[term(key=wk.LABEL_HOSTNAME)]) for _ in range(4)]
        results = env.schedule(pods)
        assert not results.pod_errors
        assert len(results.new_node_claims) == 4

    def test_not_violate_anti_affinity_zone(self):
        # topology_test.go:2332 — big zone-pinned web pods occupy every zone
        # first (FFD sorts them ahead); the anti-affine pod has nowhere left
        env = Env()
        zone_pods = [
            pod_with(
                requests={"cpu": "2"},
                node_selector={wk.LABEL_TOPOLOGY_ZONE: f"kwok-zone-{i}"},
            )
            for i in (1, 2, 3, 4)
        ]
        anti = pod_with(labels=DB, anti=[term(match=WEB)])
        results = env.schedule(zone_pods + [anti])
        assert set(results.pod_errors) == {anti}

    def test_inverse_anti_affinity_blocks_targets(self):
        # topology_test.go:2476 — an anti-affine pod already in a zone
        # repels matching pods from that zone
        node = registered_node(zone="kwok-zone-1", pool="default")
        repeller = bind_pod(
            pod_with(labels=DB, anti=[term(match=WEB)]), node
        )
        env = Env(state_nodes=[node], pods=[repeller])
        results = env.schedule([pod_with(labels=WEB) for _ in range(3)])
        assert not results.pod_errors
        assert "kwok-zone-1" not in claim_zones(results)

    def test_allow_violation_of_preferred_anti_affinity(self):
        # topology_test.go:2277
        env = Env()
        preferred = WeightedPodAffinityTerm(
            weight=50, pod_affinity_term=term(match=WEB)
        )
        pods = [pod_with(preferred_anti=[preferred]) for _ in range(6)]
        results = env.schedule(pods)
        assert not results.pod_errors


# ---------------------------------------------------------------------------
# Deep affinity specs (topology_test.go:1983-2837): late-committal zones,
# self-affinity seeding, inverse anti-affinity with existing nodes.
# Multi-pass specs use the materialize/store_skew harness.
# ---------------------------------------------------------------------------

from karpenter_tpu.apis.core import TopologySpreadConstraint

from test_topology_oracle import materialize, store_skew

S2 = {"security": "s2"}


def s2_tsc(key=wk.LABEL_HOSTNAME):
    return TopologySpreadConstraint(
        max_skew=1,
        topology_key=key,
        when_unsatisfiable="DoNotSchedule",
        label_selector=LabelSelector(match_labels=dict(S2)),
    )


class TestPodAffinityDeep:
    def test_pod_affinity_arch(self):
        # topology_test.go:1983 — same arch, different hosts (TSC)
        env = Env()
        p1 = pod_with(
            labels=dict(S2),
            requests={"cpu": "2"},
            node_selector={wk.LABEL_ARCH: "arm64"},
            topology_spread_constraints=[s2_tsc()],
        )
        p2 = pod_with(
            labels=dict(S2),
            requests={"cpu": "1"},
            affinity=[term(key=wk.LABEL_ARCH, match=S2)],
            topology_spread_constraints=[s2_tsc()],
        )
        results = env.schedule([p1, p2])
        assert not results.pod_errors
        claims = results.new_node_claims
        assert len(claims) == 2
        archs = [c.requirements.get(wk.LABEL_ARCH).values_list() for c in claims]
        assert archs == [["arm64"], ["arm64"]]

    def test_self_affinity_first_empty_domain_only_hostname(self):
        # topology_test.go:2050 — self hostname affinity seeds exactly ONE
        # domain; overflow pods fail rather than opening a second node
        np = nodepool(
            "default",
            requirements=[
                {
                    "key": wk.LABEL_INSTANCE_TYPE,
                    "operator": "In",
                    "values": ["c-1x-amd64-linux"],
                }
            ],
        )
        env = Env(node_pools=[np])

        def batch():
            return [
                pod_with(
                    labels=dict(S2),
                    requests={"cpu": "170m"},  # 5 fit on c-1x's 0.9 cpu
                    affinity=[term(key=wk.LABEL_HOSTNAME, match=S2)],
                )
                for _ in range(10)
            ]

        first = env.schedule(batch())
        assert len(first.new_node_claims) == 1
        assert len(first.new_node_claims[0].pods) == 5
        assert len(first.pod_errors) == 5
        materialize(env, first, "p1")
        second = env.schedule(batch())
        assert len(second.pod_errors) == 10

    def test_self_affinity_hostname_constrained_zones(self):
        # topology_test.go:2092 — pod affinity ignores node-selector
        # restrictions on counting: the zone-1 pod's hostname domain is the
        # only candidate, unreachable from zones 2/3
        env = Env()
        first = env.schedule(
            [
                pod_with(
                    labels=dict(S2),
                    node_selector={wk.LABEL_TOPOLOGY_ZONE: "kwok-zone-1"},
                    affinity=[term(key=wk.LABEL_HOSTNAME, match=S2)],
                )
            ]
        )
        assert not first.pod_errors
        materialize(env, first, "p1")
        pods = []
        for _ in range(10):
            p = pod_with(labels=dict(S2), affinity=[term(key=wk.LABEL_HOSTNAME, match=S2)])
            p.spec.affinity.node_affinity = NodeAffinity(
                required=[
                    NodeSelectorTerm(
                        match_expressions=[
                            {
                                "key": wk.LABEL_TOPOLOGY_ZONE,
                                "operator": "In",
                                "values": ["kwok-zone-2", "kwok-zone-3"],
                            }
                        ]
                    )
                ]
            )
            pods.append(p)
        second = env.schedule(pods)
        assert len(second.pod_errors) == 10

    def test_self_affinity_zone(self):
        # topology_test.go:2136 — three self-affine pods share one claim
        env = Env()
        results = env.schedule(
            [
                pod_with(labels=dict(S2), affinity=[term(match=S2)])
                for _ in range(3)
            ]
        )
        assert not results.pod_errors
        assert len(results.new_node_claims) == 1

    def test_self_affinity_zone_with_constraint(self):
        # topology_test.go:2160 — self zone affinity + zone-3 restriction
        env = Env()
        pods = []
        for _ in range(3):
            p = pod_with(labels=dict(S2), affinity=[term(match=S2)])
            p.spec.node_selector = {wk.LABEL_TOPOLOGY_ZONE: "kwok-zone-3"}
            pods.append(p)
        results = env.schedule(pods)
        assert not results.pod_errors
        assert len(results.new_node_claims) == 1
        assert results.new_node_claims[0].requirements.get(
            wk.LABEL_TOPOLOGY_ZONE
        ).values_list() == ["kwok-zone-3"]


class TestAntiAffinityDeep:
    def test_anti_affinity_other_schedules_first(self):
        # topology_test.go:2371 — the avoided pod schedules first into an
        # uncommitted zone, so the anti pod can't schedule anywhere
        env = Env()
        avoided = pod_with(labels=dict(S2), requests={"cpu": "2"})
        anti = pod_with(labels={}, anti=[term(match=S2)])
        results = env.schedule([avoided, anti])
        assert anti in results.pod_errors
        assert avoided not in results.pod_errors

    def test_anti_affinity_schroedinger(self):
        # topology_test.go:2512 — an uncommitted anti pod blocks the batch;
        # once its node exists the target schedules in a different zone
        env = Env()
        zone_anywhere = pod_with(labels={}, anti=[term(match=S2)], requests={"cpu": "2"})
        aff = pod_with(labels=dict(S2))
        first = env.schedule([zone_anywhere, aff])
        assert aff in first.pod_errors
        assert zone_anywhere not in first.pod_errors
        materialize(env, first, "p1")
        committed = {
            env.store.try_get("Node", f"p1-{i}").metadata.labels[wk.LABEL_TOPOLOGY_ZONE]
            for i in range(len(first.new_node_claims))
        }
        second = env.schedule([aff])
        assert not second.pod_errors
        aff_zones = set()
        for nc in second.new_node_claims:
            aff_zones.update(nc.requirements.get(wk.LABEL_TOPOLOGY_ZONE).values_list())
        for en in second.existing_nodes:
            if en.pods:
                aff_zones.add(en.labels().get(wk.LABEL_TOPOLOGY_ZONE))
        assert aff_zones, "aff pod did not land"
        assert not (aff_zones & committed)

    def test_anti_affinity_inverse_with_existing_nodes(self):
        # topology_test.go:2543 — existing pods with zone anti-affinity in
        # every zone repel a plain matching pod entirely
        env = Env()
        zone_pods = []
        for z in ("kwok-zone-1", "kwok-zone-2", "kwok-zone-3", "kwok-zone-4"):
            p = pod_with(labels={}, anti=[term(match=S2)], requests={"cpu": "2"})
            p.spec.node_selector = {wk.LABEL_TOPOLOGY_ZONE: z}
            zone_pods.append(p)
        first = env.schedule(zone_pods)
        assert not first.pod_errors
        materialize(env, first, "p1")
        second = env.schedule([pod_with(labels=dict(S2))])
        assert len(second.pod_errors) == 1

    def test_preferred_anti_affinity_inverse_with_existing_nodes(self):
        # topology_test.go:2593 — preferred inverse anti-affinity does not
        # repel once committed (only required terms are tracked inversely)
        env = Env()
        zone_pods = []
        for z in ("kwok-zone-1", "kwok-zone-2", "kwok-zone-3", "kwok-zone-4"):
            p = pod_with(
                labels={},
                preferred_anti=[
                    WeightedPodAffinityTerm(weight=10, pod_affinity_term=term(match=S2))
                ],
                requests={"cpu": "2"},
            )
            p.spec.node_selector = {wk.LABEL_TOPOLOGY_ZONE: z}
            zone_pods.append(p)
        first = env.schedule(zone_pods)
        assert not first.pod_errors
        materialize(env, first, "p1")
        second = env.schedule([pod_with(labels=dict(S2))])
        assert not second.pod_errors

    def test_affinity_preference_violated_with_conflicting_required_tsc(self):
        # topology_test.go:2643 — hostname spread wins over a pod-affinity
        # preference; everything schedules across three hosts
        env = Env()
        aff_target = pod_with(labels=dict(S2))
        spread_pods = [
            pod_with(
                labels=dict(WEB),
                preferred=[
                    WeightedPodAffinityTerm(
                        weight=50, pod_affinity_term=term(key=wk.LABEL_HOSTNAME, match=S2)
                    )
                ],
                topology_spread_constraints=[
                    TopologySpreadConstraint(
                        max_skew=1,
                        topology_key=wk.LABEL_HOSTNAME,
                        when_unsatisfiable="DoNotSchedule",
                        label_selector=LabelSelector(match_labels=dict(WEB)),
                    )
                ],
            )
            for _ in range(3)
        ]
        results = env.schedule(spread_pods + [aff_target])
        assert not results.pod_errors
        web_hosts = set()
        for nc in results.new_node_claims:
            if any(p.metadata.labels.get("app") == "web" for p in nc.pods):
                web_hosts.add(nc.hostname)
                assert sum(p.metadata.labels.get("app") == "web" for p in nc.pods) == 1
        assert len(web_hosts) == 3

    def test_anti_affinity_zone_topology_batches(self):
        # topology_test.go:2678 — late committal: one pod lands per batch
        # until every zone is occupied, then none
        env = Env()

        def batch():
            return [
                pod_with(labels=dict(S2), anti=[term(match=S2)]) for _ in range(3)
            ]

        for i, expected in enumerate([[1], [1, 1], [1, 1, 1], [1, 1, 1, 1]]):
            results = env.schedule(batch())
            scheduled = sum(len(nc.pods) for nc in results.new_node_claims) + sum(
                len(en.pods) for en in results.existing_nodes
            )
            assert scheduled == 1, (i, scheduled)
            materialize(env, results, f"p{i}")
            assert store_skew(env, match=S2) == expected
        results = env.schedule(batch())
        assert len(results.pod_errors) == 3


class TestAffinityTargets:
    def test_affinity_to_non_existent_pod(self):
        # topology_test.go:2723
        env = Env()
        results = env.schedule(
            [pod_with(labels={}, affinity=[term(match=S2)]) for _ in range(10)]
        )
        assert len(results.pod_errors) == 10

    def test_affinity_unconstrained_target_two_batches(self):
        # topology_test.go:2740 — the target's zone commits on node
        # creation; followers join it in the second batch
        env = Env()
        target = pod_with(labels=dict(S2))
        followers = [
            pod_with(labels={}, affinity=[term(match=S2)]) for _ in range(10)
        ]
        first = env.schedule([target] + followers)
        assert len(first.pod_errors) == 10
        materialize(env, first, "p1")
        target_zone = [
            env.store.try_get("Node", "p1-0").metadata.labels[wk.LABEL_TOPOLOGY_ZONE]
        ]
        second = env.schedule([pod_with(labels={}, affinity=[term(match=S2)]) for _ in range(10)])
        assert not second.pod_errors
        zones = set()
        for nc in second.new_node_claims:
            zones.update(nc.requirements.get(wk.LABEL_TOPOLOGY_ZONE).values_list())
        for en in second.existing_nodes:
            if en.pods:
                zones.add(en.labels().get(wk.LABEL_TOPOLOGY_ZONE))
        assert zones <= set(target_zone), (zones, target_zone)

    def test_affinity_constrained_target_single_batch(self):
        # topology_test.go:2773 — a zone-pinned target lets followers
        # co-schedule in one batch
        env = Env()
        target = pod_with(labels=dict(S2))
        target.spec.node_selector = {wk.LABEL_TOPOLOGY_ZONE: "kwok-zone-1"}
        followers = [
            pod_with(labels={}, affinity=[term(match=S2)]) for _ in range(10)
        ]
        results = env.schedule([target] + followers)
        assert not results.pod_errors
        for nc in results.new_node_claims:
            assert nc.requirements.get(wk.LABEL_TOPOLOGY_ZONE).values_list() == [
                "kwok-zone-1"
            ]

    def test_multiple_dependent_affinities(self):
        # topology_test.go:2802 — db -> web -> cache -> ui hostname chain
        env = Env()
        db = {"type": "db", "spread": "spread"}
        web = {"type": "web", "spread": "spread"}
        cache = {"type": "cache", "spread": "spread"}
        ui = {"type": "ui", "spread": "spread"}
        pods = [
            pod_with(labels=db),
            pod_with(labels=web, affinity=[term(key=wk.LABEL_HOSTNAME, match=db)]),
            pod_with(labels=cache, affinity=[term(key=wk.LABEL_HOSTNAME, match=web)]),
            pod_with(labels=ui, affinity=[term(key=wk.LABEL_HOSTNAME, match=cache)]),
        ]
        results = env.schedule(pods)
        assert not results.pod_errors


class TestAffinityNamespaceFiltering:
    """topology_test.go:2853-2971 — affinity targets are namespace-scoped:
    same namespace by default, opt-in via namespace lists and selectors."""

    def _spread_batch(self):
        tsc = TopologySpreadConstraint(
            max_skew=1,
            topology_key=wk.LABEL_HOSTNAME,
            when_unsatisfiable="DoNotSchedule",
            label_selector=LabelSelector(match_labels=dict(WEB)),
        )
        return [
            pod_with(labels=dict(WEB), topology_spread_constraints=[tsc])
            for _ in range(10)
        ]

    def _ns_env(self, ns_name, ns_labels=None):
        from karpenter_tpu.apis.core import Namespace, ObjectMeta

        env = Env()
        env.store.create(
            Namespace(metadata=ObjectMeta(name=ns_name, labels=ns_labels or {}))
        )
        return env

    def test_namespace_no_match(self):
        # topology_test.go:2853 — target in another namespace isn't visible
        env = self._ns_env("other-ns-no-match")
        target = pod_with(labels=dict(S2))
        target.metadata.namespace = "other-ns-no-match"
        follower = pod_with(labels={}, affinity=[term(key=wk.LABEL_HOSTNAME, match=S2)])
        results = env.schedule(self._spread_batch() + [target, follower])
        assert follower in results.pod_errors
        assert target not in results.pod_errors

    def test_namespace_list_matches(self):
        # topology_test.go:2891 — explicit namespace list makes the target
        # visible; both land on the same hostname
        env = self._ns_env("other-ns-list")
        target = pod_with(labels=dict(S2))
        target.metadata.namespace = "other-ns-list"
        t = term(key=wk.LABEL_HOSTNAME, match=S2)
        t.namespaces = ["other-ns-list"]
        follower = pod_with(labels={}, affinity=[t])
        results = env.schedule(self._spread_batch() + [target, follower])
        assert not results.pod_errors
        names = {target.metadata.name, follower.metadata.name}
        shared = [
            nc
            for nc in results.new_node_claims
            if names & {p.metadata.name for p in nc.pods}
        ]
        assert len(shared) == 1
        assert names <= {p.metadata.name for p in shared[0].pods}

    def test_empty_namespace_selector_matches_all(self):
        # topology_test.go:2930 — an empty namespaceSelector selects every
        # namespace
        env = self._ns_env("empty-ns-selector", {"foo": "bar"})
        target = pod_with(labels=dict(S2))
        target.metadata.namespace = "empty-ns-selector"
        t = term(key=wk.LABEL_HOSTNAME, match=S2)
        t.namespace_selector = LabelSelector()
        follower = pod_with(labels={}, affinity=[t])
        results = env.schedule(self._spread_batch() + [target, follower])
        assert not results.pod_errors
        names = {target.metadata.name, follower.metadata.name}
        shared = [
            nc
            for nc in results.new_node_claims
            if names & {p.metadata.name for p in nc.pods}
        ]
        assert len(shared) == 1
        assert names <= {p.metadata.name for p in shared[0].pods}
