"""Pod affinity / anti-affinity oracle: specs ported from the reference's
topology suite (topology_test.go:1939-2930 — names kept, lines cited).
Every spec runs on BOTH solver paths: the host per-pod loop and the
topo-aware device driver (ops/ffd_topo.py), which must make identical
decisions — device runs assert DEVICE_SOLVES advanced on every solve."""

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import (
    Affinity,
    LabelSelector,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    WeightedPodAffinityTerm,
)

from device_path import both_paths_fixture
from helpers import bind_pod, nodepool, registered_node, unschedulable_pod
from test_scheduler import Env as HostEnv

Env = HostEnv
path = both_paths_fixture(globals())

WEB = {"app": "web"}
DB = {"app": "db"}


def term(key=wk.LABEL_TOPOLOGY_ZONE, match=None):
    return PodAffinityTerm(
        topology_key=key,
        label_selector=LabelSelector(match_labels=dict(WEB if match is None else match)),
    )


def pod_with(labels=None, affinity=None, anti=None, preferred_anti=None,
             preferred=None, requests=None, **kwargs):
    aff = None
    if affinity or anti or preferred or preferred_anti:
        aff = Affinity(
            pod_affinity=PodAffinity(
                required=list(affinity or ()),
                preferred=list(preferred or ()),
            )
            if (affinity or preferred)
            else None,
            pod_anti_affinity=PodAntiAffinity(
                required=list(anti or ()),
                preferred=list(preferred_anti or ()),
            )
            if (anti or preferred_anti)
            else None,
        )
    return unschedulable_pod(
        labels=dict(WEB if labels is None else labels),
        affinity=aff,
        requests=requests or {"cpu": "100m"},
        **kwargs,
    )


def claim_zones(results):
    zones = set()
    for nc in results.new_node_claims:
        zones.update(nc.requirements.get(wk.LABEL_TOPOLOGY_ZONE).values_list())
    return zones


class TestPodAffinity:
    def test_empty_pod_affinity_and_anti_affinity(self):
        # topology_test.go:1939
        env = Env()
        pod = pod_with(labels={})
        pod.spec.affinity = Affinity(
            pod_affinity=PodAffinity(), pod_anti_affinity=PodAntiAffinity()
        )
        results = env.schedule([pod])
        assert not results.pod_errors

    def test_respect_pod_affinity_hostname(self):
        # topology_test.go:1949 — affine pods share one hostname
        env = Env()
        pods = [pod_with(affinity=[term(key=wk.LABEL_HOSTNAME)]) for _ in range(4)]
        results = env.schedule(pods)
        assert not results.pod_errors
        assert len(results.new_node_claims) == 1

    def test_self_affinity_zone(self):
        # topology_test.go:2136 — all pods land in one zone
        env = Env()
        results = env.schedule([pod_with(affinity=[term()]) for _ in range(6)])
        assert not results.pod_errors
        assert len(claim_zones(results)) == 1

    def test_self_affinity_zone_with_constraint(self):
        # topology_test.go:2160 — every pod provides its own zonal affinity
        # AND a zone-3 limit: one node in zone-3
        env = Env()
        pods = [
            pod_with(
                affinity=[term()],
                node_selector={wk.LABEL_TOPOLOGY_ZONE: "kwok-zone-3"},
            )
            for _ in range(3)
        ]
        results = env.schedule(pods)
        assert not results.pod_errors
        assert len(results.new_node_claims) == 1
        assert claim_zones(results) == {"kwok-zone-3"}

    def test_affinity_to_nonexistent_pod_fails(self):
        # topology_test.go:2723 — nothing to be affine to
        env = Env()
        results = env.schedule([pod_with(labels=DB, affinity=[term(match=WEB)])])
        assert len(results.pod_errors) == 1

    def test_affinity_with_zone_topology_unconstrained_target(self):
        # topology_test.go:2740 — the target's zone is undetermined within
        # the batch, so the affine pods CANNOT schedule this round; only the
        # target lands (they follow once it's bound, next round)
        env = Env()
        target = pod_with(labels=WEB)
        followers = [pod_with(labels=DB, affinity=[term(match=WEB)]) for _ in range(3)]
        results = env.schedule([target] + followers)
        assert set(results.pod_errors) == set(followers)
        assert sum(len(nc.pods) for nc in results.new_node_claims) == 1

    def test_affinity_with_zone_topology_constrained_target(self):
        # topology_test.go:2773
        env = Env()
        target = pod_with(
            labels=WEB, node_selector={wk.LABEL_TOPOLOGY_ZONE: "kwok-zone-2"}
        )
        followers = [pod_with(labels=DB, affinity=[term(match=WEB)]) for _ in range(3)]
        results = env.schedule([target] + followers)
        assert not results.pod_errors
        assert claim_zones(results) == {"kwok-zone-2"}

    def test_multiple_dependent_affinities(self):
        # topology_test.go:2802 — db -> web -> cache -> ui hostname chain
        # converges regardless of processing order (the solver requeues)
        env = Env()
        chain = [
            pod_with(labels={"app": "a"}),
            pod_with(
                labels={"app": "b"},
                affinity=[term(key=wk.LABEL_HOSTNAME, match={"app": "a"})],
            ),
            pod_with(
                labels={"app": "c"},
                affinity=[term(key=wk.LABEL_HOSTNAME, match={"app": "b"})],
            ),
            pod_with(
                labels={"app": "d"},
                affinity=[term(key=wk.LABEL_HOSTNAME, match={"app": "c"})],
            ),
        ]
        results = env.schedule(chain)
        assert not results.pod_errors

    def test_unsatisfiable_dependencies_fail(self):
        # topology_test.go:2837 — mutually exclusive zones break the chain
        env = Env()
        a = pod_with(
            labels={"app": "a"},
            node_selector={wk.LABEL_TOPOLOGY_ZONE: "kwok-zone-1"},
        )
        b = pod_with(
            labels={"app": "b"},
            affinity=[term(match={"app": "a"})],
            node_selector={wk.LABEL_TOPOLOGY_ZONE: "kwok-zone-2"},
        )
        results = env.schedule([a, b])
        assert len(results.pod_errors) == 1

    def test_allow_violation_of_preferred_pod_affinity(self):
        # topology_test.go:2244 — preference to a pod that doesn't exist
        env = Env()
        preferred = WeightedPodAffinityTerm(
            weight=50, pod_affinity_term=term(match={"app": "ghost"})
        )
        results = env.schedule([pod_with(preferred=[preferred])])
        assert not results.pod_errors


class TestPodAntiAffinity:
    def test_separate_nodes_simple_anti_affinity_hostname(self):
        # topology_test.go:2310
        env = Env()
        pods = [pod_with(anti=[term(key=wk.LABEL_HOSTNAME)]) for _ in range(4)]
        results = env.schedule(pods)
        assert not results.pod_errors
        assert len(results.new_node_claims) == 4

    def test_not_violate_anti_affinity_zone(self):
        # topology_test.go:2332 — big zone-pinned web pods occupy every zone
        # first (FFD sorts them ahead); the anti-affine pod has nowhere left
        env = Env()
        zone_pods = [
            pod_with(
                requests={"cpu": "2"},
                node_selector={wk.LABEL_TOPOLOGY_ZONE: f"kwok-zone-{i}"},
            )
            for i in (1, 2, 3, 4)
        ]
        anti = pod_with(labels=DB, anti=[term(match=WEB)])
        results = env.schedule(zone_pods + [anti])
        assert set(results.pod_errors) == {anti}

    def test_inverse_anti_affinity_blocks_targets(self):
        # topology_test.go:2476 — an anti-affine pod already in a zone
        # repels matching pods from that zone
        node = registered_node(zone="kwok-zone-1", pool="default")
        repeller = bind_pod(
            pod_with(labels=DB, anti=[term(match=WEB)]), node
        )
        env = Env(state_nodes=[node], pods=[repeller])
        results = env.schedule([pod_with(labels=WEB) for _ in range(3)])
        assert not results.pod_errors
        assert "kwok-zone-1" not in claim_zones(results)

    def test_allow_violation_of_preferred_anti_affinity(self):
        # topology_test.go:2277
        env = Env()
        preferred = WeightedPodAffinityTerm(
            weight=50, pod_affinity_term=term(match=WEB)
        )
        pods = [pod_with(preferred_anti=[preferred]) for _ in range(6)]
        results = env.schedule(pods)
        assert not results.pod_errors
