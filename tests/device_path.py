"""Shared both-paths harness for oracle suites.

Oracle modules run every spec against BOTH solver paths — the host per-pod
loop and the device fast path (plain or topo-aware driver). A module opts in
with:

    from device_path import both_paths_fixture
    from test_scheduler import Env as HostEnv

    Env = HostEnv
    path = both_paths_fixture(globals())

The device leg swaps the module-global `Env` for `DeviceEnv`, which attaches
the kwok CatalogEngine, pins DEVICE_MIN_PODS to 1, turns on STRICT (so
simulation bugs raise instead of silently falling back), and asserts
DEVICE_SOLVES advanced on every solve — a silent fallback fails loudly.
"""

import pytest

from karpenter_tpu.cloudprovider.kwok.instance_types import construct_instance_types
from karpenter_tpu.ops import ffd
from karpenter_tpu.ops.catalog import CatalogEngine

from test_scheduler import Env as HostEnv

CATALOG = construct_instance_types()


class DeviceEnv(HostEnv):
    def __init__(self, **kwargs):
        kwargs.setdefault("engine", CatalogEngine(CATALOG))
        super().__init__(**kwargs)

    def schedule(self, pods, timeout=60.0):
        s0 = ffd.DEVICE_SOLVES
        results = super().schedule(pods, timeout=timeout)
        assert ffd.DEVICE_SOLVES > s0, "expected the device path to run"
        return results


def both_paths_fixture(module_globals: dict):
    """Autouse fixture parametrizing a module over host/device paths."""

    @pytest.fixture(params=["host", "device"], autouse=True)
    def path(request, monkeypatch):
        if request.param == "device":
            monkeypatch.setattr(ffd, "DEVICE_MIN_PODS", 1)
            monkeypatch.setattr(ffd, "STRICT", True)
            monkeypatch.setitem(module_globals, "Env", DeviceEnv)
        return request.param

    return path
