"""Volume-limit scheduling specs (reference suite_test.go:2776-2919):
CSI attach limits on existing nodes force overflow onto new capacity;
pods sharing one PVC count it once. Every spec runs on BOTH solver paths
(volume shapes take the topo driver's volatile node path). The
strict-reserved-mode specs live in test_reserved_and_deleting.py."""

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import (
    CSINode,
    CSINodeDriver,
    ObjectMeta,
    PersistentVolumeClaim,
    StorageClass,
    Volume,
)
from device_path import both_paths_fixture
from helpers import node_claim_pair, nodepool, unschedulable_pod
from test_scheduler import Env as HostEnv

Env = HostEnv
path = both_paths_fixture(globals())

DRIVER = "ebs.csi.example.com"


def volume_env(attach_limit: int, **env_kwargs):
    # CSINode must exist before the Node event is ingested: limits are read
    # when cluster state (re)builds the node (cluster.py CSINode lookup)
    env = Env(**env_kwargs)
    env.store.create(StorageClass(metadata=ObjectMeta(name="fast"), provisioner=DRIVER))
    env.store.create(
        CSINode(
            metadata=ObjectMeta(name="vol-node-1"),
            drivers=[CSINodeDriver(name=DRIVER, allocatable_count=attach_limit)],
        )
    )
    node, claim = node_claim_pair("vol-node-1")
    env.store.create(node)
    env.store.create(claim)
    env.informer.flush()
    return env


def pvc_pod(env, pvc_name):
    env.store.try_get("PersistentVolumeClaim", pvc_name) or env.store.create(
        PersistentVolumeClaim(
            metadata=ObjectMeta(name=pvc_name), storage_class_name="fast"
        )
    )
    return unschedulable_pod(
        requests={"cpu": "100m"},
        volumes=[Volume(name="data", persistent_volume_claim=pvc_name)],
    )


class TestVolumeLimits:
    def test_attach_limit_forces_overflow_to_new_node(self):
        # limit 1: first PVC pod lands on the existing node, second overflows
        env = volume_env(attach_limit=1)
        pods = [pvc_pod(env, "pvc-a"), pvc_pod(env, "pvc-b")]
        results = env.schedule(pods)
        assert not results.pod_errors
        assert sum(len(en.pods) for en in results.existing_nodes) == 1
        assert len(results.new_node_claims) == 1

    def test_same_pvc_counted_once(self):
        # limit 1, both pods share one PVC → both fit the existing node
        env = volume_env(attach_limit=1)
        pods = [pvc_pod(env, "pvc-shared"), pvc_pod(env, "pvc-shared")]
        results = env.schedule(pods)
        assert not results.pod_errors
        assert sum(len(en.pods) for en in results.existing_nodes) == 2
        assert not results.new_node_claims

    def test_unlimited_driver_unconstrained(self):
        env = volume_env(attach_limit=None)
        pods = [pvc_pod(env, f"pvc-{i}") for i in range(4)]
        results = env.schedule(pods)
        assert not results.pod_errors
        assert not results.new_node_claims
