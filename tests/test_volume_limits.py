"""Volume-limit scheduling specs (reference suite_test.go:2776-2919):
CSI attach limits on existing nodes force overflow onto new capacity;
pods sharing one PVC count it once. Every spec runs on BOTH solver paths
(volume shapes take the topo driver's volatile node path). The
strict-reserved-mode specs live in test_reserved_and_deleting.py."""

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import (
    CSINode,
    CSINodeDriver,
    ObjectMeta,
    PersistentVolumeClaim,
    StorageClass,
    Volume,
)
from device_path import both_paths_fixture
from helpers import node_claim_pair, nodepool, unschedulable_pod
from test_scheduler import Env as HostEnv

Env = HostEnv
path = both_paths_fixture(globals())

DRIVER = "ebs.csi.example.com"


def volume_env(
    attach_limit: int,
    provisioner: str = DRIVER,
    csi_driver: str = DRIVER,
    node_name: str = "vol-node-1",
    sc_name: str = "fast",
    **env_kwargs,
):
    # CSINode must exist before the Node event is ingested: limits are read
    # when cluster state (re)builds the node (cluster.py CSINode lookup)
    env = Env(**env_kwargs)
    env.store.create(
        StorageClass(metadata=ObjectMeta(name=sc_name), provisioner=provisioner)
    )
    env.store.create(
        CSINode(
            metadata=ObjectMeta(name=node_name),
            drivers=[CSINodeDriver(name=csi_driver, allocatable_count=attach_limit)],
        )
    )
    node, claim = node_claim_pair(node_name)
    env.store.create(node)
    env.store.create(claim)
    env.informer.flush()
    return env


def pvc_pod(env, pvc_name, sc_name: str = "fast"):
    env.store.try_get("PersistentVolumeClaim", pvc_name) or env.store.create(
        PersistentVolumeClaim(
            metadata=ObjectMeta(name=pvc_name), storage_class_name=sc_name
        )
    )
    return unschedulable_pod(
        requests={"cpu": "100m"},
        volumes=[Volume(name="data", persistent_volume_claim=pvc_name)],
    )


class TestVolumeLimits:
    def test_attach_limit_forces_overflow_to_new_node(self):
        # limit 1: first PVC pod lands on the existing node, second overflows
        env = volume_env(attach_limit=1)
        pods = [pvc_pod(env, "pvc-a"), pvc_pod(env, "pvc-b")]
        results = env.schedule(pods)
        assert not results.pod_errors
        assert sum(len(en.pods) for en in results.existing_nodes) == 1
        assert len(results.new_node_claims) == 1

    def test_same_pvc_counted_once(self):
        # limit 1, both pods share one PVC → both fit the existing node
        env = volume_env(attach_limit=1)
        pods = [pvc_pod(env, "pvc-shared"), pvc_pod(env, "pvc-shared")]
        results = env.schedule(pods)
        assert not results.pod_errors
        assert sum(len(en.pods) for en in results.existing_nodes) == 2
        assert not results.new_node_claims

    def test_unlimited_driver_unconstrained(self):
        env = volume_env(attach_limit=None)
        pods = [pvc_pod(env, f"pvc-{i}") for i in range(4)]
        results = env.schedule(pods)
        assert not results.pod_errors
        assert not results.new_node_claims


class TestCSIMigration:
    """suite_test.go:3384 — in-tree provisioners count against the MIGRATED
    CSI driver's attach limits (volumeusage.py's plugin translation)."""

    def test_in_tree_provisioner_counts_against_migrated_driver(self):
        env = volume_env(
            attach_limit=1,
            provisioner="kubernetes.io/aws-ebs",
            csi_driver="ebs.csi.aws.com",
            node_name="mig-node-1",
            sc_name="in-tree-sc",
        )
        pods = [
            pvc_pod(env, "mig-a", sc_name="in-tree-sc"),
            pvc_pod(env, "mig-b", sc_name="in-tree-sc"),
        ]
        results = env.schedule(pods)
        assert not results.pod_errors
        # limit 1 on the migrated driver: exactly one pod fits the existing
        # node, the other overflows to a new claim
        on_node = [p for en in results.existing_nodes for p in en.pods]
        assert len(on_node) == 1
        assert len(results.new_node_claims) == 1
