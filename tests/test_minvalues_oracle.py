"""MinValues scheduling specs ported from the reference's MinValues context
(instance_selection_test.go:661-1578), run on BOTH solver paths — strict
minValues is fully supported on the device fast path (the distinct-value
count only shrinks as claims narrow, so device rejections stay monotone)."""

import pytest

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import Affinity, NodeAffinity, NodeSelectorTerm
from karpenter_tpu.cloudprovider.types import InstanceType, Offering, Offerings
from karpenter_tpu.ops.catalog import CatalogEngine
from karpenter_tpu.scheduling.requirements import Operator, Requirement, Requirements
from karpenter_tpu.utils.resources import parse_resource_list

from device_path import both_paths_fixture
from helpers import nodepool, unschedulable_pod
from test_scheduler import Env as HostEnv

Env = HostEnv
path = both_paths_fixture(globals())

# the reference's custom numeric key ("karpenter/numerical-value")
GEN_KEY = "karpenter/numerical-value"


def fake_it(name, cpu, price, arch="arm64", gen=None):
    """fake.NewInstanceType twin: one spot offering in test-zone-1, optional
    custom numeric-generation requirement."""
    rows = [
        Requirement(wk.LABEL_INSTANCE_TYPE, Operator.IN, [name]),
        Requirement(wk.LABEL_ARCH, Operator.IN, [arch]),
        Requirement(wk.LABEL_OS, Operator.IN, ["linux"]),
        Requirement(wk.LABEL_TOPOLOGY_ZONE, Operator.IN, ["test-zone-1"]),
        Requirement(
            wk.CAPACITY_TYPE_LABEL_KEY, Operator.IN, [wk.CAPACITY_TYPE_SPOT]
        ),
    ]
    if gen is not None:
        rows.append(Requirement(GEN_KEY, Operator.IN, [str(gen)]))
    return InstanceType(
        name=name,
        requirements=Requirements(*rows),
        offerings=Offerings(
            [
                Offering(
                    requirements=Requirements(
                        Requirement(
                            wk.CAPACITY_TYPE_LABEL_KEY,
                            Operator.IN,
                            [wk.CAPACITY_TYPE_SPOT],
                        ),
                        Requirement(
                            wk.LABEL_TOPOLOGY_ZONE, Operator.IN, ["test-zone-1"]
                        ),
                    ),
                    price=price,
                    available=True,
                )
            ]
        ),
        capacity=parse_resource_list(
            {"cpu": str(cpu), "memory": f"{cpu}Gi", "pods": "110"}
        ),
    )


def env_for(catalog, pools):
    kwargs = {"catalog": catalog, "node_pools": pools}
    if Env is not HostEnv:  # device leg: engine over the same custom catalog
        kwargs["engine"] = CatalogEngine(catalog)
    return Env(**kwargs)


def min_pool(*reqs):
    return [nodepool("default", requirements=list(reqs))]


def two_small_pods():
    return [
        unschedulable_pod(name=f"p-{i}", requests={"cpu": "0.9", "memory": "0.9Gi"})
        for i in range(2)
    ]


def expect_two_singleton_claims(results, min_options=2):
    assert not results.pod_errors
    assert len(results.new_node_claims) == 2
    for nc in results.new_node_claims:
        assert len(nc.pods) == 1
        assert len(nc.instance_type_options) >= min_options


class TestMinValues:
    def test_in_operator_forces_spread_across_claims(self, path):
        """instance_selection_test.go:662 — two pods that would pack onto the
        big type must split so every claim keeps >= minValues options."""
        catalog = [fake_it("instance-type-1", 1, 0.52), fake_it("instance-type-2", 4, 1.0)]
        pools = min_pool(
            {
                "key": wk.LABEL_INSTANCE_TYPE,
                "operator": "In",
                "values": ["instance-type-1", "instance-type-2"],
                "minValues": 2,
            }
        )
        results = env_for(catalog, pools).schedule(two_small_pods())
        expect_two_singleton_claims(results)

    def test_gt_operator(self, path):
        """instance_selection_test.go:739 — minValues with Gt."""
        catalog = [
            fake_it("instance-type-1", 1, 0.52, gen=2),
            fake_it("instance-type-2", 1, 1.0, gen=3),
            fake_it("instance-type-3", 4, 1.2, gen=4),
        ]
        pools = min_pool(
            {"key": GEN_KEY, "operator": "Gt", "values": ["2"], "minValues": 2}
        )
        results = env_for(catalog, pools).schedule(two_small_pods())
        expect_two_singleton_claims(results)

    def test_gt_operator_unsatisfied(self, path):
        """instance_selection_test.go:835 — pod Gt narrows to one type; the
        template's Exists minValues 2 fails with the host's message."""
        catalog = [
            fake_it("instance-type-1", 1, 0.52, gen=2),
            fake_it("instance-type-2", 4, 1.0, gen=3),
        ]
        pools = min_pool(
            {"key": GEN_KEY, "operator": "Exists", "minValues": 2}
        )
        pods = [
            unschedulable_pod(
                name=f"p-{i}",
                requests={"cpu": "0.9", "memory": "0.9Gi"},
                affinity=Affinity(
                    node_affinity=NodeAffinity(
                        required=[
                            NodeSelectorTerm(
                                match_expressions=[
                                    {
                                        "key": GEN_KEY,
                                        "operator": "Gt",
                                        "values": ["2"],
                                    }
                                ]
                            )
                        ]
                    )
                ),
            )
            for i in range(2)
        ]
        results = env_for(catalog, pools).schedule(pods)
        assert len(results.pod_errors) == 2
        for err in results.pod_errors.values():
            assert "minValues requirement is not met for label(s)" in str(err)
            assert GEN_KEY in str(err)

    def test_lt_operator(self, path):
        """instance_selection_test.go:924 — minValues with Lt."""
        catalog = [
            fake_it("instance-type-1", 1, 0.52, gen=2),
            fake_it("instance-type-2", 2, 1.0, gen=3),
            fake_it("instance-type-3", 4, 1.2, gen=4),
        ]
        pools = min_pool(
            {"key": GEN_KEY, "operator": "Lt", "values": ["4"], "minValues": 2}
        )
        results = env_for(catalog, pools).schedule(two_small_pods())
        expect_two_singleton_claims(results)

    def test_lt_operator_unsatisfied(self, path):
        """instance_selection_test.go:1019 — Lt leaves one compatible type;
        minValues 2 drops the template at construction, so no nodepool can
        host the pods."""
        catalog = [
            fake_it("instance-type-1", 2, 0.52, gen=2),
            fake_it("instance-type-2", 4, 1.2, gen=4),
        ]
        pools = min_pool(
            {"key": GEN_KEY, "operator": "Lt", "values": ["4"], "minValues": 2}
        )
        results = env_for(catalog, pools).schedule(two_small_pods())
        assert len(results.pod_errors) == 2

    def test_max_of_in_and_notin(self, path):
        """instance_selection_test.go:1090 — same key via In (minValues 1)
        and NotIn (minValues 2): the stricter count wins."""
        catalog = [
            fake_it("instance-type-1", 1, 0.52),
            fake_it("instance-type-2", 2, 1.0),
            fake_it("instance-type-3", 4, 1.2),
        ]
        pools = min_pool(
            {
                "key": wk.LABEL_INSTANCE_TYPE,
                "operator": "In",
                "values": ["instance-type-1", "instance-type-2", "instance-type-3"],
                "minValues": 1,
            },
            {
                "key": wk.LABEL_INSTANCE_TYPE,
                "operator": "NotIn",
                "values": ["instance-type-3"],
                "minValues": 2,
            },
        )
        results = env_for(catalog, pools).schedule(two_small_pods())
        expect_two_singleton_claims(results)

    def test_max_of_gt_and_lt(self, path):
        """instance_selection_test.go:1190 — Gt minValues 1 + Lt minValues 2
        on the numeric key: max applies over the window (3, 5)."""
        catalog = [
            fake_it("instance-type-1", 1, 0.52, gen=2),
            fake_it("instance-type-2", 1, 1.0, gen=3),
            fake_it("instance-type-3", 4, 1.2, gen=4),
            fake_it("instance-type-4", 4, 1.2, gen=5),
        ]
        pools = min_pool(
            {"key": GEN_KEY, "operator": "Gt", "values": ["2"], "minValues": 1},
            {"key": GEN_KEY, "operator": "Lt", "values": ["5"], "minValues": 2},
        )
        results = env_for(catalog, pools).schedule(two_small_pods())
        expect_two_singleton_claims(results)

    def test_fails_when_catalog_smaller_than_min(self, path):
        """instance_selection_test.go:1309 — minValues 11 over a 10-type
        catalog can never be satisfied."""
        catalog = [fake_it(f"instance-type-{i}", 1, 0.5 + i * 0.01) for i in range(10)]
        pools = min_pool(
            {"key": wk.LABEL_INSTANCE_TYPE, "operator": "Exists", "minValues": 11}
        )
        results = env_for(catalog, pools).schedule(
            [unschedulable_pod(name="p-0", requests={"cpu": "0.5"})]
        )
        assert len(results.pod_errors) == 1

    def test_fails_after_truncation(self, path):
        """instance_selection_test.go:1337 — the solve satisfies minValues
        but launch-time truncation to 1 option breaks it; the claim is
        rejected and its pods error."""
        catalog = [fake_it("instance-type-1", 1, 0.52), fake_it("instance-type-2", 4, 1.0)]
        pools = min_pool(
            {
                "key": wk.LABEL_INSTANCE_TYPE,
                "operator": "In",
                "values": ["instance-type-1", "instance-type-2"],
                "minValues": 2,
            }
        )
        results = env_for(catalog, pools).schedule(two_small_pods())
        assert not results.pod_errors
        results.truncate_instance_types(max_items=1)
        assert not results.new_node_claims
        assert len(results.pod_errors) == 2
        for err in results.pod_errors.values():
            assert "couldn't meet minValues requirements" in str(err)

    def test_max_of_multiple_operators_same_key(self, path):
        """instance_selection_test.go:1412 — Exists minValues 1 + In
        minValues 2 on instance-type: the max (2) applies."""
        catalog = [fake_it("instance-type-1", 1, 0.52), fake_it("instance-type-2", 4, 1.0)]
        pools = min_pool(
            {"key": wk.LABEL_INSTANCE_TYPE, "operator": "Exists", "minValues": 1},
            {
                "key": wk.LABEL_INSTANCE_TYPE,
                "operator": "In",
                "values": ["instance-type-1", "instance-type-2"],
                "minValues": 2,
            },
        )
        results = env_for(catalog, pools).schedule(two_small_pods())
        expect_two_singleton_claims(results)

    def test_multiple_requirement_keys(self, path):
        """instance_selection_test.go:1497 — arch Exists minValues 2 +
        instance-type minValues 1: joining the second pod would collapse the
        arch diversity to one, forcing a second claim."""
        catalog = [
            fake_it("instance-type-1", 1, 0.52, arch="arm64"),
            fake_it("instance-type-2", 4, 1.0, arch="amd64"),
        ]
        pools = min_pool(
            {"key": wk.LABEL_ARCH, "operator": "Exists", "minValues": 2},
            {
                "key": wk.LABEL_INSTANCE_TYPE,
                "operator": "In",
                "values": ["instance-type-1", "instance-type-2"],
                "minValues": 1,
            },
        )
        results = env_for(catalog, pools).schedule(two_small_pods())
        expect_two_singleton_claims(results)

    def test_best_effort_nodeclaim_spec_carries_relaxation(self, path):
        """provisioning/suite_test.go:2688 — under BestEffort the launched
        NodeClaim's spec carries the NARROWED instance-type values with the
        relaxed (achievable) minValues, and the relaxed annotation. Runs
        through the REAL Provisioner on both paths (the device leg pins
        DEVICE_MIN_PODS=1 via the fixture; create-time limits recheck and
        truncation run against the device-solved claims)."""
        from karpenter_tpu.ops import ffd as ffd_mod
        from karpenter_tpu.scheduling.requirements import requirements_from_dicts

        from helpers import make_provisioner_harness, nodepool, unschedulable_pod
        from karpenter_tpu.operator.options import Options

        catalog = [fake_it("instance-type-1", 1, 0.52), fake_it("instance-type-2", 4, 1.0)]
        clock, store, provider, cluster, informer, prov = make_provisioner_harness(
            options=Options(min_values_policy="BestEffort"),
            instance_types=catalog,
        )
        solves0 = ffd_mod.DEVICE_SOLVES
        store.create(
            nodepool(
                "default",
                requirements=[
                    {
                        "key": wk.LABEL_INSTANCE_TYPE,
                        "operator": "In",
                        "values": [
                            "instance-type-1",
                            "instance-type-2",
                            "instance-type-3",
                        ],
                        "minValues": 3,
                    }
                ],
            )
        )
        pod = unschedulable_pod(requests={"cpu": "0.5"})
        store.create(pod)
        informer.flush()
        prov.trigger(pod.metadata.uid)
        clock.step(1.5)
        assert prov.reconcile() is not None
        [claim] = store.list("NodeClaim")
        assert (
            claim.metadata.annotations[
                wk.NODECLAIM_MIN_VALUES_RELAXED_ANNOTATION_KEY
            ]
            == "true"
        )
        reqs = requirements_from_dicts(claim.spec.requirements)
        row = reqs.get(wk.LABEL_INSTANCE_TYPE)
        assert set(row.values_list()) == {"instance-type-1", "instance-type-2"}
        assert row.min_values == 2
        if path == "device":
            assert ffd_mod.DEVICE_SOLVES > solves0, "device path did not run"

    def test_best_effort_relaxes_before_falling_back_to_other_nodepools(self, path):
        """provisioning/suite_test.go:2758 — the high-weight pool relaxes its
        minValues rather than ceding the pod to a lower-weight pool; both
        solver paths, through the real Provisioner."""
        from karpenter_tpu.ops import ffd as ffd_mod

        from helpers import make_provisioner_harness, nodepool, unschedulable_pod
        from karpenter_tpu.operator.options import Options

        catalog = [fake_it("instance-type-1", 1, 0.52), fake_it("instance-type-2", 4, 1.0)]
        clock, store, provider, cluster, informer, prov = make_provisioner_harness(
            options=Options(min_values_policy="BestEffort"),
            instance_types=catalog,
        )
        solves0 = ffd_mod.DEVICE_SOLVES
        heavy = nodepool(
            "heavy",
            requirements=[
                {
                    "key": wk.LABEL_INSTANCE_TYPE,
                    "operator": "In",
                    "values": [
                        "instance-type-1",
                        "instance-type-2",
                        "instance-type-3",
                    ],
                    "minValues": 3,
                }
            ],
            weight=100,
        )
        light = nodepool("light", weight=10)
        store.create(heavy)
        store.create(light)
        pod = unschedulable_pod(requests={"cpu": "0.5"})
        store.create(pod)
        informer.flush()
        prov.trigger(pod.metadata.uid)
        clock.step(1.5)
        assert prov.reconcile() is not None
        [claim] = store.list("NodeClaim")
        assert claim.metadata.labels[wk.NODEPOOL_LABEL_KEY] == "heavy"
        assert (
            claim.metadata.annotations[
                wk.NODECLAIM_MIN_VALUES_RELAXED_ANNOTATION_KEY
            ]
            == "true"
        )
        if path == "device":
            assert ffd_mod.DEVICE_SOLVES > solves0, "device path did not run"

    def test_strict_falls_back_to_other_nodepools(self, path):
        """Strict policy: the minValues pool is unusable (template dropped),
        so the pod lands on the lower-weight pool instead; both solver
        paths, through the real Provisioner."""
        from karpenter_tpu.ops import ffd as ffd_mod

        from helpers import make_provisioner_harness, nodepool, unschedulable_pod

        catalog = [fake_it("instance-type-1", 1, 0.52), fake_it("instance-type-2", 4, 1.0)]
        clock, store, provider, cluster, informer, prov = make_provisioner_harness(
            instance_types=catalog,
        )
        solves0 = ffd_mod.DEVICE_SOLVES
        heavy = nodepool(
            "heavy",
            requirements=[
                {
                    "key": wk.LABEL_INSTANCE_TYPE,
                    "operator": "Exists",
                    "minValues": 3,
                }
            ],
            weight=100,
        )
        light = nodepool("light", weight=10)
        store.create(heavy)
        store.create(light)
        pod = unschedulable_pod(requests={"cpu": "0.5"})
        store.create(pod)
        informer.flush()
        prov.trigger(pod.metadata.uid)
        clock.step(1.5)
        assert prov.reconcile() is not None
        [claim] = store.list("NodeClaim")
        assert claim.metadata.labels[wk.NODEPOOL_LABEL_KEY] == "light"
        if path == "device":
            assert ffd_mod.DEVICE_SOLVES > solves0, "device path did not run"

    def test_best_effort_policy_relaxes_on_both_paths(self, path):
        """BestEffort minValues relaxation (nodeclaim.go:425-436) runs on the
        device path: the open-time write-down lands in per-claim specs, so a
        catalog with fewer types than the minimum schedules anyway, with the
        claim annotated relaxed and its requirement recording the achievable
        count — identically on host and device."""
        from karpenter_tpu.scheduler.scheduler import MIN_VALUES_POLICY_BEST_EFFORT

        catalog = [fake_it("instance-type-1", 1, 0.52), fake_it("instance-type-2", 4, 1.0)]
        pools = min_pool(
            {"key": wk.LABEL_INSTANCE_TYPE, "operator": "Exists", "minValues": 3}
        )
        kwargs = {
            "catalog": catalog,
            "node_pools": pools,
            "min_values_policy": MIN_VALUES_POLICY_BEST_EFFORT,
        }
        if Env is not HostEnv:
            kwargs["engine"] = CatalogEngine(catalog)
        pods = [unschedulable_pod(name="p-0", requests={"cpu": "0.5"})]
        results = Env(**kwargs).schedule(pods)
        assert not results.pod_errors
        [nc] = results.new_node_claims
        assert len(nc.instance_type_options) == 2
        assert (
            nc.annotations[wk.NODECLAIM_MIN_VALUES_RELAXED_ANNOTATION_KEY] == "true"
        )
        # the relaxed requirement records the achievable count
        assert nc.requirements.get(wk.LABEL_INSTANCE_TYPE).min_values == 2

    def test_best_effort_join_gates_on_relaxed_value(self, path):
        """After open-time relaxation the claim's joins gate on the RELAXED
        count: a later pod whose requirements would narrow the claim below
        the achievable-at-open diversity opens a second claim instead of
        joining (host can_add passes relax=False on joins)."""
        from karpenter_tpu.scheduler.scheduler import MIN_VALUES_POLICY_BEST_EFFORT

        catalog = [
            fake_it("instance-type-1", 16, 0.52, arch="arm64"),
            fake_it("instance-type-2", 16, 1.0, arch="amd64"),
        ]
        pools = min_pool(
            {"key": wk.LABEL_INSTANCE_TYPE, "operator": "Exists", "minValues": 3}
        )
        kwargs = {
            "catalog": catalog,
            "node_pools": pools,
            "min_values_policy": MIN_VALUES_POLICY_BEST_EFFORT,
        }
        if Env is not HostEnv:
            kwargs["engine"] = CatalogEngine(catalog)
        pods = [
            unschedulable_pod(name="p-0", requests={"cpu": "1"}),
            # node-selects arm64: joining p-0's claim would leave 1 < 2
            unschedulable_pod(
                name="p-1",
                requests={"cpu": "0.5"},
                node_selector={wk.LABEL_ARCH: "arm64"},
            ),
        ]
        results = Env(**kwargs).schedule(pods)
        assert not results.pod_errors
        assert len(results.new_node_claims) == 2
        by_pod = {
            nc.pods[0].metadata.name: nc for nc in results.new_node_claims
        }
        # p-0's claim kept both types (relaxed 3 -> 2)
        assert len(by_pod["p-0"].instance_type_options) == 2
        assert by_pod["p-0"].requirements.get(wk.LABEL_INSTANCE_TYPE).min_values == 2
        # p-1's own claim relaxed down to its single compatible type
        assert len(by_pod["p-1"].instance_type_options) == 1
        assert by_pod["p-1"].requirements.get(wk.LABEL_INSTANCE_TYPE).min_values == 1
        for nc in results.new_node_claims:
            assert (
                nc.annotations[wk.NODECLAIM_MIN_VALUES_RELAXED_ANNOTATION_KEY]
                == "true"
            )

    def test_best_effort_relaxes_before_falling_back_to_other_pools(self, path):
        """provisioning suite 'should relax minValues before falling back to
        other nodepools': the higher-weight pool relaxes its minValues and
        WINS — the solver must not skip to a lower-weight pool that would
        satisfy without relaxation."""
        from karpenter_tpu.scheduler.scheduler import MIN_VALUES_POLICY_BEST_EFFORT

        catalog = [fake_it("instance-type-1", 4, 0.52), fake_it("instance-type-2", 4, 0.52)]
        pools = [
            nodepool(
                "default",
                requirements=[
                    {
                        "key": wk.LABEL_INSTANCE_TYPE,
                        "operator": "In",
                        "values": [
                            "instance-type-1", "instance-type-2", "instance-type-3",
                        ],
                        "minValues": 3,
                    }
                ],
                weight=100,
            ),
            nodepool(
                "no-min-values",
                requirements=[
                    {
                        "key": wk.LABEL_INSTANCE_TYPE,
                        "operator": "In",
                        "values": [
                            "instance-type-1", "instance-type-2", "instance-type-3",
                        ],
                    }
                ],
                weight=10,
            ),
        ]
        kwargs = {
            "catalog": catalog,
            "node_pools": pools,
            "min_values_policy": MIN_VALUES_POLICY_BEST_EFFORT,
        }
        if Env is not HostEnv:
            kwargs["engine"] = CatalogEngine(catalog)
        results = Env(**kwargs).schedule(
            [unschedulable_pod(name="p-0", requests={"cpu": "0.9", "memory": "0.9Gi"})]
        )
        assert not results.pod_errors
        [nc] = results.new_node_claims
        assert nc.nodepool_name == "default"
        assert (
            nc.annotations[wk.NODECLAIM_MIN_VALUES_RELAXED_ANNOTATION_KEY] == "true"
        )
        assert nc.requirements.get(wk.LABEL_INSTANCE_TYPE).min_values == 2
        assert len(nc.instance_type_options) == 2

    def test_best_effort_higher_weight_pool_wins_when_both_relax(self, path):
        """provisioning suite 'should choose nodepool with higher weight when
        relaxing minValues': both pools need relaxation; weight order
        decides."""
        from karpenter_tpu.scheduler.scheduler import MIN_VALUES_POLICY_BEST_EFFORT

        catalog = [fake_it("instance-type-1", 4, 0.52), fake_it("instance-type-2", 4, 0.52)]
        min_req = {
            "key": wk.LABEL_INSTANCE_TYPE,
            "operator": "In",
            "values": ["instance-type-1", "instance-type-2", "instance-type-3"],
            "minValues": 3,
        }
        # deliberately listed lowest-weight first: the harness must order by
        # weight like the provisioner, or this assertion is vacuous
        pools = [
            nodepool("lower-weight", requirements=[dict(min_req)], weight=10),
            nodepool("default", requirements=[dict(min_req)], weight=100),
        ]
        kwargs = {
            "catalog": catalog,
            "node_pools": pools,
            "min_values_policy": MIN_VALUES_POLICY_BEST_EFFORT,
        }
        if Env is not HostEnv:
            kwargs["engine"] = CatalogEngine(catalog)
        results = Env(**kwargs).schedule(
            [unschedulable_pod(name="p-0", requests={"cpu": "0.9", "memory": "0.9Gi"})]
        )
        assert not results.pod_errors
        [nc] = results.new_node_claims
        assert nc.nodepool_name == "default"
        assert (
            nc.annotations[wk.NODECLAIM_MIN_VALUES_RELAXED_ANNOTATION_KEY] == "true"
        )

    def test_best_effort_satisfiable_keeps_strict_semantics(self, path):
        """When the catalog satisfies minValues, BestEffort must behave
        exactly like Strict: no relaxation, annotation false, original
        min_values preserved."""
        from karpenter_tpu.scheduler.scheduler import MIN_VALUES_POLICY_BEST_EFFORT

        catalog = [
            fake_it("instance-type-1", 1, 0.52),
            fake_it("instance-type-2", 4, 1.0),
            fake_it("instance-type-3", 8, 2.0),
        ]
        pools = min_pool(
            {"key": wk.LABEL_INSTANCE_TYPE, "operator": "Exists", "minValues": 3}
        )
        kwargs = {
            "catalog": catalog,
            "node_pools": pools,
            "min_values_policy": MIN_VALUES_POLICY_BEST_EFFORT,
        }
        if Env is not HostEnv:
            kwargs["engine"] = CatalogEngine(catalog)
        pods = [unschedulable_pod(name="p-0", requests={"cpu": "0.5"})]
        results = Env(**kwargs).schedule(pods)
        assert not results.pod_errors
        [nc] = results.new_node_claims
        assert len(nc.instance_type_options) == 3
        assert (
            nc.annotations[wk.NODECLAIM_MIN_VALUES_RELAXED_ANNOTATION_KEY] == "false"
        )
        assert nc.requirements.get(wk.LABEL_INSTANCE_TYPE).min_values == 3
