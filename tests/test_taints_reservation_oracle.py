"""Taint/toleration scheduling specs (topology_test.go:2996-3060) and
ReservationManager unit specs (reservationmanager_test.go:112-210), both
run against the host and device paths where eligible."""

import pytest

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import Taint, Toleration
from karpenter_tpu.scheduler.reservationmanager import ReservationManager

from helpers import nodepool, unschedulable_pod
from test_reserved_and_deleting import reserved_catalog
from test_scheduling_oracle import path, schedule  # noqa: F401 — fixture


def tainted_pool(taints=(), startup_taints=()):
    pool = nodepool("default", taints=taints)
    pool.spec.template.spec.startup_taints = list(startup_taints)
    return pool


class TestTaints:
    """topology_test.go:2996-3060."""

    def test_taint_nodes_with_nodepool_taints(self, path):
        taint = Taint(key="test", value="bar", effect="NoSchedule")
        pod = unschedulable_pod(
            tolerations=[Toleration(operator="Exists", effect="NoSchedule")]
        )
        results = schedule(path, [pod], node_pools=[tainted_pool(taints=[taint])])
        assert not results.pod_errors
        [nc] = results.new_node_claims
        assert any(
            t.key == "test" and t.value == "bar" for t in nc.template.spec.taints
        )

    def test_schedule_pods_that_tolerate_nodepool_constraints(self, path):
        taint = Taint(key="test-key", value="test-value", effect="NoSchedule")
        pools = [tainted_pool(taints=[taint])]
        tolerating = [
            unschedulable_pod(
                tolerations=[
                    Toleration(key="test-key", operator="Exists", effect="NoSchedule")
                ]
            ),
            unschedulable_pod(
                tolerations=[
                    Toleration(
                        key="test-key",
                        value="test-value",
                        operator="Equal",
                        effect="NoSchedule",
                    )
                ]
            ),
        ]
        results = schedule(path, tolerating, node_pools=pools)
        assert not results.pod_errors

        not_tolerating = [
            unschedulable_pod(),  # missing toleration
            unschedulable_pod(
                tolerations=[Toleration(key="invalid", operator="Exists")]
            ),  # key mismatch
            unschedulable_pod(
                tolerations=[
                    Toleration(key="test-key", operator="Equal", effect="NoSchedule")
                ]
            ),  # value mismatch
        ]
        results = schedule(path, not_tolerating, node_pools=pools)
        assert len(results.pod_errors) == 3

    def test_startup_taints_do_not_block_scheduling(self, path):
        startup = Taint(key="ignore-me", value="nothing-to-see-here", effect="NoSchedule")
        results = schedule(
            path,
            [unschedulable_pod()],
            node_pools=[tainted_pool(startup_taints=[startup])],
        )
        assert not results.pod_errors


class TestReservationManager:
    """reservationmanager_test.go:112-210."""

    def _manager(self, capacity=2):
        return ReservationManager(
            {"default": reserved_catalog(reservation_capacity=capacity)}
        )

    def _offering(self, capacity=2):
        return reserved_catalog(reservation_capacity=capacity)[0].offerings[1]

    def test_can_reserve_when_capacity_available(self):
        manager = self._manager(capacity=1)
        assert manager.can_reserve("host-a", self._offering())

    def test_can_reserve_when_hostname_holds_reservation(self):
        manager = self._manager(capacity=1)
        offering = self._offering()
        manager.reserve("host-a", offering)
        assert manager.can_reserve("host-a", offering)

    def test_cannot_reserve_when_exhausted(self):
        manager = self._manager(capacity=1)
        offering = self._offering()
        manager.reserve("host-a", offering)
        assert not manager.can_reserve("host-b", offering)

    def test_existing_hostname_ok_even_when_exhausted(self):
        manager = self._manager(capacity=1)
        offering = self._offering()
        manager.reserve("host-a", offering)
        # host-a already holds it: idempotently reservable
        assert manager.can_reserve("host-a", offering)

    def test_unknown_reservation_id_raises(self):
        manager = self._manager()
        from karpenter_tpu.cloudprovider.types import (
            Offering,
            RESERVATION_ID_LABEL,
        )
        from karpenter_tpu.scheduling.requirements import (
            Operator,
            Requirement,
            Requirements,
        )

        ghost = Offering(
            requirements=Requirements(
                Requirement(
                    wk.CAPACITY_TYPE_LABEL_KEY,
                    Operator.IN,
                    [wk.CAPACITY_TYPE_RESERVED],
                ),
                Requirement(RESERVATION_ID_LABEL, Operator.IN, ["cr-ghost"]),
            ),
            price=0.1,
        )
        with pytest.raises(KeyError):
            manager.can_reserve("host-a", ghost)

    def test_reserve_decrements_capacity(self):
        manager = self._manager(capacity=2)
        offering = self._offering()
        manager.reserve("host-a", offering)
        assert manager.remaining_capacity(offering) == 1
        manager.reserve("host-b", offering)
        assert manager.remaining_capacity(offering) == 0

    def test_no_double_reserve_same_hostname(self):
        manager = self._manager(capacity=2)
        offering = self._offering()
        manager.reserve("host-a", offering)
        manager.reserve("host-a", offering)
        assert manager.remaining_capacity(offering) == 1

    def test_release_restores_capacity(self):
        manager = self._manager(capacity=1)
        offering = self._offering()
        manager.reserve("host-a", offering)
        assert manager.remaining_capacity(offering) == 0
        manager.release("host-a", offering)
        assert manager.remaining_capacity(offering) == 1
        # releasing a hostname without the reservation is a no-op
        manager.release("host-b", offering)
        assert manager.remaining_capacity(offering) == 1


class TestReservationManagerBatches:
    """reservationmanager_test.go:194-350 — multi-offering calls, partial
    releases, over-reserve panics, and mixed-operation consistency."""

    @staticmethod
    def _multi_offerings(n=3, capacity=2):
        from karpenter_tpu.cloudprovider.types import (
            RESERVATION_ID_LABEL,
            InstanceType,
            Offering,
            Offerings,
        )
        from karpenter_tpu.scheduling.requirements import (
            Operator,
            Requirement,
            Requirements,
        )
        from karpenter_tpu.utils.resources import parse_resource_list

        offs = [
            Offering(
                requirements=Requirements(
                    Requirement(
                        wk.CAPACITY_TYPE_LABEL_KEY,
                        Operator.IN,
                        [wk.CAPACITY_TYPE_RESERVED],
                    ),
                    Requirement(wk.LABEL_TOPOLOGY_ZONE, Operator.IN, ["kwok-zone-1"]),
                    Requirement(RESERVATION_ID_LABEL, Operator.IN, [f"cr-{i}"]),
                ),
                price=0.1,
                available=True,
                reservation_capacity=capacity,
            )
            for i in range(n)
        ]
        it = InstanceType(
            name="multi-res",
            requirements=Requirements(
                Requirement(wk.LABEL_INSTANCE_TYPE, Operator.IN, ["multi-res"]),
            ),
            offerings=Offerings(offs),
            capacity=parse_resource_list({"cpu": "4", "memory": "16Gi"}),
        )
        manager = ReservationManager({"default": [it]})
        return manager, offs

    def test_multiple_offerings_single_reserve_call(self):
        manager, offs = self._multi_offerings()
        manager.reserve("host-a", *offs)
        for o in offs:
            assert manager.has_reservation("host-a", o)
            assert manager.remaining_capacity(o) == 1

    def test_mixed_new_and_existing_reservations(self):
        manager, offs = self._multi_offerings()
        manager.reserve("host-a", offs[0])
        manager.reserve("host-a", *offs)  # offs[0] held, others new
        assert manager.remaining_capacity(offs[0]) == 1
        assert manager.remaining_capacity(offs[1]) == 1
        assert manager.remaining_capacity(offs[2]) == 1

    def test_over_reserve_raises(self):
        manager, offs = self._multi_offerings(n=1, capacity=1)
        manager.reserve("host-a", offs[0])
        with pytest.raises(Exception):
            manager.reserve("host-b", offs[0])

    def test_partial_release(self):
        manager, offs = self._multi_offerings()
        manager.reserve("host-a", *offs)
        manager.release("host-a", offs[0], offs[1])
        assert not manager.has_reservation("host-a", offs[0])
        assert not manager.has_reservation("host-a", offs[1])
        assert manager.has_reservation("host-a", offs[2])
        assert manager.remaining_capacity(offs[0]) == 2
        assert manager.remaining_capacity(offs[2]) == 1

    def test_release_multiple_offerings_single_call(self):
        manager, offs = self._multi_offerings()
        manager.reserve("host-a", *offs)
        manager.release("host-a", *offs)
        for o in offs:
            assert manager.remaining_capacity(o) == 2

    def test_reserve_release_cycles_track_capacity(self):
        manager, offs = self._multi_offerings(n=1, capacity=2)
        o = offs[0]
        for cycle in range(5):
            manager.reserve(f"host-{cycle}", o)
            assert manager.remaining_capacity(o) == 1
            manager.release(f"host-{cycle}", o)
            assert manager.remaining_capacity(o) == 2

    def test_mixed_operations_stay_consistent(self):
        """reservationmanager_test.go:331-350 — interleaved reserves and
        releases across hosts never drift the counters."""
        manager, offs = self._multi_offerings(n=2, capacity=3)
        a, b = offs
        manager.reserve("h1", a)
        manager.reserve("h2", a, b)
        manager.reserve("h3", b)
        assert manager.remaining_capacity(a) == 1
        assert manager.remaining_capacity(b) == 1
        manager.release("h2", a)
        manager.reserve("h4", a)
        manager.release("h1", a)
        manager.release("h3", b)
        assert manager.remaining_capacity(a) == 2
        assert manager.remaining_capacity(b) == 2
        assert manager.has_reservation("h4", a)
        assert manager.has_reservation("h2", b)
