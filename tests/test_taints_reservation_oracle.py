"""Taint/toleration scheduling specs (topology_test.go:2996-3060) and
ReservationManager unit specs (reservationmanager_test.go:112-210), both
run against the host and device paths where eligible."""

import pytest

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import Taint, Toleration
from karpenter_tpu.scheduler.reservationmanager import ReservationManager

from helpers import nodepool, unschedulable_pod
from test_reserved_and_deleting import reserved_catalog
from test_scheduling_oracle import path, schedule  # noqa: F401 — fixture


def tainted_pool(taints=(), startup_taints=()):
    pool = nodepool("default", taints=taints)
    pool.spec.template.spec.startup_taints = list(startup_taints)
    return pool


class TestTaints:
    """topology_test.go:2996-3060."""

    def test_taint_nodes_with_nodepool_taints(self, path):
        taint = Taint(key="test", value="bar", effect="NoSchedule")
        pod = unschedulable_pod(
            tolerations=[Toleration(operator="Exists", effect="NoSchedule")]
        )
        results = schedule(path, [pod], node_pools=[tainted_pool(taints=[taint])])
        assert not results.pod_errors
        [nc] = results.new_node_claims
        assert any(
            t.key == "test" and t.value == "bar" for t in nc.template.spec.taints
        )

    def test_schedule_pods_that_tolerate_nodepool_constraints(self, path):
        taint = Taint(key="test-key", value="test-value", effect="NoSchedule")
        pools = [tainted_pool(taints=[taint])]
        tolerating = [
            unschedulable_pod(
                tolerations=[
                    Toleration(key="test-key", operator="Exists", effect="NoSchedule")
                ]
            ),
            unschedulable_pod(
                tolerations=[
                    Toleration(
                        key="test-key",
                        value="test-value",
                        operator="Equal",
                        effect="NoSchedule",
                    )
                ]
            ),
        ]
        results = schedule(path, tolerating, node_pools=pools)
        assert not results.pod_errors

        not_tolerating = [
            unschedulable_pod(),  # missing toleration
            unschedulable_pod(
                tolerations=[Toleration(key="invalid", operator="Exists")]
            ),  # key mismatch
            unschedulable_pod(
                tolerations=[
                    Toleration(key="test-key", operator="Equal", effect="NoSchedule")
                ]
            ),  # value mismatch
        ]
        results = schedule(path, not_tolerating, node_pools=pools)
        assert len(results.pod_errors) == 3

    def test_startup_taints_do_not_block_scheduling(self, path):
        startup = Taint(key="ignore-me", value="nothing-to-see-here", effect="NoSchedule")
        results = schedule(
            path,
            [unschedulable_pod()],
            node_pools=[tainted_pool(startup_taints=[startup])],
        )
        assert not results.pod_errors


class TestReservationManager:
    """reservationmanager_test.go:112-210."""

    def _manager(self, capacity=2):
        return ReservationManager(
            {"default": reserved_catalog(reservation_capacity=capacity)}
        )

    def _offering(self, capacity=2):
        return reserved_catalog(reservation_capacity=capacity)[0].offerings[1]

    def test_can_reserve_when_capacity_available(self):
        manager = self._manager(capacity=1)
        assert manager.can_reserve("host-a", self._offering())

    def test_can_reserve_when_hostname_holds_reservation(self):
        manager = self._manager(capacity=1)
        offering = self._offering()
        manager.reserve("host-a", offering)
        assert manager.can_reserve("host-a", offering)

    def test_cannot_reserve_when_exhausted(self):
        manager = self._manager(capacity=1)
        offering = self._offering()
        manager.reserve("host-a", offering)
        assert not manager.can_reserve("host-b", offering)

    def test_existing_hostname_ok_even_when_exhausted(self):
        manager = self._manager(capacity=1)
        offering = self._offering()
        manager.reserve("host-a", offering)
        # host-a already holds it: idempotently reservable
        assert manager.can_reserve("host-a", offering)

    def test_unknown_reservation_id_raises(self):
        manager = self._manager()
        from karpenter_tpu.cloudprovider.types import (
            Offering,
            RESERVATION_ID_LABEL,
        )
        from karpenter_tpu.scheduling.requirements import (
            Operator,
            Requirement,
            Requirements,
        )

        ghost = Offering(
            requirements=Requirements(
                Requirement(
                    wk.CAPACITY_TYPE_LABEL_KEY,
                    Operator.IN,
                    [wk.CAPACITY_TYPE_RESERVED],
                ),
                Requirement(RESERVATION_ID_LABEL, Operator.IN, ["cr-ghost"]),
            ),
            price=0.1,
        )
        with pytest.raises(KeyError):
            manager.can_reserve("host-a", ghost)

    def test_reserve_decrements_capacity(self):
        manager = self._manager(capacity=2)
        offering = self._offering()
        manager.reserve("host-a", offering)
        assert manager.remaining_capacity(offering) == 1
        manager.reserve("host-b", offering)
        assert manager.remaining_capacity(offering) == 0

    def test_no_double_reserve_same_hostname(self):
        manager = self._manager(capacity=2)
        offering = self._offering()
        manager.reserve("host-a", offering)
        manager.reserve("host-a", offering)
        assert manager.remaining_capacity(offering) == 1

    def test_release_restores_capacity(self):
        manager = self._manager(capacity=1)
        offering = self._offering()
        manager.reserve("host-a", offering)
        assert manager.remaining_capacity(offering) == 0
        manager.release("host-a", offering)
        assert manager.remaining_capacity(offering) == 1
        # releasing a hostname without the reservation is a no-op
        manager.release("host-b", offering)
        assert manager.remaining_capacity(offering) == 1
