"""Cluster state + StateNode, mirroring reference pkg/controllers/state
suite behaviors."""

import pytest

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import (
    Condition,
    Container,
    DaemonSet,
    Node,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    OwnerReference,
    Pod,
    PodSpec,
    Taint,
)
from karpenter_tpu.apis.nodeclaim import (
    CONDITION_INSTANCE_TERMINATING,
    NodeClaim,
    NodeClaimStatus,
)
from karpenter_tpu.runtime.store import Store
from karpenter_tpu.state.cluster import NODE_RESOURCE, Cluster
from karpenter_tpu.state.informer import StateInformer
from karpenter_tpu.state.statenode import PodBlockEvictionError, StateNode
from karpenter_tpu.utils.clock import FakeClock
from karpenter_tpu.utils.pdb import Limits


@pytest.fixture
def env():
    clock = FakeClock()
    store = Store(clock=clock)
    cluster = Cluster(clock, store, cloud_provider=None)
    informer = StateInformer(store, cluster)
    return clock, store, cluster, informer


def make_node(name="node-1", pid=None, pool="default-pool", registered=True, initialized=True):
    labels = {wk.NODEPOOL_LABEL_KEY: pool, wk.LABEL_INSTANCE_TYPE: "t-2-8"}
    if registered:
        labels[wk.NODE_REGISTERED_LABEL_KEY] = "true"
    if initialized:
        labels[wk.NODE_INITIALIZED_LABEL_KEY] = "true"
    return Node(
        metadata=ObjectMeta(name=name, labels=labels),
        spec=NodeSpec(provider_id=pid or f"kwok://{name}"),
        status=NodeStatus(
            capacity={"cpu": 4.0, "memory": 8.0 * 2**30, "pods": 110.0},
            allocatable={"cpu": 3.8, "memory": 7.5 * 2**30, "pods": 110.0},
        ),
    )


def make_claim(name="claim-1", pid="kwok://node-1", pool="default-pool"):
    nc = NodeClaim(metadata=ObjectMeta(name=name, labels={wk.NODEPOOL_LABEL_KEY: pool}))
    nc.status.provider_id = pid
    nc.status.capacity = {"cpu": 4.0, "memory": 8.0 * 2**30}
    nc.status.allocatable = {"cpu": 3.8, "memory": 7.5 * 2**30}
    return nc


def bound_pod(name, node_name, cpu=1.0):
    return Pod(
        metadata=ObjectMeta(name=name),
        spec=PodSpec(node_name=node_name, containers=[Container(requests={"cpu": cpu})]),
    )


class TestClusterIngestion:
    def test_node_then_pods_tracked(self, env):
        clock, store, cluster, informer = env
        store.create(make_node())
        store.create(bound_pod("p1", "node-1", cpu=1.0))
        store.create(bound_pod("p2", "node-1", cpu=0.5))
        informer.flush()
        [n] = cluster.state_nodes()
        assert n.total_pod_requests()["cpu"] == pytest.approx(1.5)
        assert n.available()["cpu"] == pytest.approx(3.8 - 1.5)

    def test_claim_then_node_merge(self, env):
        clock, store, cluster, informer = env
        store.create(make_claim())
        informer.flush()
        [n] = cluster.state_nodes()
        assert n.node is None and n.managed()
        # capacity falls back to claim status pre-initialization
        assert n.capacity()["cpu"] == 4.0
        store.create(make_node())
        informer.flush()
        [n] = cluster.state_nodes()
        assert n.node is not None and n.node_claim is not None
        assert n.registered() and n.initialized()

    def test_unregistered_claim_uses_claim_labels(self, env):
        clock, store, cluster, informer = env
        claim = make_claim()
        claim.metadata.labels["foo"] = "bar"
        store.create(claim)
        store.create(make_node(registered=False, initialized=False))
        informer.flush()
        [n] = cluster.state_nodes()
        assert not n.registered()
        assert n.labels().get("foo") == "bar"

    def test_ephemeral_taints_hidden_until_initialized(self, env):
        clock, store, cluster, informer = env
        store.create(make_claim())
        node = make_node(registered=True, initialized=False)
        node.spec.taints = [
            Taint(key=wk.TAINT_NODE_NOT_READY, effect="NoSchedule"),
            Taint(key="custom", effect="NoSchedule"),
        ]
        store.create(node)
        informer.flush()
        [n] = cluster.state_nodes()
        assert [t.key for t in n.taints()] == ["custom"]

    def test_pod_deletion_releases_usage(self, env):
        clock, store, cluster, informer = env
        store.create(make_node())
        p = store.create(bound_pod("p1", "node-1"))
        informer.flush()
        store.delete(p)
        informer.flush()
        [n] = cluster.state_nodes()
        assert n.total_pod_requests() == {}

    def test_pod_rebind_moves_usage(self, env):
        clock, store, cluster, informer = env
        store.create(make_node("node-1"))
        store.create(make_node("node-2"))
        p = store.create(bound_pod("p1", "node-1"))
        informer.flush()
        p.spec.node_name = "node-2"
        store.update(p)
        informer.flush()
        nodes = {n.name(): n for n in cluster.state_nodes()}
        assert nodes["node-1"].total_pod_requests() == {}
        assert nodes["node-2"].total_pod_requests()["cpu"] == 1.0

    def test_nodepool_resources_accounting(self, env):
        clock, store, cluster, informer = env
        store.create(make_node("node-1"))
        store.create(make_node("node-2"))
        informer.flush()
        rl = cluster.nodepool_resources_for("default-pool")
        assert rl["cpu"] == 8.0 and rl[NODE_RESOURCE] == 2.0
        cluster.mark_for_deletion("kwok://node-1")
        rl = cluster.nodepool_resources_for("default-pool")
        assert rl["cpu"] == 4.0 and rl[NODE_RESOURCE] == 1.0
        cluster.unmark_for_deletion("kwok://node-1")
        assert cluster.nodepool_resources_for("default-pool")["cpu"] == 8.0

    def test_node_deletion_cleanup(self, env):
        clock, store, cluster, informer = env
        node = store.create(make_node())
        informer.flush()
        store.delete(node)
        informer.flush()
        assert cluster.state_nodes() == []
        assert cluster.nodepool_resources_for("default-pool") == {}

    def test_synced_gate(self, env):
        clock, store, cluster, informer = env
        store.create(make_node())
        claim = make_claim(pid="")
        store.create(claim)
        informer.flush()
        assert not cluster.synced()  # claim has no provider id yet
        claim.status.provider_id = "kwok://node-1"
        store.update(claim)
        informer.flush()
        assert cluster.synced()

    def test_daemonset_pod_cache(self, env):
        clock, store, cluster, informer = env
        ds = DaemonSet(metadata=ObjectMeta(name="ds"))
        pod = bound_pod("ds-pod", "node-1")
        pod.metadata.owner_references.append(OwnerReference(kind="DaemonSet", name="ds", uid="u"))
        store.create(pod)
        store.create(ds)
        informer.flush()
        assert cluster.get_daemonset_pod(ds).metadata.name == "ds-pod"

    def test_consolidation_timestamp(self, env):
        clock, store, cluster, informer = env
        t0 = cluster.mark_unconsolidated()
        assert cluster.consolidation_state() == t0
        clock.step(301.0)
        assert cluster.consolidation_state() > t0

    def test_nomination(self, env):
        clock, store, cluster, informer = env
        store.create(make_node())
        informer.flush()
        cluster.nominate_node_for_pod("kwok://node-1")
        assert cluster.is_node_nominated("kwok://node-1")
        clock.step(25.0)
        assert not cluster.is_node_nominated("kwok://node-1")


class TestStateNodeDisruption:
    def build(self, env, **kw):
        clock, store, cluster, informer = env
        store.create(make_claim())
        store.create(make_node(**kw))
        informer.flush()
        return cluster.state_nodes()[0]

    def test_disruptable_ok(self, env):
        clock = env[0]
        n = self.build(env)
        n.validate_node_disruptable(clock.now())

    def test_uninitialized_not_disruptable(self, env):
        clock = env[0]
        n = self.build(env, initialized=False)
        with pytest.raises(ValueError, match="initialized"):
            n.validate_node_disruptable(clock.now())

    def test_deleting_claim_not_disruptable(self, env):
        clock = env[0]
        n = self.build(env)
        n.node_claim.set_condition(CONDITION_INSTANCE_TERMINATING, "True")
        with pytest.raises(ValueError, match="marked for deletion"):
            n.validate_node_disruptable(clock.now())

    def test_do_not_disrupt_annotation(self, env):
        clock = env[0]
        n = self.build(env)
        n.node.metadata.annotations[wk.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
        with pytest.raises(ValueError, match="annotation"):
            n.validate_node_disruptable(clock.now())

    def test_pods_disruptable_blocked_by_do_not_disrupt_pod(self, env):
        clock, store, cluster, informer = env
        n = self.build(env)
        pod = bound_pod("p", "node-1")
        pod.metadata.annotations[wk.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
        store.create(pod)
        with pytest.raises(PodBlockEvictionError):
            n.validate_pods_disruptable(store, Limits())


class TestSimulationIsolation:
    def test_state_nodes_are_copies(self, env):
        """Solver mutations on state_nodes() must not leak into the live
        mirror (regression: simulation corrupted hostport/volume usage)."""
        clock, store, cluster, informer = env
        store.create(make_node())
        informer.flush()
        [copy_node] = cluster.state_nodes()
        copy_node.pod_requests[("default", "phantom")] = {"cpu": 1.0}
        from karpenter_tpu.scheduling.hostportusage import HostPort
        copy_node.hostport_usage.add(
            bound_pod("phantom", "node-1"), [HostPort("0.0.0.0", 8080, "TCP")]
        )
        [live] = cluster.state_nodes()
        assert ("default", "phantom") not in live.pod_requests
        p2 = bound_pod("p2", "node-1")
        assert live.hostport_usage.conflicts(
            p2, [HostPort("0.0.0.0", 8080, "TCP")]
        ) is None
