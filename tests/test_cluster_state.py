"""Cluster state + StateNode, mirroring reference pkg/controllers/state
suite behaviors."""

import pytest

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.core import (
    Condition,
    Container,
    DaemonSet,
    Node,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    OwnerReference,
    Pod,
    PodSpec,
    Taint,
)
from karpenter_tpu.apis.nodeclaim import (
    CONDITION_INSTANCE_TERMINATING,
    NodeClaim,
    NodeClaimStatus,
)
from karpenter_tpu.runtime.store import Store
from karpenter_tpu.state.cluster import NODE_RESOURCE, Cluster
from karpenter_tpu.state.informer import StateInformer
from karpenter_tpu.state.statenode import PodBlockEvictionError, StateNode
from karpenter_tpu.utils.clock import FakeClock
from karpenter_tpu.utils.pdb import Limits


@pytest.fixture
def env():
    clock = FakeClock()
    store = Store(clock=clock)
    cluster = Cluster(clock, store, cloud_provider=None)
    informer = StateInformer(store, cluster)
    return clock, store, cluster, informer


def make_node(name="node-1", pid=None, pool="default-pool", registered=True, initialized=True):
    labels = {wk.NODEPOOL_LABEL_KEY: pool, wk.LABEL_INSTANCE_TYPE: "t-2-8"}
    if registered:
        labels[wk.NODE_REGISTERED_LABEL_KEY] = "true"
    if initialized:
        labels[wk.NODE_INITIALIZED_LABEL_KEY] = "true"
    return Node(
        metadata=ObjectMeta(name=name, labels=labels),
        spec=NodeSpec(provider_id=pid or f"kwok://{name}"),
        status=NodeStatus(
            capacity={"cpu": 4.0, "memory": 8.0 * 2**30, "pods": 110.0},
            allocatable={"cpu": 3.8, "memory": 7.5 * 2**30, "pods": 110.0},
        ),
    )


def make_claim(name="claim-1", pid="kwok://node-1", pool="default-pool"):
    nc = NodeClaim(metadata=ObjectMeta(name=name, labels={wk.NODEPOOL_LABEL_KEY: pool}))
    nc.status.provider_id = pid
    nc.status.capacity = {"cpu": 4.0, "memory": 8.0 * 2**30}
    nc.status.allocatable = {"cpu": 3.8, "memory": 7.5 * 2**30}
    return nc


def bound_pod(name, node_name, cpu=1.0):
    return Pod(
        metadata=ObjectMeta(name=name),
        spec=PodSpec(node_name=node_name, containers=[Container(requests={"cpu": cpu})]),
    )


class TestClusterIngestion:
    def test_node_then_pods_tracked(self, env):
        clock, store, cluster, informer = env
        store.create(make_node())
        store.create(bound_pod("p1", "node-1", cpu=1.0))
        store.create(bound_pod("p2", "node-1", cpu=0.5))
        informer.flush()
        [n] = cluster.state_nodes()
        assert n.total_pod_requests()["cpu"] == pytest.approx(1.5)
        assert n.available()["cpu"] == pytest.approx(3.8 - 1.5)

    def test_claim_then_node_merge(self, env):
        clock, store, cluster, informer = env
        store.create(make_claim())
        informer.flush()
        [n] = cluster.state_nodes()
        assert n.node is None and n.managed()
        # capacity falls back to claim status pre-initialization
        assert n.capacity()["cpu"] == 4.0
        store.create(make_node())
        informer.flush()
        [n] = cluster.state_nodes()
        assert n.node is not None and n.node_claim is not None
        assert n.registered() and n.initialized()

    def test_unregistered_claim_uses_claim_labels(self, env):
        clock, store, cluster, informer = env
        claim = make_claim()
        claim.metadata.labels["foo"] = "bar"
        store.create(claim)
        store.create(make_node(registered=False, initialized=False))
        informer.flush()
        [n] = cluster.state_nodes()
        assert not n.registered()
        assert n.labels().get("foo") == "bar"

    def test_ephemeral_taints_hidden_until_initialized(self, env):
        clock, store, cluster, informer = env
        store.create(make_claim())
        node = make_node(registered=True, initialized=False)
        node.spec.taints = [
            Taint(key=wk.TAINT_NODE_NOT_READY, effect="NoSchedule"),
            Taint(key="custom", effect="NoSchedule"),
        ]
        store.create(node)
        informer.flush()
        [n] = cluster.state_nodes()
        assert [t.key for t in n.taints()] == ["custom"]

    def test_pod_deletion_releases_usage(self, env):
        clock, store, cluster, informer = env
        store.create(make_node())
        p = store.create(bound_pod("p1", "node-1"))
        informer.flush()
        store.delete(p)
        informer.flush()
        [n] = cluster.state_nodes()
        assert n.total_pod_requests() == {}

    def test_pod_rebind_moves_usage(self, env):
        clock, store, cluster, informer = env
        store.create(make_node("node-1"))
        store.create(make_node("node-2"))
        p = store.create(bound_pod("p1", "node-1"))
        informer.flush()
        p.spec.node_name = "node-2"
        store.update(p)
        informer.flush()
        nodes = {n.name(): n for n in cluster.state_nodes()}
        assert nodes["node-1"].total_pod_requests() == {}
        assert nodes["node-2"].total_pod_requests()["cpu"] == 1.0

    def test_nodepool_resources_accounting(self, env):
        clock, store, cluster, informer = env
        store.create(make_node("node-1"))
        store.create(make_node("node-2"))
        informer.flush()
        rl = cluster.nodepool_resources_for("default-pool")
        assert rl["cpu"] == 8.0 and rl[NODE_RESOURCE] == 2.0
        cluster.mark_for_deletion("kwok://node-1")
        rl = cluster.nodepool_resources_for("default-pool")
        assert rl["cpu"] == 4.0 and rl[NODE_RESOURCE] == 1.0
        cluster.unmark_for_deletion("kwok://node-1")
        assert cluster.nodepool_resources_for("default-pool")["cpu"] == 8.0

    def test_node_deletion_cleanup(self, env):
        clock, store, cluster, informer = env
        node = store.create(make_node())
        informer.flush()
        store.delete(node)
        informer.flush()
        assert cluster.state_nodes() == []
        assert cluster.nodepool_resources_for("default-pool") == {}

    def test_synced_gate(self, env):
        clock, store, cluster, informer = env
        store.create(make_node())
        claim = make_claim(pid="")
        store.create(claim)
        informer.flush()
        assert not cluster.synced()  # claim has no provider id yet
        claim.status.provider_id = "kwok://node-1"
        store.update(claim)
        informer.flush()
        assert cluster.synced()

    def test_daemonset_pod_cache(self, env):
        clock, store, cluster, informer = env
        ds = DaemonSet(metadata=ObjectMeta(name="ds"))
        pod = bound_pod("ds-pod", "node-1")
        pod.metadata.owner_references.append(OwnerReference(kind="DaemonSet", name="ds", uid="u"))
        store.create(pod)
        store.create(ds)
        informer.flush()
        assert cluster.get_daemonset_pod(ds).metadata.name == "ds-pod"

    def test_consolidation_timestamp(self, env):
        clock, store, cluster, informer = env
        t0 = cluster.mark_unconsolidated()
        assert cluster.consolidation_state() == t0
        clock.step(301.0)
        assert cluster.consolidation_state() > t0

    def test_nomination(self, env):
        clock, store, cluster, informer = env
        store.create(make_node())
        informer.flush()
        cluster.nominate_node_for_pod("kwok://node-1")
        assert cluster.is_node_nominated("kwok://node-1")
        clock.step(25.0)
        assert not cluster.is_node_nominated("kwok://node-1")


class TestStateNodeDisruption:
    def build(self, env, **kw):
        clock, store, cluster, informer = env
        store.create(make_claim())
        store.create(make_node(**kw))
        informer.flush()
        return cluster.state_nodes()[0]

    def test_disruptable_ok(self, env):
        clock = env[0]
        n = self.build(env)
        n.validate_node_disruptable(clock.now())

    def test_uninitialized_not_disruptable(self, env):
        clock = env[0]
        n = self.build(env, initialized=False)
        with pytest.raises(ValueError, match="initialized"):
            n.validate_node_disruptable(clock.now())

    def test_deleting_claim_not_disruptable(self, env):
        clock = env[0]
        n = self.build(env)
        n.node_claim.set_condition(CONDITION_INSTANCE_TERMINATING, "True")
        with pytest.raises(ValueError, match="marked for deletion"):
            n.validate_node_disruptable(clock.now())

    def test_do_not_disrupt_annotation(self, env):
        clock = env[0]
        n = self.build(env)
        n.node.metadata.annotations[wk.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
        with pytest.raises(ValueError, match="annotation"):
            n.validate_node_disruptable(clock.now())

    def test_pods_disruptable_blocked_by_do_not_disrupt_pod(self, env):
        clock, store, cluster, informer = env
        n = self.build(env)
        pod = bound_pod("p", "node-1")
        pod.metadata.annotations[wk.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
        store.create(pod)
        with pytest.raises(PodBlockEvictionError):
            n.validate_pods_disruptable(store, Limits())


class TestSimulationIsolation:
    def test_state_nodes_are_copies(self, env):
        """Solver mutations on state_nodes() must not leak into the live
        mirror (regression: simulation corrupted hostport/volume usage)."""
        clock, store, cluster, informer = env
        store.create(make_node())
        informer.flush()
        [copy_node] = cluster.state_nodes()
        copy_node.pod_requests[("default", "phantom")] = {"cpu": 1.0}
        from karpenter_tpu.scheduling.hostportusage import HostPort
        copy_node.hostport_usage.add(
            bound_pod("phantom", "node-1"), [HostPort("0.0.0.0", 8080, "TCP")]
        )
        [live] = cluster.state_nodes()
        assert ("default", "phantom") not in live.pod_requests
        p2 = bound_pod("p2", "node-1")
        assert live.hostport_usage.conflicts(
            p2, [HostPort("0.0.0.0", 8080, "TCP")]
        ) is None


class TestPodSchedulingTimes:
    """state/suite_test.go:106-187 — pod schedulable/decision bookkeeping."""

    def _mark(self, cluster, pods, pool="default-pool", errors=None):
        cluster.mark_pod_scheduling_decisions(
            errors or {}, {pool: list(pods)}, {}
        )

    def test_schedulable_time_stored_once(self, env):
        clock, store, cluster, informer = env
        from helpers import nodepool

        np = nodepool("default-pool")
        np.set_condition("NodeRegistrationHealthy", "True")
        store.create(np)
        pod = bound_pod("p1", "")
        key = ("default", "p1")
        self._mark(cluster, [pod])
        first = cluster.pod_scheduling_success_time(key)
        assert first == clock.now()
        clock.step(10.0)
        self._mark(cluster, [pod])
        # suite_test.go:122 — an existing time is never overwritten
        assert cluster.pod_scheduling_success_time(key) == first

    def test_error_clears_schedulable_time_and_claim_mapping(self, env):
        clock, store, cluster, informer = env
        from helpers import nodepool

        store.create(nodepool("default-pool"))
        pod = bound_pod("p1", "")
        key = ("default", "p1")
        cluster.mark_pod_scheduling_decisions(
            {}, {"default-pool": [pod]}, {"claim-a": [pod]}
        )
        assert cluster.pod_scheduling_success_time(key) > 0
        assert cluster.pod_node_claim_mapping(key) == "claim-a"
        clock.step(5.0)
        # suite_test.go:170 — an error wipes both
        cluster.mark_pod_scheduling_decisions({pod: ValueError("no room")}, {}, {})
        assert cluster.pod_scheduling_success_time(key) == 0.0
        assert cluster.pod_node_claim_mapping(key) == ""

    def test_pod_deletion_clears_mappings(self, env):
        clock, store, cluster, informer = env
        from helpers import nodepool

        store.create(nodepool("default-pool"))
        pod = bound_pod("p1", "")
        key = ("default", "p1")
        cluster.ack_pods(pod)
        self._mark(cluster, [pod])
        store.create(pod)
        informer.flush()
        store.delete("Pod", "p1")
        informer.flush()
        # suite_test.go:137,187 — deletion clears every per-pod mapping
        assert cluster.pod_scheduling_success_time(key) == 0.0
        assert cluster.pod_ack_time(key) == 0.0
        assert cluster.pod_scheduling_decision_time(key) == 0.0

    def test_healthy_nodepool_time_requires_condition(self, env):
        clock, store, cluster, informer = env
        from helpers import nodepool

        np = nodepool("default-pool")  # NodeRegistrationHealthy unset
        store.create(np)
        pod = bound_pod("p1", "")
        key = ("default", "p1")
        self._mark(cluster, [pod])
        assert cluster.pod_healthy_nodepool_scheduled_time.get(key) is None
        np.set_condition("NodeRegistrationHealthy", "True")
        store.update(np)
        clock.step(3.0)
        self._mark(cluster, [pod])
        assert cluster.pod_healthy_nodepool_scheduled_time[key] == clock.now()


class TestUsageHydration:
    """state/suite_test.go:245-424 — volume/hostport usage survive updates."""

    def _pod_with_port(self, name, node_name, port=8080):
        from karpenter_tpu.apis.core import ContainerPort

        pod = bound_pod(name, node_name)
        pod.spec.containers[0].ports = [
            ContainerPort(container_port=80, host_port=port)
        ]
        return pod

    def test_hostport_usage_hydrated_on_node_update(self, env):
        clock, store, cluster, informer = env
        store.create(self._pod_with_port("p1", "node-1"))
        store.create(make_node())
        informer.flush()
        [n] = cluster.state_nodes()
        from karpenter_tpu.scheduling.hostportusage import HostPort

        conflict = n.hostport_usage.conflicts(
            bound_pod("p2", "node-1"), [HostPort("0.0.0.0", 8080, "TCP")]
        )
        assert conflict is not None

    def test_hostport_usage_survives_nodeclaim_update(self, env):
        clock, store, cluster, informer = env
        store.create(self._pod_with_port("p1", "node-1"))
        node = make_node()
        claim = make_claim()
        store.create(claim)
        store.create(node)
        informer.flush()
        claim.metadata.labels["refresh"] = "1"
        store.update(claim)
        informer.flush()
        [n] = cluster.state_nodes()
        from karpenter_tpu.scheduling.hostportusage import HostPort

        assert n.hostport_usage.conflicts(
            bound_pod("p2", "node-1"), [HostPort("0.0.0.0", 8080, "TCP")]
        ) is not None

    def test_same_name_node_and_claim_one_state_node(self, env):
        """suite_test.go:425 — a NodeClaim and Node sharing one name (and
        provider id) collapse into a single state node."""
        clock, store, cluster, informer = env
        store.create(make_claim(name="twin", pid="kwok://twin"))
        node = make_node(name="twin", pid="kwok://twin")
        store.create(node)
        informer.flush()
        assert len(cluster.state_nodes()) == 1


class TestPodCounting:
    """state/suite_test.go:453-645."""

    def test_unbound_pods_not_counted(self, env):
        clock, store, cluster, informer = env
        store.create(make_node())
        store.create(bound_pod("floating", ""))
        informer.flush()
        [n] = cluster.state_nodes()
        assert n.total_pod_requests().get("cpu", 0.0) == 0.0

    def test_terminal_pods_not_counted(self, env):
        clock, store, cluster, informer = env
        store.create(make_node())
        done = bound_pod("done", "node-1", cpu=2.0)
        done.status.phase = "Succeeded"
        store.create(done)
        informer.flush()
        [n] = cluster.state_nodes()
        assert n.total_pod_requests().get("cpu", 0.0) == 0.0


class TestAntiAffinityTracking:
    """state/suite_test.go:1034-1169."""

    def _anti_pod(self, name, node_name, required=True):
        from karpenter_tpu.apis.core import (
            Affinity,
            LabelSelector,
            PodAffinityTerm,
            PodAntiAffinity,
            WeightedPodAffinityTerm,
        )

        term = PodAffinityTerm(
            topology_key=wk.LABEL_HOSTNAME,
            label_selector=LabelSelector(match_labels={"app": "x"}),
        )
        anti = (
            PodAntiAffinity(required=[term])
            if required
            else PodAntiAffinity(
                preferred=[WeightedPodAffinityTerm(weight=1, pod_affinity_term=term)]
            )
        )
        pod = bound_pod(name, node_name)
        pod.spec.affinity = Affinity(pod_anti_affinity=anti)
        return pod

    def _tracked(self, cluster):
        seen = []
        cluster.for_pods_with_anti_affinity(
            lambda pod, node: (seen.append(pod.metadata.name), True)[1]
        )
        return seen

    def test_required_anti_affinity_tracked(self, env):
        clock, store, cluster, informer = env
        store.create(make_node())
        store.create(self._anti_pod("anti-1", "node-1"))
        informer.flush()
        assert self._tracked(cluster) == ["anti-1"]

    def test_preferred_anti_affinity_not_tracked(self, env):
        clock, store, cluster, informer = env
        store.create(make_node())
        store.create(self._anti_pod("soft-1", "node-1", required=False))
        informer.flush()
        assert self._tracked(cluster) == []

    def test_deleted_pod_stops_tracking(self, env):
        clock, store, cluster, informer = env
        store.create(make_node())
        store.create(self._anti_pod("anti-1", "node-1"))
        informer.flush()
        store.delete("Pod", "anti-1")
        informer.flush()
        assert self._tracked(cluster) == []


class TestSyncedVariants:
    """state/suite_test.go:1218-1555."""

    def test_synced_with_providerless_nodes(self, env):
        """:1260 — unmanaged nodes with no provider id don't block the gate
        (they're tracked under their node name)."""
        clock, store, cluster, informer = env
        node = make_node()
        node.spec.provider_id = ""
        del node.metadata.labels[wk.NODEPOOL_LABEL_KEY]
        store.create(node)
        informer.flush()
        assert cluster.synced() is True

    def test_not_synced_until_claim_resolves_provider_id(self, env):
        """:1410 — a launched claim without a provider id blocks."""
        clock, store, cluster, informer = env
        claim = make_claim()
        claim.status.provider_id = ""
        claim.set_condition("Launched", "True")
        store.create(claim)
        informer.flush()
        assert cluster.synced() is False
        claim.status.provider_id = "kwok://node-1"
        store.update(claim)
        informer.flush()
        assert cluster.synced() is True

    def test_unsynced_time_stopwatch(self, env):
        """state/metrics.go:57-62 — unsynced_time_seconds measures the
        CONTINUOUS unsynced stretch and resets to zero once synced."""
        from karpenter_tpu.state.cluster import _UNSYNCED_TIME_GAUGE

        clock, store, cluster, informer = env
        claim = make_claim()
        claim.status.provider_id = ""
        claim.set_condition("Launched", "True")
        store.create(claim)
        informer.flush()
        assert cluster.synced() is False
        clock.step(7.0)
        assert cluster.synced() is False
        assert _UNSYNCED_TIME_GAUGE.value() == 7.0
        claim.status.provider_id = "kwok://node-1"
        store.update(claim)
        informer.flush()
        assert cluster.synced() is True
        assert _UNSYNCED_TIME_GAUGE.value() == 0.0

    def test_new_node_after_initial_sync_keeps_synced(self, env):
        """:1507 — ingestion keeps pace with additions."""
        clock, store, cluster, informer = env
        store.create(make_node())
        informer.flush()
        assert cluster.synced() is True
        store.create(make_node(name="node-2", pid="kwok://node-2"))
        informer.flush()
        assert cluster.synced() is True


class TestDaemonSetCache:
    """state/suite_test.go:1557-1696."""

    def _ds_and_pod(self, name, pod_name, ts):
        from helpers import daemonset, daemonset_pod

        ds = daemonset(name)
        pod = daemonset_pod(ds)
        pod.metadata.name = pod_name
        pod.metadata.creation_timestamp = ts
        pod.spec.node_name = "node-1"
        return ds, pod

    def test_newest_pod_wins(self, env):
        clock, store, cluster, informer = env
        ds, old = self._ds_and_pod("ds-1", "old", 1.0)
        store.create(ds)
        store.create(old)
        informer.flush()
        _, new = self._ds_and_pod("ds-1", "new", 5.0)
        store.create(new)
        store.update(ds)  # reference re-reconciles the daemonset (suite:1568)
        informer.flush()
        assert cluster.get_daemonset_pod(ds).metadata.name == "new"
        # an OLDER pod must not displace the cached newest (suite:1596)
        _, stale = self._ds_and_pod("ds-1", "stale", 0.5)
        store.create(stale)
        store.update(ds)
        informer.flush()
        assert cluster.get_daemonset_pod(ds).metadata.name == "new"

    def test_daemonset_delete_clears_cache(self, env):
        clock, store, cluster, informer = env
        ds, pod = self._ds_and_pod("ds-1", "p", 1.0)
        store.create(ds)
        store.create(pod)
        informer.flush()
        assert cluster.get_daemonset_pod(ds) is not None
        store.delete("DaemonSet", "ds-1")
        informer.flush()
        assert cluster.get_daemonset_pod(ds) is None


class TestConsolidationState:
    """state/suite_test.go:1697-1739."""

    def test_state_changes_after_ttl(self, env):
        clock, store, cluster, informer = env
        first = cluster.consolidation_state()
        clock.step(1.0)
        assert cluster.consolidation_state() == first
        clock.step(301.0)  # 5m TTL elapses
        assert cluster.consolidation_state() != first

    def test_nodepool_update_changes_state(self, env):
        from helpers import nodepool

        clock, store, cluster, informer = env
        store.create(make_node())
        informer.flush()
        state = cluster.consolidation_state()
        clock.step(1.0)
        np = nodepool("default-pool")
        store.create(np)
        informer.flush()
        assert cluster.consolidation_state() != state


class TestNodePoolResourceAccounting:
    """state/suite_test.go:1933-2362."""

    def test_multiple_nodepools(self, env):
        clock, store, cluster, informer = env
        store.create(make_node(name="a-1", pid="kwok://a-1", pool="pool-a"))
        store.create(make_node(name="a-2", pid="kwok://a-2", pool="pool-a"))
        store.create(make_node(name="b-1", pid="kwok://b-1", pool="pool-b"))
        informer.flush()
        assert cluster.nodepool_resources_for("pool-a")["cpu"] == pytest.approx(8.0)
        assert cluster.nodepool_resources_for("pool-b")["cpu"] == pytest.approx(4.0)
        assert cluster.nodepool_resources_for("pool-a")[NODE_RESOURCE] == 2.0

    def test_node_switching_nodepools_moves_resources(self, env):
        clock, store, cluster, informer = env
        node = make_node(pool="pool-a")
        store.create(node)
        informer.flush()
        assert cluster.nodepool_resources_for("pool-a")["cpu"] == pytest.approx(4.0)
        node.metadata.labels[wk.NODEPOOL_LABEL_KEY] = "pool-b"
        store.update(node)
        informer.flush()
        assert cluster.nodepool_resources_for("pool-a") == {}
        assert cluster.nodepool_resources_for("pool-b")["cpu"] == pytest.approx(4.0)

    def test_mark_unmark_for_deletion_updates_resources(self, env):
        clock, store, cluster, informer = env
        store.create(make_node())
        informer.flush()
        assert cluster.nodepool_resources_for("default-pool")["cpu"] == pytest.approx(4.0)
        cluster.mark_for_deletion("kwok://node-1")
        assert cluster.nodepool_resources_for("default-pool") == {}
        cluster.unmark_for_deletion("kwok://node-1")
        assert cluster.nodepool_resources_for("default-pool")["cpu"] == pytest.approx(4.0)

    def test_no_double_subtract_on_mark_then_delete(self, env):
        """:2362 — marking for deletion and then deleting the node must not
        subtract capacity twice."""
        clock, store, cluster, informer = env
        store.create(make_node())
        informer.flush()
        cluster.mark_for_deletion("kwok://node-1")
        store.delete("Node", "node-1")
        informer.flush()
        assert cluster.nodepool_resources_for("default-pool") == {}
