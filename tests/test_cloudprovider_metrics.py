"""CloudProvider metrics decorator (reference
pkg/cloudprovider/metrics/cloudprovider.go): per-method duration/error
instrumentation, decorated by default in the operator."""

import pytest

from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
from karpenter_tpu.cloudprovider.metrics import (
    MetricsCloudProvider,
    _DURATION,
    _ERRORS,
)
from karpenter_tpu.cloudprovider.kwok.provider import KwokCloudProvider
from karpenter_tpu.cloudprovider.types import NodeClaimNotFoundError
from karpenter_tpu.metrics import global_registry
from karpenter_tpu.operator.operator import Operator
from karpenter_tpu.runtime.store import Store
from karpenter_tpu.utils.clock import FakeClock

from helpers import nodepool, unschedulable_pod


class TestMetricsCloudProvider:
    def test_duration_recorded_per_method(self):
        provider = MetricsCloudProvider(FakeCloudProvider())
        before = _DURATION.count(
            {"controller": "", "method": "list", "provider": "fake"}
        )
        provider.list()
        assert (
            _DURATION.count({"controller": "", "method": "list", "provider": "fake"})
            == before + 1
        )

    def test_errors_counted_by_type(self):
        provider = MetricsCloudProvider(FakeCloudProvider())
        labels = {
            "controller": "",
            "method": "get",
            "provider": "fake",
            "error": "NodeClaimNotFoundError",
        }
        before = _ERRORS.value(labels)
        with pytest.raises(NodeClaimNotFoundError):
            provider.get("kwok://nope")
        assert _ERRORS.value(labels) == before + 1

    def test_delegates_unwrapped_attributes(self):
        inner = FakeCloudProvider()
        provider = MetricsCloudProvider(inner)
        assert provider.name() == "fake"
        assert provider.created is inner.created

    def test_operator_decorates_by_default_and_exposes(self):
        clock = FakeClock()
        store = Store(clock=clock)
        op = Operator(store, KwokCloudProvider(store, clock), clock=clock)
        assert isinstance(op.cloud_provider, MetricsCloudProvider)
        store.create(nodepool("workers"))
        store.create(unschedulable_pod(requests={"cpu": "1"}))
        for _ in range(8):
            clock.step(2.0)
            op.run_once()
        text = global_registry.expose()
        assert "karpenter_cloudprovider_duration_seconds" in text
        assert 'method="create"' in text or "method=\"create\"" in text
