"""CloudProvider metrics decorator (reference
pkg/cloudprovider/metrics/cloudprovider.go): per-method duration/error
instrumentation, decorated by default in the operator."""

import pytest

from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
from karpenter_tpu.cloudprovider.metrics import (
    MetricsCloudProvider,
    _DURATION,
    _ERRORS,
)
from karpenter_tpu.cloudprovider.kwok.provider import KwokCloudProvider
from karpenter_tpu.cloudprovider.types import NodeClaimNotFoundError
from karpenter_tpu.metrics import global_registry
from karpenter_tpu.operator.operator import Operator
from karpenter_tpu.runtime.store import Store
from karpenter_tpu.utils.clock import FakeClock

from helpers import nodepool, unschedulable_pod


class TestMetricsCloudProvider:
    def test_duration_recorded_per_method(self):
        provider = MetricsCloudProvider(FakeCloudProvider())
        before = _DURATION.count(
            {"controller": "", "method": "list", "provider": "fake"}
        )
        provider.list()
        assert (
            _DURATION.count({"controller": "", "method": "list", "provider": "fake"})
            == before + 1
        )

    def test_errors_counted_by_type(self):
        provider = MetricsCloudProvider(FakeCloudProvider())
        labels = {
            "controller": "",
            "method": "get",
            "provider": "fake",
            "error": "NodeClaimNotFoundError",
            # typed not-found is a domain answer, not an infrastructure
            # failure — the retryable label separates outage signals
            "retryable": "false",
        }
        before = _ERRORS.value(labels)
        with pytest.raises(NodeClaimNotFoundError):
            provider.get("kwok://nope")
        assert _ERRORS.value(labels) == before + 1

    def test_delegates_unwrapped_attributes(self):
        inner = FakeCloudProvider()
        provider = MetricsCloudProvider(inner)
        assert provider.name() == "fake"
        assert provider.created is inner.created

    def test_operator_decorates_by_default_and_exposes(self):
        clock = FakeClock()
        store = Store(clock=clock)
        op = Operator(store, KwokCloudProvider(store, clock), clock=clock)
        # breaker OUTSIDE metrics, so fast-fails never reach the meters
        from karpenter_tpu.cloudprovider.breaker import BreakerCloudProvider

        assert isinstance(op.cloud_provider, BreakerCloudProvider)
        assert isinstance(op.cloud_provider._inner, MetricsCloudProvider)
        store.create(nodepool("workers"))
        store.create(unschedulable_pod(requests={"cpu": "1"}))
        for _ in range(8):
            clock.step(2.0)
            op.run_once()
        text = global_registry.expose()
        assert "karpenter_cloudprovider_duration_seconds" in text
        assert 'method="create"' in text or "method=\"create\"" in text


class TestPodMetricsFamily:
    """The reference's full pod metric family (metrics/pod/controller.go:
    60-165): live unstarted/unbound/undecided gauges that resolve away,
    bound/startup histograms, and their provisioning_* twins measured from
    the schedulability-determination time."""

    def test_lifecycle_resolves_gauges_and_observes_histograms(self):
        from karpenter_tpu.controllers import metrics_controllers as mc

        clock = FakeClock()
        store = Store(clock=clock)
        op = Operator(store, KwokCloudProvider(store, clock), clock=clock)
        store.create(nodepool("workers"))
        pod = store.create(unschedulable_pod(name="pm-1", requests={"cpu": "1"}))
        plabels = {"name": "pm-1", "namespace": "default"}
        bound0 = mc._POD_BOUND_DURATION.count()
        pstart0 = mc._POD_PROV_STARTUP.count()
        # first passes: pod pending/unbound — live gauges present
        clock.step(2.0)
        op.run_once()
        assert mc._POD_UNBOUND_TIME.value(plabels) > 0.0
        assert mc._POD_UNSTARTED.value(plabels) > 0.0
        for _ in range(10):
            clock.step(2.0)
            op.run_once()
        live = store.get("Pod", "pm-1")
        assert live.spec.node_name, "pod should be bound by now"
        # bound+running: THIS pod's live gauges resolved away (other tests'
        # pods may have left series — assert only our labels)
        key = tuple(sorted(plabels.items()))
        assert key not in mc._POD_UNBOUND_TIME.series()
        assert key not in mc._POD_UNSTARTED.series()
        assert key not in mc._POD_UNDECIDED.series()
        # ...and the histograms observed, including the provisioning twins
        assert mc._POD_BOUND_DURATION.count() == bound0 + 1
        assert mc._POD_PROV_STARTUP.count() == pstart0 + 1

    def test_node_metric_family_exposed(self):
        """The reference's full node series (metrics/node/controller.go:
        60-140): limits, daemon requests/limits, system overhead, lifetime,
        utilization percent — all present for a provisioned node."""
        clock = FakeClock()
        store = Store(clock=clock)
        op = Operator(store, KwokCloudProvider(store, clock), clock=clock)
        store.create(nodepool("workers"))
        pod = unschedulable_pod(name="nm-1", requests={"cpu": "1"})
        pod.spec.containers[0].limits = {"cpu": 2.0}
        store.create(pod)
        for _ in range(10):
            clock.step(2.0)
            op.run_once()
        text = op.metrics_text()
        for series in (
            "karpenter_nodes_total_pod_limits",
            "karpenter_nodes_total_daemon_requests",
            "karpenter_nodes_system_overhead",
            "karpenter_nodes_current_lifetime_seconds",
            "karpenter_nodes_utilization_percent",
        ):
            assert series in text, series
        from karpenter_tpu.controllers import metrics_controllers as mc

        [node] = store.list("Node")
        labels = {
            "node_name": node.metadata.name,
            "nodepool": "workers",
            "resource_type": "cpu",
        }
        assert mc._NODE_POD_LIMITS.value(labels) == 2.0
        pct = mc._NODE_UTIL_PCT.value(labels)
        assert 0.0 < pct <= 100.0
        assert mc._NODE_LIFETIME_GAUGE.value(
            {"node_name": node.metadata.name, "nodepool": "workers"}
        ) > 0.0

    def test_deleted_pod_drops_series(self):
        from karpenter_tpu.controllers import metrics_controllers as mc

        clock = FakeClock()
        store = Store(clock=clock)
        op = Operator(store, KwokCloudProvider(store, clock), clock=clock)
        # no nodepool: the pod stays pending with live gauges
        pod = store.create(unschedulable_pod(name="pm-2", requests={"cpu": "1"}))
        clock.step(2.0)
        op.run_once()
        plabels = {"name": "pm-2", "namespace": "default"}
        assert mc._POD_UNBOUND_TIME.value(plabels) > 0.0
        store.delete(pod)
        clock.step(2.0)
        op.run_once()
        assert mc._POD_UNBOUND_TIME.value(plabels) == 0.0
        assert mc._POD_UNSTARTED.value(plabels) == 0.0


class TestStatusConditionMetrics:
    """Per-CRD status-condition series, matching the operatorpkg status
    controllers the reference auto-registers (controllers.go:102-120)."""

    def _run_operator(self):
        clock = FakeClock()
        store = Store(clock=clock)
        op = Operator(store, KwokCloudProvider(store, clock), clock=clock)
        store.create(nodepool("workers"))
        store.create(unschedulable_pod(requests={"cpu": "1"}))
        for _ in range(10):
            clock.step(2.0)
            op.run_once()
        return clock, store, op

    def test_transition_duration_recorded(self):
        """Launch sets Registered=Unknown; registration flips it True some
        clock-time later — that held-for duration lands in the histogram."""
        from karpenter_tpu.apis.conditions import CONDITION_TRANSITION_SECONDS

        labels = {"kind": "NodeClaim", "type": "Registered", "status": "True"}
        before_n = CONDITION_TRANSITION_SECONDS.count(labels)
        before_sum = CONDITION_TRANSITION_SECONDS.sum(labels)
        clock, store, op = self._run_operator()
        claims = store.list("NodeClaim")
        assert claims and claims[0].condition_is_true("Registered")
        assert CONDITION_TRANSITION_SECONDS.count(labels) == before_n + 1
        # kwok registration delay is nonzero on the fake clock
        assert CONDITION_TRANSITION_SECONDS.sum(labels) > before_sum

    def test_transitions_counted(self):
        from karpenter_tpu.apis.conditions import CONDITION_TRANSITIONS_TOTAL

        labels = {"kind": "NodeClaim", "type": "Launched", "status": "True"}
        before = CONDITION_TRANSITIONS_TOTAL.value(labels)
        self._run_operator()
        assert CONDITION_TRANSITIONS_TOTAL.value(labels) == before + 1

    def test_condition_count_gauge_exposed_and_pruned(self):
        clock, store, op = self._run_operator()
        text = op.metrics_text()
        assert "karpenter_status_condition_count" in text
        assert "karpenter_status_condition_transitions_total" in text
        assert "karpenter_status_condition_transition_seconds" in text
        from karpenter_tpu.controllers.metrics_controllers import _CONDITION_COUNT

        labels = {
            "kind": "NodeClaim", "type": "Registered",
            "status": "True", "reason": "",
        }
        assert _CONDITION_COUNT.value(labels) == 1.0
        # NodePool conditions counted too
        assert any(
            k == "NodePool"
            for key, _ in _CONDITION_COUNT.series().items()
            for lk, k in key
            if lk == "kind"
        )
        # deleting the claim prunes its series on the next reconcile
        # (finalizers stripped: we want the object fully gone, not Terminating)
        for claim in store.list("NodeClaim"):
            claim.metadata.finalizers = []
            store.apply(claim)
            store.delete(claim)
        op.condition_metrics.reconcile()
        assert _CONDITION_COUNT.value(labels) == 0.0
