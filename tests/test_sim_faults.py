"""Fault injection: the spot-interruption scenario must demonstrably drive
NodeClaim retry/replacement, and probabilistic cloud/solver faults must
degrade gracefully instead of wedging the loop (ISSUE 2 acceptance)."""

from random import Random

import pytest

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.cloudprovider.kwok.provider import KwokCloudProvider
from karpenter_tpu.cloudprovider.types import (
    CreateError,
    InsufficientCapacityError,
    NodeClaimNotFoundError,
)
from karpenter_tpu.runtime.store import Store
from karpenter_tpu.sim import scenarios
from karpenter_tpu.sim.faults import FaultyCloudProvider, FlakySolverClient, interrupt
from karpenter_tpu.sim.harness import run_scenario
from karpenter_tpu.solverd import QueueFullError
from karpenter_tpu.utils.clock import FakeClock

from helpers import nodepool


def make_claim(store, name="workers-test", capacity_type=None):
    from karpenter_tpu.apis.core import ObjectMeta
    from karpenter_tpu.apis.nodeclaim import NodeClaim

    if store.try_get("NodePool", "workers") is None:
        store.create(nodepool("workers"))
    claim = NodeClaim(
        metadata=ObjectMeta(name=name, labels={wk.NODEPOOL_LABEL_KEY: "workers"})
    )
    claim.spec.requirements = [
        {"key": wk.LABEL_OS, "operator": "In", "values": ["linux"]},
        {"key": wk.LABEL_ARCH, "operator": "In", "values": ["amd64"]},
    ]
    if capacity_type is not None:
        claim.spec.requirements.append(
            {"key": wk.CAPACITY_TYPE_LABEL_KEY, "operator": "In",
             "values": [capacity_type]}
        )
    claim.spec.resources.requests = {"cpu": 1.0}
    return claim


class TestSpotInterruptionScenario:
    @pytest.fixture(scope="class")
    def result(self):
        return run_scenario(scenarios.resolve("spot-interruption", 7), 7)

    def test_interruptions_injected(self, result):
        faults = result.report["faults"]
        assert faults["spot_interruptions"] >= 1
        assert faults["capacity_reclaims"] >= 1

    def test_replacement_path_exercised(self, result):
        """Each interruption kills capacity that live pods depend on, so the
        provisioner must mint replacement NodeClaims: strictly more claims
        than the steady workload needed, and interrupted claims are gone."""
        churn = result.report["churn"]
        assert churn["nodeclaims_deleted"] >= 2  # one graceful + one reclaim
        assert churn["nodeclaims_created"] > churn["nodeclaims_deleted"]
        assert churn["nodes_at_end"] >= 1
        # a replacement claim is created AFTER the first interruption
        entries = list(result.log)
        first_fault_t = next(
            e["t"] for e in entries if e["ev"] in ("fault-interrupt", "fault-reclaim")
        )
        assert any(
            e["ev"] == "nodeclaim-added" and e["t"] > first_fault_t for e in entries
        )

    def test_workload_recovers(self, result):
        slo = result.report["slo"]
        assert slo["pods_never_bound"] == 0
        assert slo["pods_bound"] == slo["pods_submitted"]
        # the reclaim loses bound pods out-of-band; the workload driver
        # resubmits and the cluster re-places them
        assert result.report["faults"]["pods_lost"] >= 1

    def test_spot_capacity_only(self, result):
        assert set(result.report["cost"]["by_capacity_type"]) == {"spot"}

    def test_deterministic_under_faults(self, result):
        again = run_scenario(scenarios.resolve("spot-interruption", 7), 7)
        assert again.digest == result.digest


class TestFlakyCloudScenario:
    @pytest.fixture(scope="class")
    def result(self):
        return run_scenario(scenarios.resolve("flaky-cloud", 7), 7)

    def test_faults_fired_and_loop_survived(self, result):
        faults = result.report["faults"]
        assert (
            faults["launch_failures"]
            + faults["capacity_errors"]
            + faults["solver_rejections"]
        ) >= 1
        # graceful degradation: demand is still met by the end of the run
        assert result.report["slo"]["pods_never_bound"] == 0
        assert result.report["churn"]["nodes_at_end"] >= 1

    def test_outage_trips_and_recovers_the_circuit_breaker(self, result):
        """ISSUE 3 acceptance: the scheduled cloud outage must drive the
        operator's circuit breaker through open → half-open → closed, all
        recorded in the event log and folded into the report."""
        assert result.report["faults"]["cloud_outage_failures"] >= 1
        breaker = result.report["breaker"]
        assert breaker["opens"] >= 1
        assert breaker["half_opens"] >= 1
        assert breaker["closes"] >= 1
        assert breaker["state_at_end"] == "closed"
        # transition order is sane: first open precedes the final close
        transitions = [e for e in result.log if e["ev"] == "breaker"]
        assert transitions[0]["to"] == "open"
        assert transitions[-1]["to"] == "closed"

    def test_deterministic_with_breaker_and_backoff(self, result):
        """Backoff jitter and breaker timing are clock/seed-driven: the
        same seed must replay to a byte-identical event log."""
        again = run_scenario(scenarios.resolve("flaky-cloud", 7), 7)
        assert again.digest == result.digest


class TestFaultyCloudProvider:
    def _provider(self, **kwargs):
        clock = FakeClock()
        store = Store(clock=clock)
        kwok = KwokCloudProvider(store, clock)
        faulty = FaultyCloudProvider(kwok, Random(1), clock, **kwargs)
        return clock, store, faulty

    def _claim(self, store):
        return make_claim(store)

    def test_launch_failure_is_retryable_create_error(self):
        _, store, faulty = self._provider(launch_failure_rate=1.0)
        with pytest.raises(CreateError):
            faulty.create(self._claim(store))
        assert faulty.launch_failures == 1

    def test_insufficient_capacity_injection(self):
        _, store, faulty = self._provider(insufficient_capacity_rate=1.0)
        with pytest.raises(InsufficientCapacityError):
            faulty.create(self._claim(store))
        assert faulty.capacity_errors == 1

    def test_api_latency_advances_virtual_time(self):
        clock, store, faulty = self._provider(api_latency=0.5)
        t0 = clock.now()
        faulty.create(self._claim(store))
        assert clock.now() >= t0 + 0.5

    def test_delegates_provider_surface(self):
        _, store, faulty = self._provider()
        created = faulty.create(self._claim(store))
        assert faulty.get(created.status.provider_id).metadata.name == "workers-test"
        assert faulty.name() == "kwok"
        assert faulty.tick() == 0  # kwok tick passes through __getattr__
        faulty.delete(created)
        with pytest.raises(NodeClaimNotFoundError):
            faulty.get(created.status.provider_id)


class TestFlakySolverClient:
    def test_rejection_storm_raises_typed_retryable(self):
        class Inner:
            transport = "inprocess"

            def solve(self, kind, scheduler, pods, timeout=None, deadline=None,
                      request_id=None, tenant=None):
                return "solved"

            def stats(self):
                return {"transport": "inprocess"}

            def close(self):
                pass

        flaky = FlakySolverClient(Inner(), Random(1), rejection_rate=1.0)
        with pytest.raises(QueueFullError) as exc:
            flaky.solve("solve", None, [])
        assert exc.value.retryable is True
        assert flaky.stats()["injected_rejections"] == 1
        flaky.rejection_rate = 0.0
        assert flaky.solve("solve", None, []) == "solved"


class TestInterrupt:
    def _cluster(self, n=3):
        clock = FakeClock()
        store = Store(clock=clock)
        kwok = KwokCloudProvider(store, clock)
        claims = []
        for i in range(n):
            claim = make_claim(
                store,
                name=f"workers-{i}",
                capacity_type="spot" if i % 2 == 0 else "on-demand",
            )
            created = kwok.create(claim)
            # the lifecycle controller adds this on launch; graceful
            # interruption relies on it to leave the claim in "deleting"
            created.metadata.finalizers.append(wk.TERMINATION_FINALIZER)
            store.create(created)
            claims.append(created)
        clock.step(5.0)
        kwok.tick()
        return clock, store, kwok, claims

    def test_graceful_deletes_claim(self):
        _, store, kwok, _ = self._cluster()
        hit = interrupt(store, kwok, Random(2), count=1, mode="graceful",
                        capacity_type="spot")
        assert hit == 1
        deleting = [
            c for c in store.list("NodeClaim")
            if c.metadata.deletion_timestamp is not None
        ]
        assert len(deleting) == 1
        assert deleting[0].metadata.labels[wk.CAPACITY_TYPE_LABEL_KEY] == "spot"

    def test_reclaim_vanishes_instance_and_node(self):
        _, store, kwok, _ = self._cluster()
        n_nodes = len(store.list("Node"))
        hit = interrupt(store, kwok, Random(2), count=1, mode="reclaim")
        assert hit == 1
        assert len(store.list("Node")) == n_nodes - 1
        # the claim survives until GC reaps it — the instance is just gone
        gone = [
            c for c in store.list("NodeClaim")
            if c.status.provider_id not in {x.status.provider_id for x in kwok.list()}
        ]
        assert len(gone) == 1

    def test_respects_capacity_filter_and_count(self):
        _, store, kwok, _ = self._cluster(n=4)
        hit = interrupt(store, kwok, Random(2), count=10, mode="graceful",
                        capacity_type="on-demand")
        assert hit == 2  # only the two on-demand claims qualify
