import datetime as dt

from karpenter_tpu.apis.nodepool import Budget, NodePool


def ts(y, mo, d, h, mi):
    return dt.datetime(y, mo, d, h, mi, tzinfo=dt.timezone.utc).timestamp()


class TestBudgets:
    def test_percentage_rounds_up(self):
        # default 10% must allow 1 disruption even on small nodepools
        assert Budget(nodes="10%").allowed_disruptions(5, 0.0) == 1
        assert Budget(nodes="10%").allowed_disruptions(25, 0.0) == 3
        assert Budget(nodes="0%").allowed_disruptions(5, 0.0) == 0

    def test_absolute(self):
        assert Budget(nodes="3").allowed_disruptions(100, 0.0) == 3

    def test_schedule_without_duration_fails_closed(self):
        b = Budget(nodes="100%", schedule="@daily", duration=None)
        assert b.allowed_disruptions(10, ts(2026, 7, 29, 0, 30)) == 0

    def test_schedule_window(self):
        b = Budget(nodes="0", schedule="0 9 * * *", duration=3600.0)
        # inside window: restricted to 0
        assert b.allowed_disruptions(10, ts(2026, 7, 29, 9, 30)) == 0
        # outside window: unrestricted
        assert b.allowed_disruptions(10, ts(2026, 7, 29, 11, 30)) == 10

    def test_nodepool_most_restrictive_and_reasons(self):
        np_ = NodePool()
        np_.spec.disruption.budgets = [
            Budget(nodes="50%"),
            Budget(nodes="2", reasons=["Drifted"]),
        ]
        assert np_.allowed_disruptions("Empty", 10, 0.0) == 5
        assert np_.allowed_disruptions("Drifted", 10, 0.0) == 2


class TestDisruptionBudgetCounting:
    """suite_test.go:699-845 — which nodes count toward the disruption
    budget denominator and the in-flight disruption count."""

    REASONS = ("Empty", "Underutilized", "Drifted")

    def _harness(self, budget="100%", n=10):
        from karpenter_tpu.apis.nodepool import Budget
        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
        from karpenter_tpu.events.recorder import Recorder
        from karpenter_tpu.runtime.store import Store
        from karpenter_tpu.state.cluster import Cluster
        from karpenter_tpu.state.informer import StateInformer
        from karpenter_tpu.utils.clock import FakeClock

        from helpers import node_claim_pair, nodepool

        class H:
            pass

        h = H()
        h.clock = FakeClock()
        h.store = Store(clock=h.clock)
        h.provider = FakeCloudProvider()
        h.cluster = Cluster(h.clock, h.store, h.provider)
        h.informer = StateInformer(h.store, h.cluster)
        h.recorder = Recorder(clock=h.clock)
        pool = nodepool("default")
        pool.spec.disruption.budgets = [Budget(nodes=budget)]
        h.store.create(pool)
        h.pairs = []
        for i in range(n):
            node, claim = node_claim_pair(f"n-{i}")
            h.store.create(claim)
            h.store.create(node)
            h.pairs.append((node, claim))
        h.informer.flush()
        return h

    def _mapping(self, h, reason):
        from karpenter_tpu.controllers.disruption.helpers import (
            build_disruption_budget_mapping,
        )

        return build_disruption_budget_mapping(
            h.store, h.cluster, h.clock, h.recorder, reason
        )

    def test_unmanaged_nodes_not_counted(self):
        # suite_test.go:699
        from helpers import registered_node

        h = self._harness()
        bare = registered_node(name="unmanaged")
        del bare.metadata.labels["karpenter.sh/nodepool"]
        h.store.create(bare)
        h.informer.flush()
        for reason in self.REASONS:
            assert self._mapping(h, reason)["default"] == 10

    def test_uninitialized_nodes_not_counted(self):
        # suite_test.go:712
        from karpenter_tpu.apis import labels as wk

        from helpers import node_claim_pair

        h = self._harness()
        node, claim = node_claim_pair("uninit")
        node.metadata.labels[wk.NODE_INITIALIZED_LABEL_KEY] = "false"
        h.store.create(claim)
        h.store.create(node)
        h.informer.flush()
        for reason in self.REASONS:
            assert self._mapping(h, reason)["default"] == 10

    def test_terminating_nodes_not_counted(self):
        # suite_test.go:743
        from karpenter_tpu.apis.nodeclaim import CONDITION_INSTANCE_TERMINATING

        from helpers import node_claim_pair

        h = self._harness()
        node, claim = node_claim_pair("term")
        claim.set_condition(CONDITION_INSTANCE_TERMINATING, "True")
        h.store.create(claim)
        h.store.create(node)
        h.informer.flush()
        for reason in self.REASONS:
            assert self._mapping(h, reason)["default"] == 10

    def test_never_negative(self):
        # suite_test.go:775 — 10% of 10 allows 1, but 10 are already
        # disrupting: clamp at zero
        h = self._harness(budget="10%")
        h.cluster.mark_for_deletion(
            *(f"kwok://{node.metadata.name}" for node, _ in h.pairs)
        )
        for reason in self.REASONS:
            assert self._mapping(h, reason)["default"] == 0

    def test_deleting_and_marked_counted_as_disrupting(self):
        # suite_test.go:796 — one deleted pair + one MarkedForDeletion: 8
        h = self._harness()
        node0, claim0 = h.pairs[0]
        claim0.metadata.finalizers.append("karpenter.sh/test-finalizer")
        h.store.update(claim0)
        h.store.delete(claim0)
        h.informer.flush()
        node1, _ = h.pairs[1]
        h.cluster.mark_for_deletion(f"kwok://{node1.metadata.name}")
        for reason in self.REASONS:
            assert self._mapping(h, reason)["default"] == 8

    def test_not_ready_counted_as_disrupting(self):
        # suite_test.go:820 — two NotReady nodes: 8
        from karpenter_tpu.apis.core import Condition

        h = self._harness()
        for node, _ in h.pairs[:2]:
            node.status.conditions = [
                c for c in node.status.conditions if c.type != "Ready"
            ]
            node.status.conditions.append(Condition(type="Ready", status="False"))
            h.store.update(node)
        h.informer.flush()
        for reason in self.REASONS:
            assert self._mapping(h, reason)["default"] == 8


class TestBudgetScheduleWindows:
    """Satellite (ISSUE 2): build_disruption_budget_mapping under
    overlapping cron-windowed budgets and zero-budget (maintenance-freeze)
    windows — the simulator's interruption scenarios lean on this mapping
    to decide when replacements may be disrupted."""

    def _harness(self, budgets, n=10):
        from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
        from karpenter_tpu.events.recorder import Recorder
        from karpenter_tpu.runtime.store import Store
        from karpenter_tpu.state.cluster import Cluster
        from karpenter_tpu.state.informer import StateInformer
        from karpenter_tpu.utils.clock import FakeClock

        from helpers import node_claim_pair, nodepool

        class H:
            pass

        h = H()
        h.clock = FakeClock()
        h.store = Store(clock=h.clock)
        h.provider = FakeCloudProvider()
        h.cluster = Cluster(h.clock, h.store, h.provider)
        h.informer = StateInformer(h.store, h.cluster)
        h.recorder = Recorder(clock=h.clock)
        pool = nodepool("default")
        pool.spec.disruption.budgets = list(budgets)
        h.store.create(pool)
        h.pairs = []
        for i in range(n):
            node, claim = node_claim_pair(f"n-{i}")
            h.store.create(claim)
            h.store.create(node)
            h.pairs.append((node, claim))
        h.informer.flush()
        return h

    def _mapping(self, h, reason="Empty"):
        from karpenter_tpu.controllers.disruption.helpers import (
            build_disruption_budget_mapping,
        )

        return build_disruption_budget_mapping(
            h.store, h.cluster, h.clock, h.recorder, reason
        )

    def test_overlapping_windows_most_restrictive_wins(self):
        budgets = [
            Budget(nodes="3", schedule="0 9 * * *", duration=4 * 3600.0),
            Budget(nodes="1", schedule="0 10 * * *", duration=2 * 3600.0),
        ]
        h = self._harness(budgets)
        # 10:30 — both windows active: min(3, 1)
        h.clock.set_time(ts(2026, 7, 29, 10, 30))
        assert self._mapping(h)["default"] == 1
        # 09:30 — only the wide window is active
        h.clock.set_time(ts(2026, 7, 29, 9, 30))
        assert self._mapping(h)["default"] == 3
        # 12:30 — the narrow window closed at 12:00, the wide one runs to 13:00
        h.clock.set_time(ts(2026, 7, 29, 12, 30))
        assert self._mapping(h)["default"] == 3
        # 14:00 — both inactive: unrestricted
        h.clock.set_time(ts(2026, 7, 29, 14, 0))
        assert self._mapping(h)["default"] == 10

    def test_zero_budget_window_blocks_and_publishes(self):
        h = self._harness(
            [Budget(nodes="0", schedule="0 9 * * *", duration=3600.0)]
        )
        h.clock.set_time(ts(2026, 7, 29, 9, 30))
        assert self._mapping(h)["default"] == 0
        blocked = [e for e in h.recorder.events if e.reason == "DisruptionBlocked"]
        assert len(blocked) == 1
        # window over: unrestricted again, no new block event
        h.clock.set_time(ts(2026, 7, 29, 11, 0))
        assert self._mapping(h)["default"] == 10

    def test_zero_budget_window_scoped_to_reason(self):
        h = self._harness(
            [
                Budget(
                    nodes="0",
                    reasons=["Drifted"],
                    schedule="0 9 * * *",
                    duration=3600.0,
                )
            ]
        )
        h.clock.set_time(ts(2026, 7, 29, 9, 30))
        assert self._mapping(h, "Drifted")["default"] == 0
        assert self._mapping(h, "Empty")["default"] == 10

    def test_window_boundaries(self):
        b = Budget(nodes="0", schedule="0 9 * * *", duration=3600.0)
        h = self._harness([b])
        # inclusive at the opening instant
        h.clock.set_time(ts(2026, 7, 29, 9, 0))
        assert self._mapping(h)["default"] == 0
        # exclusive at the closing instant (now - start == duration)
        h.clock.set_time(ts(2026, 7, 29, 10, 0))
        assert self._mapping(h)["default"] == 10

    def test_active_window_still_subtracts_disrupting(self):
        budgets = [Budget(nodes="2", schedule="0 9 * * *", duration=3600.0)]
        h = self._harness(budgets)
        h.clock.set_time(ts(2026, 7, 29, 9, 30))
        node0, _ = h.pairs[0]
        h.cluster.mark_for_deletion(f"kwok://{node0.metadata.name}")
        assert self._mapping(h)["default"] == 1
