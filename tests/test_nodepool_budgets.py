import datetime as dt

from karpenter_tpu.apis.nodepool import Budget, NodePool


def ts(y, mo, d, h, mi):
    return dt.datetime(y, mo, d, h, mi, tzinfo=dt.timezone.utc).timestamp()


class TestBudgets:
    def test_percentage_rounds_up(self):
        # default 10% must allow 1 disruption even on small nodepools
        assert Budget(nodes="10%").allowed_disruptions(5, 0.0) == 1
        assert Budget(nodes="10%").allowed_disruptions(25, 0.0) == 3
        assert Budget(nodes="0%").allowed_disruptions(5, 0.0) == 0

    def test_absolute(self):
        assert Budget(nodes="3").allowed_disruptions(100, 0.0) == 3

    def test_schedule_without_duration_fails_closed(self):
        b = Budget(nodes="100%", schedule="@daily", duration=None)
        assert b.allowed_disruptions(10, ts(2026, 7, 29, 0, 30)) == 0

    def test_schedule_window(self):
        b = Budget(nodes="0", schedule="0 9 * * *", duration=3600.0)
        # inside window: restricted to 0
        assert b.allowed_disruptions(10, ts(2026, 7, 29, 9, 30)) == 0
        # outside window: unrestricted
        assert b.allowed_disruptions(10, ts(2026, 7, 29, 11, 30)) == 10

    def test_nodepool_most_restrictive_and_reasons(self):
        np_ = NodePool()
        np_.spec.disruption.budgets = [
            Budget(nodes="50%"),
            Budget(nodes="2", reasons=["Drifted"]),
        ]
        assert np_.allowed_disruptions("Empty", 10, 0.0) == 5
        assert np_.allowed_disruptions("Drifted", 10, 0.0) == 2
