"""One-dispatch solve unit specs (ops/fused.py + packer._solve_scan).

Decision parity lives in tests/test_device_parity.py's `fused*` classes;
this file covers the machinery around the scan: the decline taxonomy and
its metering, the post-dispatch abort → host-walk fallback, the AOT
fused-scan rungs (warm start → zero-compile serve), the per-batch dispatch
accounting on /debug/kernels, and the solverd stats surface."""

import json

import pytest

from karpenter_tpu.apis.core import (
    Condition,
    Container,
    ObjectMeta,
    Pod,
    PodSpec,
)
from karpenter_tpu.cloudprovider.kwok.instance_types import construct_instance_types
from karpenter_tpu.observability import kernels as kobs
from karpenter_tpu.ops import ffd
from karpenter_tpu.ops import fused
from karpenter_tpu.ops.catalog import CatalogEngine
from karpenter_tpu.utils.resources import parse_resource_list

from helpers import nodepool
from test_scheduler import Env

CATALOG = construct_instance_types()


def plain_pods(n: int = 128, cpus=("250m", "500m", "1", "2")):
    pods = []
    for i in range(n):
        p = Pod(
            metadata=ObjectMeta(name=f"fu-{i:05d}", uid=f"fu-uid-{i:05d}"),
            spec=PodSpec(
                containers=[
                    Container(
                        requests=parse_resource_list(
                            {"cpu": cpus[i % len(cpus)], "memory": "512Mi"}
                        )
                    )
                ]
            ),
        )
        p.metadata.creation_timestamp = 0.0
        p.status.conditions.append(
            Condition(type="PodScheduled", status="False", reason="Unschedulable")
        )
        pods.append(p)
    return pods


@pytest.fixture
def fused_on():
    old = fused.FUSED_MODE
    fused.FUSED_MODE = "on"
    yield
    fused.FUSED_MODE = old


def decline_delta(before: dict) -> dict:
    return {
        k: v - before.get(k, 0)
        for k, v in fused.FUSED_DECLINES.items()
        if v != before.get(k, 0)
    }


class TestMode:
    def test_mode_resolution(self, monkeypatch):
        monkeypatch.setattr(fused, "FUSED_MODE", "on")
        assert fused.fused_enabled()
        monkeypatch.setattr(fused, "FUSED_MODE", "off")
        assert not fused.fused_enabled()
        # auto on this CI box = CPU backend = off (the native kernel wins
        # where there is no dispatch RTT to fuse away)
        monkeypatch.setattr(fused, "FUSED_MODE", "auto")
        import jax

        assert fused.fused_enabled() == (jax.default_backend() != "cpu")

    def test_fused_off_never_routes(self, monkeypatch):
        monkeypatch.setattr(fused, "FUSED_MODE", "off")
        f0 = fused.FUSED_SOLVES
        env = Env(node_pools=[nodepool("default")], engine=CatalogEngine(CATALOG))
        results = env.schedule(plain_pods())
        assert not results.pod_errors
        assert fused.FUSED_SOLVES == f0


class TestDeclineTaxonomy:
    def test_minvalues_declines_metered(self, fused_on):
        d0 = dict(fused.FUSED_DECLINES)
        pool = nodepool(
            "minpool",
            requirements=[
                {
                    "key": "node.kubernetes.io/instance-type",
                    "operator": "Exists",
                    "minValues": 2,
                }
            ],
        )
        env = Env(node_pools=[pool], engine=CatalogEngine(CATALOG))
        results = env.schedule(plain_pods())
        assert not results.pod_errors
        assert decline_delta(d0).get("min") == 1

    def test_solver_cache_counters_carry_fused_series(self, fused_on):
        env = Env(node_pools=[nodepool("default")], engine=CatalogEngine(CATALOG))
        env.schedule(plain_pods())
        snap = ffd.solver_cache_counters()
        assert "fused_solves" in snap
        assert snap["fused_solves"] == fused.FUSED_SOLVES

    def test_claim_overflow_aborts_to_host_walk(self, fused_on, monkeypatch):
        """A scan that runs out of claim slots must abort the dispatch,
        meter `claim-overflow`, and let the host walk re-solve — identical
        results, never a wrong answer."""
        orig = fused._pow2

        def tiny_claims(n, floor):
            if floor == 256:  # only the claim-axis bucket uses this floor
                return 4
            return orig(n, floor)

        monkeypatch.setattr(fused, "_pow2", tiny_claims)
        monkeypatch.setattr(
            fused._FusedSolve,
            "_claim_estimate",
            lambda self, *a: 1,
        )
        d0 = dict(fused.FUSED_DECLINES)
        f0 = fused.FUSED_SOLVES
        env = Env(node_pools=[nodepool("default")], engine=CatalogEngine(CATALOG))
        # 4 request tiers -> far more than 4 claims
        results = env.schedule(plain_pods(192, cpus=("7", "15", "3", "2")))
        assert not results.pod_errors
        assert results.new_node_claims, "host-walk fallback produced nothing"
        assert fused.FUSED_SOLVES == f0
        assert decline_delta(d0).get("claim-overflow") == 1

    def test_decline_is_not_a_device_fallback(self, fused_on):
        """A fused decline continues to the host-walk drivers INSIDE the
        device path — DEVICE_FALLBACKS (host per-pod loop) must not move."""
        pool = nodepool(
            "minpool",
            requirements=[
                {
                    "key": "node.kubernetes.io/instance-type",
                    "operator": "Exists",
                    "minValues": 2,
                }
            ],
        )
        fb0 = ffd.DEVICE_FALLBACKS
        env = Env(node_pools=[pool], engine=CatalogEngine(CATALOG))
        env.schedule(plain_pods())
        assert ffd.DEVICE_FALLBACKS == fb0


class TestFusedAOT:
    def test_warm_start_covers_fused_rungs(self, fused_on, tmp_path):
        """With the fused path on, the AOT walk compiles the scan rungs and
        a serve-time dispatch is answered from the executable table —
        zero compiles, aot_served counted."""
        from karpenter_tpu.aot import compiler, ladder
        from karpenter_tpu.aot import runtime as aotrt
        from karpenter_tpu.aot.cache import ExecutableCache

        reg = kobs.registry()
        cache = ExecutableCache(str(tmp_path / "aot"))
        aotrt.configure(ladder.DEFAULT, cache)
        try:
            engine = CatalogEngine(CATALOG)
            summary = compiler.warm_start(engine, ladder.DEFAULT, cache)
            assert summary["errors"] == 0
            scan_execs = [
                e
                for e in aotrt.executables()
                if e["kernel"] == "packer.solve_scan"
            ]
            assert len(scan_execs) == len(
                ladder.DEFAULT.buckets("packer.solve_scan")
            )
            snap0 = reg.debug_snapshot(kernel="packer.solve_scan") or {
                "aot_served": 0, "compiles": 0,
            }
            env = Env(node_pools=[nodepool("default")], engine=engine)
            results = env.schedule(plain_pods())
            assert not results.pod_errors
            snap = reg.debug_snapshot(kernel="packer.solve_scan")
            assert snap["aot_served"] == snap0["aot_served"] + 1
            assert snap["compiles"] == snap0["compiles"]
        finally:
            aotrt.configure(None, None)
            aotrt.clear_executables()

    def test_fused_off_walk_skips_scan_rungs(self, monkeypatch, tmp_path):
        """A fused-off boot must not pay the while_loop compiles: the walk
        skips the scan rungs entirely."""
        from karpenter_tpu.aot import compiler, ladder
        from karpenter_tpu.aot import runtime as aotrt
        from karpenter_tpu.aot.cache import ExecutableCache

        monkeypatch.setattr(fused, "FUSED_MODE", "off")
        cache = ExecutableCache(str(tmp_path / "aot"))
        aotrt.configure(ladder.DEFAULT, cache)
        try:
            engine = CatalogEngine(CATALOG)
            compiler.warm_start(engine, ladder.DEFAULT, cache)
            assert not [
                e
                for e in aotrt.executables()
                if e["kernel"] == "packer.solve_scan"
            ]
        finally:
            aotrt.configure(None, None)
            aotrt.clear_executables()


class TestBatchDispatchSurface:
    def test_batch_scope_counts_and_ring(self, fused_on):
        reg = kobs.registry()
        env = Env(node_pools=[nodepool("default")], engine=CatalogEngine(CATALOG))
        env.schedule(plain_pods())  # warm
        with reg.batch_scope(label="spec") as acc:
            env.schedule(plain_pods())
        assert acc["dispatches"] == 1
        assert acc["kernels"] == {"packer.solve_scan": 1}
        last = reg.last_batches(1)[-1]
        assert last["label"] == "spec"
        assert last["dispatches"] == 1
        assert last["kernels"] == {"packer.solve_scan": 1}

    def test_debug_kernels_serves_per_batch_counts(self, fused_on):
        """Satellite fix: /debug/kernels used to show only cumulative
        per-kernel totals — the ==1 per-batch invariant is now observable
        at runtime via the `batches` section."""
        from test_serving_debug import get, make_server

        reg = kobs.registry()
        env = Env(node_pools=[nodepool("default")], engine=CatalogEngine(CATALOG))
        env.schedule(plain_pods())  # warm
        with reg.batch_scope(label="serving-spec"):
            env.schedule(plain_pods())
        server = make_server(kernel_snapshot=reg.debug_snapshot)
        try:
            code, body = get(server, "/debug/kernels")
            assert code == 200
            table = json.loads(body)
            assert table["batches"]["last"] is not None
            recent = table["batches"]["recent"]
            entry = [b for b in recent if b["label"] == "serving-spec"][-1]
            assert entry["dispatches"] == 1
            assert entry["kernels"] == {"packer.solve_scan": 1}
        finally:
            server.stop()

    def test_solverd_stats_surface_last_batch_dispatches(self):
        from karpenter_tpu.solverd.service import SolverService

        svc = SolverService()
        assert svc.stats()["last_batch_dispatches"] == 0
