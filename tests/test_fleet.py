"""solverd fleet: pool-aware client failover, affinity routing, request-id
dedup, tenant fairness, graceful drain, and the admission pipeline
(ISSUE 10 acceptance criteria)."""

import threading
import time

import pytest

from karpenter_tpu.operator.harness import CircuitBreaker
from karpenter_tpu.solverd import (
    KIND_SOLVE,
    AdmissionPipeline,
    AdmissionQueue,
    DrainingError,
    FleetClient,
    InProcessClient,
    QueueFullError,
    SocketClient,
    SolveRequest,
    SolverClient,
    SolverDaemon,
    SolverService,
    TenantQuotaExceededError,
    TransportError,
    build_solver,
    parse_tenant_weights,
)
from karpenter_tpu.utils.clock import FakeClock

from test_solverd import build_scheduler, decisions


class FakeReplica(SolverClient):
    """A scriptable replica: answers (replica_id, request_id) or raises the
    scripted error. Records every prepared request it saw."""

    transport = "fake"

    def __init__(self, rid, fail_with=None):
        self.rid = rid
        self.fail_with = fail_with
        self.calls = []

    def encode(self, kind, scheduler, pods, timeout=None, deadline=None,
               request_id=None, tenant=None, trace_carrier=None):
        from karpenter_tpu.solverd import new_request_id

        return {
            "kind": kind,
            "scheduler": scheduler,
            "request_id": request_id or new_request_id(),
            "tenant": tenant,
        }

    def solve_prepared(self, prepared):
        self.calls.append(prepared)
        if self.fail_with is not None:
            raise self.fail_with
        return (self.rid, prepared["request_id"])

    def solve_many(self, kind, batch, timeout=None, deadline=None, group=None,
                   nested=False, request_ids=None, tenant=None):
        self.calls.append({"group": group, "request_ids": request_ids})
        if self.fail_with is not None:
            raise self.fail_with
        return [(self.rid, rid) for rid in request_ids]


def fleet_of(n=2, clock=None, tenant="t", threshold=3, cooldown=5.0):
    replicas = [FakeReplica(f"r{i}") for i in range(n)]
    client = FleetClient(
        [(r.rid, r) for r in replicas],
        clock=clock or FakeClock(),
        tenant=tenant,
        breaker_threshold=threshold,
        breaker_cooldown=cooldown,
    )
    return client, replicas


class SchedStub:
    engine = None
    clock = FakeClock()


class TestRouting:
    def test_affinity_is_deterministic_and_sticky(self):
        client, replicas = fleet_of(3)
        first = client.solve(KIND_SOLVE, SchedStub(), [])[0]
        for _ in range(5):
            assert client.solve(KIND_SOLVE, SchedStub(), [])[0] == first

    def test_tenants_spread_over_replicas(self):
        # with enough tenants, rendezvous hashing must not collapse onto
        # one replica
        clock = FakeClock()
        hit = set()
        for i in range(16):
            client, _ = fleet_of(4, clock=clock, tenant=f"tenant-{i}")
            hit.add(client.solve(KIND_SOLVE, SchedStub(), [])[0])
        assert len(hit) >= 2

    def test_unhealthy_preferred_replica_skipped(self):
        client, replicas = fleet_of(2)
        preferred = client.solve(KIND_SOLVE, SchedStub(), [])[0]
        handle = next(
            r for r in client._replicas if r.replica_id == preferred
        )
        # force its breaker open
        for _ in range(3):
            handle.breaker.record_failure()
        assert handle.breaker.state == CircuitBreaker.OPEN
        other = client.solve(KIND_SOLVE, SchedStub(), [])[0]
        assert other != preferred


class TestFailover:
    def test_transport_error_fails_over_and_opens_breaker(self):
        client, replicas = fleet_of(2, threshold=2)
        preferred = client.solve(KIND_SOLVE, SchedStub(), [])[0]
        dead = next(r for r in replicas if r.rid == preferred)
        dead.fail_with = TransportError("connection refused")
        # each solve: dead replica fails -> survivor answers
        for _ in range(2):
            rid, _req = client.solve(KIND_SOLVE, SchedStub(), [])
            assert rid != preferred
        stats = client.stats()
        assert stats["failovers"] == 2
        assert stats["replays"] == 2
        breakers = {r["id"]: r["breaker"] for r in stats["replicas"]}
        assert breakers[preferred] == CircuitBreaker.OPEN
        assert stats["healthy_replicas"] == 1
        # breaker open: the dead replica is no longer attempted
        calls_before = len(dead.calls)
        client.solve(KIND_SOLVE, SchedStub(), [])
        assert len(dead.calls) == calls_before

    def test_request_id_pinned_across_failover(self):
        client, replicas = fleet_of(2)
        preferred = client.solve(KIND_SOLVE, SchedStub(), [])[0]
        dead = next(r for r in replicas if r.rid == preferred)
        survivor = next(r for r in replicas if r.rid != preferred)
        dead.fail_with = TransportError("gone")
        _rid, req_id = client.solve(KIND_SOLVE, SchedStub(), [])
        # the dead replica SAW the request (same id) before the failover
        assert dead.calls[-1]["request_id"] == req_id
        assert survivor.calls[-1]["request_id"] == req_id

    def test_rejections_do_not_fail_over(self):
        client, replicas = fleet_of(2)
        for r in replicas:
            r.fail_with = QueueFullError("full")
        with pytest.raises(QueueFullError):
            client.solve(KIND_SOLVE, SchedStub(), [])
        assert client.stats()["failovers"] == 0
        # exactly one replica was asked: backpressure answers surface as-is
        assert sum(len(r.calls) for r in replicas) == 1

    def test_tenant_quota_does_not_fail_over(self):
        client, replicas = fleet_of(2)
        for r in replicas:
            r.fail_with = TenantQuotaExceededError("quota")
        with pytest.raises(TenantQuotaExceededError):
            client.solve(KIND_SOLVE, SchedStub(), [])
        assert sum(len(r.calls) for r in replicas) == 1

    def test_draining_replica_fails_over_and_is_routed_around(self):
        clock = FakeClock()
        client, replicas = fleet_of(2, clock=clock, cooldown=5.0)
        preferred = client.solve(KIND_SOLVE, SchedStub(), [])[0]
        draining = next(r for r in replicas if r.rid == preferred)
        draining.fail_with = DrainingError("draining")
        rid, _ = client.solve(KIND_SOLVE, SchedStub(), [])
        assert rid != preferred
        stats = client.stats()
        assert stats["draining_failovers"] == 1
        assert stats["healthy_replicas"] == 1
        # routed around without another attempt while the window holds
        calls_before = len(draining.calls)
        client.solve(KIND_SOLVE, SchedStub(), [])
        assert len(draining.calls) == calls_before

    def test_drained_replica_rejoins_after_cooldown_window(self):
        """A drained replica must NOT be exiled forever: the draining
        window expires like a breaker cooldown, the next solve probes it,
        and a success restores it to rotation — the rolling-restart path
        where every replica drains once."""
        clock = FakeClock()
        client, replicas = fleet_of(2, clock=clock, cooldown=5.0)
        preferred = client.solve(KIND_SOLVE, SchedStub(), [])[0]
        draining = next(r for r in replicas if r.rid == preferred)
        draining.fail_with = DrainingError("draining")
        client.solve(KIND_SOLVE, SchedStub(), [])
        assert client.stats()["healthy_replicas"] == 1
        # the restarted replica is back; the window expires; it is probed
        # and rejoins with its affinity share
        draining.fail_with = None
        clock.step(6.0)
        assert client.stats()["healthy_replicas"] == 2
        assert client.solve(KIND_SOLVE, SchedStub(), [])[0] == preferred

    def test_rolling_drain_of_every_replica_never_bricks_the_pool(self):
        clock = FakeClock()
        client, replicas = fleet_of(2, clock=clock, cooldown=5.0)
        for victim in replicas:
            victim.fail_with = DrainingError("rolling restart")
            client.solve(KIND_SOLVE, SchedStub(), [])  # served by the other
            victim.fail_with = None
            clock.step(6.0)  # restart finishes inside the window
        # both replicas drained once and both are back
        assert client.stats()["healthy_replicas"] == 2
        assert client.solve(KIND_SOLVE, SchedStub(), [])

    def test_all_replicas_dead_raises_typed_retryable(self):
        client, replicas = fleet_of(2)
        for r in replicas:
            r.fail_with = TransportError("refused")
        with pytest.raises(TransportError) as exc:
            client.solve(KIND_SOLVE, SchedStub(), [])
        assert exc.value.retryable is True
        assert "exhausted" in str(exc.value)

    def test_all_breakers_open_fast_fails(self):
        clock = FakeClock()
        client, replicas = fleet_of(2, clock=clock, threshold=1)
        for r in replicas:
            r.fail_with = TransportError("refused")
        with pytest.raises(TransportError):
            client.solve(KIND_SOLVE, SchedStub(), [])
        # both breakers open now: no replica is attempted at all
        calls = sum(len(r.calls) for r in replicas)
        with pytest.raises(TransportError) as exc:
            client.solve(KIND_SOLVE, SchedStub(), [])
        assert "no healthy replica" in str(exc.value)
        assert sum(len(r.calls) for r in replicas) == calls
        assert "error" in client.stats()
        # cooldown elapses -> half-open probe flows again
        clock.step(10.0)
        for r in replicas:
            r.fail_with = None
        assert client.solve(KIND_SOLVE, SchedStub(), [])[0] in {"r0", "r1"}
        assert client.stats()["healthy_replicas"] >= 1

    def test_finish_failure_with_no_sibling_chains_the_real_error(self):
        """In-flight finish fails and every sibling is inadmissible: the
        raise must carry the actual transport failure, not a misleading
        'no healthy replica' total-outage answer."""
        client, replicas = fleet_of(2, threshold=1)
        preferred = client.solve(KIND_SOLVE, SchedStub(), [])[0]
        begun = next(r for r in replicas if r.rid == preferred)
        sibling = next(r for r in client._replicas if r.replica_id != preferred)
        for _ in range(2):
            sibling.breaker.record_failure()  # sibling already open
        begun.fail_with = TransportError("connection reset mid-reply")
        token = client.solve_begin(
            client.encode(KIND_SOLVE, SchedStub(), [])
        )
        with pytest.raises(TransportError) as exc:
            client.solve_finish(token)
        assert "connection reset mid-reply" in str(exc.value)

    def test_solve_many_routes_whole_group_and_pins_ids(self):
        client, replicas = fleet_of(2)
        out = client.solve_many(KIND_SOLVE, [(SchedStub(), []), (SchedStub(), [])])
        served = {rid for (rid, _), _err in zip(out, [None, None])}
        assert len(served) == 1  # one replica served the whole group
        # now kill the serving replica: the group replays as a unit with
        # the same ids
        serving = next(r for r in replicas if r.rid in served)
        survivor = next(r for r in replicas if r.rid not in served)
        serving.fail_with = TransportError("gone")
        out2 = client.solve_many(
            KIND_SOLVE, [(SchedStub(), []), (SchedStub(), [])]
        )
        assert all(rid == survivor.rid for (rid, _), _e in zip(out2, [0, 0]))
        assert (
            serving.calls[-1]["request_ids"]
            == survivor.calls[-1]["request_ids"]
        )


class TestRequestIdDedup:
    def test_service_executes_a_replayed_id_once(self):
        svc = SolverService(clock=FakeClock())
        scheduler, pods = build_scheduler(n_pods=2)
        req = SolveRequest(
            KIND_SOLVE, scheduler, pods, timeout=60.0, request_id="req-x"
        )
        first = svc.solve(req)
        # the replay: same id, fresh request object (as a re-sent frame
        # decodes into)
        scheduler2, pods2 = build_scheduler(n_pods=2)
        replay = SolveRequest(
            KIND_SOLVE, scheduler2, pods2, timeout=60.0, request_id="req-x"
        )
        second = svc.solve(replay)
        assert second is first  # answered from the dedup record
        assert svc.executed == 1
        assert svc.deduped == 1
        assert svc.executed_ids == {"req-x": 1}

    def test_replay_attaches_to_inflight_entry(self):
        svc = SolverService(clock=FakeClock())
        scheduler, pods = build_scheduler(n_pods=1)
        entry = svc.submit(
            SolveRequest(KIND_SOLVE, scheduler, pods, request_id="req-y")
        )
        again = svc.submit(
            SolveRequest(KIND_SOLVE, scheduler, pods, request_id="req-y")
        )
        assert again is entry
        assert svc.queue.depth() == 1  # never admitted twice
        svc.run_pending()
        assert svc.executed == 1

    def test_socket_replayed_frame_executes_once(self):
        svc = SolverService(clock=FakeClock())
        daemon = SolverDaemon(svc, address="127.0.0.1:0").start()
        client = SocketClient(daemon.address)
        try:
            scheduler, pods = build_scheduler(n_pods=2)
            prepared = client.encode(KIND_SOLVE, scheduler, pods, 60.0)
            r1 = client.solve_prepared(prepared)
            # the _rpc replay path re-sends the SAME frame verbatim
            r2 = client.solve_prepared(prepared)
            assert decisions(r1) == decisions(r2)
            assert svc.executed == 1
            assert svc.deduped == 1
        finally:
            client.close()
            daemon.stop()
            svc.close()

    def test_midgroup_shed_releases_cancelled_dedup_slots(self):
        """A shed solve_many group un-admits its siblings AND releases
        their dedup slots: a replay of the same ids (the lost-error-reply
        path) must re-admit and execute fresh, never attach to cancelled
        entries that no drain will ever finish."""
        svc = SolverService(clock=FakeClock(), max_queue_depth=2)
        reqs = []
        for i in range(3):
            s, p = build_scheduler(n_pods=1)
            reqs.append(
                SolveRequest(
                    KIND_SOLVE, s, list(p), timeout=60.0,
                    request_id=f"grp-{i}",
                )
            )
        with pytest.raises(QueueFullError):
            svc.solve_many(reqs)
        assert svc._dedup == {}  # cancelled ids released
        # the replayed group (same ids) admits and executes normally
        replay = []
        for i in range(2):
            s, p = build_scheduler(n_pods=1)
            replay.append(
                SolveRequest(
                    KIND_SOLVE, s, list(p), timeout=60.0,
                    request_id=f"grp-{i}",
                )
            )
        entries = svc.solve_many(replay)
        assert all(e.error is None for e in entries)
        assert svc.executed == 2
        svc.close()

    def test_midgroup_shed_keeps_other_callers_dedup_entries(self):
        """A dedup hit hands solve_many ANOTHER caller's in-flight entry;
        shedding the group must not un-admit it or release its slot — its
        real owner is still waiting on it."""
        svc = SolverService(clock=FakeClock(), max_queue_depth=2)
        s0, p0 = build_scheduler(n_pods=1)
        other = svc.submit(
            SolveRequest(KIND_SOLVE, s0, list(p0), timeout=60.0,
                         request_id="owned-elsewhere")
        )
        reqs = []
        for i, rid in enumerate(["owned-elsewhere", "grp-a", "grp-b"]):
            s, p = build_scheduler(n_pods=1)
            reqs.append(
                SolveRequest(KIND_SOLVE, s, list(p), timeout=60.0,
                             request_id=rid)
            )
        with pytest.raises(QueueFullError):
            svc.solve_many(reqs)  # grp-b tops the depth-2 queue
        # the other caller's entry survived the group cancel
        assert svc._dedup.get("owned-elsewhere") is other
        assert svc.queue.depth() == 1
        assert svc.run_pending() == 1
        assert other.done and other.error is None
        svc.close()

    def test_dedup_record_does_not_pin_the_request(self):
        svc = SolverService(clock=FakeClock())
        scheduler, pods = build_scheduler(n_pods=1)
        svc.solve(
            SolveRequest(KIND_SOLVE, scheduler, pods, request_id="req-z")
        )
        from karpenter_tpu.solverd.service import _Completed

        assert isinstance(svc._dedup["req-z"], _Completed)


class TestTenantFairness:
    def _entry(self, tenant, deadline=None):
        class E:
            def __init__(self):
                self.request = SolveRequest(
                    KIND_SOLVE, None, [], tenant=tenant, deadline=deadline
                )
                self.enqueued_at = 0.0

        return E()

    def test_quota_sheds_noisy_tenant_only(self):
        q = AdmissionQueue(FakeClock(), max_depth=16, tenant_quota=3)
        for _ in range(3):
            q.offer(self._entry("noisy"))
        with pytest.raises(TenantQuotaExceededError):
            q.offer(self._entry("noisy"))
        # the quiet tenant's headroom is untouched
        q.offer(self._entry("quiet"))
        assert q.tenant_depths() == {"noisy": 3, "quiet": 1}

    def test_quota_zero_disables(self):
        q = AdmissionQueue(FakeClock(), max_depth=8, tenant_quota=0)
        for _ in range(8):
            q.offer(self._entry("only"))
        with pytest.raises(QueueFullError):
            q.offer(self._entry("only"))

    def test_weighted_fair_drain_interleaves(self):
        q = AdmissionQueue(
            FakeClock(), tenant_quota=0,
            tenant_weights={"gold": 2.0, "free": 1.0},
        )
        entries = []
        for _ in range(4):
            entries.append(self._entry("free"))
            q.offer(entries[-1])
        for _ in range(4):
            entries.append(self._entry("gold"))
            q.offer(entries[-1])
        ready, _ = q.drain()
        order = [e.request.tenant for e in ready]
        # gold (weight 2) lands 2 entries before free's first repeat wave;
        # free is NOT pushed behind gold's whole burst either
        assert order[0] == "gold"  # 1/2 < 1/1
        assert "free" in order[:3]
        assert order != ["free"] * 4 + ["gold"] * 4  # not FIFO
        assert sorted(order) == ["free"] * 4 + ["gold"] * 4

    def test_single_tenant_batch_keeps_fifo(self):
        q = AdmissionQueue(FakeClock(), tenant_weights={"a": 2.0})
        entries = [self._entry("a") for _ in range(4)]
        for e in entries:
            q.offer(e)
        ready, _ = q.drain()
        assert ready == entries

    def test_remove_rebuilds_tenant_depths(self):
        q = AdmissionQueue(FakeClock(), tenant_quota=2)
        first = self._entry("t")
        q.offer(first)
        q.offer(self._entry("t"))
        assert q.remove([first]) == [first]
        # quota headroom returned by the un-admit
        q.offer(self._entry("t"))
        assert q.tenant_depths() == {"t": 2}

    def test_parse_tenant_weights(self):
        assert parse_tenant_weights("gold=4, free=1") == {
            "gold": 4.0, "free": 1.0,
        }
        assert parse_tenant_weights("") == {}
        assert parse_tenant_weights("bad, x=0, y=-1, z=nan2") == {}

    def test_tenant_rides_the_wire(self):
        svc = SolverService(clock=FakeClock())
        daemon = SolverDaemon(svc, address="127.0.0.1:0").start()
        client = SocketClient(daemon.address, tenant="cluster-a")
        seen = []
        orig = svc.submit

        def spy(request):
            seen.append((request.tenant, bool(request.request_id)))
            return orig(request)

        svc.submit = spy
        try:
            scheduler, pods = build_scheduler(n_pods=1)
            client.solve(KIND_SOLVE, scheduler, pods, timeout=60.0)
            assert seen == [("cluster-a", True)]
        finally:
            client.close()
            daemon.stop()
            svc.close()


class TestGracefulDrain:
    def test_drain_rejects_new_work_typed(self):
        svc = SolverService(clock=FakeClock())
        svc.drain()
        scheduler, pods = build_scheduler(n_pods=1)
        with pytest.raises(DrainingError) as exc:
            svc.submit(SolveRequest(KIND_SOLVE, scheduler, pods))
        assert exc.value.retryable is True
        assert exc.value.failover is True
        assert svc.quiesced()

    def test_inflight_finishes_while_draining(self):
        svc = SolverService(clock=FakeClock())
        started, release = threading.Event(), threading.Event()
        orig = svc.coalescer.execute

        def gated(entries):
            started.set()
            assert release.wait(timeout=5)
            return orig(entries)

        svc.coalescer.execute = gated
        scheduler, pods = build_scheduler(n_pods=1)
        result_box = []
        worker = threading.Thread(
            target=lambda: result_box.append(
                svc.solve(SolveRequest(KIND_SOLVE, scheduler, pods, timeout=60.0))
            )
        )
        worker.start()
        assert started.wait(timeout=5)
        svc.drain()
        assert not svc.quiesced()  # batch still executing
        release.set()
        worker.join(timeout=10)
        assert result_box and result_box[0].new_node_claims is not None
        assert svc.quiesced()

    def test_daemon_drain_and_stop_quiesces(self):
        svc = SolverService(clock=FakeClock())
        daemon = SolverDaemon(svc, address="127.0.0.1:0").start()
        client = SocketClient(daemon.address)
        try:
            scheduler, pods = build_scheduler(n_pods=1)
            client.solve(KIND_SOLVE, scheduler, pods, timeout=60.0)
            assert daemon.drain_and_stop(grace=5.0) is True
            # the listener is gone: a fresh solve fails typed + retryable
            with pytest.raises(TransportError):
                client.solve(KIND_SOLVE, scheduler, pods, timeout=60.0)
        finally:
            client.close()
            daemon.stop()
            svc.close()

    def test_draining_rejection_crosses_the_wire_typed(self):
        svc = SolverService(clock=FakeClock())
        daemon = SolverDaemon(svc, address="127.0.0.1:0").start()
        client = SocketClient(daemon.address)
        try:
            svc.drain()
            scheduler, pods = build_scheduler(n_pods=1)
            with pytest.raises(DrainingError):
                client.solve(KIND_SOLVE, scheduler, pods, timeout=60.0)
        finally:
            client.close()
            daemon.stop()
            svc.close()

    def test_mid_drain_client_fails_over_to_healthy_replica(self):
        """ISSUE 10 satellite 1: a client caught mid-drain re-routes the
        request to a replica that is not exiting — over real sockets."""
        clock = FakeClock()
        services = [SolverService(clock=clock) for _ in range(2)]
        daemons = [
            SolverDaemon(s, address="127.0.0.1:0", replica_id=f"r{i}").start()
            for i, s in enumerate(services)
        ]
        clients = [
            (d.replica_id, SocketClient(d.address)) for d in daemons
        ]
        fleet = FleetClient(clients, clock=clock, tenant="drain-test")
        try:
            scheduler, pods = build_scheduler(n_pods=1)
            first = fleet.solve(KIND_SOLVE, scheduler, pods, timeout=60.0)
            served = next(
                r.replica_id for r in fleet._replicas if r.solves == 1
            )
            idx = int(served[1:])
            services[idx].drain()  # SIGTERM landed on the serving replica
            scheduler2, pods2 = build_scheduler(n_pods=1)
            second = fleet.solve(KIND_SOLVE, scheduler2, pods2, timeout=60.0)
            assert decisions(first) == decisions(second)
            stats = fleet.stats()
            assert stats["draining_failovers"] == 1
            other = f"r{1 - idx}"
            assert {
                r["id"]: r["solves"] for r in stats["replicas"]
            }[other] == 1
        finally:
            fleet.close()
            for d in daemons:
                d.stop()
            for s in services:
                s.close()


class PipeStub(SolverClient):
    """Synthetic begin/finish transport with real wall costs: encode burns
    `encode_s` on the caller's thread; begin starts a timer thread standing
    in for the daemon's device execution; finish joins it."""

    transport = "stub"

    def __init__(self, encode_s=0.01, execute_s=0.02, fail_index=None):
        self.encode_s = encode_s
        self.execute_s = execute_s
        self.fail_index = fail_index
        self.encoded = 0

    def encode(self, kind, scheduler, pods, timeout=None, deadline=None,
               request_id=None, tenant=None, trace_carrier=None):
        index = self.encoded
        self.encoded += 1
        time.sleep(self.encode_s)
        if self.fail_index == ("encode", index):
            raise ValueError(f"encode {index} failed")
        return index

    def solve_begin(self, prepared):
        done = threading.Event()
        timer = threading.Timer(self.execute_s, done.set)
        timer.start()
        return (prepared, done)

    def solve_finish(self, handle):
        index, done = handle
        done.wait()
        if self.fail_index == ("solve", index):
            raise QueueFullError(f"solve {index} shed")
        return f"ok-{index}"

    def solve_prepared(self, prepared):
        return self.solve_finish(self.solve_begin(prepared))


class TestAdmissionPipeline:
    def test_results_in_order_with_per_item_errors(self):
        stub = PipeStub(encode_s=0.0, execute_s=0.0, fail_index=("solve", 1))
        pipeline = AdmissionPipeline(stub)
        out = pipeline.run(KIND_SOLVE, [(None, [])] * 3)
        assert out[0] == ("ok-0", None)
        assert out[1][0] is None and isinstance(out[1][1], QueueFullError)
        assert out[2] == ("ok-2", None)
        assert pipeline.stats()["batches"] == 3

    def test_encode_error_is_per_item(self):
        stub = PipeStub(encode_s=0.0, execute_s=0.0, fail_index=("encode", 1))
        out = AdmissionPipeline(stub).run(KIND_SOLVE, [(None, [])] * 3)
        assert out[0] == ("ok-0", None)
        assert isinstance(out[1][1], ValueError)
        assert out[2] == ("ok-2", None)

    def test_pipelined_hides_encode_behind_execution(self):
        stub = PipeStub(encode_s=0.01, execute_s=0.03)
        pipeline = AdmissionPipeline(stub)
        out = pipeline.run(KIND_SOLVE, [(None, [])] * 6)
        assert all(err is None for _r, err in out)
        stats = pipeline.stats()
        # 5 of 6 encodes ran while the previous batch executed
        assert stats["encode_overlap_fraction"] >= 0.5, stats
        assert stats["hidden_encode_s"] > 0

    def test_unpipelined_hides_nothing(self):
        stub = PipeStub(encode_s=0.005, execute_s=0.01)
        pipeline = AdmissionPipeline(stub)
        pipeline.run(KIND_SOLVE, [(None, [])] * 4, pipelined=False)
        assert pipeline.stats()["hidden_encode_s"] == 0.0
        assert pipeline.stats()["encode_overlap_fraction"] == 0.0

    def test_socket_inflight_begin_finish_roundtrip(self):
        svc = SolverService(clock=FakeClock())
        daemon = SolverDaemon(svc, address="127.0.0.1:0").start()
        client = SocketClient(daemon.address)
        try:
            scheduler, pods = build_scheduler(n_pods=2)
            direct = client.solve(KIND_SOLVE, scheduler, pods, timeout=60.0)
            scheduler2, pods2 = build_scheduler(n_pods=2)
            handle = client.solve_begin(
                client.encode(KIND_SOLVE, scheduler2, pods2, 60.0)
            )
            via_pipeline = client.solve_finish(handle)
            assert decisions(direct) == decisions(via_pipeline)
        finally:
            client.close()
            daemon.stop()
            svc.close()

    def test_socket_finish_replays_after_daemon_restart(self, tmp_path):
        """Reply lost mid-flight: the daemon restarts between begin and
        finish; finish replays the frame through the backoff path and the
        solve still answers (fresh daemon: executes once there)."""
        # unix socket: restart-on-same-address without TCP TIME_WAIT games
        address = str(tmp_path / "solverd.sock")
        svc = SolverService(clock=FakeClock())
        daemon = SolverDaemon(svc, address=address).start()
        client = SocketClient(address)
        scheduler, pods = build_scheduler(n_pods=1)
        handle = client.solve_begin(
            client.encode(KIND_SOLVE, scheduler, pods, 60.0)
        )
        daemon.stop()  # the reply will never come
        svc2 = SolverService(clock=FakeClock())
        daemon2 = SolverDaemon(svc2, address=address).start()
        try:
            result = client.solve_finish(handle)
            assert result.new_node_claims is not None
            assert svc2.executed == 1
        finally:
            client.close()
            daemon2.stop()
            svc2.close()
            svc.close()


class TestBuildSolver:
    def _opts(self, **kw):
        from karpenter_tpu.operator.options import Options

        return Options(**kw)

    def test_comma_list_builds_fleet(self):
        opts = self._opts(
            solver_transport="socket",
            solver_daemon_address="127.0.0.1:9901,127.0.0.1:9902",
            cluster_name="prod-a",
        )
        client = build_solver(opts, FakeClock())
        assert isinstance(client, FleetClient)
        assert client.tenant == "prod-a"
        assert [r.replica_id for r in client._replicas] == [
            "127.0.0.1:9901", "127.0.0.1:9902",
        ]

    def test_single_address_stays_plain_socket(self):
        opts = self._opts(
            solver_transport="socket",
            solver_daemon_address="127.0.0.1:9901",
            cluster_name="prod-a",
        )
        client = build_solver(opts, FakeClock())
        assert isinstance(client, SocketClient)
        assert client.tenant == "prod-a"

    def test_inprocess_gets_tenant_policy(self):
        opts = self._opts(
            solverd_tenant_quota=4,
            solverd_tenant_weights="gold=2,free=1",
            cluster_name="solo",
        )
        client = build_solver(opts, FakeClock())
        assert isinstance(client, InProcessClient)
        assert client.tenant == "solo"
        assert client.service.queue.tenant_quota == 4
        assert client.service.queue.tenant_weights == {
            "gold": 2.0, "free": 1.0,
        }
