"""Disruption stack: emptiness, consolidation (single/multi), drift,
budgets, validation, orchestration queue. Mirrors the reference's
disruption suite behaviors."""

import pytest

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.nodeclaim import (
    CONDITION_DISRUPTION_REASON,
    CONDITION_DRIFTED,
    CONDITION_INITIALIZED,
)
from karpenter_tpu.apis.nodepool import Budget
from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
from karpenter_tpu.controllers.disruption import Controller as DisruptionController
from karpenter_tpu.controllers.disruption import Queue as DisruptionQueue
from karpenter_tpu.controllers.disruption.consolidation import CONSOLIDATION_TTL
from karpenter_tpu.controllers.provisioning import Provisioner
from karpenter_tpu.events.recorder import Recorder
from karpenter_tpu.operator.options import Options
from karpenter_tpu.runtime.store import Store
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.state.informer import StateInformer
from karpenter_tpu.utils.clock import FakeClock

from helpers import bind_pod, node_claim_pair, nodepool, unschedulable_pod


class Env:
    def __init__(self, options=None, instance_types=None):
        self.clock = FakeClock()
        self.store = Store(clock=self.clock)
        self.provider = FakeCloudProvider(instance_types)
        self.cluster = Cluster(self.clock, self.store, self.provider)
        self.informer = StateInformer(self.store, self.cluster)
        self.recorder = Recorder(clock=self.clock)
        self.provisioner = Provisioner(
            self.store, self.provider, self.cluster, self.recorder, self.clock,
            options or Options(),
        )
        self.queue = DisruptionQueue(
            self.store, self.recorder, self.cluster, self.clock, self.provisioner
        )
        self.controller = DisruptionController(
            self.clock, self.store, self.provisioner, self.provider,
            self.recorder, self.cluster, self.queue,
        )

    def add_pair(self, name, pods=(), **kw):
        node, claim = node_claim_pair(name, **kw)
        self.store.create(claim)
        self.store.create(node)
        for p in pods:
            bind_pod(p, node)
            self.store.create(p)
        self.informer.flush()
        return node, claim

    def reconcile(self):
        """One reconcile, driving two-phase validation through its TTL: a
        command computed on the first pass parks for CONSOLIDATION_TTL and
        starts on a later pass (validation.go:152-282)."""
        self.informer.flush()
        out = self.controller.reconcile()
        self.informer.flush()
        if self.controller._pending is not None:
            self.clock.step(CONSOLIDATION_TTL + 0.1)
            out = self.controller.reconcile()
            self.informer.flush()
        return out


class TestEmptiness:
    def test_empty_node_deleted(self):
        env = Env()
        env.store.create(nodepool("default"))
        node, claim = env.add_pair("empty-1")
        assert env.reconcile() is True
        # command started: node tainted, claim has DisruptionReason
        node = env.store.get("Node", "empty-1")
        assert any(t.key == wk.DISRUPTED_TAINT_KEY for t in node.spec.taints)
        claim = env.store.get("NodeClaim", "empty-1-claim")
        assert claim.condition_is_true(CONDITION_DISRUPTION_REASON)
        # queue drains: no replacements -> delete candidates immediately
        env.queue.reconcile()
        env.informer.flush()
        assert env.store.try_get("NodeClaim", "empty-1-claim") is None

    def test_validation_sees_churn_between_phases(self):
        """A pod landing on the empty node during the validation TTL must
        abandon the command — the churn re-check the two-phase design exists
        for (validation.go:152-282)."""
        env = Env()
        env.store.create(nodepool("default"))
        node, claim = env.add_pair("empty-1")
        env.informer.flush()
        assert env.controller.reconcile() is True  # phase one: parked
        assert env.controller._pending is not None
        # a pod binds to the node while the command waits out its TTL
        pod = unschedulable_pod(requests={"cpu": "1"})
        bind_pod(pod, node)
        env.store.create(pod)
        env.informer.flush()
        env.clock.step(CONSOLIDATION_TTL + 0.1)
        assert env.controller.reconcile() is False  # phase two: abandoned
        assert env.controller._pending is None
        env.queue.reconcile()
        env.informer.flush()
        assert env.store.try_get("NodeClaim", "empty-1-claim") is not None

    def test_failed_validation_counted(self):
        """disruption/metrics.go:86 — abandoning a command at re-validation
        increments failed_validations_total."""
        from karpenter_tpu.controllers.disruption.controller import (
            _FAILED_VALIDATIONS,
        )

        before = _FAILED_VALIDATIONS.value()
        env = Env()
        env.store.create(nodepool("default"))
        node, claim = env.add_pair("empty-fv")
        env.informer.flush()
        assert env.controller.reconcile() is True
        pod = unschedulable_pod(requests={"cpu": "1"})
        bind_pod(pod, node)
        env.store.create(pod)
        env.informer.flush()
        env.clock.step(CONSOLIDATION_TTL + 0.1)
        assert env.controller.reconcile() is False
        assert _FAILED_VALIDATIONS.value() == before + 1

    def test_node_with_pods_not_empty(self):
        env = Env()
        env.store.create(nodepool("default"))
        env.add_pair("busy-1", pods=[unschedulable_pod(requests={"cpu": "1"})])
        # emptiness skips; consolidation may run but a single node with pods
        # can't consolidate to nothing cheaper here (it's the cheapest shape)

    def test_not_consolidatable_skipped(self):
        env = Env()
        env.store.create(nodepool("default"))
        env.add_pair("e-1", consolidatable=False)
        assert env.reconcile() is False

    def test_consolidation_disabled_nodepool(self):
        env = Env()
        np = nodepool("default")
        np.spec.disruption.consolidate_after = None
        env.store.create(np)
        env.add_pair("e-2")
        assert env.reconcile() is False

    def test_budget_zero_blocks(self):
        env = Env()
        np = nodepool("default")
        np.spec.disruption.budgets = [Budget(nodes="0")]
        env.store.create(np)
        env.add_pair("e-3")
        assert env.reconcile() is False


class TestSingleNodeConsolidation:
    def test_replace_underutilized_with_cheaper(self):
        env = Env()
        env.store.create(nodepool("default"))
        # big node (32 cpu) with one small pod -> cheaper replacement exists
        pod = unschedulable_pod(requests={"cpu": "1"})
        env.add_pair(
            "big-1",
            pods=[pod],
            instance_type="s-32x-amd64-linux",
            capacity={"cpu": "32", "memory": "128Gi", "pods": "110"},
        )
        assert env.reconcile() is True
        [cmd] = env.queue.get_commands()
        assert cmd.decision() == "replace"
        assert len(cmd.replacements) == 1
        # replacement claim created in store
        claims = [
            c for c in env.store.list("NodeClaim") if c.metadata.name != "big-1-claim"
        ]
        assert len(claims) == 1
        # every replacement option launches cheaper than the candidate's
        # on-demand price; with spot still cheaper the capacity type is
        # pinned to spot (consolidation.go:216-219)
        from karpenter_tpu.cloudprovider.types import Offerings
        replacement = cmd.replacements[0].node_claim
        candidate_price = 0.025 * 32 + 0.001 * 128
        for it in replacement.instance_type_options:
            worst = Offerings(it.offerings).available().worst_launch_price(
                replacement.requirements
            )
            assert worst < candidate_price
        ct = replacement.requirements.get(wk.CAPACITY_TYPE_LABEL_KEY)
        assert ct.values_list() == [wk.CAPACITY_TYPE_SPOT]

    def test_replacement_initialization_completes_command(self):
        env = Env()
        env.store.create(nodepool("default"))
        pod = unschedulable_pod(requests={"cpu": "1"})
        env.add_pair(
            "big-2",
            pods=[pod],
            instance_type="s-32x-amd64-linux",
            capacity={"cpu": "32", "memory": "128Gi", "pods": "110"},
        )
        assert env.reconcile() is True
        [cmd] = env.queue.get_commands()
        replacement_name = cmd.replacements[0].name
        env.queue.reconcile()  # replacement not initialized yet
        assert env.store.try_get("NodeClaim", "big-2-claim") is not None
        rep = env.store.get("NodeClaim", replacement_name)
        rep.set_condition(CONDITION_INITIALIZED, "True")
        env.store.update(rep)
        env.queue.reconcile()
        assert env.store.try_get("NodeClaim", "big-2-claim") is None
        assert env.queue.is_empty()

    def test_command_timeout_rolls_back(self):
        env = Env()
        env.store.create(nodepool("default"))
        pod = unschedulable_pod(requests={"cpu": "1"})
        node, claim = env.add_pair(
            "big-3",
            pods=[pod],
            instance_type="s-32x-amd64-linux",
            capacity={"cpu": "32", "memory": "128Gi", "pods": "110"},
        )
        assert env.reconcile() is True
        env.clock.step(601.0)  # maxRetryDuration
        env.queue.reconcile()
        env.informer.flush()
        # candidate survived, taint removed, condition cleared, unmarked
        node = env.store.get("Node", "big-3")
        assert not any(t.key == wk.DISRUPTED_TAINT_KEY for t in node.spec.taints)
        claim = env.store.get("NodeClaim", "big-3-claim")
        assert not claim.condition_is_true(CONDITION_DISRUPTION_REASON)
        assert env.queue.is_empty()

    def test_cheapest_node_not_replaced(self):
        env = Env()
        env.store.create(nodepool("default"))
        pod = unschedulable_pod(requests={"cpu": "3"})
        # 4-cpu node fairly full -> no cheaper single replacement
        env.add_pair("cheap-1", pods=[pod], instance_type="c-4x-amd64-linux",
                     capacity={"cpu": "4", "memory": "8Gi", "pods": "110"})
        env.reconcile()
        for cmd in env.queue.get_commands():
            assert cmd.decision() != "replace" or cmd.replacements


class TestMultiNodeConsolidation:
    def test_two_nodes_merge_into_one(self):
        env = Env()
        np = nodepool("default")
        np.spec.disruption.budgets = [Budget(nodes="100%")]
        env.store.create(np)
        for i in range(2):
            pod = unschedulable_pod(requests={"cpu": "1"})
            env.add_pair(
                f"multi-{i}",
                pods=[pod],
                instance_type="s-16x-amd64-linux",
                capacity={"cpu": "16", "memory": "64Gi", "pods": "110"},
            )
        assert env.reconcile() is True
        [cmd] = env.queue.get_commands()
        # both candidates consolidated into <= 1 replacement
        assert len(cmd.candidates) == 2
        assert len(cmd.replacements) <= 1

    def test_consolidation_timeout_counted(self, monkeypatch):
        """disruption/metrics.go:76 — hitting the multi-node 60s deadline
        mid-binary-search increments consolidation_timeouts_total and
        returns the last saved command (the reference's deadline behavior,
        multinodeconsolidation.go:117-170)."""
        from karpenter_tpu.controllers.disruption import methods as dmethods

        env = Env()
        np = nodepool("default")
        np.spec.disruption.budgets = [Budget(nodes="100%")]
        env.store.create(np)
        for i in range(3):
            pod = unschedulable_pod(requests={"cpu": "1"})
            env.add_pair(
                f"to-{i}",
                pods=[pod],
                instance_type="s-16x-amd64-linux",
                capacity={"cpu": "16", "memory": "64Gi", "pods": "110"},
            )
        before = dmethods._CONSOLIDATION_TIMEOUTS.value(
            {"consolidation_type": "multi"}
        )
        # every frontier round burns past the deadline; depth 1 keeps the
        # search multi-round so the between-rounds check actually runs
        env.provisioner.options.consolidation_frontier_depth = 1
        orig = dmethods.FrontierSimulator.solve_batch

        def slow_batch(sim, plans):
            env.clock.step(dmethods.MULTI_NODE_CONSOLIDATION_TIMEOUT + 1.0)
            return orig(sim, plans)

        monkeypatch.setattr(dmethods.FrontierSimulator, "solve_batch", slow_batch)
        env.reconcile()
        assert (
            dmethods._CONSOLIDATION_TIMEOUTS.value({"consolidation_type": "multi"})
            == before + 1
        )

    def test_spot_to_spot_requires_feature_gate(self):
        env = Env()
        env.store.create(nodepool("default"))
        pod = unschedulable_pod(requests={"cpu": "1"})
        env.add_pair(
            "spot-1",
            pods=[pod],
            instance_type="s-32x-amd64-linux",
            capacity_type=wk.CAPACITY_TYPE_SPOT,
            capacity={"cpu": "32", "memory": "128Gi", "pods": "110"},
        )
        env.reconcile()
        # gate disabled by default: no replace command for a spot candidate
        # whose replacement would also be spot
        for cmd in env.queue.get_commands():
            if cmd.candidates and cmd.candidates[0].name() == "spot-1":
                ct = cmd.replacements[0].node_claim.requirements.get(
                    wk.CAPACITY_TYPE_LABEL_KEY
                )
                assert not ct.has(wk.CAPACITY_TYPE_SPOT) or ct.has(
                    wk.CAPACITY_TYPE_ON_DEMAND
                )


class TestDrift:
    def test_drifted_node_replaced(self):
        env = Env()
        env.store.create(nodepool("default"))
        pod = unschedulable_pod(requests={"cpu": "1"})
        node, claim = env.add_pair("drift-1", pods=[pod], consolidatable=False)
        claim.set_condition("Drifted", "True", now=env.clock.now())
        env.store.update(claim)
        env.informer.flush()
        assert env.reconcile() is True
        [cmd] = env.queue.get_commands()
        assert cmd.reason == "Drifted"
        assert len(cmd.candidates) == 1 and len(cmd.replacements) == 1

    def test_empty_drifted_node_not_via_drift(self):
        # drift skips candidates with no reschedulable pods (emptiness owns them)
        env = Env()
        np = nodepool("default")
        np.spec.disruption.consolidate_after = None  # disable emptiness path
        env.store.create(np)
        node, claim = env.add_pair("drift-2", consolidatable=False)
        claim.set_condition("Drifted", "True", now=env.clock.now())
        env.store.update(claim)
        env.informer.flush()
        assert env.reconcile() is False


class TestBudgets:
    def test_percentage_budget_limits_batch(self):
        env = Env()
        np = nodepool("default")
        np.spec.disruption.budgets = [Budget(nodes="50%")]
        env.store.create(np)
        for i in range(4):
            env.add_pair(f"b-{i}")
        assert env.reconcile() is True
        [cmd] = env.queue.get_commands()
        assert len(cmd.candidates) == 2  # 50% of 4

    def test_schedule_budget_inactive(self):
        env = Env()
        np = nodepool("default")
        # active for 1h starting at midnight; fake clock starts far from it
        np.spec.disruption.budgets = [
            Budget(nodes="0", schedule="0 0 * * *", duration=3600.0)
        ]
        env.store.create(np)
        env.add_pair("b-sched")
        # budget inactive -> unrestricted -> emptiness proceeds
        assert env.reconcile() is True


class TestSpotToSpot:
    """consolidation.go:229-301 with the SpotToSpotConsolidation gate ON."""

    def _gated_env(self):
        from karpenter_tpu.operator.options import FeatureGates

        return Env(
            options=Options(
                feature_gates=FeatureGates(spot_to_spot_consolidation=True)
            )
        )

    def test_spot_to_spot_with_enough_cheaper_types(self):
        env = self._gated_env()
        env.store.create(nodepool("default"))
        pod = unschedulable_pod(requests={"cpu": "1"})
        env.add_pair(
            "spot-big",
            pods=[pod],
            instance_type="s-32x-amd64-linux",
            capacity_type=wk.CAPACITY_TYPE_SPOT,
            capacity={"cpu": "32", "memory": "128Gi", "pods": "110"},
        )
        assert env.reconcile() is True
        [cmd] = env.queue.get_commands()
        assert cmd.candidates[0].name() == "spot-big"
        [replacement] = cmd.replacements
        claim = replacement.node_claim  # scheduler NodeClaim
        ct = claim.requirements.get(wk.CAPACITY_TYPE_LABEL_KEY)
        assert ct.has(wk.CAPACITY_TYPE_SPOT)
        assert not ct.has(wk.CAPACITY_TYPE_ON_DEMAND)
        # launch set truncated to the 15 cheapest so the spot node sticks
        assert len(claim.instance_type_options) == 15

    def test_spot_to_spot_blocked_below_minimum_types(self):
        env = self._gated_env()
        pool = nodepool(
            "default",
            requirements=[
                {
                    "key": wk.LABEL_INSTANCE_TYPE,
                    "operator": "In",
                    # candidate + only 3 cheaper alternatives: below the
                    # 15-type minimum, so the command must not be issued
                    "values": [
                        "s-32x-amd64-linux",
                        "s-16x-amd64-linux",
                        "s-8x-amd64-linux",
                        "s-4x-amd64-linux",
                    ],
                }
            ],
        )
        env.store.create(pool)
        pod = unschedulable_pod(requests={"cpu": "1"})
        env.add_pair(
            "spot-thin",
            pods=[pod],
            instance_type="s-32x-amd64-linux",
            capacity_type=wk.CAPACITY_TYPE_SPOT,
            capacity={"cpu": "32", "memory": "128Gi", "pods": "110"},
        )
        env.reconcile()
        assert not any(
            cmd.candidates and cmd.candidates[0].name() == "spot-thin"
            for cmd in env.queue.get_commands()
        )
        # pin the block to the 15-type minimum, not some earlier failure
        assert any(
            "SpotToSpotConsolidation requires 15" in e.message
            for e in env.recorder.events
        )


class TestDisruptionDecisionMetrics:
    """suite_test.go:1930-2037 — decisions fire the decision/reason/
    consolidation_type counter when commands start."""

    def _assert_decision_fires(self, env, decision, reason, ctype):
        from karpenter_tpu.controllers.disruption import queue as qmod

        labels = {"decision": decision, "reason": reason, "consolidation_type": ctype}
        before = qmod._DECISIONS_TOTAL.value(labels)
        assert env.reconcile() is True
        assert qmod._DECISIONS_TOTAL.value(labels) == before + 1

    def test_single_node_empty_fires_delete_empty(self):
        # suite_test.go:1930
        env = Env()
        env.store.create(nodepool("default"))
        env.add_pair("m-empty-1")
        self._assert_decision_fires(env, "delete", "empty", "empty")

    def test_single_node_drift_fires_delete_drifted(self):
        # suite_test.go:1942 — drifted node whose pods fit elsewhere: delete
        env = Env()
        env.store.create(nodepool("default"))
        # a second, non-disruptable node able to absorb the pods
        env.add_pair("m-other-1", consolidatable=False)
        pods = [unschedulable_pod(requests={"cpu": "100m"}) for _ in range(2)]
        _, claim = env.add_pair("m-drift-1", pods=pods)
        claim.set_condition(CONDITION_DRIFTED, "True")
        env.store.update(claim)
        env.informer.flush()
        self._assert_decision_fires(env, "delete", "drifted", "")

    def test_single_node_drift_fires_replace_drifted(self):
        # suite_test.go:1967 — drifted node with pods and nowhere to put
        # them: replacement launched
        env = Env()
        env.store.create(nodepool("default"))
        pods = [unschedulable_pod(requests={"cpu": "2"}) for _ in range(2)]
        _, claim = env.add_pair("m-driftr-1", pods=pods)
        claim.set_condition(CONDITION_DRIFTED, "True")
        env.store.update(claim)
        env.informer.flush()
        self._assert_decision_fires(env, "replace", "drifted", "")

    def test_multi_node_empty_fires_delete_empty(self):
        # suite_test.go:1990 — several empty nodes coalesce into one command
        env = Env()
        env.store.create(nodepool("default"))
        for i in range(3):
            env.add_pair(f"m-multi-{i}")
        self._assert_decision_fires(env, "delete", "empty", "empty")


class TestLeftoverTaintCleanup:
    """suite_test.go — taints from abandoned/restarted disruption actions."""

    def test_leftover_disrupted_taint_removed(self):
        """A node carrying the disrupted taint with NO in-flight command gets
        untainted on the next reconcile pass (controller.go:131-152)."""
        from karpenter_tpu.scheduling.taints import DISRUPTED_NO_SCHEDULE_TAINT

        env = Env()
        env.store.create(nodepool("default"))
        node, claim = env.add_pair(
            "stale-1", pods=[unschedulable_pod(requests={"cpu": "1"})]
        )
        node.spec.taints = list(node.spec.taints) + [DISRUPTED_NO_SCHEDULE_TAINT]
        claim.set_condition("DisruptionReason", "True", reason="Underutilized")
        env.store.update(node)
        env.store.update(claim)
        env.informer.flush()
        env.controller.reconcile()
        node = env.store.get("Node", "stale-1")
        assert not any(t.key == wk.DISRUPTED_TAINT_KEY for t in node.spec.taints)
        claim = env.store.get("NodeClaim", "stale-1-claim")
        assert not claim.condition_is_true("DisruptionReason")

    def test_in_flight_command_keeps_taint(self):
        """Nodes actively being processed by the queue keep their taint."""
        env = Env()
        env.store.create(nodepool("default"))
        env.add_pair("active-1")
        assert env.reconcile() is True  # emptiness command started
        node = env.store.get("Node", "active-1")
        assert any(t.key == wk.DISRUPTED_TAINT_KEY for t in node.spec.taints)
        # another pass with the command still queued must NOT untaint
        env.controller.reconcile()
        node = env.store.get("Node", "active-1")
        assert any(t.key == wk.DISRUPTED_TAINT_KEY for t in node.spec.taints)


class TestSpotToSpotTruncation:
    """consolidation_test.go:1217-1500 — the launch set is price-ordered and
    sized max(15, minValues-needed) so the resulting spot node sticks."""

    def _gated_env(self, pools=None, instance_types=None):
        from karpenter_tpu.operator.options import FeatureGates

        env = Env(
            options=Options(
                feature_gates=FeatureGates(spot_to_spot_consolidation=True)
            ),
            instance_types=instance_types,
        )
        for p in pools or [nodepool("default")]:
            env.store.create(p)
        return env

    def _spot_candidate(self, env):
        env.add_pair(
            "spot-cand",
            pods=[unschedulable_pod(requests={"cpu": "1"})],
            instance_type="s-32x-amd64-linux",
            capacity_type=wk.CAPACITY_TYPE_SPOT,
            capacity={"cpu": "32", "memory": "128Gi", "pods": "110"},
        )

    def test_launch_set_is_the_cheapest_15(self):
        """:1217 — options are price-ordered BEFORE the flexibility
        truncation: the kept 15 are exactly the 15 cheapest spot options."""
        env = self._gated_env()
        self._spot_candidate(env)
        assert env.reconcile() is True
        [cmd] = env.queue.get_commands()
        claim = cmd.replacements[0].node_claim
        kept = claim.instance_type_options
        assert len(kept) == 15

        def cheapest_spot(it):
            return min(
                o.price
                for o in it.offerings
                if o.available and o.capacity_type == wk.CAPACITY_TYPE_SPOT
            )

        kept_prices = [cheapest_spot(it) for it in kept]
        # price-ordered within the kept set
        assert kept_prices == sorted(kept_prices)
        # no compatible option outside the kept set is cheaper than the
        # most expensive kept one
        kept_names = {it.name for it in kept}
        outside = [
            cheapest_spot(it)
            for it in env.provider.instance_types
            if it.name not in kept_names
            and it.offerings.available().has_compatible(claim.requirements)
            and it.requirements.intersects_ok(claim.requirements)
        ]
        assert all(p >= kept_prices[-1] for p in outside)

    def test_min_values_expands_the_launch_set(self):
        """:1327 — minValues needing more than 15 types wins the max()."""
        pool = nodepool(
            "default",
            requirements=[
                {
                    "key": wk.LABEL_INSTANCE_TYPE,
                    "operator": "Exists",
                    "minValues": 25,
                }
            ],
        )
        env = self._gated_env(pools=[pool])
        self._spot_candidate(env)
        assert env.reconcile() is True
        [cmd] = env.queue.get_commands()
        claim = cmd.replacements[0].node_claim
        assert len(claim.instance_type_options) == 25

    def test_small_min_values_keeps_default_truncation(self):
        """:1447 — minValues satisfiable within 15 keeps the default cap."""
        pool = nodepool(
            "default",
            requirements=[
                {
                    "key": wk.LABEL_INSTANCE_TYPE,
                    "operator": "Exists",
                    "minValues": 5,
                }
            ],
        )
        env = self._gated_env(pools=[pool])
        self._spot_candidate(env)
        assert env.reconcile() is True
        [cmd] = env.queue.get_commands()
        claim = cmd.replacements[0].node_claim
        assert len(claim.instance_type_options) == 15
